#!/usr/bin/env python
"""Localhost multi-process cluster harness: a REAL ``jax.distributed``
CPU cluster of N OS processes, for the pod-scale streaming suite.

``run_cluster(payload, ...)`` spawns N workers (each owning
``devs`` virtual CPU devices via ``--xla_force_host_platform_device_count``),
joins them through ``bolt_tpu.parallel.multihost.initialize`` (which
arms the gloo cross-process collective transport on CPU), runs the
named payload in every process, and returns the per-process JSON
results plus any ``.npy`` artifacts the payload saved.

The harness is also the pod's FAULT REPORTER: when one worker dies
(``kill -9``, an uncaught error) while its peers still run, the
survivors would block forever inside the next cross-host collective —
so the monitor terminates them and raises a POINTED ``RuntimeError``
naming the dead process and its exit code.  ``expect_dead=True``
(the checkpoint/resume kill tests) instead returns the exit codes.

Used by tests/test_multihost.py, scripts/bench_all.py (config 11) and
scripts/perf_regress.py (the ``multihost_stream`` family); run
standalone as ``python scripts/multihost_harness.py`` for a smoke pass
of the parity payload.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _dump_stacks(procs, grace=1.5):
    """Ask every still-running worker to dump all thread stacks into
    its log (faulthandler on SIGUSR1, armed in worker_main) before the
    monitor SIGKILLs it — a wedged survivor's log otherwise says
    nothing about WHERE it wedged."""
    import signal
    alive = [p for p in procs if p.poll() is None]
    for p in alive:
        try:
            p.send_signal(signal.SIGUSR1)
        except OSError:
            pass
    hold = time.time() + grace
    while time.time() < hold and any(p.poll() is None for p in alive):
        time.sleep(0.05)


def free_ports(n):
    """``n`` DISTINCT free ports (all bound simultaneously before any
    is released — sequential ``free_port`` calls tend to hand the same
    just-released port back, and a reform coordinator reusing the old
    cluster's port would connect the survivors to the ORPHANED old
    service instead of the new one)."""
    import socket
    socks = [socket.socket() for _ in range(n)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


# ---------------------------------------------------------------------
# the parent side
# ---------------------------------------------------------------------

def run_cluster(payload, nproc=2, devs=1, timeout=300, env=None,
                worker_env=None, expect_dead=False, out_dir=None,
                tolerate=(), extra_workers=None):
    """Stand up an ``nproc``-process cluster and run ``payload`` in
    every process.  Returns ``(results, out_dir, rcs)`` where
    ``results`` is the list of per-process result dicts (``None`` for a
    process that died) and ``rcs`` the exit codes.

    ``env`` adds to every worker's environment; ``worker_env`` is a
    ``{pid: {...}}`` per-worker overlay (how the fault tests arm
    ``BOLT_CHAOS`` on ONE process).  With ``expect_dead=False`` a
    worker death while peers still run raises the pointed
    ``RuntimeError``.  ``tolerate`` names pids whose death is the
    SCENARIO (the reform tests kill one worker and expect the
    survivors to detect it, reform and finish): a tolerated death
    neither terminates the survivors nor fails the run — its result
    slot is ``None`` and its exit code lands in ``rcs``.

    ``extra_workers`` is a ``{wid: {...env}}`` map of ADDITIONAL
    processes spawned OUTSIDE the initial cluster (``wid >= nproc``):
    the rejoiner of the 3→2→3 elastic scenario runs the same payload
    but skips the bootstrap ``multihost.initialize`` (arm
    ``BOLT_MH_REJOINER=1`` in its env) and joins later through
    ``supervisor.attach``.  Extra workers must succeed and their
    results are required before the exit-barrier release."""
    own_dir = out_dir is None
    if own_dir:
        out_dir = tempfile.mkdtemp(prefix="bolt-mh-")
    else:
        os.makedirs(out_dir, exist_ok=True)
    tolerate = set(tolerate)
    extra_workers = dict(extra_workers or {})
    base = dict(os.environ)
    base.pop("BOLT_CHAOS", None)         # never inherit a stale arming
    base.update({
        "BOLT_MH_PAYLOAD": str(payload),
        "BOLT_MH_NPROC": str(nproc),
        "BOLT_MH_DEVS": str(devs),
        "BOLT_MH_PORT": str(free_port()),
        "BOLT_MH_OUT": out_dir,
    })
    if env:
        base.update({k: str(v) for k, v in env.items()})
    wids = list(range(nproc)) + sorted(extra_workers)
    if wids != list(range(len(wids))):
        raise ValueError("extra_workers ids must be contiguous from "
                         "nproc (got %s)" % sorted(extra_workers))
    procs, logs = [], []
    for pid in wids:
        e = dict(base)
        if worker_env and pid in worker_env:
            e.update({k: str(v) for k, v in worker_env[pid].items()})
        if pid in extra_workers:
            e.update({k: str(v) for k, v in extra_workers[pid].items()})
        log = open(os.path.join(out_dir, "worker.%d.log" % pid), "wb")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(pid)],
            env=e, stdout=log, stderr=subprocess.STDOUT))
    total = len(wids)
    rcs = [None] * total
    deadline = time.time() + timeout
    released = False
    try:
        while any(rc is None for rc in rcs):
            for pid, p in enumerate(procs):
                if rcs[pid] is None:
                    rcs[pid] = p.poll()
            if not released:
                # the EXIT BARRIER: a worker that finishes first must
                # not tear the coordination service down under a peer
                # still mid-payload (the peer's error-poll thread
                # aborts the process on "service unavailable").
                # Workers hold their teardown until this parent-side
                # release lands — written once every worker the
                # scenario expects to SURVIVE has durably produced its
                # result (or already exited).
                if all(rcs[pid] is not None
                       or os.path.exists(os.path.join(
                           out_dir, "result.%d.json" % pid))
                       for pid in range(total) if pid not in tolerate):
                    rel = os.path.join(out_dir, "release")
                    with open(rel + ".tmp", "w") as f:
                        f.write("1")
                    os.replace(rel + ".tmp", rel)
                    released = True
            bad = [pid for pid, rc in enumerate(rcs)
                   if rc is not None and rc != 0 and pid not in tolerate]
            if bad and any(rc is None for rc in rcs):
                # a peer is gone: survivors will block in the next
                # cross-host collective forever.  Short grace (they may
                # be dying of the same injected fault), then terminate
                # and report POINTEDLY which process died.
                grace = time.time() + 3.0
                while time.time() < grace and any(
                        p.poll() is None for p in procs):
                    time.sleep(0.05)
                _dump_stacks(procs)
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for pid, p in enumerate(procs):
                    if rcs[pid] is None:
                        rcs[pid] = p.wait()
                if not expect_dead:
                    dead = bad[0]
                    raise RuntimeError(
                        "multihost cluster: process %d died (exit code "
                        "%s) before the run finished — its peers were "
                        "blocked on the next cross-host collective and "
                        "have been terminated; see %s"
                        % (dead, rcs[dead],
                           os.path.join(out_dir,
                                        "worker.%d.log" % dead)))
                break
            if time.time() > deadline:
                _dump_stacks(procs)
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                raise RuntimeError(
                    "multihost cluster timed out after %ss (logs in %s)"
                    % (timeout, out_dir))
            time.sleep(0.05)
        for pid, p in enumerate(procs):
            if rcs[pid] is None:
                rcs[pid] = p.wait()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
    results = []
    for pid in range(total):
        path = os.path.join(out_dir, "result.%d.json" % pid)
        if os.path.exists(path):
            with open(path) as f:
                results.append(json.load(f))
        else:
            results.append(None)
    if not expect_dead:
        for pid, rc in enumerate(rcs):
            if pid in tolerate:
                continue              # its death IS the scenario
            if rc != 0 or results[pid] is None:
                with open(os.path.join(out_dir, "worker.%d.log" % pid),
                          "rb") as f:
                    tail = f.read()[-4000:].decode(errors="replace")
                raise RuntimeError(
                    "multihost worker %d failed (rc=%s):\n%s"
                    % (pid, rc, tail))
    return results, out_dir, rcs


# ---------------------------------------------------------------------
# the worker side
# ---------------------------------------------------------------------

def _bootstrap(pid):
    """Per-worker preamble: force the virtual CPU topology BEFORE any
    backend query, then join the cluster through the blessed
    multihost.initialize door (which arms gloo on CPU).  A REJOINER
    (``BOLT_MH_REJOINER=1`` — the replacement process of the elastic
    3→2→3 scenario) skips the initialize: it joins LATER through
    ``supervisor.attach`` once the incumbents publish a plan."""
    devs = int(os.environ["BOLT_MH_DEVS"])
    nproc = int(os.environ["BOLT_MH_NPROC"])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=%d" % devs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, _REPO)
    from bolt_tpu.parallel import multihost
    if nproc > 1 and os.environ.get("BOLT_MH_REJOINER") != "1":
        ok = multihost.initialize(
            coordinator_address="127.0.0.1:%s" % os.environ["BOLT_MH_PORT"],
            num_processes=nproc, process_id=pid)
        assert ok, "multihost.initialize declined"
    return multihost


# user stage funcs at module level: bytecode-identical across processes
# AND across runs, so program keys (and checkpoint fingerprints) match
ADD1 = lambda v: v + 1  # noqa: E731


def _mesh():
    import jax
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()), ("k",))


def _crafted(n, vdim, period=8):
    """Bit-exactness-crafted data: period-``period`` integer pattern
    (+ a half-step per value slot).  Sums are exact in f32, every
    shard of a multiple-of-``period`` record range has the SAME mean,
    so the hierarchical (per-shard + collective) moments equal the
    single-process moments BIT for bit — the same trick the
    crafted-Welford stream suite uses.  ``period=4`` keeps the moments
    exact on shard lengths divisible by 4 (a 96-record key axis split
    3 ways into 8-record slab shards AND 2 ways into 12-record ones —
    the elastic 3→2→3 scenario's geometry)."""
    import numpy as np
    r = np.arange(n, dtype=np.float32) % period
    v = np.arange(vdim, dtype=np.float32) * 0.5
    return (r[:, None] + v[None, :]).astype(np.float32)


def _value(barray):
    """Host value of a (possibly replicated cross-process) result."""
    from bolt_tpu.parallel import multihost
    return multihost.local_value(barray._data)


def payload_stream_parity(pid):
    """The acceptance payload: streamed sum AND fused stats('sum','var')
    over a per-process fromcallback source, with the compile-once,
    zero-leaked-span, BLT012, fromiter and explain() proofs recorded."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import analysis, engine, obs
    from bolt_tpu.parallel import multihost
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "64"))
    vdim = 8
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "16"))
    x = _crafted(n, vdim)
    mesh = _mesh()
    obs.clear()
    obs.enable()
    rows = []                       # list.append is thread-safe (the
    #                                 uploader pool calls concurrently)

    def loader(idx):
        rows.append(len(range(*idx[0].indices(n))))
        return x[idx]

    def make():
        return bolt.fromcallback(loader, (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    res = {"pid": pid, "nproc": multihost.process_count()}

    # --- streamed sum: compile-once proof across TWO passes -----------
    c0 = engine.counters()
    s1 = make().map(ADD1).sum().cache()
    c1 = engine.counters()
    np.save(os.path.join(out, "sum.%d.npy" % pid), _value(s1))
    make().map(ADD1).sum().cache()
    c2 = engine.counters()
    res["aot_first_pass"] = c1["aot_compiles"] - c0["aot_compiles"]
    res["misses_first_pass"] = c1["misses"] - c0["misses"]
    res["recompiles_second_pass"] = (
        c2["aot_compiles"] - c1["aot_compiles"]
        + c2["misses"] - c1["misses"])
    res["transfer_bytes"] = c2["transfer_bytes"] - c0["transfer_bytes"]

    # --- fused multi-stat: stats("sum", "var") one pass ---------------
    st = make().map(ADD1).stats("sum", "var")
    np.save(os.path.join(out, "stats_sum.%d.npy" % pid),
            _value(st["sum"]))
    np.save(os.path.join(out, "stats_var.%d.npy" % pid),
            _value(st["var"]))

    # --- per-process ingest contract: this process produced ONLY its
    # own shard of every slab (3 passes x its fraction of the records)
    res["rows_produced"] = sum(rows)
    res["rows_expected"] = 3 * (n // multihost.process_count())

    # --- the per-host plan in explain() -------------------------------
    res["explain_multiprocess"] = (
        "MULTI-PROCESS" in analysis.explain(make().map(ADD1))
        if multihost.process_count() > 1 else True)

    # --- BLT012: an indivisible slab refuses, and check() forecasts ---
    bad = bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                            dtype=np.float32, chunks=3,
                            per_process=True)
    if multihost.process_count() > 1:
        try:
            bad.map(ADD1).sum().cache()
            res["blt012_refused"] = False
        except ValueError as exc:
            res["blt012_refused"] = "BLT012" in str(exc)
        res["blt012_forecast"] = analysis.check(
            bad.map(ADD1)).has("BLT012")
    else:
        res["blt012_refused"] = res["blt012_forecast"] = True

    # --- fromiter: re-iterable streams per process; one-shot refuses --
    blocks = [x[i:i + chunks] for i in range(0, n, chunks)]
    fi = bolt.fromiter(blocks, (n, vdim), mesh, dtype=np.float32)
    np.save(os.path.join(out, "fromiter_sum.%d.npy" % pid),
            _value(fi.map(ADD1).sum().cache()))

    # --- a REPLICATING mesh axis: with >1 device per process, a 2-axis
    # mesh whose second axis does not shard the key replicates each
    # per-process shard across local devices — the local-box dedup and
    # the psum-over-participating-axes-only paths must still fold
    # exactly (key extent 6 keeps axis "b" unabsorbed)
    import jax
    if multihost.process_count() > 1 and len(jax.devices()) >= 4:
        from jax.sharding import Mesh
        dv = np.asarray(jax.devices()).reshape(
            multihost.process_count(), -1)
        mesh2 = Mesh(dv, ("a", "b"))
        xq = (np.arange(6 * 4) % 4).astype(np.float32).reshape(6, 4)
        srcq = bolt.fromcallback(lambda idx: xq[idx], (6, 4), mesh2,
                                 dtype=np.float32, chunks=2,
                                 per_process=True)
        sq = _value(srcq.map(ADD1).sum().cache())
        res["replicated_axis_ok"] = bool(
            np.array_equal(sq, (xq + 1).sum(axis=0)))
    if multihost.process_count() > 1:
        try:
            bolt.fromiter((b for b in blocks), (n, vdim), mesh,
                          dtype=np.float32)
            res["oneshot_refused"] = False
        except ValueError as exc:
            res["oneshot_refused"] = "one-shot" in str(exc).lower() \
                or "RE-ITERABLE" in str(exc)
    else:
        res["oneshot_refused"] = True

    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_single_ref(pid):
    """The single-process reference: identical data and pipelines on a
    one-process mesh of the SAME total device count — the bit-identity
    baseline the 2-process run is compared against."""
    import numpy as np
    import bolt_tpu as bolt
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "64"))
    vdim = 8
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "16"))
    x = _crafted(n, vdim)
    mesh = _mesh()

    def make():
        return bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    np.save(os.path.join(out, "ref_sum.npy"),
            _value(make().map(ADD1).sum().cache()))
    st = make().map(ADD1).stats("sum", "var")
    np.save(os.path.join(out, "ref_stats_sum.npy"), _value(st["sum"]))
    np.save(os.path.join(out, "ref_stats_var.npy"), _value(st["var"]))
    blocks = [x[i:i + chunks] for i in range(0, n, chunks)]
    fi = bolt.fromiter(blocks, (n, vdim), mesh, dtype=np.float32)
    np.save(os.path.join(out, "ref_fromiter_sum.npy"),
            _value(fi.map(ADD1).sum().cache()))
    return {"pid": pid, "ok": True}


def payload_resume(pid):
    """Checkpointed streamed sum over 8 slabs; the parent arms
    BOLT_CHAOS to SIGKILL every process mid-run, then re-runs this
    payload clean — the second run must RESUME (stream_resumes >= 1)
    and reproduce the uninterrupted result bit-identically."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine
    out = os.environ["BOLT_MH_OUT"]
    ck = os.environ["BOLT_MH_CKPT"]
    n, vdim, chunks = 64, 8, 8                      # 8 slabs of 8
    x = _crafted(n, vdim)
    mesh = _mesh()
    c0 = engine.counters()
    src = bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                            dtype=np.float32, chunks=chunks,
                            checkpoint=ck, per_process=True)
    s = src.map(ADD1).sum().cache()
    c1 = engine.counters()
    np.save(os.path.join(out, "resume_sum.%d.npy" % pid), _value(s))
    return {"pid": pid,
            "resumes": c1["stream_resumes"] - c0["stream_resumes"],
            "slabs": c1["stream_chunks"] - c0["stream_chunks"]}


def payload_bench(pid):
    """The config-11 / perf-family payload: stream a larger crafted
    source through the per-process pipeline, recording this process's
    ingest bytes and wall seconds (per-process GB/s) plus the
    compile-once counters."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine, obs
    from bolt_tpu.obs.trace import clock
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "4096"))
    vdim = int(os.environ.get("BOLT_MH_VDIM", "256"))
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "512"))
    x = _crafted(n, vdim)
    mesh = _mesh()
    obs.clear()
    obs.enable()

    def make():
        return bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    warm = make().map(ADD1).sum().cache()           # compile + warm
    _value(warm)
    c0 = engine.counters()
    t0 = clock()
    s = make().map(ADD1).sum().cache()
    val = _value(s)
    wall = clock() - t0
    c1 = engine.counters()
    np.save(os.path.join(out, "bench_sum.%d.npy" % pid), val)
    res = {
        "pid": pid,
        "wall_s": wall,
        "transfer_bytes": c1["transfer_bytes"] - c0["transfer_bytes"],
        "slabs": c1["stream_chunks"] - c0["stream_chunks"],
        "recompiles_warm": (c1["aot_compiles"] - c0["aot_compiles"]
                            + c1["misses"] - c0["misses"]),
        "leaked_spans": obs.active_count(),
    }
    obs.disable()
    return res


def payload_reform(pid):
    """The ISSUE-11 acceptance payload: pod fault tolerance end to end.

    With ``BOLT_CHAOS`` armed on ONE worker (the victim), each
    SURVIVOR: (1) catches the watchdog's ``PeerLostError`` from the
    killed checkpointed streamed sum — named dead peer, no hang; (2)
    proves the WATCHDOG BARRIER converts too (``multihost.barrier`` →
    ``PeerLostError`` within 2× the deadline); (3)
    ``multihost.reform``'s onto the survivors (coordinator port from
    ``BOLT_MH_REFORM_PORT``); (4) RESUMES the sum on the shrunk mesh
    from the 3-process checkpoint (topology remap — the fold partials
    are replicated global values); then (5) runs a checkpointed fused
    ``stats("sum","var")`` on the reformed pod through an injected
    abort + resume — the pod ABORT-path checkpoint write
    (``stream_save(rendezvous=False)``) proven end to end.  Run
    without chaos (any nproc) both pipelines stream clean — the
    reference/baseline leg."""
    import time as _time
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import _chaos, engine, obs
    from bolt_tpu import checkpoint as ckptlib
    from bolt_tpu.parallel import multihost, podwatch
    from bolt_tpu.obs.trace import clock

    out = os.environ["BOLT_MH_OUT"]
    ckroot = os.environ["BOLT_MH_CKPT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "96"))
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "12"))
    vdim = int(os.environ.get("BOLT_MH_VDIM", "8"))
    pace = float(os.environ.get("BOLT_MH_PACE", "0"))
    x = _crafted(n, vdim)
    ck_sum = os.path.join(ckroot, "sum")
    res = {"pid": pid, "start_nproc": multihost.process_count()}
    obs.clear()
    obs.enable()

    def loader(idx):
        if pace:
            _time.sleep(pace)         # emulated storage-fetch latency
        return x[idx]

    def make_sum():
        src = bolt.fromcallback(loader, (n, vdim), _mesh(),
                                dtype=np.float32, chunks=chunks,
                                checkpoint=ck_sum, per_process=True)
        return src.map(ADD1).sum()

    ec0 = engine.counters()
    t0 = clock()
    try:
        s = make_sum().cache()
        res["peer_lost"] = False
        res["wall_s"] = clock() - t0
    except multihost.PeerLostError as exc:
        t_caught = clock()
        res["peer_lost"] = True
        res["caught_peer"] = exc.peer
        res["caught_slab"] = exc.slab
        res["caught_phase"] = exc.phase
        # how stale was the victim when we learned? ~the heartbeat
        # verdict latency — the detection_seconds observable
        deadline = podwatch.deadline() or 5.0
        td = clock()
        while not podwatch.dead_peers() and clock() - td < 2 * deadline:
            _time.sleep(0.05)
        dead = podwatch.dead_peers()
        res["dead_peers"] = list(dead)
        res["detection_s"] = (
            podwatch.peers().get(dead[0], {}).get("age") if dead
            else None)
        # (2) a hung BARRIER converts on every survivor, within 2x the
        # watchdog deadline (the dead peer can never arrive)
        tb = clock()
        try:
            multihost.barrier("post-loss-probe")
            res["barrier_peerlost"] = False
        except multihost.PeerLostError:
            res["barrier_peerlost"] = True
        res["barrier_s"] = clock() - tb
        res["watchdog_deadline"] = deadline
        # (3) reform onto the survivors (rank mapping from the watch)
        import jax
        survivors = podwatch.alive_peers()
        tr = clock()
        new_pid = multihost.reform(
            "127.0.0.1:%s" % os.environ["BOLT_MH_REFORM_PORT"],
            num_processes=len(survivors) or
            multihost.process_count() - 1)
        res["reform_s"] = clock() - tr
        res["new_pid"] = new_pid
        res["new_nproc"] = multihost.process_count()
        res["new_devices"] = jax.device_count()
        # (4) resume the checkpointed sum on the shrunk mesh
        t4 = clock()
        s = make_sum().cache()
        _value(s)
        res["resume_s"] = clock() - t4
        # recovery = everything AFTER the survivor learned of the loss
        res["recovery_s"] = clock() - t_caught
    np.save(os.path.join(out, "reform_sum.%d.npy" % pid), _value(s))
    ec1 = engine.counters()
    res["sum_resumes"] = ec1["stream_resumes"] - ec0["stream_resumes"]
    res["sum_stale_ckpt"] = ckptlib.stream_pending(ck_sum)
    res["arbiter_leaked"] = 0         # no server in this payload
    # partial observations land NOW (debug breadcrumb for a stats-leg
    # failure) — under a name the parent's exit-barrier release logic
    # does NOT count as a finished worker
    tmp = os.path.join(out, "partial.%d.json.tmp" % pid)
    with open(tmp, "w") as f:
        json.dump(res, f)
    os.replace(tmp, os.path.join(out, "partial.%d.json" % pid))

    # ---- (5) fused stats on the (possibly reformed) pod: injected
    # abort -> pod abort-path checkpoint -> resume, bit-identical ----
    n2, chunks2 = 128, 16             # 8 slabs; per-process shards stay
    x2 = _crafted(n2, vdim)           # period-aligned (Welford-exact)
    ck_st = os.path.join(ckroot, "stats")
    if n2 % (multihost.process_count() * 8):
        # the crafted-Welford exactness needs period-aligned per-
        # process shards; the scenario runs this leg on <=2 processes
        # (the reformed pod / the clean baseline) where they are
        res["stats_skipped"] = multihost.process_count()
        res["leaked_spans"] = obs.active_count()
        obs.disable()
        return res

    def make_stats():
        src = bolt.fromcallback(lambda idx: x2[idx], (n2, vdim),
                                _mesh(), dtype=np.float32,
                                chunks=chunks2, checkpoint=ck_st,
                                per_process=True)
        return src.map(ADD1).stats("sum", "var")

    if multihost.process_count() > 1:
        # every surviving process injects the SAME deterministic
        # mid-run fault: the abort-path write (no rendezvous — the
        # satellite fix) must leave a resumable watermark
        _chaos.inject("stream.upload", nth=5)
        try:
            _value(make_stats()["sum"])
            res["stats_died"] = None
        except Exception as exc:      # noqa: BLE001 — recorded
            res["stats_died"] = type(exc).__name__
        finally:
            _chaos.clear()
        res["stats_ckpt_after_abort"] = ckptlib.stream_pending(ck_st)
    st = make_stats()
    np.save(os.path.join(out, "reform_stats_sum.%d.npy" % pid),
            _value(st["sum"]))
    np.save(os.path.join(out, "reform_stats_var.%d.npy" % pid),
            _value(st["var"]))
    ec2 = engine.counters()
    res["stats_resumes"] = ec2["stream_resumes"] - ec1["stream_resumes"]
    res["stats_stale_ckpt"] = ckptlib.stream_pending(ck_st)
    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_serve_pod(pid):
    """Serve-layer pod degradation (ISSUE 11): a Server per process
    submits a streamed per-process pipeline; the victim is SIGKILLed
    mid-run.  The survivor's in-flight future must FAIL with
    ``PeerLostError`` (never hang), the arbiter must read ZERO bytes
    after the failure (the lease returned everything), and admission
    must drain (``pod_paused``) until a reform notification resumes
    the queue."""
    import time as _time
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import obs, serve
    from bolt_tpu.parallel import multihost, podwatch

    out = os.environ["BOLT_MH_OUT"]
    n, vdim, chunks = 64, 8, 8
    x = _crafted(n, vdim)
    obs.clear()
    obs.enable()

    def make():
        src = bolt.fromcallback(lambda idx: x[idx], (n, vdim), _mesh(),
                                dtype=np.float32, chunks=chunks,
                                per_process=True)
        return src.map(ADD1).sum()

    res = {"pid": pid, "nproc": multihost.process_count()}
    with serve.serving(workers=1, budget_bytes=16 << 20) as sv:
        fut = sv.submit(make(), tenant="podtest")
        exc = fut.exception(timeout=120)
        res["future_error"] = (type(exc).__name__ if exc is not None
                               else None)
        res["future_peer"] = getattr(exc, "peer", None)
        res["arbiter_bytes_after_abort"] = \
            sv.stats()["arbiter"]["in_use_bytes"]
        t0 = _time.monotonic()
        while not sv.pod_paused() and _time.monotonic() - t0 < 30:
            _time.sleep(0.05)
        res["pod_paused"] = sv.pod_paused()
        # the reform notification resumes the queue (the full reform
        # dance is payload_reform's job; here only serve's reaction is
        # under test)
        podwatch.notify_reform()
        res["pod_resumed"] = not sv.pod_paused()
    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_supervise(pid):
    """The ISSUE-12 acceptance payload: SELF-HEALING end to end.

    Every process runs ``Server(supervise=True)`` and submits three
    pipelines in SPMD order:

    * **A** (checkpointed paced sum): the victim is SIGKILLed mid-A —
      survivors' futures succeed with ZERO caller intervention (the
      held ``retries=`` re-attempt resumes once the supervisor's
      automatic 3→2 reform lands);
    * **B** (checkpointed paced sum): a REPLACEMENT process
      (``BOLT_MH_REJOINER=1``, skipped ``multihost.initialize``) rings
      the rejoin door MID-B — incumbents quiesce at a slab-boundary
      checkpoint, the supervisor reforms 2→3, and B's re-attempt
      resumes on the re-expanded pod (the rejoiner submits B too and
      joins the same resumed slab schedule);
    * **C** (fused ``stats("sum","var")``, period-4 crafted data): a
      clean run on the re-expanded 3-wide pod.

    Run without chaos/rejoiner (the reference leg) all three stream
    clean 3-wide; sums are integer-exact under any process grouping
    and C's shards are period-aligned at both widths, so every saved
    artifact must be BIT-IDENTICAL between the legs."""
    import glob as _glob
    import time as _time
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine, obs, serve
    from bolt_tpu import checkpoint as ckptlib
    from bolt_tpu.parallel import multihost, podwatch, supervisor
    from bolt_tpu.obs.trace import clock

    out = os.environ["BOLT_MH_OUT"]
    ckroot = os.environ["BOLT_MH_CKPT"]
    hbdir = os.environ["BOLT_POD_HB_DIR"]
    pace = float(os.environ.get("BOLT_MH_PACE", "0.2"))
    rejoiner = os.environ.get("BOLT_MH_REJOINER") == "1"
    n, chunks, vdim = 96, 12, 8           # 8 slabs; 12 % 3 == 12 % 2 == 0
    x = _crafted(n, vdim)                 # integer-exact sums
    x2 = _crafted(n, vdim, period=4)      # moment-exact at widths 2 AND 3
    obs.clear()
    obs.enable()
    res = {"pid": pid, "rejoiner": rejoiner}
    deaths = []
    podwatch.on_peer_death(
        lambda dead: deaths.append(
            (dead, podwatch.peers().get(dead, {}).get("age"), clock())))

    def loader(idx):
        if pace:
            _time.sleep(pace)
        return x[idx]

    # jobs are FACTORIES, not arrays: a retry after a reform must
    # rebuild the pipeline against the CURRENT (reformed) mesh — the
    # checkpoint fingerprint ignores topology, so the re-attempt
    # resumes the same logical run on the new pod width
    def make_sum(name):
        def job():
            src = bolt.fromcallback(
                loader, (n, vdim), _mesh(), dtype=np.float32,
                chunks=chunks, checkpoint=os.path.join(ckroot, name),
                per_process=True)
            return src.map(ADD1).sum().cache()
        return job

    def make_stats():
        def job():
            src = bolt.fromcallback(
                lambda idx: x2[idx], (n, vdim), _mesh(),
                dtype=np.float32, chunks=24,
                checkpoint=os.path.join(ckroot, "statsC"),
                per_process=True)
            return src.map(ADD1).stats("sum", "var")
        return job

    if rejoiner:
        # wait until every incumbent survivor has B in flight, then
        # ring the doorbell and join through the published plan
        want = int(os.environ.get("BOLT_MH_EXPECT_BSTART", "2"))
        hold = _time.monotonic() + 180
        while len(_glob.glob(os.path.join(out, "b_started.*"))) < want:
            if _time.monotonic() > hold:
                raise RuntimeError("rejoiner: b_started gate never "
                                   "opened")
            _time.sleep(0.02)
        t0 = clock()
        sup = supervisor.attach(
            os.environ.get("BOLT_MH_REJOIN_ID", "w%db" % pid), dir=hbdir)
        res["attach_s"] = clock() - t0
        res["new_pid"] = multihost.process_index()
        res["new_nproc"] = multihost.process_count()
        sv = serve.start(workers=1, budget_bytes=64 << 20,
                         supervise=sup)
    else:
        sv = serve.start(workers=1, budget_bytes=64 << 20,
                         supervise=True)

    ec0 = engine.counters()
    try:
        if not rejoiner:
            # ---- A: kill -9 mid-stream -> automatic shrink ----------
            tA = clock()
            futA = sv.submit(make_sum("sumA"), tenant="elastic",
                             retries=3)
            sA = futA.result(timeout=300)
            res["wall_a"] = clock() - tA
            np.save(os.path.join(out, "sup_sumA.%d.npy" % pid),
                    _value(sA))
            ecA = engine.counters()
            res["a_resumes"] = ecA["stream_resumes"] \
                - ec0["stream_resumes"]
            stA = sv.stats()
            res["a_reforms"] = stA["totals"]["reforms"]
            res["a_peer_losses"] = stA["totals"]["peer_losses"]
            res["budget_share_after_a"] = stA["pod"]["budget_share"]
            res["detection_age"] = deaths[0][1] if deaths else None
            supA = (sv.supervisor.stats() if sv.supervisor is not None
                    else {})
            res["reform_s"] = supA.get("last_reform_seconds")
            res["recovery_s"] = supA.get("last_recovery_seconds")

            # ---- B: rejoin arrives mid-stream -> quiesce + grow -----
            tB = clock()
            futB = sv.submit(make_sum("sumB"), tenant="elastic",
                             retries=3)
            gate = os.path.join(out, "b_started.%d" % pid)
            with open(gate + ".tmp", "w") as f:
                f.write("1")
            os.replace(gate + ".tmp", gate)
        else:
            tB = clock()
            futB = sv.submit(make_sum("sumB"), tenant="elastic",
                             retries=3)
        ecB0 = engine.counters()
        sB = futB.result(timeout=300)
        res["wall_b"] = clock() - tB
        np.save(os.path.join(out, "sup_sumB.%d.npy" % pid), _value(sB))
        ecB = engine.counters()
        res["b_resumes"] = ecB["stream_resumes"] - ecB0["stream_resumes"]
        stB = sv.stats()
        res["reforms"] = stB["totals"]["reforms"]
        res["rejoins"] = stB["totals"]["rejoins"]
        res["supervise_seconds"] = stB["totals"]["supervise_seconds"]
        res["budget_share_after_b"] = stB["pod"]["budget_share"]
        res["nproc_after_b"] = multihost.process_count()
        if sv.supervisor is not None:
            sup_st = sv.supervisor.stats()
            res["rejoin_recovery_s"] = sup_st.get(
                "last_recovery_seconds")

        # ---- C: clean fused stats on the re-expanded pod ------------
        tC = clock()
        futC = sv.submit(make_stats(), tenant="elastic", retries=3)
        stats = futC.result(timeout=300)
        res["wall_c"] = clock() - tC
        np.save(os.path.join(out, "sup_statsC_sum.%d.npy" % pid),
                _value(stats["sum"]))
        np.save(os.path.join(out, "sup_statsC_var.%d.npy" % pid),
                _value(stats["var"]))
        res["arbiter_bytes_after"] = \
            sv.stats()["arbiter"]["in_use_bytes"]

        # ---- checker integration on the live re-expanded pod --------
        from bolt_tpu import analysis
        blocks = [x[i:i + chunks] for i in range(0, n, chunks)]
        fi = bolt.fromiter(blocks, (n, vdim), _mesh(),
                           dtype=np.float32)
        res["blt014"] = analysis.check(fi.map(ADD1)).has("BLT014")
        probe = bolt.fromcallback(lambda idx: x[idx], (n, vdim),
                                  _mesh(), dtype=np.float32,
                                  chunks=chunks, per_process=True)
        res["explain_supervised"] = \
            "SUPERVISED" in analysis.explain(probe.map(ADD1))
    finally:
        serve.stop(wait=True)
    # hygiene observables: no stale ckpt, no leaked spans, no stale
    # transport markers beyond the one-epoch grace the sweep keeps
    res["stale_ckpt"] = [name for name in ("sumA", "sumB", "statsC")
                         if ckptlib.stream_pending(
                             os.path.join(ckroot, name))]
    tr = podwatch.transport()
    res["stale_markers"] = (tr.stale_marker_count()
                            if tr is not None else 0)
    res["final_epoch"] = podwatch.epoch()
    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_precollective(pid):
    """The pre-collective death bound (ISSUE 12): the victim dies at
    its FIRST upload — before any collective was ever dispatched — and
    the survivor's readiness rendezvous must convert that into a
    pointed ``PeerLostError`` within ~2x ``BOLT_POD_TIMEOUT``, not
    gloo's ~30s connect timeout."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu.parallel import multihost, podwatch
    from bolt_tpu.obs.trace import clock

    n, vdim, chunks = 64, 8, 8
    x = _crafted(n, vdim)

    def make():
        src = bolt.fromcallback(lambda idx: x[idx], (n, vdim), _mesh(),
                                dtype=np.float32, chunks=chunks,
                                per_process=True)
        return src.map(ADD1).sum()

    res = {"pid": pid, "deadline": podwatch.deadline()}
    t0 = clock()
    try:
        make().cache()
        res["pre_peerlost"] = False
    except multihost.PeerLostError as exc:
        res["pre_peerlost"] = True
        res["pre_elapsed"] = clock() - t0
        res["pre_phase"] = exc.phase
        res["pre_peer"] = exc.peer
    return res


def payload_codec_pod(pid):
    """The ISSUE-14 pod leg: each process ENCODES its local shard, so
    per-process ingest (DCN/gloo) bytes shrink by the codec's wire
    ratio; the lossless delta-f32 pod sum stays BIT-IDENTICAL to the
    raw pod sum (the shard_map decode is shard-local by construction),
    bf16 lands within its envelope, and sidecar codecs (int8) refuse
    the multi-process mesh pointedly."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine, obs
    from bolt_tpu.parallel import multihost
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "64"))
    vdim = 8
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "16"))
    x = _crafted(n, vdim)
    mesh = _mesh()
    obs.clear()
    obs.enable()

    def make(codec=None):
        return bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True, codec=codec)

    res = {"pid": pid, "nproc": multihost.process_count()}
    c0 = engine.counters()
    raw = make().map(ADD1).sum().cache()
    c1 = engine.counters()
    dl = make("delta-f32").map(ADD1).sum().cache()
    c2 = engine.counters()
    bf = make("bf16").map(ADD1).sum().cache()
    c3 = engine.counters()
    np.save(os.path.join(out, "codec_raw.%d.npy" % pid), _value(raw))
    np.save(os.path.join(out, "codec_delta.%d.npy" % pid), _value(dl))
    np.save(os.path.join(out, "codec_bf16.%d.npy" % pid), _value(bf))
    res["raw_bytes"] = c1["transfer_bytes"] - c0["transfer_bytes"]
    res["delta_bytes"] = c2["transfer_bytes"] - c1["transfer_bytes"]
    res["bf16_bytes"] = c3["transfer_bytes"] - c2["transfer_bytes"]
    if multihost.process_count() > 1:
        try:
            make("int8").map(ADD1).sum().cache()
            res["sidecar_refused"] = False
        except ValueError as exc:
            res["sidecar_refused"] = "sidecar" in str(exc)
    else:
        res["sidecar_refused"] = True
    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_swap(pid):
    """The ISSUE-18 pod leg: a streamed ``swap`` re-buckets every slab
    through ONE ``lax.all_to_all`` per slab inside shard_map (phase 1)
    and concatenates the resident buckets (phase 2) — BIT-IDENTICAL on
    every process to the materialise-first in-memory swap of the same
    per-process source.  Also proves the pointed pod-spill refusal
    (disk spill is single-process only) and zero leaked spans."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine, obs, stream
    from bolt_tpu.parallel import multihost
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "64"))
    vdim = 8
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "16"))
    x = _crafted(n, vdim)
    mesh = _mesh()
    obs.clear()
    obs.enable()

    def make():
        return bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    res = {"pid": pid, "nproc": multihost.process_count()}
    c0 = engine.counters()
    streamed = make().swap((0,), (0,))
    res["lazy_after_swap"] = streamed._stream is not None
    sval = _value(streamed)        # resolves the two-phase shuffle
    c1 = engine.counters()
    mat = make()
    mat.cache()                    # materialise FIRST: the in-memory path
    mval = _value(mat.swap((0,), (0,)))
    np.save(os.path.join(out, "swap_streamed.%d.npy" % pid), sval)
    np.save(os.path.join(out, "swap_materialised.%d.npy" % pid), mval)
    res["shuffle_bytes"] = c1["shuffle_bytes"] - c0["shuffle_bytes"]
    res["spill_bytes"] = c1["spill_bytes"] - c0["spill_bytes"]
    # spill is single-process only: a pod plan past the budget refuses
    # POINTEDLY before any rendezvous (symmetric on every process, so
    # no peer is left hanging at the all-to-all)
    try:
        with stream.spill(dir=out, budget=1):
            make().swap((0,), (0,))._data
        res["pod_spill_refused"] = False
    except RuntimeError as exc:
        res["pod_spill_refused"] = "single-process" in str(exc)
    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_sched_verify(pid):
    """The dispatch-schedule verifier's acceptance payload (ISSUE 17):

    * matched phase — every process runs the SAME streamed pipeline,
      then ``multihost.verify_schedule`` must agree bit-identically on
      the digest;
    * skew phase — ``BOLT_CHAOS=mh.sched.skew:1:raise`` armed on ONE
      process makes it enqueue an extra LOCAL single-device program
      (no cross-process collective, so nothing can hang — only the
      schedules diverge); the next verify must raise a pointed
      :class:`ScheduleDivergenceError` on every process, naming the
      first divergent slot instead of wedging in gloo."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import _chaos, engine
    from bolt_tpu.parallel import multihost
    engine.schedule_log_arm(True)
    n, vdim = 32, 4
    x = _crafted(n, vdim)
    mesh = _mesh()
    res = {"pid": pid, "nproc": multihost.process_count()}
    b = bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                          dtype=np.float32, chunks=4,
                          per_process=True).map(ADD1).sum().cache()
    res["sum"] = float(np.asarray(_value(b)).sum())
    res["digest_matched"] = multihost.verify_schedule("matched")
    res["count_matched"] = engine.schedule_digest()[0]
    try:
        _chaos.hit("mh.sched.skew")
        res["skewed"] = False
    except _chaos.ChaosError:
        res["skewed"] = True
        import jax
        from jax.sharding import Mesh
        lmesh = Mesh(np.asarray(jax.local_devices()[:1]), ("k",))
        bolt.array(_crafted(8, vdim), context=lmesh).map(ADD1) \
            .sum().cache()
    try:
        multihost.verify_schedule("skewed", timeout=30.0)
        res["divergence"] = None
    except multihost.ScheduleDivergenceError as exc:
        res["divergence"] = {"peer": exc.peer, "index": exc.index,
                             "local_key": exc.local_key,
                             "message": str(exc)[:400]}
    return res


PAYLOADS = {
    "stream_parity": payload_stream_parity,
    "single_ref": payload_single_ref,
    "codec_pod": payload_codec_pod,
    "resume": payload_resume,
    "bench": payload_bench,
    "reform": payload_reform,
    "serve_pod": payload_serve_pod,
    "supervise": payload_supervise,
    "precollective": payload_precollective,
    "sched_verify": payload_sched_verify,
    "swap": payload_swap,
}


def worker_main(pid):
    import faulthandler
    import signal
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    _bootstrap(pid)
    payload = PAYLOADS[os.environ["BOLT_MH_PAYLOAD"]]
    res = payload(pid)
    out = os.environ["BOLT_MH_OUT"]
    tmp = os.path.join(out, "result.%d.json.tmp" % pid)
    with open(tmp, "w") as f:
        json.dump(res, f)
    os.replace(tmp, os.path.join(out, "result.%d.json" % pid))
    print("worker %d OK" % pid, flush=True)
    # the EXIT BARRIER (see run_cluster): hold the teardown until the
    # parent has seen every surviving worker's result — the first
    # worker out must not kill the coordination service under a peer
    # still mid-payload (its error-poll thread would abort the process
    # on "service unavailable"; the coordination shutdown barrier alone
    # does not reliably hold it on this runtime)
    release = os.path.join(out, "release")
    hold = time.time() + 60
    while not os.path.exists(release) and time.time() < hold:
        time.sleep(0.02)
    try:
        from bolt_tpu.parallel import multihost
        multihost.shutdown()
    except Exception:
        pass
    if os.environ.get("BOLT_MH_HARD_EXIT") == "1":
        # a reformed worker holds dead-backend threads (the old pod's
        # hung gloo contexts) that can wedge interpreter teardown; the
        # result is durably on disk, so leave without ceremony
        sys.stdout.flush()
        os._exit(0)


# ---------------------------------------------------------------------
# the elastic bench (bench_all config 13 / perf_regress
# multihost_elastic): the 3→2→3 self-healing scenario + the
# pre-collective death bound
# ---------------------------------------------------------------------

def run_supervise_bench(nproc=3, pace=0.2, kill_at=4, pod_timeout=2.0,
                        timeout=420, workdir=None):
    """The ISSUE-12 acceptance scenario, packaged for the bench
    harness: a CLEAN ``nproc``-process reference run of the supervised
    workload (pipelines A, B, C — see ``payload_supervise``), then the
    ELASTIC leg — worker 1 SIGKILLed mid-A (automatic 3→2 shrink with
    zero caller intervention), a replacement process rejoining mid-B
    (quiesce + 2→3 re-expansion), C clean on the re-expanded pod.
    Every artifact must be bit-identical between legs; the gate is
    scenario-vs-clean wall < 2.5x plus zero leaked arbiter bytes /
    spans / stale transport markers / stale checkpoints."""
    import shutil
    import numpy as np
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="bolt-mh-elastic-")
    env = {"BOLT_MH_PACE": pace, "BOLT_POD_TIMEOUT": pod_timeout,
           "BOLT_CHECKPOINT_EVERY": "1", "BOLT_MH_HARD_EXIT": "1",
           "BOLT_SUPERVISE_BACKOFF": "0.25"}
    try:
        out_c = os.path.join(workdir, "out-clean")
        out_e = os.path.join(workdir, "out-elastic")
        os.makedirs(out_c, exist_ok=True)
        os.makedirs(out_e, exist_ok=True)
        # -- the clean 3-wide reference -------------------------------
        res_c, out_c, _ = run_cluster(
            "supervise", nproc=nproc, devs=1, timeout=timeout,
            out_dir=out_c,
            env=dict(env, BOLT_MH_CKPT=os.path.join(workdir, "ck-clean"),
                     BOLT_POD_HB_DIR=os.path.join(workdir, "hb-clean")))
        clean_s = max(r["wall_a"] + r["wall_b"] + r["wall_c"]
                      for r in res_c)
        refs = {name: np.load(os.path.join(out_c, "%s.0.npy" % name))
                for name in ("sup_sumA", "sup_sumB", "sup_statsC_sum",
                             "sup_statsC_var")}
        # -- the elastic leg: kill mid-A, rejoin mid-B ----------------
        res, out, rcs = run_cluster(
            "supervise", nproc=nproc, devs=1, timeout=timeout,
            tolerate={1}, out_dir=out_e,
            env=dict(env, BOLT_MH_CKPT=os.path.join(workdir, "ck-el"),
                     BOLT_POD_HB_DIR=os.path.join(workdir, "hb-el"),
                     BOLT_MH_EXPECT_BSTART=str(nproc - 1)),
            worker_env={1: {"BOLT_CHAOS":
                            "stream.upload:%d:kill" % kill_at}},
            extra_workers={nproc: {"BOLT_MH_REJOINER": "1",
                                   "BOLT_MH_REJOIN_ID": "w1b"}})
        done = [r for r in res if r is not None]
        survivors = [r for r in done if not r["rejoiner"]]
        rejoiner = [r for r in done if r["rejoiner"]]
        bit = all(
            np.array_equal(np.load(os.path.join(
                out, "%s.%d.npy" % (name, r["pid"]))), refs[name])
            for r in done
            for name in refs
            if not (r["rejoiner"] and name == "sup_sumA"))
        scenario_s = max(r["wall_a"] + r["wall_b"] + r["wall_c"]
                         for r in survivors)
        return {
            "clean_s": clean_s,
            "scenario_s": scenario_s,
            "scenario_over_clean": scenario_s / clean_s,
            "detection_s": max(r.get("detection_age") or 0.0
                               for r in survivors),
            "reform_s": max(r.get("reform_s") or 0.0
                            for r in survivors),
            "recovery_s": max(r.get("recovery_s") or 0.0
                              for r in survivors),
            "rejoin_s": max(r.get("rejoin_recovery_s") or 0.0
                            for r in survivors),
            "attach_s": (rejoiner[0].get("attach_s")
                         if rejoiner else None),
            "pod_timeout": float(pod_timeout),
            "victim_rc": rcs[1],
            "survivors": len(survivors),
            "rejoined": len(rejoiner),
            "a_resumes": sum(r.get("a_resumes", 0) for r in survivors),
            "b_resumes": sum(r.get("b_resumes", 0) for r in survivors),
            "reforms": max(r.get("reforms", 0) for r in done),
            "rejoins": max(r.get("rejoins", 0) for r in done),
            "nproc_final": max(r.get("nproc_after_b", 0) for r in done),
            "budget_share_after_a": min(
                r.get("budget_share_after_a", 1.0) for r in survivors),
            "budget_share_after_b": max(
                r.get("budget_share_after_b", 0.0) for r in done),
            "bit_identical": bool(bit),
            "arbiter_bytes": max(r.get("arbiter_bytes_after", 0)
                                 for r in done),
            "stale_ckpt": sorted({c for r in done
                                  for c in r.get("stale_ckpt", [])}),
            "stale_markers": max(r.get("stale_markers", 0)
                                 for r in done),
            "leaked_spans": sum(r.get("leaked_spans", 0) for r in done),
            "blt014": all(r.get("blt014") for r in done),
            "explain_supervised": all(r.get("explain_supervised")
                                      for r in done),
        }
    except BaseException:
        own = False      # keep worker logs for post-mortem
        raise
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def run_precollective_probe(pod_timeout=2.0, timeout=180, workdir=None):
    """The closed pre-collective bound, measured: worker 1 dies at its
    FIRST upload (no collective ever dispatched); the survivor must
    catch ``PeerLostError`` within 2x ``pod_timeout`` — not gloo's
    ~30s connect timeout."""
    import shutil
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="bolt-mh-precoll-")
    try:
        res, out, rcs = run_cluster(
            "precollective", nproc=2, devs=1, timeout=timeout,
            tolerate={1}, out_dir=os.path.join(workdir, "out"),
            env={"BOLT_POD_TIMEOUT": pod_timeout,
                 "BOLT_MH_HARD_EXIT": "1",
                 "BOLT_POD_HB_DIR": os.path.join(workdir, "hb")},
            worker_env={1: {"BOLT_CHAOS": "stream.upload:1:kill"}})
        r = res[0]
        return {"victim_rc": rcs[1],
                "pre_peerlost": r.get("pre_peerlost"),
                "pre_elapsed": r.get("pre_elapsed"),
                "pre_phase": r.get("pre_phase"),
                "pod_timeout": float(pod_timeout)}
    except BaseException:
        own = False      # keep worker logs for post-mortem
        raise
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


# ---------------------------------------------------------------------
# the reform bench (bench_all config 12 / perf_regress multihost_resume)
# ---------------------------------------------------------------------

def run_reform_bench(nproc=3, nkeys=96, chunks=12, vdim=8, pace=0.25,
                     kill_at=7, pod_timeout=2.0, timeout=420,
                     workdir=None):
    """The ISSUE-11 acceptance scenario, packaged for the bench
    harness: a CLEAN ``nproc-1``-process run of the reform workload
    (the unkilled post-shrink baseline), then an ``nproc``-process run
    with worker 1 SIGKILLed mid-stream — every survivor must raise
    ``PeerLostError`` (watchdog within 2× ``BOLT_POD_TIMEOUT``),
    ``multihost.reform`` onto the survivors, and resume bit-identically
    to the clean run.  ``recovery_s`` is the max survivor wall from
    the moment it LEARNED of the loss to the resumed result (barrier
    probe + reform + resume) — the gate compares it against the clean
    run's wall (< 2.0x).  ``chunks`` must divide both the ``nproc``-
    and ``(nproc-1)``-wide key-axis assignments.

    ``pace`` (per-slab loader latency) and ``kill_at`` (the victim's
    fatal upload) place the death MID-STREAM: the gloo sockets are
    established by slab 0's collective and several watermarks are
    checkpointed, so peer death surfaces as a fast transport error and
    the resume provably skips retired slabs.  A victim killed before
    the FIRST collective instead costs gloo's own connect timeout
    (~30s) — bounded and converted, but not the fast path this bench
    measures."""
    import shutil
    import numpy as np
    own = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="bolt-mh-reform-")
    env = {"BOLT_MH_NKEYS": nkeys, "BOLT_MH_CHUNKS": chunks,
           "BOLT_MH_VDIM": vdim, "BOLT_MH_PACE": pace,
           "BOLT_POD_TIMEOUT": pod_timeout, "BOLT_MH_HARD_EXIT": "1",
           "BOLT_CHECKPOINT_EVERY": "1"}
    try:
        # -- the unkilled baseline on the post-shrink topology --------
        out_c = os.path.join(workdir, "out-clean")
        out_k = os.path.join(workdir, "out-kill")
        os.makedirs(out_c, exist_ok=True)
        os.makedirs(out_k, exist_ok=True)
        res_c, out_c, _ = run_cluster(
            "reform", nproc=nproc - 1, devs=1, timeout=timeout,
            out_dir=out_c,
            env=dict(env, BOLT_MH_CKPT=os.path.join(workdir, "ck-clean"),
                     BOLT_POD_HB_DIR=os.path.join(workdir, "hb-clean")))
        clean_s = max(r["wall_s"] for r in res_c)
        ref = np.load(os.path.join(out_c, "reform_sum.0.npy"))
        ref_ssum = np.load(os.path.join(out_c, "reform_stats_sum.0.npy"))
        ref_svar = np.load(os.path.join(out_c, "reform_stats_var.0.npy"))

        # -- the kill: nproc processes, worker 1 is the victim --------
        port, reform_port = free_ports(2)
        res, out, rcs = run_cluster(
            "reform", nproc=nproc, devs=1, timeout=timeout,
            tolerate={1}, out_dir=out_k,
            env=dict(env, BOLT_MH_CKPT=os.path.join(workdir, "ck-kill"),
                     BOLT_MH_PORT=port, BOLT_MH_REFORM_PORT=reform_port,
                     BOLT_POD_HB_DIR=os.path.join(workdir, "hb-kill")),
            worker_env={1: {"BOLT_CHAOS":
                            "stream.upload:%d:kill" % kill_at}})
        survivors = [r for r in res if r is not None]
        bit = all(
            np.array_equal(np.load(os.path.join(
                out, "reform_sum.%d.npy" % r["pid"])), ref)
            and np.array_equal(np.load(os.path.join(
                out, "reform_stats_sum.%d.npy" % r["pid"])), ref_ssum)
            and np.array_equal(np.load(os.path.join(
                out, "reform_stats_var.%d.npy" % r["pid"])), ref_svar)
            for r in survivors)
        ck_kill = os.path.join(workdir, "ck-kill")
        stale = [p for sub in ("sum", "stats")
                 for p in glob_dir(os.path.join(ck_kill, sub))]
        recovery_s = max(r.get("recovery_s") or 0.0 for r in survivors)
        return {
            "clean_s": clean_s,
            "recovery_s": recovery_s,
            "recovery_over_clean": recovery_s / clean_s,
            "detection_s": max(r.get("detection_s") or 0.0
                               for r in survivors),
            "reform_s": max(r.get("reform_s") or 0.0
                            for r in survivors),
            "resume_s": max(r.get("resume_s") or 0.0
                            for r in survivors),
            "barrier_s": max(r.get("barrier_s") or 0.0
                             for r in survivors),
            "pod_timeout": float(pod_timeout),
            "survivors": len(survivors),
            "victim_rc": rcs[1],
            "peer_lost_everywhere": all(r.get("peer_lost")
                                        for r in survivors),
            "barrier_peerlost": all(r.get("barrier_peerlost")
                                    for r in survivors),
            "sum_resumes": sum(r.get("sum_resumes", 0)
                               for r in survivors),
            "stats_resumes": sum(r.get("stats_resumes", 0)
                                 for r in survivors),
            "bit_identical": bool(bit),
            "stale_checkpoint_files": stale,
            "leaked_spans": sum(r.get("leaked_spans", 0)
                                for r in survivors),
        }
    except BaseException:
        own = False      # keep worker logs for post-mortem
        raise
    finally:
        if own:
            shutil.rmtree(workdir, ignore_errors=True)


def glob_dir(path):
    """Stream-checkpoint files still under ``path`` (the zero-stale
    gate; empty/missing dirs read clean)."""
    import glob as _glob
    return [os.path.basename(p) for p in
            _glob.glob(os.path.join(path, "stream_*"))]


# ---------------------------------------------------------------------
# standalone smoke
# ---------------------------------------------------------------------

def main():
    import shutil
    import numpy as np
    results, out, _ = run_cluster("stream_parity", nproc=2, devs=1)
    _, out1, _ = run_cluster("single_ref", nproc=1, devs=2, out_dir=out)
    ok = all(r and r["recompiles_second_pass"] == 0
             and r["leaked_spans"] == 0 for r in results)
    a = np.load(os.path.join(out, "sum.0.npy"))
    b = np.load(os.path.join(out, "sum.1.npy"))
    ref = np.load(os.path.join(out, "ref_sum.npy"))
    ok = ok and np.array_equal(a, ref) and np.array_equal(b, ref)
    for pid in (0, 1):
        for name in ("stats_sum", "stats_var"):
            got = np.load(os.path.join(out, "%s.%d.npy" % (name, pid)))
            want = np.load(os.path.join(out, "ref_%s.npy" % name))
            ok = ok and np.array_equal(got, want)
    print("multihost harness smoke:", "PASS" if ok else "FAIL")
    print(json.dumps(results, indent=1))
    shutil.rmtree(out, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker_main(int(sys.argv[2]))
    else:
        main()
