#!/usr/bin/env python
"""Localhost multi-process cluster harness: a REAL ``jax.distributed``
CPU cluster of N OS processes, for the pod-scale streaming suite.

``run_cluster(payload, ...)`` spawns N workers (each owning
``devs`` virtual CPU devices via ``--xla_force_host_platform_device_count``),
joins them through ``bolt_tpu.parallel.multihost.initialize`` (which
arms the gloo cross-process collective transport on CPU), runs the
named payload in every process, and returns the per-process JSON
results plus any ``.npy`` artifacts the payload saved.

The harness is also the pod's FAULT REPORTER: when one worker dies
(``kill -9``, an uncaught error) while its peers still run, the
survivors would block forever inside the next cross-host collective —
so the monitor terminates them and raises a POINTED ``RuntimeError``
naming the dead process and its exit code.  ``expect_dead=True``
(the checkpoint/resume kill tests) instead returns the exit codes.

Used by tests/test_multihost.py, scripts/bench_all.py (config 11) and
scripts/perf_regress.py (the ``multihost_stream`` family); run
standalone as ``python scripts/multihost_harness.py`` for a smoke pass
of the parity payload.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------------------------
# the parent side
# ---------------------------------------------------------------------

def run_cluster(payload, nproc=2, devs=1, timeout=300, env=None,
                worker_env=None, expect_dead=False, out_dir=None):
    """Stand up an ``nproc``-process cluster and run ``payload`` in
    every process.  Returns ``(results, out_dir, rcs)`` where
    ``results`` is the list of per-process result dicts (``None`` for a
    process that died) and ``rcs`` the exit codes.

    ``env`` adds to every worker's environment; ``worker_env`` is a
    ``{pid: {...}}`` per-worker overlay (how the fault tests arm
    ``BOLT_CHAOS`` on ONE process).  With ``expect_dead=False`` a
    worker death while peers still run raises the pointed
    ``RuntimeError``."""
    own_dir = out_dir is None
    if own_dir:
        out_dir = tempfile.mkdtemp(prefix="bolt-mh-")
    base = dict(os.environ)
    base.pop("BOLT_CHAOS", None)         # never inherit a stale arming
    base.update({
        "BOLT_MH_PAYLOAD": str(payload),
        "BOLT_MH_NPROC": str(nproc),
        "BOLT_MH_DEVS": str(devs),
        "BOLT_MH_PORT": str(free_port()),
        "BOLT_MH_OUT": out_dir,
    })
    if env:
        base.update({k: str(v) for k, v in env.items()})
    procs, logs = [], []
    for pid in range(nproc):
        e = dict(base)
        if worker_env and pid in worker_env:
            e.update({k: str(v) for k, v in worker_env[pid].items()})
        log = open(os.path.join(out_dir, "worker.%d.log" % pid), "wb")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(pid)],
            env=e, stdout=log, stderr=subprocess.STDOUT))
    rcs = [None] * nproc
    deadline = time.time() + timeout
    try:
        while any(rc is None for rc in rcs):
            for pid, p in enumerate(procs):
                if rcs[pid] is None:
                    rcs[pid] = p.poll()
            bad = [pid for pid, rc in enumerate(rcs)
                   if rc is not None and rc != 0]
            if bad and any(rc is None for rc in rcs):
                # a peer is gone: survivors will block in the next
                # cross-host collective forever.  Short grace (they may
                # be dying of the same injected fault), then terminate
                # and report POINTEDLY which process died.
                grace = time.time() + 3.0
                while time.time() < grace and any(
                        p.poll() is None for p in procs):
                    time.sleep(0.05)
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                for pid, p in enumerate(procs):
                    if rcs[pid] is None:
                        rcs[pid] = p.wait()
                if not expect_dead:
                    dead = bad[0]
                    raise RuntimeError(
                        "multihost cluster: process %d died (exit code "
                        "%s) before the run finished — its peers were "
                        "blocked on the next cross-host collective and "
                        "have been terminated; see %s"
                        % (dead, rcs[dead],
                           os.path.join(out_dir,
                                        "worker.%d.log" % dead)))
                break
            if time.time() > deadline:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                raise RuntimeError(
                    "multihost cluster timed out after %ss (logs in %s)"
                    % (timeout, out_dir))
            time.sleep(0.05)
        for pid, p in enumerate(procs):
            if rcs[pid] is None:
                rcs[pid] = p.wait()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()
    results = []
    for pid in range(nproc):
        path = os.path.join(out_dir, "result.%d.json" % pid)
        if os.path.exists(path):
            with open(path) as f:
                results.append(json.load(f))
        else:
            results.append(None)
    if not expect_dead:
        for pid, rc in enumerate(rcs):
            if rc != 0 or results[pid] is None:
                with open(os.path.join(out_dir, "worker.%d.log" % pid),
                          "rb") as f:
                    tail = f.read()[-4000:].decode(errors="replace")
                raise RuntimeError(
                    "multihost worker %d failed (rc=%s):\n%s"
                    % (pid, rc, tail))
    return results, out_dir, rcs


# ---------------------------------------------------------------------
# the worker side
# ---------------------------------------------------------------------

def _bootstrap(pid):
    """Per-worker preamble: force the virtual CPU topology BEFORE any
    backend query, then join the cluster through the blessed
    multihost.initialize door (which arms gloo on CPU)."""
    devs = int(os.environ["BOLT_MH_DEVS"])
    nproc = int(os.environ["BOLT_MH_NPROC"])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=%d" % devs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, _REPO)
    from bolt_tpu.parallel import multihost
    if nproc > 1:
        ok = multihost.initialize(
            coordinator_address="127.0.0.1:%s" % os.environ["BOLT_MH_PORT"],
            num_processes=nproc, process_id=pid)
        assert ok, "multihost.initialize declined"
    return multihost


# user stage funcs at module level: bytecode-identical across processes
# AND across runs, so program keys (and checkpoint fingerprints) match
ADD1 = lambda v: v + 1  # noqa: E731


def _mesh():
    import jax
    import numpy as np
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()), ("k",))


def _crafted(n, vdim):
    """Bit-exactness-crafted data: period-8 integer pattern (+ a half-
    step per value slot).  Sums are exact in f32, every shard of a
    multiple-of-8 record range has the SAME mean, so the hierarchical
    (per-shard + collective) moments equal the single-process moments
    BIT for bit — the same trick the crafted-Welford stream suite
    uses."""
    import numpy as np
    r = np.arange(n, dtype=np.float32) % 8
    v = np.arange(vdim, dtype=np.float32) * 0.5
    return (r[:, None] + v[None, :]).astype(np.float32)


def _value(barray):
    """Host value of a (possibly replicated cross-process) result."""
    from bolt_tpu.parallel import multihost
    return multihost.local_value(barray._data)


def payload_stream_parity(pid):
    """The acceptance payload: streamed sum AND fused stats('sum','var')
    over a per-process fromcallback source, with the compile-once,
    zero-leaked-span, BLT012, fromiter and explain() proofs recorded."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import analysis, engine, obs
    from bolt_tpu.parallel import multihost
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "64"))
    vdim = 8
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "16"))
    x = _crafted(n, vdim)
    mesh = _mesh()
    obs.clear()
    obs.enable()
    rows = []                       # list.append is thread-safe (the
    #                                 uploader pool calls concurrently)

    def loader(idx):
        rows.append(len(range(*idx[0].indices(n))))
        return x[idx]

    def make():
        return bolt.fromcallback(loader, (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    res = {"pid": pid, "nproc": multihost.process_count()}

    # --- streamed sum: compile-once proof across TWO passes -----------
    c0 = engine.counters()
    s1 = make().map(ADD1).sum().cache()
    c1 = engine.counters()
    np.save(os.path.join(out, "sum.%d.npy" % pid), _value(s1))
    make().map(ADD1).sum().cache()
    c2 = engine.counters()
    res["aot_first_pass"] = c1["aot_compiles"] - c0["aot_compiles"]
    res["misses_first_pass"] = c1["misses"] - c0["misses"]
    res["recompiles_second_pass"] = (
        c2["aot_compiles"] - c1["aot_compiles"]
        + c2["misses"] - c1["misses"])
    res["transfer_bytes"] = c2["transfer_bytes"] - c0["transfer_bytes"]

    # --- fused multi-stat: stats("sum", "var") one pass ---------------
    st = make().map(ADD1).stats("sum", "var")
    np.save(os.path.join(out, "stats_sum.%d.npy" % pid),
            _value(st["sum"]))
    np.save(os.path.join(out, "stats_var.%d.npy" % pid),
            _value(st["var"]))

    # --- per-process ingest contract: this process produced ONLY its
    # own shard of every slab (3 passes x its fraction of the records)
    res["rows_produced"] = sum(rows)
    res["rows_expected"] = 3 * (n // multihost.process_count())

    # --- the per-host plan in explain() -------------------------------
    res["explain_multiprocess"] = (
        "MULTI-PROCESS" in analysis.explain(make().map(ADD1))
        if multihost.process_count() > 1 else True)

    # --- BLT012: an indivisible slab refuses, and check() forecasts ---
    bad = bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                            dtype=np.float32, chunks=3,
                            per_process=True)
    if multihost.process_count() > 1:
        try:
            bad.map(ADD1).sum().cache()
            res["blt012_refused"] = False
        except ValueError as exc:
            res["blt012_refused"] = "BLT012" in str(exc)
        res["blt012_forecast"] = analysis.check(
            bad.map(ADD1)).has("BLT012")
    else:
        res["blt012_refused"] = res["blt012_forecast"] = True

    # --- fromiter: re-iterable streams per process; one-shot refuses --
    blocks = [x[i:i + chunks] for i in range(0, n, chunks)]
    fi = bolt.fromiter(blocks, (n, vdim), mesh, dtype=np.float32)
    np.save(os.path.join(out, "fromiter_sum.%d.npy" % pid),
            _value(fi.map(ADD1).sum().cache()))

    # --- a REPLICATING mesh axis: with >1 device per process, a 2-axis
    # mesh whose second axis does not shard the key replicates each
    # per-process shard across local devices — the local-box dedup and
    # the psum-over-participating-axes-only paths must still fold
    # exactly (key extent 6 keeps axis "b" unabsorbed)
    import jax
    if multihost.process_count() > 1 and len(jax.devices()) >= 4:
        from jax.sharding import Mesh
        dv = np.asarray(jax.devices()).reshape(
            multihost.process_count(), -1)
        mesh2 = Mesh(dv, ("a", "b"))
        xq = (np.arange(6 * 4) % 4).astype(np.float32).reshape(6, 4)
        srcq = bolt.fromcallback(lambda idx: xq[idx], (6, 4), mesh2,
                                 dtype=np.float32, chunks=2,
                                 per_process=True)
        sq = _value(srcq.map(ADD1).sum().cache())
        res["replicated_axis_ok"] = bool(
            np.array_equal(sq, (xq + 1).sum(axis=0)))
    if multihost.process_count() > 1:
        try:
            bolt.fromiter((b for b in blocks), (n, vdim), mesh,
                          dtype=np.float32)
            res["oneshot_refused"] = False
        except ValueError as exc:
            res["oneshot_refused"] = "one-shot" in str(exc).lower() \
                or "RE-ITERABLE" in str(exc)
    else:
        res["oneshot_refused"] = True

    res["leaked_spans"] = obs.active_count()
    obs.disable()
    return res


def payload_single_ref(pid):
    """The single-process reference: identical data and pipelines on a
    one-process mesh of the SAME total device count — the bit-identity
    baseline the 2-process run is compared against."""
    import numpy as np
    import bolt_tpu as bolt
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "64"))
    vdim = 8
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "16"))
    x = _crafted(n, vdim)
    mesh = _mesh()

    def make():
        return bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    np.save(os.path.join(out, "ref_sum.npy"),
            _value(make().map(ADD1).sum().cache()))
    st = make().map(ADD1).stats("sum", "var")
    np.save(os.path.join(out, "ref_stats_sum.npy"), _value(st["sum"]))
    np.save(os.path.join(out, "ref_stats_var.npy"), _value(st["var"]))
    blocks = [x[i:i + chunks] for i in range(0, n, chunks)]
    fi = bolt.fromiter(blocks, (n, vdim), mesh, dtype=np.float32)
    np.save(os.path.join(out, "ref_fromiter_sum.npy"),
            _value(fi.map(ADD1).sum().cache()))
    return {"pid": pid, "ok": True}


def payload_resume(pid):
    """Checkpointed streamed sum over 8 slabs; the parent arms
    BOLT_CHAOS to SIGKILL every process mid-run, then re-runs this
    payload clean — the second run must RESUME (stream_resumes >= 1)
    and reproduce the uninterrupted result bit-identically."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine
    out = os.environ["BOLT_MH_OUT"]
    ck = os.environ["BOLT_MH_CKPT"]
    n, vdim, chunks = 64, 8, 8                      # 8 slabs of 8
    x = _crafted(n, vdim)
    mesh = _mesh()
    c0 = engine.counters()
    src = bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                            dtype=np.float32, chunks=chunks,
                            checkpoint=ck, per_process=True)
    s = src.map(ADD1).sum().cache()
    c1 = engine.counters()
    np.save(os.path.join(out, "resume_sum.%d.npy" % pid), _value(s))
    return {"pid": pid,
            "resumes": c1["stream_resumes"] - c0["stream_resumes"],
            "slabs": c1["stream_chunks"] - c0["stream_chunks"]}


def payload_bench(pid):
    """The config-11 / perf-family payload: stream a larger crafted
    source through the per-process pipeline, recording this process's
    ingest bytes and wall seconds (per-process GB/s) plus the
    compile-once counters."""
    import numpy as np
    import bolt_tpu as bolt
    from bolt_tpu import engine, obs
    from bolt_tpu.obs.trace import clock
    out = os.environ["BOLT_MH_OUT"]
    n = int(os.environ.get("BOLT_MH_NKEYS", "4096"))
    vdim = int(os.environ.get("BOLT_MH_VDIM", "256"))
    chunks = int(os.environ.get("BOLT_MH_CHUNKS", "512"))
    x = _crafted(n, vdim)
    mesh = _mesh()
    obs.clear()
    obs.enable()

    def make():
        return bolt.fromcallback(lambda idx: x[idx], (n, vdim), mesh,
                                 dtype=np.float32, chunks=chunks,
                                 per_process=True)

    warm = make().map(ADD1).sum().cache()           # compile + warm
    _value(warm)
    c0 = engine.counters()
    t0 = clock()
    s = make().map(ADD1).sum().cache()
    val = _value(s)
    wall = clock() - t0
    c1 = engine.counters()
    np.save(os.path.join(out, "bench_sum.%d.npy" % pid), val)
    res = {
        "pid": pid,
        "wall_s": wall,
        "transfer_bytes": c1["transfer_bytes"] - c0["transfer_bytes"],
        "slabs": c1["stream_chunks"] - c0["stream_chunks"],
        "recompiles_warm": (c1["aot_compiles"] - c0["aot_compiles"]
                            + c1["misses"] - c0["misses"]),
        "leaked_spans": obs.active_count(),
    }
    obs.disable()
    return res


PAYLOADS = {
    "stream_parity": payload_stream_parity,
    "single_ref": payload_single_ref,
    "resume": payload_resume,
    "bench": payload_bench,
}


def worker_main(pid):
    _bootstrap(pid)
    payload = PAYLOADS[os.environ["BOLT_MH_PAYLOAD"]]
    res = payload(pid)
    out = os.environ["BOLT_MH_OUT"]
    tmp = os.path.join(out, "result.%d.json.tmp" % pid)
    with open(tmp, "w") as f:
        json.dump(res, f)
    os.replace(tmp, os.path.join(out, "result.%d.json" % pid))
    print("worker %d OK" % pid, flush=True)


# ---------------------------------------------------------------------
# standalone smoke
# ---------------------------------------------------------------------

def main():
    import shutil
    import numpy as np
    results, out, _ = run_cluster("stream_parity", nproc=2, devs=1)
    _, out1, _ = run_cluster("single_ref", nproc=1, devs=2, out_dir=out)
    ok = all(r and r["recompiles_second_pass"] == 0
             and r["leaked_spans"] == 0 for r in results)
    a = np.load(os.path.join(out, "sum.0.npy"))
    b = np.load(os.path.join(out, "sum.1.npy"))
    ref = np.load(os.path.join(out, "ref_sum.npy"))
    ok = ok and np.array_equal(a, ref) and np.array_equal(b, ref)
    for pid in (0, 1):
        for name in ("stats_sum", "stats_var"):
            got = np.load(os.path.join(out, "%s.%d.npy" % (name, pid)))
            want = np.load(os.path.join(out, "ref_%s.npy" % name))
            ok = ok and np.array_equal(got, want)
    print("multihost harness smoke:", "PASS" if ok else "FAIL")
    print(json.dumps(results, indent=1))
    shutil.rmtree(out, ignore_errors=True)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker_main(int(sys.argv[2]))
    else:
        main()
