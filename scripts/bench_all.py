#!/usr/bin/env python
"""All five BASELINE comparison configs, local (NumPy oracle) vs TPU.

``bench.py`` is the driver-facing single-line harness (config 1 + the 10 GB
north-star, timed INCLUDING the scalar result fetch); this script measures
the full config table from ``BASELINE.json``.

Timing methodology: the TPU column times device-side completion at steady
state — launches are pipelined (dispatch is async), the host syncs once on
the last result via a one-element probe, and the probe's measured pure
round-trip (~65 ms through this environment's remote tunnel — an
attachment artifact, not a property of the framework or hardware) is
subtracted.  The full-array host transfer is likewise excluded; parity
against the oracle is still asserted on the full fetched result, once,
outside the timed region.  Config 4 (filter) dispatches fully async — the
fused mask→compact→count program runs per iteration and only the LAST
result's survivor count is synced (filter results are lazy-count pending
arrays; the reference pays a Spark job per filter at the same spot).
User functions are hoisted so jit caches
hit across iterations (defining a lambda inside the timed closure would
recompile every pass — see README dtype/tracing notes).
"""

import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bolt_tpu as bolt  # noqa: E402
from bolt_tpu.utils import allclose  # noqa: E402


def timed(fn, iters=3):
    out = fn()
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return out, best


def sync(barray):
    """Force device-side completion of a bolt array via a 1-element probe.

    Indexes (never reshapes): an eager reshape of a TPU array is a physical
    relayout copy — doubling HBM for a 10 GB operand."""
    data = barray._data
    return float(np.asarray(jax.device_get(data[(0,) * data.ndim])))


def timed_tpu(launch, iters=10):
    """Steady-state device time per iteration.

    ``launch()`` must asynchronously dispatch one full iteration and return
    the bolt array to synchronise on.  Launches are pipelined (in-order
    per-device execution: the last result completing implies all ran); the
    closing probe's pure round-trip is measured on an already-materialised
    result and subtracted."""
    tail = launch()
    sync(tail)  # compile + warm
    rts = []
    for _ in range(3):
        t0 = time.perf_counter()
        sync(tail)
        rts.append(time.perf_counter() - t0)
    roundtrip = min(rts)
    keep = []  # hold references so no buffer is deleted mid-flight
    t0 = time.perf_counter()
    for _ in range(iters):
        keep.append(launch())
    sync(keep[-1])
    per_iter = (time.perf_counter() - t0 - roundtrip) / iters
    return keep[-1], per_iter


ADD1 = lambda v: v + 1
SQRT = np.sqrt
MEANPOS = lambda v: v.mean() > 0
SVALS = lambda blk: jnp.linalg.svd(blk, compute_uv=False)[None, :]


def main():
    rows = []
    rs = np.random.RandomState(0)

    # ---- config 1: ones((200,200,64,64)).map(x+1).sum() --------------
    shape = (200, 200, 64, 64)
    xl = np.ones(shape, np.float32)
    bt = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()
    axes = tuple(range(4))
    lo, lt = timed(lambda: float((xl + 1).sum(dtype=np.float32)))
    to_arr, tt = timed_tpu(lambda: bt.map(ADD1).sum(axis=axes))
    to = float(to_arr.toarray())
    rows.append(("1 map->sum 0.66GB", lt, tt, "bit-exact" if lo == to else "MISMATCH"))

    # ---- config 2: ufuncs + axis reductions over the split axis ------
    x = (np.abs(rs.randn(4096, 256, 64)) + 0.5).astype(np.float32)
    bt = bolt.array(x, mode="tpu").cache()

    def local2():
        m = np.sqrt(x)
        return m.mean(axis=0), m.std(axis=0), m.var(axis=0), m.max(axis=0)

    tpu2_outs = []

    def tpu2():
        m = bt.map(SQRT)
        tpu2_outs[:] = [getattr(m, n)() for n in ("mean", "std", "var", "max")]
        return tpu2_outs[-1]

    lo, lt = timed(local2)
    _, tt = timed_tpu(tpu2)
    ok = all(allclose(a, np.asarray(b.toarray()), rtol=1e-4, atol=1e-5)
             for a, b in zip(lo, tpu2_outs))
    rows.append(("2 ufunc+reductions", lt, tt, "allclose" if ok else "MISMATCH"))

    # ---- config 3: swap() key<->value exchange on a 4D array ---------
    x = rs.randn(512, 128, 64, 32).astype(np.float32)
    bt = bolt.array(x, mode="tpu", axis=(0, 1)).cache()
    lo_arr, lt = timed(lambda: np.ascontiguousarray(np.transpose(x, (1, 2, 0, 3))))

    to, tt = timed_tpu(lambda: bt.swap((0,), (0,)), iters=5)
    ok = allclose(lo_arr, to.toarray())
    rows.append(("3 swap all-to-all", lt, tt, "exact" if ok else "MISMATCH"))

    # ---- config 4: filter() / boolean mask on the keyed axis ---------
    x = rs.randn(16384, 128, 32).astype(np.float32)
    bt = bolt.array(x, mode="tpu").cache()
    lo_arr, lt = timed(lambda: x[x.mean(axis=(1, 2)) > 0])

    # filter dispatches async (lazy-count pending result); the closing
    # sync resolves the last iteration's count + probe
    to, tt = timed_tpu(lambda: bt.filter(MEANPOS), iters=5)
    ok = allclose(lo_arr, to.toarray())
    rows.append(("4 filter mask", lt, tt, "exact" if ok else "MISMATCH"))

    # ---- config 5: per-chunk SVD (tall-skinny PCA) -------------------
    x = rs.randn(8, 131072, 16).astype(np.float32)
    bt = bolt.array(x, mode="tpu").cache()
    nchunk, csize = 128, 1024

    def local5():
        return np.stack([np.stack([
            np.linalg.svd(x[k, i * csize:(i + 1) * csize], compute_uv=False)
            for i in range(nchunk)]) for k in range(x.shape[0])])

    lo_arr, lt = timed(local5)
    to, tt = timed_tpu(
        lambda: bt.chunk(size=(csize,), axis=(0,)).map(SVALS).unchunk(),
        iters=5)
    ok = allclose(lo_arr, to.toarray().reshape(lo_arr.shape), rtol=1e-2, atol=1e-2)
    rows.append(("5 per-chunk SVD", lt, tt, "allclose" if ok else "MISMATCH"))

    # ---- config 5b: same workload, TPU-first algorithm ---------------
    # singular values via the Gram matrix (MXU matmul + small eigvalsh)
    # instead of QR-iteration SVD — see bolt_tpu/ops svdvals docstring
    from bolt_tpu.ops import svdvals
    GRAM = lambda blk: svdvals(blk)[None, :]
    to, tt = timed_tpu(
        lambda: bt.chunk(size=(csize,), axis=(0,)).map(GRAM).unchunk(),
        iters=5)
    ok = allclose(lo_arr, to.toarray().reshape(lo_arr.shape), rtol=1e-2, atol=1e-2)
    rows.append(("5b gram-SVD (MXU)", lt, tt, "allclose" if ok else "MISMATCH"))

    print("%-22s %10s %10s %9s  %s" % ("config", "local s", "tpu s", "speedup", "parity"))
    for name, lt, tt, parity in rows:
        print("%-22s %10.4f %10.4f %8.1fx  %s" % (name, lt, tt, lt / tt, parity))
    print("(tpu column: steady-state device time; filter results are "
          "lazy-count, so config 4 pipelines like the rest and pays its "
          "single count sync only at the closing resolution)",
          file=sys.stderr)
    if any(r[3] == "MISMATCH" for r in rows):
        sys.exit(1)


if __name__ == "__main__":
    main()
