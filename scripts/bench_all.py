#!/usr/bin/env python
"""All five BASELINE comparison configs, local (NumPy oracle) vs TPU.

``bench.py`` is the driver-facing single-line harness (config 1 + the 10 GB
north-star, timed INCLUDING the scalar result fetch); this script measures
the full config table from ``BASELINE.json``.

Timing methodology: the TPU column times device-side completion at steady
state — launches are pipelined (dispatch is async), the host syncs once on
the last result via a one-element probe, and the probe's measured pure
round-trip (~65 ms through this environment's remote tunnel — an
attachment artifact, not a property of the framework or hardware) is
subtracted.  The full-array host transfer is likewise excluded; parity
against the oracle is still asserted on the full fetched result, once,
outside the timed region.  Config 4 (filter) dispatches fully async — the
fused mask→compact→count program runs per iteration and only the LAST
result's survivor count is synced (filter results are lazy-count pending
arrays; the reference pays a Spark job per filter at the same spot).
User functions are hoisted so jit caches
hit across iterations (defining a lambda inside the timed closure would
recompile every pass — see README dtype/tracing notes).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import bolt_tpu as bolt  # noqa: E402
from bolt_tpu.utils import allclose  # noqa: E402


def timed(fn, iters=3):
    out = fn()
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return out, best


def sync(barray):
    """Force device-side completion of a bolt array via a 1-element probe.

    Indexes (never reshapes): an eager reshape of a TPU array is a physical
    relayout copy — doubling HBM for a 10 GB operand."""
    data = barray._data
    return float(np.asarray(jax.device_get(data[(0,) * data.ndim])))


def timed_tpu(launch, iters=40, keep_all=True):
    """Steady-state device time per iteration.

    ``launch()`` must asynchronously dispatch one full iteration and return
    the bolt array to synchronise on.  Launches are pipelined (in-order
    per-device execution: the last result completing implies all ran); the
    closing probe's pure round-trip is measured on an already-materialised
    result and subtracted.  ``keep_all=False`` drops intermediate result
    handles as the loop runs (PJRT frees each buffer once its execution
    retires) — required for multi-GB outputs, where holding every
    iteration's result would overflow HBM (the runtime keeps ~2
    executions in flight, so queue depth never stacks buffers).

    ROUND-3 CORRECTION (BASELINE.md "measurement correction"): the
    subtracted round-trip is NOISY on this attach (28–110 ms, drifting
    between its measurement and its use), so the residual error is
    ~drift/iters per iteration.  ``iters`` therefore defaults HIGH (40):
    at 40 launches even an 80 ms drift biases a per-iter figure by only
    2 ms.  Callers timing sub-50 ms ops must not lower it; slow ops
    (≥0.2 s/iter) may, since the bias is relatively tiny there."""
    tail = launch()
    sync(tail)  # compile + warm
    rts = []
    for _ in range(3):
        t0 = time.perf_counter()
        sync(tail)
        rts.append(time.perf_counter() - t0)
    roundtrip = min(rts)
    if not keep_all:
        tail = None  # free the warm result: multi-GB outputs must not
        #              stack up (input + 2 in-flight is the HBM watermark)
    keep = []  # hold references so no buffer is deleted mid-flight
    out = None
    t0 = time.perf_counter()
    for _ in range(iters):
        out = launch()
        if keep_all:
            keep.append(out)
    sync(out)
    per_iter = (time.perf_counter() - t0 - roundtrip) / iters
    return out, per_iter


ADD1 = lambda v: v + 1
SQRT = np.sqrt
MEANPOS = lambda v: v.mean() > 0
SVALS = lambda blk: jnp.linalg.svd(blk, compute_uv=False)[None, :]


# ----------------------------------------------------------------------
# static-analysis twins: every benchmark config's DEFERRED pipeline at
# small geometry, for bolt_tpu.analysis.check — the abstract checker
# must predict each config's result shape/dtype with ZERO XLA compiles
# (engine misses unchanged).  `python scripts/bench_all.py --check`
# runs the gate standalone; tests/test_static_analysis.py runs it in
# tier-1 on the virtual CPU mesh.
# ----------------------------------------------------------------------

def pipelines(mesh=None, nkeys=16):
    """``[(config name, pipeline object)]`` — the pre-terminal deferred
    state of each BASELINE config (map chains, deferred filters, a
    chunked view over a chain, a lazy streaming source), built at toy
    sizes on ``mesh`` (default: the process default mesh)."""
    import bolt_tpu as bolt
    if mesh is None:
        from bolt_tpu.parallel import default_mesh
        mesh = default_mesh()
    rs = np.random.RandomState(7)
    k = nkeys
    x2 = (np.abs(rs.randn(k, 6, 4)) + 0.5).astype(np.float32)
    x4 = rs.randn(k, 6, 4).astype(np.float32)
    # configs 6/7's lazy out-of-core sources: nothing uploads during the
    # check — the streaming plans are interpreted abstractly
    x6 = np.ones((k, 8, 4), np.float32)
    stream6 = bolt.fromcallback(lambda idx: x6[idx], (k, 8, 4), mesh,
                                dtype=np.float32, chunks=max(1, k // 4))
    x7 = (np.arange(k * 8 * 4, dtype=np.int64) % 7).astype(
        np.float32).reshape(k, 8, 4)
    stream7 = bolt.fromcallback(lambda idx: x7[idx], (k, 8, 4), mesh,
                                dtype=np.float32, chunks=max(1, k // 4))
    x8 = rs.randn(k, 6, 4).astype(np.float32)
    x9 = np.ones((k, 8, 4), np.float32)
    stream9 = bolt.fromcallback(lambda idx: x9[idx], (k, 8, 4), mesh,
                                dtype=np.float32, chunks=max(1, k // 4))
    x10 = (np.arange(k * 8 * 4, dtype=np.int64) % 7).astype(
        np.float32).reshape(k, 8, 4)
    stream10 = bolt.fromcallback(lambda idx: x10[idx], (k, 8, 4), mesh,
                                 dtype=np.float32, chunks=max(1, k // 8))
    x11 = (np.arange(k * 8, dtype=np.int64) % 8).astype(
        np.float32).reshape(k, 8)
    stream11 = bolt.fromcallback(lambda idx: x11[idx], (k, 8), mesh,
                                 dtype=np.float32, chunks=max(1, k // 4),
                                 per_process=True)
    x12 = (np.arange(k * 8, dtype=np.int64) % 8).astype(
        np.float32).reshape(k, 8)
    stream12 = bolt.fromcallback(lambda idx: x12[idx], (k, 8), mesh,
                                 dtype=np.float32, chunks=max(1, k // 4),
                                 per_process=True)
    x13 = (np.arange(k * 8, dtype=np.int64) % 8).astype(
        np.float32).reshape(k, 8)
    stream13 = bolt.fromcallback(lambda idx: x13[idx], (k, 8), mesh,
                                 dtype=np.float32, chunks=max(1, k // 4),
                                 per_process=True)
    x15 = (np.arange(k * 8 * 4, dtype=np.int64) % 9).astype(
        np.float32).reshape(k, 8, 4)
    stream15 = bolt.fromcallback(lambda idx: x15[idx], (k, 8, 4), mesh,
                                 dtype=np.float32, chunks=max(1, k // 4),
                                 codec="bf16")
    x16 = (np.arange(k * 8 * 4, dtype=np.int64) % 11).astype(
        np.float32).reshape(k, 8, 4)
    stream16 = bolt.fromcallback(lambda idx: x16[idx], (k, 8, 4), mesh,
                                 dtype=np.float32, chunks=max(1, k // 4))
    return [
        ("1 map->sum", bolt.array(np.ones((k, 8, 4), np.float32),
                                  mesh).map(ADD1)),
        ("2 ufunc+reductions", bolt.array(x2, mesh).map(SQRT)),
        ("3 swap all-to-all", bolt.array(
            rs.randn(k, 4, 6).astype(np.float32), mesh).map(ADD1)),
        ("4 filter mask", bolt.array(x4, mesh).filter(MEANPOS)),
        ("4b filter->sum fused", bolt.array(x4, mesh).filter(MEANPOS)),
        ("5 per-chunk SVD", bolt.array(
            rs.randn(8, 32, 4).astype(np.float32),
            mesh).map(ADD1).chunk(size=(8,), axis=(0,))),
        ("6 stream chunked map->sum",
         stream6.chunk(size=(4,), axis=(0,)).map(ADD1)),
        ("7 stream_sum_parallel", stream7.map(ADD1)),
        ("8 multi_stat_fused", bolt.array(x8, mesh).map(ADD1)),
        ("9 serve_multitenant", stream9.map(ADD1)),
        ("10 stream_resume", stream10.map(ADD1)),
        ("11 multihost_stream", stream11.map(ADD1)),
        ("12 multihost_resume", stream12.map(ADD1)),
        ("13 multihost_elastic", stream13.map(ADD1)),
        ("14 serve_smallreq", bolt.array(
            np.ones((k, 8, 4), np.float32), mesh).map(ADD1)),
        ("15 stream_codec", stream15.map(ADD1)),
        ("16 stream_swap", stream16.swap((0,), (0,))),
    ]


def check_configs(mesh=None):
    """Run :func:`bolt_tpu.analysis.check` over every config pipeline;
    verify zero compiles during checking, that the predicted
    shape/dtype match the materialised result, and — with the obs
    tracer armed for the duration — that no config leaks an open span
    (``obs.active_count()`` back to zero after each).  Returns a
    process exit code (0 ok / 1 any mismatch, compile or leak)."""
    from bolt_tpu import analysis, engine, obs
    failed = False
    obs.clear()
    obs.enable()
    for name, arr in pipelines(mesh=mesh):
        c0 = engine.counters()
        rep = analysis.check(arr)
        c1 = engine.counters()
        compiled = (c1["misses"] - c0["misses"]
                    + c1["aot_compiles"] - c0["aot_compiles"]
                    + c1["dispatches"] - c0["dispatches"])
        print("== %s" % name)
        print(rep)
        target = arr.unchunk() if hasattr(arr, "unchunk") else arr
        got_shape = tuple(target.shape)          # resolves/dispatches NOW
        got_dtype = np.dtype(target.dtype)
        pred = rep.shape
        if rep.dynamic:
            shape_ok = (pred[0] is None and pred[1:] == got_shape[1:])
        else:
            shape_ok = pred == got_shape
        leaked = obs.active_count()
        ok = (shape_ok and np.dtype(rep.dtype) == got_dtype
              and compiled == 0 and leaked == 0)
        print("   predicted %s %s | executed %s %s | compiles during "
              "check: %d | leaked spans: %d -> %s"
              % (pred, rep.dtype, got_shape, got_dtype, compiled, leaked,
                 "OK" if ok else "MISMATCH"))
        failed = failed or not ok
        if name.startswith("7"):
            # the parallel-ingest executor gate (ISSUE 5): stream the
            # terminal through an uploader pool TWICE — the per-slab
            # executable (and its acc-fused level-0 twin) must compile
            # exactly once, so the second pass adds ZERO compiles; and
            # the pool run must leak no spans.  cache() forces each
            # LAZY terminal to actually stream
            from bolt_tpu import stream as _stream
            with _stream.uploaders(2):
                arr.sum().cache()            # first pass compiles
                c0 = engine.counters()
                arr.sum().cache()
                c1 = engine.counters()
            recompiled = (c1["misses"] - c0["misses"]
                          + c1["aot_compiles"] - c0["aot_compiles"])
            leaked7 = obs.active_count()
            ok7 = (recompiled == 0 and leaked7 == 0
                   and c1["stream_upload_threads"] >= 1)
            print("   streamed twice via uploader pool: recompiles on "
                  "2nd pass: %d | leaked spans: %d | uploader "
                  "high-water: %d -> %s"
                  % (recompiled, leaked7, c1["stream_upload_threads"],
                     "OK" if ok7 else "MISMATCH"))
            failed = failed or not ok7
        if name.startswith("8"):
            # the fused multi-stat gate (ISSUE 7): four terminals on
            # one chain must (a) be forecast by the checker (BLT009,
            # zero compiles), (b) fuse into ONE dispatch — the
            # bytes-read model: 1 read of the input vs 4, well under
            # the 1.25x single-pass budget — and (c) compile exactly
            # once: the second fused pass adds ZERO compiles and leaks
            # no spans.
            hs = [arr.sum(), arr.var(), arr.min(), arr.max()]
            rep8 = analysis.check(hs[0])
            s8, v8, mn8, mx8 = bolt.compute(*hs)
            c0 = engine.counters()
            h2 = [arr.sum(), arr.var(), arr.min(), arr.max()]
            bolt.compute(*h2)
            c1 = engine.counters()
            recompiled = (c1["misses"] - c0["misses"]
                          + c1["aot_compiles"] - c0["aot_compiles"])
            fused_disp = c1["dispatches"] - c0["dispatches"]
            leaked8 = obs.active_count()
            bytes_ratio = fused_disp / 1.0     # reads per fused pass
            ok8 = (rep8.has("BLT009") and recompiled == 0
                   and leaked8 == 0 and bytes_ratio <= 1.25
                   and c1["fused_stat_terminals"]
                   - c0["fused_stat_terminals"] == 4)
            print("   fused 4-terminal group: BLT009 forecast %s | "
                  "recompiles on 2nd pass: %d | dispatches (= input "
                  "reads) per fused pass: %d (budget 1.25x of the "
                  "single-pass model) | leaked spans: %d -> %s"
                  % (rep8.has("BLT009"), recompiled, fused_disp,
                     leaked8, "OK" if ok8 else "MISMATCH"))
            failed = failed or not ok8
        if name.startswith("9"):
            # the multi-tenant serving gate (ISSUE 8): N identical
            # tenants submitted concurrently must (a) COMPILE ONCE —
            # cold-cache counters for 4 tenants equal a single cold
            # tenant's (the engine's build/compile coalescing), (b)
            # return bit-identical results to the single-tenant run,
            # (c) keep the admission queue bounded, and (d) leak no
            # spans.
            from bolt_tpu import serve as _serve
            from bolt_tpu.parallel import default_mesh
            mesh9 = mesh if mesh is not None else default_mesh()
            k9 = 16
            x9 = np.ones((k9, 8, 4), np.float32)

            def make9():
                src = bolt.fromcallback(lambda idx: x9[idx],
                                        (k9, 8, 4), mesh9,
                                        dtype=np.float32,
                                        chunks=max(1, k9 // 4))
                return src.map(ADD1).sum()

            ref9 = np.asarray(make9().toarray())   # single-tenant run
            engine.clear()
            c0 = engine.counters()
            with _serve.serving(workers=4, queue_limit=8) as sv:
                futs = [sv.submit(make9(), tenant="t%d" % i)
                        for i in range(4)]
                outs = [np.asarray(f.result(timeout=600).toarray())
                        for f in futs]
                depth_hw = sv.stats()["queue_depth_high_water"]
            c1 = engine.counters()
            four9 = (c1["misses"] - c0["misses"],
                     c1["aot_compiles"] - c0["aot_compiles"])
            engine.clear()
            c0 = engine.counters()
            make9().toarray()
            c1 = engine.counters()
            one9 = (c1["misses"] - c0["misses"],
                    c1["aot_compiles"] - c0["aot_compiles"])
            leaked9 = obs.active_count()
            bit9 = all(np.array_equal(o, ref9) for o in outs)
            ok9 = (four9 == one9 and bit9 and leaked9 == 0
                   and depth_hw <= 8)
            print("   4 identical tenants: builds/compiles %s vs single "
                  "tenant %s (ONE compile across tenants) | bit-identical "
                  "to single-tenant run: %s | queue depth high-water: %d "
                  "(limit 8) | leaked spans: %d -> %s"
                  % (four9, one9, bit9, depth_hw, leaked9,
                     "OK" if ok9 else "MISMATCH"))
            failed = failed or not ok9
        if name.startswith("10"):
            # the resumable-streams gate (ISSUE 9): an uploader death
            # mid-run must leave (a) a checkpoint whose re-run resumes
            # BIT-IDENTICALLY, (b) zero leaked arbiter bytes — the
            # failed run's lease returns everything, (c) zero leaked
            # spans, (d) zero stale checkpoint files once the resumed
            # run succeeds.
            import tempfile
            from bolt_tpu import _chaos as _cha
            from bolt_tpu import checkpoint as _ckpt
            from bolt_tpu import serve as _serve
            from bolt_tpu import stream as _stream
            from bolt_tpu.parallel import default_mesh
            mesh10 = mesh if mesh is not None else default_mesh()
            k10 = 16
            x10 = (np.arange(k10 * 8 * 4, dtype=np.int64) % 7).astype(
                np.float32).reshape(k10, 8, 4)

            def make10(ck=None):
                src = bolt.fromcallback(lambda idx: x10[idx],
                                        (k10, 8, 4), mesh10,
                                        dtype=np.float32, chunks=2,
                                        checkpoint=ck)     # 8 slabs
                return src.map(ADD1).sum()

            ref10 = np.asarray(make10().toarray())
            ckd = tempfile.mkdtemp(prefix="bolt-bench-resume-")
            with _serve.serving(workers=1, budget_bytes=64 << 20) as sv:
                _cha.inject("stream.upload", nth=5)
                died = False
                try:
                    with _stream.uploaders(1):
                        make10(ckd).cache()
                except _cha.ChaosError:
                    died = True
                finally:
                    _cha.clear()
                leaked_fail = sv.stats()["arbiter"]["in_use_bytes"]
                had_ckpt = _ckpt.stream_pending(ckd)
                out10 = np.asarray(make10(ckd).toarray())
                leaked_ok = sv.stats()["arbiter"]["in_use_bytes"]
            ec10 = engine.counters()
            leaked10 = obs.active_count()
            ok10 = (died and had_ckpt and np.array_equal(out10, ref10)
                    and leaked_fail == 0 and leaked_ok == 0
                    and not _ckpt.stream_pending(ckd)
                    and ec10["stream_resumes"] >= 1 and leaked10 == 0)
            print("   uploader death mid-run: died %s | checkpoint "
                  "written %s | resumed bit-identical %s | leaked "
                  "arbiter bytes after fail/success: %d/%d | stale "
                  "checkpoint files %s | leaked spans: %d -> %s"
                  % (died, had_ckpt, np.array_equal(out10, ref10),
                     leaked_fail, leaked_ok, _ckpt.stream_pending(ckd),
                     leaked10, "OK" if ok10 else "MISMATCH"))
            failed = failed or not ok10
        if name.startswith("11"):
            # the pod-scale streaming gate (ISSUE 10): a REAL 2-process
            # jax.distributed localhost cluster streams the per-process
            # fromcallback sum + fused stats and must be (a)
            # BIT-IDENTICAL to the single-process run, (b) compiled
            # exactly once per process (second streamed pass adds zero
            # builds), (c) span-clean in every worker.  Environments
            # WITHOUT the CPU cross-process collective transport skip
            # (capability probe, like tests/test_multihost.py) — a real
            # cluster failure on a capable runtime still fails the gate.
            import shutil
            if "jax_cpu_collectives_implementation" not in getattr(
                    jax.config, "values", {}):
                print("   multihost gate SKIPPED: no CPU cross-process "
                      "collective transport on this jax")
                continue
            mh = _load_mh_harness()
            try:
                res11, out11, _ = mh.run_cluster("stream_parity",
                                                 nproc=2, devs=1)
                mh.run_cluster("single_ref", nproc=1, devs=2,
                               out_dir=out11)
            except RuntimeError as exc:
                print("   multihost cluster FAILED: %s" % exc)
                failed = True
            else:
                ref11 = np.load(os.path.join(out11, "ref_sum.npy"))
                refs = {nm: np.load(os.path.join(
                    out11, "ref_%s.npy" % nm))
                    for nm in ("stats_sum", "stats_var")}
                bit11 = all(
                    np.array_equal(np.load(os.path.join(
                        out11, "sum.%d.npy" % p)), ref11)
                    and all(np.array_equal(np.load(os.path.join(
                        out11, "%s.%d.npy" % (nm, p))), refs[nm])
                        for nm in refs)
                    for p in (0, 1))
                once11 = all(r["aot_first_pass"] > 0
                             and r["recompiles_second_pass"] == 0
                             for r in res11)
                clean11 = all(r["leaked_spans"] == 0 for r in res11)
                ok11 = bit11 and once11 and clean11 \
                    and all(r["blt012_refused"] and r["blt012_forecast"]
                            for r in res11)
                print("   2-process cluster: bit-identical to "
                      "single-process %s | compiles once per process %s "
                      "(first pass %s, second pass %s) | BLT012 "
                      "refusal+forecast %s | leaked spans %s -> %s"
                      % (bit11, once11,
                         [r["aot_first_pass"] for r in res11],
                         [r["recompiles_second_pass"] for r in res11],
                         all(r["blt012_refused"] for r in res11),
                         [r["leaked_spans"] for r in res11],
                         "OK" if ok11 else "MISMATCH"))
                failed = failed or not ok11
                shutil.rmtree(out11, ignore_errors=True)
        if name.startswith("12"):
            # the pod fault-tolerance gate (ISSUE 11): kill -9 of ONE
            # process in a 3-process cluster must (a) raise the
            # pointed PeerLostError on EVERY survivor — watchdog (and
            # barrier conversion) within 2x BOLT_POD_TIMEOUT, (b)
            # reform 3->2 and resume BIT-IDENTICALLY to the unkilled
            # 2-process run (sum AND fused stats via the pod
            # abort-path checkpoint), (c) leave ZERO stale checkpoint
            # files and ZERO leaked spans; and a serving tenant on a
            # pod must fail its in-flight future with PeerLostError,
            # read ZERO leaked arbiter bytes after the abort, and
            # drain/resume admission around the reform.
            import shutil as _sh12
            import tempfile as _tf12
            if "jax_cpu_collectives_implementation" not in getattr(
                    jax.config, "values", {}):
                print("   multihost_resume gate SKIPPED: no CPU "
                      "cross-process collective transport on this jax")
                continue
            mh = _load_mh_harness()
            try:
                r12 = mh.run_reform_bench()
                base12 = _tf12.mkdtemp(prefix="bolt-bench-servepod-")
                res12, out12, rcs12 = mh.run_cluster(
                    "serve_pod", nproc=2, devs=1, timeout=200,
                    tolerate={1},
                    env={"BOLT_POD_TIMEOUT": 2, "BOLT_MH_HARD_EXIT": "1",
                         "BOLT_POD_HB_DIR": os.path.join(base12, "hb")},
                    worker_env={1: {"BOLT_CHAOS":
                                    "stream.upload:5:kill"}})
            except RuntimeError as exc:
                print("   multihost_resume cluster FAILED: %s" % exc)
                failed = True
            else:
                sp12 = res12[0]
                ok12 = (r12["peer_lost_everywhere"]
                        and r12["barrier_peerlost"]
                        and r12["detection_s"] <= 2 * r12["pod_timeout"]
                        and r12["barrier_s"] <= 2 * r12["pod_timeout"]
                        and r12["bit_identical"]
                        and r12["sum_resumes"] >= 2
                        and r12["stats_resumes"] >= 2
                        and r12["stale_checkpoint_files"] == []
                        and r12["leaked_spans"] == 0
                        and sp12["future_error"] == "PeerLostError"
                        and sp12["arbiter_bytes_after_abort"] == 0
                        and sp12["pod_paused"] and sp12["pod_resumed"]
                        and sp12["leaked_spans"] == 0)
                print("   3->2 kill -9: PeerLostError on every survivor "
                      "%s (detection %.2fs, barrier %.4fs, deadline "
                      "%.1fs) | reform %.2fs + resume %.2fs, "
                      "bit-identical %s (sum resumes %d, stats resumes "
                      "%d) | stale ckpt files %s | leaked spans %d | "
                      "serve: future=%s arbiter_bytes=%d "
                      "paused/resumed=%s/%s -> %s"
                      % (r12["peer_lost_everywhere"], r12["detection_s"],
                         r12["barrier_s"], r12["pod_timeout"],
                         r12["reform_s"], r12["resume_s"],
                         r12["bit_identical"], r12["sum_resumes"],
                         r12["stats_resumes"],
                         r12["stale_checkpoint_files"],
                         r12["leaked_spans"], sp12["future_error"],
                         sp12["arbiter_bytes_after_abort"],
                         sp12["pod_paused"], sp12["pod_resumed"],
                         "OK" if ok12 else "MISMATCH"))
                failed = failed or not ok12
                _sh12.rmtree(out12, ignore_errors=True)
                _sh12.rmtree(base12, ignore_errors=True)
        if name.startswith("13"):
            # the self-healing pod gate (ISSUE 12): kill -9 of ONE
            # process under Server(supervise=True) must (a) shrink 3->2
            # and RE-EXPAND 2->3 (a replacement process rejoins
            # mid-stream) with ZERO caller intervention, (b) stay
            # BIT-IDENTICAL to the unkilled 3-process run for every
            # artifact (sums A/B, fused stats C), (c) finish under 2.5x
            # the clean wall with zero leaked arbiter bytes / spans /
            # stale checkpoints / stale transport markers, (d) flag
            # BLT014 and render the SUPERVISED explain() plan on the
            # live pod; and a peer dead BEFORE the first collective
            # must raise PeerLostError within 2x BOLT_POD_TIMEOUT (the
            # pre-collective bound, closed).
            if "jax_cpu_collectives_implementation" not in getattr(
                    jax.config, "values", {}):
                print("   multihost_elastic gate SKIPPED: no CPU "
                      "cross-process collective transport on this jax")
                continue
            mh = _load_mh_harness()
            try:
                r13 = mh.run_supervise_bench()
                p13 = mh.run_precollective_probe()
            except RuntimeError as exc:
                print("   multihost_elastic cluster FAILED: %s" % exc)
                failed = True
            else:
                # resume-count gate vs the SCENARIO'S OWN run, not the
                # committed PERF.json tally (the PR 13 flake): under
                # full-suite load the kill can land before a survivor's
                # first checkpoint, so per-survivor resume counts are
                # timing-dependent — the proof the resume PATH works is
                # >= 1 resume per recovery leg, and correctness is the
                # bit-identity gate either way
                ok13 = (r13["victim_rc"] == -9
                        and r13["survivors"] == 2
                        and r13["rejoined"] == 1
                        and r13["nproc_final"] == 3
                        and r13["detection_s"] <= 2 * r13["pod_timeout"]
                        and r13["scenario_over_clean"] < 2.5
                        and r13["bit_identical"]
                        and r13["a_resumes"] >= 1
                        and r13["b_resumes"] >= 1
                        and r13["arbiter_bytes"] == 0
                        and r13["leaked_spans"] == 0
                        and r13["stale_ckpt"] == []
                        and r13["stale_markers"] == 0
                        and r13["blt014"]
                        and r13["explain_supervised"]
                        and p13["pre_peerlost"]
                        and p13["pre_elapsed"]
                        <= 2 * p13["pod_timeout"])
                print("   3->2->3 supervised: victim rc %s, detection "
                      "%.2fs (deadline %.1fs), reform %.3fs, rejoin "
                      "%.3fs — scenario %.3fs vs clean %.3fs (%.2fx, "
                      "gate < 2.5x), resumes A/B %d/%d, final width %d, "
                      "budget share %.2f->%.2f, bit-identical %s | "
                      "leaks: arbiter %d spans %d stale-ckpt %s "
                      "stale-markers %d | BLT014 %s explain %s | "
                      "pre-collective PeerLost %.2fs (bound %.1fs) -> %s"
                      % (r13["victim_rc"], r13["detection_s"],
                         r13["pod_timeout"], r13["reform_s"],
                         r13["rejoin_s"], r13["scenario_s"],
                         r13["clean_s"], r13["scenario_over_clean"],
                         r13["a_resumes"], r13["b_resumes"],
                         r13["nproc_final"],
                         r13["budget_share_after_a"],
                         r13["budget_share_after_b"],
                         r13["bit_identical"], r13["arbiter_bytes"],
                         r13["leaked_spans"], r13["stale_ckpt"],
                         r13["stale_markers"], r13["blt014"],
                         r13["explain_supervised"],
                         p13["pre_elapsed"] or -1.0,
                         2 * p13["pod_timeout"],
                         "OK" if ok13 else "MISMATCH"))
                failed = failed or not ok13
        if name.startswith("14"):
            # the continuous micro-batching gate (ISSUE 13): queued
            # same-key small requests under Server(batching=...) must
            # (a) coalesce into batched dispatches whose every result is
            # BIT-IDENTICAL to its standalone dispatch, (b) run ZERO
            # fresh XLA compiles at steady state across the bucketed
            # widths (batched.warm pre-compiles them), (c) be forecast
            # by the checker (BLT015, zero compiles), (d) leak no spans
            # and leave zero arbiter bytes in use.
            from bolt_tpu import serve as _serve
            from bolt_tpu.tpu import batched as _batched
            from bolt_tpu.parallel import default_mesh
            mesh14 = mesh if mesh is not None else default_mesh()
            k14 = 16
            xs14 = [np.full((k14, 8, 4), float(i + 1), np.float32)
                    for i in range(6)]
            b14 = [bolt.array(x, mesh14).cache() for x in xs14]

            def make14(i=0):
                return b14[i % 6].map(ADD1).sum()

            refs14 = [np.asarray(make14(i).toarray()) for i in range(6)]
            with _serve.serving(workers=2, queue_limit=64,
                                batching={"max_batch": 8,
                                          "linger": 0.01}) as sv:
                rep14 = analysis.check(make14())      # BLT015 forecast
                c0 = engine.counters()
                _batched.warm(make14, buckets=sv.batching.buckets)
                c1 = engine.counters()
                warm_compiled = (c1["misses"] - c0["misses"]
                                 + c1["aot_compiles"] - c0["aot_compiles"])
                c0 = engine.counters()
                futs = [sv.submit(make14(i), tenant="t%d" % (i % 3))
                        for i in range(24)]
                outs14 = [np.asarray(f.result(timeout=600).toarray())
                          for f in futs]
                c1 = engine.counters()
                leaked_bytes = sv.stats()["arbiter"]["in_use_bytes"]
                occ = sv.stats()["batching"]["occupancy"]
            recompiled = (c1["misses"] - c0["misses"]
                          + c1["aot_compiles"] - c0["aot_compiles"])
            batched_disp = (c1["batched_dispatches"]
                            - c0["batched_dispatches"])
            bit14 = all(np.array_equal(o, refs14[i % 6])
                        for i, o in enumerate(outs14))
            leaked14 = obs.active_count()
            ok14 = (rep14.has("BLT015") and warm_compiled > 0
                    and recompiled == 0 and batched_disp >= 1
                    and bit14 and leaked_bytes == 0 and leaked14 == 0)
            print("   serve micro-batching: BLT015 forecast %s | warm "
                  "compiles %d then steady-state recompiles %d across "
                  "bucketed widths | batched dispatches %d (occupancy "
                  "%s) | bit-identical %s | leaked arbiter bytes %d | "
                  "leaked spans %d -> %s"
                  % (rep14.has("BLT015"), warm_compiled, recompiled,
                     batched_disp, occ, bit14, leaked_bytes, leaked14,
                     "OK" if ok14 else "MISMATCH"))
            failed = failed or not ok14
        if name.startswith("15"):
            # the codec-encoded ingest gate (ISSUE 14): (a) BLT016
            # forecast (zero compiles — already gated above), (b) the
            # bf16-encoded stream moves <= 0.55x the raw f32 bytes
            # through the transfer counters, (c) the LOSSLESS codec is
            # BIT-IDENTICAL to uncompressed streaming, (d) the second
            # encoded pass adds ZERO fresh compiles, (e) zero leaked
            # spans and zero arbiter bytes after streaming under a
            # serving budget.
            from bolt_tpu import serve as _serve
            from bolt_tpu.parallel import default_mesh
            mesh15 = mesh if mesh is not None else default_mesh()
            k15 = 16
            x15g = (np.arange(k15 * 8 * 4, dtype=np.int64) % 9).astype(
                np.float32).reshape(k15, 8, 4)

            def make15(codec=None):
                src = bolt.fromcallback(lambda idx: x15g[idx],
                                        (k15, 8, 4), mesh15,
                                        dtype=np.float32, chunks=4,
                                        codec=codec)
                return src.map(ADD1).sum()

            ref15 = np.asarray(make15().toarray())
            with _serve.serving(workers=1, budget_bytes=64 << 20) as sv:
                c0 = engine.counters()
                out_b = np.asarray(make15("bf16").toarray())
                c1 = engine.counters()
                out_b2 = np.asarray(make15("bf16").toarray())
                c2 = engine.counters()
                out_l = np.asarray(make15("delta-f32").toarray())
                leak_bytes15 = sv.stats()["arbiter"]["in_use_bytes"]
            ratio15 = (c1["transfer_bytes"] - c0["transfer_bytes"]) \
                / float(x15g.nbytes)
            recomp15 = (c2["misses"] - c1["misses"]
                        + c2["aot_compiles"] - c1["aot_compiles"])
            bit15 = np.array_equal(out_l, ref15)
            det15 = np.array_equal(out_b, out_b2)     # deterministic
            close15 = bool(np.allclose(out_b, ref15, rtol=1e-2))
            leaked15 = obs.active_count()
            ok15 = (rep.has("BLT016") and ratio15 <= 0.55 and bit15
                    and det15 and close15 and recomp15 == 0
                    and leaked15 == 0 and leak_bytes15 == 0)
            print("   codec ingest: BLT016 forecast %s | bf16 wire "
                  "bytes %.2fx raw (gate <= 0.55) | lossless "
                  "bit-identical %s | bf16 within envelope %s, "
                  "deterministic %s | recompiles on 2nd encoded pass "
                  "%d | leaked arbiter bytes %d | leaked spans %d -> %s"
                  % (rep.has("BLT016"), ratio15, bit15, close15, det15,
                     recomp15, leak_bytes15, leaked15,
                     "OK" if ok15 else "MISMATCH"))
            failed = failed or not ok15
        if name.startswith("16"):
            # the out-of-core shuffle gate (ISSUE 18): a swap recorded
            # on a streamed source must (a) forecast its shuffle plan
            # (BLT017) in AGREEMENT with the measured resident/spill
            # decision — the checker runs the same planner against the
            # same budget resolution as the dispatcher, so drift here
            # is a real bug, (b) stay bit-identical to the
            # materialise-first transpose on BOTH the resident and the
            # forced-spill legs, (c) add ZERO fresh compiles on a
            # second identical pass, and (d) leave nothing behind: no
            # leaked spans, no arbiter bytes, no spill files after
            # spill_clear.
            import shutil as _sh16
            import tempfile as _tf16
            from bolt_tpu import checkpoint as _ckpt16
            from bolt_tpu import serve as _serve16
            from bolt_tpu import stream as _stream16
            from bolt_tpu.parallel import default_mesh
            mesh16 = mesh if mesh is not None else default_mesh()
            k16 = 16
            x16g = (np.arange(k16 * 8 * 4, dtype=np.int64) % 11).astype(
                np.float32).reshape(k16, 8, 4)

            def make16():
                src = bolt.fromcallback(lambda idx: x16g[idx],
                                        (k16, 8, 4), mesh16,
                                        dtype=np.float32, chunks=4)
                return src.swap((0,), (0,))

            def blt017(a):
                ds = [d for d in analysis.check(a).diagnostics
                      if d.code == "BLT017"]
                return ds[0] if ds else None

            ref16 = np.transpose(x16g, (1, 0, 2))
            td16 = _tf16.mkdtemp(prefix="bolt-gate16-")
            with _serve16.serving(workers=1, budget_bytes=64 << 20) as sv:
                d_res = blt017(make16())
                c0 = engine.counters()
                out_res = np.asarray(make16()._data)
                c1 = engine.counters()
                out_res2 = np.asarray(make16()._data)
                c2 = engine.counters()
                with _stream16.spill(dir=td16, budget=1):
                    d_sp = blt017(make16())
                    out_sp = np.asarray(make16()._data)
                c3 = engine.counters()
                leak_bytes16 = sv.stats()["arbiter"]["in_use_bytes"]
            forecast_res = (d_res is not None and d_res.severity == "info"
                            and "resident" in d_res.message)
            forecast_sp = (d_sp is not None and d_sp.severity == "info"
                           and "spill" in d_sp.message)
            ran_res = (c1["spill_bytes"] == c0["spill_bytes"]
                       and c1["shuffle_bytes"] > 0)
            ran_sp = c3["spill_bytes"] > c1["spill_bytes"]
            recomp16 = (c2["misses"] - c1["misses"]
                        + c2["aot_compiles"] - c1["aot_compiles"])
            spilled_files16 = _ckpt16.spill_pending(td16)
            _ckpt16.spill_clear(td16)
            cleared16 = not _ckpt16.spill_pending(td16)
            _sh16.rmtree(td16, ignore_errors=True)
            bit16 = (np.array_equal(out_res, ref16)
                     and np.array_equal(out_res2, ref16)
                     and np.array_equal(out_sp, ref16))
            leaked16 = obs.active_count()
            ok16 = (forecast_res and forecast_sp and ran_res and ran_sp
                    and spilled_files16 and cleared16 and bit16
                    and recomp16 == 0 and leaked16 == 0
                    and leak_bytes16 == 0)
            print("   stream_swap: BLT017 forecast resident %s / spill "
                  "%s agree with measured %s/%s | bit-identical %s | "
                  "recompiles on 2nd pass %d | leaked arbiter bytes %d "
                  "| leaked spans %d | spill dir cleared %s -> %s"
                  % (forecast_res, forecast_sp, ran_res, ran_sp, bit16,
                     recomp16, leak_bytes16, leaked16, cleared16,
                     "OK" if ok16 else "MISMATCH"))
            failed = failed or not ok16
    obs.disable()
    # thread-census hygiene: every pool/watch/supervisor the configs
    # started must be torn down — a leaked bolt-* thread is an executor
    # that skipped its shutdown path
    census = obs.thread_census()
    print("thread census after all configs: %s -> %s"
          % (census or "{}", "OK" if not census else "LEAKED"))
    failed = failed or bool(census)
    return 1 if failed else 0


def _load_mh_harness():
    """The localhost multi-process cluster harness (shared loader:
    bolt_tpu.utils.load_script)."""
    from bolt_tpu.utils import load_script
    return load_script("multihost_harness")


# ----------------------------------------------------------------------
# Bit-identical pseudo-random data on BOTH sides without moving a byte
# through the host<->device tunnel (~17 MB/s here: shipping a 2 GB input
# or fetching a 2 GB result would take ~2 minutes and time the tunnel,
# not the chip).  A u32 LCG + xorshift is exact integer arithmetic with
# identical wraparound in numpy and jnp; the top 24 bits convert to
# float32 exactly, so tpu-generated and host-generated arrays are EQUAL,
# and parity can be asserted on small sampled slices of big results.
# ----------------------------------------------------------------------

def lcg_np(shape, salt=0):
    # blockwise + in-place: the naive expression materialises ~6 full-
    # size temporaries, which on a slow host measured 158 s for 4.3 GB;
    # one preallocated output and 64 MB scratch blocks cut that ~4x
    n = int(np.prod(shape))
    out = np.empty(n, np.float32)
    step = 1 << 24
    for s in range(0, n, step):
        e = min(s + step, n)
        v = np.arange(s, e, dtype=np.uint32)
        v += np.uint32(salt)
        v *= np.uint32(2654435761)
        v += np.uint32(12345)
        v ^= v >> np.uint32(13)
        v >>= np.uint32(8)
        blk = v.astype(np.float32)
        blk /= np.float32(1 << 24)
        blk -= np.float32(0.5)
        out[s:e] = blk
    return out.reshape(shape)


def lcg_tpu(shape, axis=(0,), salt=0):
    from bolt_tpu.parallel.sharding import key_sharding
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.parallel import default_mesh
    mesh = default_mesh()
    split = len(axis)

    def gen():
        n = int(np.prod(shape))
        i = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(salt)
        v = i * jnp.uint32(2654435761) + jnp.uint32(12345)
        v = v ^ (v >> jnp.uint32(13))
        out = ((v >> jnp.uint32(8)).astype(jnp.float32)
               / jnp.float32(1 << 24) - jnp.float32(0.5))
        return out.reshape(shape)

    data = jax.jit(gen, out_shardings=key_sharding(mesh, shape, split))()
    return BoltArrayTPU(data, split, mesh)


def fetch(barray, index):
    """Small sampled slice of a device result (never the full array)."""
    return np.asarray(barray[index].toarray())


def main():
    def _progress(*row):
        print("done: %s  local=%.3fs tpu=%.4fs %s" % row,
              file=sys.stderr, flush=True)
        return row

    rows = []
    rs = np.random.RandomState(0)

    # ---- config 1: ones((200,200,64,64)).map(x+1).sum() --------------
    shape = (200, 200, 64, 64)
    xl = np.ones(shape, np.float32)
    bt = bolt.ones(shape, mode="tpu", dtype=np.float32).cache()
    axes = tuple(range(4))
    lo, lt = timed(lambda: float((xl + 1).sum(dtype=np.float32)))
    # .cache() forces each LAZY stat terminal to dispatch (async) so
    # every pipelined iteration really runs — stat results are pending
    # fused-group handles since the bolt.compute layer
    to_arr, tt = timed_tpu(lambda: bt.map(ADD1).sum(axis=axes).cache())
    to = float(to_arr.toarray())
    rows.append(_progress("1 map->sum 0.66GB", lt, tt, "bit-exact" if lo == to else "MISMATCH"))

    # ---- config 2: ufuncs + axis reductions over the split axis ------
    # 2.1 GB (round 2): the round-1 268 MB shape measured 3.6 ms — at or
    # below this environment's ~3 ms dispatch floor, so the speedup said
    # more about the tunnel than the chip (VERDICT r1 weak-4)
    shape2 = (8192, 1024, 64)
    x = np.abs(lcg_np(shape2)) + np.float32(0.5)
    bt = lcg_tpu(shape2).map(lambda v: jnp.abs(v) + 0.5).cache()

    def local2():
        m = np.sqrt(x)
        return m.mean(axis=0), m.std(axis=0), m.var(axis=0), m.max(axis=0)

    tpu2_outs = []

    def tpu2():
        m = bt.map(SQRT)
        # cache() per terminal: resolve each standalone (4 sequential
        # passes, the config's historical meaning) instead of letting
        # the four lazy handles fuse into one multi-stat pass —
        # config 8 measures the fused form
        tpu2_outs[:] = [getattr(m, n)().cache()
                        for n in ("mean", "std", "var", "max")]
        return tpu2_outs[-1]

    lo, lt = timed(local2, iters=2)
    _, tt = timed_tpu(tpu2)
    # reduced outputs are small (value-shaped): full-fetch parity
    ok = all(allclose(a, np.asarray(b.toarray()), rtol=1e-4, atol=1e-5)
             for a, b in zip(lo, tpu2_outs))
    rows.append(_progress("2 ufunc+reductions 2.1GB", lt, tt, "allclose" if ok else "MISMATCH"))
    del x

    # ---- config 3: swap() key<->value exchange on a 4D array ---------
    # 4.3 GB (round 2, was 512 MB / 0.7 ms — floor-bound); intermediate
    # swap outputs are dropped as the loop runs (keep_all=False: 24
    # retained 4.3 GB results would overflow HBM many times over — the
    # runtime's ~2 in-flight executions bound the true watermark)
    del bt
    # 4.3 GB: at 2.1 GB the swap measured 6.3 ms — genuinely ~670 GB/s
    # read+write but still within 3x of the dispatch floor; doubling the
    # size puts device time unambiguously in charge.  keep_all=False
    # (plus timed_tpu freeing the warm result) bounds the HBM watermark
    # at input + ~2 in-flight 4.3 GB outputs regardless of iters, so
    # iters=6 amortises closing-sync jitter properly.
    shape3 = (2048, 128, 64, 64)
    x = lcg_np(shape3, salt=3)
    bt = lcg_tpu(shape3, axis=(0, 1), salt=3).cache()
    lo_arr, lt = timed(
        lambda: np.ascontiguousarray(np.transpose(x, (1, 2, 0, 3))), iters=1)

    to, tt = timed_tpu(lambda: bt.swap((0,), (0,)), iters=24, keep_all=False)
    # 4.3 GB output: parity on sampled slices (identical LCG data on both
    # sides), not a minutes-long full fetch through the tunnel
    ok = (to.shape == lo_arr.shape
          and allclose(lo_arr[5, 9], fetch(to, np.s_[5, 9]))
          and allclose(lo_arr[127, 63], fetch(to, np.s_[127, 63]))
          and allclose(lo_arr[:, 0, 17], fetch(to, np.s_[:, 0, 17])))
    rows.append(_progress("3 swap all-to-all 4.3GB", lt, tt, "exact*" if ok else "MISMATCH"))
    del x, lo_arr

    # ---- config 4: filter() / boolean mask on the keyed axis ---------
    # 0.94 GB (round 2, was 268 MB): the largest size that keeps the
    # fused lazy-count path (its padded compaction buffer doubles HBM,
    # capped at 1 GB) so iterations still pipeline
    del bt, to
    shape4 = (14336, 256, 64)
    x = lcg_np(shape4, salt=4)
    bt = lcg_tpu(shape4, salt=4).cache()
    lo_arr, lt = timed(lambda: x[x.mean(axis=(1, 2)) > 0], iters=2)

    # filter() now DEFERS (reduction terminals fuse the predicate);
    # materialising configs must dispatch the compaction program
    # explicitly so every pipelined iteration runs.  keep_all=False:
    # at 24 iterations the pending results' padded buffers (0.94 GB
    # each) must retire as the loop runs, not accumulate
    def launch4():
        out = bt.filter(MEANPOS)
        out._resolve_fpending()     # async dispatch, count stays on device
        return out

    to, tt = timed_tpu(launch4, iters=24, keep_all=False)
    # ~0.5 GB of survivors: parity on count + sampled survivor rows
    ok = (to.shape == lo_arr.shape
          and allclose(lo_arr[:2], fetch(to, np.s_[:2]))
          and allclose(lo_arr[-1], fetch(to, np.s_[-1])))
    rows.append(_progress("4 filter mask 0.94GB", lt, tt, "exact*" if ok else "MISMATCH"))

    # ---- config 4b: fused filter→sum terminal (ISSUE 1) --------------
    # the predicate folds into the reduction combine: ONE pass over the
    # input, no compaction buffer — vs config 4's ~3 passes
    lo_sum, lt4b = timed(lambda: x[x.mean(axis=(1, 2)) > 0].sum(axis=0),
                         iters=2)
    to4b, tt4b = timed_tpu(lambda: bt.filter(MEANPOS).sum().cache(),
                           iters=24)
    ok4b = allclose(lo_sum, fetch(to4b, np.s_[:]), rtol=1e-4)
    rows.append(_progress("4b filter->sum fused 0.94GB", lt4b, tt4b,
                          "close*" if ok4b else "MISMATCH"))
    del x, lo_arr

    # ---- config 5: per-chunk SVD (tall-skinny PCA) -------------------
    # 2.1 GB (round 2, was 67 MB): 32768 chunks of (1024, 16)
    del bt, to
    shape5 = (8, 4194304, 16)
    x = lcg_np(shape5, salt=5)
    bt = lcg_tpu(shape5, salt=5).cache()
    nchunk, csize = 4096, 1024

    def local5():
        return np.stack([np.stack([
            np.linalg.svd(x[k, i * csize:(i + 1) * csize], compute_uv=False)
            for i in range(nchunk)]) for k in range(x.shape[0])])

    lo_arr, lt = timed(local5, iters=1)
    to, tt = timed_tpu(
        lambda: bt.chunk(size=(csize,), axis=(0,)).map(SVALS).unchunk(),
        iters=5)
    # output is small ((8, 4096, 16) = 2 MB): full-fetch parity
    ok = allclose(lo_arr, to.toarray().reshape(lo_arr.shape), rtol=1e-2, atol=1e-2)
    rows.append(_progress("5 per-chunk SVD 2.1GB", lt, tt, "allclose" if ok else "MISMATCH"))

    # ---- config 5b: same workload, TPU-first algorithm ---------------
    # singular values via the Gram matrix (MXU matmul + small eigvalsh)
    # instead of QR-iteration SVD — see bolt_tpu/ops svdvals docstring
    from bolt_tpu.ops import svdvals
    GRAM = lambda blk: svdvals(blk)[None, :]
    to, tt = timed_tpu(
        lambda: bt.chunk(size=(csize,), axis=(0,)).map(GRAM).unchunk(),
        iters=5)
    ok = allclose(lo_arr, to.toarray().reshape(lo_arr.shape), rtol=1e-2, atol=1e-2)
    rows.append(_progress("5b gram-SVD (MXU) 2.1GB", lt, tt, "allclose" if ok else "MISMATCH"))

    # ---- config 6: streamed out-of-core map->sum (stream_sum) --------
    # the ISSUE-3 executor: host-resident data streams slab-by-slab
    # through the double-buffered prefetch pipeline into the fused
    # per-slab map+sum, partials merging on device.  The host array here
    # FITS in RAM (it must, to build the oracle), but the device only
    # ever holds prefetch-depth slabs — the timing is the out-of-core
    # ingest path: host->device transfer overlapped with compute, so it
    # gauges the attach link, not HBM.  A streamed run is synchronous
    # end-to-end (the executor blocks per slab), so it is timed directly
    # rather than through the async-launch harness.
    del bt, to, x, lo_arr
    shape6 = (8192, 256, 64)                      # 0.5 GB over the link
    x6 = lcg_np(shape6, salt=6)
    lo6, lt6 = timed(lambda: (x6 + 1).sum(axis=0, dtype=np.float32),
                     iters=2)

    def launch6():
        src = bolt.fromcallback(lambda idx: x6[idx], shape6, mode="tpu",
                                dtype=np.float32, chunks=512)
        return src.chunk(size=(64,), axis=(0,)).map(ADD1).sum()

    from bolt_tpu import profile as _profile
    sync(launch6())                               # compile the slab programs
    c0 = _profile.engine_counters()
    t0 = time.perf_counter()
    to6 = launch6()
    sync(to6)
    tt6 = time.perf_counter() - t0
    c1 = _profile.engine_counters()
    dl = {k: c1[k] - c0[k] for k in c1}
    eff = (dl["stream_overlap_seconds"] / dl["stream_ingest_seconds"]
           if dl["stream_ingest_seconds"] else 0.0)
    print("   stream_sum: %d slabs, %.0f MB shipped, overlap_efficiency "
          "%.2f" % (dl["stream_chunks"], dl["transfer_bytes"] / 1e6, eff),
          file=sys.stderr)
    ok6 = allclose(lo6, np.asarray(to6.toarray()), rtol=1e-4, atol=1e-4)
    rows.append(_progress("6 stream_sum 0.5GB ingest", lt6, tt6,
                          "allclose" if ok6 else "MISMATCH"))

    # ---- config 7: parallel-ingest streamed sum (ISSUE 5) ------------
    # the same out-of-core workload as config 6 through the N-way
    # uploader pool + async dispatch: workers produce AND upload slabs
    # concurrently (per-device sub-blocks), slab programs dispatch into
    # the bounded in-flight window with the level-0 fold fused in.  The
    # counter deltas prove the pipeline: >1 concurrent uploader and
    # ~half the dispatches per slab of the pre-pool executor.
    from bolt_tpu import stream as _stream
    with _stream.uploaders(4):
        sync(launch6())                       # warm the pool-run programs
        c0 = _profile.engine_counters()
        t0 = time.perf_counter()
        to7 = launch6()
        sync(to7)
        tt7 = time.perf_counter() - t0
        c1 = _profile.engine_counters()
    dl = {k: c1[k] - c0[k] for k in c1}
    eff7 = (dl["stream_overlap_seconds"] / dl["stream_ingest_seconds"]
            if dl["stream_ingest_seconds"] else 0.0)
    print("   stream_sum_parallel: %d slabs, %.0f MB shipped, "
          "concurrent uploaders (hw) %d, in-flight hw %d, "
          "dispatches/slab %.2f, overlap_efficiency %.2f"
          % (dl["stream_chunks"], dl["transfer_bytes"] / 1e6,
             c1["stream_upload_threads"],
             c1["stream_inflight_high_water"],
             dl["dispatches"] / max(dl["stream_chunks"], 1), eff7),
          file=sys.stderr)
    ok7 = allclose(lo6, np.asarray(to7.toarray()), rtol=1e-4, atol=1e-4)
    rows.append(_progress("7 stream_sum_parallel", lt6, tt7,
                          "allclose" if ok7 else "MISMATCH"))

    # ---- config 8: fused multi-stat terminal (ISSUE 7) ---------------
    # bolt.compute(m.sum(), m.var(), m.min(), m.max()): four terminals
    # from ONE pass over a >= 1 GB input — vs the sequential form's four
    # passes.  The bytes-read model is dispatch-counted (one fused
    # dispatch over the chain = one read of the input; four standalone
    # dispatches = four reads); the measured ratio is wall-clock.
    # Parity is the acceptance contract: every fused result BIT-equal
    # to its standalone terminal.
    shape8 = (8192, 256, 128)                     # 1.07 GB f32
    x8 = lcg_np(shape8, salt=8)
    bt8 = lcg_tpu(shape8, salt=8).cache()
    lo8, lt8 = timed(lambda: ((x8 + 1).sum(axis=0),
                              (x8 + 1).var(axis=0),
                              (x8 + 1).min(axis=0),
                              (x8 + 1).max(axis=0)), iters=1)

    def fused8():
        m = bt8.map(ADD1)
        s, v, mn, mx = bolt.compute(m.sum(), m.var(), m.min(), m.max())
        return mx                 # all four share the one dispatch

    def seq8():
        m = bt8.map(ADD1)
        # resolve one at a time: each singleton group dispatches its own
        # standalone pass (the pre-fusion cost model)
        m.sum().cache()
        m.var().cache()
        m.min().cache()
        return m.max().cache()

    from bolt_tpu import engine as _engine8
    _, tt8s = timed_tpu(seq8, iters=8)
    c0 = _engine8.counters()
    to8, tt8 = timed_tpu(fused8, iters=8)
    c1 = _engine8.counters()
    per_iter_disp = (c1["dispatches"] - c0["dispatches"]) / float(8 + 1)
    fused_res = fused8()
    seq_last = seq8()
    bit8 = np.array_equal(np.asarray(fused_res.toarray()),
                          np.asarray(seq_last.toarray()))
    ok8 = (bit8 and allclose(lo8[3], np.asarray(fused_res.toarray()),
                             rtol=1e-4, atol=1e-4))
    print("   multi_stat_fused: 4 terminals, dispatches/iter %.2f "
          "(model: 1 fused read vs 4 sequential), measured seq/fused "
          "wall ratio %.2fx, fused-vs-standalone %s"
          % (per_iter_disp, tt8s / tt8,
             "bit-exact" if bit8 else "MISMATCH"), file=sys.stderr)
    rows.append(_progress("8 multi_stat_fused 1.1GB", lt8, tt8,
                          "exact*" if ok8 else "MISMATCH"))

    # ---- config 9: multi-tenant serve (ISSUE 8) ----------------------
    # the load generator: N tenants, each an IDENTICAL streamed
    # reduction over a storage-latency-bound source (the per-slab sleep
    # emulates the object-store/DMA fetch a production loader pays —
    # on this container that wait is what concurrency can recover; the
    # on-device program itself is config 6/7's).  The serialised
    # baseline runs the same four jobs one at a time; the serve row's
    # "speedup" column IS the aggregate-throughput scaling factor the
    # acceptance gate demands (>= 2.5x at 4 tenants).  Engine-counter
    # proof rides along: a COLD 4-tenant round compiles exactly what a
    # cold single tenant does, and every tenant's result is
    # bit-identical to its single-tenant run.
    from bolt_tpu import serve as _serve
    shape9 = (2048, 256, 64)                      # 128 MB per tenant
    x9 = lcg_np(shape9, salt=9)
    lat9 = float(os.environ.get("BOLT_SERVE_BENCH_LATENCY", "0.025"))
    tenants9 = 4

    def read9(idx):
        time.sleep(lat9)                 # emulated storage fetch latency
        return x9[idx]

    def make9():
        src = bolt.fromcallback(read9, shape9, mode="tpu",
                                dtype=np.float32, chunks=128)  # 16 slabs
        return src.map(ADD1).sum()

    sync(make9())                                 # compile slab programs
    ref9 = np.asarray(make9().toarray())          # single-tenant result

    t0 = time.perf_counter()
    for _ in range(tenants9):
        sync(make9())                             # one at a time
    ser9 = time.perf_counter() - t0

    with _serve.serving(workers=tenants9, queue_limit=2 * tenants9) as sv:
        t0 = time.perf_counter()
        futs = [sv.submit(make9(), tenant="t%d" % i)
                for i in range(tenants9)]
        outs9 = [f.result(timeout=600) for f in futs]
        conc9 = time.perf_counter() - t0
        lats = sorted(f.finished_s - f.submitted_s for f in futs)
        depth_hw9 = sv.stats()["queue_depth_high_water"]
    p50 = lats[len(lats) // 2]
    p99 = lats[min(len(lats) - 1, int(len(lats) * 0.99))]
    bit9 = all(np.array_equal(np.asarray(o.toarray()), ref9)
               for o in outs9)

    # the ONE-compile proof: cold 4-tenant round vs cold single tenant
    _engine8.clear()
    c0 = _engine8.counters()
    with _serve.serving(workers=tenants9) as sv:
        [f.result(timeout=600) for f in
         [sv.submit(make9(), tenant="t%d" % i) for i in range(tenants9)]]
    c1 = _engine8.counters()
    four9 = (c1["misses"] - c0["misses"],
             c1["aot_compiles"] - c0["aot_compiles"])
    _engine8.clear()
    c0 = _engine8.counters()
    sync(make9())
    c1 = _engine8.counters()
    one9 = (c1["misses"] - c0["misses"],
            c1["aot_compiles"] - c0["aot_compiles"])

    nbytes9 = int(np.prod(shape9)) * 4
    agg_gbps = tenants9 * nbytes9 / conc9 / 1e9
    ser_gbps = tenants9 * nbytes9 / ser9 / 1e9
    ok9 = (bit9 and four9 == one9 and ser9 / conc9 >= 2.5
           and depth_hw9 <= 2 * tenants9)
    print("   serve_multitenant: %d tenants x %d MB, aggregate %.2f GB/s "
          "vs serialised %.2f GB/s (%.2fx, gate >= 2.5x), latency "
          "p50 %.3fs p99 %.3fs, cold compiles 4-tenant %s == 1-tenant "
          "%s, queue depth hw %d, per-slab storage latency %gs"
          % (tenants9, nbytes9 >> 20, agg_gbps, ser_gbps, ser9 / conc9,
             p50, p99, four9, one9, depth_hw9, lat9), file=sys.stderr)
    rows.append(_progress("9 serve_multitenant 4x128MB", ser9, conc9,
                          "exact*" if ok9 else "MISMATCH"))

    # ---- config 10: resumable streams (ISSUE 9) ----------------------
    # the kill -9 proof as a measured row: a child process streams the
    # canonical 8-slab reduction, is SIGKILLed at upload 6 by the
    # BOLT_CHAOS env, and a fresh child resumes from the surviving
    # slab-level checkpoint.  "local s" is the clean child's in-run
    # wall, "tpu s" the resumed child's (it streams only the remaining
    # slabs — the gate is recovery < 1.5x clean, plus bit-identity).
    import importlib.util as _ilu
    _spec = _ilu.spec_from_file_location(
        "chaos_run", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "chaos_run.py"))
    _chaos_run = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_chaos_run)
    r10 = _chaos_run.run_resume_bench()
    ok10 = (r10["identical"] and r10["resumes"] >= 1
            and not r10["stale_checkpoint"]
            and r10["recovery_s"] < 1.5 * r10["clean_s"])
    print("   stream_resume: killed rc=%s at upload 6/8, resumed %d of "
          "%d slabs, recovery %.3fs vs clean %.3fs (gate < 1.5x), "
          "bit-identical %s"
          % (r10["killed_rc"], r10["slabs_resumed"], r10["slabs_total"],
             r10["recovery_s"], r10["clean_s"], r10["identical"]),
          file=sys.stderr)
    rows.append(_progress("10 stream_resume kill -9", r10["clean_s"],
                          r10["recovery_s"],
                          "exact*" if ok10 else "MISMATCH"))

    # ---- config 11: pod-scale streaming (ISSUE 10) -------------------
    # a REAL 2-process jax.distributed localhost CPU cluster streams the
    # per-process fromcallback sum (each process produces and uploads
    # only its own shard of every slab; the cross-host fold is the slab
    # program's psum).  "local s" is the single-process run of the same
    # workload on the same TOTAL device count; "tpu s" the 2-process
    # cluster wall (max across workers).  The aggregate-vs-single ratio
    # and per-process GB/s land on stderr; parity is bit-identity of
    # the folded result across every process and the single run.
    import shutil as _sh11
    mh = _load_mh_harness()
    env11 = {"BOLT_MH_NKEYS": "4096", "BOLT_MH_VDIM": "256",
             "BOLT_MH_CHUNKS": "512"}
    try:
        res11, out11, _ = mh.run_cluster("bench", nproc=2, devs=1,
                                         env=env11)
        res11s, out11s, _ = mh.run_cluster("bench", nproc=1, devs=2,
                                           env=env11)
    except RuntimeError as exc:
        # an environment without the CPU cross-process collective
        # transport must not lose configs 1-10's results to config 11
        print("   multihost_stream SKIPPED: %s" % exc, file=sys.stderr)
    else:
        wall11 = max(r["wall_s"] for r in res11)
        single11 = res11s[0]["wall_s"]
        nbytes11 = 4096 * 256 * 4
        per_proc = [r["transfer_bytes"] / r["wall_s"] / 1e9
                    for r in res11]
        ref11 = np.load(os.path.join(out11s, "bench_sum.0.npy"))
        bit11 = all(np.array_equal(
            np.load(os.path.join(out11, "bench_sum.%d.npy" % p)), ref11)
            for p in (0, 1))
        ok11 = (bit11 and all(r["recompiles_warm"] == 0 for r in res11)
                and all(r["leaked_spans"] == 0 for r in res11))
        print("   multihost_stream: 2 processes x %d MB/2, per-process "
              "%s GB/s, aggregate-vs-single-process ratio %.2fx, warm "
              "recompiles %s, bit-identical across pod %s"
              % (nbytes11 >> 20,
                 ["%.2f" % g for g in per_proc], single11 / wall11,
                 [r["recompiles_warm"] for r in res11], bit11),
              file=sys.stderr)
        rows.append(_progress("11 multihost_stream 2proc", single11,
                              wall11, "exact*" if ok11 else "MISMATCH"))
        _sh11.rmtree(out11, ignore_errors=True)
        _sh11.rmtree(out11s, ignore_errors=True)

    # ---- config 12: pod fault tolerance (ISSUE 11) -------------------
    # kill -9 of one process in a 3-process cluster: every survivor
    # raises PeerLostError (watchdog within 2x BOLT_POD_TIMEOUT),
    # reforms onto the 2 survivors and resumes from the rendezvous-
    # consistent checkpoint.  "local s" is the clean 2-process run of
    # the same workload, "tpu s" the RECOVERY wall (learn -> barrier
    # probe -> reform -> resume); the gate is recovery < 2.0x clean
    # plus bit-identity to the unkilled run.
    try:
        r12 = mh.run_reform_bench()
    except RuntimeError as exc:
        print("   multihost_resume SKIPPED: %s" % exc, file=sys.stderr)
    else:
        ok12 = (r12["peer_lost_everywhere"] and r12["bit_identical"]
                and r12["detection_s"] <= 2 * r12["pod_timeout"]
                and r12["recovery_over_clean"] < 2.0
                and r12["stale_checkpoint_files"] == []
                and r12["leaked_spans"] == 0)
        print("   multihost_resume: victim rc %s, detection %.2fs "
              "(deadline %.1fs), reform %.2fs, resume %.2fs — recovery "
              "%.3fs vs clean %.3fs (%.2fx, gate < 2.0x), resumes "
              "sum/stats %d/%d, bit-identical %s"
              % (r12["victim_rc"], r12["detection_s"],
                 r12["pod_timeout"], r12["reform_s"], r12["resume_s"],
                 r12["recovery_s"], r12["clean_s"],
                 r12["recovery_over_clean"], r12["sum_resumes"],
                 r12["stats_resumes"], r12["bit_identical"]),
              file=sys.stderr)
        rows.append(_progress("12 multihost_resume 3->2", r12["clean_s"],
                              r12["recovery_s"],
                              "exact*" if ok12 else "MISMATCH"))

    # ---- config 13: self-healing pods (ISSUE 12) ---------------------
    # kill -9 of one process under Server(supervise=True): the pod
    # shrinks 3->2 AUTOMATICALLY (no caller intervention), a restarted
    # replacement rejoins mid-stream and the pod re-expands 2->3.
    # "local s" is the clean 3-process run of the same supervised
    # workload, "tpu s" the elastic scenario wall; the gate is
    # scenario < 2.5x clean plus bit-identity of every artifact to the
    # unkilled run.
    try:
        r13 = mh.run_supervise_bench()
    except RuntimeError as exc:
        print("   multihost_elastic SKIPPED: %s" % exc, file=sys.stderr)
    else:
        ok13 = (r13["bit_identical"] and r13["rejoined"] == 1
                and r13["nproc_final"] == 3
                and r13["detection_s"] <= 2 * r13["pod_timeout"]
                and r13["scenario_over_clean"] < 2.5
                and r13["arbiter_bytes"] == 0
                and r13["leaked_spans"] == 0
                and r13["stale_ckpt"] == []
                and r13["stale_markers"] == 0)
        print("   multihost_elastic: victim rc %s, detection %.2fs "
              "(deadline %.1fs), auto-reform %.3fs, rejoin recovery "
              "%.3fs — scenario %.3fs vs clean %.3fs (%.2fx, gate "
              "< 2.5x), resumes A/B %d/%d, final width %d, "
              "bit-identical %s"
              % (r13["victim_rc"], r13["detection_s"],
                 r13["pod_timeout"], r13["reform_s"], r13["rejoin_s"],
                 r13["scenario_s"], r13["clean_s"],
                 r13["scenario_over_clean"], r13["a_resumes"],
                 r13["b_resumes"], r13["nproc_final"],
                 r13["bit_identical"]),
              file=sys.stderr)
        rows.append(_progress("13 multihost_elastic 3->2->3",
                              r13["clean_s"], r13["scenario_s"],
                              "exact*" if ok13 else "MISMATCH"))

    # ---- config 14: continuous micro-batching (ISSUE 13) -------------
    # the high-QPS small-request firehose: many SAME-SHAPE map->sum
    # requests against ONE serve worker.  The unbatched leg dispatches
    # one 8-device program per request — per-request launch + collective
    # rendezvous, not bytes, is the roofline — while the batched leg
    # coalesces up to 16 requests into one stacked/vmapped dispatch
    # (Server(batching=...), bolt_tpu/tpu/batched.py).  Saturation
    # methodology: the queue is pre-filled behind a parked worker and
    # the measured wall is the DRAIN — aggregate server throughput at
    # high offered QPS; "local s" is the unbatched leg, "tpu s" the
    # batched one, so the speedup column IS the >= 3x acceptance gate.
    # Rides along: bit-identity of every batched result to its
    # standalone dispatch, zero fresh compiles at steady state (bucketed
    # widths pre-warmed via batched.warm), and the sparse single-request
    # p50 with batching ARMED staying < 1.2x of the unbatched server's.
    import threading as _threading
    from bolt_tpu import serve as _serve14
    from bolt_tpu.tpu import batched as _batched14
    shape14 = (128, 32)
    nreq14, nb14 = 256, 8
    xs14 = [lcg_np(shape14, salt=140 + i) for i in range(nb14)]
    b14 = [lcg_tpu(shape14, salt=140 + i).cache() for i in range(nb14)]

    def make14(i=0):
        return b14[i % nb14].map(ADD1).sum()

    refs14 = [np.asarray(make14(i).toarray()) for i in range(nb14)]

    def saturated14(sv):
        # the drain window is SERVER-side: first dispatch opportunity
        # (the gate opening) to the last future's finished_s — the
        # client's result-collection loop stays outside the window,
        # exactly like timed_tpu keeps the host fetch outside
        best = float("inf")
        outs = None
        for _ in range(3):
            gate = _threading.Event()
            blocker = sv.submit(gate.wait)       # parks the ONE worker
            futs = [sv.submit(make14(i), tenant="t%d" % (i % 4))
                    for i in range(nreq14)]
            t0 = time.perf_counter()
            gate.set()
            outs = [f.result(timeout=600) for f in futs]
            best = min(best, max(f.finished_s for f in futs) - t0)
            blocker.result(timeout=30)
        return best, outs

    def sparse14(sv, n=30):
        # min-of-2 medians: a single 30-request window's median is
        # noisy on a loaded 1-core container, and the p50 gate compares
        # two separately-measured windows
        meds = []
        [sv.submit(make14()).result(timeout=60) for _ in range(5)]
        for _ in range(2):
            lats = []
            for _ in range(n):
                f = sv.submit(make14())
                f.result(timeout=60)
                lats.append(f.finished_s - f.submitted_s)
                time.sleep(0.005)
            lats.sort()
            meds.append(lats[len(lats) // 2])
        return min(meds)

    from bolt_tpu import engine as _engine14
    with _serve14.serving(workers=1, queue_limit=2 * nreq14) as sv:
        [f.result(timeout=60) for f in
         [sv.submit(make14(i)) for i in range(16)]]          # warm
        wall14u, _ = saturated14(sv)
        p50_off = sparse14(sv)
    with _serve14.serving(workers=1, queue_limit=2 * nreq14,
                          batching={"max_batch": 16,
                                    "linger": 0.002}) as sv:
        _batched14.warm(make14, buckets=sv.batching.buckets)
        [f.result(timeout=60) for f in
         [sv.submit(make14(i)) for i in range(16)]]          # warm
        c0 = _engine14.counters()
        wall14b, outs14 = saturated14(sv)
        c1 = _engine14.counters()
        p50_on = sparse14(sv)
        st14 = sv.stats()["batching"]
    bit14 = all(np.array_equal(np.asarray(o.toarray()), refs14[i % nb14])
                for i, o in enumerate(outs14))
    recompiled14 = (c1["misses"] - c0["misses"]
                    + c1["aot_compiles"] - c0["aot_compiles"])
    occ14 = ((c1["batched_requests"] - c0["batched_requests"])
             / max(1, c1["batched_dispatches"] - c0["batched_dispatches"]))
    dpr14 = (c1["dispatches"] - c0["dispatches"]) / (3.0 * nreq14)
    ratio14 = wall14u / wall14b
    p50r14 = p50_on / p50_off
    ok14 = (bit14 and ratio14 >= 3.0 and recompiled14 == 0
            and p50r14 < 1.2)
    print("   serve_smallreq: %d x %s requests, 1 worker — aggregate "
          "%.0f req/s batched vs %.0f unbatched (%.2fx, gate >= 3x), "
          "occupancy %.1f, dispatches/request %.3f, steady-state "
          "recompiles %d, sparse p50 %.0f/%.0f us (%.2fx, gate < 1.2x), "
          "bit-identical %s"
          % (nreq14, shape14, nreq14 / wall14b, nreq14 / wall14u,
             ratio14, occ14, dpr14, recompiled14, 1e6 * p50_on,
             1e6 * p50_off, p50r14, bit14), file=sys.stderr)
    print("   batching stats: %s" % (st14,), file=sys.stderr)
    rows.append(_progress("14 serve_smallreq 256x16KB", wall14u, wall14b,
                          "exact*" if ok14 else "MISMATCH"))
    del xs14

    # ---- config 15: codec-encoded ingest (ISSUE 14) ------------------
    # the SAME transfer-bound streamed sum as config 6/7, with the
    # ingest codec armed: uploader workers ENCODE each slab on host,
    # the wire representation crosses the link (transfer counters
    # prove the ratio), and the slab program DECODES on device fused
    # into the fold.  "local s" is the RAW f32 streamed pass, "tpu s"
    # the bf16-encoded one — the speedup column is the wall-clock win
    # of moving half the bytes on this attach; the int8 (0.25x) and
    # lossless delta-f32 (1.0x, bit-exact) legs ride along.  Parity
    # gates: bf16 wire bytes <= 0.55x raw, delta BIT-IDENTICAL to the
    # raw pass, lossy legs inside their documented envelopes.
    shape15 = (8192, 256, 64)                     # 0.5 GB raw
    x15 = lcg_np(shape15, salt=15)

    def launch15(codec=None):
        src = bolt.fromcallback(lambda idx: x15[idx], shape15,
                                mode="tpu", dtype=np.float32,
                                chunks=512, codec=codec)
        return src.sum()

    def run15(codec=None):
        c0 = _profile.engine_counters()
        t0 = time.perf_counter()
        out = launch15(codec)
        sync(out)
        wall = time.perf_counter() - t0
        c1 = _profile.engine_counters()
        return (np.asarray(out.toarray()), wall,
                c1["transfer_bytes"] - c0["transfer_bytes"])

    with _stream.uploaders(4):
        for cdc in (None, "bf16", "int8", "delta-f32"):
            sync(launch15(cdc))                   # compile slab programs
        ref15, traw15, braw15 = run15()
        out15b, tb15, bb15 = run15("bf16")
        out15i, ti15, bi15 = run15("int8")
        out15d, td15, bd15 = run15("delta-f32")
    rb15, ri15, rd15 = (bb15 / braw15, bi15 / braw15, bd15 / braw15)
    bit15 = np.array_equal(out15d, ref15)
    okb15 = allclose(out15b, ref15, rtol=1e-2)
    step15 = (x15.max() - x15.min()) / 255.0
    oki15 = np.max(np.abs(out15i - ref15)) <= step15 / 2 * shape15[0]
    ok15 = (rb15 <= 0.55 and ri15 <= 0.30 and bit15 and okb15
            and bool(oki15))
    print("   stream_codec: raw %.0f MB %.3fs | bf16 %.2fx bytes "
          "%.3fs (%.2fx wall) | int8 %.2fx bytes %.3fs (%.2fx wall) | "
          "delta-f32 %.2fx bytes %.3fs, bit-identical %s | bf16 "
          "envelope ok %s, int8 bound ok %s"
          % (braw15 / 1e6, traw15, rb15, tb15, traw15 / tb15, ri15,
             ti15, traw15 / ti15, rd15, td15, bit15, okb15,
             bool(oki15)), file=sys.stderr)
    rows.append(_progress("15 stream_codec bf16 0.5GB", traw15, tb15,
                          "exact*" if ok15 else "MISMATCH"))
    del x15

    # ---- config 16: out-of-core streamed swap (ISSUE 18) -------------
    # the tentpole leg: a swap RECORDED on a streamed source resolves
    # through the two-phase shuffle (per-slab on-device re-bucket
    # overlapped with ingest, then a resident concat) instead of
    # materialising the whole source first.  "local s" is the
    # materialise-first baseline — cache() the full source into device
    # memory, then the in-memory swap; "tpu s" is the streamed shuffle
    # over the SAME callback source, so the speedup column is what
    # overlapping the re-bucket with ingest buys on this attach.  The
    # forced-spill leg (budget ~ one bucket: every re-keyed bucket
    # rides the checkpoint-slab spill files to disk and phase 2
    # re-streams them) rides along on stderr with its byte gauges.
    import shutil as _sh16m
    import tempfile as _tf16m
    from bolt_tpu import checkpoint as _ckpt16m
    shape16 = (2048, 256, 64)                     # 128 MB raw
    x16 = lcg_np(shape16, salt=16)

    def launch16():
        src = bolt.fromcallback(lambda idx: x16[idx], shape16,
                                mode="tpu", dtype=np.float32,
                                chunks=256)
        return src.swap((0,), (0,))

    def mat16():
        src = bolt.fromcallback(lambda idx: x16[idx], shape16,
                                mode="tpu", dtype=np.float32,
                                chunks=256)
        src.cache()
        return src.swap((0,), (0,))

    with _stream.uploaders(4):
        np.asarray(launch16()._data)              # compile both phases
        t16s, t16m = float("inf"), float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            out16m = np.asarray(mat16()._data)
            t16m = min(t16m, time.perf_counter() - t0)
        for _ in range(2):
            t0 = time.perf_counter()
            out16s = np.asarray(launch16()._data)
            t16s = min(t16s, time.perf_counter() - t0)
        td16m = _tf16m.mkdtemp(prefix="bolt-bench16-")
        try:
            with _stream.spill(dir=td16m, budget=1):
                t0 = time.perf_counter()
                out16sp = np.asarray(launch16()._data)
                t16sp = time.perf_counter() - t0
            c16 = _profile.engine_counters()
            stale16 = _ckpt16m.spill_pending(td16m)
            _ckpt16m.spill_clear(td16m)
        finally:
            _sh16m.rmtree(td16m, ignore_errors=True)
    bit16 = (np.array_equal(out16s, out16m)
             and np.array_equal(out16sp, out16m)
             and np.array_equal(out16m, np.transpose(x16, (1, 0, 2))))
    ok16 = bit16 and stale16                      # the spill leg spilled
    print("   stream_swap: %d MB streamed %.3fs vs materialise-first "
          "%.3fs (%.2fx) | forced-spill %.3fs (spill %.0f MB, shuffle "
          "%.0f MB moved) | all legs bit-identical %s"
          % (x16.nbytes // 2**20, t16s, t16m, t16m / t16s, t16sp,
             c16["spill_bytes"] / 1e6, c16["shuffle_bytes"] / 1e6,
             bit16), file=sys.stderr)
    rows.append(_progress("16 stream_swap 128MB", t16m, t16s,
                          "exact" if ok16 else "MISMATCH"))
    del x16

    print("%-26s %10s %10s %9s  %s" % ("config", "local s", "tpu s", "speedup", "parity"))
    for name, lt, tt, parity in rows:
        print("%-26s %10.4f %10.4f %8.1fx  %s" % (name, lt, tt, lt / tt, parity))
    print("(tpu column: steady-state device time; filter results are "
          "lazy-count, so config 4 pipelines like the rest and pays its "
          "single count sync only at the closing resolution.  exact* = "
          "bit-exact on sampled slices of a multi-GB result, full fetch "
          "skipped — inputs are bit-identical LCG data on both sides)",
          file=sys.stderr)
    if any(r[3] == "MISMATCH" for r in rows):
        sys.exit(1)


if __name__ == "__main__":
    if "--check" in sys.argv:
        sys.exit(check_configs())
    from bolt_tpu import obs
    trace_path = obs.trace_arg(sys.argv)
    if trace_path:
        code = 0
        try:
            with obs.timeline(trace_path):
                main()
        except SystemExit as e:       # a parity MISMATCH exit: the trace
            code = e.code or 0        # of the FAILED run is the point —
        #                               report it before re-exiting
        print(obs.report(), file=sys.stderr)
        print("obs timeline written to %s (load in chrome://tracing or "
              "Perfetto)" % trace_path, file=sys.stderr)
        sys.exit(code)
    main()
