#!/usr/bin/env python
"""Worked examples: the reference's real-world workflows, TPU-native.

Upstream Bolt's primary consumer was the Thunder ecosystem (large-scale
image / time-series analysis); these examples exercise the same jobs
through this framework.  Each section asserts parity against NumPy, so the
file doubles as an integration test: ``python scripts/examples.py``
(runs on whatever devices jax sees — force the 8-device CPU mesh with
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``).

The same code is shown in ``docs/EXAMPLES.md``.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import bolt_tpu as bolt
from bolt_tpu.parallel import default_mesh


def section(title):
    print("==", title, flush=True)


def main():
    mesh = default_mesh()
    rs = np.random.RandomState(7)

    # ------------------------------------------------------------------
    section("1. image-stack statistics (mean/std image over time)")
    # A stack of 512 images of 64x96 pixels; time is the key axis, so the
    # stack is sharded over the mesh and each device holds whole images.
    stack = rs.randn(512, 64, 96).astype(np.float32)
    b = bolt.array(stack, mesh, axis=(0,))
    st = b.stats()                      # one shard_map Welford pass
    assert np.allclose(np.asarray(st.mean()), stack.mean(axis=0), atol=1e-5)
    assert np.allclose(np.asarray(st.stdev()), stack.std(axis=0), atol=1e-4)

    # ------------------------------------------------------------------
    section("2. per-image preprocessing chain (deferred, fused)")
    # Subtract a baseline, clip, square — the chain defers and compiles
    # into ONE program when the reduction forces it.
    baseline = stack.mean()
    mapped = b.map(lambda im: np.clip(im - baseline, 0, None) ** 2)
    total = float(mapped.sum(axis=(0, 1, 2)).toarray())
    expected = (np.clip(stack - baseline, 0, None) ** 2).sum(dtype=np.float64)
    assert np.allclose(total, expected, rtol=1e-5)

    # ------------------------------------------------------------------
    section("3. images -> per-pixel time series (swap re-axis)")
    # Key axis time -> value; pixel rows -> key: afterwards each record is
    # one row's time series, ready for per-pixel temporal analysis.
    series = b.swap((0,), (0,))         # all_to_all under the hood
    assert series.shape == (64, 512, 96) and series.split == 1
    assert np.allclose(series.toarray(), np.transpose(stack, (1, 0, 2)))
    # temporal detrend per pixel row, then back to image layout
    detrended = series.map(lambda ts: ts - ts.mean(axis=0, keepdims=True))
    back = detrended.swap((0,), (0,))
    expect = stack - stack.mean(axis=0, keepdims=True)
    assert np.allclose(back.toarray(), expect, atol=1e-4)

    # ------------------------------------------------------------------
    section("4. halo-padded chunked smoothing of a long series")
    # One long (16, 40000)-sample series bank; chunk the long axis with a
    # 1-sample halo so a 3-tap moving average is exact across block edges.
    bank = rs.randn(16, 40000).astype(np.float32)
    lb = bolt.array(bank, mesh, axis=(0,))

    import jax.numpy as jnp

    def smooth(block):                  # shape-preserving on the padded block
        left = jnp.roll(block, 1, axis=0)
        right = jnp.roll(block, -1, axis=0)
        return (left + block + right) / 3.0

    sm = lb.chunk(size=(5000,), axis=(0,), padding=1).map(smooth).unchunk()
    full = smooth(bank.T).T             # oracle: smooth the whole series
    got = sm.toarray()
    # interior exact (boundaries differ: np.roll wraps on the full array)
    assert np.allclose(got[:, 1:-1], full[:, 1:-1], atol=1e-5)

    # the packaged form: ops.smooth (zero boundary) matches the raw
    # chunk-padding pipeline away from the array edges
    from bolt_tpu.ops import smooth as box_smooth
    got2 = box_smooth(lb, 3, axis=(0,), size=(5000,)).toarray()
    assert np.allclose(got2[:, 1:-1], full[:, 1:-1], atol=1e-5)

    # ------------------------------------------------------------------
    section("5. tall-skinny PCA via per-chunk SVD (BASELINE config 5)")
    npts, nfeat = 32768, 16
    data = rs.randn(npts, nfeat).astype(np.float32)
    pb = bolt.array(data[None], mesh, axis=(0,))  # one record: the matrix
    sv = pb.chunk(size=(4096,), axis=(0,)).map(
        lambda blk: jnp.linalg.svd(blk, compute_uv=False)[None, :]).unchunk()
    expect = np.stack([
        np.linalg.svd(data[i * 4096:(i + 1) * 4096], compute_uv=False)
        for i in range(npts // 4096)])
    assert np.allclose(np.asarray(sv.toarray())[0], expect, rtol=1e-2, atol=1e-2)

    # ------------------------------------------------------------------
    section("5b. whole-array distributed PCA (one SPMD program)")
    from bolt_tpu.ops import pca
    scores, comps, svals = pca(bolt.array(data, mesh, axis=(0,)),
                               k=4, center=True)
    xc = data - data.mean(axis=0)
    expect_sv = np.linalg.svd(xc, compute_uv=False)[:4]
    assert np.allclose(svals, expect_sv, rtol=1e-3)
    assert scores.mode == "tpu" and scores.shape == (npts, 4)

    # ------------------------------------------------------------------
    section("5c. distributed least squares (per-pixel trend fit)")
    # fit a linear trend to every pixel's time series in ONE call: the
    # sharded design matrix stays sharded, GSPMD inserts the all-reduce
    from bolt_tpu.ops import lstsq
    t = np.arange(512, dtype=np.float64)
    design = np.stack([np.ones_like(t), t], axis=1)        # (512, 2)
    targets = bolt.array(stack.reshape(512, -1), mesh, axis=(0,))
    coef = np.asarray(lstsq(design, targets))   # bolt array direct
    ref = np.linalg.lstsq(design, stack.reshape(512, -1), rcond=None)[0]
    assert np.allclose(coef, ref, atol=1e-6)

    # ------------------------------------------------------------------
    section("6. select + mask: keyed filtering")
    means = stack.mean(axis=(1, 2))
    bright = b.filter(lambda im: im.mean() > 0)
    assert bright.shape == ((means > 0).sum(), 64, 96)
    assert np.allclose(bright.toarray(), stack[means > 0])

    # ------------------------------------------------------------------
    section("7. checkpoint / restore")
    import tempfile
    from bolt_tpu import checkpoint
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ckpt")
        checkpoint.save(path, b)
        b2 = checkpoint.load(path, context=mesh)
        assert b2.split == b.split
        assert np.allclose(b2.toarray(), stack)

    # ------------------------------------------------------------------
    section("8. sharded loading + on-device RNG")
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "big.npy")
        disk = rs.randn(64, 32).astype(np.float32)
        np.save(path, disk)
        mm = np.load(path, mmap_mode="r")
        # each device reads ONLY its own slice of the file
        ld = bolt.fromcallback(lambda idx: mm[idx], mm.shape, mesh)
        assert np.array_equal(ld.toarray(), disk)
    rnd = bolt.randn((64, 32), mesh, dtype=np.float32, seed=0)
    assert abs(float(np.asarray(rnd.toarray()).mean())) < 0.1

    # ------------------------------------------------------------------
    section("8c. out-of-core streaming through the uploader pool")
    # an explicit dtype keeps fromcallback LAZY: the reduction streams
    # slab-by-slab through the N-way uploader pool (workers produce and
    # upload concurrently; the re-sequencer keeps the fold in slab
    # order, so the result is bit-identical to single-threaded ingest)
    from bolt_tpu import stream as _stream
    big = rs.randn(96, 16, 8).astype(np.float32)
    src = bolt.fromcallback(lambda idx: big[idx], big.shape, mesh,
                            dtype=np.float32, chunks=16)
    with _stream.uploaders(4), _stream.prefetch(2):
        m = src.map(lambda v: v + 1.0).mean()
    # production numerics (x64 off): compare against the materialised
    # device path at f32 tolerance, and the NumPy oracle likewise
    mat = bolt.array(big, mesh).map(lambda v: v + 1.0).mean()
    assert np.allclose(np.asarray(m.toarray()), np.asarray(mat.toarray()),
                       rtol=1e-5, atol=1e-5)
    assert np.allclose(np.asarray(m.toarray()),
                       (big + 1).mean(axis=0, dtype=np.float64),
                       rtol=1e-4, atol=1e-4)
    ec = bolt.profile.engine_counters()
    assert ec["stream_chunks"] >= 6 and ec["stream_upload_threads"] >= 1

    # ------------------------------------------------------------------
    section("8d. one-pass statistics: bolt.compute fused multi-stat")
    # four lazy stat terminals on one deferred chain fuse into ONE
    # tuple-output program (one read of the data); every fused result
    # is bit-identical to its standalone terminal
    xm = rs.randn(64, 16, 8).astype(np.float32)
    chain = bolt.array(xm, mesh).map(lambda v: v * 2.0)
    c0 = bolt.profile.engine_counters()
    s8, v8, lo8, hi8 = bolt.compute(chain.sum(), chain.var(),
                                    chain.min(), chain.max())
    c1 = bolt.profile.engine_counters()
    assert c1["dispatches"] - c0["dispatches"] == 1     # ONE pass
    assert c1["fused_stat_terminals"] - c0["fused_stat_terminals"] == 4
    sa = bolt.array(xm, mesh).map(lambda v: v * 2.0).sum()
    assert np.array_equal(np.asarray(s8.toarray()),
                          np.asarray(sa.toarray()))     # bit-identical
    # the fluent form works on out-of-core streams too: ONE ingest pass
    st8 = bolt.fromcallback(lambda idx: xm[idx], xm.shape, mesh,
                            dtype=np.float32, chunks=16)
    d8 = st8.stats("sum", "min", "max")
    assert np.array_equal(np.asarray(d8["min"].toarray()),
                          xm.min(axis=0))
    # ptp rides the fused min/max pair; explain() forecasts the fusion
    assert np.allclose(np.asarray(chain.ptp().toarray()),
                       np.ptp(xm * 2.0, axis=0), rtol=1e-6)
    from bolt_tpu import analysis as _analysis
    chain2 = bolt.array(xm, mesh).map(lambda v: v + 1.0)
    h1, h2 = chain2.sum(), chain2.var()
    assert "fusable terminal set" in _analysis.explain(h1)
    bolt.compute(h1, h2)

    # ------------------------------------------------------------------
    section("8e. multi-tenant serving: N pipelines, one engine")
    # N tenants share one process and one mesh: serve.submit queues each
    # pipeline, worker threads drain the per-tenant queues round-robin,
    # the device-memory arbiter keeps every stream inside ONE bytes
    # budget, and identical pipeline shapes compile ONCE across tenants
    from bolt_tpu import serve as _serve
    xs = rs.randn(96, 16, 8).astype(np.float32)
    double = lambda v: v * 2.0          # hoisted: tenants SHARE the
    #                                     callable, so programs coalesce

    def tenant_pipeline():
        src = bolt.fromcallback(lambda idx: xs[idx], xs.shape, mesh,
                                dtype=np.float32, chunks=24)
        return src.map(double).sum()

    expected = np.asarray(tenant_pipeline().toarray())  # single-tenant
    with _serve.serving(workers=3, budget_bytes=64 << 20) as sv:
        futs = [sv.submit(tenant_pipeline(), tenant=t)
                for t in ("ana", "ben", "caro")]
        for f in futs:                  # bit-identical per tenant
            assert np.array_equal(np.asarray(f.result().toarray()),
                                  expected)
        st = sv.stats()
    assert st["totals"]["completed"] >= 3
    # per-tenant accounting: each tenant's scoped engine counters saw
    # exactly its own ingest traffic
    for t in ("ana", "ben", "caro"):
        assert st["tenants"][t]["completed"] == 1
        assert st["tenants"][t]["transfer_bytes"] >= xs.nbytes
    # admission control: a pipeline that could NEVER fit the budget is
    # rejected up front (the checker forecasts it as BLT010)
    with _serve.serving(workers=1, budget_bytes=4096) as sv:
        huge = bolt.fromcallback(lambda idx: xs[idx], xs.shape, mesh,
                                 dtype=np.float32, chunks=96).sum()
        try:
            sv.submit(huge)
            raise AssertionError("BLT010 pipeline was admitted")
        except _serve.AdmissionError:
            pass

    # ------------------------------------------------------------------
    section("8f. survive a preemption: resumable streams + retry")
    # out-of-core runs over hours of data must survive worker failure:
    # checkpoint=dir persists the retired-slab watermark + fold state,
    # stream.retries absorbs flaky ingest in-run, and a killed run
    # restarted over the same source resumes BIT-IDENTICALLY from the
    # last retired slab.  The deterministic fault registry
    # (bolt_tpu._chaos) plays the failures on demand.
    import tempfile
    from bolt_tpu import _chaos as chaos
    from bolt_tpu import checkpoint as _ck
    from bolt_tpu import stream as _stream
    xr = rs.randn(64, 16, 8).astype(np.float32)
    ckd = tempfile.mkdtemp()

    def resumable_pipeline(ck=ckd):
        src = bolt.fromcallback(lambda idx: xr[idx], xr.shape, mesh,
                                dtype=np.float32, chunks=8,  # 8 slabs
                                checkpoint=ck)
        return src.map(lambda v: v + 1.0).sum()

    expected = np.asarray(resumable_pipeline(ck=None).toarray())
    # a flaky upload is absorbed in-run by the retry budget (the slab
    # re-attempts in place, fenced so it can never double-fold)
    chaos.inject("stream.upload", nth=2)
    with _stream.retries(1):
        got = np.asarray(resumable_pipeline().toarray())
    chaos.clear()
    assert np.array_equal(got, expected)
    # a KILLED run leaves a checkpoint; the re-run resumes from the
    # last retired slab and the result is bit-identical
    chaos.inject("stream.upload", nth=5)
    try:
        with _stream.uploaders(1):
            resumable_pipeline().cache()
        raise AssertionError("chaos fault did not fire")
    except chaos.ChaosError:
        pass
    finally:
        chaos.clear()
    assert _ck.stream_pending(ckd)              # the watermark survived
    got2 = np.asarray(resumable_pipeline().toarray())    # resumes
    assert np.array_equal(got2, expected)       # bit-identical
    assert not _ck.stream_pending(ckd)          # success cleared it
    ec = bolt.profile.engine_counters()
    assert ec["stream_resumes"] >= 1 and ec["stream_retries"] >= 1

    # ------------------------------------------------------------------
    section("8g. stream a pod-sized dataset: multi-process ingest")
    # the SAME loader + pipeline, scaled to a mesh spanning PROCESSES:
    # per_process=True makes each host produce and upload only its own
    # shard of every slab, and the slab program folds across hosts with
    # one mesh collective per slab (bolt_tpu.parallel.multihost).  The
    # proof stands up a REAL 2-process jax.distributed CPU cluster on
    # localhost and bit-compares against the single-process run.
    from bolt_tpu.utils import load_script
    _mh = load_script("multihost_harness")
    import shutil as _shutil
    try:
        _res, _out, _ = _mh.run_cluster("stream_parity", nproc=2, devs=1)
        _mh.run_cluster("single_ref", nproc=1, devs=2, out_dir=_out)
        _ref = np.load(os.path.join(_out, "ref_sum.npy"))
        for _pid in (0, 1):
            got_mh = np.load(os.path.join(_out, "sum.%d.npy" % _pid))
            assert np.array_equal(got_mh, _ref)      # bit-identical
        assert all(r["recompiles_second_pass"] == 0 for r in _res)
        assert all(r["blt012_refused"] for r in _res)
        _shutil.rmtree(_out, ignore_errors=True)
        print("  2-process cluster streamed bit-identically to the "
              "single-process run")
    except RuntimeError as exc:
        # an environment without the CPU collective transport skips
        print("  (pod example skipped: %s)" % exc)

    # ------------------------------------------------------------------
    section("8h. survive a pod member loss: shrink-and-resume")
    # the ISSUE-11 outage drill on a REAL 3-process localhost cluster:
    # one member is SIGKILLed mid-stream; every survivor raises the
    # pointed PeerLostError (liveness watchdog, never a hang), reforms
    # onto the 2 survivors (multihost.reform) and RESUMES from the
    # rendezvous-consistent checkpoint — bit-identical to the unkilled
    # 2-process baseline, with recovery bounded against its wall.
    try:
        _r = _mh.run_reform_bench()
        assert _r["peer_lost_everywhere"] and _r["barrier_peerlost"]
        assert _r["victim_rc"] == -9
        assert _r["bit_identical"]
        assert _r["sum_resumes"] >= 2 and _r["stats_resumes"] >= 2
        assert _r["stale_checkpoint_files"] == []
        print("  victim killed (rc %d); survivors raised PeerLostError "
              "in %.2fs (deadline %.1fs), reformed 3->2 in %.2fs and "
              "resumed bit-identically — recovery %.2fx the clean wall"
              % (_r["victim_rc"], _r["detection_s"], _r["pod_timeout"],
                 _r["reform_s"], _r["recovery_over_clean"]))
    except RuntimeError as exc:
        print("  (pod fault example skipped: %s)" % exc)

    # ------------------------------------------------------------------
    section("8i. lose a worker, get it back: the self-healing pod")
    # the ISSUE-12 drill: kill -9 one member of a 3-process pod running
    # Server(supervise=True) — the survivors reform 3->2 AUTOMATICALLY
    # (zero caller intervention; the held retry resumes from the
    # checkpoint) — then a replacement process rings the rejoin door
    # mid-stream and the pod re-expands 2->3 through a slab-boundary
    # quiesce.  Every artifact must be bit-identical to the unkilled
    # 3-process run, and nothing may leak.
    try:
        _e = _mh.run_supervise_bench()
        assert _e["victim_rc"] == -9 and _e["survivors"] == 2
        assert _e["rejoined"] == 1 and _e["nproc_final"] == 3
        assert _e["bit_identical"]
        # the wall ratio is gated against the scenario's OWN clean run
        # by bench config 13 on a quiet machine; here — examples run
        # alongside anything — it is REPORTED, with only a loose sanity
        # bound, so background load cannot flake the drill (the PR 13
        # known flake: timing/count gates vs committed expectations)
        assert _e["scenario_over_clean"] < 10
        assert _e["stale_ckpt"] == [] and _e["stale_markers"] == 0
        assert _e["arbiter_bytes"] == 0 and _e["leaked_spans"] == 0
        assert _e["blt014"] and _e["explain_supervised"]
        print("  victim killed (rc %d): auto-reform 3->2 in %.2fs with "
              "zero caller intervention; replacement rejoined and the "
              "pod re-expanded 2->3 in %.2fs — every artifact "
              "bit-identical, scenario %.2fx the clean wall"
              % (_e["victim_rc"], _e["recovery_s"], _e["rejoin_s"],
                 _e["scenario_over_clean"]))
        _p = _mh.run_precollective_probe()
        assert _p["pre_peerlost"]
        assert _p["pre_elapsed"] <= 2 * _p["pod_timeout"]
        print("  pre-collective death surfaced as PeerLostError in "
              "%.2fs (bound %.1fs — not gloo's ~30s connect)"
              % (_p["pre_elapsed"], 2 * _p["pod_timeout"]))
    except RuntimeError as exc:
        print("  (self-healing example skipped: %s)" % exc)

    # ------------------------------------------------------------------
    section("8j. high-QPS small requests: continuous micro-batching")
    # the ISSUE-13 shape: a firehose of SMALL identical-shape pipelines
    # where per-request dispatch overhead, not bytes, is the roofline.
    # Server(batching=...) coalesces queued same-key requests — across
    # tenants — into ONE stacked dispatch (bucketed widths, pad lanes
    # discarded), every lane bit-identical to its standalone dispatch,
    # with zero fresh compiles at steady state once the buckets are
    # warm (batched.warm).
    from bolt_tpu import engine as _engine8j
    from bolt_tpu import serve as _serve8j
    from bolt_tpu.tpu import batched as _batched8j
    _SCALE = lambda v: v * 2.0   # hoisted: same-key requests must share
    #                              stage callables (identity-keyed)
    req8j = [rs.randn(64, 8).astype(np.float32) for _ in range(6)]
    base8j = [bolt.array(x, mesh).cache() for x in req8j]

    def handle8j(i=0):
        return base8j[i % 6].map(_SCALE).sum()

    refs8j = [np.asarray(handle8j(i).toarray()) for i in range(6)]
    with _serve8j.serving(workers=2,
                          batching={"max_batch": 8,
                                    "linger": 0.005}) as sv:
        _batched8j.warm(handle8j, buckets=sv.batching.buckets)
        rep8j = bolt.analysis.check(handle8j())
        assert rep8j.has("BLT015")        # batch eligibility, forecast
        c0 = _engine8j.counters()
        futs = [sv.submit(handle8j(i), tenant="u%d" % (i % 3))
                for i in range(24)]
        outs = [np.asarray(f.result(timeout=120).toarray())
                for f in futs]
        c1 = _engine8j.counters()
        st8j = sv.stats()["batching"]
    assert all(np.array_equal(o, refs8j[i % 6])
               for i, o in enumerate(outs))       # bit-identical lanes
    assert c1["misses"] == c0["misses"]           # steady state: zero
    assert c1["aot_compiles"] == c0["aot_compiles"]   # fresh compiles
    saved = ((c1["batched_requests"] - c0["batched_requests"])
             - (c1["batched_dispatches"] - c0["batched_dispatches"]))
    print("  24 same-shape requests over 3 tenants: %d coalesced "
          "dispatches served %d requests (%d dispatches saved), zero "
          "fresh compiles, every result bit-identical; occupancy %s"
          % (c1["batched_dispatches"] - c0["batched_dispatches"],
             c1["batched_requests"] - c0["batched_requests"], saved,
             st8j["occupancy"].get("mean")))

    # ------------------------------------------------------------------
    section("8k. stream a dataset at half the bytes: codec ingest")
    # the ISSUE-14 lever for the transfer-bound streaming path: the
    # SAME loader, with an ingest codec armed — uploader workers ENCODE
    # each slab on host, half the bytes cross the link (the transfer
    # counters are the proof), and the slab program DECODES on device
    # fused into the fold.  Lossy codecs are an explicit opt-in with
    # documented envelopes; the lossless "delta-f32" codec is
    # BIT-IDENTICAL to uncompressed streaming and allowed everywhere
    # (order statistics included).
    from bolt_tpu import engine as _engine8k
    from bolt_tpu import stream as _stream8k
    big8k = (np.abs(rs.randn(512, 64, 8)) + 0.5).astype(np.float32)

    def load8k(codec=None):
        src = bolt.fromcallback(lambda idx: big8k[idx], big8k.shape,
                                mesh, dtype=np.float32, chunks=128,
                                codec=codec)
        return src.map(lambda v: v + 1).sum()

    rep8k = bolt.analysis.check(load8k("bf16"))
    assert rep8k.has("BLT016")            # bytes-saved forecast
    ref8k = np.asarray(load8k().toarray())
    c0 = _engine8k.counters()
    half8k = np.asarray(load8k("bf16").toarray())      # 0.5x the bytes
    c1 = _engine8k.counters()
    wire8k = c1["transfer_bytes"] - c0["transfer_bytes"]
    assert wire8k == big8k.nbytes // 2    # the wire-bytes proof
    assert np.allclose(half8k, ref8k, rtol=1e-2)       # bf16 envelope
    exact8k = np.asarray(load8k("delta-f32").toarray())
    assert np.array_equal(exact8k, ref8k)              # LOSSLESS
    # the scope form: one thread's opt-in, same stack discipline as
    # stream.uploaders — a per-source codec= always wins over it
    with _stream8k.codec("delta-f32"):
        assert np.array_equal(np.asarray(load8k().toarray()), ref8k)
    print("  streamed %d MB as %d MB on the wire (%.2fx): bf16 within "
          "1e-2, delta-f32 bit-identical, decode fused on device "
          "(codec_bytes_raw/wire: %d/%d)"
          % (big8k.nbytes >> 20, wire8k >> 20,
             wire8k / big8k.nbytes,
             c1["codec_bytes_raw"] - c0["codec_bytes_raw"],
             c1["codec_bytes_wire"] - c0["codec_bytes_wire"]))

    # ------------------------------------------------------------------
    section("8l. swap a dataset larger than HBM: the streamed shuffle")
    # the ISSUE-18 tentpole: a swap RECORDED on a streamed source stays
    # lazy and resolves as a two-phase shuffle — phase 1 re-buckets
    # each slab on device as it lands (overlapping ingest), phase 2
    # concatenates the resident buckets, or — past the budget — spills
    # them through the checkpoint slab files and re-streams them.  The
    # result is bit-identical to materialise-then-swap: a shuffle moves
    # bytes, it never rounds.
    import tempfile as _tf8l
    from bolt_tpu import checkpoint as _ckpt8l
    big8l = rs.randn(512, 64, 8).astype(np.float32)

    def load8l():
        return bolt.fromcallback(lambda idx: big8l[idx], big8l.shape,
                                 mesh, dtype=np.float32, chunks=128)

    swapped = load8l().swap((0,), (0,))   # lazy: nothing streamed yet
    rep8l = bolt.analysis.check(swapped)
    assert rep8l.has("BLT017")            # the shuffle-plan forecast
    got8l = np.asarray(swapped._data)     # resolves the two phases
    ref8l = np.transpose(big8l, (1, 0, 2))
    assert np.array_equal(got8l, ref8l)   # BIT-identical
    # force the out-of-core leg: a one-byte budget spills every
    # re-keyed bucket to disk and phase 2 re-streams them — same bits;
    # post-swap chunk().map() stages ride the re-streamed source
    spill8l = _tf8l.mkdtemp(prefix="bolt-ex8l-")
    with _stream8k.spill(dir=spill8l, budget=1):
        out8l = (load8l().swap((0,), (0,))
                 .chunk((16, 8)).map(lambda blk: blk * 2.0)
                 .unchunk())
        assert np.array_equal(np.asarray(out8l._data), ref8l * 2.0)
    c8l = _engine8k.counters()
    assert c8l["spill_bytes"] > 0         # the buckets really hit disk
    _ckpt8l.spill_clear(spill8l)          # sweep the bolt-spill-* dir
    print("  streamed swap bit-identical resident AND spilled "
          "(shuffle %d KB moved, spill %d KB written, %.3fs)"
          % (c8l["shuffle_bytes"] >> 10, c8l["spill_bytes"] >> 10,
             c8l["shuffle_seconds"]))

    # ------------------------------------------------------------------
    section("9. time-series pipeline: detrend -> zscore -> PCA")
    # per-pixel calcium-imaging-style workflow: remove each pixel's slow
    # drift, standardise, then find the dominant temporal components —
    # the per-record transforms are deferred maps, so they fuse into the
    # PCA program: ONE compiled pass over the data
    import scipy.signal
    from bolt_tpu.ops import detrend, pca, zscore
    npix, T = 128, 40
    drift = np.linspace(0, 3, T)
    sig = np.sin(np.linspace(0, 6 * np.pi, T))
    traces = (rs.randn(npix, T) * 0.2 + drift
              + np.outer(rs.randn(npix), sig)).astype(np.float64)
    tb = bolt.array(traces, mesh, axis=(0,))
    clean = zscore(detrend(tb, order=1), epsilon=1e-9)
    scores, comps, svals = pca(clean, k=2)
    ref = scipy.signal.detrend(traces, axis=1)
    ref = (ref - ref.mean(1, keepdims=True)) / (ref.std(1, keepdims=True) + 1e-9)
    rv = np.linalg.svd(ref, compute_uv=False)
    assert np.allclose(svals, rv[:2], rtol=1e-6)
    # the dominant component tracks the injected oscillation
    c0 = np.asarray(comps[:, 0])
    sig_z = scipy.signal.detrend(sig)
    sig_z /= np.linalg.norm(sig_z)
    assert abs(np.dot(c0, sig_z)) > 0.95

    # ------------------------------------------------------------------
    section("10. event detection: crosscorr + fourier + quantile")
    # which traces carry the oscillation?  crosscorr scores every record
    # against the template; fourier reads coherence at the known bin;
    # quantile gives per-record thresholds — all compiled on-mesh
    from bolt_tpu.ops import crosscorr, fourier
    rs10 = np.random.RandomState(123)
    load10 = rs10.randn(npix)
    tr10 = rs10.randn(npix, T) * 0.3 + np.outer(load10, sig)
    tb10 = bolt.array(tr10, mesh, axis=(0,))
    r = crosscorr(tb10, sig, lag=0).toarray()[:, 0]
    top = np.argsort(np.abs(load10))[-8:]
    bottom = np.argsort(np.abs(load10))[:8]
    assert np.abs(r[top]).mean() > 0.5 > np.abs(r[bottom]).mean()
    coh, phase = fourier(tb10, freq=3)    # sig = 3 cycles over the window
    coh = np.asarray(coh.toarray())
    assert coh.shape == (npix,)
    assert coh[top].mean() > coh[bottom].mean()
    q90 = tb10.quantile(0.9, axis=(1,))   # per-trace 90th percentile
    assert np.allclose(np.asarray(q90.toarray()),
                       np.quantile(tr10, 0.9, axis=1), atol=1e-8)

    # ------------------------------------------------------------------
    section("11. grouped analysis: segment_reduce + topk + histogram")
    # per-condition trial averages (reduceByKey), the strongest responders
    # per condition, and the response distribution — all on-mesh
    from bolt_tpu.ops import histogram, segment_reduce, topk, unique
    rs11 = np.random.RandomState(11)
    ntrial, cond = 64, rs11.randint(0, 4, size=64)
    resp = rs11.randn(ntrial, 32) + cond[:, None] * 0.5   # condition effect
    rb = bolt.array(resp, mesh, axis=(0,))
    means = segment_reduce(rb, cond, num_segments=4, op="mean")
    got = np.asarray(means.toarray())
    for g in range(4):
        assert np.allclose(got[g], resp[cond == g].mean(axis=0), atol=1e-6)
    # group means should be ordered by the injected effect
    assert got.mean(axis=1)[0] < got.mean(axis=1)[3]
    vals, idx = topk(means, 3, axis=1)     # strongest channels per group
    ref_idx = np.argsort(-got, axis=1, kind="stable")[:, :3]
    assert np.array_equal(np.asarray(idx.toarray()), ref_idx)
    assert np.allclose(np.asarray(vals.toarray()),
                       np.take_along_axis(got, ref_idx, axis=1))
    counts, edges = histogram(rb, bins=12)
    cn, en = np.histogram(resp, bins=12)
    assert np.array_equal(counts, cn) and np.allclose(edges, en)
    labels_seen = unique(bolt.array(cond, mesh))
    assert np.array_equal(labels_seen, np.unique(cond))

    print("ALL EXAMPLES OK")


if __name__ == "__main__":
    main()
