"""Tracing, timing and debug instrumentation.

The reference ships NO in-repo tracing/profiling — users fall back to the
Spark UI and JVM metrics (SURVEY §5).  The TPU stack does better for free:
``jax.profiler`` captures device traces viewable in TensorBoard/Perfetto,
and XLA programs have precise completion semantics, so wall-clock and GB/s
numbers are meaningful.  This module packages that:

* :func:`trace` — context manager writing a device trace to a log dir.
* :func:`annotate` — names a region so it shows up in the trace timeline.
* :func:`timeit` — robust wall-clock of a function over device arrays,
  fetching results to force completion (NOTE: fetching, not
  ``block_until_ready``, is the reliable barrier on remote-attached
  devices).
* :func:`throughput` — GB/s given bytes touched, the BASELINE "GB/s/chip"
  metric.
* :func:`debug_nans` — toggles jax NaN checking (the race-detector slot in
  SURVEY §5: SPMD is race-free by construction; numeric poison is the
  practical hazard, so that's what debug mode checks).
"""

import contextlib
import time

import numpy as np

import jax


def trace(logdir):
    """Device-trace context manager::

        with bolt_tpu.profile.trace("/tmp/trace"):
            b.map(f).sum().toarray()

    View with TensorBoard's profile plugin or Perfetto."""
    return jax.profiler.trace(logdir)


def annotate(name):
    """Name a region in the device trace timeline."""
    return jax.profiler.TraceAnnotation(name)


def timeit(fn, iters=5, warmup=1):
    """``(result, best_seconds)`` for ``fn()`` over ``iters`` timed runs.

    Works on ANY pytree result: each run blocks on the whole output via
    ``jax.block_until_ready`` (tuples/dicts/dataclasses of arrays, and
    non-array leaves, all handled — not just objects exposing a
    ``.block_until_ready`` method), then pulls it to the host
    (``jax.device_get``) so the timing includes real completion — on
    remote-attached devices the fetch is the reliable barrier.

    ``iters`` must be >= 1 (a "best of zero runs" has no answer);
    negative ``warmup`` counts as zero.
    """
    if iters < 1:
        raise ValueError(
            "timeit needs iters >= 1 (got %r): best-of is undefined over "
            "zero timed runs" % (iters,))
    result = None
    for _ in range(max(warmup, 0)):
        result = jax.device_get(jax.block_until_ready(fn()))
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        result = jax.device_get(jax.block_until_ready(fn()))
        best = min(best, time.perf_counter() - t0)
    return result, best


def throughput(nbytes, seconds):
    """GB/s for ``nbytes`` touched in ``seconds`` (the BASELINE
    "GB/s/chip" metric when run single-chip)."""
    return nbytes / 1e9 / seconds


def array_bytes(barray):
    """Logical payload bytes of a bolt array."""
    return int(np.prod(barray.shape, dtype=np.int64)) * barray.dtype.itemsize


def debug_nans(enable=True):
    """Toggle jax's NaN checking for all subsequently compiled programs."""
    jax.config.update("jax_debug_nans", bool(enable))


@contextlib.contextmanager
def instrument():
    """Context manager recording per-op-family execution counts, compile
    (executable-build) counts and host dispatch time for every bolt
    operation run inside it::

        with bolt_tpu.profile.instrument() as stats:
            b.map(f).sum().toarray()
            b.stats()
        print(bolt_tpu.profile.report(stats))

    ``stats`` maps op family — the executable-cache key prefix:
    ``"chain"`` (materialising a deferred map chain), ``"first"``,
    ``"reduce"``, ``"stat"`` (mean/sum/... family), ``"welford"``,
    ``"filter-fused"``, ``"swap"``, ``"getitem"``, ... — to
    ``{"calls", "builds", "dispatch_s"}``.  ``builds`` counts jit-cache
    misses — the RECOMPILE detector: a pipeline that rebuilds the same
    family every iteration (e.g. a fresh lambda per call) shows
    ``builds == calls`` instead of ``builds == 1``.  ``dispatch_s`` is
    host-side dispatch (launches are async); use :func:`timeit` or
    :func:`trace` for device-completion timing.

    The reference has nothing comparable in-repo (Spark UI fills the
    slot, SURVEY §5); this is the framework-level half of that story.
    """
    import bolt_tpu.stream as _stream
    import bolt_tpu.tpu.array as _arr
    import bolt_tpu.tpu.chunk as _chunk
    import bolt_tpu.tpu.multistat as _mstat
    import bolt_tpu.tpu.stack as _stack
    import bolt_tpu.tpu.stats as _stats
    # every module binds _cached_jit by name at import; snapshot and
    # restore EACH binding so nested/overlapping contexts unwind cleanly
    saved = {m: m._cached_jit for m in (_arr, _chunk, _mstat, _stack,
                                        _stats, _stream)}
    orig = _arr._cached_jit
    stats = {}

    def wrapped(key, builder):
        fam = key[0] if isinstance(key, tuple) and key else str(key)
        e = stats.setdefault(
            fam, {"calls": 0, "builds": 0, "dispatch_s": 0.0})

        def counting_builder():
            e["builds"] += 1
            return builder()

        fn = orig(key, counting_builder)

        def timed(*a, **k):
            t0 = time.perf_counter()
            out = fn(*a, **k)
            e["calls"] += 1
            e["dispatch_s"] += time.perf_counter() - t0
            return out
        return timed

    for m in saved:
        m._cached_jit = wrapped
    try:
        yield stats
    finally:
        for m, fn in saved.items():
            # restore only our own wrapper: if an inner instrument() is
            # still live (contexts should exit LIFO, but generators /
            # ExitStacks can misorder), leave its wrapper counting
            # rather than silently disabling it
            if m._cached_jit is wrapped:
                m._cached_jit = fn


def report(stats):
    """Human-readable table for :func:`instrument` results."""
    lines = ["%-16s %7s %7s %12s" % ("family", "calls", "builds",
                                     "dispatch_s")]
    for fam in sorted(stats):
        e = stats[fam]
        lines.append("%-16s %7d %7d %12.4f"
                     % (fam, e["calls"], e["builds"], e["dispatch_s"]))
    return "\n".join(lines)


def engine_counters():
    """Snapshot of the central dispatch engine's counters (see
    :mod:`bolt_tpu.engine`): executable-cache ``hits``/``misses``,
    ``aot_compiles`` with ``lower_seconds``/``compile_seconds`` split
    (the persistent on-disk cache drives ``compile_seconds`` to ~0 in a
    warm process), ``dispatches``/``dispatch_seconds`` host-side launch
    accounting, ``fallbacks`` (dispatches the AOT path could not serve),
    ``donations`` (terminal buffer donations granted),
    ``persistent_hits``/``persistent_misses`` for the on-disk XLA
    cache, and the static-analysis tallies: ``diagnostics`` (findings
    emitted by ``bolt_tpu.analysis.check``), ``strict_checks`` /
    ``strict_rejections`` (pre-dispatch checks run and dispatches
    refused inside an ``analysis.strict()`` scope).  The snapshot is
    consistent — taken under the same lock every increment holds.

    Since PR 4 the backing store is the ``"engine"`` counter group in
    the :mod:`bolt_tpu.obs.metrics` registry (this function is a thin
    facade over ``engine.counters()``, itself a facade over the group):
    identical keys, types and semantics, now enumerable alongside every
    other metric via ``bolt_tpu.obs.registry().snapshot()``."""
    from bolt_tpu import engine
    return engine.counters()


def reset_engine_counters():
    from bolt_tpu import engine
    engine.reset_counters()


def overlap_efficiency(counters=None):
    """Fraction of streaming ingest time (host production + upload)
    hidden behind device compute, from the engine's ``stream_*``
    counters: ``stream_overlap_seconds / stream_ingest_seconds`` where
    per run ``overlap = max(0, ingest + compute − wall)``.  Ingest is
    summed across the uploader pool's workers (parallel ingest can
    exceed wall time — that surplus IS hidden work), and compute is the
    consumer's dispatch + window/final sync time, so the ratio stays
    meaningful under async dispatch.  ``0.0`` when nothing has streamed
    (or nothing overlapped); values toward ``1.0`` mean transfer is
    fully hidden — the out-of-core pipeline runs at compute speed, not
    ingest speed.

    Well-defined on EVERY input: a fresh process, a CPU-only container
    that never streamed, or a hand-built ``counters`` dict with keys
    missing all return ``0.0`` instead of dividing by zero."""
    c = engine_counters() if counters is None else counters
    ingest = c.get("stream_ingest_seconds", 0.0) or 0.0
    if ingest <= 0.0:
        return 0.0
    return (c.get("stream_overlap_seconds", 0.0) or 0.0) / ingest


def engine_report(counters=None):
    """Human-readable table of the engine counters::

        print(bolt_tpu.profile.engine_report())

    A fresh process (or an empty/all-zero ``counters`` dict) renders a
    "(no engine activity)" note instead of raising or printing a wall
    of zeros as if something ran."""
    c = engine_counters() if counters is None else counters
    lines = ["%-24s %12s" % ("counter", "value")]
    if not c or not any(v for v in c.values()):
        lines.append("(no engine activity)")
        return "\n".join(lines)
    for k in sorted(c):
        v = c[k]
        lines.append("%-24s %12s"
                     % (k, ("%.4f" % v) if isinstance(v, float) else v))
    return "\n".join(lines)


def memory_stats(device=None):
    """Per-device memory counters (HBM on TPU) as a dict.  Keys follow
    the PJRT convention (``bytes_in_use``, ``bytes_limit``,
    ``peak_bytes_in_use``, ...).

    DOCUMENTED DEGRADED SHAPE: returns the empty dict ``{}`` — never
    raises — when the backend lacks ``memory_stats()`` (CPU containers),
    when the query returns nothing, or when no device is visible at
    all; callers can always write ``memory_stats().get("bytes_in_use",
    0)``."""
    try:
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
    except Exception:
        return {}
    return dict(stats) if stats else {}
