"""Multi-tenant serving layer: MANY pipelines, ONE engine, shared HBM.

Everything below this module optimises one pipeline at a time; a
process serving heavy traffic runs many of them at once, and two naive
concurrent streams each assume sole ownership of device memory (their
donation rings independently sized to the whole budget) while their
dispatches serialise on ad-hoc locks.  This module is the scheduler
that lets N tenants share one process and one device mesh safely:

* a **device-memory arbiter** (:class:`DeviceArbiter`) generalises the
  streaming executor's donation ring + in-flight window into ONE
  process-wide bytes-weighted budget: streamed slab uploads
  (``bolt_tpu.stream`` acquires per slab, in slab order, releasing on
  confirmed retirement) and terminal dispatches (the worker leases a
  pipeline's estimated working set) draw permits from it, so N tenants
  split HBM instead of each assuming all of it.  Waiters are queued
  per tenant and granted **round-robin across tenants, FIFO within a
  tenant** — fair share across tenants, in-order budget delivery per
  stream (the executor's ``_Reseq`` fencing keeps each tenant's fold
  bit-exact regardless of grant interleaving);
* a **fair-share scheduler** (:class:`Server`): ``submit(pipeline,
  tenant=...)`` returns a :class:`Future`; worker threads pop jobs
  round-robin across per-tenant queues, so one chatty tenant cannot
  starve the rest, while each tenant's own jobs run in submission
  order.  ``Server(weights={tenant: n})`` generalises the rotation to
  a WEIGHTED fair share: the head tenant is served up to *n* queued
  jobs (integer credits) per turn — default 1 keeps the plain
  round-robin bit-for-bit, and any tenant with work is still served
  within one rotation (starvation-free);
* a **fleet-warm start**: ``Server(start_warm=dir)`` attaches a
  pre-seeded ``engine.persistent_cache`` directory before the first
  submit, so a fresh process serves its first request with ZERO fresh
  XLA compiles (executables load from disk, counted as the engine's
  ``persistent_warm_hits``);
* **cross-tenant coalescing of identical executables**: the engine
  cache is keyed on program structure, and ``engine.get`` /
  ``_Dispatch`` now coalesce concurrent identical builds/compiles
  (``coalesced_builds`` / ``coalesced_compiles`` counters), so N
  tenants running the same pipeline shape trace and compile it ONCE —
  provided they share the stage callables (hoist user functions to
  module level, as every bench does; two bytecode-identical lambdas
  are distinct cache keys);
* **admission control with backpressure**: the queue is bounded
  (``queue_limit``); ``policy="queue"`` blocks the submitter until
  room frees (backpressure), ``policy="reject"`` raises
  :class:`AdmissionError` immediately.  A pipeline whose estimated
  working set exceeds the WHOLE budget can never run and is rejected
  at submit time — the ``BLT010`` diagnostic
  (``bolt_tpu.analysis.check`` emits it whenever a serving arbiter is
  active, so ``explain()`` shows the refusal before anything is
  queued);
* **continuous micro-batching** (``Server(batching=...)``, ROADMAP
  item 4): a high-QPS service is mostly a firehose of SMALL
  identical-shape pipelines where per-request dispatch overhead — not
  bytes — is the roofline.  Queued requests sharing a BATCH KEY (same
  pipeline structure, shapes, dtypes, terminal and sharding — see
  ``bolt_tpu.tpu.batched.batch_key``), ACROSS tenants, coalesce into
  ONE stacked dispatch: inputs stack along a new leading axis, the
  standalone terminal body runs vmapped (the ``StackedArray`` batched-
  execution idea applied to the request queue), and each lane's
  results scatter back to its request's ``Future`` — BIT-IDENTICAL to
  the standalone dispatch.  Partial batches pad to bucketed widths
  (powers of two up to ``max_batch``) so steady state compiles a small
  fixed executable set and then runs zero fresh XLA compiles
  (``bolt_tpu.tpu.batched.warm`` pre-compiles the buckets for a
  fleet); a worker that found at least one coalescible partner lingers
  up to ``linger`` seconds to fill the bucket, while a lone request
  never waits.  Per-request attribution is preserved: every future
  keeps its own wait/assembly/run seconds and ``batch_width``, every
  tenant its own counters and arbiter leases.  Diagnostics:
  ``BLT015`` forecasts batch eligibility, engine counters
  ``batched_dispatches``/``batched_requests`` and the
  ``serve.batch_occupancy.hist`` histogram record the realised
  coalescing (``stats()["batching"]`` summarises them).

Observability: queue depth (+ high-water), per-job queue-wait and run
seconds (totals per tenant, a log2 histogram overall), arbiter
in-use/high-water bytes and wait counts all land in
``bolt_tpu.obs.registry()`` under ``serve.*`` names; every job runs
inside an ``engine.tenant(<name>)`` scope, so the engine counters —
transfer bytes, compiles, dispatches — are ALSO tallied per tenant
(``engine.tenant_counters(name)``), streamed ingest traffic included
(the executor forwards the tag into its uploader pool).

The blessed entry points::

    with bolt_tpu.serve.serving(workers=4, budget_bytes=2 << 30) as sv:
        futs = [sv.submit(make_pipeline(), tenant=t) for t in tenants]
        outs = [f.result() for f in futs]

or the module-level :func:`submit`, which lazily starts a default
server (env-tunable: ``BOLT_SERVE_WORKERS`` / ``BOLT_SERVE_BUDGET``
/ ``BOLT_SERVE_QUEUE_LIMIT`` / ``BOLT_SERVE_BATCHING`` — with
``BOLT_SERVE_MAX_BATCH`` and ``BOLT_SERVE_LINGER`` tuning the armed
policy).  Lint rule BLT108 keeps this module and
``stream.py`` the ONLY homes of raw thread construction in the
package — every other concurrency need routes through one of them.
"""

import contextlib
import os
import threading
from collections import OrderedDict, deque

from bolt_tpu import _lockdep
from bolt_tpu import engine as _engine
from bolt_tpu.obs import metrics as _metrics
from bolt_tpu.obs import trace as _obs
from bolt_tpu.obs.trace import clock as _clock
from bolt_tpu.parallel import podwatch as _podwatch
from bolt_tpu.parallel.podwatch import PeerLostError  # noqa: F401 — the
#   retryable pod-outage error submit(retries=) honours; re-exported so
#   serving callers need not import the liveness layer

# ---------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------

# process-wide HBM budget for the arbiter.  The default is deliberately
# conservative (1 GB): serving N tenants means N rings + N in-flight
# windows, and the budget is what keeps their SUM bounded; size it to
# the device's usable HBM in production.
_DEF_BUDGET = int(os.environ.get("BOLT_SERVE_BUDGET", str(1 << 30)))
_DEF_WORKERS = max(1, int(os.environ.get("BOLT_SERVE_WORKERS", "4")))
_DEF_QUEUE = max(1, int(os.environ.get("BOLT_SERVE_QUEUE_LIMIT", "64")))
# continuous micro-batching default: OFF unless armed by env (the knob
# is Server(batching=...); BOLT_SERVE_BATCHING=1 arms the default
# server / bare Server() with the default policy)
_DEF_BATCHING = os.environ.get("BOLT_SERVE_BATCHING", "").lower() \
    in ("1", "true", "yes")

# per-tenant + global serve counter schema (obs registry groups
# "serve" and "serve/<tenant>")
_SCHEMA = {
    "submitted": 0,            # jobs accepted into the queue
    "rejected": 0,             # jobs refused (queue full / BLT010)
    "completed": 0,            # jobs finished successfully
    "failed": 0,               # jobs whose pipeline raised
    "queue_wait_seconds": 0.0,  # total submit->start wait
    "run_seconds": 0.0,        # total start->finish execution time
    "retried": 0,              # per-submit retry attempts consumed
    "expired": 0,              # jobs failed on their deadline= budget
    "peer_losses": 0,          # pod peer deaths observed (ISSUE 11 —
                               # admission drained until the reform)
    "reforms": 0,              # supervised reforms driven (ISSUE 12)
    "rejoins": 0,              # identities folded back in by reform-up
    "supervise_seconds": 0.0,  # total pause -> resume recovery wall
}


class AdmissionError(RuntimeError):
    """A submission the server refused: the bounded queue is full under
    ``policy="reject"``, or the pipeline's estimated device working set
    exceeds the arbiter's whole budget (BLT010 — it could never run)."""


class DeadlineError(RuntimeError):
    """A job's per-submit ``deadline=`` budget (seconds since submit)
    expired before it could start; delivered through
    ``Future.result()``."""


class BatchPolicy:
    """Continuous micro-batching policy (``Server(batching=...)``):

    * ``max_batch`` — widest coalesced dispatch (one batched program
      serves up to this many queued same-key requests; default
      ``BOLT_SERVE_MAX_BATCH`` / 16);
    * ``linger`` — micro-wait in seconds to FILL a forming batch: once
      a worker's gather found at least one coalescible partner it waits
      up to this long for more same-key arrivals before dispatching
      (default ``BOLT_SERVE_LINGER`` / 0.002).  A lone request never
      lingers, so low-QPS single-request latency is untouched;
    * ``buckets`` — the compiled batch widths (default powers of two up
      to ``max_batch``): partial batches PAD to the next bucket, so
      steady state compiles a small fixed executable set and then runs
      zero fresh XLA compiles;
    * ``autotune`` — the width-autotuning scaffold (off by default):
      when True, :meth:`rearm` (called by ``batched.warm(make,
      policy=...)`` on a re-arm) re-derives the bucket set from the
      OBSERVED ``serve.batch_occupancy.hist`` distribution
      (``batched.autotune_buckets``), so the compiled widths track the
      occupancy mix traffic actually realises.  With autotune off the
      static knobs are untouched — today's behaviour exactly.
    """

    __slots__ = ("max_batch", "linger", "buckets", "autotune")

    def __init__(self, max_batch=None, linger=None, buckets=None,
                 autotune=False):
        from bolt_tpu.tpu import batched as _batched
        self.autotune = bool(autotune)
        if buckets:
            buckets = tuple(sorted(int(b) for b in buckets))
            if buckets[0] < 2:
                raise ValueError("batch buckets must be >= 2, got %r"
                                 % (buckets,))
            if max_batch is None:
                max_batch = buckets[-1]
        self.max_batch = int(max_batch if max_batch is not None
                             else _batched.DEFAULT_MAX_BATCH)
        if self.max_batch < 2:
            raise ValueError("max_batch must be >= 2, got %d"
                             % self.max_batch)
        self.linger = float(linger if linger is not None
                            else _batched.DEFAULT_LINGER)
        if self.linger < 0:
            raise ValueError("linger must be >= 0 seconds, got %r"
                             % (linger,))
        self.buckets = buckets or _batched.buckets_for(self.max_batch)
        if self.buckets[-1] != self.max_batch:
            raise ValueError(
                "the largest bucket (%d) must EQUAL max_batch (%d): a "
                "smaller one cannot serve a full batch, a larger one "
                "would pad every dispatch past the promised widest "
                "width" % (self.buckets[-1], self.max_batch))

    def rearm(self, hist_buckets=None):
        """Autotune re-arm: replace :attr:`buckets` with the set
        :func:`bolt_tpu.tpu.batched.autotune_buckets` derives from the
        observed ``serve.batch_occupancy.hist`` (``hist_buckets``
        overrides the registry read, for tests).  Returns True when
        the buckets changed hands; a no-op False when ``autotune`` is
        off (static knobs untouched) or nothing has been observed yet.
        The derived set always ends at ``max_batch``, preserving the
        policy invariant."""
        if not self.autotune:
            return False
        from bolt_tpu.tpu import batched as _batched
        if hist_buckets is None:
            from bolt_tpu.obs import metrics as _metrics
            hist_buckets = _metrics.registry().histogram(
                "serve.batch_occupancy.hist", lo=0, hi=9).buckets()
        derived = _batched.autotune_buckets(hist_buckets, self.max_batch)
        if derived is None:
            return False
        self.buckets = derived
        return True

    def __repr__(self):
        return ("BatchPolicy(max_batch=%d, linger=%g, buckets=%s%s)"
                % (self.max_batch, self.linger, self.buckets,
                   ", autotune" if self.autotune else ""))


# ---------------------------------------------------------------------
# the device-memory arbiter
# ---------------------------------------------------------------------

class _Ticket:
    __slots__ = ("nbytes", "granted", "skipped")

    def __init__(self, nbytes):
        self.nbytes = nbytes
        self.granted = False
        self.skipped = 0      # grants that bypassed this waiting head


# grants that may bypass a waiting head ticket before the arbiter stops
# feeding newer requests and drains toward it (the anti-starvation
# barrier: without it, sustained small-slab traffic keeps _used high
# forever and a large request never sees the budget it needs)
_STARVE_LIMIT = 64


class DeviceArbiter:
    """Process-wide bytes-weighted device-memory budget.

    ``acquire(nbytes, tenant)`` blocks until the bytes fit (or the
    caller's ``stop`` event fires); ``release(nbytes)`` returns them.
    Waiters queue FIFO per tenant and are granted round-robin ACROSS
    tenants — the fair-share rule — with one escape: a request larger
    than the whole budget is granted when nothing else holds bytes
    (it runs alone), so an oversized slab degrades to serial execution
    instead of hanging forever.

    Prefer :meth:`lease` over raw acquire/release: a
    :class:`ArbiterLease` tracks its own outstanding bytes and
    ``close()`` returns whatever an aborted run still held.
    """

    def __init__(self, budget_bytes):
        self.budget = int(budget_bytes)
        if self.budget <= 0:
            raise ValueError("arbiter budget must be positive, got %d"
                             % self.budget)
        self._cond = _lockdep.condition("serve.arbiter")
        self._used = 0
        self._queues = OrderedDict()       # tenant -> deque[_Ticket]
        self._ring = deque()               # tenants with waiters (RR)
        reg = _metrics.registry()
        self._g_used = reg.gauge("serve.arbiter_in_use_bytes")
        self._g_hw = reg.gauge("serve.arbiter_in_use_high_water")
        self._c_waits = reg.counter("serve.arbiter_waits")
        self._c_wait_s = reg.counter("serve.arbiter_wait_seconds", 0.0)

    # -- accounting ----------------------------------------------------

    def in_use(self):
        with self._cond:
            return self._used

    def waiting(self):
        """Queued (ungranted) requests across all tenants."""
        with self._cond:
            return sum(len(q) for q in self._queues.values())

    # -- the grant rule ------------------------------------------------

    def _fits(self, nbytes):
        return self._used + nbytes <= self.budget or self._used == 0

    def _grant_locked(self):
        """Round-robin across tenants with waiters, FIFO within each:
        grant every head ticket that fits, looping until a full cycle
        grants nothing.  The rotation pointer advances only PAST a
        grantee (a full cycle of failed probes returns the ring to its
        origin), so the next grant always starts at the tenant after
        the last one served — fair share, not scan-order luck."""
        made = True
        while made and self._ring:
            made = False
            # anti-starvation barrier: a head ticket bypassed by more
            # than _STARVE_LIMIT grants becomes the ONLY grantable one —
            # releases then drain _used toward it instead of feeding an
            # endless stream of newer, smaller requests (without this, a
            # near-budget request under sustained small-slab traffic
            # would wait forever; with it, starvation is bounded)
            starved = None
            for q in self._queues.values():
                tk = q[0] if q else None
                if tk is not None and tk.skipped >= _STARVE_LIMIT and \
                        (starved is None or tk.skipped > starved.skipped):
                    starved = tk
            for _ in range(len(self._ring)):
                t = self._ring[0]
                q = self._queues.get(t)
                tk = q[0] if q else None
                if tk is not None and self._fits(tk.nbytes) \
                        and (starved is None or tk is starved):
                    q.popleft()
                    tk.granted = True
                    self._used += tk.nbytes
                    made = True
                    for q2 in self._queues.values():  # age bypassed heads
                        if q2 and q2[0] is not tk:
                            q2[0].skipped += 1
                    self._ring.rotate(-1)   # next cycle starts AFTER t
                    break                   # rescan from the new head
                self._ring.rotate(-1)
        for t in [t for t, q in self._queues.items() if not q]:
            del self._queues[t]
            try:
                self._ring.remove(t)
            except ValueError:
                pass
        self._g_used.set(self._used)
        self._g_hw.high_water(self._used)
        self._cond.notify_all()

    # -- the public doors ----------------------------------------------

    def acquire(self, nbytes, tenant="default", stop=None):
        """Block until ``nbytes`` fit in the budget (True), or until
        ``stop`` is set (False — nothing was acquired)."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        tk = _Ticket(nbytes)
        t0 = _clock()
        with self._cond:
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = deque()
                self._ring.append(tenant)
            q.append(tk)
            self._grant_locked()
            waited = not tk.granted
            while not tk.granted:
                if stop is not None and stop.is_set():
                    # withdraw (grants happen under this lock, so an
                    # ungranted ticket is still safely in its queue)
                    q.remove(tk)
                    self._grant_locked()   # a later head may now fit
                    return False
                self._cond.wait(0.05)
        if waited:
            self._c_waits.inc()
            self._c_wait_s.inc(_clock() - t0)
        return True

    def release(self, nbytes):
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._cond:
            self._used = max(0, self._used - nbytes)
            self._grant_locked()

    def resize(self, budget_bytes):
        """Re-point the budget (degraded-capacity admission, ISSUE 12:
        a supervised pod that shrank N→M rescales to the surviving
        share, and BLT010 floors recompute against the new value on
        the very next submit).  Growing re-grants queued waiters
        immediately; shrinking never claws back granted bytes — the
        budget simply stays over-committed until releases drain it."""
        budget_bytes = int(budget_bytes)
        if budget_bytes <= 0:
            raise ValueError("arbiter budget must be positive, got %d"
                             % budget_bytes)
        with self._cond:
            self.budget = budget_bytes
            self._grant_locked()

    def lease(self, tenant="default"):
        return ArbiterLease(self, tenant)


class ArbiterLease:
    """One run's handle on the arbiter: tracks outstanding bytes so an
    abort path can return EVERYTHING it still holds with one
    :meth:`close` (idempotent; release of bytes never acquired is
    clamped to the outstanding balance)."""

    __slots__ = ("arbiter", "tenant", "_lock", "_out")

    def __init__(self, arbiter, tenant):
        self.arbiter = arbiter
        self.tenant = tenant
        self._lock = _lockdep.lock("serve.lease")
        self._out = 0

    def outstanding(self):
        with self._lock:
            return self._out

    def acquire(self, nbytes, stop=None):
        ok = self.arbiter.acquire(nbytes, self.tenant, stop=stop)
        if ok:
            with self._lock:
                self._out += int(nbytes)
        return ok

    def release(self, nbytes):
        with self._lock:
            n = min(int(nbytes), self._out)
            self._out -= n
        if n:
            self.arbiter.release(n)

    def close(self):
        with self._lock:
            n = self._out
            self._out = 0
        if n:
            self.arbiter.release(n)


# ---------------------------------------------------------------------
# futures
# ---------------------------------------------------------------------

class Future:
    """The handle :meth:`Server.submit` returns.  ``result(timeout)``
    blocks for the pipeline's value (re-raising its exception);
    ``wait_seconds`` / ``run_seconds`` expose the job's queue and
    execution time once known."""

    __slots__ = ("tenant", "_event", "_result", "_exc", "submitted_s",
                 "started_s", "finished_s", "batch_width",
                 "assembly_seconds")

    def __init__(self, tenant):
        self.tenant = tenant
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self.submitted_s = _clock()
        self.started_s = None
        self.finished_s = None
        # micro-batching attribution (None when the job ran standalone):
        # how many requests this job's coalesced dispatch actually
        # served, and the assembly window — gather scan + linger
        # micro-wait + claim, i.e. pop to dispatch begin (the device
        # execution itself is run_seconds' job)
        self.batch_width = None
        self.assembly_seconds = None

    def done(self):
        return self._event.is_set()

    def _finish(self, result=None, exc=None):
        self._result = result
        self._exc = exc
        self.finished_s = _clock()
        self._event.set()

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve job still pending after %ss"
                               % timeout)
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("serve job still pending after %ss"
                               % timeout)
        return self._exc

    @property
    def wait_seconds(self):
        """Submit → start queue wait (None until started)."""
        if self.started_s is None:
            return None
        return self.started_s - self.submitted_s

    @property
    def run_seconds(self):
        """Start → finish execution time (None until finished)."""
        if self.started_s is None or self.finished_s is None:
            return None
        return self.finished_s - self.started_s

    def __repr__(self):
        state = ("done" if self.done()
                 else "running" if self.started_s is not None
                 else "queued")
        return "<serve.Future tenant=%r %s>" % (self.tenant, state)


# ---------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------

def _normalise(pipeline):
    """One uniform job shape: a zero-arg callable returning the result.

    Accepted inputs: a zero-arg callable (called as-is); a bolt array
    carrying lazy state (a pending stat handle, a deferred chain, a
    streaming source) — resolved via ``.cache()`` and returned; any
    other object is rejected eagerly (a silent pass-through would hide
    a caller bug until ``result()``)."""
    if callable(pipeline) and not hasattr(pipeline, "cache"):
        return pipeline, None
    cache = getattr(pipeline, "cache", None)
    if callable(cache):
        return (lambda: pipeline.cache()), pipeline
    raise TypeError(
        "serve.submit needs a zero-arg callable or a bolt array "
        "pipeline (got %r)" % type(pipeline).__name__)


def _estimate(arr):
    """The MINIMUM device working set of a bolt-array pipeline (the
    BLT010 admission floor: one slab for streams — the arbiter degrades
    the ring; base + result for in-memory pipelines).  None when
    nothing could be estimated (callables, local arrays).  Streaming
    plans under an ingest codec (ISSUE 14) estimate — and the executor
    leases — the COMPRESSED slab bytes: ``admission_floor_bytes``
    applies the codec's wire ratio, so a bf16-encoded tenant is
    admitted at half the budget footprint its raw twin would claim."""
    try:
        h = getattr(arr, "_spending", None)
        if h is not None and h.group.kind == "chain":
            # fast path for the high-QPS small-request shape: the
            # admission floor of a chain-kind stat group is its one-pass
            # read — exactly analysis.working_set_bytes' answer, without
            # the per-submit import/isinstance walk
            return int(h.group.base.nbytes)
        from bolt_tpu.analysis import admission_floor_bytes
        return admission_floor_bytes(arr)
    except Exception:
        return None


class Server:
    """The multi-tenant scheduler: per-tenant FIFO queues drained
    round-robin by ``workers`` threads, every job leased against the
    shared :class:`DeviceArbiter` and executed inside its tenant's
    ``engine.tenant`` counter scope.  See the module docstring for the
    full contract."""

    def __init__(self, workers=None, budget_bytes=None, queue_limit=None,
                 policy="queue", weights=None, start_warm=None,
                 supervise=False, batching=None):
        if policy not in ("queue", "reject"):
            raise ValueError("policy must be 'queue' or 'reject', got %r"
                             % (policy,))
        # continuous micro-batching (ROADMAP item 4): queued same-key
        # requests — same pipeline structure, shapes, dtypes, terminal
        # and sharding, ACROSS tenants — coalesce into ONE stacked
        # dispatch (bolt_tpu/tpu/batched.py), results scattered back to
        # their futures bit-identically.  batching=True arms the
        # default BatchPolicy, a dict/BatchPolicy tunes max_batch /
        # linger / buckets; None falls back to BOLT_SERVE_BATCHING;
        # False is explicitly off.
        if batching is None:
            batching = _DEF_BATCHING
        self.batching = None
        self._batched = None
        if batching:
            if batching is True:
                self.batching = BatchPolicy()
            elif isinstance(batching, BatchPolicy):
                self.batching = batching
            elif isinstance(batching, dict):
                self.batching = BatchPolicy(**batching)
            else:
                raise ValueError(
                    "batching must be True/False, a dict of BatchPolicy "
                    "kwargs, or a BatchPolicy (got %r)" % (batching,))
            # arm() happens at the END of __init__: a constructor that
            # raises past this point must not leak the armed count
            # (nothing would ever disarm it, leaving the lazy-reduce
            # door open with no batching server alive)
        self.workers = int(workers if workers is not None
                           else _DEF_WORKERS)
        self.queue_limit = int(queue_limit if queue_limit is not None
                               else _DEF_QUEUE)
        self.policy = policy
        # weighted fair share: tenant -> integer credits per rotation.
        # The scheduler serves up to weight(t) queued jobs from tenant t
        # before moving to the next tenant with work; the default weight
        # 1 keeps today's one-job-per-tenant round-robin bit-for-bit.
        # The ring still guarantees starvation freedom: any tenant with
        # queued work is served within one rotation (sum of weights).
        self._weights = {}
        if weights:
            for t, w in dict(weights).items():
                w = int(w)
                if w < 1:
                    raise ValueError(
                        "tenant weight must be a positive integer, got "
                        "%r for tenant %r" % (w, t))
                self._weights[str(t)] = w
        self._credits = {}             # tenant -> credits left this turn
        # fleet-warm start (ROADMAP item 4 remainder): attach the
        # pre-seeded on-disk XLA cache BEFORE the first submit, so a
        # fresh process serves its first request without a compile
        # storm; engine counter persistent_warm_hits is the proof
        self.warm_dir = None
        if start_warm is not None:
            self.warm_dir = _engine.warm_start(start_warm)
        self.arbiter = DeviceArbiter(budget_bytes if budget_bytes
                                     is not None else _DEF_BUDGET)
        self._cond = _lockdep.condition("serve.scheduler")
        self._queues = OrderedDict()       # tenant -> deque of jobs
        self._ring = deque()               # tenants with queued jobs
        self._depth = 0
        self._closing = False
        self._stop = threading.Event()     # workers exit once drained
        self._cancel = threading.Event()   # close(wait=False) ONLY: a
        #                                    leased job's arbiter wait
        #                                    must survive a clean drain
        # pod fault integration (ISSUE 11): a peer death drains
        # admission — in-flight streamed futures fail with the
        # executor's PeerLostError (their arbiter leases return in the
        # worker's finally), workers start nothing new — until
        # multihost.reform notifies the liveness layer and the queue
        # resumes.  Subscriptions are deregistered on close().
        self._pod_ok = threading.Event()
        self._pod_ok.set()
        self._pod_lost = None
        self._pod_reason = None
        self._pause_t0 = None
        self._pw_handles = (
            _podwatch.on_peer_death(self._on_peer_death),
            _podwatch.on_reform(self._on_pod_reform))
        # self-healing pods (ISSUE 12): supervise=True attaches a
        # recovery supervisor — peer death still drains admission, but
        # the reform is now DRIVEN automatically (elect → plan →
        # multihost.reform → resume), rejoined processes re-expand the
        # pod through the quiesce gate, and the arbiter budget is
        # rescaled to the surviving capacity share (BLT010 floors
        # recompute against it).  Pass an existing Supervisor (the
        # rejoiner's attach() handle) to adopt it instead.
        self.supervisor = None
        self._own_supervisor = False
        self._budget0 = None
        self._pod_nproc0 = None
        if supervise:
            from bolt_tpu.parallel import multihost as _multihost
            from bolt_tpu.parallel import supervisor as _supervisor
            self._budget0 = self.arbiter.budget
            n = _multihost.process_count()
            self._pod_nproc0 = n if n > 1 else None
            if supervise is True:
                self.supervisor = _supervisor.Supervisor(
                    on_pause=self._sup_pause, on_resume=self._sup_resume)
                self._own_supervisor = True
            else:
                self.supervisor = supervise
                self.supervisor.on_pause = self._sup_pause
                self.supervisor.on_resume = self._sup_resume
        reg = _metrics.registry()
        self._counters = reg.group("serve", _SCHEMA)
        self._tc_cache = {}            # tenant -> registry group (memo)
        self._g_depth = reg.gauge("serve.queue_depth")
        self._g_depth_hw = reg.gauge("serve.queue_depth_high_water")
        self._h_wait = reg.histogram("serve.queue_wait_seconds.hist")
        # batch-occupancy distribution: one observation per coalesced
        # dispatch, value = requests served (log2 buckets cover 1..256)
        self._h_occ = reg.histogram("serve.batch_occupancy.hist",
                                    lo=0, hi=9)
        self._threads = [
            threading.Thread(target=self._worker,
                             name="bolt-serve-worker-%d" % i, daemon=True)
            for i in range(self.workers)]
        for th in self._threads:
            th.start()
        if self.batching is not None:
            # truly LAST: nothing in __init__ can raise past this point,
            # so the armed count can never leak without a server owning
            # its disarm (workers started above consume nothing until
            # the first submit)
            from bolt_tpu.tpu import batched as _batched
            self._batched = _batched
            _batched.arm()            # opens multistat's lazy-reduce door

    # -- pod fault integration (bolt_tpu.parallel.podwatch) ------------

    def _on_peer_death(self, pid):
        """Liveness-watch callback: a pod peer died — drain admission
        until the pod reforms.  Fired from the watch thread."""
        self._pod_lost = pid
        self._pod_ok.clear()
        self._counters.add("peer_losses")
        _obs.event("serve.peer_lost", peer=pid)
        with self._cond:
            self._cond.notify_all()

    def _on_pod_reform(self):
        """Liveness-watch callback: ``multihost.reform`` rebuilt the
        runtime on the survivors — resume the queue."""
        self._pod_lost = None
        self._pod_ok.set()
        _obs.event("serve.pod_resumed")
        with self._cond:
            self._cond.notify_all()

    def pod_paused(self):
        """Is admission drained behind a pod peer loss (awaiting
        ``multihost.reform``)?"""
        return not self._pod_ok.is_set()

    # -- the supervisor's hooks (Server(supervise=True), ISSUE 12) -----

    def _sup_pause(self, reason):
        """Supervisor hook: a recovery started (death or rejoin
        quiesce) — drain admission exactly like a raw peer loss."""
        if self._pod_nproc0 is None:
            # the server may have been constructed BEFORE
            # multihost.initialize (process_count read 1 then): the
            # pre-loss width is still visible at pause time — capture
            # it now, or the post-shrink resume would record the
            # SHRUNK width as full capacity and skip the rescale
            try:
                from bolt_tpu.parallel import multihost as _multihost
                n = _multihost.process_count()
                self._pod_nproc0 = n if n > 1 else None
            except Exception:         # noqa: BLE001 — best effort
                pass
        self._pod_reason = reason
        self._pause_t0 = _clock()
        self._pod_ok.clear()
        _obs.event("serve.supervise_pause", reason=str(reason))
        with self._cond:
            self._cond.notify_all()

    def _sup_resume(self, info):
        """Supervisor hook: the reform landed — count it, rescale the
        arbiter budget to the surviving capacity share (degraded-
        capacity admission: BLT010 floors recompute against the new
        budget on the next submit), and resume the queue."""
        keys = {"rejoins": len(info.get("rejoined", ()))}
        if not info.get("deferred"):
            # a deferred growth resumed the pod UNTOUCHED (no reform
            # happened — the pod never went idle for the quiesce)
            keys["reforms"] = 1
        if self._pause_t0 is not None:
            keys["supervise_seconds"] = _clock() - self._pause_t0
            self._pause_t0 = None
        self._counters.update(**keys)
        nproc = int(info.get("nproc") or 0)
        if nproc > 1 and self._budget0 is not None:
            if self._pod_nproc0 is None or nproc > self._pod_nproc0:
                self._pod_nproc0 = nproc      # full capacity sighting
            share = nproc / self._pod_nproc0
            self.arbiter.resize(max(1, int(self._budget0 * share)))
        self._pod_reason = None
        self._pod_lost = None
        self._pod_ok.set()
        _obs.event("serve.supervise_resume", nproc=nproc)
        with self._cond:
            self._cond.notify_all()

    # -- submission ----------------------------------------------------

    def _tenant_counters(self, tenant):
        # memoised per server: the registry group lookup (string format
        # + registry lock) measured as a real per-request cost on the
        # high-QPS small-request path (3-4 lookups per job)
        g = self._tc_cache.get(tenant)
        if g is None:
            g = self._tc_cache[tenant] = _metrics.registry().group(
                "serve/%s" % tenant, _SCHEMA)
        return g

    def _reject(self, tenant, why):
        self._counters.add("rejected")
        self._tenant_counters(tenant).add("rejected")
        raise AdmissionError(why)

    def submit(self, pipeline, tenant="default", retries=0,
               deadline=None):
        """Queue ``pipeline`` for tenant ``tenant``; returns a
        :class:`Future`.  Raises :class:`AdmissionError` when the
        pipeline can never fit the arbiter budget (BLT010), or when the
        queue is full under ``policy="reject"``; under
        ``policy="queue"`` a full queue BLOCKS the submitter until a
        worker frees a slot (backpressure, not unbounded memory).

        Per-submit fault policy (ISSUE 9 — tenant failures stay
        isolated): ``retries=n`` re-runs a raising job up to *n* times
        on its worker (each attempt's exception chained to the one
        before; the arbiter lease spans the attempts and is ALWAYS
        returned); ``deadline=s`` bounds seconds-since-submit — a job
        still queued past it fails with :class:`DeadlineError` instead
        of running, and an expired deadline also stops further
        retries.  Neither affects other tenants' futures."""
        if self._closing:
            raise RuntimeError("serve.Server is closed")
        tenant = str(tenant)
        if not self._pod_ok.is_set():
            # admission is drained behind a pod peer loss: reject-policy
            # servers refuse pointedly, queue-policy servers apply
            # backpressure until multihost.reform resumes the pod
            if self.policy == "reject":
                why = ("pod peer %s was lost" % self._pod_lost
                       if self._pod_lost is not None
                       else "supervised recovery in progress (%s)"
                       % self._pod_reason)
                self._reject(tenant,
                             "admission drained: %s and the pod has "
                             "not reformed yet (multihost.reform "
                             "resumes the queue)" % why)
            while not self._pod_ok.wait(0.05):
                if self._closing:
                    raise RuntimeError("serve.Server is closed")
                sup = self.supervisor
                if sup is not None and sup.failed is not None:
                    self._reject(tenant,
                                 "supervised recovery abandoned (%s); "
                                 "admission stays drained until a "
                                 "manual multihost.reform" % sup.failed)
        retries = max(0, int(retries))
        if deadline is not None:
            deadline = float(deadline)
            if deadline <= 0:
                raise ValueError("deadline must be positive seconds "
                                 "since submit, got %r" % (deadline,))
        job, arr = _normalise(pipeline)
        # the SUBMITTER's effective ingest codec rides into the worker
        # (ISSUE 14): stream scopes are thread-local, so a tenant's
        # `with stream.codec("bf16"): submit(...)` would otherwise be
        # silently dropped on the worker thread — while the admission
        # floor below, computed HERE, already priced the wire bytes.
        # current_codec() collapses scope + process default into one
        # name, so re-entering it on the worker preserves exactly the
        # semantics the submitter saw (a per-source codec= still wins).
        from bolt_tpu import stream as _streamlib
        cname = _streamlib.current_codec()
        if cname is not None:
            inner = job

            def job():
                with _streamlib.codec(cname):
                    return inner()
        est = _estimate(arr) if arr is not None else None
        if est is not None and est > self.arbiter.budget:
            # BLT010: could NEVER run — admitting it would wedge a
            # worker forever (analysis.check emits the same finding)
            self._reject(tenant,
                         "pipeline needs ~%d bytes of device memory but "
                         "the serving budget is %d bytes (BLT010); "
                         "shrink the slabs/operand or raise "
                         "budget_bytes" % (est, self.arbiter.budget))
        fut = Future(tenant)
        # streaming pipelines lease per slab INSIDE the executor — an
        # upfront worker lease on top would double-charge the budget
        # (and deadlock it when budget ~ one slab).  A stream hides in
        # two shapes: a raw stream-backed array, or a pending-stat
        # handle whose GROUP folds a stream source.
        streaming = False
        if arr is not None:
            if getattr(arr, "_stream", None) is not None:
                streaming = True
            else:
                h = getattr(arr, "_spending", None)
                if h is not None and h.group.kind == "stream":
                    streaming = True
        # the batch key (continuous micro-batching): the coalescing
        # identity of an in-memory lazy pipeline — None keeps the job
        # on the standalone path (callables, streams, donating chains,
        # batching off)
        bkey = None
        bt = self._batched            # close() clears it; a submit
        if bt is not None and arr is not None and not streaming:
            bkey = bt.batch_key(arr)  # racing a close must fall to the
            #                           documented closed-server error,
            #                           not an AttributeError
        admitted = False
        with self._cond:
            while self._depth >= self.queue_limit and not self._closing \
                    and self.policy != "reject":
                self._cond.wait(0.05)     # backpressure: block submitter
            if self._closing:
                raise RuntimeError("serve.Server is closed")
            if self._depth < self.queue_limit:
                q = self._queues.get(tenant)
                if q is None:
                    q = self._queues[tenant] = deque()
                    self._ring.append(tenant)
                # streaming pipelines lease per slab inside the
                # executor; in-memory pipelines lease their estimated
                # working set around the dispatch
                q.append((fut, job, None if streaming else est, retries,
                          deadline, bkey,
                          arr if bkey is not None else None))
                self._depth += 1
                self._g_depth.set(self._depth)
                self._g_depth_hw.high_water(self._depth)
                self._cond.notify_all()
                admitted = True
        if not admitted:
            self._reject(tenant,
                         "admission queue is full (%d queued, limit %d, "
                         "policy='reject')" % (self.queue_limit,
                                               self.queue_limit))
        self._counters.add("submitted")
        self._tenant_counters(tenant).add("submitted")
        return fut

    # -- the worker loop -----------------------------------------------

    def _pop(self):
        """Next job, weighted round-robin across tenants (FIFO within
        one); None once the server is draining and every queue is
        empty.  A tenant at the head of the ring is served up to its
        WEIGHT jobs (integer credits, default 1 — bit-for-bit the old
        round-robin) before the rotation advances; credits reset each
        time the tenant returns to the head, and a tenant whose queue
        drains mid-turn forfeits the rest of its credits."""
        with self._cond:
            while True:
                if not self._pod_ok.is_set() and not self._stop.is_set():
                    # peer lost: drain — current jobs finish (or fail
                    # with PeerLostError), nothing new starts until the
                    # reform notification (close() still drains: a
                    # stopping server must terminate, and its jobs fail
                    # fast against the dead pod)
                    self._cond.wait(0.05)
                    continue
                for _ in range(len(self._ring)):
                    t = self._ring[0]
                    q = self._queues.get(t)
                    if not q:
                        self._ring.rotate(-1)
                        continue
                    item = q.popleft()
                    credit = self._credits.pop(
                        t, self._weights.get(t, 1)) - 1
                    if not q:
                        del self._queues[t]
                        self._ring.remove(t)
                    elif credit > 0:
                        # weight left and work left: stay at the head
                        # for the next pop
                        self._credits[t] = credit
                    else:
                        self._ring.rotate(-1)
                    self._depth -= 1
                    self._g_depth.set(self._depth)
                    self._cond.notify_all()
                    return t, item
                if self._stop.is_set():
                    return None
                self._cond.wait(0.05)

    def _run_attempts(self, job, fut, tenant, nretry, deadline):
        """Execute one job with its per-submit retry/deadline policy:
        an expired deadline stops further attempts, and the chaining
        (oldest-first back to the original; pointed error on an
        exhausted budget; the untouched original at budget 0) is the
        shared ``utils.chain_retry_step`` — one policy for serve AND
        the streaming executor's slab retries."""
        from bolt_tpu.utils import chain_retry_step
        attempt = 0
        prev = None
        while True:
            try:
                return job()
            except BaseException as exc:    # noqa: BLE001 — delivered
                expired = deadline is not None and \
                    _clock() - fut.submitted_s > deadline
                allowed = attempt < nretry and not expired \
                    and not self._cancel.is_set()
                poisoned_backend = (
                    isinstance(exc, RuntimeError)
                    and "Unable to initialize backend" in str(exc))
                if allowed and poisoned_backend:
                    # a failed topology exchange leaves this process's
                    # own KV key behind, so a verbatim re-attempt dies
                    # instantly on ALREADY_EXISTS (and starves every
                    # peer waiting on a fresh insert) — purge the stale
                    # keys first so the retry can actually bring the
                    # backend up (multihost.heal_backend_init)
                    from bolt_tpu.parallel import multihost as _mh
                    _mh.heal_backend_init()
                if allowed and (isinstance(exc, PeerLostError)
                                or poisoned_backend):
                    # a pod outage IS retryable (the whole point of
                    # retries= under serving) — but only once the pod
                    # reforms: hold the re-attempt behind the admission
                    # drain instead of burning the budget into a dead
                    # pod.  A latched QUIESCE holds it too — the gate
                    # can trip BEFORE this process's own supervisor
                    # pauses admission (process 0 decides first), and a
                    # re-run in that window would stream into peers
                    # already tearing down for the reform.  Deadline,
                    # cancel AND a closing server cut it off —
                    # close(wait=True) must terminate even when the
                    # reform never comes.
                    while allowed and (
                            not self._pod_ok.wait(0.05)
                            or _podwatch.quiesce_requested()
                            is not None):
                        if _podwatch.quiesce_requested() is not None:
                            self._stop.wait(0.05)
                        if self._cancel.is_set() or self._stop.is_set() \
                                or (deadline is not None
                                    and _clock() - fut.submitted_s
                                    > deadline):
                            allowed = False
                        sup = self.supervisor
                        if sup is not None and sup.failed is not None:
                            # the supervisor gave up (retry budget
                            # exhausted): deliver the loss instead of
                            # holding for a reform that never comes
                            allowed = False
                if allowed:
                    self._counters.add("retried")
                    self._tenant_counters(tenant).add("retried")
                    _obs.event("serve.retry", tenant=tenant,
                               attempt=attempt + 1,
                               error=type(exc).__name__)
                prev = chain_retry_step(exc, prev, attempt, allowed,
                                        "serve job", "submit retries=")
                attempt += 1

    def _worker(self):
        while True:
            got = self._pop()
            if got is None:
                return
            tenant, item = got
            extras = ()
            t_gather = _clock()
            if item[5] is not None and self.batching is not None:
                extras = self._gather_batch(item[5], item[2])
            if extras:
                self._run_batch([(tenant, item)] + extras, t_gather)
            else:
                self._run_one(tenant, item)

    def _run_one(self, tenant, item):
        """Execute one job standalone (the pre-batching worker body)."""
        fut, job, est, nretry, deadline = item[:5]
        fut.started_s = _clock()
        wait = fut.started_s - fut.submitted_s
        self._counters.add("queue_wait_seconds", wait)
        self._tenant_counters(tenant).add("queue_wait_seconds", wait)
        self._h_wait.observe(wait)
        sp = _obs.begin("serve.run", tenant=tenant,
                        queued_s=round(wait, 6))
        lease = self.arbiter.lease(tenant) if est else None
        try:
            with _engine.tenant(tenant):
                if deadline is not None and wait > deadline:
                    # expired while queued: fail WITHOUT running —
                    # the tenant's latency budget is already blown
                    self._counters.add("expired")
                    self._tenant_counters(tenant).add("expired")
                    raise DeadlineError(
                        "deadline %.3fs exceeded before the job "
                        "started (queued %.3fs)" % (deadline, wait))
                # stop on CANCEL only: a close(wait=True) drain must
                # let queued leased jobs wait out the arbiter and run
                if lease is not None and not lease.acquire(
                        est, stop=self._cancel):
                    raise RuntimeError(
                        "server cancelled before the job's working "
                        "set (%d bytes) was granted" % est)
                out = self._run_attempts(job, fut, tenant, nretry,
                                         deadline)
            fut._finish(result=out)
            key = "completed"
        except BaseException as exc:    # noqa: BLE001 — delivered
            fut._finish(exc=exc)        # through Future.result()
            key = "failed"
        finally:
            if lease is not None:
                lease.close()           # leases are ALWAYS returned
            _obs.end(sp)
        run_s = fut.finished_s - fut.started_s
        self._counters.update(**{key: 1, "run_seconds": run_s})
        self._tenant_counters(tenant).update(
            **{key: 1, "run_seconds": run_s})

    # -- continuous micro-batching (bolt_tpu/tpu/batched.py) -----------

    def _gather_batch(self, bkey, head_est):
        """Pull every queued job sharing ``bkey`` — ACROSS tenants,
        FIFO within each — up to the policy's ``max_batch``, lingering
        up to ``linger`` seconds to fill the bucket once at least one
        partner was found.  A gather that finds nothing returns
        immediately (a lone request never waits).  Width is ALSO capped
        by the arbiter budget: the coalesced dispatch's footprint is
        the members' working sets PLUS the bucket-width stacked input
        copy (~2x the sum), and assembling a batch the budget would
        have serialised per-request must not bypass that arbitration.
        Gathered jobs bypass the weighted-rotation credits: coalescing
        is work-conserving — it only accelerates jobs that would
        otherwise each pay their own dispatch, and the batch serves
        multiple tenants at once."""
        pol = self.batching
        limit = pol.max_batch - 1       # the popped head is lane 0
        est = int(head_est or 0)
        if est:
            # equal keys ⇒ equal geometry ⇒ equal per-request estimate:
            # the coalesced lease is (W + bucket_width(W)) x est — the
            # members plus the PADDED stacked copy — so pick the widest
            # W the budget covers (a batch the budget would have
            # serialised per-request must not assemble and then hit the
            # arbiter's runs-alone escape)
            from bolt_tpu.tpu.batched import bucket_width
            w = 1
            for cand in range(pol.max_batch, 1, -1):
                if (cand + bucket_width(cand, pol.buckets)) * est \
                        <= self.arbiter.budget:
                    w = cand
                    break
            limit = min(limit, w - 1)
        out = []
        t0 = None
        while limit > 0:
            with self._cond:
                for t in list(self._queues):
                    if len(out) >= limit:
                        break
                    q = self._queues[t]
                    keep = deque()
                    # stop as soon as the batch fills: examined
                    # non-matching jobs go back to the FRONT in order,
                    # the unexamined tail is never touched — the scan
                    # is O(taken + skipped), not O(queue depth)
                    while q and len(out) < limit:
                        it = q.popleft()
                        if it[5] == bkey:
                            out.append((t, it))
                            self._depth -= 1
                        else:
                            keep.append(it)
                    if keep:
                        q.extendleft(reversed(keep))
                    elif not q:
                        del self._queues[t]
                        self._ring.remove(t)
                        self._credits.pop(t, None)
                if out:
                    self._g_depth.set(self._depth)
                    self._cond.notify_all()   # free blocked submitters
                full = len(out) >= limit
                stopping = (self._closing or self._stop.is_set()
                            or self._cancel.is_set())
            if full or stopping or pol.linger <= 0 or not out:
                return out
            now = _clock()
            if t0 is None:
                t0 = now
            rem = pol.linger - (now - t0)
            if rem <= 0:
                return out
            with self._cond:
                self._cond.wait(rem)    # a submit notifies the cond
        return out                      # budget-capped width < 2: the
        #                                 head runs standalone under its
        #                                 own per-request arbitration

    def _run_batch(self, items, t_gather):
        """One coalesced dispatch serving ``len(items)`` same-key
        requests: per-request wait/deadline/lease accounting first
        (attribution preserved — every future keeps its own wait, run
        and assembly seconds, every tenant its own counters), then ONE
        claimed batched program (``batched.claim``/``dispatch``), then
        per-request adoption through the normal retry machinery.  Any
        claim/dispatch failure degrades every live request to its
        standalone dispatch — batching is an optimisation, never a new
        failure mode.  Note: the coalesced dispatch itself is
        CROSS-TENANT and runs outside any ``engine.tenant`` scope — its
        engine counters (dispatches, transfer bytes) land in the global
        tally only; per-tenant SERVE counters are unaffected."""
        width = len(items)
        bsp = _obs.begin("serve.batch", width=width)
        t_start = _clock()
        live = []
        lease = None
        # per-request attribution is preserved, but the COUNTER totals
        # apply once per (batch, tenant): every locked registry update
        # measured as real per-request cost at small-request QPS, and
        # totals aggregate identically
        agg = {}

        def _acc(tenant, **deltas):
            d = agg.setdefault(tenant, {})
            for k, v in deltas.items():
                d[k] = d.get(k, 0 if isinstance(v, int) else 0.0) + v
        try:
            for t, it in items:
                fut, _, est, _, dl = it[:5]
                fut.started_s = t_start
                wait = t_start - fut.submitted_s
                _acc(t, queue_wait_seconds=wait)
                self._h_wait.observe(wait)
                if dl is not None and wait > dl:
                    _acc(t, expired=1)
                    self._finish_batched(t, fut, None, DeadlineError(
                        "deadline %.3fs exceeded before the job "
                        "started (queued %.3fs)" % (dl, wait)), _acc)
                    continue
                live.append((t, it))
            # ONE summed lease covers the whole coalesced dispatch —
            # the members' working sets PLUS the bucket-width stacked
            # input copy the batched program materialises (pad lanes
            # included); accounted under the head tenant — per-request
            # arbiter round-trips measured as a real cost at
            # small-request QPS
            total_est = sum(it[2] or 0 for _, it in live)
            if len(live) > 1 and total_est:
                total_est += self._batched.bucket_width(
                    len(live), self.batching.buckets) * max(
                    it[2] or 0 for _, it in live)
            if live and total_est:
                lease = self.arbiter.lease(live[0][0])
                if not lease.acquire(total_est, stop=self._cancel):
                    lease.close()
                    lease = None
                    for t, it in live:
                        self._finish_batched(t, it[0], None, RuntimeError(
                            "server cancelled before the batch's "
                            "working set (%d bytes) was granted"
                            % total_est), _acc)
                    live = []
            batch = None
            if len(live) > 1:
                try:
                    batch = self._batched.claim(
                        [it[6] for _, it in live], live[0][1][5])
                    if batch is not None:
                        # assembly = pop -> dispatch begin: the gather
                        # scan, the linger micro-wait and the claim —
                        # the documented gather+linger+stack window,
                        # NOT the device execution (run_seconds covers
                        # that)
                        asm = _clock() - t_gather
                        self._batched.dispatch(batch,
                                               self.batching.buckets)
                        # realised coalescing only: a degraded gather
                        # (failed claim/dispatch, expired members) must
                        # not count as a coalesced dispatch, and only
                        # requests the dispatch actually SERVED carry
                        # the batch attribution (claim may drop raced
                        # members — they dispatch standalone below and
                        # keep the documented None)
                        served = {id(a) for a in batch.arrs}
                        self._h_occ.observe(len(served))
                        for _, it in live:
                            if id(it[6]) in served:
                                it[0].batch_width = len(served)
                                it[0].assembly_seconds = asm
                except BaseException:   # noqa: BLE001 — degrade, the
                    if batch is not None:   # per-request adoption below
                        self._batched.unclaim(batch)   # re-dispatches
                    #                                    standalone
            # adoption (or standalone execution when the claim/dispatch
            # degraded): the normal per-request retry/exception path
            for t, it in live:
                fut, job, _, nretry, dl = it[:5]
                sp = _obs.begin("serve.run", tenant=t, batched=width)
                try:
                    try:
                        with _engine.tenant(t):
                            out = self._run_attempts(job, fut, t,
                                                     nretry, dl)
                        self._finish_batched(t, fut, out, None, _acc)
                    except BaseException as exc:    # noqa: BLE001
                        self._finish_batched(t, fut, None, exc, _acc)
                finally:
                    _obs.end(sp)
        finally:
            if lease is not None:
                lease.close()           # leases are ALWAYS returned
            for t, deltas in agg.items():
                self._counters.update(**deltas)
                self._tenant_counters(t).update(**deltas)
            _obs.end(bsp)

    def _finish_batched(self, tenant, fut, result, exc, acc):
        """Deliver one batched request's outcome: identical future
        delivery to the standalone path's, counters accumulated into
        the batch's per-tenant aggregate instead of N locked registry
        updates."""
        if exc is None:
            fut._finish(result=result)
            key = "completed"
        else:
            fut._finish(exc=exc)
            key = "failed"
        acc(tenant, **{key: 1,
                       "run_seconds": fut.finished_s - fut.started_s})

    # -- lifecycle / introspection -------------------------------------

    def queue_depth(self):
        with self._cond:
            return self._depth

    def stats(self):
        """One consistent-ish status dict: global serve counters, queue
        depth, arbiter state, a ``"batching"`` summary, and a
        per-tenant breakdown (serve counters + LIVE queue depth + that
        tenant's scoped ENGINE counters — transfer bytes, dispatches,
        compiles).

        Documented DEGRADED shapes (like ``profile.memory_stats``):
        ``"batching"`` is ``{}`` — never an AttributeError — when the
        server runs without a batching policy, and its ``"occupancy"``
        sub-dict is ``{}`` until the first coalesced dispatch;
        ``"tenants"`` is ``{}`` before any submit, and a tenant that
        only ever queued (never ran) still appears with zeroed run
        counters and its live ``queue_depth``."""
        reg = _metrics.registry()
        with self._cond:
            depths = {t: len(q) for t, q in self._queues.items()}
        out = {"queue_depth": self.queue_depth(),
               "queue_depth_high_water": self._g_depth_hw.value,
               "arbiter": {"budget_bytes": self.arbiter.budget,
                           "in_use_bytes": self.arbiter.in_use(),
                           "in_use_high_water": reg.gauge(
                               "serve.arbiter_in_use_high_water").value,
                           "waits": reg.counter(
                               "serve.arbiter_waits").value},
               "pod": {"paused": self.pod_paused(),
                       "lost_peer": self._pod_lost,
                       "reason": self._pod_reason,
                       "supervised": self.supervisor is not None,
                       "quarantine": (self.supervisor.quarantined()
                                      if self.supervisor is not None
                                      else []),
                       "budget_share": (
                           self.arbiter.budget / self._budget0
                           if self._budget0 else 1.0)},
               "batching": self._batching_stats(),
               "totals": self._counters.snapshot(),
               "tenants": {}}
        for name in reg.names():
            if name.startswith("serve/"):
                t = name.split("/", 1)[1]
                entry = dict(reg.get(name).snapshot())
                eng = _engine.tenant_counters(t)
                entry["transfer_bytes"] = eng["transfer_bytes"]
                entry["dispatches"] = eng["dispatches"]
                entry["aot_compiles"] = eng["aot_compiles"]
                entry["queue_depth"] = depths.pop(t, 0)
                out["tenants"][t] = entry
        for t, d in depths.items():
            # queued-but-never-counted tenants (a submit can sit queued
            # before its counter group exists under races): still show
            # their live depth
            out["tenants"].setdefault(t, {})["queue_depth"] = d
        return out

    def _batching_stats(self):
        """The ``stats()["batching"]`` block: ``{}`` when batching is
        off; else the policy knobs plus the realised coalescing — the
        engine's ``batched_dispatches``/``batched_requests`` tallies
        and a batch-occupancy summary derived from the
        ``serve.batch_occupancy.hist`` registry histogram (``{}`` until
        the first coalesced dispatch).  Like the engine counters these
        are PROCESS-global tallies — a second batching server in one
        process inherits its predecessor's totals."""
        pol = self.batching
        if pol is None:
            return {}
        ec = _engine.counters()
        occ = {}
        snap = self._h_occ.snapshot()
        if snap["count"]:
            occ = {"dispatches": snap["count"],
                   "mean": round(snap["sum"] / snap["count"], 2),
                   "buckets": [(b, c) for b, c in self._h_occ.buckets()
                               if c]}
        return {"max_batch": pol.max_batch,
                "linger": pol.linger,
                "buckets": pol.buckets,
                "batched_dispatches": ec["batched_dispatches"],
                "batched_requests": ec["batched_requests"],
                "occupancy": occ}

    def close(self, wait=True):
        """Stop the server.  ``wait=True`` drains queued jobs first and
        joins the workers; ``wait=False`` fails every queued job with a
        RuntimeError and returns once workers exit their current job."""
        with self._cond:
            self._closing = True
            if not wait:
                self._cancel.set()
                while self._queues:
                    _, q = self._queues.popitem()
                    for fut, *_ in q:
                        fut._finish(exc=RuntimeError(
                            "serve.Server closed before this job ran"))
                self._ring.clear()
                self._depth = 0
                self._g_depth.set(0)
            self._stop.set()
            self._cond.notify_all()
        for th in self._threads:
            th.join()
        for h in self._pw_handles:
            _podwatch.remove_callback(h)   # a closed server must not
            #                                pause/resume from beyond
        if self.supervisor is not None:
            if self._own_supervisor:
                self.supervisor.close()
            else:                          # adopted: detach our hooks,
                self.supervisor.on_pause = None    # leave it running
                self.supervisor.on_resume = None
        if self.warm_dir is not None:
            # the warm tally covers THIS server's lifetime; the cache
            # stays attached (artifacts keep serving), only the
            # persistent_warm_hits arming ends
            _engine.disarm_warm_start()
        if self._batched is not None:
            self._batched.disarm()     # closes the lazy-reduce door
            self._batched = None       # (idempotent across re-close)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(wait=exc == (None, None, None))


# ---------------------------------------------------------------------
# the module-level (default-server) doors
# ---------------------------------------------------------------------

_ACTIVE = None
_ACTIVE_LOCK = _lockdep.lock("serve.active")


def start(workers=None, budget_bytes=None, queue_limit=None,
          policy="queue", weights=None, start_warm=None,
          supervise=False, batching=None):
    """Start and install THE process server (at most one may be active
    — the arbiter is only a global budget if there is one of it).
    Returns the :class:`Server`."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        if _ACTIVE is not None:
            raise RuntimeError(
                "a serve.Server is already active; stop() it first "
                "(the device-memory budget must have one owner)")
        _ACTIVE = Server(workers=workers, budget_bytes=budget_bytes,
                         queue_limit=queue_limit, policy=policy,
                         weights=weights, start_warm=start_warm,
                         supervise=supervise, batching=batching)
        return _ACTIVE


def stop(wait=True):
    """Stop and uninstall the active server (no-op when none is)."""
    global _ACTIVE
    with _ACTIVE_LOCK:
        sv, _ACTIVE = _ACTIVE, None
    if sv is not None:
        sv.close(wait=wait)


def active():
    """The installed :class:`Server`, or None."""
    return _ACTIVE


def device_arbiter():
    """The active server's :class:`DeviceArbiter` (None when no server
    is running) — the door ``bolt_tpu.stream`` checks per run."""
    sv = _ACTIVE
    return sv.arbiter if sv is not None else None


def submit(pipeline, tenant="default", retries=0, deadline=None):
    """Submit through the active server, lazily starting the default
    one (env-tuned) when none is running."""
    global _ACTIVE
    sv = _ACTIVE
    if sv is None:
        with _ACTIVE_LOCK:
            if _ACTIVE is None:
                _ACTIVE = Server()
            sv = _ACTIVE
    return sv.submit(pipeline, tenant=tenant, retries=retries,
                     deadline=deadline)


@contextlib.contextmanager
def serving(workers=None, budget_bytes=None, queue_limit=None,
            policy="queue", weights=None, start_warm=None,
            supervise=False, batching=None):
    """Scoped server lifetime::

        with bolt_tpu.serve.serving(workers=4) as sv:
            fut = sv.submit(pipeline, tenant="a")
            out = fut.result()

    Drains and stops on clean exit; cancels queued jobs when the body
    raised.  ``weights={tenant: n}`` generalises the round-robin to a
    weighted fair share (integer credits per rotation; default 1 keeps
    the plain round-robin); ``start_warm=dir`` preloads the engine's
    persistent-cache artifacts so a fresh process serves its first
    request without a compile storm; ``supervise=True`` attaches the
    pod recovery supervisor (``parallel.supervisor``) — peer death and
    rejoin reform the pod automatically, held ``retries=`` re-attempts
    resume from the checkpoint, and the arbiter budget tracks the
    surviving capacity share; ``batching=True`` (or a
    :class:`BatchPolicy` / dict of its kwargs) arms continuous
    micro-batching — queued same-key small requests coalesce into ONE
    stacked dispatch, bit-identical to standalone, at bucketed
    widths."""
    sv = start(workers=workers, budget_bytes=budget_bytes,
               queue_limit=queue_limit, policy=policy, weights=weights,
               start_warm=start_warm, supervise=supervise,
               batching=batching)
    try:
        yield sv
    except BaseException:
        stop(wait=False)
        raise
    else:
        stop(wait=True)
