"""bolt_tpu — a TPU-native unified n-dimensional array.

One API over two backends (reference: ``bolt/__init__.py`` re-exports —
symbol-level citation, SURVEY.md §0):

* ``mode='local'`` — NumPy, the semantic oracle;
* ``mode='tpu'``  — a sharded ``jax.Array`` over a device mesh, with
  ``map``/``reduce``/statistics lowering to compiled SPMD programs and
  ``swap`` lowering to an ``all_to_all`` resharding.

>>> import bolt_tpu as bolt
>>> b = bolt.ones((8, 100, 50), context=mesh)   # keys: (8,) on the mesh
>>> b.map(lambda x: x + 1).sum().toarray()
"""

__version__ = "0.5.0"

from bolt_tpu.factory import (array, concatenate, fromcallback, fromiter,
                              full, ones, rand, randn, zeros)
from bolt_tpu.base import BoltArray, HostFallbackWarning
from bolt_tpu.local.array import BoltArrayLocal
from bolt_tpu.tpu.array import BoltArrayTPU
from bolt_tpu.tpu.multistat import compute
from bolt_tpu._precision import precision
from bolt_tpu.utils import allclose

__all__ = ["array", "ones", "zeros", "full", "rand", "randn",
           "fromcallback", "fromiter", "concatenate", "compute",
           "allclose", "precision", "BoltArray", "BoltArrayLocal",
           "BoltArrayTPU", "HostFallbackWarning", "__version__"]

_SUBMODULES = ("analysis", "checkpoint", "engine", "obs", "profile",
               "parallel", "ops", "serve", "statcounter", "stream",
               "utils")


def __getattr__(name):
    # lazy submodule access (bolt.checkpoint, bolt.profile, ...) without
    # importing their heavier dependencies at package import
    if name in _SUBMODULES:
        import importlib
        return importlib.import_module("bolt_tpu." + name)
    raise AttributeError("module 'bolt_tpu' has no attribute %r" % (name,))
