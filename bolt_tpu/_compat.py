"""Version-compatibility shims for the jax API surface this framework uses.

The framework targets the modern jax surface (``jax.shard_map``,
``jax.lax.axis_size``, Explicit/Auto mesh axis types); older runtimes
(0.4.x) spell the same machinery differently.  Every module that touches a
version-sensitive symbol goes through this file, so the compatibility
policy lives in ONE place instead of scattered ``hasattr`` probes:

* :func:`shard_map` — ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` spelling; the new ``check_vma`` flag maps
  onto the old ``check_rep``.
* :func:`axis_size` — ``jax.lax.axis_size`` when present, else
  ``psum(1, name)`` (static under shard_map: mesh extents are trace-time
  constants, so permutation schedules can still be built from it).
* :func:`make_mesh` / :func:`ensure_auto_mesh` — Auto axis-typing where
  the runtime has typed mesh axes; a plain mesh (implicitly Auto — typed
  axes do not exist) otherwise.
* the **survivable distributed runtime** block
  (:func:`distributed_initialize` / :func:`distributed_teardown` /
  :func:`distributed_client` / :func:`clear_backends`) — the pod
  fault-tolerance layer's foundation (ISSUE 11).  Stock
  ``jax.distributed.initialize`` builds its coordination-service client
  with the DEFAULT missed-heartbeat callback, which ``LOG(QFATAL)``'s
  the process the moment a peer dies ("Terminating process because the
  JAX distributed service detected fatal errors") — the survivors of a
  ``kill -9`` are then executed by their own runtime before any
  recovery code can run.  The survivable bring-up passes a NON-FATAL
  callback (routed to ``bolt_tpu.parallel.podwatch``) and
  ``shutdown_on_destruction=False``, so peer death becomes an event the
  liveness layer handles instead of a process abort.  All of it is
  version-probed here: runtimes without the ``xla_extension`` hooks
  fall back to the stock (fatal) ``jax.distributed.initialize``.
"""

import numpy as np

import jax

HAS_AXIS_TYPES = hasattr(jax.sharding, "AxisType")

# The version watershed the suites key xfails on: runtimes predating the
# jax.shard_map promotion (0.4.x/0.5.x) also carry the GSPMD and
# jnp.ufunc behavior gaps documented per-test.  True = OLD runtime.
OLD_JAX = not hasattr(jax, "shard_map")


def make_mesh(shape, axis_names):
    """An n-d mesh with Auto-typed axes on runtimes that type mesh axes
    (this framework drives sharding through constraints and lets GSPMD
    propagate, which requires Auto); on older runtimes every mesh is
    implicitly Auto already."""
    if HAS_AXIS_TYPES:
        auto = (jax.sharding.AxisType.Auto,) * len(tuple(axis_names))
        return jax.make_mesh(tuple(shape), tuple(axis_names),
                             axis_types=auto)
    return jax.make_mesh(tuple(shape), tuple(axis_names))


def ensure_auto_mesh(mesh):
    """An Auto-axis-typed twin of ``mesh`` (identity where the runtime has
    no axis types, or where the mesh is Auto-typed already)."""
    if not HAS_AXIS_TYPES:
        return mesh
    types = getattr(mesh, "axis_types", None)
    if types is None or all(t == jax.sharding.AxisType.Auto for t in types):
        return mesh
    return jax.sharding.Mesh(mesh.devices, mesh.axis_names)


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with the cross-version replication-check flag
    (``check_vma`` new / ``check_rep`` old).  Defaults to True — the
    same default as both jax spellings — so call sites migrated from a
    bare ``jax.shard_map`` keep the replication checker."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name):
    """Extent of a mapped mesh axis inside a shard_map body.  The psum
    fallback is a trace-time constant (mesh extents are static), so
    callers may use it to build Python-level schedules (ppermute pair
    lists) on either runtime."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return int(np.asarray(jax.lax.psum(1, axis_name)))


# ---------------------------------------------------------------------
# the survivable distributed runtime (bolt_tpu.parallel.multihost /
# bolt_tpu.parallel.podwatch — the pod fault-tolerance foundation)
# ---------------------------------------------------------------------

def _distributed_state():
    """jax's distributed-runtime singleton (version-probed)."""
    try:
        from jax._src import distributed
        return distributed.global_state
    except Exception:
        return None


def distributed_client():
    """The live coordination-service client (the ``jax.distributed``
    KV store the podwatch heartbeat transport can ride), or ``None``
    when the distributed runtime is not up."""
    st = _distributed_state()
    return getattr(st, "client", None) if st is not None else None


def can_survive_peer_loss():
    """Does this runtime expose the client options the survivable
    bring-up needs (custom missed-heartbeat callback +
    shutdown_on_destruction)?"""
    try:
        from jax.lib import xla_extension as xe
        return (hasattr(xe, "get_distributed_runtime_client")
                and hasattr(xe, "get_distributed_runtime_service"))
    except Exception:
        return False


# heartbeat tolerance of the SURVIVABLE bring-up: wide enough that the
# coordination service never declares a peer dead on its own (the
# liveness layer — bolt_tpu.parallel.podwatch — owns detection, with
# second-scale deadlines).  One would rather hand the client a benign
# Python missed_heartbeat_callback, but this jaxlib's nanobind bridge
# for it is BROKEN (the absl::Status argument has no registered caster:
# invoking any Python callback aborts the survivor with std::bad_cast —
# strictly worse than the stock QFATAL), so the fatal path is instead
# made unreachable by tolerance.
_SURVIVABLE_HB_INTERVAL = 10          # seconds between runtime heartbeats
_SURVIVABLE_HB_MISSING = 100000       # ~never: podwatch detects instead


def distributed_initialize(coordinator_address, num_processes,
                           process_id, on_fatal=None, init_timeout=120):
    """Bring up the distributed runtime with a SURVIVABLE client.

    Like ``jax.distributed.initialize`` — process 0 additionally hosts
    the coordination service — but peer death can no longer execute the
    survivors: the stock client's missed-heartbeat/error-poll handler
    ``LOG(QFATAL)``'s the process the moment the service declares a
    peer unhealthy, so the service/client heartbeat tolerance is set
    wide enough that it NEVER fires (detection belongs to
    ``bolt_tpu.parallel.podwatch``, with second-scale deadlines), and
    ``shutdown_on_destruction=False`` keeps a survivor's client
    teardown off the doomed shutdown barrier.  ``on_fatal`` is
    accepted for API symmetry but NOT installed — this jaxlib's
    Python-callback bridge aborts on invocation (see the comment
    above).  Falls back to the stock fatal initialize on runtimes
    without the hooks.  Returns True when the survivable path was
    taken."""
    del on_fatal                      # see the bridge note above
    st = _distributed_state()
    if st is None or not can_survive_peer_loss():
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        return False
    from jax.lib import xla_extension as xe
    if process_id == 0 and getattr(st, "service", None) is None:
        st.service = xe.get_distributed_runtime_service(
            "[::]:" + str(coordinator_address).rsplit(":", 1)[1],
            num_processes,
            heartbeat_interval=_SURVIVABLE_HB_INTERVAL,
            max_missing_heartbeats=_SURVIVABLE_HB_MISSING)
    if getattr(st, "client", None) is not None:
        raise RuntimeError("distributed client already initialized")
    client = xe.get_distributed_runtime_client(
        coordinator_address, process_id, init_timeout=init_timeout,
        heartbeat_interval=_SURVIVABLE_HB_INTERVAL,
        max_missing_heartbeats=_SURVIVABLE_HB_MISSING,
        shutdown_on_destruction=False, use_compression=True)
    client.connect()
    st.client = client
    st.process_id = process_id
    st.num_processes = num_processes
    st.coordinator_address = coordinator_address
    return True


def distributed_teardown(graceful=True):
    """Release the distributed runtime's client/service WITHOUT the
    stock shutdown's fatal error paths: a clean pod may take the
    shutdown barrier (``graceful=True``); a pod that lost a peer must
    NOT (the barrier would fail against the dead task and the stock
    path aborts the process) — its handles are dropped instead.

    ORDER MATTERS on the non-graceful path: the coordination client's
    error-poll thread ``LOG(QFATAL)``'s the process if the service
    vanishes under it, and the gloo-backed CPU backend holds a
    reference to the client — so the backends must be released FIRST
    (``clear_backends``, which the reform path runs before this), the
    client reference dropped (its destructor cancels and joins the
    poll thread), and only then may a coordinator shut its service
    down.  Survivors on OTHER processes poll this service too: it is
    shut down on a delay-free best-effort basis only at graceful exit;
    a reforming coordinator leaves it running (tolerant heartbeats
    keep it silent) so a peer mid-reform never observes the
    "coordination service unavailable" fatal."""
    st = _distributed_state()
    if st is None:
        return
    client = getattr(st, "client", None)
    if client is not None:
        if graceful:
            try:
                client.shutdown()
            except Exception:
                pass
        st.client = None
        del client                    # destructor joins the poll thread
    if getattr(st, "service", None) is not None:
        if graceful:
            try:
                st.service.shutdown()
            except Exception:
                pass
        else:
            # leave the old service RUNNING: peers' old clients may
            # still be polling it mid-reform, and killing it converts
            # their tolerant silence into the fatal UNAVAILABLE poll.
            # It idles on the old port for the rest of the process
            # (reforms are rare; the new service binds a fresh port).
            _ORPHANED_SERVICES.append(st.service)
        st.service = None
    st.process_id = 0
    st.num_processes = None
    st.coordinator_address = None


# services a non-graceful teardown abandons (kept referenced so their
# destructors never run mid-flight; see distributed_teardown)
_ORPHANED_SERVICES = []


def clear_backends():
    """Forget every live XLA backend (and the jit caches pinning them)
    so the next backend query rebuilds against the CURRENT distributed
    topology — the reform step between ``distributed_teardown`` and a
    re-``distributed_initialize`` on a shrunk pod.  The topology query
    helpers (``process_count``/``process_index``/device counts) are
    ``lru_cache``'d ON TOP of the backend table and must be dropped
    with it, or a reformed pod keeps answering with the dead
    topology."""
    from jax._src import xla_bridge as xb
    if not hasattr(xb, "_clear_backends"):
        # refusing beats pretending: a reform that cannot drop the old
        # backends would hand the caller a "recovered" runtime whose
        # gloo contexts still point at the dead topology
        raise RuntimeError(
            "this jax version exposes no backend-reset hook "
            "(jax._src.xla_bridge._clear_backends); multihost.reform "
            "cannot rebuild the runtime in-process here — restart the "
            "surviving processes over the same checkpoint dir instead")
    xb._clear_backends()
    for name in ("process_count", "process_index", "device_count",
                 "local_device_count", "process_indices"):
        fn = getattr(xb, name, None)
        if fn is not None and hasattr(fn, "cache_clear"):
            fn.cache_clear()
        jfn = getattr(jax, name, None)
        if jfn is not None and jfn is not fn \
                and hasattr(jfn, "cache_clear"):
            jfn.cache_clear()
    jax.clear_caches()
