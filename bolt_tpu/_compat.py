"""Version-compatibility shims for the jax API surface this framework uses.

The framework targets the modern jax surface (``jax.shard_map``,
``jax.lax.axis_size``, Explicit/Auto mesh axis types); older runtimes
(0.4.x) spell the same machinery differently.  Every module that touches a
version-sensitive symbol goes through this file, so the compatibility
policy lives in ONE place instead of scattered ``hasattr`` probes:

* :func:`shard_map` — ``jax.shard_map`` when present, else the
  ``jax.experimental.shard_map`` spelling; the new ``check_vma`` flag maps
  onto the old ``check_rep``.
* :func:`axis_size` — ``jax.lax.axis_size`` when present, else
  ``psum(1, name)`` (static under shard_map: mesh extents are trace-time
  constants, so permutation schedules can still be built from it).
* :func:`make_mesh` / :func:`ensure_auto_mesh` — Auto axis-typing where
  the runtime has typed mesh axes; a plain mesh (implicitly Auto — typed
  axes do not exist) otherwise.
"""

import numpy as np

import jax

HAS_AXIS_TYPES = hasattr(jax.sharding, "AxisType")

# The version watershed the suites key xfails on: runtimes predating the
# jax.shard_map promotion (0.4.x/0.5.x) also carry the GSPMD and
# jnp.ufunc behavior gaps documented per-test.  True = OLD runtime.
OLD_JAX = not hasattr(jax, "shard_map")


def make_mesh(shape, axis_names):
    """An n-d mesh with Auto-typed axes on runtimes that type mesh axes
    (this framework drives sharding through constraints and lets GSPMD
    propagate, which requires Auto); on older runtimes every mesh is
    implicitly Auto already."""
    if HAS_AXIS_TYPES:
        auto = (jax.sharding.AxisType.Auto,) * len(tuple(axis_names))
        return jax.make_mesh(tuple(shape), tuple(axis_names),
                             axis_types=auto)
    return jax.make_mesh(tuple(shape), tuple(axis_names))


def ensure_auto_mesh(mesh):
    """An Auto-axis-typed twin of ``mesh`` (identity where the runtime has
    no axis types, or where the mesh is Auto-typed already)."""
    if not HAS_AXIS_TYPES:
        return mesh
    types = getattr(mesh, "axis_types", None)
    if types is None or all(t == jax.sharding.AxisType.Auto for t in types):
        return mesh
    return jax.sharding.Mesh(mesh.devices, mesh.axis_names)


def shard_map(f, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` with the cross-version replication-check flag
    (``check_vma`` new / ``check_rep`` old).  Defaults to True — the
    same default as both jax spellings — so call sites migrated from a
    bare ``jax.shard_map`` keep the replication checker."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def axis_size(axis_name):
    """Extent of a mapped mesh axis inside a shard_map body.  The psum
    fallback is a trace-time constant (mesh extents are static), so
    callers may use it to build Python-level schedules (ppermute pair
    lists) on either runtime."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return int(np.asarray(jax.lax.psum(1, axis_name)))
