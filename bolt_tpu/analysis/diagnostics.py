"""Structured diagnostics for the abstract pipeline checker.

Every finding the checker (:mod:`bolt_tpu.analysis.check`) emits is a
:class:`Diagnostic` with a stable ``BLT0xx`` code, a severity, the index
of the pipeline stage it anchors to, and a fix hint — the compiler-style
contract the repo linter (:mod:`bolt_tpu.analysis.astlint`) mirrors with
its ``BLT1xx`` range.  The full code table lives in ``docs/API.md``.

Severities:

* ``error``   — the pipeline WILL fail at compile or dispatch time
  (``analysis.strict()`` refuses to dispatch on these);
* ``warning`` — the pipeline runs but something is probably not what the
  author intended (silent dtype widening, idle devices);
* ``info``    — a behavior worth knowing about before dispatch (an
  upcoming buffer donation, a dynamic shape pending a count sync).
"""

# code -> (default severity, short title).  The checker's BLT0xx range;
# the AST linter owns BLT1xx (see astlint.RULES).
CODES = {
    "BLT001": ("error", "pipeline stage fails abstract tracing"),
    "BLT002": ("error", "recorded result aval contradicts the chain"),
    "BLT003": ("warning", "stage widens the pipeline dtype"),
    "BLT004": ("warning", "key axes do not divide the mesh"),
    "BLT005": ("error", "read path hits a donated buffer"),
    "BLT006": ("info", "terminal will donate the chain base"),
    "BLT007": ("error", "filter predicate is not a scalar per record"),
    "BLT008": ("info", "result shape is dynamic until a count sync"),
    "BLT009": ("info", "fusable terminal set: one pass serves N stats"),
    "BLT010": ("error", "pipeline exceeds the serving admission budget"),
    "BLT011": ("warning",
               "one-shot iterator source under resumable(): resume "
               "impossible"),
    "BLT012": ("error",
               "streamed key axis does not divide the multi-process "
               "topology"),
    "BLT013": ("warning",
               "multi-process stream has no recovery path: peer loss "
               "discards all partials"),
    "BLT014": ("warning",
               "supervised pod stream's source cannot serve a rejoined "
               "process: re-expansion impossible for this run"),
    "BLT015": ("info",
               "terminal is batch-eligible: a batching server coalesces "
               "same-key requests into one dispatch"),
    "BLT016": ("info",
               "codec-encoded ingest: streamed slabs ship compressed "
               "and decode on device"),
    "BLT017": ("info",
               "streamed shuffle plan: the swap re-buckets slab by "
               "slab, resident in HBM or spilled past the budget"),
}

SEVERITIES = ("error", "warning", "info")


class Diagnostic:
    """One checker finding: ``code`` (``BLT0xx``), ``severity``,
    ``stage`` (pipeline stage index; ``-1`` for array-level findings),
    ``message`` and a ``hint`` suggesting the fix."""

    __slots__ = ("code", "severity", "stage", "message", "hint")

    def __init__(self, code, stage, message, hint="", severity=None):
        if code not in CODES:
            raise ValueError("unknown diagnostic code %r" % (code,))
        self.code = code
        self.severity = severity or CODES[code][0]
        if self.severity not in SEVERITIES:
            raise ValueError("unknown severity %r" % (self.severity,))
        self.stage = int(stage)
        self.message = message
        self.hint = hint

    def __repr__(self):
        return "Diagnostic(%s %s stage=%d: %s)" % (
            self.code, self.severity, self.stage, self.message)

    def render(self):
        where = "stage %d" % self.stage if self.stage >= 0 else "array"
        out = "%s %-7s %s: %s" % (self.code, self.severity, where,
                                  self.message)
        if self.hint:
            out += "\n        hint: %s" % self.hint
        return out


class Stage:
    """One abstract-interpretation step of a pipeline: the operation
    label, the inferred full (keys+values) ``shape``/``dtype``, the key
    ``split``, and the derived ``PartitionSpec`` (``None`` when sharding
    could not be derived).  ``dynamic`` marks a leading key extent that
    is only an upper bound (a filter whose survivor count has not been
    synced); ``note`` carries free-form context for :func:`explain`."""

    __slots__ = ("index", "op", "shape", "dtype", "split", "spec",
                 "dynamic", "note")

    def __init__(self, index, op, shape, dtype, split, spec=None,
                 dynamic=False, note=""):
        self.index = index
        self.op = op
        self.shape = tuple(shape)
        self.dtype = dtype
        self.split = split
        self.spec = spec
        self.dynamic = dynamic
        self.note = note

    def render(self):
        if self.dynamic:
            shape = "(<=%s)" % ", ".join(str(s) for s in self.shape)
        else:
            shape = str(self.shape)
        out = "stage %d  %-24s %-18s %-10s split=%d" % (
            self.index, self.op, shape, str(self.dtype), self.split)
        if self.spec is not None:
            out += "  spec=%s" % (tuple(self.spec),)
        if self.note:
            out += "  [%s]" % self.note
        return out


class Report:
    """The checker's result: the per-stage abstract interpretation and
    every diagnostic, plus the predicted terminal ``shape``/``dtype``.

    ``shape`` uses ``None`` for a dynamic leading extent (a pending
    filter count); ``max_shape`` gives the padded upper bound instead.
    ``ok`` is True when no *error*-severity diagnostic was emitted —
    warnings and infos do not fail a pipeline (and do not block
    :func:`bolt_tpu.analysis.strict` dispatch)."""

    __slots__ = ("target", "stages", "diagnostics", "dynamic")

    def __init__(self, target, stages, diagnostics, dynamic=False):
        self.target = target            # "tpu" / "local" / view label
        self.stages = list(stages)
        self.diagnostics = list(diagnostics)
        self.dynamic = bool(dynamic)

    # -- outcome ------------------------------------------------------

    @property
    def errors(self):
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self):
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def infos(self):
        return [d for d in self.diagnostics if d.severity == "info"]

    @property
    def ok(self):
        return not self.errors

    def has(self, code):
        return any(d.code == code for d in self.diagnostics)

    # -- prediction ---------------------------------------------------

    @property
    def shape(self):
        """Predicted result shape; a dynamic (un-synced filter count)
        leading extent reads ``None``."""
        if not self.stages:
            return None
        last = self.stages[-1]
        if last.dynamic:
            return (None,) + tuple(last.shape[1:])
        return tuple(last.shape)

    @property
    def max_shape(self):
        """Predicted shape with dynamic extents at their upper bound."""
        return tuple(self.stages[-1].shape) if self.stages else None

    @property
    def dtype(self):
        return self.stages[-1].dtype if self.stages else None

    @property
    def split(self):
        return self.stages[-1].split if self.stages else None

    def __str__(self):
        lines = ["bolt_tpu.analysis report (%s)" % self.target]
        for s in self.stages:
            lines.append("  " + s.render())
        if self.diagnostics:
            lines.append("diagnostics:")
            for d in self.diagnostics:
                lines.append("  " + d.render())
        lines.append("result: %s"
                     % ("OK" if self.ok
                        else "%d error(s)" % len(self.errors)))
        return "\n".join(lines)

    def __repr__(self):
        return "<analysis.Report %s: %d stage(s), %d diagnostic(s)>" % (
            "ok" if self.ok else "ERRORS", len(self.stages),
            len(self.diagnostics))


class PipelineError(RuntimeError):
    """Raised by a :func:`bolt_tpu.analysis.strict` scope when a
    dispatching terminal's pre-compile check finds error-severity
    diagnostics.  Carries the offending :class:`Report` as ``report``."""

    def __init__(self, op, report):
        self.op = op
        self.report = report
        msgs = "; ".join("%s: %s" % (d.code, d.message)
                         for d in report.errors)
        super().__init__(
            "analysis.strict(): refusing to dispatch %s — %s" % (op, msgs))
