"""AST-based repo invariant linter: mechanical enforcement of the
codebase rules that PR reviews kept re-litigating.

Rules (the ``BLT1xx`` range; the abstract pipeline checker owns
``BLT0xx`` — see :mod:`bolt_tpu.analysis.diagnostics`):

* **BLT101** — no bare ``jax.jit`` outside ``engine.py``.  Every
  compiled program must go through the central dispatch engine (AOT
  compile cache, hit/miss/compile-time counters, persistent on-disk
  cache); a ``jax.jit`` call is allowed only inside a *builder* —
  a function or lambda passed to ``_cached_jit(key, builder)`` /
  ``engine.get(key, builder)``, whose returned jitted callable the
  engine owns.
* **BLT102** — no version-sensitive jax API outside ``_compat.py``
  (``jax.shard_map`` / ``jax.experimental.shard_map``,
  ``jax.lax.axis_size``, ``jax.sharding.AxisType``, ``jax.make_mesh``).
  The cross-version policy lives in ONE file; scattered ``hasattr``
  probes are exactly what ``_compat`` exists to prevent.
* **BLT103** — no ``precision=`` literals (a string constant or a
  ``jax.lax.Precision`` member) at call sites outside ``_precision.py``.
  Matmul-class precision must route through ``_precision.resolve()`` so
  the scoped ``bolt.precision(...)`` policy — or a deliberate,
  auditable ``resolve("highest")`` pin — always applies.  Function
  *defaults* (``def f(..., precision="highest")``) are the documented
  pinned defaults and are allowed.
* **BLT104** — no ``._concrete`` access outside ``tpu/array.py``.
  Reads must go through ``._data``, which runs the ``_guard_donated``
  donation gate; a direct ``._concrete`` read can hand out a buffer a
  donating terminal already consumed.
* **BLT106** — no raw ``time.perf_counter()`` bookkeeping outside
  ``obs/`` and ``profile.py``.  Durations must come from
  ``bolt_tpu.obs`` (``obs.clock`` for counters, ``obs.span`` for
  timeline intervals) so every timing in the package shares one clock
  and lands on one exportable timeline instead of in scattered private
  stopwatches.
* **BLT107** — no ``block_until_ready`` outside ``stream.py`` /
  ``engine.py`` / ``profile.py``.  A stray sync point serialises the
  dispatch pipeline — exactly the hazard the async streaming executor
  removes; synchronisation belongs to the executor's bounded in-flight
  window, the counted transfer layer, and the profiling barriers, not
  to op code.
* **BLT108** — no raw ``threading.Thread`` / pool-executor construction
  outside ``stream.py``, ``serve.py`` and ``parallel/podwatch.py``.
  Concurrency has exactly three blessed homes: the streaming
  executor's uploader pool, the serving layer's scheduler, and the pod
  liveness watch's heartbeat thread — all arbiter-aware or
  fault-funnelled and obs-instrumented.  A stray thread elsewhere
  bypasses the device-memory budget, the tenant counter scoping and
  the liveness guards (locks, events, and conditions are fine; it is
  thread *construction* that must be centralised).
* **BLT109** — no ``os.kill``/``signal`` use outside ``_chaos.py``,
  tests and scripts.  Fault injection has ONE blessed home — the
  deterministic chaos registry (``bolt_tpu/_chaos.py``) and its named
  seams; a stray ``os.kill``/``signal.signal`` in production code
  bypasses the registry's determinism (nth-hit counting, env arming)
  and turns the chaos harness's assertions into luck.
* **BLT110** — no ``jax.distributed`` / ``jax.process_index`` /
  ``jax.process_count`` outside ``parallel/multihost.py`` (and
  ``_compat.py``).  Process topology has ONE blessed home: the
  multi-process bootstrap, the per-process ingest contract and the
  rendezvous barriers all live in ``bolt_tpu.parallel.multihost``; a
  scattered ``jax.process_index()`` probe bypasses the pod bring-up
  policy (gloo arming on CPU, idempotent initialize) and the BLT012
  divisibility reasoning that module centralises.  Device attributes
  (``dev.process_index``) are data, not topology calls, and stay
  allowed.

A finding on line *N* is suppressed when that line carries a
``# lint: allow(BLT1xx <reason>)`` pragma — the escape hatch for the
documented exceptions (e.g. the module-level ``@jax.jit`` label-minmax
program in ``ops/group.py``).

This module imports ONLY the standard library, so
``scripts/lint_bolt.py`` runs in milliseconds with no jax import.
"""

import ast
import os

RULES = {
    "BLT101": "bare jax.jit outside the engine dispatch path",
    "BLT102": "version-sensitive jax API outside bolt_tpu/_compat.py",
    "BLT103": "precision= literal bypassing _precision.resolve()",
    "BLT104": "._concrete access bypassing the _guard_donated gate",
    "BLT105": "raw jax.device_put outside the stream transfer layer",
    "BLT106": "raw time.perf_counter bookkeeping outside bolt_tpu.obs",
    "BLT107": "stray block_until_ready sync point outside the executor",
    "BLT108": "raw thread/executor construction outside stream.py/serve.py",
    "BLT109": "os.kill/signal fault injection outside the chaos seams",
    "BLT110": "jax.distributed/process-topology call outside "
              "parallel/multihost.py",
}

# rule -> path suffixes (os-normalised) exempt from it; an entry ending
# with the path separator exempts every file under a directory of that
# name (e.g. the whole obs/ subsystem)
_EXEMPT = {
    "BLT101": ("engine.py",),
    "BLT102": ("_compat.py",),
    "BLT103": ("_precision.py",),
    "BLT104": (os.path.join("tpu", "array.py"),),
    # stream.transfer IS the counted device_put wrapper
    "BLT105": ("stream.py",),
    # obs owns the clock; profile.py is the user-facing timing facade
    "BLT106": ("obs" + os.sep, "profile.py"),
    # the executor's window/transfer syncs, the engine's AOT plumbing,
    # and profile's timing barriers are the sanctioned sync points
    "BLT107": ("stream.py", "engine.py", "profile.py"),
    # the blessed concurrency homes: the uploader pool, the
    # multi-tenant scheduler, the pod liveness heartbeat, and the
    # pod recovery supervisor's driver thread
    "BLT108": ("stream.py", "serve.py",
               os.path.join("parallel", "podwatch.py"),
               os.path.join("parallel", "supervisor.py")),
    # the one blessed fault-injection home (plus tests/scripts, whose
    # whole job is to trip and observe faults)
    "BLT109": ("_chaos.py", "tests" + os.sep, "scripts" + os.sep),
    # the one blessed process-topology home (plus _compat for any
    # version-sensitive spelling, and tests/scripts, which stand up the
    # localhost clusters themselves)
    "BLT110": (os.path.join("parallel", "multihost.py"), "_compat.py",
               "tests" + os.sep, "scripts" + os.sep),
}

# process-topology calls BLT110 confines to parallel/multihost.py
_TOPOLOGY_CALLS = {
    "jax.process_index",
    "jax.process_count",
}

# process-signal fault calls BLT109 forbids outside the blessed seams
_FAULT_CALLS = {
    "os.kill",
    "os.killpg",
    "os.abort",
    "signal.signal",
    "signal.raise_signal",
    "signal.pthread_kill",
    "signal.setitimer",
    "signal.alarm",
}

# constructors BLT108 forbids outside the blessed homes (dotted, alias-
# resolved like every other chain rule)
_THREAD_CONSTRUCTORS = {
    "threading.Thread",
    "threading.Timer",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "concurrent.futures.thread.ThreadPoolExecutor",
    "concurrent.futures.process.ProcessPoolExecutor",
    "multiprocessing.pool.ThreadPool",
    "multiprocessing.pool.Pool",
    "multiprocessing.Process",
}

_VERSION_SENSITIVE = {
    "jax.shard_map",
    "jax.experimental.shard_map",
    "jax.lax.axis_size",
    "jax.sharding.AxisType",
    "jax.make_mesh",
}

# call names whose second argument is an engine builder
_BUILDER_SINKS = {"_cached_jit"}
_BUILDER_SINK_ATTRS = {"engine.get", "_engine.get"}


class Finding:
    """One linter finding: ``code``, ``path``, ``line``/``col`` and a
    message (plus the rule's one-line title)."""

    __slots__ = ("code", "path", "line", "col", "message")

    def __init__(self, code, path, line, col, message):
        self.code = code
        self.path = path
        self.line = line
        self.col = col
        self.message = message

    @property
    def title(self):
        return RULES[self.code]

    def render(self):
        return "%s:%d:%d: %s %s" % (self.path, self.line, self.col,
                                    self.code, self.message)

    def __repr__(self):
        return "<Finding %s %s:%d>" % (self.code, self.path, self.line)


def _dotted(node):
    """``a.b.c`` attribute/name chain as a dotted string (or None)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _exempt(code, path):
    """Suffix match ANCHORED on a path separator: ``upstream.py`` must
    not inherit ``stream.py``'s exemption (nor ``myengine.py``
    ``engine.py``'s).  Directory entries (trailing separator) exempt any
    file under a component of that exact name — ``obs/`` matches
    ``bolt_tpu/obs/trace.py`` but not ``jobs/trace.py``."""
    norm = os.path.normpath(path)
    for suffix in _EXEMPT[code]:
        if suffix.endswith(os.sep):
            if (os.sep + suffix) in (os.sep + norm) \
                    or norm.startswith(suffix):
                return True
        elif norm == suffix or norm.endswith(os.sep + suffix):
            return True
    return False


def _builder_regions(tree):
    """Line spans of every function/lambda passed as the builder
    argument to ``_cached_jit``/``engine.get`` — the only places a
    ``jax.jit`` call is the engine's own, not a bypass.

    Name builders (``def build(): ...`` then ``_cached_jit(key,
    build)``) are resolved within the ENCLOSING function scope of the
    sink call, not module-wide — a same-named local builder in an
    unrelated function must not whitelist a direct-called jit there."""
    spans = []
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node

    def scope_of(node):
        while node in parents:
            node = parents[node]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.Module)):
                return node
        return tree

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        is_sink = (isinstance(fn, ast.Name) and fn.id in _BUILDER_SINKS) \
            or (_dotted(fn) in _BUILDER_SINK_ATTRS)
        if not is_sink or len(node.args) < 2:
            continue
        builder = node.args[1]
        if isinstance(builder, ast.Lambda):
            spans.append((builder.lineno, builder.end_lineno))
        elif isinstance(builder, ast.Name):
            for cand in ast.walk(scope_of(node)):
                if isinstance(cand, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and cand.name == builder.id:
                    spans.append((cand.lineno, cand.end_lineno))
    return spans


def _in_spans(line, spans):
    return any(lo <= line <= hi for lo, hi in spans)


def _pragma_lines(src):
    """Line numbers carrying a ``lint: allow(CODE ...)`` pragma, mapped
    to the allowed code."""
    allowed = {}
    for i, line in enumerate(src.splitlines(), start=1):
        if "lint: allow(" not in line:
            continue
        frag = line.split("lint: allow(", 1)[1]
        code = frag.split()[0].rstrip(")") if frag.split() else ""
        allowed[i] = code
    return allowed


def lint_source(src, path="<string>"):
    """Lint one module's source text; returns a list of
    :class:`Finding` (sorted by line)."""
    tree = ast.parse(src, filename=path)
    pragmas = _pragma_lines(src)
    findings = []

    def emit(code, node, message):
        line = getattr(node, "lineno", 0)
        if _exempt(code, path):
            return
        if pragmas.get(line) == code:
            return
        findings.append(Finding(code, path, line,
                                getattr(node, "col_offset", 0), message))

    builder_spans = _builder_regions(tree)

    # import aliases: local name -> dotted origin ("from jax import jit"
    # AND "import time as _time" — renamed plain imports must not dodge
    # the chain rules)
    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
                if a.name.startswith("jax.experimental.shard_map"):
                    emit("BLT102", node,
                         "import of jax.experimental.shard_map; route it "
                         "through bolt_tpu._compat.shard_map")
        if isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = "%s.%s" % (node.module,
                                                         a.name)
            # BLT102: importing the version-sensitive module itself
            if node.module.startswith("jax.experimental.shard_map"):
                emit("BLT102", node,
                     "import of jax.experimental.shard_map; route it "
                     "through bolt_tpu._compat.shard_map")
            else:
                for a in node.names:
                    full = "%s.%s" % (node.module, a.name)
                    if full in _VERSION_SENSITIVE:
                        emit("BLT102", node,
                             "import of %s; route it through "
                             "bolt_tpu._compat" % full)

    def resolved(node):
        """Dotted chain with the leading import alias expanded."""
        dotted = _dotted(node)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        origin = aliases.get(head)
        if origin:
            return origin + ("." + rest if rest else "")
        return dotted

    for node in ast.walk(tree):
        # ---- BLT101: bare jax.jit --------------------------------------
        jit_nodes = []
        if isinstance(node, ast.Call) and resolved(node.func) == "jax.jit":
            jit_nodes.append(node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                # bare @jax.jit only — a @jax.jit(...) decorator is a
                # Call and the branch above already sees it
                if not isinstance(dec, ast.Call) \
                        and resolved(dec) == "jax.jit":
                    jit_nodes.append(dec)
        for jn in jit_nodes:
            if not _in_spans(jn.lineno, builder_spans):
                emit("BLT101", jn,
                     "bare jax.jit bypasses the engine's AOT compile "
                     "cache; return it from a builder passed to "
                     "_cached_jit/engine.get")

        # ---- BLT102: version-sensitive attribute chains ----------------
        if isinstance(node, ast.Attribute):
            dotted = resolved(node)
            if dotted in _VERSION_SENSITIVE:
                emit("BLT102", node,
                     "%s is version-sensitive; use the bolt_tpu._compat "
                     "shim" % dotted)

        # ---- BLT103: precision= literals -------------------------------
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg != "precision":
                    continue
                v = kw.value
                literal = isinstance(v, ast.Constant) \
                    and isinstance(v.value, str)
                if not literal:
                    # alias-aware like BLT101/102: `from jax.lax import
                    # Precision as P; precision=P.HIGHEST` must match
                    vd = resolved(v) or ""
                    literal = ".Precision." in "." + vd
                if literal:
                    emit("BLT103", kw.value,
                         "precision literal at a call site bypasses the "
                         "scoped policy; pass "
                         "_precision.resolve(...) instead (use "
                         "resolve('highest') for a deliberate pin)")

        # ---- BLT104: ._concrete outside the donation gate --------------
        if isinstance(node, ast.Attribute) and node.attr == "_concrete":
            emit("BLT104", node,
                 "._concrete bypasses the _guard_donated donation gate; "
                 "read ._data instead")

        # ---- BLT105: raw jax.device_put outside stream.transfer --------
        if isinstance(node, ast.Call) \
                and resolved(node.func) == "jax.device_put":
            emit("BLT105", node,
                 "raw jax.device_put bypasses the counted transfer layer "
                 "(transfer_bytes/transfer_seconds stay blind); route it "
                 "through bolt_tpu.stream.transfer")

        # ---- BLT107: stray sync points outside the executor ------------
        if isinstance(node, ast.Attribute) \
                and node.attr == "block_until_ready":
            # covers jax.block_until_ready(x) AND x.block_until_ready()
            emit("BLT107", node,
                 "a block_until_ready here serialises the async dispatch "
                 "pipeline (the perf hazard the streaming executor's "
                 "bounded in-flight window exists to remove); let the "
                 "executor/profiling layers own synchronisation")
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Name) \
                and resolved(node.func) == "jax.block_until_ready":
            # from-import form: `from jax import block_until_ready`
            # (the dotted form is an Attribute — the branch above; this
            # one must not double-report it off the enclosing Call)
            emit("BLT107", node,
                 "a block_until_ready here serialises the async dispatch "
                 "pipeline (the perf hazard the streaming executor's "
                 "bounded in-flight window exists to remove); let the "
                 "executor/profiling layers own synchronisation")

        # ---- BLT110: jax.distributed / process-topology calls ----------
        if isinstance(node, ast.Call) \
                and resolved(node.func) in _TOPOLOGY_CALLS:
            emit("BLT110", node,
                 "%s outside the blessed topology home; route it "
                 "through bolt_tpu.parallel.multihost (process_index/"
                 "process_count/is_multiprocess), which owns the pod "
                 "bring-up policy" % resolved(node.func))
        if isinstance(node, ast.Attribute) \
                and resolved(node) == "jax.distributed":
            emit("BLT110", node,
                 "jax.distributed outside the blessed topology home; "
                 "bootstrap/teardown live in bolt_tpu.parallel."
                 "multihost.initialize/shutdown (which also arm the "
                 "CPU collective transport the localhost clusters "
                 "need)")
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax.distributed" \
                        or a.name.startswith("jax.distributed."):
                    emit("BLT110", node,
                         "import of jax.distributed outside the blessed "
                         "topology home; use bolt_tpu.parallel.multihost")
        if isinstance(node, ast.ImportFrom) and node.module \
                and (node.module == "jax.distributed"
                     or node.module.startswith("jax.distributed.")
                     or (node.module == "jax"
                         and any(a.name == "distributed"
                                 for a in node.names))):
            emit("BLT110", node,
                 "import of jax.distributed outside the blessed "
                 "topology home; use bolt_tpu.parallel.multihost")

        # ---- BLT109: os.kill / signal fault injection ------------------
        if isinstance(node, ast.Call) \
                and resolved(node.func) in _FAULT_CALLS:
            emit("BLT109", node,
                 "%s outside the blessed fault seams; route the fault "
                 "through bolt_tpu._chaos.inject/hit (deterministic "
                 "nth-hit counting, BOLT_CHAOS env arming) so the chaos "
                 "harness can reproduce it" % resolved(node.func))
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "signal" or a.name.startswith("signal."):
                    emit("BLT109", node,
                         "import of the signal module outside the "
                         "blessed fault seams; fault injection lives in "
                         "bolt_tpu._chaos (lint rule BLT109)")
        if isinstance(node, ast.ImportFrom) and node.module == "signal":
            emit("BLT109", node,
                 "import from the signal module outside the blessed "
                 "fault seams; fault injection lives in bolt_tpu._chaos "
                 "(lint rule BLT109)")

        # ---- BLT108: raw thread/executor construction ------------------
        if isinstance(node, ast.Call) \
                and resolved(node.func) in _THREAD_CONSTRUCTORS:
            emit("BLT108", node,
                 "%s constructed outside the blessed concurrency homes "
                 "(stream.py's uploader pool, serve.py's scheduler); a "
                 "stray thread bypasses the device-memory arbiter, the "
                 "per-tenant counter scoping and the liveness guards — "
                 "route the work through bolt_tpu.serve.submit or the "
                 "streaming executor" % resolved(node.func))

        # ---- BLT106: raw perf_counter bookkeeping outside obs ----------
        if isinstance(node, ast.Call) \
                and resolved(node.func) == "time.perf_counter":
            emit("BLT106", node,
                 "raw time.perf_counter() keeps its timing off the shared "
                 "clock and the obs timeline; use bolt_tpu.obs.clock() "
                 "for counter bookkeeping or obs.span(...) for a traced "
                 "interval")

    findings.sort(key=lambda f: (f.line, f.col))
    return findings


def lint_file(path):
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def iter_py_files(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths):
    findings = []
    for p in paths:
        if os.path.isdir(p):
            for f in iter_py_files(p):
                findings.extend(lint_file(f))
        else:
            findings.extend(lint_file(p))
    return findings


def lint_package(root=None):
    """Lint the ``bolt_tpu`` package (zero findings is a tier-1
    invariant — ``tests/test_static_analysis.py``)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return lint_paths([root])
