"""Static concurrency analysis: the lock-hierarchy lint pass.

The runtime lockdep witness (:mod:`bolt_tpu._lockdep`) catches the
inversions a test actually EXECUTES; this pass catches the ones it
doesn't — every lock creation and every lexically-nested acquisition in
the repo is checked against the declared rank table WITHOUT running a
single thread.

Rules (continuing the ``BLT1xx`` range owned by
:mod:`bolt_tpu.analysis.astlint`):

* **BLT111** — a lock created outside the declared inventory.  Raw
  ``threading.Lock()`` / ``RLock()`` / ``Condition()`` construction in
  package code bypasses the hierarchy entirely (the witness cannot rank
  what it cannot see); construction must go through
  ``_lockdep.lock/rlock/condition(name)`` — and the ``name`` must be a
  string literal present in ``_lockdep.RANKS``, so the static table and
  the runtime witness can never drift apart.
* **BLT112** — a static acquisition-order inversion: a ``with`` block
  acquiring a ranked lock lexically inside a ``with`` holding an
  equal-or-higher-ranked one.  Rank order is the deadlock-freedom
  proof; one inverted nesting anywhere breaks it for every thread in
  the process.
* **BLT113** — an indefinite blocking call (``barrier`` /
  ``sync_global_devices``, ``Future.result()``, ``queue.get()``,
  ``wait()``/``join()`` without a timeout, ``time.sleep``) lexically
  under a ranked lock.  A thread parked under a lock stalls every
  thread contending that lock for the full wait — and a COLLECTIVE
  under a lock is the classic distributed deadlock: the peer process
  that must join the rendezvous may first need the very lock this
  process sleeps on.
* **BLT114** — a compiled-executable enqueue (``.jitted(...)`` or a
  name bound from ``.compile()`` / ``.compiled.get(...)``) outside a
  ``with order_lock():`` block.  Per-process dispatch order IS the
  cross-process collective contract; one unordered enqueue reorders
  the schedule and wedges the pod (the hazard PR 7's order lock
  exists to close — this rule makes the discipline mechanical).

Same pragma escape hatch as the other chain rules: a finding on line
*N* is suppressed by ``# lint: allow(BLT11x <reason>)`` on that line.

Lexical honesty: the pass reasons about one module at a time and about
*lexical* nesting only.  A nested ``def`` resets the held-lock stack
(the closure runs later, not under the lock), and cross-module call
chains are the runtime witness's job.  The two layers share ONE rank
table — this module loads it from ``bolt_tpu._lockdep`` (stdlib-only)
so the lint path still starts in milliseconds with no jax import.
"""

import ast
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))


def _load(modname, path):
    mod = sys.modules.get(modname)
    if mod is not None:
        return mod
    import importlib.util
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec so a later package import adopts this
    # instance (one rank table, one RULES registry, process-wide)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


_astlint = sys.modules.get("bolt_astlint") \
    or _load("bolt_tpu.analysis.astlint",
             os.path.join(_HERE, "astlint.py"))
_lockdep = _load("bolt_tpu._lockdep",
                 os.path.join(os.path.dirname(_HERE), "_lockdep.py"))

Finding = _astlint.Finding
_dotted = _astlint._dotted
_pragma_lines = _astlint._pragma_lines
iter_py_files = _astlint.iter_py_files

RANKS = _lockdep.RANKS

RULES = {
    "BLT111": "lock created outside the declared _lockdep inventory",
    "BLT112": "static lock-acquisition order inversion",
    "BLT113": "indefinite blocking call while holding a ranked lock",
    "BLT114": "executable enqueue outside the engine order lock",
}

# Finding.title resolves through the astlint registry; merging keeps
# one BLT1xx namespace (and one --codes listing) across both passes
_astlint.RULES.update(RULES)

_EXEMPT = {
    # the witness constructs the raw primitives it wraps; tests and
    # scripts build scratch locks for their own harnesses
    "BLT111": ("_lockdep.py", "tests" + os.sep, "scripts" + os.sep),
    "BLT112": ("_lockdep.py", "tests" + os.sep, "scripts" + os.sep),
    "BLT113": ("_lockdep.py", "tests" + os.sep, "scripts" + os.sep),
    "BLT114": ("tests" + os.sep, "scripts" + os.sep),
}

# raw constructors BLT111 forbids (alias-resolved like every chain rule)
_RAW_LOCKS = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
}

# the inventory factories (any import spelling of bolt_tpu._lockdep)
_FACTORY_TAILS = {"lock", "rlock", "condition"}

# dotted tails that block indefinitely regardless of arguments
_BLOCKING_TAILS = {"barrier", "sync_global_devices", "wait_ready"}

# attribute calls that block indefinitely ONLY when called with no
# timeout at all (zero args, zero keywords)
_BLOCKING_IF_BARE = {"wait", "result", "join", "get", "acquire"}


def _exempt(code, path):
    """Separator-anchored suffix match (same semantics as astlint's)."""
    norm = os.path.normpath(path)
    for suffix in _EXEMPT[code]:
        if suffix.endswith(os.sep):
            if (os.sep + suffix) in (os.sep + norm) \
                    or norm.startswith(suffix):
                return True
        elif norm == suffix or norm.endswith(os.sep + suffix):
            return True
    return False


def _is_lockdep_factory(resolved_name):
    """True for any import spelling of the inventory factories:
    ``_lockdep.lock`` / ``bolt_tpu._lockdep.rlock`` / a bare
    ``condition`` from-imported out of the module."""
    if resolved_name is None:
        return False
    head, _, tail = resolved_name.rpartition(".")
    return tail in _FACTORY_TAILS and head.endswith("_lockdep")


def _lock_bindings(tree, resolved):
    """Two maps resolving lock expressions to inventory names:

    * ``names``: module/function-level ``X = _lockdep.lock("n")``
    * ``attrs``: instance-attribute ``self.x = _lockdep.rlock("n")``

    (Per-module granularity: attribute names are distinctive within a
    module here; cross-class collisions would merely merge same-module
    bindings, never invent a rank.)"""
    names, attrs = {}, {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        val = node.value
        if not (isinstance(val, ast.Call)
                and _is_lockdep_factory(resolved(val.func))
                and val.args
                and isinstance(val.args[0], ast.Constant)
                and isinstance(val.args[0].value, str)):
            continue
        inv = val.args[0].value
        tgt = node.targets[0]
        if isinstance(tgt, ast.Name):
            names[tgt.id] = inv
        elif isinstance(tgt, ast.Attribute):
            attrs[tgt.attr] = inv
    return names, attrs


def _with_item_name(expr, resolved, names, attrs):
    """Inventory name a ``with <expr>:`` item acquires, or None when
    the expression is not a ranked lock (unresolvable expressions are
    SKIPPED, never guessed — no false positives)."""
    if isinstance(expr, ast.Call):
        dotted = resolved(expr.func) or ""
        if dotted == "order_lock" or dotted.endswith(".order_lock"):
            return "engine.order"
        return None
    if isinstance(expr, ast.Name):
        return names.get(expr.id)
    if isinstance(expr, ast.Attribute):
        return attrs.get(expr.attr)
    return None


def _enqueue_names(fn_node):
    """Local names in ``fn_node`` bound from a compiled executable —
    ``fn = lowered.compile()`` or ``fn = self.compiled.get(sig)`` —
    whose later CALL is a dispatch enqueue (BLT114)."""
    out = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        val = node.value
        if not (isinstance(val, ast.Call)
                and isinstance(val.func, ast.Attribute)):
            continue
        attr = val.func.attr
        owner = _dotted(val.func.value) or ""
        if attr == "compile" or (attr == "get"
                                 and owner.endswith("compiled")):
            out.add(node.targets[0].id)
    return out


def _is_blocking(node, resolved):
    """Message for a call that can block indefinitely, or None."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in _BLOCKING_TAILS:
            return "%s() is a collective/rendezvous" % fn.attr
        if fn.attr in _BLOCKING_IF_BARE and not node.args \
                and not node.keywords:
            return ".%s() with no timeout blocks indefinitely" % fn.attr
    dotted = resolved(fn)
    if dotted == "time.sleep":
        return "time.sleep() parks the thread"
    return None


def lint_source(src, path="<string>"):
    """Run BLT111–BLT114 over one module's source; returns sorted
    :class:`Finding` objects (the astlint class — one render format)."""
    tree = ast.parse(src, filename=path)
    pragmas = _pragma_lines(src)
    findings = []

    def emit(code, node, message):
        line = getattr(node, "lineno", 0)
        if _exempt(code, path):
            return
        if pragmas.get(line) == code:
            return
        findings.append(Finding(code, path, line,
                                getattr(node, "col_offset", 0), message))

    aliases = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    aliases[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = "%s.%s" % (node.module,
                                                         a.name)

    def resolved(node):
        dotted = _dotted(node)
        if dotted is None:
            return None
        head, _, rest = dotted.partition(".")
        origin = aliases.get(head)
        if origin:
            return origin + ("." + rest if rest else "")
        return dotted

    names, attrs = _lock_bindings(tree, resolved)

    # ---- BLT111: creation sites ------------------------------------
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = resolved(node.func)
        if dotted in _RAW_LOCKS:
            emit("BLT111", node,
                 "raw %s() is invisible to the lock-hierarchy witness; "
                 "construct it through bolt_tpu._lockdep."
                 "lock/rlock/condition(name) with a declared inventory "
                 "name" % dotted)
        elif _is_lockdep_factory(dotted):
            arg = node.args[0] if node.args else None
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                emit("BLT111", node,
                     "lock inventory name must be a string literal so "
                     "the static pass can rank it")
            elif arg.value not in RANKS:
                emit("BLT111", node,
                     "lock name %r is not in the declared inventory "
                     "(_lockdep.RANKS); add it with a rank reflecting "
                     "its nesting depth" % arg.value)

    # ---- BLT112/113/114: the held-stack walk -----------------------
    def walk(node, held, enqueue):
        # a nested function's body runs LATER, not under the lock
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            enq = enqueue | _enqueue_names(node)
            for child in ast.iter_child_nodes(node):
                walk(child, [], enq)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                inv = _with_item_name(item.context_expr, resolved,
                                      names, attrs)
                if inv is None:
                    continue
                rank = RANKS.get(inv)
                if rank is None:
                    continue
                for outer_name, outer_rank in held:
                    if outer_rank >= rank and outer_name != inv:
                        emit("BLT112", item.context_expr,
                             "acquiring %r (rank %d) inside %r (rank "
                             "%d) inverts the declared order; "
                             "restructure so the lower rank is taken "
                             "first, or re-rank the inventory"
                             % (inv, rank, outer_name, outer_rank))
                acquired.append((inv, rank))
            inner = held + acquired
            for child in node.body:
                walk(child, inner, enqueue)
            return
        if isinstance(node, ast.Call):
            if held:
                why = _is_blocking(node, resolved)
                if why is not None:
                    emit("BLT113", node,
                         "%s while holding %r — every thread "
                         "contending that lock stalls for the full "
                         "wait (a collective here is the classic "
                         "cross-process deadlock); release the lock "
                         "first or bound the wait"
                         % (why, held[-1][0]))
            fn = node.func
            is_enqueue = (isinstance(fn, ast.Attribute)
                          and fn.attr == "jitted") \
                or (isinstance(fn, ast.Name) and fn.id in enqueue)
            if is_enqueue \
                    and not any(n == "engine.order" for n, _ in held):
                emit("BLT114", node,
                     "compiled-executable enqueue outside `with "
                     "order_lock():` — per-process dispatch order is "
                     "the cross-process collective contract; an "
                     "unordered enqueue can wedge the pod")
        for child in ast.iter_child_nodes(node):
            walk(child, held, enqueue)

    walk(tree, [], set())
    findings.sort(key=lambda f: (f.line, f.col))
    return findings


def lint_file(path):
    with open(path, "r", encoding="utf-8") as fh:
        return lint_source(fh.read(), path)


def lint_paths(paths):
    findings = []
    for p in paths:
        if os.path.isdir(p):
            for f in iter_py_files(p):
                findings.extend(lint_file(f))
        else:
            findings.extend(lint_file(p))
    return findings


def lint_package(root=None):
    """Run the concurrency pass over ``bolt_tpu`` (zero findings is a
    tier-1 invariant, same as the astlint pass)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return lint_paths([root])
