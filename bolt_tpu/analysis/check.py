"""Abstract pipeline checker: interpret a deferred pipeline without
compiling or dispatching anything.

PR 1 made pipelines deferred, fused and donation-aware — a
:class:`~bolt_tpu.tpu.array.BoltArrayTPU` can be an opaque
``(base, funcs)`` program whose shape/dtype/sharding errors and
use-after-donate crashes only surface at XLA compile or dispatch time.
:func:`check` walks that recorded state — the ``_chain`` map chain, a
deferred ``_fpending`` filter, a ``_pending`` compaction — and abstractly
interprets it stage by stage with ``jax.eval_shape`` (abstract
interpretation only: ZERO XLA compiles, proven by the engine counters
staying flat), inferring the result shape, dtype and key sharding per
stage and emitting structured ``BLT0xx`` diagnostics
(:mod:`bolt_tpu.analysis.diagnostics`) for:

* stages that fail abstract tracing (``BLT001``);
* a recorded result aval that lies about what the chain produces
  (``BLT002`` — the ``value_shape``-lie class);
* silent dtype widening along the chain (``BLT003`` — an f32 pipeline
  that materialises f64 doubles its HBM footprint);
* key axes that do not divide the mesh, leaving devices idle
  (``BLT004``);
* donation-safety violations: any read path that hits a ``_donated``
  buffer (``BLT005``), and a forecast of the terminal donation the
  engine's policy WILL grant (``BLT006``);
* a filter predicate that is not scalar-per-record (``BLT007``) and
  dynamic shapes pending a survivor-count sync (``BLT008``).

The interpretation applies each stage through the SAME
``_chain_apply`` the compiled program uses, so predicted and executed
shape/dtype cannot drift (``tests/test_pipeline_fuzz.py`` asserts this
parity on every fuzzed pipeline).
"""

import sys

import numpy as np

import jax

from bolt_tpu.obs import trace as _obs
from bolt_tpu.analysis.diagnostics import Diagnostic, Report, Stage
from bolt_tpu.parallel.sharding import key_spec, spec_names
from bolt_tpu.utils import prod


def _name(func):
    return getattr(func, "__name__", None) or type(func).__name__


def _func_label(func):
    from bolt_tpu.tpu.array import _WithKeysFunc
    if isinstance(func, _WithKeysFunc):
        return "map(%s, with_keys)" % _name(func.func)
    return "map(%s)" % _name(func)


def _stage_eval(func, split, aval):
    """Abstractly apply ONE chain stage — through the same
    ``_chain_apply`` the compiled program runs, so the prediction cannot
    drift from execution.  Results are memoised in the array module's
    eval cache (keyed on func identity + input aval)."""
    from bolt_tpu.tpu.array import _cached_eval_shape, _chain_apply
    key = ("analysis-stage", func, split, tuple(aval.shape),
           str(aval.dtype))
    return _cached_eval_shape(
        key, lambda: jax.eval_shape(
            lambda d: _chain_apply((func,), split, d),
            jax.ShapeDtypeStruct(tuple(aval.shape), aval.dtype)))


def _would_donate(arr):
    """Would the NEXT terminal donate this array's chain base?  Mirrors
    the terminals exactly by delegating to ``_chain_donate_ok`` with the
    same reference pattern (attribute access straight into the call, no
    extra locals — the ownership test is refcount-based)."""
    from bolt_tpu.tpu.array import _chain_donate_ok
    if arr._fpending is not None:
        return _chain_donate_ok(arr._fpending)
    if arr._chain is not None:
        return _chain_donate_ok(arr._chain)
    return False


def _idle_device_check(mesh, shape, split, stage_idx, diags, seen):
    """``BLT004`` once per report: the derived key sharding leaves mesh
    devices idle because the key extents do not divide the mesh.
    Malformed state (split beyond the rank — exactly what hand-built
    deferred arrays can carry) must not crash the checker: the shape
    contradiction gets its own BLT002, so sharding is simply skipped."""
    if seen or mesh is None or not split:
        return seen
    try:
        spec = key_spec(mesh, shape, split)
        names = [n for e in spec for n in spec_names(e)]
        assigned = prod([mesh.shape[n] for n in names]) if names else 1
        full = prod([mesh.shape[n] for n in mesh.axis_names
                     if mesh.shape[n] > 1])
    except Exception:
        return seen
    if assigned < full:
        diags.append(Diagnostic(
            "BLT004", stage_idx,
            "key axes %s assign only %d of %d mesh devices (extents do "
            "not divide the mesh %s)"
            % (tuple(shape[:split]), assigned, full, dict(mesh.shape)),
            hint="reshape the key axes (keys.reshape) or choose key "
                 "extents divisible by the mesh axis sizes"))
        return True
    return seen


def _spec(mesh, shape, split):
    try:
        return key_spec(mesh, shape, split)
    except Exception:
        return None


def _fmt_bytes(n):
    if n >= 1 << 30:
        return "%.1f GB" % (n / float(1 << 30))
    if n >= 1 << 20:
        return "%.1f MB" % (n / float(1 << 20))
    return "%d B" % n


def _group_bytes(g):
    """Bytes ONE pass of a stat group reads (the fusion forecast's
    bytes-read model)."""
    if g.kind == "chain":
        return int(g.base.nbytes)
    if g.kind == "fpending":
        return int(g.fpending[0].nbytes)
    return prod(g.source.shape) * np.dtype(g.source.dtype).itemsize


# ---------------------------------------------------------------------
# admission budget (the serving layer's BLT010 contract)
# ---------------------------------------------------------------------

def _effective_codec(src):
    """The codec a run over ``src`` would resolve (source ``codec=``
    wins over the caller's ``stream.codec()`` scope), WITHOUT the dtype
    validation ``stream.resolve_codec`` performs — the checker wants to
    FORECAST the refusal (BLT016 warning), not raise it.  Unknown names
    cannot arm through any public door (``fromcallback``/``fromiter``,
    the scope and ``set_codec`` all validate pointedly), but a
    hand-built source must degrade to "no forecast", never crash the
    checker — the run itself still refuses at ``resolve_codec``."""
    from bolt_tpu import stream as _stream
    name = src.codec if src.codec is not None else _stream.current_codec()
    if name is None:
        return None
    from bolt_tpu.tpu import codec as _codeclib
    try:
        return _codeclib.get(name)
    except ValueError:
        return None


def _stream_slab_bytes(src):
    """One slab's DEVICE bytes — the WIRE representation when a codec
    is armed (the ring holds and the arbiter leases compressed slabs;
    the admission floor recomputes through the codec ratio)."""
    itemsize = src.dtype.itemsize
    c = _effective_codec(src)
    if c is not None:
        try:
            itemsize = c.wire_dtype(src.dtype).itemsize
        except ValueError:
            pass          # refused combination: the run never streams
    return int(src.slab * prod(src.shape[1:]) * itemsize)


def _stream_ring_bytes(src):
    """A streaming plan's peak device footprint: slab bytes times the
    donated-ring bound (prefetch depth + uploader pool) — exactly the
    budget one run's slabs can hold at once in ``stream.execute``."""
    from bolt_tpu import stream as _stream
    return _stream_slab_bytes(src) * (_stream.prefetch_depth()
                                      + _stream.pool_size(src))


def _admission_budget():
    """The ACTIVE serving arbiter's byte budget, or None when
    ``bolt_tpu.serve`` is not running (consulted via ``sys.modules`` so
    checking never imports the serving layer)."""
    sv = sys.modules.get("bolt_tpu.serve")
    if sv is None:
        return None
    arb = sv.device_arbiter()
    return arb.budget if arb is not None else None


def _note_admission(est, idx, diags):
    """``BLT010``: the pipeline's MINIMUM device working set — the
    floor it can degrade to under budget pressure (one slab for
    streams; the whole base + result for in-memory pipelines) — exceeds
    the serving budget: ``serve.submit`` rejects it, because a worker
    that admitted it would hog or wedge the arbiter forever."""
    budget = _admission_budget()
    if budget is None or est is None or est <= budget:
        return
    diags.append(Diagnostic(
        "BLT010", idx,
        "minimum device working set ~%s exceeds the serving admission "
        "budget %s even fully degraded; serve.submit will reject this "
        "pipeline" % (_fmt_bytes(int(est)), _fmt_bytes(int(budget))),
        hint="shrink the operand or streaming slabs "
             "(fromcallback(chunks=...)), or start the server with a "
             "larger budget_bytes"))


def admission_floor_bytes(obj):
    """The MINIMUM device bytes ``obj``'s pipeline needs at once — the
    number admission control (``serve.submit`` / BLT010) compares
    against the serving budget.  Streaming plans degrade to ONE slab in
    flight (the arbiter's starvation valve shallows the ring), so their
    floor is the slab size; in-memory pipelines cannot shrink, so their
    floor is :func:`working_set_bytes`.  None when nothing can be
    estimated."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.tpu.chunk import ChunkedArray
    from bolt_tpu.tpu.stack import StackedArray
    arr = obj
    if isinstance(arr, (ChunkedArray, StackedArray)):
        arr = arr._barray
    if not isinstance(arr, BoltArrayTPU):
        return None
    if arr._spending is not None and arr._spending.group.kind == "stream":
        return _stream_slab_bytes(arr._spending.group.source)
    if arr._stream is not None:
        return _stream_slab_bytes(arr._stream)
    return working_set_bytes(arr)


def working_set_bytes(obj):
    """Estimated PEAK device bytes ``obj``'s pipeline needs at once —
    the number admission control compares against the serving budget:

    * streaming plan → slab bytes x (prefetch depth + uploader pool),
      the donated-ring bound;
    * pending stat group → the group's one-pass read (stream groups use
      the ring bound);
    * deferred chain / filter / concrete array → source bytes + result
      bytes (input and output coexist during the dispatch).

    Returns ``None`` for objects with nothing to estimate (local
    arrays)."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.tpu.chunk import ChunkedArray
    from bolt_tpu.tpu.stack import StackedArray
    arr = obj
    if isinstance(arr, (ChunkedArray, StackedArray)):
        arr = arr._barray
    if not isinstance(arr, BoltArrayTPU):
        return None
    if arr._spending is not None:
        g = arr._spending.group
        if g.kind == "stream":
            return _stream_ring_bytes(g.source)
        return int(_group_bytes(g))
    if arr._stream is not None:
        return _stream_ring_bytes(arr._stream)
    aval = arr._aval
    out_bytes = (prod(tuple(aval.shape)) * np.dtype(aval.dtype).itemsize
                 if aval is not None else 0)
    if arr._fpending is not None:
        return int(arr._fpending[0].nbytes) + int(out_bytes)
    if arr._chain is not None:
        return int(arr._chain[0].nbytes) + int(out_bytes)
    return int(out_bytes)


def _batching_policy():
    """The ACTIVE server's batching policy, or ``None`` when no
    batching-enabled server is running (consulted via ``sys.modules``
    like the BLT010 budget — checking never imports the serving
    layer)."""
    sv = sys.modules.get("bolt_tpu.serve")
    if sv is None:
        return None
    srv = sv.active()
    return getattr(srv, "batching", None) if srv is not None else None


def _note_batchable(arr, idx, diags):
    """``BLT015``: forecast serve micro-batching — a batching-enabled
    server is active and this pipeline carries a batch key
    (``bolt_tpu.tpu.batched.batch_key``), so queued same-key requests
    (same structure, shapes, dtypes, terminal and sharding — across
    tenants) will coalesce into ONE stacked dispatch at bucketed
    widths, bit-identical to the standalone dispatch."""
    pol = _batching_policy()
    if pol is None:
        return
    bt = sys.modules.get("bolt_tpu.tpu.batched")
    if bt is None:
        return
    try:
        key = bt.batch_key(arr)
    except Exception:
        return
    if key is None:
        return
    diags.append(Diagnostic(
        "BLT015", idx,
        "terminal is batch-eligible (%s form): the active batching "
        "server coalesces up to %d queued same-key requests — same "
        "pipeline structure/shape/dtype/terminal/sharding, across "
        "tenants — into ONE stacked dispatch at bucketed widths %s, "
        "each lane bit-identical to its standalone dispatch"
        % (key[0], pol.max_batch, tuple(pol.buckets)),
        hint="submit same-shape pipelines concurrently to share one "
             "batched executable; serve.stats()['batching'] shows the "
             "realised occupancy, batched.warm() pre-compiles the "
             "buckets"))


def _note_fusable(arr, idx, diags):
    """``BLT009``: forecast the single-pass fusion — this array's
    source carries a live fused stat group (bolt_tpu/tpu/multistat.py),
    so its pending terminals will resolve from ONE read instead of one
    pass each.  ``explain()`` thereby shows the single-pass plan and
    the bytes-read estimate before anything dispatches."""
    g = getattr(arr, "_stat_group", None)
    if g is not None:
        _note_fusable_group(g, idx, diags)


def _check_spending(arr, target, stages, diags):
    """Abstractly interpret a PENDING STAT array (the lazy result of a
    ``sum()``-family terminal): nothing dispatches — the group's source
    and the terminal's derived aval are reported, plus the ``BLT009``
    fusion forecast."""
    h = arr._spending
    g = h.group
    if g.kind == "stream":
        src_shape = tuple(g.source.shape)
        src_dtype = np.dtype(g.source.dtype)
        label = "stream source (%s)" % g.source.kind
    elif g.kind == "fpending":
        base = g.fpending[0]
        src_shape = tuple(base.shape)
        src_dtype = np.dtype(base.dtype)
        label = "filtered chain base"
    else:
        src_shape = tuple(g.base.shape)
        src_dtype = np.dtype(g.base.dtype)
        label = "chain base" if g.funcs else "base (concrete)"
    stages.append(Stage(0, label, src_shape, src_dtype, g.split,
                        _spec(arr._mesh, src_shape, g.split)))
    stages.append(Stage(
        1, "%s() [pending stat]" % h.name, tuple(h.aval.shape),
        np.dtype(h.aval.dtype), h.new_split,
        _spec(arr._mesh, tuple(h.aval.shape), h.new_split),
        note="terminal of a %d-member fused group, not yet dispatched"
             % len(g.members)))
    _note_fusable_group(g, 1, diags)
    _note_batchable(arr, 1, diags)
    _note_admission(_stream_slab_bytes(g.source) if g.kind == "stream"
                    else _group_bytes(g), 1, diags)
    if g.kind == "stream":
        _note_codec(g.source, 1, diags,
                    members=[m.name for m in g.members])
    return Report(target + ", pending stat", stages, diags)


def _note_fusable_group(g, idx, diags):
    pend = [m for m in g.members if m.result is None]
    if g.dispatched or not pend:
        return
    names = ", ".join(m.name for m in pend)
    nbytes = _group_bytes(g)
    diags.append(Diagnostic(
        "BLT009", idx,
        "fusable terminal set: %d pending stat terminal(s) [%s] resolve "
        "from ONE %s pass reading ~%s (instead of %d passes / ~%s); "
        "results are bit-identical to the standalone terminals"
        % (len(pend), names, g.kind, _fmt_bytes(nbytes), len(pend),
           _fmt_bytes(nbytes * len(pend))),
        hint="read any member (or bolt.compute(...)) to dispatch the "
             "group; terminals on other sources fall back per group"))


def _note_codec(src, idx, diags, members=()):
    """``BLT016``: forecast codec-encoded ingest (ISSUE 14) — the bytes
    this streaming plan will NOT move over the host→device link, plus a
    WARNING when a lossy codec meets a bit-exactness-sensitive terminal
    (order statistics — the executor will refuse) or a dtype the codec
    cannot encode."""
    c = _effective_codec(src)
    if c is None:
        return
    raw = int(prod(src.shape) * src.dtype.itemsize)
    try:
        wire = int(prod(src.shape) * c.wire_dtype(src.dtype).itemsize)
    except ValueError as exc:
        diags.append(Diagnostic(
            "BLT016", idx,
            "codec %r cannot encode this %s pipeline — the streamed "
            "run will refuse pointedly: %s"
            % (c.name, np.dtype(src.dtype), str(exc).splitlines()[0]),
            severity="warning",
            hint="pick a codec that supports the dtype, or stream "
                 "uncompressed"))
        return
    sensitive = sorted({m for m in members if m in ("min", "max",
                                                    "ptp")})
    if not c.lossless and sensitive:
        diags.append(Diagnostic(
            "BLT016", idx,
            "lossy codec %r meets the bit-exactness-sensitive order "
            "statistic(s) %s — the streamed run will refuse them "
            "(a quantised extremum is never the intended answer)"
            % (c.name, sensitive), severity="warning",
            hint="use the lossless 'delta-f32' codec for order stats, "
                 "or resolve them over an uncompressed source"))
        return
    diags.append(Diagnostic(
        "BLT016", idx,
        "codec-encoded ingest (%s%s): one full pass ships ~%s on the "
        "wire instead of ~%s (%.2fx)%s"
        % (c.name, "" if c.lossless else ", LOSSY opt-in",
           _fmt_bytes(wire), _fmt_bytes(raw),
           (wire / raw) if raw else 1.0,
           " — lossless: bit-identical to uncompressed streaming"
           if c.lossless else ""),
        hint="uploader workers encode per slab (codec_bytes_raw/"
             "codec_bytes_wire engine counters); the slab program "
             "decodes on device fused into the fold — zero extra HBM "
             "passes, and the arbiter leases the wire bytes"))


def _note_shuffle(src, stage, aval, split, mesh, idx, diags):
    """``BLT017``: forecast the streamed shuffle (ISSUE 18) — the SAME
    planner the executor runs (``parallel.shuffle.plan_shuffle`` fed by
    ``stream.swap_budget()``/``spill_scope()``), so the forecast and
    the dispatch-time resident/spill decision cannot drift.  INFO for a
    servable plan; WARNING when the plan forecasts spill with no spill
    directory configured (the executor will refuse pointedly) or when
    the pod geometry refuses the collective outright."""
    from bolt_tpu import stream as _stream
    from bolt_tpu.parallel import shuffle as _shuffle
    perm, new_split = stage[1], stage[2]
    spill_dir, _ = _stream.spill_scope()
    try:
        plan = _shuffle.plan_shuffle(
            tuple(aval.shape), np.dtype(aval.dtype), split, perm,
            new_split, mesh, src.slab, _stream.swap_budget(), spill_dir)
    except ValueError as exc:
        diags.append(Diagnostic(
            "BLT017", idx,
            "the streamed shuffle refuses this swap — the run will "
            "raise identically at dispatch: %s"
            % str(exc).splitlines()[0], severity="warning",
            hint="reshape the pipeline so the swap satisfies the pod "
                 "geometry, or materialise first (toarray) and swap "
                 "in memory"))
        return
    if not plan.resident and plan.sharded:
        diags.append(Diagnostic(
            "BLT017", idx,
            plan.describe() + " — but disk spill is single-process "
            "only: the multi-process executor will refuse this swap "
            "at dispatch",
            severity="warning",
            hint="raise the arbiter budget so the re-keyed buckets "
                 "stay resident, or materialise first (toarray) and "
                 "swap in memory"))
        return
    if not plan.resident and plan.spill_dir is None:
        diags.append(Diagnostic(
            "BLT017", idx,
            plan.describe() + " — but NO spill directory is "
            "configured: the executor will refuse this swap at "
            "dispatch rather than materialise silently",
            severity="warning",
            hint="wrap the run in bolt_tpu.stream.spill(dir=...) to "
                 "license disk spill, or raise the arbiter budget so "
                 "the re-keyed buckets stay resident"))
        return
    diags.append(Diagnostic(
        "BLT017", idx, plan.describe(),
        hint="phase 1 re-buckets each uploaded slab on device (one "
             "all-to-all per slab on pods) and %s; phase 2 streams "
             "the buckets through the standard slab machinery — "
             "bit-identical to the materialised swap "
             "(shuffle_bytes/spill_bytes engine counters)"
             % ("keeps them resident in HBM under the arbiter lease"
                if plan.resident
                else "spills them codec-encoded to the fingerprint "
                     "directory")))


def _check_predicate(pred, vshape, vdtype, idx, diags):
    """Abstractly trace a filter predicate over one value block and emit
    BLT001 (trace failure) / BLT007 (non-scalar per record) — the ONE
    predicate contract, shared by the deferred-filter and streaming-plan
    walks so their diagnostics cannot drift."""
    try:
        from bolt_tpu.tpu.array import _cached_eval_shape
        paval = _cached_eval_shape(
            ("filter", pred, tuple(vshape), str(np.dtype(vdtype))),
            lambda: jax.eval_shape(
                pred, jax.ShapeDtypeStruct(tuple(vshape),
                                           np.dtype(vdtype))))
    except Exception as exc:
        first = str(exc).splitlines()[0] if str(exc) else ""
        diags.append(Diagnostic(
            "BLT001", idx,
            "filter predicate %s fails abstract tracing: %s%s"
            % (_name(pred), type(exc).__name__,
               ": " + first if first else ""),
            hint="the predicate must trace over one value block"))
    else:
        if prod(tuple(getattr(paval, "shape", ()))) != 1:
            diags.append(Diagnostic(
                "BLT007", idx,
                "filter predicate %s returns shape %s per record; it "
                "must reduce each value block to ONE truth value"
                % (_name(pred), tuple(paval.shape)),
                hint="reduce inside the predicate, e.g. "
                     "lambda v: (v > 0).all()"))


def check(obj):
    """Abstractly interpret ``obj``'s recorded pipeline; returns a
    :class:`~bolt_tpu.analysis.diagnostics.Report`.

    Accepts a ``BoltArrayTPU``, a ``ChunkedArray``/``StackedArray`` view
    (checked through its underlying array), or a local array (trivial
    report).  Never compiles, dispatches, syncs a survivor count or
    resolves deferred state — ``engine.counters()`` is unchanged except
    for the ``diagnostics`` tally this check feeds.  Each check records
    an ``analysis.check`` span on the obs timeline (attributes: finding
    count, dynamic flag) — under ``analysis.strict()`` those spans sit
    inside the terminal's dispatch span, making the gate's cost
    visible."""
    with _obs.span("analysis.check") as sp:
        rep = _check_impl(obj)
        sp.set(diagnostics=len(rep.diagnostics),
               dynamic=bool(getattr(rep, "dynamic", False)))
        return rep


def _check_impl(obj):
    from bolt_tpu import engine
    from bolt_tpu.tpu.array import BoltArrayTPU

    target = "tpu"
    arr = obj
    # unwrap the thin views — their pipeline state IS the wrapped array's
    from bolt_tpu.tpu.chunk import ChunkedArray
    from bolt_tpu.tpu.stack import StackedArray
    if isinstance(arr, ChunkedArray):
        target = "tpu, chunked view plan=%s" % (arr.plan,)
        arr = arr._barray
    elif isinstance(arr, StackedArray):
        target = "tpu, stacked view size=%d" % arr.size
        arr = arr._barray

    if not isinstance(arr, BoltArrayTPU):
        # local oracle (or anything array-like): nothing deferred to check
        shape = tuple(np.shape(np.asarray(arr))) \
            if not hasattr(arr, "shape") else tuple(arr.shape)
        dtype = np.dtype(getattr(arr, "dtype", np.asarray(arr).dtype))
        rep = Report("local", [Stage(0, "base", shape, dtype,
                                     getattr(arr, "split", 0) or 0)], [])
        return rep

    diags = []
    stages = []

    if arr._donated:
        op = arr._donated if isinstance(arr._donated, str) \
            else "a donating terminal"
        diags.append(Diagnostic(
            "BLT005", -1,
            "this array's device buffer was donated to %s; every read "
            "path (toarray, reduce, map, ...) will raise" % op,
            hint="re-materialise from the source array, or disable the "
                 "policy with engine.donation(None) before the "
                 "consuming terminal"))
        # a donating PENDING terminal may still be joinable: further
        # stat calls ride the same group (one donate for N stats)
        _note_fusable(arr, -1, diags)
        rep = Report(target, stages, diags)
        engine.record_diagnostics(len(diags))
        return rep

    if arr._spending is not None:
        # a lazy stat result (bolt_tpu/tpu/multistat.py): report the
        # group's single-pass plan without dispatching anything
        rep = _check_spending(arr, target, stages, diags)
        engine.record_diagnostics(len(diags))
        return rep

    if arr._stream is not None:
        # streaming plan (bolt_tpu.stream): walk the recorded stage
        # chain abstractly — same _stage_apply bodies the per-slab
        # program traces, eval_shape only, ZERO XLA compiles
        _note_fusable(arr, 0, diags)
        rep = _check_stream(arr, target, stages, diags)
        engine.record_diagnostics(len(diags))
        return rep

    # donation forecast BEFORE binding any base/chain local (the
    # ownership test is refcount-based; an extra local would mask it)
    will_donate = _would_donate(arr)

    mesh = arr._mesh
    fp = arr._fpending
    pend = arr._pending
    idle_seen = False
    dynamic = False

    if fp is not None:
        base, funcs, pred, walk_split, vshape, n, vdtype = fp
    elif arr._chain is not None:
        base, funcs = arr._chain
        walk_split = arr._split
    elif pend is not None:
        padded, _cnt = pend
        shape = tuple(padded.shape)
        stages.append(Stage(0, "filter compaction (pending)", shape,
                            np.dtype(padded.dtype), 1,
                            _spec(mesh, shape, 1), dynamic=True,
                            note="survivor count not yet synced"))
        diags.append(Diagnostic(
            "BLT008", 0,
            "the result shape is dynamic: at most %d records survive; "
            "reading .shape syncs one scalar from device" % shape[0]))
        rep = Report(target, stages, diags, dynamic=True)
        engine.record_diagnostics(len(diags))
        return rep
    else:
        aval = arr._aval
        shape = tuple(aval.shape)
        stages.append(Stage(0, "base (concrete)", shape,
                            np.dtype(aval.dtype), arr._split,
                            _spec(mesh, shape, arr._split)))
        _idle_device_check(mesh, shape, arr._split, 0, diags, idle_seen)
        _note_fusable(arr, 0, diags)
        rep = Report(target, stages, diags)
        engine.record_diagnostics(len(diags))
        return rep

    # ---- stage 0: the chain base ------------------------------------
    if getattr(base, "is_deleted", lambda: False)():
        diags.append(Diagnostic(
            "BLT005", 0,
            "the chain base buffer has been deleted (donated to a "
            "swap(donate=True) or consumed by a donating terminal); "
            "materialising this pipeline will raise",
            hint="rebuild the pipeline from a live source array"))
        rep = Report(target, stages, diags)
        engine.record_diagnostics(len(diags))
        return rep

    aval = jax.ShapeDtypeStruct(tuple(base.shape), base.dtype)
    stages.append(Stage(0, "base", aval.shape, np.dtype(aval.dtype),
                        walk_split, _spec(mesh, aval.shape, walk_split)))
    idle_seen = _idle_device_check(mesh, aval.shape, walk_split, 0,
                                   diags, idle_seen)

    # ---- the deferred map chain, one abstract stage per func --------
    failed = False
    for i, func in enumerate(funcs):
        label = _func_label(func)
        try:
            nxt = _stage_eval(func, walk_split, aval)
        except Exception as exc:
            first = str(exc).splitlines()[0] if str(exc) else ""
            diags.append(Diagnostic(
                "BLT001", i + 1,
                "%s fails abstract tracing on input %s %s: %s%s"
                % (label, tuple(aval.shape), np.dtype(aval.dtype),
                   type(exc).__name__, ": " + first if first else ""),
                hint="the stage would fail identically at compile time; "
                     "fix the callable's shape/dtype contract"))
            failed = True
            break
        old, new = np.dtype(aval.dtype), np.dtype(nxt.dtype)
        if new.itemsize > old.itemsize:
            diags.append(Diagnostic(
                "BLT003", i + 1,
                "%s widens the pipeline dtype %s -> %s (the materialised "
                "result costs %dx the base's HBM)"
                % (label, old, new, new.itemsize // old.itemsize),
                hint="keep constants in the input dtype or cast back "
                     "with astype/map(dtype=...) if the widening is "
                     "unintended"))
        aval = nxt
        stages.append(Stage(i + 1, label, aval.shape, np.dtype(aval.dtype),
                            walk_split, _spec(mesh, aval.shape,
                                              walk_split)))
        idle_seen = _idle_device_check(mesh, aval.shape, walk_split,
                                       i + 1, diags, idle_seen)

    if not failed and fp is None:
        # the chain's recorded result aval must agree with the derived one
        rec = arr._aval
        if rec is not None and (tuple(rec.shape) != tuple(aval.shape)
                                or np.dtype(rec.dtype)
                                != np.dtype(aval.dtype)):
            diags.append(Diagnostic(
                "BLT002", len(funcs),
                "the recorded result aval %s %s contradicts what the "
                "chain actually produces (%s %s)"
                % (tuple(rec.shape), np.dtype(rec.dtype),
                   tuple(aval.shape), np.dtype(aval.dtype)),
                hint="a value_shape/dtype hint lied, or deferred state "
                     "was constructed by hand; trust the derived aval"))

    if not failed and fp is not None:
        # ---- the deferred filter: predicate + dynamic compaction ----
        pidx = len(funcs) + 1
        mapped_ok = (prod(aval.shape[:walk_split]) == n
                     and tuple(aval.shape[walk_split:]) == tuple(vshape)
                     and np.dtype(aval.dtype) == np.dtype(vdtype))
        if not mapped_ok:
            diags.append(Diagnostic(
                "BLT002", pidx,
                "the recorded filter state (n=%d, value shape %s, dtype "
                "%s) contradicts the mapped chain result %s %s"
                % (n, tuple(vshape), np.dtype(vdtype),
                   tuple(aval.shape), np.dtype(aval.dtype)),
                hint="deferred filter state was constructed by hand or "
                     "the chain drifted; rebuild via filter()"))
        _check_predicate(pred, vshape, vdtype, pidx, diags)
        out_shape = (n,) + tuple(vshape)
        stages.append(Stage(pidx, "filter(%s)" % _name(pred), out_shape,
                            np.dtype(vdtype), 1, _spec(mesh, out_shape, 1),
                            dynamic=True,
                            note="survivor count pending (<= %d)" % n))
        diags.append(Diagnostic(
            "BLT008", pidx,
            "the result shape is dynamic: at most %d records survive the "
            "predicate; reading .shape dispatches the fused compaction "
            "and syncs one scalar" % n))
        dynamic = True

    if not failed:
        _note_admission(
            int(base.nbytes)
            + prod(tuple(stages[-1].shape))
            * np.dtype(stages[-1].dtype).itemsize,
            len(stages) - 1, diags)

    if will_donate and not failed:
        nbytes = int(base.nbytes)
        diags.append(Diagnostic(
            "BLT006", len(stages) - 1,
            "the next dispatching terminal will DONATE the %d-byte chain "
            "base to XLA (sole owner, >= engine.donation_min_bytes()); "
            "this array serves exactly ONE terminal and then becomes "
            "unreadable" % nbytes,
            hint="hold another reference to the source array or scope "
                 "engine.donation(None) to keep it readable"))

    _note_fusable(arr, len(stages) - 1, diags)
    _note_batchable(arr, len(stages) - 1, diags)
    rep = Report(target, stages, diags, dynamic=dynamic)
    engine.record_diagnostics(len(diags))
    return rep


def _note_resumable(src, idx, diags):
    """``BLT011``: this streaming plan is checkpointed (a per-source
    ``checkpoint=`` dir or an active ``stream.resumable()`` scope) but
    its source is a ONE-SHOT iterator — the iterator dies with the
    process, so a killed run can never re-stream the surviving slabs:
    resume is impossible and every checkpoint write is wasted."""
    from bolt_tpu import stream as _stream
    scope = _stream.checkpoint_scope()
    ck_dir = src.ckpt if src.ckpt is not None else (
        scope[0] if scope is not None else None)
    if ck_dir is None or src.kind != "iter" or src.blocks is None:
        return
    if iter(src.blocks) is not src.blocks:
        return                      # re-iterable (a list of blocks): fine
    diags.append(Diagnostic(
        "BLT011", idx,
        "resumable checkpointing is armed (dir %r) but this fromiter "
        "source is a one-shot iterator: a killed run cannot re-stream "
        "it, so resume is impossible and the checkpoint is wasted"
        % ck_dir,
        hint="use fromcallback (random access) or pass a re-iterable "
             "block list so a restarted run can skip the already-"
             "retired slabs"))


def _stream_ckpt_dir(src):
    """The checkpoint dir a run over ``src`` would use (per-source
    ``checkpoint=`` wins over the thread's ``resumable()`` scope), or
    ``None``."""
    from bolt_tpu import stream as _stream
    if src.ckpt is not None:
        return src.ckpt
    scope = _stream.checkpoint_scope()
    return scope[0] if scope is not None else None


def _active_supervisor():
    """The installed recovery supervisor, probed through
    ``sys.modules`` so merely checking a pipeline never imports (or
    spins up) the supervision layer."""
    import sys
    sup = sys.modules.get("bolt_tpu.parallel.supervisor")
    if sup is None:
        return None
    return sup.active()


def _recovery_plan(src, nproc):
    """The pod fault-tolerance plan ``explain()`` renders for a
    multi-process stream: heartbeat cadence, watchdog deadline, the
    resume topology a peer loss would lead to (ISSUE 11), and — when a
    recovery supervisor is installed — the SUPERVISED contract: the
    backoff budget, the quarantine state, and the rejoin door
    (ISSUE 12)."""
    from bolt_tpu.parallel import podwatch as _pw
    cfg = _pw.config()
    if cfg.get("timeout"):
        hb = ("peer loss -> PeerLostError (heartbeat %.3gs, watchdog "
              "deadline %.3gs, %s transport)"
              % (cfg["interval"], cfg["timeout"], cfg["transport"]))
    else:
        hb = "watchdog OFF (BOLT_POD_TIMEOUT=0): peer loss may hang"
    ck_dir = _stream_ckpt_dir(src)
    if ck_dir is not None:
        resume = ("resume topology: reform to the survivors (<= %d "
                  "processes) and resume from %r" % (nproc - 1, ck_dir))
    else:
        resume = ("NO checkpoint dir: peer loss discards all partials "
                  "(BLT013)")
    plan = "recovery plan: %s; %s" % (hb, resume)
    sup = _active_supervisor()
    if sup is not None:
        scfg = sup.config()
        q = scfg.get("quarantine") or []
        plan += ("; SUPERVISED: auto-reform (%d retries, %.3gs "
                 "exponential backoff), rejoin door open via the %s "
                 "transport (quiesce at a slab-boundary checkpoint, "
                 "reform UP, resume bit-identically), quarantine %s"
                 % (scfg["retries"], scfg["backoff"], cfg["transport"],
                    sorted(q) if q else "empty"))
    return plan


def _note_pod_recovery(src, nproc, idx, diags):
    """``BLT013``: this pipeline streams across processes but has no
    recovery path — either no checkpoint dir is armed (a single peer
    loss discards every fold partial) or the mesh is SUB-POD (the
    checkpoint rendezvous covers the whole runtime, so resumable
    checkpointing is refused there)."""
    if nproc <= 1:
        return
    from bolt_tpu.parallel import multihost as _mh
    ck_dir = _stream_ckpt_dir(src)
    if ck_dir is None:
        diags.append(Diagnostic(
            "BLT013", idx,
            "this pipeline streams across %d processes with NO "
            "checkpoint dir: a single peer loss discards every fold "
            "partial and the whole run restarts from scratch "
            "(recovery impossible)" % nproc,
            hint="arm stream.resumable(dir) or fromcallback/fromiter "
                 "checkpoint=dir so the survivors can "
                 "multihost.reform() and resume from the last "
                 "rendezvous-consistent watermark"))
        return
    runtime = _mh.process_count()
    if runtime > 1 and nproc != runtime:
        diags.append(Diagnostic(
            "BLT013", idx,
            "this stream's mesh spans %d of the runtime's %d "
            "processes (a SUB-POD mesh): the checkpoint rendezvous "
            "barrier covers the whole runtime, so resumable "
            "checkpointing is refused and peer loss discards all "
            "partials" % (nproc, runtime),
            hint="stream the checkpointed run on a mesh covering "
                 "every process, or drop checkpoint=/resumable() for "
                 "this sub-mesh run"))


def _note_supervised_source(src, nproc, idx, diags):
    """``BLT014``: a recovery supervisor is installed (automatic
    re-expansion is armed — ``Server(supervise=True)`` or a standalone
    ``parallel.supervisor.Supervisor``), this pipeline streams across
    processes, but its source is a ``fromiter`` block iterable: only a
    per-process ``fromcallback`` loader (shared storage, global
    coordinates) lets a REJOINED replacement process re-ingest its
    shard of the remaining slabs, so the supervisor cannot grow the
    pod during this run — re-expansion waits for the next
    per-process-sourced stream."""
    if nproc <= 1 or src.kind != "iter":
        return
    if _active_supervisor() is None:
        return
    diags.append(Diagnostic(
        "BLT014", idx,
        "automatic re-expansion is armed (a recovery supervisor is "
        "installed) but this %d-process stream reads a fromiter block "
        "iterable: a REJOINED replacement process has no way to "
        "re-ingest its shard mid-run, so the supervisor cannot grow "
        "the pod during this stream" % nproc,
        hint="use fromcallback(..., per_process=True) with a shared-"
             "storage loader (any process can then produce any shard "
             "range), or accept that re-expansion defers to the next "
             "per-process-sourced run"))


def _check_stream(arr, target, stages, diags):
    """Abstractly interpret a STREAMING plan (a lazy ``fromcallback``/
    ``fromiter`` source plus its recorded device-side stages).  Nothing
    uploads, compiles or streams — each stage evaluates through the SAME
    ``stream._stage_apply`` body the per-slab executable traces."""
    from bolt_tpu import stream as _stream
    from bolt_tpu.parallel import multihost as _mh
    src = arr._stream
    mesh = arr._mesh
    walk_split = src.split
    nslabs = -(-src.shape[0] // src.slab) if src.shape[0] else 0
    aval = jax.ShapeDtypeStruct(tuple(src.shape), src.dtype)
    nproc = _mh.mesh_process_count(mesh)
    note = ("out-of-core: ~%d slabs of %d records, prefetch depth %d, "
            "uploader pool %d"
            % (nslabs, src.slab, _stream.prefetch_depth(),
               _stream.pool_size(src)))
    if nproc > 1:
        # the per-host plan (explain() shows it): each process produces
        # and uploads only its shard of every slab; the cross-host fold
        # is the slab program's mesh collective
        note += ("; MULTI-PROCESS: %d hosts x ~%d records/slab each "
                 "(per-process ingest, shard_map cross-host fold over "
                 "axes %s)"
                 % (nproc, src.slab // nproc,
                    _mh.key_collective_axes(mesh, src.shape,
                                            walk_split) or ("?",)))
        # the RECOVERY PLAN (ISSUE 11): what happens to this run when a
        # peer dies — heartbeat cadence, watchdog deadline, and the
        # topology a reform would resume on
        note += "; " + _recovery_plan(src, nproc)
    stages.append(Stage(
        0, "stream source (%s)" % src.kind, aval.shape,
        np.dtype(aval.dtype), walk_split,
        _spec(mesh, aval.shape, walk_split), note=note))
    if nproc > 1:
        # BLT012: a slab whose record extent does not divide the
        # key-axis device assignment has no per-process split — the
        # executor refuses it with this same message
        mh_err = _mh.slab_divisibility_error(
            mesh, src.shape, walk_split,
            src.slab_ranges() if src.kind == "callback" else [])
        if mh_err is not None:
            if mh_err.startswith("BLT012: "):
                mh_err = mh_err[len("BLT012: "):]
            diags.append(Diagnostic(
                "BLT012", 0, mh_err,
                hint="pick chunks= and key extents that are multiples "
                     "of the key-axis device assignment; uneven tails "
                     "cannot stream across processes"))
    _note_admission(_stream_slab_bytes(src), 0, diags)
    _note_codec(src, 0, diags)
    _note_resumable(src, 0, diags)
    _note_pod_recovery(src, nproc, 0, diags)
    _note_supervised_source(src, nproc, 0, diags)
    idle_seen = _idle_device_check(mesh, aval.shape, walk_split, 0, diags,
                                   False)
    dynamic = False
    for i, stage in enumerate(src.stages):
        idx = i + 1
        if stage[0] == "filter":
            pred = stage[1]
            n = prod(aval.shape[:walk_split])
            vshape = tuple(aval.shape[walk_split:])
            _check_predicate(pred, vshape, aval.dtype, idx, diags)
            out_shape = (n,) + vshape
            stages.append(Stage(idx, "filter(%s) [streamed]" % _name(pred),
                                out_shape, np.dtype(aval.dtype), 1,
                                _spec(mesh, out_shape, 1), dynamic=True,
                                note="survivor count pending (<= %d); "
                                     "streamed reductions fold the mask "
                                     "per slab" % n))
            diags.append(Diagnostic(
                "BLT008", idx,
                "the result shape is dynamic: at most %d records survive "
                "the predicate; streamed reduction terminals fold the "
                "mask without materialising, any other consumer "
                "materialises the whole source" % n))
            dynamic = True
            break
        label = "%s [streamed]" % _stream.stage_label(stage)
        try:
            nxt = _stream.stage_aval(stage, walk_split, aval)
        except Exception as exc:
            first = str(exc).splitlines()[0] if str(exc) else ""
            diags.append(Diagnostic(
                "BLT001", idx,
                "%s fails abstract tracing on input %s %s: %s%s"
                % (label, tuple(aval.shape), np.dtype(aval.dtype),
                   type(exc).__name__, ": " + first if first else ""),
                hint="the stage would fail identically inside the "
                     "per-slab program; fix the callable's shape/dtype "
                     "contract"))
            break
        if stage[0] == "swap":
            # the shuffle forecast anchors on the PRE-swap geometry
            # (the planner's input), then the walk adopts the swapped
            # split for every later stage
            _note_shuffle(src, stage, aval, walk_split, mesh, idx, diags)
            walk_split = stage[2]
        old, new = np.dtype(aval.dtype), np.dtype(nxt.dtype)
        if new.itemsize > old.itemsize:
            diags.append(Diagnostic(
                "BLT003", idx,
                "%s widens the pipeline dtype %s -> %s (every streamed "
                "slab costs %dx its upload size on device)"
                % (label, old, new, new.itemsize // old.itemsize),
                hint="keep constants in the input dtype or cast back "
                     "with map(dtype=...) if the widening is unintended"))
        aval = nxt
        stages.append(Stage(idx, label, aval.shape, np.dtype(aval.dtype),
                            walk_split, _spec(mesh, aval.shape,
                                              walk_split)))
        idle_seen = _idle_device_check(mesh, aval.shape, walk_split, idx,
                                       diags, idle_seen)
    return Report(target + ", streaming (out-of-core)", stages, diags,
                  dynamic=dynamic)


def explain(obj):
    """Human-readable per-stage rendering of :func:`check`'s report."""
    return str(check(obj))
