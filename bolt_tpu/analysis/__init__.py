"""bolt_tpu.analysis — static analysis for deferred pipelines and the repo.

Two halves:

* **Abstract pipeline checker** (:func:`check` / :func:`explain`): walk a
  ``BoltArrayTPU``'s deferred ``_chain``/``_pending``/``_fpending`` state
  and abstractly interpret it with ``jax.eval_shape``-style tracing —
  result shape, dtype and key sharding per stage, plus structured
  ``BLT0xx`` diagnostics (shape failures, aval lies, dtype widening,
  mesh-indivisible key splits, donation-safety violations) — with ZERO
  XLA compiles (``engine.counters()`` stays flat apart from the
  ``diagnostics`` tally the checker feeds).

      rep = bolt_tpu.analysis.check(b.map(f).filter(p))
      print(rep)                  # per-stage table + diagnostics
      rep.shape, rep.dtype        # the prediction a terminal will realise

* **Repo invariant linter** (:mod:`bolt_tpu.analysis.astlint`,
  ``scripts/lint_bolt.py``): AST rules ``BLT1xx`` enforcing the engine /
  ``_compat`` / ``_precision`` / donation-gate routing invariants;
  zero findings on ``bolt_tpu/`` itself is a tier-1 test.

:func:`strict` arms the engine's pre-dispatch gate: inside the scope,
every dispatching terminal (chain materialisation, ``reduce``, the stat
family, fused filters, ``chunk().map``, ``stacked().map``) first runs
the checker and REFUSES to dispatch — raising :class:`PipelineError`
before any compile — when error-severity findings exist::

    with bolt_tpu.analysis.strict():
        b.map(broken).sum()       # raises PipelineError, zero compiles
"""

import contextlib
import threading

from bolt_tpu import _lockdep
from bolt_tpu import engine as _engine
from bolt_tpu.analysis.diagnostics import (CODES, Diagnostic,
                                           PipelineError, Report, Stage)
from bolt_tpu.analysis.check import (admission_floor_bytes, check,
                                     explain, working_set_bytes)
from bolt_tpu.analysis import astlint

__all__ = ["check", "explain", "strict", "in_strict", "CODES",
           "Diagnostic", "Report", "Stage", "PipelineError", "astlint",
           "working_set_bytes", "admission_floor_bytes"]

_tls = threading.local()
_ACTIVE = 0                       # strict scopes alive across ALL threads
_ACTIVE_LOCK = _lockdep.lock("analysis.strict")


def in_strict():
    """True while the calling thread is inside a :func:`strict` scope."""
    return getattr(_tls, "depth", 0) > 0


def _strict_dispatch_guard(arr, op):
    """The engine's pre-dispatch gate (installed by :func:`strict`):
    check the array about to dispatch ``op``; refuse — BEFORE any
    compile — on error-severity findings.  Threads outside a strict
    scope pass through untouched (the scope is thread-local)."""
    if not in_strict():
        return
    _engine.strict_checked()
    rep = check(arr)
    if not rep.ok:
        _engine.strict_rejected()
        raise PipelineError(op, rep)


@contextlib.contextmanager
def strict():
    """Scope making the engine run :func:`check` before every
    dispatching terminal and refuse (``PipelineError``) on
    error-severity findings.  Nests; thread-local (concurrent threads
    outside the scope dispatch normally).  Engine counters account the
    gate: ``strict_checks`` runs, ``strict_rejections`` refusals,
    ``diagnostics`` findings."""
    global _ACTIVE
    _tls.depth = getattr(_tls, "depth", 0) + 1
    with _ACTIVE_LOCK:
        _ACTIVE += 1
        if _ACTIVE == 1:
            _engine.set_strict_guard(_strict_dispatch_guard)
    try:
        yield
    finally:
        _tls.depth -= 1
        with _ACTIVE_LOCK:
            _ACTIVE -= 1
            if _ACTIVE == 0:
                _engine.set_strict_guard(None)
