"""Constructors for the ``mode='local'`` backend.

Reference: ``bolt/local/construct.py :: ConstructLocal`` (symbol-level
citation, see SURVEY.md §0).
"""

import numpy as np

from bolt_tpu.local.array import BoltArrayLocal


class ConstructLocal:
    """Thin NumPy wrappers returning :class:`BoltArrayLocal`."""

    @staticmethod
    def _argcheck(*args, **kwargs):
        """The local backend is the dispatch fallback; it claims a call only
        when asked for by name (reference: ``bolt/local/construct.py ::
        ConstructLocal._argcheck``)."""
        return kwargs.get("mode") == "local"

    @staticmethod
    def array(a, dtype=None):
        return BoltArrayLocal(np.asarray(a, dtype=dtype))

    @staticmethod
    def ones(shape, dtype=None):
        return BoltArrayLocal(np.ones(shape, dtype=dtype))

    @staticmethod
    def zeros(shape, dtype=None):
        return BoltArrayLocal(np.zeros(shape, dtype=dtype))

    @staticmethod
    def full(shape, value, dtype=None):
        return BoltArrayLocal(np.full(shape, value, dtype=dtype))

    @staticmethod
    def _float_dtype(dtype):
        if dtype is not None and not np.issubdtype(np.dtype(dtype),
                                                   np.floating):
            # same contract as the TPU backend: truncating uniform [0, 1)
            # to int would silently return zeros
            raise ValueError("random constructors require a float dtype, "
                             "got %s" % np.dtype(dtype))
        return dtype

    @staticmethod
    def fromcallback(fn, shape, axis=(0,), dtype=None):
        """Local analog of the sharded loader: one callback call covering
        the whole array (a single 'shard').  ``axis`` gets the same
        key-axes-first treatment as the TPU backend, so a loader written
        against one backend serves the other unchanged."""
        from bolt_tpu.utils import inshape, tupleize
        shape = tuple(shape)
        axes = sorted(tupleize(axis))
        inshape(shape, axes)
        rest = [i for i in range(len(shape)) if i not in axes]
        shape = tuple(shape[i] for i in axes + rest)
        block = np.asarray(fn(tuple(slice(0, n) for n in shape)),
                           dtype=dtype)
        if block.shape != shape:
            raise ValueError("fromcallback callback returned shape %s "
                             "(expected %s)" % (block.shape, shape))
        return BoltArrayLocal(block)

    @staticmethod
    def fromiter(blocks, shape, axis=(0,), dtype=None):
        """Local analog of the streaming iterator constructor: blocks
        (key-axes-first layout, concatenated along the first key axis)
        are assembled into one host array.  ``dtype`` is required, like
        the TPU backend (and ``np.fromiter``)."""
        if dtype is None:
            raise ValueError(
                "fromiter requires an explicit dtype (blocks are consumed "
                "lazily, so the element type cannot be inferred up front)")
        from bolt_tpu.utils import inshape, iter_record_blocks, tupleize
        shape = tuple(shape)
        axes = sorted(tupleize(axis))
        inshape(shape, axes)
        rest = [i for i in range(len(shape)) if i not in axes]
        shape = tuple(shape[i] for i in axes + rest)
        out = np.empty(shape, dtype=dtype)
        for lo, hi, block in iter_record_blocks(blocks, shape, dtype):
            out[lo:hi] = block
        return BoltArrayLocal(out)

    @staticmethod
    def randn(shape, dtype=None, seed=0):
        """Standard-normal array (extension beyond the reference factory;
        RNG streams differ between backends by construction)."""
        dtype = ConstructLocal._float_dtype(dtype)
        # same seed normalization as the TPU backend: any Python int works
        x = np.random.default_rng(seed % (1 << 32)).standard_normal(shape)
        return BoltArrayLocal(x.astype(dtype) if dtype is not None else x)

    @staticmethod
    def rand(shape, dtype=None, seed=0):
        """Uniform [0, 1) array (extension beyond the reference factory)."""
        dtype = ConstructLocal._float_dtype(dtype)
        x = np.random.default_rng(seed % (1 << 32)).random(shape)
        return BoltArrayLocal(x.astype(dtype) if dtype is not None else x)

    @staticmethod
    def concatenate(arrays, axis=0):
        if not isinstance(arrays, (tuple, list)) or len(arrays) == 0:
            raise ValueError("concatenate requires a non-empty tuple of arrays")
        return BoltArrayLocal(np.concatenate([np.asarray(a) for a in arrays], axis))
