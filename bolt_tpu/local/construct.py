"""Constructors for the ``mode='local'`` backend.

Reference: ``bolt/local/construct.py :: ConstructLocal`` (symbol-level
citation, see SURVEY.md §0).
"""

import numpy as np

from bolt_tpu.local.array import BoltArrayLocal


class ConstructLocal:
    """Thin NumPy wrappers returning :class:`BoltArrayLocal`."""

    @staticmethod
    def _argcheck(*args, **kwargs):
        """The local backend is the dispatch fallback; it claims a call only
        when asked for by name (reference: ``bolt/local/construct.py ::
        ConstructLocal._argcheck``)."""
        return kwargs.get("mode") == "local"

    @staticmethod
    def array(a, dtype=None):
        return BoltArrayLocal(np.asarray(a, dtype=dtype))

    @staticmethod
    def ones(shape, dtype=None):
        return BoltArrayLocal(np.ones(shape, dtype=dtype))

    @staticmethod
    def zeros(shape, dtype=None):
        return BoltArrayLocal(np.zeros(shape, dtype=dtype))

    @staticmethod
    def _float_dtype(dtype):
        if dtype is not None and not np.issubdtype(np.dtype(dtype),
                                                   np.floating):
            # same contract as the TPU backend: truncating uniform [0, 1)
            # to int would silently return zeros
            raise ValueError("random constructors require a float dtype, "
                             "got %s" % np.dtype(dtype))
        return dtype

    @staticmethod
    def randn(shape, dtype=None, seed=0):
        """Standard-normal array (extension beyond the reference factory;
        RNG streams differ between backends by construction)."""
        dtype = ConstructLocal._float_dtype(dtype)
        x = np.random.default_rng(seed).standard_normal(shape)
        return BoltArrayLocal(x.astype(dtype) if dtype is not None else x)

    @staticmethod
    def rand(shape, dtype=None, seed=0):
        """Uniform [0, 1) array (extension beyond the reference factory)."""
        dtype = ConstructLocal._float_dtype(dtype)
        x = np.random.default_rng(seed).random(shape)
        return BoltArrayLocal(x.astype(dtype) if dtype is not None else x)

    @staticmethod
    def concatenate(arrays, axis=0):
        if not isinstance(arrays, (tuple, list)) or len(arrays) == 0:
            raise ValueError("concatenate requires a non-empty tuple of arrays")
        return BoltArrayLocal(np.concatenate([np.asarray(a) for a in arrays], axis))
