"""The ``mode='local'`` backend: a ``numpy.ndarray`` subclass.

This is the semantic oracle — every TPU-backend parity test compares against
this implementation (reference: ``bolt/local/array.py :: BoltArrayLocal``;
symbol-level citation, see SURVEY.md §0).
"""

from itertools import product as _product

import numpy as np

from bolt_tpu.base import BoltArray
from bolt_tpu.utils import inshape, prod, tupleize


class BoltArrayLocal(np.ndarray, BoltArray):
    """NumPy-backed bolt array.

    Being an ``ndarray`` subclass, it inherits the full NumPy operator and
    reduction surface (``+``, ``mean(axis=...)``, ``T``, slicing, …); the
    bolt-specific functional operators (``map``/``filter``/``reduce``) treat
    the ``axis`` argument as the key-axis set, exactly like the distributed
    backend (reference: ``bolt/local/array.py`` — ``__new__`` view-cast,
    functional ops via key-axes-to-front reshape).
    """

    _mode = "local"

    def __new__(cls, array):
        return np.asarray(array).view(cls)

    @property
    def mode(self):
        return self._mode

    @property
    def _constructor(self):
        from bolt_tpu.local.construct import ConstructLocal
        return ConstructLocal

    # ------------------------------------------------------------------
    # internal: move key axes to the front and flatten them
    # ------------------------------------------------------------------

    def _kv_reshape(self, axis):
        """Return ``(flat, key_shape, value_shape)`` where ``flat`` has shape
        ``(prod(key_shape), *value_shape)`` with key axes moved to the front.

        Reference: the reshape idiom inside
        ``bolt/local/array.py :: BoltArrayLocal.map``.
        """
        axes = sorted(tupleize(axis))
        inshape(self.shape, axes)
        rest = [i for i in range(self.ndim) if i not in axes]
        key_shape = tuple(self.shape[a] for a in axes)
        value_shape = tuple(self.shape[i] for i in rest)
        moved = np.transpose(np.asarray(self), axes + rest)
        flat = moved.reshape((prod(key_shape),) + value_shape)
        return flat, key_shape, value_shape

    # ------------------------------------------------------------------
    # functional operators
    # ------------------------------------------------------------------

    def map(self, func, axis=(0,), value_shape=None, dtype=None, with_keys=False):
        """Apply ``func`` to the value block at every key tuple.

        ``value_shape``/``dtype`` are accepted for cross-backend signature
        parity but are inferred from the results here.

        Reference: ``bolt/local/array.py :: BoltArrayLocal.map``.
        """
        flat, key_shape, _ = self._kv_reshape(axis)
        if with_keys:
            keys = _product(*[range(k) for k in key_shape])
            items = [func((k, v)) for k, v in zip(keys, flat)]
        else:
            items = [func(v) for v in flat]
        out = np.asarray(items)
        if dtype is not None:
            out = out.astype(dtype)
        return BoltArrayLocal(out.reshape(key_shape + out.shape[1:]))

    def filter(self, func, axis=(0,), sort=False):
        """Keep value blocks for which ``func`` is truthy; survivors are
        re-keyed to a flat ``(n,)`` key axis.

        Reference: ``bolt/local/array.py :: BoltArrayLocal.filter``.
        """
        flat, _, value_shape = self._kv_reshape(axis)
        items = [v for v in flat if func(v)]
        out = np.asarray(items)
        if len(items) == 0:
            out = out.reshape((0,) + value_shape)
        return BoltArrayLocal(out)

    def reduce(self, func, axis=(0,), keepdims=False):
        """Fixed-order pairwise tree combine of all value blocks with
        ``func`` — the SAME combine order as the distributed backend's
        compiled tree, so f32 ``reduce(add)`` is bit-exact across backends
        and non-associative reducers cannot silently diverge (the reference
        local backend uses a sequential left fold, but its Spark twin's
        ``rdd.treeReduce`` order is unspecified anyway — matching orders
        across OUR backends is the stronger contract; SURVEY §7 hard
        part 2).

        Reference: ``bolt/local/array.py :: BoltArrayLocal.reduce``.
        """
        flat, key_shape, value_shape = self._kv_reshape(axis)
        if flat.shape[0] == 0:
            raise TypeError("reduce of an empty array with no initial value")
        x = flat
        while x.shape[0] > 1:
            half = x.shape[0] // 2
            combined = np.asarray(
                [func(a, b) for a, b in zip(x[:half], x[half:2 * half])])
            rem = x[2 * half:]
            x = np.concatenate([combined, rem], axis=0) if rem.shape[0] \
                else combined
        out = np.asarray(x[0])
        if out.shape != value_shape:
            raise ValueError(
                "reduce produced shape %s, expected value shape %s"
                % (out.shape, value_shape))
        if keepdims:
            out = out.reshape((1,) * len(key_shape) + value_shape)
        return BoltArrayLocal(out)

    def stats(self, *requested, axis=None, accumulate=None, **kwargs):
        """Moment statistics over key axes, returned as a
        :class:`~bolt_tpu.statcounter.StatCounter` — the same contract the
        TPU backend serves via its shard_map Welford combine (reference:
        ``BoltArraySpark.stats`` via ``rdd.aggregate(StatCounter)``).

        ``axis=None`` means the leading axis, this backend's default key
        axis.

        The FLUENT form ``stats("sum", "var", "min", ...)`` mirrors the
        TPU backend's fused multi-stat (an ordered ``{name: array}``
        dict, any of sum/mean/var/std/min/max/prod/all/any/ptp) — here
        it is one NumPy pass per name, the semantic oracle the fused
        programs are parity-tested against.  ``accumulate`` is accepted
        for signature parity; the oracle always computes exactly."""
        if requested and all(isinstance(r, str) for r in requested):
            from collections import OrderedDict
            from bolt_tpu.tpu.multistat import LAZY_NAMES
            for n in requested:
                if n not in LAZY_NAMES:
                    raise ValueError(
                        "unknown statistic %r; choose from %s"
                        % (n, ", ".join(LAZY_NAMES)))
            axes = (0,) if axis is None else tuple(sorted(tupleize(axis)))
            x = np.asarray(self)
            out = OrderedDict()
            for n in requested:
                out[n] = BoltArrayLocal(getattr(np, n)(x, axis=axes))
            return out
        from bolt_tpu.statcounter import StatCounter
        if requested:
            # legacy positional form: stats(requested_tuple[, axis])
            if len(requested) > 2:
                raise TypeError("stats() takes at most 2 positional "
                                "arguments (requested, axis)")
            kwargs.setdefault("requested", requested[0])
            if len(requested) == 2:
                if axis is not None:
                    raise TypeError("stats() got axis twice")
                axis = requested[1]
        requested = kwargs.pop("requested",
                               ("mean", "var", "std", "min", "max"))
        if kwargs:
            raise TypeError("unexpected keyword arguments %r"
                            % sorted(kwargs))
        axes = (0,) if axis is None else tuple(sorted(tupleize(axis)))
        inshape(self.shape, axes)
        x = np.asarray(self)
        n = prod(tuple(self.shape[a] for a in axes))
        mu = x.mean(axis=axes, keepdims=True)
        m2 = ((x - mu) ** 2).sum(axis=axes)
        return StatCounter.from_moments(
            n, np.squeeze(mu, axis=axes), m2,
            minValue=x.min(axis=axes), maxValue=x.max(axis=axes),
            stats=requested)

    def ptp(self, axis=None, keepdims=False):
        """Peak-to-peak (max − min).  numpy ≥2 removed the ndarray method
        in favour of ``np.ptp``; this restores it with ndarray reduction
        conventions (``axis=None`` reduces everything), matching this
        backend's inherited mean/sum family."""
        return BoltArrayLocal(np.ptp(np.asarray(self), axis=axis,
                                     keepdims=keepdims))

    def quantile(self, q, axis=(0,), keepdims=False, method="linear"):
        """The ``q``-th quantile over ``axis`` (default: the leading axis,
        this backend's default key axis; ``None`` means the same, matching
        ``stats``).  ``q``: a scalar, or a 1-d array that prepends a q
        axis like ``np.quantile`` — matching the distributed backend;
        superset of the reference."""
        from bolt_tpu.utils import check_q
        qarr = check_q(q)
        axes = (0,) if axis is None else tuple(sorted(tupleize(axis)))
        inshape(self.shape, axes)
        return BoltArrayLocal(np.quantile(
            np.asarray(self), qarr if qarr.ndim else float(q), axis=axes,
            keepdims=keepdims, method=method))

    def median(self, axis=(0,), keepdims=False):
        """Median over ``axis`` (default: the leading axis)."""
        return self.quantile(0.5, axis=axis, keepdims=keepdims)

    # ------------------------------------------------------------------
    # chunked / stacked views (superset of the reference, which has them
    # only on the distributed backend — ``bolt/spark/chunk.py`` /
    # ``bolt/spark/stack.py``; here the same contract runs on NumPy so
    # mode-agnostic user code needs no SparkContext/mesh)
    # ------------------------------------------------------------------

    def chunk(self, size="150", axis=None, padding=None, key_axis=(0,)):
        """Decompose the value axes into chunks; returns a
        :class:`~bolt_tpu.local.chunk.LocalChunkedArray`.

        ``key_axis`` names this array's key axes (the distributed backend
        carries its split intrinsically; this backend, like its ``map``,
        takes the key-axis set per call) — they are moved to the front, and
        ``axis``/``size``/``padding`` address the remaining value axes
        exactly as on the TPU backend."""
        from bolt_tpu.local.chunk import LocalChunkedArray
        flat, key_shape, value_shape = self._kv_reshape(key_axis)
        data = flat.reshape(key_shape + value_shape)
        return LocalChunkedArray.chunk(data, len(key_shape), size=size,
                                       axis=axis, padding=padding)

    def stacked(self, size=1000, key_axis=(0,)):
        """Batch flat key records into blocks; returns a
        :class:`~bolt_tpu.local.stack.LocalStackedArray` (same contract as
        the TPU backend's compatibility view)."""
        from bolt_tpu.local.stack import LocalStackedArray
        flat, key_shape, value_shape = self._kv_reshape(key_axis)
        data = flat.reshape(key_shape + value_shape)
        return LocalStackedArray(data, len(key_shape), size)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------

    @staticmethod
    def _mixes_advanced(index):
        """True when ``index`` (a tuple) mixes advanced entries in a way
        where numpy's zipped convention diverges from this framework's
        orthogonal one: two or more advanced (list/ndarray) indices, or
        one advanced index with a scalar alongside (a scalar counts as a
        0-d advanced index to numpy, whose "separated advanced indices
        move to the front" rule would then diverge).  Shared by
        ``__getitem__`` and ``__setitem__`` so read and write semantics
        cannot desynchronize."""
        nadv = sum(1 for i in index
                   if isinstance(i, (list, np.ndarray))
                   and not (isinstance(i, np.ndarray) and i.ndim == 0))
        nscalar = sum(1 for i in index
                      if isinstance(i, (int, np.integer))
                      or (isinstance(i, np.ndarray) and i.ndim == 0
                          and i.dtype != bool))
        return nadv >= 2 or bool(nadv and nscalar)

    def __getitem__(self, index):
        """ndarray indexing, EXCEPT that two or more advanced (list /
        ndarray / boolean) indices apply orthogonally per axis (``np.ix_``
        semantics) — matching the distributed backend and the reconstructed
        reference's per-axis ``_getadvanced`` (``bolt/spark/array.py``),
        instead of numpy's zipped point-selection.  ``b[[0, 1], :, [0, 2]]``
        therefore returns the same shape on both backends (VERDICT r1
        weak-3).  Single advanced indices are identical under both
        conventions and delegate to numpy."""
        if not isinstance(index, tuple):
            # a lone index can never mix advanced entries: ndarray fast path
            return super().__getitem__(index)
        if not self._mixes_advanced(index):
            return super().__getitem__(index)
        from bolt_tpu.utils import normalize_index
        norm, squeezed = normalize_index(index, self.shape)
        out = np.asarray(self)[tuple(
            s if isinstance(s, slice) else slice(None) for s in norm)]
        for ax, s in enumerate(norm):
            if isinstance(s, np.ndarray):
                out = np.take(out, s, axis=ax)
        if squeezed:
            out = out.reshape(tuple(
                s for i, s in enumerate(out.shape) if i not in squeezed))
        return BoltArrayLocal(out)

    # ------------------------------------------------------------------
    # mutation (the distributed backend's device arrays are immutable;
    # ``set`` is the functional update both backends share, and this
    # backend's inherited in-place ``__setitem__`` is overridden only to
    # keep ≥2 advanced indices orthogonal, matching ``__getitem__``)
    # ------------------------------------------------------------------

    def set(self, index, value):
        """Functional indexed update: a NEW array equal to this one with
        ``self[index] = value`` applied — same indexing semantics as
        ``__getitem__`` (two or more advanced indices apply
        orthogonally); ``value`` broadcasts against the selected region
        and casts to this dtype (numpy assignment semantics).  Mirrors
        the distributed backend's method, where device arrays cannot be
        assigned in place."""
        from bolt_tpu.utils import assignment_index, normalize_index
        norm, squeezed = normalize_index(index, self.shape)
        out = np.array(self)
        out[assignment_index(norm, self.shape, squeezed)] = value
        return BoltArrayLocal(out)

    def __setitem__(self, index, value):
        """ndarray in-place assignment, EXCEPT that multiple-advanced
        (and scalar-plus-advanced) indices assign to the region
        ``__getitem__`` with the same index would read — the ORTHOGONAL
        per-axis cross product, dims in axis order — matching this
        backend's ``__getitem__`` and both backends' ``set`` (same
        rerouting condition as ``__getitem__``)."""
        if isinstance(index, tuple) and self._mixes_advanced(index):
            from bolt_tpu.utils import assignment_index, normalize_index
            norm, squeezed = normalize_index(index, self.shape)
            index = assignment_index(norm, self.shape, squeezed)
        return super().__setitem__(index, value)

    # ------------------------------------------------------------------
    # conversions
    # ------------------------------------------------------------------

    def first(self):
        """The value block at the first key (axis-0 record).

        Reference: ``bolt/local/array.py :: BoltArrayLocal.first``.
        """
        return np.asarray(self)[0]

    def concatenate(self, arry, axis=0):
        """Concatenate with another array along ``axis``.

        Reference: ``bolt/local/array.py :: BoltArrayLocal.concatenate``.
        """
        if isinstance(arry, BoltArray):
            arry = arry.toarray()
        return BoltArrayLocal(np.concatenate((np.asarray(self), np.asarray(arry)), axis))

    def toarray(self, out=None):
        if out is not None:
            BoltArray._check_out(out, self.shape, self.dtype)
            out[...] = np.asarray(self)
            return out
        return np.asarray(self)

    def iter_shards(self):
        """Single-shard analog of the distributed backend's
        :meth:`~bolt_tpu.tpu.array.BoltArrayTPU.iter_shards`: one
        ``(index, block)`` covering the whole array, so shard-walking
        code is mode-agnostic.  The block is a COPY, like the device
        backend's host fetches — mutating it never aliases the array."""
        yield (tuple(slice(0, d) for d in self.shape),
               np.array(np.asarray(self)))

    def tolocal(self):
        return self

    def tojax(self, context=None, axis=(0,)):
        """Distribute over ``context`` and unwrap to the sharded
        ``jax.Array`` (reference: ``bolt/local/array.py ::
        BoltArrayLocal.tordd(sc, axis)`` — distribute, then unwrap to the
        engine-native records)."""
        return self.totpu(context=context, axis=axis).tojax()

    def __repr__(self):
        return BoltArray.__repr__(self)
