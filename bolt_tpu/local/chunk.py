"""Local-backend chunking: the NumPy oracle for the chunk semantics.

The reference only has ``ChunkedArray`` on the distributed backend
(``bolt/spark/chunk.py``; symbol-level citation, SURVEY.md §0) — local users
had no way to run chunked code without a SparkContext.  This view closes
that asymmetry: the same ``chunk(size, axis, padding) → map → unchunk``
contract (plans, halo padding, ragged tails, ``keys_to_values`` /
``values_to_keys``) executes on plain NumPy, so mode-agnostic user code and
the parity tests have a local oracle for every chunked operation.

Unlike :class:`bolt_tpu.tpu.chunk.ChunkedArray` (a zero-copy plan over the
mesh-resident array), this implementation really materialises each block —
clarity over speed; it is the semantic reference, not a fast path.
"""

from itertools import product as _product

import numpy as np

from bolt_tpu.utils import (check_value_shape, chunk_align, chunk_pad,
                            chunk_plan, iterexpand, prod, tupleize)


class LocalChunkedArray:
    """A chunk view over a NumPy array whose leading ``split`` axes are
    keys.  Mirrors the TPU :class:`~bolt_tpu.tpu.chunk.ChunkedArray`
    surface (minus ``shard``, which needs a mesh)."""

    def __init__(self, data, split, plan, padding):
        self._data = np.asarray(data)
        self._split = int(split)
        self._plan = tuple(int(p) for p in plan)
        self._padding = tuple(int(p) for p in padding)

    @classmethod
    def chunk(cls, data, split, size="150", axis=None, padding=None):
        data = np.asarray(data)
        vshape = data.shape[split:]
        axes, size, padding = chunk_align(vshape, axis, size, padding)
        plan = chunk_plan(vshape, data.dtype.itemsize, size, axes,
                          padding=padding)
        pad = chunk_pad(plan, axes, padding, vshape)
        return cls(data, split, plan, pad)

    # ------------------------------------------------------------------
    # properties (same contract as the TPU view)
    # ------------------------------------------------------------------

    @property
    def plan(self):
        return self._plan

    @property
    def padding(self):
        return self._padding

    @property
    def kshape(self):
        return self._data.shape[:self._split]

    @property
    def vshape(self):
        return self._data.shape[self._split:]

    @property
    def shape(self):
        return self._data.shape

    @property
    def split(self):
        return self._split

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def mode(self):
        return "local"

    @property
    def grid(self):
        return tuple(-(-v // c) for v, c in zip(self.vshape, self._plan))

    @property
    def uniform(self):
        return all(v % c == 0 for v, c in zip(self.vshape, self._plan))

    # ------------------------------------------------------------------
    # per-block map
    # ------------------------------------------------------------------

    def map(self, func, value_shape=None, dtype=None):
        """Apply ``func`` to every chunk of every record.

        Same contract as the TPU view: with a uniform plan and no padding
        the block shape may change (rank-preserving); with padding or a
        ragged tail ``func`` must preserve the block shape so the halo can
        be trimmed and the tiles reassembled.
        """
        vshape = self.vshape
        nv = len(vshape)
        plan = self._plan
        pad = self._padding
        grid = self.grid
        shape_change_ok = self.uniform and not any(pad)
        flat = self._data.reshape((prod(self.kshape),) + vshape)

        def one_record(rec):
            cells = {}
            for gi in _product(*[range(g) for g in grid]):
                core0 = [gi[i] * plan[i] for i in range(nv)]
                core1 = [min(vshape[i], core0[i] + plan[i]) for i in range(nv)]
                lo = [max(0, core0[i] - pad[i]) for i in range(nv)]
                hi = [min(vshape[i], core1[i] + pad[i]) for i in range(nv)]
                blk = rec[tuple(slice(lo[i], hi[i]) for i in range(nv))]
                out = np.asarray(func(blk))
                if shape_change_ok:
                    if out.ndim != nv:
                        raise ValueError(
                            "chunked map must preserve block rank: block %s "
                            "-> %s" % (str(blk.shape), str(out.shape)))
                    cells[gi] = out
                else:
                    if out.shape != blk.shape:
                        raise ValueError(
                            "with padding or a ragged chunk plan, the mapped "
                            "function must preserve the block shape; got %s "
                            "-> %s" % (str(blk.shape), str(out.shape)))
                    cells[gi] = out[tuple(
                        slice(core0[i] - lo[i], core0[i] - lo[i]
                              + core1[i] - core0[i]) for i in range(nv))]

            def assemble(prefix, level):
                if level == nv:
                    return cells[tuple(prefix)]
                return np.concatenate(
                    [assemble(prefix + [i], level + 1)
                     for i in range(grid[level])], axis=level)
            return assemble([], 0)

        if flat.shape[0]:
            out = np.stack([one_record(rec) for rec in flat])
        else:
            # zero records: the empty result must still carry the value
            # shape func WOULD produce, inferred by running it on a zeros
            # probe (the TPU path uses eval_shape; this backend executes
            # func for real — silence the numeric warnings an all-zeros
            # block can trigger in funcs that divide/log their input)
            with np.errstate(all="ignore"):
                probe = one_record(np.zeros(vshape, self._data.dtype))
            out = np.zeros((0,) + probe.shape, probe.dtype)
        check_value_shape(value_shape, tuple(
            o // g for o, g in zip(out.shape[1:], grid)) if shape_change_ok
            else tuple(plan))
        if dtype is not None:
            out = out.astype(dtype)
        out = out.reshape(self.kshape + out.shape[1:])
        new_plan = (tuple(o // g for o, g in
                          zip(out.shape[self._split:], grid))
                    if shape_change_ok else plan)
        return LocalChunkedArray(out, self._split, new_plan, pad)

    # ------------------------------------------------------------------
    # axis exchange (same algebra as the TPU view / reference swap)
    # ------------------------------------------------------------------

    def keys_to_values(self, axes, size=None):
        """Move key axes into the values (landing at the FRONT of the value
        group, in the order given).  Moving every key axis is allowed; the
        result has ``split=0`` until ``values_to_keys`` restores keys."""
        axes = tuple(tupleize(axes))
        split = self._split
        for a in axes:
            if a < 0 or a >= split:
                raise ValueError(
                    "key axis %d out of range for split %d" % (a, split))
        if len(set(axes)) != len(axes):
            raise ValueError("keys_to_values axes must be unique")
        keys_rest = [k for k in range(split) if k not in axes]
        nv = len(self.vshape)
        perm = keys_rest + list(axes) + [split + v for v in range(nv)]
        data = np.transpose(self._data, perm)
        moved = [self._data.shape[a] for a in axes]
        if size is not None:
            sizes = iterexpand(size, len(moved))
            for s in sizes:
                if int(s) < 1:
                    raise ValueError(
                        "chunk size must be >= 1, got %d" % int(s))
            moved = [min(int(s), m) for s, m in zip(sizes, moved)]
        return LocalChunkedArray(
            data, len(keys_rest), tuple(moved) + self._plan,
            (0,) * len(axes) + self._padding)

    def values_to_keys(self, axes):
        """Move value axes into the keys (appended after the existing key
        axes, in the order given)."""
        axes = tuple(tupleize(axes))
        nv = len(self.vshape)
        for a in axes:
            if a < 0 or a >= nv:
                raise ValueError(
                    "value axis %d out of range for %d value axes" % (a, nv))
        if len(set(axes)) != len(axes):
            raise ValueError("values_to_keys axes must be unique")
        split = self._split
        keep = [i for i in range(nv) if i not in axes]
        perm = (list(range(split)) + [split + v for v in axes]
                + [split + v for v in keep])
        data = np.transpose(self._data, perm)
        return LocalChunkedArray(
            data, split + len(axes), tuple(self._plan[i] for i in keep),
            tuple(self._padding[i] for i in keep))

    # ------------------------------------------------------------------

    def unchunk(self):
        """Back to a :class:`~bolt_tpu.local.array.BoltArrayLocal` — the
        data never left its assembled layout."""
        from bolt_tpu.local.array import BoltArrayLocal
        return BoltArrayLocal(self._data)

    def __repr__(self):
        s = "ChunkedArray\n"
        s += "mode: local\n"
        s += "shape: %s\n" % str(self.shape)
        s += "split: %d\n" % self.split
        s += "plan: %s\n" % str(self._plan)
        s += "padding: %s\n" % str(self._padding)
        s += "grid: %s\n" % str(self.grid)
        return s
