"""Local-backend stacking: the NumPy oracle for the stacked semantics.

The reference's ``StackedArray`` exists only on the distributed backend
(``bolt/spark/stack.py``; symbol-level citation, SURVEY.md §0).  This view
closes the asymmetry the same way :mod:`bolt_tpu.local.chunk` does for
chunking: the same block-wise ``map`` contract (``func`` sees
``(n, *value_shape)`` and must preserve ``n``) on plain NumPy.
"""

import numpy as np

from bolt_tpu.utils import check_value_shape, prod


class LocalStackedArray:
    """A block-batched view over a NumPy array whose leading ``split`` axes
    are keys.  Mirrors :class:`~bolt_tpu.tpu.stack.StackedArray`."""

    def __init__(self, data, split, size):
        if int(size) < 1:
            raise ValueError("stack size must be >= 1, got %r" % (size,))
        self._data = np.asarray(data)
        self._split = int(split)
        self._size = int(size)

    @property
    def shape(self):
        return self._data.shape

    @property
    def split(self):
        return self._split

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def mode(self):
        return "local"

    @property
    def size(self):
        return self._size

    @property
    def nblocks(self):
        n = prod(self.shape[:self._split])
        return -(-n // self._size)

    def map(self, func, value_shape=None, dtype=None):
        """Apply ``func`` block-wise; record counts must be preserved so
        ``unstack`` can restore the key axes."""
        kshape = self.shape[:self._split]
        vshape = self.shape[self._split:]
        n = prod(kshape)
        flat = self._data.reshape((n,) + vshape)
        outs = []
        for i in range(0, n, self._size):
            blk = flat[i:i + self._size]
            out = np.asarray(func(blk))
            if out.ndim < 1 or out.shape[0] != blk.shape[0]:
                raise ValueError(
                    "stacked map must preserve the record count: block of "
                    "%d records -> %s"
                    % (blk.shape[0],
                       out.shape[0] if out.ndim >= 1 else "none"))
            outs.append(out)
        if outs:
            out = np.concatenate(outs, axis=0)
        else:
            # zero records: infer the output value shape func WOULD produce
            # (warnings silenced — an all-zeros probe block may divide/log)
            with np.errstate(all="ignore"):
                probe = np.asarray(func(np.zeros((self._size,) + vshape,
                                                 self._data.dtype)))
            out = np.zeros((0,) + probe.shape[1:], probe.dtype)
        check_value_shape(value_shape, tuple(out.shape[1:]))
        if dtype is not None:
            out = out.astype(dtype)
        return LocalStackedArray(out.reshape(kshape + out.shape[1:]),
                                 self._split, self._size)

    def unstack(self):
        """Back to a :class:`~bolt_tpu.local.array.BoltArrayLocal`."""
        from bolt_tpu.local.array import BoltArrayLocal
        return BoltArrayLocal(self._data)

    def __repr__(self):
        s = "StackedArray\n"
        s += "mode: local\n"
        s += "shape: %s\n" % str(self.shape)
        s += "split: %d\n" % self.split
        s += "size: %d\n" % self._size
        s += "nblocks: %d\n" % self.nblocks
        return s
