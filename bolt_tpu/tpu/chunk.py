"""Chunking: block decomposition of the value axes.

Reference: ``bolt/spark/chunk.py :: ChunkedArray`` — records re-keyed to
``((key-tuple, chunk-id-tuple), block)`` with a per-value-axis ``plan`` of
chunk sizes (MB budget or explicit), optional halo ``padding``, per-block
``map``, shuffle-based ``unchunk``, and the ``keys_to_values`` /
``values_to_keys`` axis-exchange primitives behind ``swap`` (symbol-level
citations, SURVEY.md §0).

TPU-native design: the underlying array already lives sharded on the mesh,
so a ``ChunkedArray`` is a **thin view** (the BASELINE north-star's words) —
``chunk()`` records a plan without moving a byte, ``unchunk()`` returns the
wrapped array, and only ``map`` launches a compiled program: the uniform
no-padding path reshapes value axes into (grid, block) pairs and nested-
``vmap``s the function over keys+grid (one fused SPMD launch); the general
path (ragged tails, halo padding) groups blocks by static clamp category
(≤4 per chunked axis), vmaps each category's dynamic-sliced padded blocks
through ``func`` per record, trims the halo, and reassembles with the same
recursive concatenate tree the reference's ``unchunk`` uses — all inside
one jit whose trace cost is independent of the grid size.
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from bolt_tpu import engine as _engine
from bolt_tpu import stream as _streamlib
from bolt_tpu.obs import trace as _obs
from bolt_tpu.parallel.sharding import combined_spec
from bolt_tpu.tpu.array import (BoltArrayTPU, _TRACE_ERRORS, _cached_jit,
                                _canon, _chain_apply, _chain_donate_ok,
                                _check_live, _check_value_shape, _constrain,
                                _traceable)
from bolt_tpu.utils import (chunk_align, chunk_pad, chunk_plan, iterexpand,
                            tupleize)


def _constrain_chunked(out, mesh, split, vshard):
    """Sharding constraint preserving explicit value-axis shards where the
    output shape still divides; key-only sharding otherwise."""
    if vshard:
        try:
            spec = combined_spec(mesh, out.shape, split, vshard)
            return jax.lax.with_sharding_constraint(
                out, NamedSharding(mesh, spec))
        except ValueError:
            pass
    return _constrain(out, mesh, split)


def _axis_categories(v, c, p, g):
    """Static clamp categories for a chunked axis of length ``v`` with
    chunk size ``c``, halo ``p`` and ``g`` blocks.  Every block in a
    category shares the same padded-slice size and trim, so a whole
    category maps under one vmap.  Categories (block indices):

    - ``g == 1``: the lone block (no halo possible beyond the edges);
    - otherwise: first (0), interior (1..g-3, halo never clips since
      ``p < c``), penultimate (g-2, its upper halo may clip into a short
      ragged tail), last (g-1, ragged tail, upper halo clipped at ``v``).

    Each dict: ``count`` blocks, padded slice start ``start0 + i*stride``
    of length ``size``, core region ``[t0, t1)`` within the slice.
    """
    if g == 1:
        return [dict(count=1, start0=0, stride=0, size=v, t0=0, t1=v)]
    cats = [dict(count=1, start0=0, stride=0, size=min(v, c + p),
                 t0=0, t1=c)]
    if g >= 3:
        if g > 3:
            cats.append(dict(count=g - 3, start0=c - p, stride=c,
                             size=c + 2 * p, t0=p, t1=p + c))
        pen0 = (g - 2) * c - p
        cats.append(dict(count=1, start0=pen0, stride=0,
                         size=min(v, (g - 1) * c + p) - pen0, t0=p, t1=p + c))
    hi0 = (g - 1) * c - p
    tail = v - (g - 1) * c
    cats.append(dict(count=1, start0=hi0, stride=0, size=v - hi0,
                     t0=p, t1=p + tail))
    return cats


def _uniform_map_body(data, func, split, plan, canon=None):
    """The uniform no-padding chunked-map program body: reshape the
    value axes into (grid, block) pairs, nested-vmap ``func`` over
    keys+grid, reassemble, optionally cast.  Geometry derives from
    ``data.shape``, so the SAME traced body serves the materialised
    whole-array program below AND the streaming executor's per-slab
    program (``bolt_tpu/stream.py``) — parity by construction."""
    kshape = data.shape[:split]
    vshape = data.shape[split:]
    nv = len(vshape)
    grid = tuple(v // c for v, c in zip(vshape, plan))
    newshape = kshape + tuple(
        x for v, c in zip(vshape, plan) for x in (v // c, c))
    r = data.reshape(newshape)
    g_axes = [split + 2 * i for i in range(nv)]
    c_axes = [split + 2 * i + 1 for i in range(nv)]
    r = jnp.transpose(
        r, tuple(range(split)) + tuple(g_axes) + tuple(c_axes))
    f = func
    for _ in range(split + nv):
        f = jax.vmap(f)
    out = f(r)
    ob = out.shape[split + nv:]
    if len(ob) != nv:
        raise ValueError(
            "chunked map must preserve block rank: block %s "
            "-> %s" % (str(tuple(plan)), str(tuple(ob))))
    perm = tuple(range(split)) + tuple(
        x for i in range(nv) for x in (split + i, split + nv + i))
    out = jnp.transpose(out, perm)
    merged = kshape + tuple(g * o for g, o in zip(grid, ob))
    out = out.reshape(merged)
    if canon is not None:
        out = out.astype(canon)
    return out


def _general_map_body(data, func, split, plan, pad, canon=None):
    """The general (ragged-tail / halo-padding) chunked-map program
    body — the ≤4-clamp-category dynamic-slice scheme described on
    :meth:`ChunkedArray.map`.  Like :func:`_uniform_map_body`, geometry
    derives from ``data.shape`` so the streaming per-slab program runs
    the identical trace."""
    kshape = data.shape[:split]
    vshape = data.shape[split:]
    nv = len(vshape)
    grid = tuple(-(-v // c) for v, c in zip(vshape, plan))
    axes_cats = [_axis_categories(vshape[i], plan[i], pad[i], grid[i])
                 for i in range(nv)]

    def group(sig):
        sizes = tuple(c["size"] for c in sig)

        def one(*idx):
            starts = [jnp.int32(0)] * split + [
                c["start0"] + idx[i] * c["stride"]
                for i, c in enumerate(sig)]
            blk = jax.lax.dynamic_slice(
                data, starts, kshape + sizes)
            f = func
            for _ in range(split):
                f = jax.vmap(f)
            out = f(blk)
            if out.shape != blk.shape:
                raise ValueError(
                    "with padding or a ragged chunk plan, the "
                    "mapped function must preserve the block "
                    "shape; got %s -> %s"
                    % (str(sizes), str(out.shape[split:])))
            trim = (slice(None),) * split + tuple(
                slice(c["t0"], c["t1"]) for c in sig)
            return out[trim]

        g_fn = one
        for i in reversed(range(nv)):
            in_axes = [None] * nv
            in_axes[i] = 0
            g_fn = jax.vmap(g_fn, in_axes=tuple(in_axes))
        res = g_fn(*(jnp.arange(c["count"], dtype=jnp.int32)
                     for c in sig))
        # (count_0..count_{nv-1}, *kshape, *trims) →
        # (*kshape, count_0*trim_0, ...)
        perm = tuple(range(nv, nv + split)) + tuple(
            x for i in range(nv) for x in (i, nv + split + i))
        res = jnp.transpose(res, perm)
        return res.reshape(kshape + tuple(
            c["count"] * (c["t1"] - c["t0"]) for c in sig))

    def assemble(prefix, level):
        if level == nv:
            return group(tuple(prefix))
        parts = [assemble(prefix + [c], level + 1)
                 for c in axes_cats[level] if c["count"] > 0]
        if len(parts) == 1:
            return parts[0]
        return jnp.concatenate(parts, axis=split + level)

    out = assemble([], 0)
    if canon is not None:
        out = out.astype(canon)
    return out


class ChunkedArray:
    """A chunk-plan view over a :class:`BoltArrayTPU`."""

    def __init__(self, barray, plan, padding, vshard=None):
        self._barray = barray
        self._plan = tuple(int(p) for p in plan)
        self._padding = tuple(int(p) for p in padding)
        # value-axis -> mesh-axis shards (sequence-parallel analog)
        self._vshard = dict(vshard) if vshard else {}

    # ------------------------------------------------------------------
    # construction (reference: ``ChunkedArray._chunk``)
    # ------------------------------------------------------------------

    @classmethod
    def chunk(cls, barray, size="150", axis=None, padding=None):
        """Compute the chunk ``plan``.

        ``size``: a string is a per-block megabyte budget (the reference's
        ``size='150'`` default) — the largest chunkable axis is halved until
        the block fits; an int/tuple gives explicit chunk sizes for the
        chosen ``axis`` set.  ``padding`` adds a halo (elements borrowed
        from neighbouring chunks, clipped at the array edge) on the chunked
        axes.
        """
        split = barray.split
        vshape = barray.shape[split:]
        axes, size, padding = chunk_align(vshape, axis, size, padding)
        plan = chunk_plan(vshape, barray.dtype.itemsize, size, axes,
                          padding=padding)
        pad = chunk_pad(plan, axes, padding, vshape)
        return cls(barray, plan, pad)

    # ------------------------------------------------------------------
    # properties (reference: ``ChunkedArray.plan/padding/kshape/vshape/
    # uniform``)
    # ------------------------------------------------------------------

    @property
    def plan(self):
        return self._plan

    @property
    def padding(self):
        return self._padding

    @property
    def kshape(self):
        b = self._barray
        return b.shape[:b.split]

    @property
    def vshape(self):
        b = self._barray
        return b.shape[b.split:]

    @property
    def shape(self):
        return self._barray.shape

    @property
    def split(self):
        return self._barray.split

    @property
    def dtype(self):
        return self._barray.dtype

    @property
    def mode(self):
        return "tpu"

    @property
    def grid(self):
        """Number of chunks along each value axis."""
        return tuple(-(-v // c) for v, c in zip(self.vshape, self._plan))

    @property
    def uniform(self):
        """True when every chunk has the same shape (no ragged tail)."""
        return all(v % c == 0 for v, c in zip(self.vshape, self._plan))

    @property
    def vshard(self):
        """Value-axis → mesh-axis shards (empty unless :meth:`shard`-ed)."""
        return dict(self._vshard)

    # ------------------------------------------------------------------
    # value-axis sharding: the sequence/context-parallel analog.  The
    # reference scales a too-long contiguous axis by chunking it over
    # workers (SURVEY §2.4 "block/chunk decomposition ... closest analog to
    # sequence parallelism"); here the axis is split across the mesh
    # itself, and padded per-block maps get their halos from GSPMD's
    # inserted neighbour collectives.
    # ------------------------------------------------------------------

    def shard(self, mesh_axis, axis=None):
        """Shard a chunked value axis across the (unused) mesh axis
        ``mesh_axis``.  ``axis`` defaults to the first chunked value axis.
        Returns a new :class:`ChunkedArray` whose underlying data is
        resharded (an ICI scatter, no host round-trip)."""
        b = self._barray
        if axis is None:
            chunked = [i for i, (v, c) in enumerate(zip(self.vshape, self._plan))
                       if c < v]
            axis = chunked[0] if chunked else 0
        vshard = dict(self._vshard)
        vshard[axis] = mesh_axis
        spec = combined_spec(b.mesh, b.shape, b.split, vshard)  # validates
        data = _streamlib.transfer(b._data, NamedSharding(b.mesh, spec))
        return ChunkedArray(BoltArrayTPU(data, b.split, b.mesh),
                            self._plan, self._padding, vshard)

    # ------------------------------------------------------------------
    # per-block map (reference: ``ChunkedArray.map`` with padding trim)
    # ------------------------------------------------------------------

    def map(self, func, value_shape=None, dtype=None):
        """Apply ``func`` to every chunk of every record; returns a new
        :class:`ChunkedArray`.

        With no padding and a uniform plan, ``func`` may change the block
        shape (rank-preserving — e.g. the per-chunk SVD of BASELINE config
        5); with padding or a ragged tail, ``func`` must preserve the block
        shape so the halo can be trimmed and the tiles reassembled.
        """
        func = _traceable(func)
        _engine.strict_guard(self._barray, "chunk().map()")
        hint_ob = None
        if value_shape is not None:
            # reference-parity hint: validate the per-block output shape
            # (reference ChunkedArray.map accepts the same hint to skip
            # its run-one-block inference)
            try:
                hint_ob = jax.eval_shape(func, jax.ShapeDtypeStruct(
                    tuple(self._plan), self._barray._aval.dtype))
            except _TRACE_ERRORS:
                # non-traceable func: skip hint validation (errors surface
                # at the real trace)
                hint_ob = None
            _check_value_shape(
                value_shape, None if hint_ob is None else tuple(hint_ob.shape))
        b = self._barray
        if b._stream is not None and not self._vshard:
            # streaming source (out-of-core): record the per-block map as
            # a device-side stage — nothing uploads or compiles until a
            # reduction terminal drives the double-buffered pipeline
            out = _streamlib.chunked_map_stage(self, func, dtype)
            if out is not NotImplemented:
                return out
        split = b.split
        mesh = b.mesh
        kshape = self.kshape
        vshape = self.vshape
        nv = len(vshape)
        plan = self._plan
        pad = self._padding
        grid = self.grid
        padded = any(p > 0 for p in pad)
        vshard = dict(self._vshard)
        vs_key = tuple(sorted(vshard.items()))
        # a deferred chain on the underlying array fuses INTO the chunked
        # program — no materialised intermediate between map and chunk.map;
        # a sole-owned chain base additionally DONATES its buffer to the
        # program (the chunked output is input-sized, so XLA aliases the
        # two — the chunk→map→unchunk pipeline's donation-aware terminal)
        donate = b.deferred and _chain_donate_ok(b._chain)
        base, funcs = b._chain_parts()
        canon = None if dtype is None else _canon(dtype)

        if self.uniform and not padded:
            # decide the OUTPUT's value sharding up front so the returned
            # metadata matches what the constraint actually applies: a
            # shape-changing block func can break divisibility, in which
            # case the axis really is re-replicated and we say so
            if vshard:
                keep = False
                try:
                    ob_shape = tuple(hint_ob.shape) if hint_ob is not None \
                        else tuple(jax.eval_shape(
                            func, jax.ShapeDtypeStruct(
                                tuple(plan), b._aval.dtype)).shape)
                except _TRACE_ERRORS:
                    ob_shape = None
                if ob_shape is not None and len(ob_shape) == nv:
                    out_full = kshape + tuple(
                        g * o for g, o in zip(grid, ob_shape))
                    try:
                        combined_spec(mesh, out_full, split, vshard)
                        keep = True
                    except ValueError:
                        pass
                if not keep:
                    # unverifiable or indivisible output: the constraint
                    # would fall back to key-only sharding, so the metadata
                    # must not claim otherwise
                    import warnings
                    warnings.warn(
                        "chunked map output does not (verifiably) divide the "
                        "mesh for value shard %s; the axis is now replicated"
                        % (vshard,))
                    vshard = {}
                    vs_key = ()

            def build():
                def run(data):
                    data = _chain_apply(funcs, split, data)
                    out = _uniform_map_body(data, func, split, plan, canon)
                    return _constrain_chunked(out, mesh, split, vshard)
                return jax.jit(run, donate_argnums=(0,) if donate else ())

            fn = _cached_jit(("chunk-map-u", func, funcs, base.shape,
                              str(base.dtype), split, plan, vs_key, canon,
                              donate, mesh), build)
            with _obs.span("chunk.map", path="uniform", donate=donate):
                out = fn(_check_live(base))
            if donate:
                b._consume_donated("chunk().map()")
            new_plan = tuple(o // g for o, g in zip(out.shape[split:], grid))
            return ChunkedArray(BoltArrayTPU(out, split, mesh), new_plan, pad,
                                vshard)

        # general path: ragged tails and/or halo padding.  Blocks along a
        # chunked axis fall into at most FOUR static clamp categories —
        # first (halo clipped below), interior, penultimate (halo may clip
        # into a short tail), last (ragged tail, halo clipped above) — so
        # each category product is one nested-vmapped dynamic_slice +
        # per-record func + static trim.  Trace cost is O(4^chunked_axes),
        # independent of the grid size (a 10k-chunk axis traces func the
        # same ≤4 times a 3-chunk axis does); the reference pays a record
        # per block here, we pay one compiled program.
        def build():
            def run(data):
                data = _chain_apply(funcs, split, data)
                out = _general_map_body(data, func, split, plan, pad, canon)
                return _constrain_chunked(out, mesh, split, vshard)
            return jax.jit(run, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("chunk-map-g", func, funcs, base.shape,
                          str(base.dtype), split, plan, pad, vs_key, canon,
                          donate, mesh), build)
        with _obs.span("chunk.map", path="general", donate=donate):
            out = fn(_check_live(base))
        if donate:
            b._consume_donated("chunk().map()")
        return ChunkedArray(BoltArrayTPU(out, split, mesh), plan, pad, vshard)

    # ------------------------------------------------------------------
    # axis exchange (reference: ``ChunkedArray.keys_to_values`` /
    # ``values_to_keys`` — the primitives behind ``swap``)
    # ------------------------------------------------------------------

    def keys_to_values(self, axes, size=None):
        """Move key axes into the values (they land at the FRONT of the
        value group in the order given, matching the swap algebra).  The
        data movement is the resharding inside ``swap`` — an ``all_to_all``
        over the mesh.  Moving EVERY key axis is allowed (the reference
        keeps blocks keyed by chunk ids); the result has ``split=0`` until
        ``values_to_keys`` restores key axes."""
        axes = tuple(tupleize(axes))
        split = self._barray.split
        for a in axes:
            if a < 0 or a >= split:
                raise ValueError(
                    "key axis %d out of range for split %d" % (a, split))
        if len(set(axes)) != len(axes):
            raise ValueError("keys_to_values axes must be unique")
        swapped = self._barray._do_swap(axes, ())
        moved = [self._barray.shape[a] for a in axes]
        if size is not None:
            sizes = iterexpand(size, len(moved))
            for s in sizes:
                if int(s) < 1:
                    raise ValueError(
                        "chunk size must be >= 1, got %d" % int(s))
            moved = [min(int(s), m) for s, m in zip(sizes, moved)]
        new_plan = tuple(moved) + self._plan
        new_pad = (0,) * len(moved) + self._padding
        # surviving value axes shift right by the number moved in
        new_vshard = {va + len(moved): name
                      for va, name in self._vshard.items()}
        return self._rewrap(swapped, new_plan, new_pad, new_vshard)

    def values_to_keys(self, axes):
        """Move value axes into the keys (appended after the existing key
        axes, matching the swap algebra)."""
        axes = tuple(tupleize(axes))
        nv = len(self.vshape)
        for a in axes:
            if a < 0 or a >= nv:
                raise ValueError(
                    "value axis %d out of range for %d value axes" % (a, nv))
        swapped = self._barray.swap((), axes)
        keep = [i for i in range(nv) if i not in axes]
        new_plan = tuple(self._plan[i] for i in keep)
        new_pad = tuple(self._padding[i] for i in keep)
        new_vshard = {pos: self._vshard[old]
                      for pos, old in enumerate(keep) if old in self._vshard}
        return self._rewrap(swapped, new_plan, new_pad, new_vshard)

    def _rewrap(self, barray, plan, padding, vshard):
        """Wrap a swapped underlying array, re-applying value-axis shards
        that survived the swap (the swap itself constrains to key-only
        sharding, which would silently re-replicate a long axis the user
        sharded to fit memory)."""
        if vshard:
            try:
                spec = combined_spec(barray.mesh, barray.shape, barray.split,
                                     vshard)
            except ValueError:
                import warnings
                warnings.warn(
                    "value-axis shard %s no longer divides after the axis "
                    "exchange; the axis is now replicated" % (vshard,))
                vshard = {}
            else:
                data = _streamlib.transfer(
                    barray._data, NamedSharding(barray.mesh, spec))
                barray = BoltArrayTPU(data, barray.split, barray.mesh)
        return ChunkedArray(barray, plan, padding, vshard)

    # ------------------------------------------------------------------

    def unchunk(self):
        """Back to a :class:`BoltArrayTPU` — a no-op unwrap: the data never
        left its assembled, mesh-resident layout (reference:
        ``ChunkedArray.unchunk`` pays a full shuffle here)."""
        return self._barray

    # ------------------------------------------------------------------
    # reduction terminals (ISSUE 3): the chunked view is thin, so these
    # delegate to the wrapped array's terminals — which means a chunked
    # view over a STREAMING source (a lazy ``fromcallback``/``fromiter``)
    # runs the out-of-core double-buffered executor
    # (``bolt_tpu/stream.py``), while a materialised view compiles the
    # standard fused programs.  One code path, two execution engines.
    # ------------------------------------------------------------------

    def sum(self, axis=None, keepdims=False):
        """Sum over ``axis`` (default: all key axes); streams when the
        underlying array is an out-of-core source."""
        return self._barray.sum(axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        """Mean over ``axis`` (default: all key axes); streamed means
        merge per-chunk Welford/statcounter moments on device."""
        return self._barray.mean(axis=axis, keepdims=keepdims)

    def var(self, axis=None, keepdims=False, ddof=0):
        """Variance over ``axis`` (``ddof`` like the array method)."""
        return self._barray.var(axis=axis, keepdims=keepdims, ddof=ddof)

    def std(self, axis=None, keepdims=False, ddof=0):
        """Standard deviation over ``axis``."""
        return self._barray.std(axis=axis, keepdims=keepdims, ddof=ddof)

    def reduce(self, func, axis=(0,), keepdims=False):
        """Pairwise-tree reduction over the key axes; streamed sources
        fold per-chunk partials with ``func`` on device."""
        return self._barray.reduce(func, axis=axis, keepdims=keepdims)

    def filter(self, func, axis=(0,), sort=False):
        """Filter records by a predicate — leaves the chunked view (the
        result is re-keyed flat, like the array method).  On a streaming
        source the predicate stays lazy and reduction terminals fold its
        mask into the per-chunk pass."""
        return self._barray.filter(func, axis=axis, sort=sort)

    def __repr__(self):
        s = "ChunkedArray\n"
        s += "mode: tpu\n"
        s += "shape: %s\n" % str(self.shape)
        s += "split: %d\n" % self.split
        s += "plan: %s\n" % str(self._plan)
        s += "padding: %s\n" % str(self._padding)
        s += "grid: %s\n" % str(self.grid)
        return s
