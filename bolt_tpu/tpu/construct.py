"""Constructors for the ``mode='tpu'`` backend.

Reference: ``bolt/spark/construct.py :: ConstructSpark`` (symbol-level
citation, SURVEY.md §0).  Where the reference moves key axes to the front,
flattens, enumerates key tuples and ``sc.parallelize``-s the records, this
backend builds (or places) ONE global ``jax.Array`` with the key sharding —
``ones``/``zeros`` are materialised *directly sharded on device* via a jitted
constant with ``out_shardings``, never on the host (SURVEY §3.1: a 10 GB
array is never resident in driver memory).
"""

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu.parallel import multihost as _multihost
from bolt_tpu.parallel.mesh import default_mesh, ensure_auto
from bolt_tpu.parallel.sharding import is_mesh, key_sharding
from bolt_tpu.utils import inshape, tupleize


class ConstructTPU:
    """Builds :class:`~bolt_tpu.tpu.array.BoltArrayTPU` instances."""

    @staticmethod
    def _argcheck(*args, **kwargs):
        """Claim the construction when a ``jax.sharding.Mesh`` appears as a
        positional arg or as ``context=``, or ``mode='tpu'`` is explicit
        (reference: ``ConstructSpark._argcheck`` detects a SparkContext)."""
        if kwargs.get("mode") == "tpu":
            return True
        if is_mesh(kwargs.get("context")):
            return True
        return any(is_mesh(a) for a in args)

    @staticmethod
    def _resolve(context):
        if context is None:
            return default_mesh()
        if not is_mesh(context):
            raise ValueError("context must be a jax.sharding.Mesh, got %r"
                             % (context,))
        return ensure_auto(context)

    @staticmethod
    def array(a, context=None, axis=(0,), dtype=None, npartitions=None):
        """Distribute an array-like with ``axis`` as the key axes.

        Key axes are moved to the front of the logical shape (the reference
        does the same before parallelizing: ``ConstructSpark._wrap``'s
        moveaxis+reshape).  ``npartitions`` is accepted for signature parity;
        the partition count is the mesh size.
        """
        from bolt_tpu.base import BoltArray
        from bolt_tpu.tpu.array import BoltArrayTPU
        mesh = ConstructTPU._resolve(context)
        axes = sorted(tupleize(axis))
        if len(axes) == 0:
            raise ValueError("at least one key axis is required")

        if isinstance(a, BoltArrayTPU):
            a = a._data
        elif isinstance(a, BoltArray):
            a = a.toarray()
        elif not isinstance(a, (np.ndarray, jax.Array)):
            # plain sequences (list/tuple/nested) need materializing before
            # the shape checks below
            a = np.asarray(a, dtype=dtype)

        inshape(a.shape, axes)
        rest = [i for i in range(a.ndim) if i not in axes]
        perm = axes + rest
        split = len(axes)
        multihost = _multihost.is_multiprocess(mesh)

        # device arrays stay on device: transpose/cast/reshard without a
        # host round-trip.  On a multi-host mesh this path also serves
        # global (non-fully-addressable) inputs, which CANNOT go to host;
        # a process-LOCAL device array there takes the host path below,
        # since device_put cannot scatter it across processes.
        if isinstance(a, jax.Array) and (not multihost
                                         or not a.is_fully_addressable):
            data = a if perm == list(range(a.ndim)) else jnp.transpose(a, perm)
            if dtype is not None:
                target = jax.dtypes.canonicalize_dtype(np.dtype(dtype))
                if target != data.dtype:
                    data = data.astype(target)
            from bolt_tpu import stream as _streamlib
            data = _streamlib.transfer(
                data, key_sharding(mesh, data.shape, split))
            return BoltArrayTPU(data, split, mesh)

        a = np.asarray(a, dtype=dtype)
        # canonicalise to what the backend holds (f64→f32 unless x64 is on):
        # explicit and silent, not warn-and-truncate
        a = a.astype(jax.dtypes.canonicalize_dtype(a.dtype))
        a = np.transpose(a, perm)
        sharding = key_sharding(mesh, a.shape, split)
        if multihost:
            # every process holds (or can produce) the full logical array;
            # each device picks out its own shard — the single-controller
            # construction path (SURVEY §7 hard part 6)
            data = jax.make_array_from_callback(
                a.shape, sharding, lambda idx: a[idx])
        else:
            # complex hosts upload as real/imag pairs — some attach
            # transports have no complex DMA and one failed transfer
            # poisons the session (see array._complex_safe_put)
            from bolt_tpu.tpu.array import _complex_safe_put
            data = _complex_safe_put(a, sharding)
        return BoltArrayTPU(data, split, mesh)

    @staticmethod
    def _device_build_spec(shape, context, axis, dtype):
        """Shared prologue for the build-directly-on-device constructors:
        ``(mesh, key-axes-first shape, split, canonical dtype, sharding)``
        — the key-axis permutation and dtype rules must stay identical
        across ``ones``/``zeros``/``rand``/``randn``."""
        mesh = ConstructTPU._resolve(context)
        shape = tupleize(shape)
        axes = sorted(tupleize(axis))
        if len(axes) == 0:
            raise ValueError("at least one key axis is required")
        inshape(shape, axes)
        rest = [i for i in range(len(shape)) if i not in axes]
        shape = tuple(shape[i] for i in axes + rest)
        if dtype is None:
            dtype = np.float64  # numpy's default, canonicalised below
        dtype = jax.dtypes.canonicalize_dtype(np.dtype(dtype))
        sharding = key_sharding(mesh, shape, len(axes))
        return mesh, shape, len(axes), dtype, sharding

    @staticmethod
    def _filled(fill, shape, context, axis, dtype):
        from bolt_tpu.tpu.array import BoltArrayTPU, _cached_jit
        mesh, shape, split, dtype, sharding = \
            ConstructTPU._device_build_spec(shape, context, axis, dtype)
        # engine-routed like every other program: repeated ones()/zeros()
        # of one geometry reuse ONE counted AOT executable.  Scalar fills
        # constant-fold into the program (key carries the value);
        # array-like fills — unhashable, so they cannot key — pass as a
        # broadcast ARGUMENT instead (key carries only their geometry,
        # and the cached closure pins no array memory).
        try:
            hash(fill)
            if fill != fill:
                # NaN: hashable but never equal to itself, so a raw key
                # would MISS (and insert) on every call — ride the
                # argument path, keyed on geometry only
                raise TypeError
        except TypeError:
            farr = np.asarray(fill)
            fn = _cached_jit(
                ("construct-full-arr", farr.shape, str(farr.dtype),
                 shape, str(dtype), sharding),
                lambda: jax.jit(lambda f: jnp.full(shape, f, dtype=dtype),
                                out_shardings=sharding))
            return BoltArrayTPU(fn(farr), split, mesh)
        fn = _cached_jit(
            ("construct-full", fill, shape, str(dtype), sharding),
            lambda: jax.jit(lambda: jnp.full(shape, fill, dtype=dtype),
                            out_shardings=sharding))
        return BoltArrayTPU(fn(), split, mesh)

    @staticmethod
    def _random(kind, shape, context, axis, dtype, seed):
        """Sharded random array, generated ON the devices: one jitted
        program with sharded output, so each device computes only its own
        shard's stream (threefry is counter-based/partitionable) and a
        10 GB random array never exists on the host — the same
        no-host-materialisation rule as ``ones``/``zeros``.  Extension
        beyond the reference factory (which has only
        array/ones/zeros/concatenate); RNG streams differ from the local
        backend's NumPy generator by construction."""
        from bolt_tpu.tpu.array import BoltArrayTPU, _cached_jit
        mesh, shape, split, dtype, sharding = \
            ConstructTPU._device_build_spec(shape, context, axis, dtype)
        if not jnp.issubdtype(dtype, jnp.floating):
            raise ValueError("random constructors require a float dtype, "
                             "got %s" % dtype)
        sampler = jax.random.normal if kind == "randn" else jax.random.uniform

        def builder():
            # seed is a traced argument: one compile per (kind, shape,
            # dtype, mesh), reused across seeds
            return jax.jit(
                lambda seed: sampler(jax.random.key(seed), shape,
                                     dtype=dtype),
                out_shardings=sharding)

        fn = _cached_jit(("construct-random", kind, shape, str(dtype), split,
                          mesh), builder)
        # normalize: any Python int works, matching the local backend
        return BoltArrayTPU(fn(jnp.uint32(seed % (1 << 32))), split, mesh)

    @staticmethod
    def fromcallback(fn, shape, context=None, axis=(0,), dtype=None,
                     chunks=None, checkpoint=None, per_process=False,
                     codec=None):
        """Build a distributed array by calling ``fn`` per index range —
        the sharded data-loader slot.

        ``fn(index)`` receives a tuple of per-axis ``slice`` objects
        covering one range of the KEY-AXES-FIRST logical ``shape`` and
        returns that block (anything ``np.asarray`` accepts: a memmap
        read, an HDF5/zarr slice, a computed tile).  The reference's
        analog is the driver-side ``sc.parallelize`` scatter
        (``bolt/spark/construct.py :: ConstructSpark.array``), which
        must materialise the full array at the driver first; here no
        full copy ever exists anywhere.

        With an EXPLICIT ``dtype`` (single-process) the result is a LAZY
        STREAMING source (ISSUE 3): nothing is produced or uploaded at
        construction.  Reduction terminals — directly or through a
        ``chunk()``/``stacked()`` view — stream the data slab-by-slab
        through the double-buffered out-of-core executor
        (:mod:`bolt_tpu.stream`), so datasets LARGER than device memory
        reduce in one pass; any other consumer materialises it with one
        callback call per device shard, exactly as before.  ``chunks``
        sets the records per streamed slab (default: a
        ``BOLT_STREAM_SLAB_BYTES`` budget, 64 MB).  ``dtype=None`` means
        "whatever the callback produces" and stays eager (the element
        type cannot be known without calling the loader).

        Note ``shape`` is interpreted key-axes-first (like
        ``ones``/``zeros``): ``axis`` names which of those axes are
        keys, and they are moved to the front before ``fn`` sees slices.

        ``per_process=True`` opts into the MULTI-PROCESS ingest
        contract (``bolt_tpu.parallel.multihost``): on a mesh spanning
        processes, each host's streaming executor invokes ``fn`` only
        for its own contiguous sub-range of each slab's leading key
        axis and uploads only that shard — the pod-scale streaming
        path, with the cross-host fold done by mesh-axis collectives
        inside the slab program.  ``fn`` must therefore serve any index
        range on any host (a shared filesystem / object-store reader).
        Single-process meshes accept the flag as a no-op (local range =
        the whole slab), so one loader runs unchanged from laptop to
        pod.

        ``codec=`` names an ingest codec (the ``bolt_tpu.tpu.codec``
        registry: ``"bf16"``/``"f16"``/``"int8"``/``"delta-f32"``):
        streamed runs over this source ENCODE each slab on the
        uploader workers and DECODE on device inside the slab program,
        shipping the wire bytes instead of the raw ones.  Wins over
        any ``stream.codec()`` scope; materialising consumers ignore
        it (they upload raw).  Lossy codecs are an explicit accuracy
        opt-in — see the codec module's contract table.
        """
        from bolt_tpu.tpu.array import BoltArrayTPU
        explicit = dtype is not None
        mesh, shape, split, dtype, sharding = \
            ConstructTPU._device_build_spec(shape, context, axis, dtype)
        multihost = _multihost.is_multiprocess(mesh)
        if per_process and not explicit:
            raise ValueError(
                "fromcallback(per_process=True) requires an explicit "
                "dtype: the per-process contract is a streaming plan, "
                "and streaming sources record their element type up "
                "front")
        if explicit and (not multihost or per_process):
            # lazy streaming source; materialisation (stream.materialize)
            # replays the per-shard upload below bit-identically.  On a
            # multi-process mesh this is the per_process=True contract:
            # the executor invokes fn per host, for that host's shard of
            # each slab only.
            from bolt_tpu import stream as _streamlib
            src = _streamlib.StreamSource.from_callback(
                fn, shape, split, dtype, mesh, chunks=chunks,
                checkpoint=checkpoint, codec=codec)
            return BoltArrayTPU._streamed(src)
        # dtype=None means "whatever the callback produces" (the loader
        # knows its storage dtype); an explicit dtype converts each block
        dtype = dtype if explicit else None

        def produce(index):
            block = np.asarray(fn(index), dtype=dtype)
            want = tuple(len(range(*s.indices(n)))
                         for s, n in zip(index, shape))
            if block.shape != want:
                raise ValueError(
                    "fromcallback callback returned shape %s for index %s "
                    "(expected %s)" % (block.shape, index, want))
            return block

        from bolt_tpu.obs.trace import clock as _clock
        t0 = _clock()
        data = jax.make_array_from_callback(shape, sharding, produce)
        from bolt_tpu import engine as _engine
        _engine.record_transfer(data.nbytes, _clock() - t0)
        return BoltArrayTPU(data, split, mesh)

    @staticmethod
    def fromiter(blocks, shape, context=None, axis=(0,), dtype=None,
                 checkpoint=None, codec=None):
        """Lazy streaming construction from an ITERABLE of consecutive
        record blocks — the sequential twin of :meth:`fromcallback` for
        sources that cannot random-access (a decompression stream, a
        database cursor, a generator).

        ``blocks`` yields arrays in KEY-AXES-FIRST layout, concatenated
        along the first key axis; together they must cover ``shape``
        exactly.  ``dtype`` is REQUIRED (``np.fromiter`` precedent —
        blocks are consumed lazily, so the element type cannot be
        inferred up front).  Reduction terminals stream the iterator
        once through the out-of-core executor; materialising consumers
        assemble it on host first (needs host RAM for the full array).

        On a MULTI-PROCESS mesh, RE-ITERABLE sources (a list of blocks,
        an object with a fresh ``__iter__``) stream under the
        per-process contract (``bolt_tpu.parallel.multihost``): every
        process iterates its own copy of the iterable, slices out its
        shard of each global block, and uploads only that — the
        cross-host fold runs as mesh-axis collectives in the slab
        program.  One-shot iterators (generators, cursors) are refused
        with a pointed error below.
        """
        from bolt_tpu.tpu.array import BoltArrayTPU
        if dtype is None:
            raise ValueError(
                "fromiter requires an explicit dtype (blocks are consumed "
                "lazily, so the element type cannot be inferred up front)")
        mesh, shape, split, dtype, _ = \
            ConstructTPU._device_build_spec(shape, context, axis, dtype)
        if _multihost.is_multiprocess(mesh) \
                and iter(blocks) is blocks:
            # the BLT011 reasoning, terminally: a one-shot iterator dies
            # with its process, so a killed run can never re-stream it
            # (resume impossible) — and on a pod EVERY process must walk
            # the block sequence to slice its own shard of each slab,
            # which a single-consumption cursor cannot survive either:
            # ingest is impossible too.
            raise ValueError(
                "fromiter on a multi-process mesh requires a RE-ITERABLE "
                "source (e.g. a list of blocks, or an object whose "
                "__iter__ starts fresh): each process iterates its own "
                "copy and uploads only its per-process shard of every "
                "slab (bolt_tpu.parallel.multihost contract).  A "
                "one-shot iterator cannot serve that — nor can a killed "
                "run ever resume from it (the BLT011 rule: the iterator "
                "dies with the process).  Use fromcallback("
                "per_process=True) for random-access loaders")
        from bolt_tpu import stream as _streamlib
        src = _streamlib.StreamSource.from_iter(blocks, shape, split,
                                                dtype, mesh,
                                                checkpoint=checkpoint,
                                                codec=codec)
        return BoltArrayTPU._streamed(src)

    @staticmethod
    def randn(shape, context=None, axis=(0,), dtype=None, seed=0):
        """Sharded standard-normal array, generated directly on device."""
        return ConstructTPU._random("randn", shape, context, axis, dtype, seed)

    @staticmethod
    def rand(shape, context=None, axis=(0,), dtype=None, seed=0):
        """Sharded uniform [0, 1) array, generated directly on device."""
        return ConstructTPU._random("rand", shape, context, axis, dtype, seed)

    @staticmethod
    def ones(shape, context=None, axis=(0,), dtype=None):
        """Sharded array of ones, built directly on device."""
        return ConstructTPU._filled(1, shape, context, axis, dtype)

    @staticmethod
    def zeros(shape, context=None, axis=(0,), dtype=None):
        """Sharded array of zeros, built directly on device."""
        return ConstructTPU._filled(0, shape, context, axis, dtype)

    @staticmethod
    def full(shape, value, context=None, axis=(0,), dtype=None):
        """Sharded array filled with ``value``, built directly on device.
        Like ``numpy.full``, the dtype defaults to the fill value's (so
        this entry point agrees with the local backend even when called
        directly, not just through the factory)."""
        if dtype is None:
            dtype = np.asarray(value).dtype
        return ConstructTPU._filled(value, shape, context, axis, dtype)

    @staticmethod
    def concatenate(arrays, axis=0, context=None):
        """Concatenate a sequence of arrays along ``axis`` into one
        distributed array (reference: ``ConstructSpark.concatenate``)."""
        if not isinstance(arrays, (tuple, list)) or len(arrays) == 0:
            raise ValueError("concatenate requires a non-empty tuple of arrays")
        from bolt_tpu.base import BoltArray
        from bolt_tpu.tpu.array import BoltArrayTPU
        first = arrays[0]
        if isinstance(first, BoltArrayTPU):
            out = first
            for other in arrays[1:]:
                out = out.concatenate(other, axis=axis)
            return out
        mats = [a.toarray() if isinstance(a, BoltArray) else np.asarray(a)
                for a in arrays]
        return ConstructTPU.array(np.concatenate(mats, axis), context=context)
