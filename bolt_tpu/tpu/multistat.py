"""One-pass multi-terminal statistics: the fused ``bolt.compute`` layer.

The single-terminal reductions are at the HBM roofline — a ``map→sum``
pass reads every byte once, and no further single-chip win exists for
ONE statistic.  What Bolt's design has always promised (PAPER.md: every
StatCounter moment from one pass over the values) is doing MORE per byte
read: this module makes ``a.sum()``-family terminals *lazy*
:class:`PendingStat` handles and groups handles that share a source —
the same deferred ``_chain``, the same deferred ``_fpending`` filter, or
the same out-of-core stream — into a :class:`_StatGroup` that dispatches
ONE tuple-output program::

    s, v, lo, hi = bolt.compute(a.sum(), a.var(), a.min(), a.max())
    # map/filter stages applied once, four partials from ONE HBM pass

Laziness is read-transparent: everything observable at call time stays
at call time (axis validation, the ``analysis.strict`` gate, the
donation decision — a sole-owned chain base is consumed by its FIRST
pending terminal and later siblings join the same group, so N fused
stats cost one donate), and only the engine dispatch moves to the first
read.  A handle read before any sibling exists resolves through the
EXACT standalone program — same engine key, same expressions — so a
lone ``a.sum()`` is byte-for-byte the pre-fusion terminal; a fused
group's outputs are bit-identical to those standalone terminals because
the tuple program traces the same per-terminal expressions over one
shared read (XLA's sibling multi-output fusion serves them from a
single traversal).

Grouping rule: same ``_chain``/``_fpending``/stream source ⇒ same
program; anything else falls back per group ("mixed chains fall back
per group").  ``ptp`` routes through the fused min/max pair — its slots
dedup against sibling ``min``/``max`` members, so
``compute(a.ptp(), a.min(), a.max())`` still emits exactly two extrema
from one pass (and ``a.ptp()`` alone shares the pair program's key
instead of owning a private one).

Reduced-precision accumulation (``compute(..., accumulate="bf16")`` or
the :func:`bolt_tpu._precision.accumulate` scope) is the opt-in fast
path for the additive terminals of an in-memory fused group: values
cast to bf16, accumulated in f32 (the accumulate-in-f32 contract; "f32"
casts values to f32, which for f32 pipelines is exactly the default
arithmetic); ``accumulate="int8"`` is the integer twin — int8 values,
int32 accumulator (accumulate-in-i32), integer additive terminals
(sum/prod) only, exact for values in int8 range.  The default
(``None``) stays bit-exact; order statistics (min/max/any/all, the
pair behind ptp) are always exact.

Streamed groups fold a tuple accumulator through the PR 5 pipeline
(``stream.execute(terminal="multi")``): one ingest pass feeds every
member, the shared ``(n, mu, M2)`` moments triple serves all of
mean/var/std, and Chan denominators stay exact on power-of-two slab
counts — streamed multi-stat matches materialised bit-exactly there.
"""

from collections import OrderedDict
import threading

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu import _lockdep
from bolt_tpu import engine as _engine
from bolt_tpu import _precision
from bolt_tpu import stream as _streamlib
from bolt_tpu.obs import trace as _obs
from bolt_tpu.utils import inshape, prod, tupleize


def _cached_jit(key, builder):
    """Engine-routed executable dispatch (same contract as the op
    modules')."""
    return _engine.get(key, builder)


# terminals that defer as PendingStat handles (everything _stat serves)
LAZY_NAMES = ("sum", "mean", "var", "std", "min", "max", "prod", "all",
              "any", "ptp")

# deferred-filter groups: min/max need the survivor-count sync (the
# zero-size error contract) and stay eager; ptp resolves the filter
_FPENDING_LAZY = ("sum", "prod", "any", "all", "mean", "var", "std")

# streamed groups: the accumulator components the slab programs emit
# (prod/all/any have no bit-exact streamed fold and materialise)
_STREAM_LAZY = ("sum", "mean", "var", "std", "min", "max", "ptp")

# accumulate= applies to the additive reductions only; order statistics
# are exact regardless.  The float modes (bf16/f32) serve the whole
# additive family; "int8" serves the INTEGER additive terminals — the
# moment family is float-valued and ignores it
_ADDITIVE = ("sum", "prod", "mean", "var", "std")
_INT_ADDITIVE = ("sum", "prod")

_OPS = {"mean": jnp.mean, "var": jnp.var, "std": jnp.std,
        "sum": jnp.sum, "max": jnp.max, "min": jnp.min,
        "prod": jnp.prod, "all": jnp.all, "any": jnp.any,
        "ptp": jnp.ptp}


class PendingStat:
    """One lazy stat terminal: the member record of a
    :class:`_StatGroup`.  Holds the normalised spec, the abstractly
    derived output aval, and (after the group dispatches) the concrete
    result the owning array adopts on first read."""

    __slots__ = ("group", "name", "axes", "keepdims", "ddof", "aval",
                 "new_split", "result")

    def __init__(self, group, name, axes, keepdims, ddof, aval,
                 new_split):
        self.group = group
        self.name = name
        self.axes = axes
        self.keepdims = bool(keepdims)
        self.ddof = ddof
        self.aval = aval
        self.new_split = int(new_split)
        self.result = None

    def __repr__(self):
        return "PendingStat(%s, axes=%s%s)" % (
            self.name, self.axes,
            ", resolved" if self.result is not None else "")


def _slot(member):
    """Program-output slot(s) one member needs — ``ptp`` expands to the
    min/max pair so its slots dedup against sibling extrema members."""
    if member.name == "ptp":
        return (("max", member.axes, member.keepdims, None),
                ("min", member.axes, member.keepdims, None))
    return ((member.name, member.axes, member.keepdims, member.ddof),)


class _StatGroup:
    """A set of pending stat terminals sharing ONE single-pass source.

    ``kind``:

    * ``"chain"``   — a deferred map chain (or a concrete base): the
      fused program applies the chain once and emits one reduction per
      slot.  ``donate`` was decided (with the standalone terminals'
      exact refcount test) when the FIRST handle was created; the
      consumed source keeps a pointer here so later siblings join the
      group — one donate for N stats.
    * ``"fpending"`` — a deferred filter: mapped chain + predicate mask
      traced once, every member folds the same mask.
    * ``"stream"``  — a lazy out-of-core source: one ingest pass through
      ``stream.execute(terminal="multi")`` feeds a tuple accumulator.
    """

    __slots__ = ("kind", "mesh", "split", "base", "funcs", "fpending",
                 "source", "donate", "in_aval", "members", "dispatched",
                 "lock", "rfunc", "claimed", "claim_event")

    def __init__(self, kind, mesh, split, base=None, funcs=(),
                 fpending=None, source=None, donate=False, in_aval=None):
        self.kind = kind
        self.mesh = mesh
        self.split = split
        self.base = base
        self.funcs = funcs
        self.fpending = fpending
        self.source = source
        self.donate = donate
        self.in_aval = in_aval
        self.members = []
        self.dispatched = False
        self.lock = _lockdep.lock("multistat.group")
        # a chain group carrying a deferred reduce(func) terminal
        # (bolt_tpu/tpu/batched.py's lazy door): singleton, never joined
        # by stat members — its standalone resolution is the EXACT eager
        # reduce program
        self.rfunc = None
        # serve micro-batching claim (bolt_tpu/tpu/batched.py): while a
        # batched dispatch owns this group, resolve() WAITS on the claim
        # event instead of dispatching standalone, and try_join declines
        # new members (they could never ride the already-shaped batch)
        self.claimed = False
        self.claim_event = None

    # -- joining -------------------------------------------------------

    def try_join(self, axis, name, keepdims, ddof):
        """Validate ``(axis, name, ...)`` against this group's kind and
        geometry; returns a new member handle, or NotImplemented when
        the spec cannot ride this group's fused program (the caller
        falls back to the eager path)."""
        if self.rfunc is not None:
            # a deferred-reduce group is singleton by contract: its one
            # slot is the reduce tree, which no stat member can share
            return NotImplemented
        if self.kind == "stream":
            h = _stream_member(self, name, axis, keepdims, ddof)
        elif self.kind == "fpending":
            h = _fpending_member(self, name, axis, keepdims, ddof)
        else:
            h = _chain_member(self, name, axis, keepdims, ddof)
        if h is not NotImplemented:
            with self.lock:
                if self.dispatched or self.claimed:
                    # a concurrent reader resolved the group (or a serve
                    # batched dispatch claimed it) between the caller's
                    # check and this append: the new member would never
                    # be filled — decline, the caller starts a fresh
                    # group / eager path
                    return NotImplemented
                self.members.append(h)
        return h

    # -- resolution ----------------------------------------------------

    def resolve(self, accumulate=None):
        """Dispatch the group's program(s), filling every member's
        ``result``.  Idempotent and thread-safe; ``accumulate`` is the
        per-call reduced-precision override (``bolt.compute``'s
        kwarg).  While a serve batched dispatch holds this group's
        CLAIM (bolt_tpu/tpu/batched.py), a concurrent reader waits for
        the batched fill (or the unclaim, after which it dispatches
        standalone) instead of double-dispatching."""
        while True:
            with self.lock:
                if self.dispatched:
                    return
                ev = self.claim_event if self.claimed else None
                if ev is None:
                    mode = _precision.resolve_accumulate(accumulate)
                    if mode is not None and self.rfunc is not None:
                        # reduce(func) IGNORES accumulate and runs
                        # exact, deferred or not — exactly what the
                        # eager path always did (compute(handle,
                        # accumulate=...) must not start raising just
                        # because a batching server armed the lazy
                        # door)
                        mode = None
                    elif mode is not None and self.kind != "chain":
                        if accumulate is not None:
                            raise ValueError(
                                "accumulate=%r applies to in-memory "
                                "fused reductions only; this group "
                                "streams/filters (%s) and runs exact"
                                % (accumulate, self.kind))
                        mode = None     # ambient scope: exact fallback
                    if self.rfunc is not None:
                        self._resolve_reduce()
                    elif self.kind == "chain":
                        self._resolve_chain(mode)
                    elif self.kind == "fpending":
                        self._resolve_fpending()
                    else:
                        self._resolve_stream()
                    self.dispatched = True
                    return
            # claimed by a serve batched dispatch on a worker thread:
            # wait for the fill/unclaim and re-check (the timeout only
            # bounds a claim owner dying without its unclaim finally)
            ev.wait(1.0)

    def _resolve_reduce(self):
        """Standalone resolution of a deferred ``reduce(func)`` handle:
        the EXACT eager reduce program — same engine key (donate=False,
        the lazy door refuses donating chains), same traced pairwise
        tree (`array._reduce_tree_expr`)."""
        from bolt_tpu.tpu.array import _check_live, _constrain, \
            _reduce_tree_expr
        m = self.members[0]
        func = self.rfunc
        base, funcs, split, mesh = (self.base, self.funcs, self.split,
                                    self.mesh)
        shape = tuple(self.in_aval.shape)
        n = prod(shape[:split])
        vshape = shape[split:]
        keepdims = m.keepdims

        def build():
            def reducer(data):
                out = _reduce_tree_expr(data, func, funcs, split, n,
                                        vshape, keepdims)
                return _constrain(out, mesh, m.new_split)
            return jax.jit(reducer)

        fn = _cached_jit(("reduce", func, funcs, base.shape,
                          str(base.dtype), split, keepdims, False, mesh),
                         build)
        with _obs.span("array.reduce", funcs=len(funcs), donate=False):
            m.result = fn(_check_live(base))

    def _resolve_chain(self, mode):
        from bolt_tpu.tpu.array import _check_live, _chain_apply, \
            _constrain
        members = self.members
        base, funcs, split, mesh = (self.base, self.funcs, self.split,
                                    self.mesh)
        donate = self.donate
        if len(members) == 1 and members[0].name != "ptp" and mode is None:
            # standalone resolution: the EXACT pre-fusion terminal —
            # same engine key, same traced expressions
            m = members[0]

            def build():
                op = _OPS[m.name]
                kwargs = {} if m.ddof is None else {"ddof": m.ddof}

                def stat(data):
                    mapped = _chain_apply(funcs, split, data)
                    out = op(mapped, axis=m.axes, keepdims=m.keepdims,
                             **kwargs)
                    return _constrain(out, mesh, m.new_split)
                return jax.jit(stat,
                               donate_argnums=(0,) if donate else ())

            fn = _cached_jit(("stat", m.name, funcs, base.shape,
                              str(base.dtype), split, m.axes, m.keepdims,
                              m.ddof, donate, mesh), build)
            with _obs.span("array.stat", op=m.name, funcs=len(funcs),
                           donate=donate):
                m.result = fn(_check_live(base))
            return

        # the fused multi-terminal program: one read, one slot per
        # distinct (name, axes, keepdims, ddof) — sorted for an
        # order-insensitive key, deduped so compute(ptp, min, max)
        # still emits exactly two extrema
        slots = sorted({s for m in members for s in _slot(m)}, key=repr)
        slots = tuple(slots)
        nsplit = {s: _new_split(split, s[1], s[2]) for s in slots}

        def build():
            def stat(data):
                outs = _chain_stat_exprs(data, funcs, split, slots, mode)
                return tuple(_constrain(o, mesh, nsplit[s])
                             for o, s in zip(outs, slots))
            return jax.jit(stat, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("multi-stat", slots, funcs, base.shape,
                          str(base.dtype), split, donate, mode, mesh),
                         build)
        with _obs.span("array.multi_stat", terminals=len(members),
                       slots=len(slots), funcs=len(funcs),
                       donate=donate, accumulate=mode or "exact"):
            outs = fn(_check_live(base))
        if len(members) > 1:
            _engine.record_fused_stats(len(members))
        index = {s: i for i, s in enumerate(slots)}
        for m in members:
            if m.name == "ptp":
                mx = outs[index[_slot(m)[0]]]
                mn = outs[index[_slot(m)[1]]]
                m.result = _sub_program(mx.shape, mx.dtype, mesh)(mx, mn)
            else:
                m.result = outs[index[_slot(m)[0]]]

    def _resolve_fpending(self):
        from bolt_tpu.tpu.array import _check_live, _chain_apply, \
            _constrain, _masked_stat_expr, _pred_mask
        members = self.members
        base, funcs, pred, psplit, vshape, n, vdtype = self.fpending
        mesh = self.mesh
        donate = self.donate
        if len(members) == 1:
            # standalone resolution: the exact filter-stat terminal of
            # the eager path (same key, same expressions; never
            # needs_count — min/max handles are not lazy here)
            m = members[0]

            def build():
                def stat(data):
                    mapped = _chain_apply(funcs, psplit, data)
                    flat = mapped.reshape((n,) + tuple(vshape))
                    mask = _pred_mask(pred, flat)
                    mfull = mask.reshape((n,) + (1,) * len(vshape))
                    out = _masked_stat_expr(
                        m.name, flat, mask, mfull, m.axes, m.keepdims,
                        m.ddof, vshape, vdtype)
                    return _constrain(out, mesh, m.new_split)
                return jax.jit(stat,
                               donate_argnums=(0,) if donate else ())

            fn = _cached_jit(("filter-stat", m.name, pred, funcs,
                              base.shape, str(base.dtype), psplit,
                              m.axes, m.keepdims, m.ddof, donate, mesh),
                             build)
            m.result = fn(_check_live(base))
            return

        slots = sorted({s for m in members for s in _slot(m)}, key=repr)
        slots = tuple(slots)

        def build():
            def stat(data):
                mapped = _chain_apply(funcs, psplit, data)
                flat = mapped.reshape((n,) + tuple(vshape))
                mask = _pred_mask(pred, flat)
                mfull = mask.reshape((n,) + (1,) * len(vshape))
                outs = []
                for (name, axes, keepdims, ddof) in slots:
                    outs.append(_constrain(
                        _masked_stat_expr(name, flat, mask, mfull, axes,
                                          keepdims, ddof, vshape,
                                          vdtype),
                        mesh, 1 if keepdims else 0))
                return tuple(outs)
            return jax.jit(stat, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("multi-filter-stat", slots, pred, funcs,
                          base.shape, str(base.dtype), psplit, donate,
                          mesh), build)
        with _obs.span("array.multi_stat", terminals=len(members),
                       slots=len(slots), filtered=True, donate=donate):
            outs = fn(_check_live(base))
        _engine.record_fused_stats(len(members))
        index = {s: i for i, s in enumerate(slots)}
        for m in members:
            m.result = outs[index[_slot(m)[0]]]

    def _resolve_stream(self):
        members = self.members
        if (len(members) == 1
                and members[0].name in ("sum", "mean", "var", "std")):
            # standalone resolution: the exact pre-fusion streamed
            # terminal (same slab/merge/finalise programs and keys)
            m = members[0]
            out = _streamlib.execute(None, m.name, ddof=m.ddof,
                                     source=self.source)
            m.result = out.tojax()
            return
        specs = tuple((m.name, m.ddof) for m in members)
        outs = _streamlib.execute(None, "multi", specs=specs,
                                  source=self.source)
        if len(members) > 1:
            _engine.record_fused_stats(len(members))
        for m, out in zip(members, outs):
            m.result = out


def _new_split(split, axes, keepdims):
    nkeys = sum(1 for a in axes if a < split)
    return split if keepdims else split - nkeys


def _chain_stat_exprs(data, funcs, split, slots, mode):
    """The UNCONSTRAINED per-slot reduction expressions over one chain
    input — the shared body of the fused multi-stat program above AND
    the serve layer's batched (vmapped) program
    (``bolt_tpu/tpu/batched.py``): one traced arithmetic, so a batched
    lane computes bit-identically to its standalone dispatch.  The
    caller applies the per-slot sharding constraint."""
    from bolt_tpu.tpu.array import _chain_apply
    mapped = _chain_apply(funcs, split, data)
    return tuple(_stat_expr(mapped, name, axes, keepdims, ddof, mode)
                 for (name, axes, keepdims, ddof) in slots)


def _stat_expr(mapped, name, axes, keepdims, ddof, mode):
    """The per-terminal reduction expression of the fused program —
    with ``mode=None`` exactly the standalone terminal's expression
    (bit-identity of fused vs standalone is parity-locked in
    tests/test_multistat.py); ``mode`` casts the ADDITIVE terminals'
    values ("bf16" accumulates in f32 — the accumulate-in-f32 contract;
    "f32" is exact for f32 pipelines) and leaves order statistics
    untouched."""
    op = _OPS[name]
    kwargs = {} if ddof is None else {"ddof": ddof}
    if mode == "int8":
        # the integer twin of bf16: int8 values, int32 accumulator (the
        # accumulate-in-i32 contract) — integer additive terminals of
        # integer pipelines only; everything else stays exact
        if name in _INT_ADDITIVE \
                and jnp.issubdtype(mapped.dtype, jnp.integer):
            return op(mapped.astype(jnp.int8), axis=axes,
                      dtype=jnp.int32, keepdims=keepdims, **kwargs)
    elif mode is not None and name in _ADDITIVE \
            and jnp.issubdtype(mapped.dtype, jnp.floating):
        if mode == "bf16":
            return op(mapped.astype(jnp.bfloat16), axis=axes,
                      dtype=jnp.float32, keepdims=keepdims, **kwargs)
        return op(mapped.astype(jnp.float32), axis=axes,
                  keepdims=keepdims, **kwargs)
    return op(mapped, axis=axes, keepdims=keepdims, **kwargs)


def _sub_program(shape, dtype, mesh):
    """``max − min`` for a ``ptp`` member — exactly ``jnp.ptp``'s own
    arithmetic, as one tiny cached program shared by every ptp of this
    geometry."""
    key = ("multi-stat-sub", tuple(shape), str(dtype), mesh)

    def build():
        return jax.jit(jnp.subtract)
    return _cached_jit(key, build)


# ---------------------------------------------------------------------
# handle creation (the lazy door _stat calls first)
# ---------------------------------------------------------------------

def defer_stat(arr, axis, name, keepdims, ddof):
    """Create (or join) a lazy :class:`PendingStat` for ``arr``'s
    ``name`` terminal; returns the pending result array, or
    NotImplemented when this spec must take the eager path (non-lazy
    name, consumed source without a live group, a geometry the fused
    machinery does not serve)."""
    if name not in LAZY_NAMES:
        return NotImplemented
    g = arr._stat_group
    if g is not None and g.dispatched:
        g = arr._stat_group = None
    if g is not None and not arr._donated and (
            (g.kind == "stream" and arr._stream is None)
            or (g.kind == "fpending" and arr._fpending is None)
            or (g.kind == "chain" and g.funcs and arr._chain is None)):
        # the source materialised since the group formed: new terminals
        # must compute from the CONCRETE data, not re-run the recorded
        # chain/filter/stream (a one-shot iterator could not stream
        # again anyway, and re-applying a map chain would silently
        # double the one-pass cost model); the old group's own members
        # still resolve from their recorded source.  Donated sources
        # have no other state — they keep joining their group.
        g = None
    if g is not None:
        h = g.try_join(axis, name, keepdims, ddof)
        if h is not NotImplemented:
            return _wrap(arr, g, h)
        if arr._donated:
            return NotImplemented     # consumed; eager path raises guard
        # live source, spec ineligible for the existing group: eager
        return NotImplemented
    if arr._donated:
        return NotImplemented
    g = _new_group(arr, axis, name, keepdims, ddof)
    if g is NotImplemented:
        return NotImplemented
    arr._stat_group = g
    return _wrap(arr, g, g.members[0])


def _wrap(arr, group, handle):
    from bolt_tpu.tpu.array import BoltArrayTPU
    out = BoltArrayTPU(None, handle.new_split, group.mesh)
    out._aval = handle.aval
    out._spending = handle
    return out


def _new_group(arr, axis, name, keepdims, ddof):
    from bolt_tpu.tpu.array import _chain_donate_ok
    mesh = arr._mesh
    if arr._stream is not None and _streamlib.has_swap(arr._stream):
        # a recorded swap resolves BEFORE the group forms (ISSUE 18):
        # the two-phase shuffle re-seats the array on a swap-free
        # source (or on concrete data if the shuffle fell back to
        # materialise), and the group machinery below sees only
        # geometry it already serves
        _streamlib._swap_resolved(arr)
    if arr._stream is not None:
        g = _StatGroup("stream", mesh, arr._stream.split,
                       source=arr._stream)
        if g.try_join(axis, name, keepdims, ddof) is NotImplemented:
            return NotImplemented
        return g
    if arr._fpending is not None:
        donate = _chain_donate_ok(arr._fpending)     # [0] is the base
        g = _StatGroup("fpending", mesh, 1, fpending=arr._fpending,
                       donate=donate)
        if g.try_join(axis, name, keepdims, ddof) is NotImplemented:
            return NotImplemented
        if donate:
            # today's semantics, kept eager: the first donating
            # terminal consumes the source; siblings join THIS group
            # (one donate serves every member)
            arr._consume_donated("filter().%s()" % name)
        return g
    # standard chain / concrete base.  The donation decision runs with
    # the standalone terminals' exact reference pattern (attribute
    # access straight into the call — the ownership test is
    # refcount-based)
    donate = arr.deferred and _chain_donate_ok(arr._chain)
    base, funcs = arr._chain_parts()
    g = _StatGroup("chain", mesh, arr._split, base=base, funcs=funcs,
                   donate=donate,
                   in_aval=jax.ShapeDtypeStruct(tuple(arr._aval.shape),
                                                arr._aval.dtype))
    if g.try_join(axis, name, keepdims, ddof) is NotImplemented:
        return NotImplemented
    if donate:
        arr._consume_donated("%s()" % name)
    return g


def _chain_member(g, name, axis, keepdims, ddof):
    from bolt_tpu.tpu.array import _cached_eval_shape
    shape = tuple(g.in_aval.shape)
    split = g.split
    if axis is None:
        axes = tuple(range(split)) if split else tuple(range(len(shape)))
    else:
        axes = tuple(sorted(tupleize(axis)))
        inshape(shape, axes)
    if name in ("min", "max", "ptp") \
            and prod([shape[a] for a in axes]) == 0:
        return NotImplemented          # zero-size: eager raise contract
    kwargs = {} if ddof is None else {"ddof": ddof}
    aval = _cached_eval_shape(
        ("stat-aval", name, shape, str(g.in_aval.dtype), axes, keepdims,
         ddof),
        lambda: jax.eval_shape(
            lambda x: _OPS[name](x, axis=axes, keepdims=keepdims,
                                 **kwargs), g.in_aval))
    return PendingStat(g, name, axes, keepdims, ddof, aval,
                       _new_split(split, axes, keepdims))


def _fpending_member(g, name, axis, keepdims, ddof):
    _, _, _, _, vshape, n, vdtype = g.fpending
    if name not in _FPENDING_LAZY:
        return NotImplemented
    ndim = 1 + len(vshape)
    if axis is None:
        axes = (0,)                    # the flat key axis (split=1)
    else:
        axes = tuple(sorted(tupleize(axis)))
        for a in axes:
            if not 0 <= a < ndim:
                return NotImplemented  # let the eager path reject
    if 0 not in axes:
        return NotImplemented
    vdtype = np.dtype(vdtype)
    if name in ("var", "std") and np.issubdtype(vdtype,
                                                np.complexfloating):
        return NotImplemented
    ref = _OPS[name]
    kwargs = {} if ddof is None else {"ddof": ddof}
    aval = jax.eval_shape(
        lambda x: ref(x, axis=axes, keepdims=keepdims, **kwargs),
        jax.ShapeDtypeStruct((n,) + tuple(vshape), vdtype))
    return PendingStat(g, name, axes, keepdims, ddof, aval,
                       1 if keepdims else 0)


def _stream_member(g, name, axis, keepdims, ddof):
    st = _streamlib.result_state(g.source)
    if name not in _STREAM_LAZY or keepdims or st.n == 0:
        return NotImplemented
    if axis is not None:
        if tuple(sorted(tupleize(axis))) != tuple(range(st.split)):
            return NotImplemented
    if st.pred is not None and name in ("min", "max", "ptp"):
        # zero survivors would need the materialised error contract
        return NotImplemented
    if name in ("mean", "var", "std") and np.issubdtype(
            st.dtype, np.complexfloating):
        return NotImplemented          # mirror the fused-filter gate
    probe = jax.ShapeDtypeStruct((max(st.n, 1),) + tuple(st.vshape),
                                 st.dtype)
    kwargs = {} if ddof is None else {"ddof": ddof}
    aval = jax.eval_shape(
        lambda x: _OPS[name](x, axis=0, **kwargs), probe)
    return PendingStat(g, name, tuple(range(st.split)), False, ddof,
                       aval, 0)


def defer_reduce(arr, func, axes, keepdims):
    """Lazy door for ``reduce(func)`` — armed ONLY while a
    batching-enabled serving layer is active (``bolt_tpu.serve``
    ``Server(batching=...)`` arms ``bolt_tpu/tpu/batched.py``): a
    full-key-axis reduce over a plain chain/concrete source defers as a
    singleton pending-handle group so the serve scheduler can coalesce
    same-shape requests into ONE batched dispatch.  Standalone
    resolution is the EXACT eager program (same key, same traced tree),
    so a deferred handle read outside any batch is byte-for-byte the
    eager terminal.  Returns ``NotImplemented`` (→ the eager path) when
    the door is unarmed or the geometry does not fit: misaligned axes,
    streams/filters/pending compactions, donating chains (donation
    semantics stay eager), non-traceable reducers, or a reducer whose
    output drifts from the value shape (the eager call-time error
    contract is preserved)."""
    import sys as _sys
    bt = _sys.modules.get("bolt_tpu.tpu.batched")
    if bt is None or not bt.armed():
        return NotImplemented
    if (arr._donated or arr._stream is not None
            or arr._fpending is not None or arr._pending is not None
            or arr._stat_group is not None):
        return NotImplemented
    split = arr._split
    if split == 0 or tuple(axes) != tuple(range(split)):
        return NotImplemented
    shape = tuple(arr._aval.shape)
    n = prod(shape[:split])
    if n == 0:
        return NotImplemented          # eager empty-reduce raise contract
    vshape = shape[split:]
    dtype = arr._aval.dtype
    from bolt_tpu.tpu.array import _TRACE_ERRORS, _cached_eval_shape, \
        _chain_donate_ok
    vaval = jax.ShapeDtypeStruct(vshape, dtype)
    try:
        oav = _cached_eval_shape(
            ("reduce", func, vshape, str(vaval.dtype)),
            lambda: jax.eval_shape(func, vaval, vaval))
    except _TRACE_ERRORS:
        return NotImplemented          # host-fallback path resolves
    if tuple(oav.shape) != tuple(vshape):
        return NotImplemented          # eager call-time ValueError
    if arr.deferred and _chain_donate_ok(arr._chain):
        return NotImplemented          # keep the donating eager terminal
    base, funcs = arr._chain_parts()
    g = _StatGroup("chain", arr._mesh, split, base=base, funcs=funcs,
                   donate=False,
                   in_aval=jax.ShapeDtypeStruct(shape, dtype))
    g.rfunc = func
    new_split = split if keepdims else 0
    aval = jax.ShapeDtypeStruct(
        ((1,) * split + tuple(vshape)) if keepdims else tuple(vshape),
        oav.dtype)
    m = PendingStat(g, "reduce", tuple(axes), keepdims, None, aval,
                    new_split)
    g.members.append(m)
    return _wrap(arr, g, m)


# ---------------------------------------------------------------------
# the public multi-output terminal
# ---------------------------------------------------------------------

def compute(*stats, accumulate=None):
    """Resolve pending statistics with as few passes as possible::

        s, v, lo, hi = bolt.compute(a.sum(), a.var(), a.min(), a.max())

    Handles sharing one source (the same deferred chain, deferred
    filter, or out-of-core stream) dispatch ONE fused tuple program —
    map/filter stages applied once, one read of the data for the whole
    group, each result bit-identical to its standalone terminal.
    Mixed sources fall back per group; already-concrete inputs (any
    backend) pass through untouched.  Returns the inputs in argument
    order (a single input comes back bare).

    ``accumulate`` opts the group's additive reductions into the
    reduced-precision path ("bf16" values with f32 accumulation, or
    "f32"); default ``None`` is bit-exact.  See
    :func:`bolt_tpu._precision.accumulate` for the scoped form."""
    if not stats:
        raise TypeError("compute() needs at least one statistic")
    seen, groups = set(), []
    for s in stats:
        h = getattr(s, "_spending", None)
        if h is not None and h.result is None:
            if id(h.group) not in seen:
                seen.add(id(h.group))
                groups.append(h.group)
    for g in groups:
        g.resolve(accumulate)
    if accumulate is not None and not groups:
        _precision._check_accumulate(accumulate)   # validate even if moot
    return stats[0] if len(stats) == 1 else tuple(stats)


def fluent_stats(arr, names, axis=None, accumulate=None):
    """``a.stats("sum", "var", "min")`` — the fluent fused multi-stat:
    one pending handle per name (each exactly the standalone method's
    spec), resolved together through :func:`compute`, returned as an
    ordered ``{name: value-shaped array}`` dict."""
    for n in names:
        if n not in LAZY_NAMES:
            raise ValueError(
                "unknown statistic %r; choose from %s"
                % (n, ", ".join(LAZY_NAMES)))
    if arr._stream is not None and any(n not in _STREAM_LAZY
                                       for n in names):
        # a name with no streamed fold (prod/all/any) would materialise
        # the source MID-LIST — consuming a one-shot iterator out from
        # under the streamed siblings (and double-ingesting re-iterable
        # sources).  Materialise ONCE up front instead: every name then
        # computes from the concrete data as one fused chain group.
        arr.cache()
    handles = [getattr(arr, n)(axis=axis) for n in names]
    compute(*handles, accumulate=accumulate)
    return OrderedDict(zip(names, handles))
