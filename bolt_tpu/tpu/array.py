"""The ``mode='tpu'`` backend: a sharded ``jax.Array`` over a device mesh.

Structural replacement for ``bolt/spark/array.py :: BoltArraySpark``
(symbol-level citations throughout; the reference mount was empty — see
SURVEY.md §0).  Where the reference holds an RDD of
``(key-tuple, value-ndarray)`` records plus ``(shape, split, dtype,
ordered)``, this backend holds ONE global ``jax.Array`` carrying the full
logical shape (key axes leading) whose ``NamedSharding`` maps key axes onto
mesh axes — the key/value split IS the sharding spec, and the reference's
per-record Python hot loops, tree reductions and shuffles lower to a single
compiled XLA program per op:

=====================  ==========================================  =============================
reference call site    Spark mechanism                             lowering here
=====================  ==========================================  =============================
``map``                ``rdd.mapValues`` per-record Python loop    ``jit(vmap(func))`` w/ sharding
``reduce``             ``rdd.treeReduce``                          fixed-order pairwise tree, compiled
``mean/var/std``       ``rdd.aggregate(StatCounter...)``           ``jnp`` reductions / psum-Welford
``swap``               chunk → shuffle → unchunk                   transpose + reshard (all_to_all)
``toarray``            ``sortByKey().collect()``                   ``jax.device_get`` (ICI gather)
``cache``              RDD persistence                             arrays are device-resident already
=====================  ==========================================  =============================

**Laziness and fusion.**  Like the reference's RDDs (transformations are
lazy, actions execute), a traceable ``map`` is deferred: the array records a
chain of per-record functions over its parent and materialises on demand.
When an action (``reduce``, ``sum``/``mean``/…, ``toarray``) consumes a
deferred chain, the whole pipeline compiles to ONE fused XLA program —
``ones(10GB).map(f).sum()`` reads HBM once and never materialises the mapped
intermediate, which is what lets the 10 GB north-star workload fit and run
at HBM bandwidth.  ``cache()`` forces materialisation, exactly like the
reference pinning an RDD.

Arrays are always ordered (a global ``jax.Array`` has no record ordering to
lose — ``toarray`` is key-ordered by construction, matching the reference's
sorted collect).
"""

import sys
import threading
import warnings
from collections import OrderedDict
from functools import lru_cache

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu import _lockdep
from bolt_tpu import engine as _engine
from bolt_tpu import stream as _streamlib
from bolt_tpu.base import BoltArray, HostFallbackWarning
from bolt_tpu.obs import trace as _obs
from bolt_tpu.parallel.sharding import key_sharding
from bolt_tpu.utils import (argpack, check_value_shape as _check_value_shape,
                            inshape, isreshapeable, istransposeable, prod,
                            tupleize)

# Compiled-executable cache keyed on (operation, user function, static
# geometry): repeated calls with the same func/shape reuse the executable
# (the analog of Spark reusing a cached stage).  The table itself now
# lives in the central dispatch engine (bolt_tpu/engine.py) — one keyed
# AOT compile cache for every op family, with hit/miss/compile-time
# counters and optional on-disk persistence — and is aliased here for
# introspection: tests and tools scan its keys, and entries answer
# ``.lower`` like the jitted callables they wrap.  Closures in the cache
# deliberately capture only (mesh, geometry) — never an array — so
# cached entries pin no device memory.
_JIT_CACHE = _engine._CACHE
_JIT_CACHE_MAX = _engine.CACHE_MAX

# stable callables for scalar operator operands (see _scalar_fn)
_SCALAR_FN_CACHE = OrderedDict()

# binary ufuncs whose reduce/reduceat fold order provably matches numpy's
# (verified empirically np-vs-jnp over float/int operands).  numpy's
# generic non-reorderable reduce uses a buffer-striding order that is
# NEITHER a left nor right fold (np.power.reduce([2,3,2,1.5]) == 2**1.5,
# yet power.accumulate IS the left fold) — power/arctan2 and anything
# unverified reject loudly instead of returning silently different
# numbers.  accumulate (sequential by definition) and outer
# (order-free broadcast) need no gate.
_UFUNC_FOLD_SAFE = frozenset([
    "add", "subtract", "multiply", "divide", "true_divide",
    "floor_divide", "maximum", "minimum", "fmax", "fmin", "hypot",
    "logaddexp", "logaddexp2", "copysign", "nextafter", "heaviside",
    "fmod", "mod", "remainder", "float_power", "logical_and",
    "logical_or", "logical_xor", "bitwise_and", "bitwise_or",
    "bitwise_xor", "left_shift", "right_shift", "gcd", "lcm"])


@lru_cache(maxsize=256)
def _round_fn(decimals):
    def f(v):
        return jnp.round(v, decimals)
    f.__name__ = "round_%d" % decimals
    return f


@lru_cache(maxsize=64)
def _cast_fn(dtype):
    """Stable per-dtype cast callable (streamed ``map(dtype=...)``
    records it as a stage; a fresh lambda per call would defeat the
    per-slab executable cache)."""
    dt = np.dtype(dtype)

    def f(v):
        return v.astype(dt)
    f.__name__ = "astype_%s" % dt
    return f

# toarray's batched pending-filter fetch ships the FULL padded buffer to
# save one round-trip; above this size the worst case (few survivors) costs
# more in transfer than the round-trip saves, so resolve first instead
_PENDING_FETCH_MAX_BYTES = 32 << 20

# the fused (lazy-count) filter materialises an n-row padded compaction
# buffer — a full-size transient copy.  Above this input size that copy
# threatens HBM (a 10 GB filter would need 20 GB); fall back to the
# two-phase path whose gather output is only survivor-count rows
_FILTER_FUSED_MAX_BYTES = 1 << 30

# HBM-scale guards (VERDICT r2 weak-4).  Ops whose TRANSIENT working set
# is a multiple of the input (unique's sorted copy, topk's transposed
# copy, argsort's sort scratch) switch to bounded chunked paths above
# this size — the _FILTER_FUSED_MAX_BYTES pattern; ops whose OUTPUT is
# inherently input-sized (sort, cumsum, argsort) additionally check the
# total demand up front so a doomed program fails with a clear error
# before dispatch instead of an opaque XLA OOM.
_CHUNK_MAX_BYTES = 1 << 30

# device-memory limit resolution: explicit override > BOLT_HBM_BYTES env
# > the device's own report (memory_stats) > an ASSUMED smallest-current-
# TPU default (warn-only — larger chips may still fit the op)
_HBM_LIMIT_OVERRIDE = None
_ASSUMED_TPU_HBM_BYTES = 16 << 30          # v5e


_HBM_DEVICE_REPORT = None                   # resolved once per process


def _hbm_limit():
    """``(bytes, known)`` — the device memory budget and whether it is
    authoritative (reported/configured) or assumed.  The override and
    env var stay dynamic (tests flip them); the DEVICE query — a
    potentially-RPC call on remote attach — resolves once per
    process."""
    import os
    if _HBM_LIMIT_OVERRIDE is not None:
        return int(_HBM_LIMIT_OVERRIDE), True
    env = os.environ.get("BOLT_HBM_BYTES")
    if env:
        return int(env), True
    global _HBM_DEVICE_REPORT
    if _HBM_DEVICE_REPORT is None:
        report = (None, False)                       # CPU: host RAM
        try:
            dev = jax.local_devices()[0]
            if dev.platform == "tpu":
                # assumed default FIRST, so a raising memory_stats()
                # (possible on remote attach) still leaves the guards
                # armed rather than silently disabled
                report = (_ASSUMED_TPU_HBM_BYTES, False)
        except Exception:
            dev = None
        try:
            stats = (dev.memory_stats() or {}) if dev is not None else {}
            if stats.get("bytes_limit"):
                report = (int(stats["bytes_limit"]), True)
        except Exception:
            pass
        _HBM_DEVICE_REPORT = report
    return _HBM_DEVICE_REPORT


def slab_plan(shape, axis, in_bytes):
    """``(carry_axis, bounds)`` for slabbing an HBM-scale op along an
    axis other than its target ``axis`` — slabs of at most
    ``_CHUNK_MAX_BYTES`` with a shared recipe so the chunked paths
    (argsort, topk) cannot drift.  ``None`` when no other axis can
    carry the slabbing.  The LARGEST other axis carries it — a small
    first axis could not cut slabs fine enough to honour the bound."""
    cands = [a for a in range(len(shape)) if a != axis and shape[a] > 1]
    if not cands:
        return None
    cax = max(cands, key=lambda a: shape[a])
    nslabs = min(shape[cax], max(2, -(-in_bytes // _CHUNK_MAX_BYTES)))
    bounds = np.linspace(0, shape[cax], nslabs + 1).astype(int)
    pairs = [(int(s0), int(s1))
             for s0, s1 in zip(bounds[:-1], bounds[1:]) if s0 != s1]
    return cax, pairs


def _gather_bucket(count, cap):
    """Next power of two ≥ ``count`` (≥1, capped at ``cap``): the size
    band a dynamic survivor gather pads to so its executable is reused
    across calls whose counts drift within the band (VERDICT r3
    weak-5)."""
    b = 1
    while b < count:
        b <<= 1
    return min(b, cap)


def hbm_check(op, need_bytes, model):
    """Fail fast (or warn, when the limit is only assumed) when ``op``'s
    estimated device demand ``need_bytes`` cannot fit.  ``model`` is the
    human-readable memory model ("input + output + sort scratch") shown
    in the message — the documented per-op accounting."""
    limit, known = _hbm_limit()
    if limit is None or need_bytes <= limit:
        return
    msg = ("%s needs ~%.1f GB of device memory (%s) but the device "
           "holds %.1f GB" % (op, need_bytes / float(1 << 30), model,
                              limit / float(1 << 30)))
    if known:
        raise MemoryError(msg)
    from bolt_tpu.base import HBMPressureWarning
    warnings.warn(msg + "; this limit is ASSUMED (device did not report "
                  "capacity) — set BOLT_HBM_BYTES to your chip's HBM "
                  "size for an exact up-front check", HBMPressureWarning,
                  stacklevel=3)


# multi-host toarray broadcasts each remote shard region in pieces of at
# most this many bytes, bounding the per-device HBM overhead of the
# cross-host collect at any array size (the full-array replication a
# plain allgather would do); pieces are host-sliced, so compiled-program
# count scales with distinct piece shapes, not array size
_GATHER_SLAB_BYTES = 256 << 20

# introspection for tests/smoke: piece accounting of the last
# _gather_multihost call ({"regions", "broadcasts", "max_piece_bytes"})
_LAST_GATHER_STATS = None


_LRU_LOCK = _lockdep.rlock("tpu.lru")


def _lru_get(cache, key, build):
    """Shared bounded-LRU policy for the aval/scalar-callable caches.
    NOTE: keys hold strong references to user callables, so a closure
    capturing a large array stays alive until its entry evicts — the
    values are the cheap part (executables/avals), the keys are what can
    pin memory in pathological many-distinct-closures sessions.
    Locked: concurrent tenants (bolt_tpu.serve) walk these OrderedDicts
    from many threads, and an unguarded move_to_end/popitem pair can
    corrupt the linkage; ``build`` runs under the lock — it is
    eval_shape-class host work, never an XLA compile."""
    with _LRU_LOCK:
        out = cache.get(key)
        if out is None:
            out = build()
            cache[key] = out
            if len(cache) > _JIT_CACHE_MAX:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
        return out


def _cached_jit(key, builder):
    """Keyed executable dispatch through the central engine: compiled at
    most once per (key, argument signature), AOT, counted, and shared
    across every op family (``bolt_tpu.profile.instrument`` patches this
    name per module to count calls/builds)."""
    return _engine.get(key, builder)


def _chain_donate_ok(chain):
    """True when a deferred chain's base buffer may be DONATED to the
    compiled program of a consuming terminal (reduce/_stat/chain
    materialisation/chunked map): the chain tuple must be the buffer's
    sole owner — no other live bolt array wraps it and no other chain
    shares it — and the buffer must be at least
    ``engine.donation_min_bytes()`` big (small interactive arrays stay
    readable after a terminal; HBM-scale one-shot chains get input+output
    overlap, halving their peak footprint).

    Ownership is decided by Python refcounts, twice over: the BASE must
    have exactly three references (the chain tuple, our local, and
    getrefcount's argument), and the chain TUPLE itself must be owned by
    exactly one wrapper (``_clone`` copies share the tuple — a shared
    tuple means another live array can still re-materialise from the
    base, so donation must not fire).  Callers MUST invoke this before
    binding their own local to the base (a fourth reference would mask
    sole ownership, failing safe: no donation)."""
    base = chain[0]
    floor = _engine.donation_min_bytes()
    if floor is None or base.nbytes < floor:
        return False
    if getattr(base, "is_deleted", lambda: False)():
        return False
    # chain refs when unshared: the owner's attribute, the caller's
    # argument-stack slot, our parameter, getrefcount's argument — a
    # fifth means a _clone shares the tuple (threshold verified by
    # tests/test_engine.py::test_clone_shared_chain_blocks_donation on
    # both the shared and unshared sides, so an interpreter that changes
    # call-stack refcounting fails loudly there, not silently here)
    if sys.getrefcount(chain) > 4:
        return False
    return sys.getrefcount(base) <= 3


# abstract-shape inference results, keyed on (func identity, input aval):
# jax.eval_shape re-traces the callable each call (~ms of host work),
# which at steady state was measured as the dominant per-dispatch
# framework overhead vs raw jax on small-array pipelines
_EVAL_CACHE = OrderedDict()


def _cached_eval_shape(key, thunk):
    return _lru_get(_EVAL_CACHE, key, thunk)


def _constrain(out, mesh, split):
    """Key-sharding constraint on a traced intermediate (shapes are static
    at trace time, so the spec is computable inside jit)."""
    return jax.lax.with_sharding_constraint(
        out, key_sharding(mesh, out.shape, split))


def _traceable(func):
    """Translate a NumPy ufunc to its jnp twin so reference user code
    (``b.reduce(np.maximum)``) traces on TPU; other callables pass through
    (``mode='tpu'`` requires jax-compatible callables — SURVEY §7 hard
    part 4 — with a host fallback as the escape hatch)."""
    if isinstance(func, np.ufunc):
        jf = getattr(jnp, func.__name__, None)
        if jf is not None:
            return jf
    return func




# Exceptions that mean "this callable cannot be traced by jax" — every
# tracer-concreteness failure derives from JAXTypeError (Concretization,
# TracerArray/Bool/IntegerConversion); NonConcreteBooleanIndexError is the
# one traceability failure raised under JAXIndexError instead.  Anything
# else out of eval_shape (plain TypeError from a shape mismatch,
# AttributeError from a typo, ValueError from user asserts) is a genuine
# bug in the user's callable and must surface, not silently reroute a
# 100×-slower host round-trip (VERDICT r1 weak-1).
_TRACE_ERRORS = (jax.errors.JAXTypeError, jax.errors.NonConcreteBooleanIndexError)


def _warn_fallback(op, func, exc):
    name = getattr(func, "__name__", repr(func))
    warnings.warn(
        "%s: callable %r is not jax-traceable (%s: %s); falling back to the "
        "local oracle via a device->host->device round-trip. Rewrite with "
        "the jax-compatible numpy-API subset to stay on device."
        % (op, name, type(exc).__name__,
           str(exc).splitlines()[0] if str(exc) else ""),
        HostFallbackWarning, stacklevel=3)


def _canon(dtype):
    """Canonicalise a dtype to what the backend can hold (f64→f32 unless
    x64 is enabled) — explicit and silent rather than warn-and-truncate."""
    return jax.dtypes.canonicalize_dtype(np.dtype(dtype))


def _complex_safe_get(x):
    """``device_get`` that never ships a complex buffer over the wire.

    Some attach transports (this environment's remote tunnel) have no
    complex DMA: ONE attempted complex transfer fails UNIMPLEMENTED and
    poisons every later transfer in the session.  Complex arrays
    therefore fetch as two real views (one tiny fused program each)
    combined on host; real arrays take the direct path unchanged."""
    if not np.issubdtype(np.dtype(x.dtype), np.complexfloating):
        return jax.device_get(x)
    re, im = jax.device_get((jnp.real(x), jnp.imag(x)))
    out = np.asarray(re) + 1j * np.asarray(im)
    return out.astype(np.dtype(x.dtype), copy=False)


def _complex_safe_put(a, sharding=None):
    """host→device that never ships a complex buffer (the upload twin of
    :func:`_complex_safe_get`): real and imag parts transfer separately
    and ONE cached program combines them on device, already laid out on
    ``sharding`` when given."""
    a = np.asarray(a)
    if not np.issubdtype(a.dtype, np.complexfloating):
        return (_streamlib.transfer(a, sharding) if sharding is not None
                else jnp.asarray(a))
    re = np.ascontiguousarray(a.real)
    im = np.ascontiguousarray(a.imag)
    if sharding is not None:
        dre = _streamlib.transfer(re, sharding)
        dim = _streamlib.transfer(im, sharding)
    else:
        dre, dim = jnp.asarray(re), jnp.asarray(im)

    def build():
        return jax.jit(jax.lax.complex)
    fn = _cached_jit(("cplx_combine", tuple(a.shape), str(re.dtype),
                      sharding), build)
    return fn(dre, dim)


def _check_live(arr):
    """Guard reads of a buffer that a ``swap(..., donate=True)`` may have
    consumed — deferred children can hold the donated parent's buffer."""
    if getattr(arr, "is_deleted", lambda: False)():
        raise RuntimeError(
            "the underlying device buffer was donated to a "
            "swap(..., donate=True) and is no longer readable")
    return arr


def _check_sort_kind(kind):
    """Shared ``kind`` validation for sort/argsort (numpy's exact
    rejection wording); returns True when numpy-identical tie order is
    guaranteed."""
    if kind not in (None, "quicksort", "heapsort", "mergesort", "stable"):
        raise ValueError("sort kind must be one of 'quick', 'heap', "
                         "or 'stable' (got %r)" % (kind,))
    return kind in ("stable", "mergesort")


class _WithKeysFunc:
    """Deferred-chain entry for ``map(func, with_keys=True)``: ``func``
    takes ``((k0, ..., kn-1), value)`` and needs the key indices
    alongside each block, so :func:`_chain_apply` expands it with traced
    ``unravel_index`` keys instead of a plain nested vmap.  Hash/eq
    delegate to the wrapped callable so two maps of the same func share
    compiled programs (the executable cache keys on chain tuples)."""

    __slots__ = ("func",)

    def __init__(self, func):
        self.func = func

    def __hash__(self):
        return hash((_WithKeysFunc, self.func))

    def __eq__(self, other):
        return type(other) is _WithKeysFunc and self.func == other.func


def _reduce_tree_expr(data, func, funcs, split, n, vshape, keepdims):
    """The fixed-order pairwise-tree reduction expression — ONE traced
    body shared by the eager ``reduce`` program, the lazy reduce
    handle's standalone resolution (``bolt_tpu/tpu/multistat.py``) and
    the serve layer's batched (vmapped) program
    (``bolt_tpu/tpu/batched.py``), so every form computes bit-identical
    results.  Applies the deferred chain, folds the flattened records
    pairwise, validates the reducer's value shape, and restores
    ``keepdims`` key axes; the caller applies the sharding constraint."""
    mapped = _chain_apply(funcs, split, data)
    x = mapped.reshape((n,) + mapped.shape[split:])
    vfunc = jax.vmap(func)
    while x.shape[0] > 1:
        half = x.shape[0] // 2
        combined = vfunc(x[:half], x[half:2 * half])
        rem = x[2 * half:]
        x = jnp.concatenate([combined, rem], axis=0) if rem.shape[0] \
            else combined
    out = x[0]
    if out.shape != tuple(vshape):
        raise ValueError(
            "reduce produced shape %s, expected value shape %s"
            % (out.shape, tuple(vshape)))
    if keepdims:
        out = out.reshape((1,) * split + tuple(vshape))
    return out


def _chain_apply(funcs, split, data):
    """Apply a deferred map chain: each func nested-vmapped over the
    ``split`` leading key axes, in order; ``with_keys`` entries vmap
    over flattened records zipped with their (traced, int32 — matching
    the shape-inference avals) key tuples."""
    out = data
    for func in funcs:
        if isinstance(func, _WithKeysFunc):
            kshape = out.shape[:split]
            n = prod(kshape)
            flat = out.reshape((n,) + out.shape[split:])
            keys = jnp.unravel_index(jnp.arange(n, dtype=jnp.int32),
                                     kshape)

            def one(v, *k, _f=func.func):
                return _f((tuple(k), v))

            res = jax.vmap(one)(flat, *keys)
            out = res.reshape(kshape + res.shape[1:])
            continue
        f = func
        for _ in range(split):
            f = jax.vmap(f)
        out = f(out)
    return out


def _pred_mask(pred, flat):
    """Filter predicate as a bool mask over flattened records — the ONE
    coercion rule (`asarray(...,bool).reshape(())` per record) shared by
    the compaction program and both fused filter terminals, so the
    paths' semantics cannot diverge."""
    return jax.vmap(
        lambda v: jnp.asarray(pred(v), dtype=bool).reshape(()))(flat)


def _masked_stat_expr(name, flat, mask, mfull, axes, keepdims, ddof,
                      vshape, vdtype):
    """ONE masked reduction over the flattened filtered records — the
    arithmetic of the fused ``filter(...).sum()``-family terminals,
    factored out so the standalone filter-stat program and the fused
    multi-terminal program (bolt_tpu/tpu/multistat.py) trace the SAME
    expressions and cannot drift.  ``mean/var/std`` divide by the
    masked COUNT computed in the same pass (var as the one-pass moment
    form ``(Σx² − (Σx)²/n)/(n−ddof)``); the rest fold dropped records
    onto their identity."""
    vdtype = np.dtype(vdtype)
    op = {"sum": jnp.sum, "prod": jnp.prod, "any": jnp.any,
          "all": jnp.all, "max": jnp.max, "min": jnp.min}.get(name)
    ref = {"mean": jnp.mean, "var": jnp.var, "std": jnp.std}.get(
        name, op)
    # output dtype from jnp's own promotion rule on a 1-record probe,
    # so fused and eager results always agree on dtype
    out_dt = jax.eval_shape(
        lambda x: ref(x, axis=axes), jax.ShapeDtypeStruct(
            (1,) + tuple(vshape), vdtype)).dtype
    if name in ("sum", "prod", "any", "all", "max", "min"):
        if name in ("sum", "prod", "any", "all"):
            ident = {"sum": 0, "prod": 1, "any": False,
                     "all": True}[name]
        elif np.issubdtype(vdtype, np.floating) or \
                np.issubdtype(vdtype, np.complexfloating):
            ident = -np.inf if name == "max" else np.inf
        elif vdtype == np.bool_:
            ident = name == "min"
        else:
            info = np.iinfo(vdtype)
            ident = info.min if name == "max" else info.max
        v = jnp.where(mfull, flat, jnp.asarray(ident, flat.dtype))
        out = op(v, axis=axes, keepdims=keepdims)
        if out.dtype != out_dt:
            out = out.astype(out_dt)
        return out
    # element count each output slot divides by beyond the mask: the
    # reduced VALUE axes are dense (the mask only thins records)
    prodv = prod([vshape[a - 1] for a in axes if a > 0])
    cnt = jnp.sum(mask, dtype=jnp.int32)
    den = (cnt * prodv).astype(out_dt)
    xf = jnp.where(mfull, flat, jnp.zeros((), flat.dtype)).astype(out_dt)
    s1 = jnp.sum(xf, axis=axes, keepdims=keepdims)
    if name == "mean":
        return s1 / den
    dd = 0.0 if ddof is None else ddof
    s2 = jnp.sum(xf * xf, axis=axes, keepdims=keepdims)
    out = (s2 - s1 * s1 / den) / (den - dd)
    if name == "std":
        out = jnp.sqrt(out)
    return out


class BoltArrayTPU(BoltArray):
    """Distributed n-d array: key axes sharded over a TPU mesh, value axes
    local to each device."""

    _mode = "tpu"

    def __init__(self, data, split, mesh):
        if data is not None and (split < 0 or split > data.ndim):
            raise ValueError("split %d out of range for %d-d array" % (split, data.ndim))
        self._concrete = data
        self._split = int(split)
        self._mesh = mesh
        # deferred map chain: (base jax.Array, (func, ...)) or None
        self._chain = None
        # pending dynamic-shape result: (padded jax.Array, count device
        # scalar) from filter() — the survivor count has not been read on
        # host yet, so the logical shape is not known (see filter())
        self._pending = None
        # deferred filter: (base, funcs, predicate, parent_split, vshape,
        # n, value dtype) — no program has been DISPATCHED yet, so a
        # reduction terminal can fold the predicate into its own pass
        # (see filter / _fused_filter_stat); any other consumer resolves
        # it into the _pending compaction form first
        self._fpending = None
        # lazy out-of-core stream source (bolt_tpu/stream.py): no device
        # data exists yet; reduction terminals run the double-buffered
        # streaming executor, everything else materialises via ._data
        self._stream = None
        # lazy stat terminal (bolt_tpu/tpu/multistat.py): this array IS
        # the not-yet-dispatched result of a sum()/var()/... terminal —
        # a PendingStat handle into a shared single-pass group; the
        # first read resolves the group (fused with any siblings)
        self._spending = None
        # the live (undispatched) stat group reading THIS array's
        # terminals — later sum()/var()/... calls join it, so N stats
        # on one source fuse into one pass (and one donate)
        self._stat_group = None
        self._donated = False
        self._aval = None if data is None else jax.ShapeDtypeStruct(
            data.shape, data.dtype)

    @classmethod
    def _deferred(cls, base, funcs, split, mesh, aval):
        b = cls(None, split, mesh)
        b._chain = (base, tuple(funcs))
        b._aval = aval
        return b

    @classmethod
    def _streamed(cls, source):
        """Wrap a lazy out-of-core :class:`bolt_tpu.stream.StreamSource`:
        shape/dtype answer abstractly from the recorded stage chain, the
        streaming terminals (``sum``/``mean``/``var``/``std``/``reduce``)
        run the double-buffered executor, and any other consumer
        materialises transparently through ``._data`` (per-shard callback
        upload + the standard deferred/chunked/stacked programs)."""
        st = _streamlib.result_state(source)
        b = cls(None, st.split, source.mesh)
        b._stream = source
        b._aval = None if st.dynamic else jax.ShapeDtypeStruct(
            tuple(st.shape), st.dtype)
        return b

    # ------------------------------------------------------------------
    # properties (reference: ``BoltArraySpark`` properties, SURVEY §2.2)
    # ------------------------------------------------------------------

    @property
    def shape(self):
        if self._stream is not None and self._aval is None:
            # a streamed filter: the survivor count is unknowable
            # without running the pipeline — materialise (mirrors the
            # pending-filter count sync)
            self._data
        if self._fpending is not None:
            self._resolve_fpending()
        if self._pending is not None:
            self._resolve_pending()
        if self._aval is None:
            # a filter array consumed by a donating terminal: its count
            # was never synced, so the metadata is unknowable — raise
            # the named donation guard, not AttributeError (chain-
            # donated arrays keep answering from their recorded aval)
            self._guard_donated()
        return tuple(self._aval.shape)

    @property
    def dtype(self):
        if self._stream is not None and self._aval is None:
            # dtype is known abstractly even for a streamed filter
            return np.dtype(_streamlib.result_state(self._stream).dtype)
        if self._fpending is not None:
            # dtype is known without dispatching the filter program
            return np.dtype(self._fpending[6])
        if self._pending is not None:
            # dtype is known without syncing the survivor count
            return np.dtype(self._pending[0].dtype)
        if self._aval is None:
            self._guard_donated()   # consumed filter (see shape)
        return np.dtype(self._aval.dtype)

    @property
    def split(self):
        """Number of leading key axes (reference: ``BoltArraySpark.split``)."""
        return self._split

    @property
    def mesh(self):
        return self._mesh

    @property
    def deferred(self):
        """True while this array is an unmaterialised map chain (the
        analog of an RDD transformation not yet executed)."""
        return self._concrete is None and self._chain is not None

    @property
    def streaming(self):
        """True while this array is a lazy out-of-core stream source
        (``fromcallback``/``fromiter``): nothing is resident on device;
        reduction terminals stream it slab-by-slab, other consumers
        materialise it (which requires the full array to fit)."""
        return self._stream is not None

    @property
    def pending(self):
        """True while this array is an unresolved dynamic-shape result (a
        ``filter`` whose survivor count has not been synced to host): the
        compacted data lives on device, but the logical shape is unknown
        until one scalar fetch.  Reading ``shape`` (or any consumer)
        resolves it; ``toarray`` resolves it with a single batched
        transfer.  A still-DEFERRED filter (no program dispatched yet —
        reductions fuse the predicate into their own pass) reports
        pending too: its survivor count is equally unknown."""
        return self._pending is not None or self._fpending is not None

    def _consume_donated(self, op="a donating pipeline terminal",
                         granted=True):
        """Mark this array consumed by the donating operation ``op``: its
        chain base buffer was handed to XLA, so the chain can never be
        re-materialised — reads now raise the :meth:`_guard_donated`
        gate, whose message names ``op`` (so a use-after-donate error
        says WHICH terminal consumed the buffer).  ``granted=False``
        records the donation without counting it as an engine-policy
        grant (``swap(donate=True)`` is user-explicit, not granted)."""
        self._chain = None
        self._concrete = None
        self._fpending = None
        self._donated = op
        if granted:
            _engine.donation_granted()

    def _guard_donated(self):
        """THE donation gate: every read of this array's device state
        goes through here (via ``._data``); a buffer consumed by a
        donating terminal raises, naming the consuming operation.  The
        repo linter (BLT104) forbids ``._concrete`` reads that would
        skip this gate."""
        if self._donated:
            op = self._donated if isinstance(self._donated, str) \
                else "a donating pipeline terminal"
            _obs.event("array.donated_read", op=op)
            raise RuntimeError(
                "this array's device buffer was donated to %s and can no "
                "longer be read (donation-aware terminals consume a "
                "sole-owned array; scope bolt_tpu.engine.donation(None) "
                "to keep sources readable, and bolt_tpu.analysis.check "
                "flags this before dispatch)" % op)

    def _resolve_fpending(self):
        """Dispatch the deferred filter's fused compaction program (ONE
        compiled pass: map chain + predicate + stable compaction + count)
        — the result becomes a *pending* ``(padded, count)`` pair exactly
        as the eager fused filter produced; the survivor count stays on
        device until the shape is read.  A sole-owned base donates its
        buffer to the program (the compaction buffer is input-sized)."""
        if self._fpending is None:
            return
        _engine.strict_guard(self, "filter() compaction")
        donate = _chain_donate_ok(self._fpending)   # [0] is the base
        base, funcs, func, split, vshape, n, _ = self._fpending
        mesh = self._mesh

        def build():
            def fused(data):
                mapped = _chain_apply(funcs, split, data)
                flat = mapped.reshape((n,) + vshape)
                mask = _pred_mask(func, flat)
                # survivor indices in increasing (key) order, padded with 0s
                # beyond the count — rows past the count are garbage and are
                # sliced away at resolution
                perm = jnp.nonzero(mask, size=n, fill_value=0)[0]
                padded = jnp.take(flat, perm, axis=0)
                return (_constrain(padded, mesh, 1),
                        jnp.sum(mask, dtype=jnp.int32))
            return jax.jit(fused, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("filter-fused", func, funcs, base.shape,
                          str(base.dtype), split, donate, mesh), build)
        with _obs.span("array.filter", funcs=len(funcs), donate=donate):
            padded, cnt = fn(_check_live(base))
        self._fpending = None
        self._pending = (padded, cnt)
        if donate:
            _engine.donation_granted()

    def _resolve_pending(self, count=None):
        """Slice the padded on-device buffer down to the true
        ``(n, *value_shape)``; syncs the survivor count (one scalar host
        fetch) unless the caller already knows it.  A still-deferred
        filter dispatches its compaction program first."""
        if self._fpending is not None:
            self._resolve_fpending()
        if self._pending is None:
            return
        padded, cnt = self._pending
        if count is None:
            count = int(jax.device_get(cnt))
        mesh = self._mesh

        def build():
            def sl(p):
                out = jax.lax.slice_in_dim(p, 0, count, axis=0)
                return _constrain(out, mesh, 1)
            return jax.jit(sl)

        fn = _cached_jit(("filter-slice", padded.shape, str(padded.dtype),
                          count, mesh), build)
        self._concrete = fn(padded)
        self._aval = jax.ShapeDtypeStruct(self._concrete.shape,
                                          self._concrete.dtype)
        self._pending = None

    def _resolve_spending(self):
        """Adopt the result of this array's lazy stat terminal,
        dispatching its group's single-pass program on first need (any
        pending siblings of the group resolve in the same dispatch —
        the read-side half of ``bolt.compute``)."""
        h = self._spending
        if h is None:
            return
        if h.result is None:
            h.group.resolve()
        self._concrete = h.result
        self._aval = jax.ShapeDtypeStruct(h.result.shape, h.result.dtype)
        self._spending = None

    @property
    def _data(self):
        """The concrete sharded ``jax.Array``; materialises a deferred
        chain on first access (one fused compiled program)."""
        self._guard_donated()
        if self._spending is not None:
            self._resolve_spending()
        if self._stream is not None:
            # materialise the lazy out-of-core source through the
            # STANDARD machinery (stream.materialize replays every
            # recorded stage via the normal deferred/chunked/stacked
            # programs), then adopt the result
            source = self._stream
            out = _streamlib.materialize(source)
            data = out._data            # resolves deferred/pending state
            # adopt only AFTER materialisation succeeded: a transient
            # source failure (an IOError mid-callback) must leave the
            # array still streaming so a retry re-raises the REAL error
            # instead of crashing on half-cleared state
            self._stream = None
            self._concrete = data
            self._split = out._split
            self._aval = jax.ShapeDtypeStruct(data.shape, data.dtype)
            return _check_live(self._concrete)
        if self._fpending is not None:
            self._resolve_fpending()
        if self._pending is not None:
            self._resolve_pending()
        if self._concrete is None:
            _engine.strict_guard(self, "map-chain materialisation")
            # chained-map terminal: a sole-owned base donates its buffer
            # to the materialising program (the output is input-sized, so
            # XLA aliases them — one buffer instead of two)
            donate = _chain_donate_ok(self._chain)
            base, funcs = self._chain
            mesh, split = self._mesh, self._split

            def build():
                def run(d):
                    return _constrain(_chain_apply(funcs, split, d), mesh, split)
                return jax.jit(run, donate_argnums=(0,) if donate else ())

            fn = _cached_jit(("chain", funcs, base.shape, str(base.dtype),
                              split, donate, mesh), build)
            with _obs.span("array.chain", funcs=len(funcs),
                           donate=donate, bytes=int(base.nbytes)):
                self._concrete = fn(_check_live(base))
            self._chain = None
            if donate:
                _engine.donation_granted()
        return _check_live(self._concrete)

    def _chain_parts(self):
        """``(base jax.Array, funcs)`` for fusing this array into a bigger
        program: the unmaterialised chain if deferred, else the concrete
        data with an empty chain."""
        return self._chain if self.deferred else (self._data, ())

    def _adopt_materialised(self, data):
        """Adopt ``data`` as this deferred chain's materialised result —
        the scatter half of a serve BATCHED dispatch
        (``bolt_tpu/tpu/batched.py``): the lane's output is exactly what
        the standalone ``("chain", ...)`` program would have produced,
        so the chain is simply retired."""
        self._concrete = data
        self._aval = jax.ShapeDtypeStruct(tuple(data.shape), data.dtype)
        self._chain = None

    def _adopt_resolved(self, res):
        """Adopt the result of resolving this array's swap stages
        (``stream.resolve_swaps`` — ISSUE 18): ``res`` is either still
        streaming (a resident shuffle re-streams its buckets, a spilled
        one streams them from disk) or concrete (the materialise
        fallback).  Either way it IS this array's value — same shape,
        dtype, split — so the identity simply re-seats on the resolved
        representation and every later terminal sees a swap-free
        source."""
        if res._stream is not None:
            self._stream = res._stream
            self._concrete = None
        else:
            self._stream = None
            self._concrete = res._concrete
            self._chain = res._chain
        self._split = res._split
        self._aval = res._aval

    @property
    def keys(self):
        """Key-axis shape view (reference: ``bolt/spark/shapes.py :: Keys``)."""
        from bolt_tpu.tpu.shapes import Keys
        return Keys(self)

    @property
    def values(self):
        """Value-axis shape view (reference: ``bolt/spark/shapes.py :: Values``)."""
        from bolt_tpu.tpu.shapes import Values
        return Values(self)

    @property
    def _constructor(self):
        from bolt_tpu.tpu.construct import ConstructTPU
        return ConstructTPU

    def _wrap(self, data, split):
        return BoltArrayTPU(data, split, self._mesh)

    # ------------------------------------------------------------------
    # alignment (reference: ``bolt/spark/array.py :: BoltArraySpark._align``)
    # ------------------------------------------------------------------

    def _align(self, axes):
        """Ensure the requested ``axes`` are exactly the key axes, swapping
        if they are not — same algorithm as the reference: value axes named
        in ``axes`` move to keys, key axes missing from ``axes`` move to
        values."""
        inshape(self.shape, axes)
        tokeys = [a - self._split for a in axes if a >= self._split]
        tovalues = [a for a in range(self._split) if a not in axes]
        if tokeys or tovalues:
            return self.swap(tovalues, tokeys)
        return self

    # ------------------------------------------------------------------
    # functional operators
    # ------------------------------------------------------------------

    def map(self, func, axis=(0,), value_shape=None, dtype=None, with_keys=False):
        """Apply ``func`` to every key's value block as ONE compiled SPMD
        program: nested ``vmap`` over the key axes under ``jit`` with a key
        sharding on the output, so each device maps only its local blocks
        and no data moves (reference: ``BoltArraySpark.map`` →
        ``rdd.mapValues`` with a one-record job for shape inference; here
        shape inference is ``jax.eval_shape`` — SURVEY §3.2).

        Traceable maps are DEFERRED (lazy, like the reference's RDD
        transformations) and fuse with downstream maps/reductions; any
        materialising consumer compiles the whole chain at once.

        ``func`` must be jax-traceable in this mode (numpy-API subset);
        non-traceable callables fall back to a host round-trip through the
        local oracle, preserving semantics at the cost of a transfer.
        ``value_shape``/``dtype`` are accepted for signature parity and
        validated when given.
        """
        func = _traceable(func)
        axes = sorted(tupleize(axis))
        aligned = self._align(axes)
        split = aligned._split
        kshape = aligned.shape[:split]
        vshape = aligned.shape[split:]

        try:
            if with_keys:
                def infer_wk():
                    kavals = tuple(jax.ShapeDtypeStruct((), jnp.int32)
                                   for _ in range(split))
                    return jax.eval_shape(
                        lambda k, v: func((k, v)), kavals,
                        jax.ShapeDtypeStruct(vshape, aligned._aval.dtype))
                out_aval = _cached_eval_shape(
                    ("map-wk", func, split, vshape,
                     str(aligned._aval.dtype)), infer_wk)
            else:
                out_aval = _cached_eval_shape(
                    ("map", func, vshape, str(aligned._aval.dtype)),
                    lambda: jax.eval_shape(
                        func,
                        jax.ShapeDtypeStruct(vshape, aligned._aval.dtype)))
        except _TRACE_ERRORS as exc:
            # non-traceable func: host fallback through the local oracle
            _warn_fallback("map", func, exc)
            local = aligned.tolocal().map(
                func, axis=tuple(range(split)), value_shape=value_shape,
                dtype=dtype, with_keys=with_keys)
            return self._constructor.array(
                local.toarray(), context=self._mesh, axis=tuple(range(split)))

        _check_value_shape(value_shape, tuple(out_aval.shape))

        mesh = self._mesh
        full_aval = jax.ShapeDtypeStruct(kshape + tuple(out_aval.shape),
                                         out_aval.dtype)

        if aligned._stream is not None and not with_keys:
            # streaming source (out-of-core): record the map as a
            # device-side stage — it fuses into the per-slab program.
            # (with_keys maps need GLOBAL key indices, which a slab-local
            # program cannot produce; they materialise below.)
            out = _streamlib.map_stage(aligned, func)
            if dtype is not None and np.dtype(dtype) != np.dtype(
                    full_aval.dtype):
                out = _streamlib.map_stage(out, _cast_fn(_canon(dtype)))
            return out

        # defer: extend the chain (or start one) without executing —
        # with_keys maps defer too (as _WithKeysFunc entries), so
        # map(f, with_keys=True).sum() is ONE fused program like any
        # other chain (VERDICT r2 weak-5)
        entry = _WithKeysFunc(func) if with_keys else func
        if aligned.deferred:
            base, funcs = aligned._chain
            out = BoltArrayTPU._deferred(base, funcs + (entry,), split,
                                         mesh, full_aval)
        else:
            out = BoltArrayTPU._deferred(aligned._data, (entry,), split,
                                         mesh, full_aval)
        if dtype is not None and np.dtype(dtype) != np.dtype(full_aval.dtype):
            return out.astype(dtype)
        return out

    def filter(self, func, axis=(0,), sort=False):
        """Dynamic-shape filter, fully on device: ONE fused compiled program
        applies any deferred map chain, evaluates the vmapped predicate,
        stably compacts the surviving records to the front of a padded
        ``(nkeys, *value_shape)`` buffer, and counts them — all without
        leaving the device.  The result is returned immediately in a
        *pending* state: the survivor count (the only thing XLA's static
        shapes cannot express) is synced lazily — one scalar fetch when the
        shape is first needed, or batched into ``toarray``'s transfer so a
        ``filter(...).toarray()`` pipeline pays a single host round-trip.

        Output records are re-keyed to a flat ``(n,)`` key space with
        ``split=1`` in original key order — the reference's re-key-to-linear
        semantics (``BoltArraySpark.filter``); the reference pays a Spark
        job at the same spot for shape inference (SURVEY §7 hard part 1).
        ``sort`` is accepted for parity; output is always ordered.

        The fused path's padded compaction buffer is a full-size transient
        copy; above ``_FILTER_FUSED_MAX_BYTES`` (HBM-scale inputs) the
        two-phase mask→count→gather path runs instead, whose output is
        survivor-count rows only.
        """
        func = _traceable(func)
        axes = sorted(tupleize(axis))
        aligned = self._align(axes)
        split = aligned._split
        kshape = aligned.shape[:split]
        vshape = aligned.shape[split:]
        n = prod(kshape)
        mesh = self._mesh

        try:
            pred_aval = _cached_eval_shape(
                ("filter", func, vshape, str(aligned._aval.dtype)),
                lambda: jax.eval_shape(
                    func, jax.ShapeDtypeStruct(vshape, aligned._aval.dtype)))
        except _TRACE_ERRORS as exc:
            # non-traceable predicate: host fallback through the local oracle
            _warn_fallback("filter", func, exc)
            out = aligned.tolocal().filter(func, axis=tuple(range(split)))
            data = _streamlib.transfer(
                np.asarray(out), key_sharding(mesh, out.shape, 1))
            return self._wrap(data, 1)
        if prod(getattr(pred_aval, "shape", ())) != 1:
            raise ValueError(
                "filter predicate must return a scalar truth value per "
                "record; got shape %s for value shape %s"
                % (tuple(pred_aval.shape), vshape))

        if aligned._stream is not None:
            # streaming source: the predicate stays lazy (a trailing
            # stream stage); reduction terminals fold its mask into the
            # per-slab pass — out-of-core filter(...).sum() never
            # materialises anything input-sized
            return _streamlib.filter_stage(aligned, func)

        nbytes = n * prod(vshape) * np.dtype(aligned._aval.dtype).itemsize
        if nbytes > _FILTER_FUSED_MAX_BYTES:
            # the padded compaction buffer would be a full-size HBM copy;
            # take the memory-safe two-phase path (its gather output is
            # survivor-count rows only) at the cost of an eager count sync
            return self._filter_eager(func, aligned, split, vshape, n, mesh)

        # DEFER: no program dispatches here.  A reduction terminal
        # (sum/mean/reduce/...) folds the predicate into its own pass —
        # ONE read of HBM, no compaction buffer; any other consumer
        # resolves through the fused compaction program exactly as
        # before (see _resolve_fpending).
        base, funcs = aligned._chain_parts()
        out = BoltArrayTPU(None, 1, mesh)
        out._fpending = (base, funcs, func, split, vshape, n,
                         np.dtype(aligned._aval.dtype))
        return out

    def _filter_eager(self, func, aligned, split, vshape, n, mesh):
        """Two-phase filter for inputs too large for a padded compaction
        copy: compiled mask → host count sync → compiled gather into a
        BUCKET-sized buffer (next power of two ≥ count) — peak HBM is
        input + <2× survivors, never 2× input.

        Bucketing (VERDICT r3 weak-5): the gather executable is cached on
        the bucket, not the exact survivor count, so repeated HBM-scale
        filters with drifting counts reuse ONE compiled gather per
        power-of-two band instead of paying a fresh XLA compile each
        call.  The result is returned *pending* ``(bucket_buffer,
        count)`` like the fused path — the count-exact slice (the only
        per-count program left, a trivial compile) happens at shape
        resolution."""

        def build():
            def masker(data):
                return _pred_mask(func, data.reshape((n,) + vshape))
            return jax.jit(masker)

        mask = _cached_jit(("filter-mask", func, aligned.shape,
                            str(aligned.dtype), split, mesh),
                           build)(aligned._data)
        idx = np.nonzero(np.asarray(jax.device_get(mask)))[0]
        cnt = len(idx)
        bucket = _gather_bucket(cnt, n)
        ids = np.zeros(bucket, dtype=np.int32)
        ids[:cnt] = idx                       # pad rows re-gather record 0;
                                              # they are sliced away below

        def gather_build():
            def gather(data, ids):
                flat = data.reshape((n,) + vshape)
                out = jnp.take(flat, ids, axis=0)
                return _constrain(out, mesh, 1)
            return jax.jit(gather)

        out = _cached_jit(("filter-gather", aligned.shape, str(aligned.dtype),
                           split, bucket, mesh), gather_build)(
            aligned._data, jnp.asarray(ids))
        if bucket == cnt:
            return self._wrap(out, 1)
        res = BoltArrayTPU(None, 1, mesh)
        res._pending = (out, cnt)
        res._resolve_pending(count=cnt)       # count already synced: the
        return res                            # slice is eager, no fetch

    def reduce(self, func, axis=(0,), keepdims=False):
        """Fixed-order pairwise tree reduction over the key axes, compiled:
        each round vmaps the binary ``func`` over half the records
        (log2(n) rounds, deterministic order — the reference's
        ``rdd.treeReduce`` has *unspecified* combine order, so this is
        stricter; SURVEY §7 hard part 2).  A deferred map chain on the
        input fuses into the same program (map→reduce reads HBM once).
        """
        func = _traceable(func)
        _engine.strict_guard(self, "reduce()")
        if self._fpending is not None:
            # deferred filter feeding the reduce: fold the predicate into
            # the pairwise tree — one fused HBM pass (see
            # _fused_filter_reduce; NotImplemented geometries resolve)
            out = self._fused_filter_reduce(func, axis, keepdims)
            if out is not NotImplemented:
                return out
        axes = sorted(tupleize(axis))
        if self._stream is not None:
            # lazy out-of-core source: stream the pairwise tree (per-slab
            # trees, cross-slab pairwise merges — fold order follows slab
            # boundaries, like the reference's treeReduce)
            out = _streamlib.maybe_reduce(self, func, tuple(axes), keepdims)
            if out is not NotImplemented:
                return out
        # lazy door while a batching-enabled serving layer is armed
        # (bolt_tpu/tpu/multistat.py): a full-key-axis reduce over a
        # plain chain defers as a pending handle so the serve scheduler
        # can coalesce same-shape requests into ONE batched dispatch;
        # standalone resolution reuses the EXACT eager program (same
        # engine key, same traced tree), so results and caching are
        # unchanged.  NotImplemented falls through to the eager path.
        from bolt_tpu.tpu import multistat as _ms
        out = _ms.defer_reduce(self, func, tuple(axes), keepdims)
        if out is not NotImplemented:
            return out
        aligned = self._align(axes)
        split = aligned._split
        kshape = aligned.shape[:split]
        vshape = aligned.shape[split:]
        n = prod(kshape)
        if n == 0:
            # same error contract as the local oracle (and functools.reduce)
            raise TypeError("reduce of an empty array with no initial value")
        mesh = self._mesh
        new_split = split if keepdims else 0

        vaval = jax.ShapeDtypeStruct(vshape, aligned._aval.dtype)
        try:
            _cached_eval_shape(
                ("reduce", func, vshape, str(vaval.dtype)),
                lambda: jax.eval_shape(func, vaval, vaval))
        except _TRACE_ERRORS as exc:
            # non-traceable reducer: host fallback through the local oracle
            _warn_fallback("reduce", func, exc)
            out = aligned.tolocal().reduce(
                func, axis=tuple(range(split)), keepdims=keepdims)
            data = _streamlib.transfer(
                np.asarray(out), key_sharding(mesh, out.shape, new_split))
            return self._wrap(data, new_split)

        # donation-aware terminal: consuming a sole-owned deferred chain
        # frees the parent buffer inside the reduction program (checked
        # BEFORE binding the base local — see _chain_donate_ok)
        donate = aligned.deferred and _chain_donate_ok(aligned._chain)
        base, funcs = aligned._chain_parts()

        def build():
            def reducer(data):
                out = _reduce_tree_expr(data, func, funcs, split, n,
                                        vshape, keepdims)
                return _constrain(out, mesh, new_split)
            return jax.jit(reducer, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("reduce", func, funcs, base.shape, str(base.dtype),
                          split, keepdims, donate, mesh), build)
        with _obs.span("array.reduce", funcs=len(funcs), donate=donate):
            out = self._wrap(fn(_check_live(base)), new_split)
        if donate:
            aligned._consume_donated("reduce()")
        return out

    # ------------------------------------------------------------------
    # statistics (reference: ``BoltArraySpark._stat/stats`` + StatCounter
    # aggregation — SURVEY §3.4; here they are single compiled XLA
    # reductions whose cross-device combine is the psum tree GSPMD inserts)
    # ------------------------------------------------------------------

    def _stat(self, axis, name, keepdims=False, ddof=None):
        _engine.strict_guard(self, "%s()" % name)
        # lazy door (bolt_tpu/tpu/multistat.py): the stat family defers
        # as a PendingStat handle — validation/strict/donation stay
        # eager here, only the dispatch moves to the first read, and
        # handles sharing this source fuse into ONE single-pass program
        # (bolt.compute / a.stats(...)).  NotImplemented falls through
        # to the eager paths (consumed sources, zero-size extrema,
        # geometries the fused machinery does not serve).
        from bolt_tpu.tpu import multistat as _ms
        out = _ms.defer_stat(self, axis, name, keepdims, ddof)
        if out is not NotImplemented:
            return out
        if self._stream is not None:
            # lazy out-of-core source: run the reduction as a streamed
            # double-buffered pipeline when the geometry allows (all key
            # axes, no keepdims); anything else materialises below
            out = _streamlib.maybe_stat(self, axis, name, keepdims, ddof)
            if out is not NotImplemented:
                return out
        if self._fpending is not None:
            # an unmaterialised filter feeding a reduction: fold the
            # predicate mask straight into the reduce — ONE fused HBM
            # pass, no compaction buffer (falls through to the resolving
            # path for geometries the fused program does not serve)
            out = self._fused_filter_stat(axis, name, keepdims, ddof)
            if out is not NotImplemented:
                return out
        if axis is None:
            axes = tuple(range(self._split)) if self._split else tuple(range(self.ndim))
        else:
            axes = tuple(sorted(tupleize(axis)))
            inshape(self.shape, axes)
        mesh = self._mesh
        split = self._split
        nkeys_reduced = sum(1 for a in axes if a < split)
        new_split = split if keepdims else split - nkeys_reduced

        # donation-aware terminal (see _chain_donate_ok: checked before
        # the base local exists)
        donate = self.deferred and _chain_donate_ok(self._chain)
        base, funcs = self._chain_parts()

        def build():
            op = {"mean": jnp.mean, "var": jnp.var, "std": jnp.std,
                  "sum": jnp.sum, "max": jnp.max, "min": jnp.min,
                  "prod": jnp.prod, "all": jnp.all, "any": jnp.any,
                  "ptp": jnp.ptp}[name]
            kwargs = {} if ddof is None else {"ddof": ddof}

            def stat(data):
                mapped = _chain_apply(funcs, split, data)
                out = op(mapped, axis=axes, keepdims=keepdims, **kwargs)
                return _constrain(out, mesh, new_split)
            return jax.jit(stat, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("stat", name, funcs, base.shape, str(base.dtype),
                          split, axes, keepdims, ddof, donate, mesh), build)
        with _obs.span("array.stat", op=name, funcs=len(funcs),
                       donate=donate):
            out = self._wrap(fn(_check_live(base)), new_split)
        if donate:
            self._consume_donated("%s()" % name)
        return out

    # identity each fusable reduction folds non-surviving records onto:
    # where(mask, v, identity) makes dropped rows (NaNs included) inert,
    # collapsing filter→reduce to ONE pass over the input
    _FUSED_STAT_NAMES = ("sum", "prod", "any", "all", "mean", "var",
                         "std", "max", "min")

    def _fused_filter_stat(self, axis, name, keepdims, ddof):
        """Single-pass ``filter(...).sum()``-family terminal: the
        predicate mask folds into the reduction combine, so the 3-pass
        mask+count+compact pipeline (and its input-sized compaction
        buffer) never runs.  Returns NotImplemented for geometries the
        fused program does not serve (the caller resolves and takes the
        materialising path):

        * reductions that keep the (dynamic) key axis — the output shape
          would need the survivor count;
        * ``ptp`` (needs both extrema identities at once) and
          complex-var/std (resolve instead of reimplementing numpy's
          abs²-moment rules);
        * ``max``/``min`` ARE fused but sync the survivor count (one
          scalar fetch, same price the eager path pays) to preserve the
          zero-size reduction error.

        ``mean``/``var``/``std`` divide by the masked COUNT (computed in
        the same pass); var uses the one-pass moment form
        ``(Σx² − (Σx)²/n)/(n−ddof)`` — single HBM read, documented as
        slightly less cancellation-robust than the two-pass eager form."""
        vshape = self._fpending[4]
        ndim = 1 + len(vshape)
        if axis is None:
            axes = (0,)                      # the flat key axis (split=1)
        else:
            axes = tuple(sorted(tupleize(axis)))
            for a in axes:
                if not 0 <= a < ndim:
                    return NotImplemented    # let the eager path reject
        if 0 not in axes or name not in self._FUSED_STAT_NAMES:
            return NotImplemented
        vdtype = np.dtype(self._fpending[6])
        if name in ("var", "std") and np.issubdtype(vdtype,
                                                    np.complexfloating):
            return NotImplemented
        donate = _chain_donate_ok(self._fpending)    # [0] is the base
        base, funcs, pred, psplit, vshape, n, _ = self._fpending
        mesh = self._mesh
        new_split = 1 if keepdims else 0
        needs_count = name in ("max", "min")

        def build():
            def stat(data):
                mapped = _chain_apply(funcs, psplit, data)
                flat = mapped.reshape((n,) + tuple(vshape))
                mask = _pred_mask(pred, flat)
                mfull = mask.reshape((n,) + (1,) * len(vshape))
                cnt = jnp.sum(mask, dtype=jnp.int32)
                # the per-terminal masked reduction lives in ONE module
                # function, shared with the fused multi-terminal
                # program (bolt_tpu/tpu/multistat.py) — single and
                # fused filter-stats trace identical arithmetic
                out = _masked_stat_expr(name, flat, mask, mfull, axes,
                                        keepdims, ddof, vshape, vdtype)
                out = _constrain(out, mesh, new_split)
                return (out, cnt) if needs_count else out
            return jax.jit(stat, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("filter-stat", name, pred, funcs, base.shape,
                          str(base.dtype), psplit, axes, keepdims, ddof,
                          donate, mesh), build)
        out = fn(_check_live(base))
        if donate:
            # mark consumption BEFORE any error path below: the program
            # already took the buffer, and a zero-survivor raise must
            # leave this array guarded, not pointing at a deleted base
            self._consume_donated("filter().%s()" % name)
        if needs_count:
            out, cnt = out
            if int(jax.device_get(cnt)) == 0:
                # match the eager path's zero-size reduction rejection
                raise ValueError(
                    "zero-size array to reduction operation %s which has "
                    "no identity" % name)
        return self._wrap(out, new_split)

    def _fused_filter_reduce(self, func, axis, keepdims):
        """Single-pass ``filter(...).reduce(func)``: the pairwise tree
        carries a VALIDITY bit per slot — combining a valid with an
        invalid slot selects the valid operand unchanged (no identity
        element needed for arbitrary ``func``; garbage from combining
        dropped records, NaNs included, is discarded by the select).  One
        scalar sync of the survivor count afterwards preserves the
        empty-reduce error contract.  NotImplemented (→ resolve-and-
        materialise path) off the flat key axis or for non-traceable
        reducers."""
        axes = tuple(sorted(tupleize(axis)))
        if axes != (0,):
            return NotImplemented
        donate = _chain_donate_ok(self._fpending)    # [0] is the base
        base, funcs, pred, psplit, vshape, n, vdtype = self._fpending
        if n == 0:
            raise TypeError("reduce of an empty array with no initial value")
        vaval = jax.ShapeDtypeStruct(tuple(vshape), vdtype)
        try:
            _cached_eval_shape(
                ("reduce", func, tuple(vshape), str(vdtype)),
                lambda: jax.eval_shape(func, vaval, vaval))
        except _TRACE_ERRORS:
            return NotImplemented            # host fallback path resolves
        mesh = self._mesh
        new_split = 1 if keepdims else 0

        def build():
            def reducer(data):
                mapped = _chain_apply(funcs, psplit, data)
                flat = mapped.reshape((n,) + tuple(vshape))
                mask = _pred_mask(pred, flat)
                cnt = jnp.sum(mask, dtype=jnp.int32)
                vfunc = jax.vmap(func)

                def bc(m, like):
                    return m.reshape(m.shape + (1,) * (like.ndim - 1))

                x, valid = flat, mask
                while x.shape[0] > 1:
                    half = x.shape[0] // 2
                    a, b = x[:half], x[half:2 * half]
                    va, vb = valid[:half], valid[half:2 * half]
                    comb = vfunc(a, b)
                    if comb.shape != a.shape:
                        raise ValueError(
                            "reduce produced shape %s, expected value "
                            "shape %s" % (comb.shape[1:], tuple(vshape)))
                    # both valid → combined; one valid → that operand
                    # (combined may be garbage and is discarded)
                    sel = jnp.where(bc(va & vb, comb), comb,
                                    jnp.where(bc(va, comb), a, b))
                    vsel = va | vb
                    rem, vrem = x[2 * half:], valid[2 * half:]
                    if rem.shape[0]:
                        x = jnp.concatenate([sel, rem], axis=0)
                        valid = jnp.concatenate([vsel, vrem], axis=0)
                    else:
                        x, valid = sel, vsel
                out = x[0]
                if out.shape != tuple(vshape):
                    raise ValueError(
                        "reduce produced shape %s, expected value shape %s"
                        % (out.shape, tuple(vshape)))
                if keepdims:
                    out = out.reshape((1,) + tuple(vshape))
                return _constrain(out, mesh, new_split), cnt
            return jax.jit(reducer, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("filter-reduce", func, pred, funcs, base.shape,
                          str(base.dtype), psplit, keepdims, donate, mesh),
                         build)
        out, cnt = fn(_check_live(base))
        if donate:
            # before the zero-survivor raise: the buffer is already gone,
            # so the array must carry the guard, not the deleted base
            self._consume_donated("filter().reduce()")
        if int(jax.device_get(cnt)) == 0:
            # every record was filtered out: same contract as reducing an
            # (0, ...)-shaped resolved result
            raise TypeError("reduce of an empty array with no initial value")
        return self._wrap(out, new_split)

    def mean(self, axis=None, keepdims=False):
        """Mean over ``axis`` (default: all key axes)."""
        return self._stat(axis, "mean", keepdims)

    def var(self, axis=None, keepdims=False, ddof=0):
        """Variance over ``axis`` (``ddof=0`` population default, matching
        the reference StatCounter; ``ddof=1`` for the sample variance,
        like the ndarray method the local backend inherits; fractional
        ddof passes through like numpy's)."""
        return self._stat(axis, "var", keepdims, ddof=ddof)

    def std(self, axis=None, keepdims=False, ddof=0):
        """Standard deviation over ``axis`` (``ddof`` like :meth:`var`)."""
        return self._stat(axis, "std", keepdims, ddof=ddof)

    def ptp(self, axis=None, keepdims=False):
        """Peak-to-peak (max − min) over ``axis`` — the ndarray method
        (numpy ≥2 spells it ``np.ptp``); one compiled program."""
        return self._stat(axis, "ptp", keepdims)

    def sum(self, axis=None, keepdims=False):
        return self._stat(axis, "sum", keepdims)

    def max(self, axis=None, keepdims=False):
        return self._stat(axis, "max", keepdims)

    def min(self, axis=None, keepdims=False):
        return self._stat(axis, "min", keepdims)

    def prod(self, axis=None, keepdims=False):
        """Product over ``axis`` — this backend's mean-family convention
        (default: all KEY axes, unlike bare ``ndarray.prod()`` which
        reduces everything; pass ``axis=tuple(range(b.ndim))`` for the
        full reduction), as one compiled program."""
        return self._stat(axis, "prod", keepdims)

    def all(self, axis=None, keepdims=False):
        """Truth-reduction AND over ``axis`` (mean-family convention:
        default reduces the key axes — see :meth:`prod`)."""
        return self._stat(axis, "all", keepdims)

    def any(self, axis=None, keepdims=False):
        """Truth-reduction OR over ``axis`` (mean-family convention:
        default reduces the key axes — see :meth:`prod`)."""
        return self._stat(axis, "any", keepdims)

    def cumsum(self, axis=None):
        """Cumulative sum (ndarray semantics: int axis, negative wrap, or
        ``None`` for the cumsum of the FLATTENED array, returned with a
        single flat key axis like ``filter``'s output convention)."""
        return self._cum("cumsum", axis)

    def cumprod(self, axis=None):
        """Cumulative product (ndarray semantics, see :meth:`cumsum`)."""
        return self._cum("cumprod", axis)

    def _one_axis(self, axis):
        """Normalise a single-int axis (Integral check, negative wrap,
        range check) — shared by argmax/argmin/cumsum/cumprod."""
        from numbers import Integral
        if not isinstance(axis, Integral):
            # TypeError matches the inherited ndarray methods on the local
            # backend, so portable error handling sees one exception type
            raise TypeError("axis %r is not an integer" % (axis,))
        axis = int(axis)
        if axis < 0:
            axis += self.ndim
        inshape(self.shape, (axis,))
        return axis

    def _cum(self, name, axis):
        if axis is not None:
            axis = self._one_axis(axis)
        mesh = self._mesh
        split = self._split
        new_split = (1 if split else 0) if axis is None else split
        # memory model: input + full-size output (dtype may widen: bool
        # cumsum counts in the canonical int) — inherent to the op, so
        # the guard is the up-front demand check, not a bounded path
        out_item = np.dtype(_canon(np.cumsum(
            np.zeros(1, self.dtype)).dtype)).itemsize
        hbm_check(name, self.size * (self.dtype.itemsize + out_item),
                  "input + full-size output")
        base, funcs = self._chain_parts()

        def build():
            op = {"cumsum": jnp.cumsum, "cumprod": jnp.cumprod}[name]

            def run(data):
                mapped = _chain_apply(funcs, split, data)
                out = op(mapped, axis=axis)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("cum", name, funcs, base.shape, str(base.dtype),
                          split, axis, mesh), build)
        return self._wrap(fn(_check_live(base)), new_split)

    def stats(self, *requested, axis=None, accumulate=None, **kwargs):
        """Statistics in one pass, two forms:

        * ``stats()`` / ``stats(("mean", "var"))`` /
          ``stats(requested=..., axis=...)`` — the reference contract: a
          :class:`~bolt_tpu.statcounter.StatCounter` of Welford moments
          via the explicit shard_map combine
          (``bolt_tpu/tpu/stats.py :: welford``).
        * ``stats("sum", "var", "min", ...)`` — the fluent FUSED
          multi-stat (bolt_tpu/tpu/multistat.py): every requested
          terminal (any of sum/mean/var/std/min/max/prod/all/any/ptp)
          from ONE single-pass program over this array — deferred
          chains applied once, streamed sources ingested once — each
          result bit-identical to its standalone terminal; returns an
          ordered ``{name: value-shaped array}`` dict.  ``accumulate``
          opts the additive terminals into the reduced-precision path
          (see :func:`bolt_tpu.tpu.multistat.compute`).
        """
        if requested and all(isinstance(r, str) for r in requested):
            from bolt_tpu.tpu.multistat import fluent_stats
            return fluent_stats(self, requested, axis=axis,
                                accumulate=accumulate)
        from bolt_tpu.tpu.stats import welford
        if requested:
            # legacy positional form: stats(requested_tuple[, axis])
            if len(requested) > 2:
                raise TypeError("stats() takes at most 2 positional "
                                "arguments (requested, axis)")
            kwargs.setdefault("requested", requested[0])
            if len(requested) == 2:
                if axis is not None:
                    raise TypeError("stats() got axis twice")
                axis = requested[1]
        return welford(self, axis=axis, **kwargs)

    def quantile(self, q, axis=None, keepdims=False, method="linear"):
        """The ``q``-th quantile over ``axis`` (default: all key axes) —
        one compiled program (XLA sorts on device; GSPMD gathers the
        reduced axes as needed).  ``q``: a scalar or a 1-d array of values
        in [0, 1]; a 1-d ``q`` prepends a q axis to the result, exactly
        like ``np.quantile`` — that new axis is a flat KEY axis (the same
        convention as ``filter``'s flat output key), so the remaining key
        axes stay leading.  Superset of the reference (no quantiles in
        Bolt/StatCounter)."""
        from bolt_tpu.utils import check_q
        qarr = check_q(q)
        vector_q = qarr.ndim == 1
        if axis is None:
            axes = tuple(range(self._split)) if self._split \
                else tuple(range(self.ndim))
        else:
            axes = tuple(sorted(tupleize(axis)))
            inshape(self.shape, axes)
        mesh = self._mesh
        split = self._split
        nkeys_reduced = sum(1 for a in axes if a < split)
        new_split = (split if keepdims else split - nkeys_reduced) \
            + (1 if vector_q else 0)
        base, funcs = self._chain_parts()

        def build():
            # q is a traced ARGUMENT, not a trace constant: sweeping many
            # quantiles reuses one compiled program instead of recompiling
            # (and re-caching) per q (per q-LENGTH for vector q — jit
            # retraces per aval internally)
            def stat(data, qv):
                mapped = _chain_apply(funcs, split, data)
                xf = mapped.astype(jnp.promote_types(mapped.dtype,
                                                     jnp.float32))
                out = jnp.quantile(xf, jnp.asarray(qv, xf.dtype), axis=axes,
                                   keepdims=keepdims, method=method)
                return _constrain(out, mesh, new_split)
            return jax.jit(stat)

        fn = _cached_jit(("quantile", method, funcs, base.shape,
                          str(base.dtype), split, axes, keepdims, vector_q,
                          mesh), build)
        return self._wrap(fn(_check_live(base),
                             qarr if vector_q else float(q)), new_split)

    def median(self, axis=None, keepdims=False):
        """Median over ``axis`` (default: all key axes)."""
        return self.quantile(0.5, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        """Index of the maximum along ONE axis (numpy semantics: an int
        axis, or ``None`` for the index into the flattened array) — the
        local backend inherits exactly this from ``ndarray``.  One
        compiled program; ties resolve to the first occurrence, like
        numpy."""
        return self._arg_stat("argmax", axis, keepdims)

    def argmin(self, axis=None, keepdims=False):
        """Index of the minimum along ONE axis (numpy semantics)."""
        return self._arg_stat("argmin", axis, keepdims)

    def _arg_stat(self, name, axis, keepdims):
        if axis is not None:
            axis = self._one_axis(axis)
        mesh = self._mesh
        split = self._split
        if axis is None:
            new_split = 0
        else:
            new_split = split - (1 if axis < split and not keepdims else 0)
        base, funcs = self._chain_parts()

        def build():
            op = {"argmax": jnp.argmax, "argmin": jnp.argmin}[name]

            def stat(data):
                mapped = _chain_apply(funcs, split, data)
                out = op(mapped, axis=axis, keepdims=keepdims)
                return _constrain(out, mesh, new_split)
            return jax.jit(stat)

        fn = _cached_jit(("argstat", name, funcs, base.shape,
                          str(base.dtype), split, axis, keepdims, mesh),
                         build)
        return self._wrap(fn(_check_live(base)), new_split)

    # ------------------------------------------------------------------
    # elementwise operators
    #
    # The reference's Spark array has NO operator overloads — elementwise
    # math goes through ``map`` (SURVEY §2.2) and only the local ndarray
    # subclass gets them from numpy.  Providing them here is a deliberate
    # superset: the same expressions now run on both backends.  Scalar
    # operands defer (fuse into the map chain); array operands broadcast
    # against the full logical shape in one compiled program.
    # ------------------------------------------------------------------

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        """Route numpy ufunc calls into the deferred map chain, so
        ``np.sin(b)`` / ``np.add(x, b)`` work identically on both backends
        (the local backend inherits this from ndarray — VERDICT r1 weak-3).
        Plain ``__call__`` with a jnp twin is served, and so are the
        binary-ufunc METHODS ``reduce``/``accumulate``/``outer``/
        ``reduceat`` (the local backend answers those natively through
        ndarray — VERDICT r4 missing-3); ``out=``/``where=``/``at`` and
        multi-output ufuncs return NotImplemented rather than silently
        gathering the distributed array to host through ``__array__``."""
        if method in ("reduce", "accumulate", "outer", "reduceat"):
            return self._ufunc_method(ufunc, method, inputs, kwargs)
        if method != "__call__" or kwargs or ufunc.nout != 1:
            return NotImplemented
        jf = getattr(jnp, ufunc.__name__, None)
        if jf is None or len(inputs) not in (1, 2):
            return NotImplemented
        if len(inputs) == 1:
            return self._unary(jf)
        a, b = inputs
        if ufunc.__name__ == "matmul":
            # contraction, not elementwise: route around the broadcast check
            return self._matmul(b if a is self else a, reverse=a is not self)
        if a is self:
            return self._elementwise(b, jf)
        return self._elementwise(a, jf, reverse=True)

    def _ufunc_method(self, ufunc, method, inputs, kwargs):
        """Device lowerings for the ufunc *methods* — ``np.add.reduce(b)``,
        ``np.multiply.accumulate(b)``, ``np.subtract.outer(b, w)``,
        ``np.add.reduceat(b, idx)`` — ONE fused program each through the
        ``jnp.ufunc`` twins, so the method surface answers identically on
        both backends (reference: the ndarray-native methods of
        ``bolt/local/array.py`` — SURVEY §2.3; VERDICT r4 missing-3 named
        this the one known cross-backend divergence).  Binary ufuncs with
        callable-but-unwrapped jnp twins (e.g. ``np.hypot``) are wrapped
        via ``jnp.frompyfunc`` with the numpy identity.  ``out=`` /
        non-default ``where=`` / ``at`` stay NotImplemented → TypeError,
        never a silent host gather."""
        from bolt_tpu.tpu.npdispatch import _device_fused
        if ufunc.nin != 2 or ufunc.nout != 1:
            return NotImplemented
        jf = getattr(jnp, ufunc.__name__, None)
        if jf is None:
            return NotImplemented
        if not isinstance(jf, jnp.ufunc):
            if not callable(jf):
                return NotImplemented
            jf = jnp.frompyfunc(jf, 2, 1, identity=ufunc.identity)
        kwargs = dict(kwargs)
        if kwargs.pop("out", None) is not None:
            return NotImplemented          # in-place target: explicit no
        where = kwargs.pop("where", True)
        if where is not True and not (np.ndim(where) == 0
                                      and bool(np.asarray(where))):
            return NotImplemented          # masked reduce: explicit no
        name = ufunc.__name__

        if method == "reduce":
            if len(inputs) != 1 or inputs[0] is not self:
                return NotImplemented
            axis = kwargs.pop("axis", 0)
            dtype = kwargs.pop("dtype", None)
            keepdims = kwargs.pop("keepdims", False)
            initial = kwargs.pop("initial", None)
            if kwargs:
                return NotImplemented
            if initial is not None and not isinstance(initial, (int, float,
                                                                complex)):
                if np.ndim(initial) == 0:
                    initial = np.asarray(initial).item()
                else:
                    return NotImplemented
            if name not in _UFUNC_FOLD_SAFE:
                return NotImplemented      # see _UFUNC_FOLD_SAFE
            if axis is None:
                axes = tuple(range(self.ndim))
            else:
                axes = tuple(sorted(self._one_axis(a)
                                    for a in tupleize(axis)))
                if len(set(axes)) != len(axes):
                    raise ValueError("duplicate value in 'axis'")
            if len(axes) > 1:
                # let numpy itself validate multi-axis reducibility on a
                # one-element dummy: non-reorderable ufuncs (subtract,
                # divide) must raise its exact ValueError here, not take
                # the sequential device path to an order-dependent value
                ufunc.reduce(np.zeros((1,) * self.ndim, self.dtype),
                             axis=axes)
            split = self._split
            nkeys = sum(1 for a in axes if a < split)
            new_split = split if (keepdims or not axes) else split - nkeys
            dt = None if dtype is None else _canon(dtype)

            # XLA rejects a cross-partition xor reduce computation
            # (UNIMPLEMENTED: Unsupported reduction computation), so a
            # key-axis xor cannot ride the GSPMD all-reduce.  Logical
            # parity is exactly a mod-2 sum — served below; the per-bit
            # bitwise form has no cheap collective and rejects loudly.
            if name == "bitwise_xor" and any(a < split for a in axes):
                return NotImplemented
            if name == "logical_xor" and axes:
                def body(v):
                    ax = axes if len(axes) > 1 else axes[0]
                    out = (jnp.sum(v.astype(bool).astype(jnp.int32),
                                   axis=ax, keepdims=keepdims) % 2
                           ).astype(bool)
                    if initial is not None:
                        out = jnp.logical_xor(out, bool(initial))
                    return out if dt is None else out.astype(dt)
                return _device_fused(
                    "ufunc_reduce", [self], self, new_split, body,
                    (name, axes, str(dt), keepdims,
                     type(initial).__name__, initial))

            def body(v):
                if not axes:
                    # numpy's axis=() applies op(initial, elem) per element
                    out = v.astype(dt) if dt is not None else v
                    return out if initial is None else jf(initial, out)
                if len(axes) == 1:
                    return jf.reduce(v, axis=axes[0], dtype=dt,
                                     keepdims=keepdims, initial=initial)
                try:
                    return jf.reduce(v, axis=axes, dtype=dt,
                                     keepdims=keepdims, initial=initial)
                except NotImplementedError:
                    # frompyfunc-wrapped twins reduce one axis per pass
                    # (scan lowering); ``initial`` joins only the LAST
                    # pass so each output element folds it exactly once
                    out = v
                    for i, ax in enumerate(reversed(axes)):
                        last = i == len(axes) - 1
                        out = jf.reduce(
                            out, axis=ax, dtype=dt, keepdims=keepdims,
                            initial=initial if last else None)
                    return out
            return _device_fused(
                "ufunc_reduce", [self], self, new_split, body,
                (name, axes, str(dt), keepdims,
                 type(initial).__name__, initial))

        if method == "accumulate":
            if len(inputs) != 1 or inputs[0] is not self:
                return NotImplemented
            axis = kwargs.pop("axis", 0)
            dtype = kwargs.pop("dtype", None)
            if kwargs:
                return NotImplemented
            if axis is None:               # numpy's exact rejection
                raise ValueError("accumulate does not allow multiple axes")
            axis = self._one_axis(axis)
            dt = None if dtype is None else _canon(dtype)
            # memory model mirrors _cum: input + full-size output, with
            # the output dtype taken from numpy's own promotion rule
            try:
                out_dt = ufunc.accumulate(np.zeros(1, self.dtype)).dtype
            except Exception:
                out_dt = self.dtype
            out_item = np.dtype(_canon(dt or out_dt)).itemsize
            hbm_check("%s.accumulate" % name,
                      self.size * (self.dtype.itemsize + out_item),
                      "input + full-size output")

            def body(v):
                return jf.accumulate(v, axis=axis, dtype=dt)
            return _device_fused(
                "ufunc_accumulate", [self], self, self._split, body,
                (name, axis, str(dt)))

        if method == "outer":
            dtype = kwargs.pop("dtype", None)
            if kwargs or len(inputs) != 2:
                return NotImplemented
            dt = None if dtype is None else _canon(dtype)
            a, b = inputs
            # keys survive only when the LEADING operand carries them (its
            # axes lead the outer's result); otherwise the result is
            # replicated — correct, and guarded by the demand check below
            new_split = a.split if isinstance(a, BoltArrayTPU) else 0
            out_dt = dt if dt is not None else np.result_type(
                getattr(a, "dtype", type(a)), getattr(b, "dtype", type(b)))
            in_bytes = sum(
                int(np.size(op)) * np.dtype(
                    _canon(getattr(op, "dtype", out_dt))).itemsize
                for op in (a, b))
            hbm_check("%s.outer" % name,
                      int(np.size(a)) * int(np.size(b))
                      * np.dtype(_canon(out_dt)).itemsize + in_bytes,
                      "both inputs + full outer product")

            def body(x, y):
                out = jf.outer(x, y)
                return out if dt is None else out.astype(dt)
            return _device_fused("ufunc_outer", [a, b], self, new_split,
                                 body, (name, str(dt)))

        if method == "reduceat":
            if len(inputs) != 2 or inputs[0] is not self:
                return NotImplemented
            axis = kwargs.pop("axis", 0)
            dtype = kwargs.pop("dtype", None)
            if kwargs or name not in _UFUNC_FOLD_SAFE:
                return NotImplemented
            if axis is None:               # numpy's exact rejection
                raise ValueError("reduceat does not allow multiple axes")
            axis = self._one_axis(axis)
            dt = None if dtype is None else _canon(dtype)
            # the indices ride through _device_fused as a runtime operand
            # (bolt arrays fuse on device — no silent host gather; host
            # lists are device-coerced once); executables cache by shape
            indices = inputs[1]
            if np.ndim(indices) != 1:
                return NotImplemented
            if not isinstance(indices, BoltArrayTPU):
                # host-visible indices validate up front (numpy raises
                # IndexError where jax's gather would silently clamp);
                # distributed index arrays are exempt — checking them
                # would be the silent gather this method forbids
                n_ax = self.shape[axis]
                host_idx = np.asarray(indices)
                bad = (host_idx < 0) | (host_idx >= n_ax)
                if host_idx.size and bad.any():
                    raise IndexError(
                        "index %d out-of-bounds in %s.reduceat [0, %d)"
                        % (int(host_idx[bad][0]), name, n_ax))
            nidx = int(np.shape(indices)[0])
            out_elems = (self.size // max(self.shape[axis], 1)) * nidx
            hbm_check("%s.reduceat" % name,
                      self.size * self.dtype.itemsize
                      + out_elems * np.dtype(_canon(dt or self.dtype)
                                             ).itemsize,
                      "input + one output slot per index")

            def body(v, idx):
                return jf.reduceat(v, idx, axis=axis, dtype=dt)
            return _device_fused(
                "ufunc_reduceat", [self, indices], self, self._split,
                body, (name, axis, str(dt)))

        return NotImplemented

    def _scalar_fn(self, op, other, reverse):
        """A per-(op, scalar) callable with a STABLE identity, so deferred
        chains built from repeated scalar expressions hit the jit cache
        instead of recompiling per fresh lambda.

        The key includes the scalar's TYPE: dict lookup hashes by
        equality and ``0 == 0.0 == False``, so without it ``b * 2.0``
        after ``b * 2`` would reuse the int-closing callable and silently
        change an integer array's result dtype."""
        key = (op.__name__, type(other).__name__, other, reverse)
        fn = _SCALAR_FN_CACHE.get(key)
        if fn is None:
            if reverse:
                def fn(v, _op=op, _o=other):
                    return _op(_o, v)
            else:
                def fn(v, _op=op, _o=other):
                    return _op(v, _o)
            _SCALAR_FN_CACHE[key] = fn
            if len(_SCALAR_FN_CACHE) > _JIT_CACHE_MAX:
                _SCALAR_FN_CACHE.popitem(last=False)
        else:
            _SCALAR_FN_CACHE.move_to_end(key)
        return fn

    def _coerce_operand(self, other):
        """Device-side coercion of a non-bolt operand.  A ``jax.Array``
        already on this mesh's devices feeds the compiled op directly
        (bouncing it through ``np.asarray`` would round-trip device→host→
        device per call — measured 12 s for a 0.27 GB weight through a
        remote attach — and outright fails for non-addressable arrays); an
        array committed elsewhere (another backend/device) takes the host
        path so mixed-device code keeps working."""
        if isinstance(other, jax.Array):
            try:
                if set(other.devices()).issubset(
                        set(self._mesh.devices.flat)):
                    return other
            except Exception:
                pass
        return _complex_safe_put(np.asarray(other))

    def _coerce_bolt_operand(self, value, what):
        """Unwrap a possibly-bolt operand for a compiled program: a
        same-mesh TPU array passes through as its device data (foreign
        meshes get :meth:`_check_mesh`'s loud rejection), a local array
        gathers to host; anything else returns unchanged.  ONE home for
        the contract shared by ``set``/``searchsorted``/
        ``segment_reduce`` labels."""
        from bolt_tpu.base import BoltArray
        if isinstance(value, BoltArray):
            if value.mode == "tpu":
                self._check_mesh(value, what)
                return value.tojax()
            return np.asarray(value)
        return value

    def _check_mesh(self, other, what):
        """Binary ops take same-mesh operands only: silently constraining a
        foreign-mesh array to ``self``'s mesh would hide a (potentially
        DCN-wide) data move, or die later in XLA with an opaque error
        (VERDICT r1 weak-5)."""
        if other._mesh != self._mesh:
            raise ValueError(
                "%s operands live on different meshes (%s vs %s); move one "
                "explicitly first, e.g. other.tolocal().totpu(context=self."
                "mesh) or bolt_tpu.parallel.reshard" % (
                    what, getattr(self._mesh, "shape_tuple", self._mesh),
                    getattr(other._mesh, "shape_tuple", other._mesh)))

    def _elementwise(self, other, op, reverse=False):
        opname = op.__name__
        if isinstance(other, (int, float, complex, np.number)):
            fn = self._scalar_fn(op, other, reverse)
            if self._split == 0:
                out = _cached_jit(
                    ("ew0", opname, type(other).__name__, other, self.shape,
                     str(self.dtype), reverse, self._mesh),
                    lambda: jax.jit(fn))(self._data)
                return self._wrap(out, 0)
            return self.map(fn, axis=tuple(range(self._split)))
        if isinstance(other, BoltArrayTPU):
            self._check_mesh(other, "elementwise")
            odata = other._data
        elif isinstance(other, BoltArray):
            odata = _complex_safe_put(other.toarray())
        else:
            odata = self._coerce_operand(other)
        # numpy broadcasting is symmetric: the result may OUTGROW self
        # (np.ones(8) * b_scalar).  Keys survive while they remain the
        # leading axes with unchanged lengths; a result that gains
        # leading dims is replicated.  (The shape-mismatch ValueError
        # for incompatible operands comes from broadcast_shapes itself.)
        out_shape = np.broadcast_shapes(self.shape, odata.shape)
        mesh, split = self._mesh, self._split
        if out_shape != self.shape:
            if len(out_shape) != self.ndim or \
                    out_shape[:split] != self.shape[:split]:
                split = 0
            out_item = np.dtype(_canon(np.result_type(
                self.dtype, odata.dtype))).itemsize
            need = int(np.prod(out_shape)) * out_item \
                + self.size * self.dtype.itemsize \
                + int(odata.size) * odata.dtype.itemsize
            hbm_check(opname, need, "both inputs + broadcast output")

        def build():
            def run(a, b):
                out = op(b, a) if reverse else op(a, b)
                return _constrain(out, mesh, split)
            return jax.jit(run)

        fn = _cached_jit(("ew", opname, self.shape, tuple(odata.shape),
                          str(self.dtype), str(odata.dtype), split, reverse,
                          mesh), build)
        return self._wrap(fn(self._data, odata), split)

    def __add__(self, other):
        return self._elementwise(other, jnp.add)

    __radd__ = __add__

    def __sub__(self, other):
        return self._elementwise(other, jnp.subtract)

    def __rsub__(self, other):
        return self._elementwise(other, jnp.subtract, reverse=True)

    def __mul__(self, other):
        return self._elementwise(other, jnp.multiply)

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._elementwise(other, jnp.divide)

    def __rtruediv__(self, other):
        return self._elementwise(other, jnp.divide, reverse=True)

    def __pow__(self, other):
        return self._elementwise(other, jnp.power)

    def __mod__(self, other):
        return self._elementwise(other, jnp.mod)

    def __rmod__(self, other):
        return self._elementwise(other, jnp.mod, reverse=True)

    def __rpow__(self, other):
        return self._elementwise(other, jnp.power, reverse=True)

    def __floordiv__(self, other):
        return self._elementwise(other, jnp.floor_divide)

    def __rfloordiv__(self, other):
        return self._elementwise(other, jnp.floor_divide, reverse=True)

    def _matmul(self, other, reverse=False, op=jnp.matmul,
                precision=None):
        """Contraction with ndarray semantics (``op`` = ``jnp.matmul`` for
        ``@``, ``jnp.dot`` for :meth:`dot`), batched over the key axes:
        ONE compiled program on the full logical array — the MXU-shaped
        path, far better than a per-record map.  The key axes stay
        key-sharded whenever they survive as leading output axes;
        otherwise (contracted or displaced by broadcasting) the result is
        re-keyed to ``split=0``.  ``precision=None`` resolves through the
        scoped policy (``bolt.precision``), pinned at "highest"."""
        from bolt_tpu._precision import resolve
        precision = resolve(precision)
        if isinstance(other, BoltArrayTPU):
            self._check_mesh(other, op.__name__)
            odata = other._data
        elif isinstance(other, BoltArray):
            odata = _complex_safe_put(other.toarray())
        else:
            odata = self._coerce_operand(other)
        # self.shape (not _aval, which is None on a pending filter result)
        # resolves the lazy survivor count first
        self_aval = jax.ShapeDtypeStruct(self.shape, self.dtype)
        a_aval = jax.ShapeDtypeStruct(odata.shape, odata.dtype) if reverse \
            else self_aval
        b_aval = self_aval if reverse \
            else jax.ShapeDtypeStruct(odata.shape, odata.dtype)
        # shape/dtype validation without execution; numpy raises
        # ValueError for contraction mismatches where jax raises
        # TypeError — normalise so portable error handling sees one type
        try:
            out_aval = jax.eval_shape(op, a_aval, b_aval)
        except TypeError as e:
            raise ValueError(str(e)) from None
        out_shape = tuple(out_aval.shape)
        split = self._split
        # keys survive when they still lead the output: self contributes
        # its batch dims plus (non-reverse) its row axis, so key axes past
        # `cap` are contracted; extra broadcast batch dims from a
        # higher-rank operand can displace the keys (matmul prepends them;
        # dot appends, but the conservative re-key is merely suboptimal)
        cap = self.ndim - (2 if reverse else 1)
        new_split = min(split, max(cap, 0))
        if (len(odata.shape) > self.ndim
                or out_shape[:new_split] != self.shape[:new_split]):
            new_split = 0
        mesh = self._mesh

        def build():
            def run(a, b):
                # default "highest": f32 accumulation on the MXU, matching
                # the numpy oracle to ulp level — TPU's native bf16 passes
                # diverge at ~1e-2 but run ~2.8x faster (measured 45 vs
                # 16 ms on 8192^2; dot(precision=) opts in)
                out = op(b, a, precision=precision) if reverse \
                    else op(a, b, precision=precision)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit((op.__name__, self.shape, tuple(odata.shape),
                          str(self.dtype), str(odata.dtype), split, reverse,
                          str(precision), mesh), build)
        return self._wrap(fn(self._data, odata), new_split)

    def __matmul__(self, other):
        return self._matmul(other)

    def __rmatmul__(self, other):
        return self._matmul(other, reverse=True)

    def dot(self, other, *, precision=None):
        """``numpy.dot`` semantics (the ndarray method the local backend
        inherits): matrix product for 2-d, inner product for 1-d, and for
        higher ranks the sum-product over self's LAST axis and ``other``'s
        second-to-last — which differs from ``@``'s stacked matmul.  One
        compiled MXU program.

        ``precision`` (keyword-only — ndarray.dot's second POSITIONAL is
        ``out``, which this backend does not take): ``None`` resolves
        through the scoped policy (``bolt.precision``), pinned at
        ``"highest"`` — f32 MXU accumulation, ulp-level numpy parity;
        ``"default"`` (bf16 passes) measured 2.8x faster on an 8192x8192
        product at ~1e-2 relative error.  ``@`` follows the SCOPE (the
        operator spelling cannot carry options) and stays "highest"
        outside one."""
        return self._matmul(other, op=jnp.dot, precision=precision)

    def take(self, indices, axis=None, mode="raise"):
        """Select elements by index (the ndarray method the local backend
        inherits): ``axis=None`` indexes the flattened array (result
        re-keyed to a flat key axis), an int axis gathers along it —
        numpy semantics, one compiled program.  ``mode``: ``'raise'``
        (default — negative wrap, out-of-bounds rejected), ``'wrap'``
        (modular), ``'clip'``.  Index-dtype rules follow numpy exactly:
        float NDARRAYS are rejected, float sequences/scalars truncate,
        booleans cast to 0/1 (not masks)."""
        if mode not in ("raise", "wrap", "clip"):
            raise ValueError("mode must be 'raise', 'wrap' or 'clip', "
                             "got %r" % (mode,))
        arraylike = isinstance(indices, np.ndarray) or (
            hasattr(indices, "__array__")
            and not isinstance(indices, (list, tuple)))
        idx = np.asarray(indices)
        if idx.dtype == bool:
            idx = idx.astype(np.intp)
        elif not np.issubdtype(idx.dtype, np.integer):
            if arraylike:
                raise TypeError(
                    "Cannot cast take indices from %s to integer"
                    % (idx.dtype,))
            idx = np.trunc(idx).astype(np.intp)   # numpy truncates sequences
        if axis is not None:
            axis = self._one_axis(axis)
        dim = prod(self.shape) if axis is None else self.shape[axis]
        if mode == "wrap":
            wrapped = idx % dim
        elif mode == "clip":
            wrapped = np.clip(idx, 0, dim - 1)
        else:
            wrapped = np.where(idx < 0, idx + dim, idx)
            if idx.size and (wrapped.min() < 0 or wrapped.max() >= dim):
                raise IndexError(
                    "take index out of bounds for size %d" % dim)
        mesh = self._mesh
        split = self._split
        new_split = (1 if split and idx.ndim else 0) if axis is None \
            else (split if axis >= split or idx.ndim == 1
                  else split + idx.ndim - 1)
        base, funcs = self._chain_parts()

        def build():
            def run(data, ids):
                mapped = _chain_apply(funcs, split, data)
                if axis is None:
                    out = jnp.take(mapped.reshape(-1), ids, axis=0)
                else:
                    out = jnp.take(mapped, ids, axis=axis)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("take", funcs, base.shape, str(base.dtype),
                          split, axis, idx.shape, mesh), build)
        out = fn(_check_live(base), jnp.asarray(wrapped, dtype=jnp.int32))
        return self._wrap(out, new_split)

    def argsort(self, axis=-1, kind=None):
        """Indices that would sort along ``axis`` (ndarray semantics:
        default LAST axis; ``None`` flattens to a 1-d result, re-keyed to
        a flat key axis like ``cumsum``).  ``kind='stable'`` (or numpy's
        synonym ``'mergesort'``) guarantees numpy-identical tie order;
        other kinds sort equal elements in an unspecified (numpy:
        quicksort's, here XLA's) order."""
        stable = _check_sort_kind(kind)
        if axis is not None:
            axis = self._one_axis(axis)
        mesh = self._mesh
        split = self._split
        new_split = (1 if split else 0) if axis is None else split
        in_bytes = self.size * self.dtype.itemsize
        out_bytes = self.size * np.dtype(
            jax.dtypes.canonicalize_dtype(np.int64)).itemsize
        if axis is not None and in_bytes > _CHUNK_MAX_BYTES:
            chunked = self._argsort_chunked(axis, stable, in_bytes,
                                            out_bytes)
            if chunked is not None:
                return chunked
        # memory model: input + index output + the variadic sort's
        # (value, iota) scratch of the same again
        hbm_check("argsort", 2 * (in_bytes + out_bytes),
                  "input + index output + variadic-sort scratch of both")
        base, funcs = self._chain_parts()

        def build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                if axis is None:
                    out = jnp.argsort(mapped.reshape(-1), stable=stable)
                else:
                    out = jnp.argsort(mapped, axis=axis, stable=stable)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("argsort", funcs, base.shape, str(base.dtype),
                          split, axis, stable, mesh), build)
        return self._wrap(fn(_check_live(base)), new_split)

    def _argsort_chunked(self, axis, stable, in_bytes, out_bytes):
        """Bounded-workspace argsort along ``axis`` for HBM-scale inputs
        (VERDICT r2 weak-4): rows are independent, so slabs along another
        axis argsort separately and write into ONE donated output buffer
        (`.at[slab].set` with buffer donation — XLA updates in place, no
        copy-per-slab accumulation).  Peak = input + output + two
        slab-sized sort transients, instead of 2×(input+output).
        Returns None when no other axis can carry the slabbing."""
        plan = slab_plan(self.shape, axis, in_bytes)
        if plan is None:
            return None
        cax, pairs = plan
        mesh, split = self._mesh, self._split
        slab_bytes = in_bytes // len(pairs)
        hbm_check("argsort", in_bytes + out_bytes + 2 * slab_bytes,
                  "input + index output + per-slab sort transients")
        data = self._data                   # chain materialises once
        idx_dtype = jax.dtypes.canonicalize_dtype(np.int64)

        def zeros_build():
            def z():
                return _constrain(jnp.zeros(data.shape, idx_dtype),
                                  mesh, split)
            return jax.jit(z)

        buf = _cached_jit(("argsort-buf", data.shape, str(idx_dtype),
                           split, mesh), zeros_build)()
        for s0, s1 in pairs:

            def upd_build(s0=s0, s1=s1):
                def upd(b, d):
                    slab = jax.lax.slice_in_dim(d, s0, s1, axis=cax)
                    idx = jnp.argsort(slab, axis=axis, stable=stable)
                    sl = tuple(slice(s0, s1) if a == cax else slice(None)
                               for a in range(d.ndim))
                    return _constrain(b.at[sl].set(idx), mesh, split)
                return jax.jit(upd, donate_argnums=(0,))

            buf = _cached_jit(("argsort-slab", data.shape,
                               str(data.dtype), split, axis, stable,
                               s0, s1, cax, mesh),
                              upd_build)(buf, data)
        return self._wrap(buf, split)

    # ------------------------------------------------------------------
    # inherited-ndarray method surface (the local backend gets all of
    # these from ``numpy.ndarray``; providing them here keeps
    # mode-agnostic code running on both backends — VERDICT r2 missing-2.
    # Reference: ``bolt/local/array.py`` — the ndarray subclass)
    # ------------------------------------------------------------------

    def sort(self, axis=-1, kind=None):
        """Sort along ``axis`` IN PLACE and return ``None`` — the ndarray
        calling convention the local backend inherits.  Device buffers
        are immutable, so "in place" is at the wrapper level: this handle
        rebinds to the sorted array (other handles, and the numpy views
        the local backend can alias, are unaffected — this backend has no
        views).  ``kind`` accepts ndarray.sort's names; values are
        identical under any of them."""
        _check_sort_kind(kind)
        axis = self._one_axis(axis)
        # memory model: input + sorted output + XLA sort scratch
        hbm_check("sort", 3 * self.size * self.dtype.itemsize,
                  "input + sorted output + sort scratch")
        mesh, split = self._mesh, self._split
        base, funcs = self._chain_parts()

        def build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                return _constrain(jnp.sort(mapped, axis=axis), mesh, split)
            return jax.jit(run)

        out = _cached_jit(("sort", funcs, base.shape, str(base.dtype),
                           split, axis, mesh), build)(_check_live(base))
        self._concrete = out
        self._chain = None
        self._aval = jax.ShapeDtypeStruct(out.shape, out.dtype)
        return None

    def ravel(self, order="C"):
        """Flatten to 1-d, the result keyed by a single flat key axis
        (``filter``'s output convention; a ``split=0`` input stays
        value-only).  ``order='F'`` flattens column-major (a reversed
        transpose on device); ``'A'``/``'K'`` follow the LOGICAL C
        layout — device arrays have no host memory order for them to
        inspect (the only divergence from numpy: a non-contiguous local
        oracle view could answer 'A'/'K' in F order)."""
        if order not in ("C", "F", "A", "K"):
            raise ValueError(
                "order must be one of 'C', 'F', 'A', or 'K' (got %r)"
                % (order,))
        fortran = order == "F"
        mesh, split = self._mesh, self._split
        new_split = 1 if split else 0
        base, funcs = self._chain_parts()

        def build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                if fortran:
                    mapped = mapped.transpose(range(mapped.ndim)[::-1])
                return _constrain(mapped.reshape(-1), mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("ravel", funcs, base.shape, str(base.dtype),
                          split, fortran, mesh), build)
        return self._wrap(fn(_check_live(base)), new_split)

    def flatten(self, order="C"):
        """Flattened copy (``ndarray.flatten``); identical to
        :meth:`ravel` here — both produce a fresh device array."""
        return self.ravel(order=order)

    def repeat(self, repeats, axis=None):
        """Repeat elements (ndarray semantics: ``axis=None`` flattens
        first; ``repeats`` a scalar, or a 1-d array matching the axis
        length — floats truncate like numpy).  The output length is
        computed on host, so the compiled program has a static shape;
        an array ``repeats`` is a traced argument (distinct repeat
        vectors of one total length reuse a program)."""
        rep = np.asarray(repeats)
        if rep.ndim > 1:
            raise ValueError("object too deep for desired array")
        if rep.dtype == bool or not np.issubdtype(rep.dtype, np.integer):
            rep = np.trunc(rep).astype(np.int64)   # numpy truncates floats
        if rep.size and rep.min() < 0:
            raise ValueError("negative dimensions are not allowed")
        if axis is not None:
            axis = self._one_axis(axis)
        dim = prod(self.shape) if axis is None else self.shape[axis]
        if rep.ndim == 1 and rep.size not in (1, dim):
            raise ValueError(
                "operands could not be broadcast together with shape "
                "(%d,) (%d,)" % (dim, rep.size))
        if rep.ndim == 1 and rep.size == 1:
            rep = np.full(dim, rep[0])      # numpy broadcasts size-1 repeats
        total = int(rep.sum()) if rep.ndim else int(rep) * dim
        mesh, split = self._mesh, self._split
        new_split = split if axis is not None else (1 if split else 0)
        base, funcs = self._chain_parts()

        def build():
            def run(data, r):
                mapped = _chain_apply(funcs, split, data)
                out = jnp.repeat(mapped, r, axis=axis,
                                 total_repeat_length=total)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("repeat", funcs, base.shape, str(base.dtype),
                          split, axis, rep.shape, total, mesh), build)
        return self._wrap(fn(_check_live(base), jnp.asarray(rep)), new_split)

    def _diag_axes(self, axis1, axis2):
        axis1 = self._one_axis(axis1)
        axis2 = self._one_axis(axis2)
        if axis1 == axis2:
            raise ValueError("axis1 and axis2 cannot be the same")
        return axis1, axis2

    def diagonal(self, offset=0, axis1=0, axis2=1):
        """Diagonal over the (``axis1``, ``axis2``) planes (ndarray
        semantics: both axes are removed and the diagonal appears as the
        LAST axis — a value axis; remaining key axes stay leading)."""
        axis1, axis2 = self._diag_axes(axis1, axis2)
        offset = int(offset)
        mesh, split = self._mesh, self._split
        new_split = split - sum(1 for a in (axis1, axis2) if a < split)
        base, funcs = self._chain_parts()

        def build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                out = jnp.diagonal(mapped, offset, axis1, axis2)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("diagonal", funcs, base.shape, str(base.dtype),
                          split, offset, axis1, axis2, mesh), build)
        return self._wrap(fn(_check_live(base)), new_split)

    def trace(self, offset=0, axis1=0, axis2=1, dtype=None):
        """Sum of the (``axis1``, ``axis2``) diagonal.  The accumulator
        dtype is whatever numpy's ``ndarray.trace`` would produce for
        this input (asked of numpy directly, then canonicalised), so the
        backends agree — e.g. int8/bool promote to the canonical int."""
        axis1, axis2 = self._diag_axes(axis1, axis2)
        offset = int(offset)
        # numpy decides the output dtype (probe on an empty 2-d); the
        # backend canonicalises it (int64→int32 when x64 is off)
        target = _canon(np.empty((1, 1), dtype=self.dtype)
                        .trace(dtype=dtype).dtype)
        mesh, split = self._mesh, self._split
        new_split = split - sum(1 for a in (axis1, axis2) if a < split)
        base, funcs = self._chain_parts()

        def build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                out = jnp.diagonal(mapped, offset, axis1, axis2)
                out = jnp.sum(out.astype(target), axis=-1)
                return _constrain(out, mesh, new_split)
            return jax.jit(run)

        fn = _cached_jit(("trace", funcs, base.shape, str(base.dtype),
                          split, offset, axis1, axis2, str(target), mesh),
                         build)
        return self._wrap(fn(_check_live(base)), new_split)

    def nonzero(self):
        """Indices of non-zero elements as a tuple of host int64 arrays,
        one per dimension — the plain-ndarray return the local backend
        inherits.  Dynamic count → the two-phase pattern (SURVEY §7 hard
        part 1): one compiled mask+count program, one scalar sync, then a
        count-shaped gather; the host receives only the indices."""
        mesh, split = self._mesh, self._split
        base, funcs = self._chain_parts()

        def count_build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                # canonical int: int64 under x64, so a >2**31 match
                # count cannot wrap (x64-off cannot index past 2**31
                # anyway — int32 indices are platform-wide there)
                return jnp.sum(mapped != 0,
                               dtype=jax.dtypes.canonicalize_dtype(np.int64))
            return jax.jit(run)

        k = int(jax.device_get(_cached_jit(
            ("nonzero-count", funcs, base.shape, str(base.dtype), split,
             mesh), count_build)(_check_live(base))))

        def gather_build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                return jnp.nonzero(mapped, size=k)
            return jax.jit(run)

        out = jax.device_get(_cached_jit(
            ("nonzero-gather", funcs, base.shape, str(base.dtype), split,
             k, mesh), gather_build)(_check_live(base)))
        return tuple(np.asarray(i).astype(np.int64) for i in out)

    def searchsorted(self, v, side="left", sorter=None):
        """Insertion points keeping this (1-d, sorted) array sorted —
        computed on device, returned as host indices (the plain-ndarray
        return the local backend inherits): a numpy int for scalar ``v``,
        an int64 ndarray shaped like ``v`` otherwise."""
        if self.ndim != 1:
            raise ValueError("object too deep for desired array")
        if side not in ("left", "right"):
            raise ValueError(
                "'%s' is an invalid value for keyword 'side'" % (side,))
        v = self._coerce_bolt_operand(v, "searchsorted values")
        varr = v if isinstance(v, jax.Array) else np.asarray(v)
        scalar = np.ndim(varr) == 0
        if sorter is not None:
            sorter = np.asarray(sorter)
            if not np.issubdtype(sorter.dtype, np.integer):
                # numpy's exact rejection — silent truncation would
                # search a wrongly-permuted array
                raise TypeError("sorter must only contain integers")
            if sorter.shape != self.shape:
                raise ValueError("sorter.size must equal a.size")
        mesh, split = self._mesh, self._split
        base, funcs = self._chain_parts()

        def build():
            def run(data, vv, srt):
                mapped = _chain_apply(funcs, split, data)
                if srt is not None:
                    mapped = jnp.take(mapped, srt, axis=0)
                return jnp.searchsorted(mapped, vv, side=side)
            return jax.jit(run)

        fn = _cached_jit(("searchsorted", funcs, base.shape,
                          str(base.dtype), split, side,
                          sorter is not None, mesh), build)
        srt = None if sorter is None else jnp.asarray(sorter, jnp.int32)
        out = np.asarray(jax.device_get(fn(_check_live(base), varr, srt)))
        out = out.astype(np.int64)
        return out[()] if scalar else out

    @property
    def real(self):
        """Real part (elementwise; defers and fuses like a map)."""
        return self._unary(jnp.real)

    @property
    def imag(self):
        """Imaginary part — zeros of the same dtype for real input, like
        numpy (elementwise; defers and fuses like a map)."""
        return self._unary(jnp.imag)

    def conj(self):
        """Elementwise complex conjugate (identity for real dtypes)."""
        return self._unary(jnp.conj)

    conjugate = conj

    def set(self, index, value):
        """Functional indexed update: a NEW array equal to this one with
        ``self[index] = value`` applied — the cross-backend mutation
        story (device arrays are immutable; ``__setitem__`` raises and
        points here, and the local backend offers the same method).

        Supports the same per-axis index forms as ``__getitem__``
        (ints / slices / lists / 1-d int or bool arrays / one Ellipsis);
        two or more advanced indices select ORTHOGONALLY, matching
        ``__getitem__``.  ``value`` broadcasts against the selected
        region and casts to this array's dtype (numpy assignment
        semantics).  One compiled scatter program per index geometry."""
        from bolt_tpu.utils import assignment_index, normalize_index
        norm, squeezed = normalize_index(index, self.shape)
        idx = assignment_index(norm, self.shape, squeezed)
        value = self._coerce_bolt_operand(value, "set value")
        val = value if isinstance(value, jax.Array) else np.asarray(value)
        # numpy assignment tolerates EXTRA leading length-1 dims on the
        # value (relative to the region, which drops scalar-indexed
        # axes); jax's scatter does not — squeeze them for parity
        region_ndim = self.ndim - len(squeezed)
        while val.ndim > region_ndim and val.shape[0] == 1:
            val = val.reshape(val.shape[1:])
        arrays = {ax: jnp.asarray(a) for ax, a in enumerate(idx)
                  if isinstance(a, np.ndarray)}
        static = tuple(None if isinstance(s, np.ndarray) else s
                       for s in idx)
        mesh, split = self._mesh, self._split
        base, funcs = self._chain_parts()

        def build():
            def run(data, v, iarrs):
                mapped = _chain_apply(funcs, split, data)
                full = tuple(iarrs[ax] if ax in iarrs else s
                             for ax, s in enumerate(static))
                out = mapped.at[full].set(v.astype(mapped.dtype))
                return _constrain(out, mesh, split)
            return jax.jit(run)

        key = ("set", funcs, base.shape, str(base.dtype), split,
               tuple((s.start, s.stop, s.step) if isinstance(s, slice)
                     else s for s in static),
               tuple((ax, a.shape) for ax, a in sorted(arrays.items())),
               tuple(val.shape), str(val.dtype), mesh)
        out = _cached_jit(key, build)(_check_live(base), val, arrays)
        return self._wrap(out, split)

    def __setitem__(self, index, value):
        raise TypeError(
            "'%s' does not support item assignment: device arrays are "
            "immutable.  Use b = b.set(index, value) for a functional "
            "update with the same indexing semantics (the local backend "
            "offers the same method)" % type(self).__name__)

    def item(self, *args):
        """Copy the selected element to a Python scalar (ndarray
        semantics: no args require size 1, one int is a flat index,
        ``ndim`` ints are per-axis — negatives wrap).  ONE element is
        gathered on device and fetched — never the array (one tiny
        compiled program per distinct index; a static index keeps GSPMD
        from all-gathering the sharded operand)."""
        from numbers import Integral
        if len(args) == 1 and isinstance(args[0], tuple):
            args = args[0]
        if not all(isinstance(a, Integral) for a in args):
            raise TypeError("item() takes integer arguments")
        if not args:
            if prod(self.shape) != 1:
                raise ValueError(
                    "can only convert an array of size 1 to a Python "
                    "scalar")
            multi = (0,) * self.ndim
        elif len(args) == 1:
            flat = int(args[0])
            size = prod(self.shape)
            if flat < 0:
                flat += size
            if not 0 <= flat < size:
                raise IndexError(
                    "index %d is out of bounds for size %d"
                    % (int(args[0]), size))
            multi = tuple(int(i) for i in
                          np.unravel_index(flat, self.shape)) \
                if self.ndim else ()
        else:
            if len(args) != self.ndim:
                raise ValueError("incorrect number of indices for array")
            multi = []
            for a, dim in zip(args, self.shape):
                i = int(a)
                if i < 0:
                    i += dim
                if not 0 <= i < dim:
                    raise IndexError(
                        "index %d is out of bounds for axis of size %d"
                        % (int(a), dim))
                multi.append(i)
            multi = tuple(multi)
        mesh, split = self._mesh, self._split
        base, funcs = self._chain_parts()

        def build():
            def run(data):
                mapped = _chain_apply(funcs, split, data)
                return mapped[multi]
            return jax.jit(run)

        out = _cached_jit(("item", funcs, base.shape, str(base.dtype),
                           split, multi, mesh), build)(_check_live(base))
        return np.asarray(_complex_safe_get(out)).item()

    def tolist(self):
        """Nested Python lists of the gathered array (ndarray
        semantics: a FULL host gather — size-bound like toarray)."""
        return self.toarray().tolist()

    # In-place operators: jax arrays are immutable, so these are the
    # functional rebinding form (``b += 1`` rebinds ``b`` to a new array;
    # other references to the old array are unchanged — jax's own
    # convention; true aliasing mutation is impossible on device).
    __iadd__ = __add__
    __isub__ = __sub__
    __imul__ = __mul__
    __itruediv__ = __truediv__
    __ifloordiv__ = __floordiv__
    __ipow__ = __pow__
    __imod__ = __mod__
    __imatmul__ = __matmul__

    def _unary(self, op):
        if self._split:
            return self.map(op, axis=tuple(range(self._split)))
        return self._wrap(
            _cached_jit((op.__name__ + "0", self.shape, str(self.dtype),
                         self._mesh),
                        lambda: jax.jit(op))(self._data), 0)

    def __neg__(self):
        # jnp.negative matches numpy in rejecting boolean negate, keeping
        # the two backends' semantics identical
        return self._unary(jnp.negative)

    def __abs__(self):
        return self._unary(jnp.abs)

    def clip(self, min=None, max=None, a_min=None, a_max=None):
        """Bound values to ``[min, max]`` — the ndarray method (and
        keyword names) the local backend inherits; ``a_min``/``a_max``
        accepted as np.clip-style aliases.

        Composed from the elementwise machinery — ``maximum(min)`` then
        ``minimum(max)``, numpy's ordering (the upper bound wins when
        ``min > max``) — so scalar bounds defer/fuse through the cached
        per-scalar callables and array bounds broadcast-validate against
        the FULL logical shape (key axes included) in one compiled
        program, exactly like operators."""
        if a_min is not None:
            if min is not None:
                raise ValueError("pass min= or a_min=, not both")
            min = a_min
        if a_max is not None:
            if max is not None:
                raise ValueError("pass max= or a_max=, not both")
            max = a_max
        if min is None and max is None:
            raise ValueError("clip needs at least one of min/max")
        out = self
        if min is not None:
            out = out._elementwise(min, jnp.maximum)
        if max is not None:
            out = out._elementwise(max, jnp.minimum)
        return out

    def round(self, decimals=0):
        """Round to ``decimals`` places (ndarray semantics; banker's
        rounding at .5, identical on both backends)."""
        from numbers import Integral
        if not isinstance(decimals, Integral):
            # ndarray.round raises TypeError here; silent int() truncation
            # would mask a caller bug only on this backend
            raise TypeError("decimals must be an integer, got %r"
                            % (decimals,))
        return self._unary(_round_fn(int(decimals)))

    def __lt__(self, other):
        return self._elementwise(other, jnp.less)

    def __le__(self, other):
        return self._elementwise(other, jnp.less_equal)

    def __gt__(self, other):
        return self._elementwise(other, jnp.greater)

    def __ge__(self, other):
        return self._elementwise(other, jnp.greater_equal)

    def __eq__(self, other):
        try:
            return self._elementwise(other, jnp.equal)
        except Exception:
            # non-comparable operand (None, sentinels): let Python fall
            # back to identity comparison
            return NotImplemented

    def __ne__(self, other):
        try:
            return self._elementwise(other, jnp.not_equal)
        except Exception:
            return NotImplemented

    __hash__ = None

    # ------------------------------------------------------------------
    # re-axis: THE signature operation
    # ------------------------------------------------------------------

    def swap(self, kaxes, vaxes, size="150", donate=False):
        """Move key axes ``kaxes`` into the values and value axes ``vaxes``
        into the keys.

        ``donate=True`` hands this array's device buffer to XLA for reuse —
        essential at HBM-filling sizes, where input + output of a re-axis
        cannot coexist (a 10 GB swap needs 20 GB without donation).  The
        donated array becomes unreadable afterwards, like the reference's
        consumed RDD lineage stage.

        New keys = (remaining keys) + (moved-in value axes); new values =
        (moved-out key axes) + (remaining value axes) — the reference's
        composite-key algebra (``BoltArraySpark.swap`` → ``ChunkedArray.
        keys_to_values/values_to_keys`` → shuffle → unchunk, SURVEY §3.3).

        Here the whole pipeline is one compiled transpose whose output
        carries the *new* key sharding: GSPMD lowers the sharding change to
        an ``all_to_all`` over ICI — the TPU-native form of the reference's
        cluster-wide shuffle.  ``size`` (the reference's chunk-size budget
        for the shuffle) is accepted and ignored: XLA chooses its own
        collective tiling.
        """
        kaxes = tuple(tupleize(kaxes) or ())
        vaxes = tuple(tupleize(vaxes) or ())
        split = self._split
        nvalue = self.ndim - split
        for a in kaxes:
            if a < 0 or a >= split:
                raise ValueError("key axis %d out of range for split %d" % (a, split))
        for a in vaxes:
            if a < 0 or a >= nvalue:
                raise ValueError("value axis %d out of range for %d value axes" % (a, nvalue))
        if len(set(kaxes)) != len(kaxes) or len(set(vaxes)) != len(vaxes):
            raise ValueError("swap axes must be unique")
        if len(kaxes) == split and len(vaxes) == 0:
            raise ValueError("cannot perform a swap that would leave the "
                             "array with no key axes")
        return self._do_swap(kaxes, vaxes, donate=donate)

    def _do_swap(self, kaxes, vaxes, donate=False):
        """The swap lowering without the no-key-axes guard — the chunk
        primitives (``keys_to_values`` over every key axis) legitimately
        produce key-less intermediates, which this representation supports
        as ``split=0``."""
        split = self._split
        nvalue = self.ndim - split
        keys_rest = [k for k in range(split) if k not in kaxes]
        values_rest = [v for v in range(nvalue) if v not in vaxes]
        perm = (keys_rest + [split + v for v in vaxes]
                + list(kaxes) + [split + v for v in values_rest])
        new_split = len(keys_rest) + len(vaxes)
        if perm == list(range(self.ndim)) and new_split == split:
            return self
        if self._stream is not None:
            # a STREAMED source records the swap as a lazy stage instead
            # of materialising (ISSUE 18): the terminal that eventually
            # consumes the chain resolves it through the two-phase
            # shuffle (stream.resolve_swaps) — all-to-all re-bucketing
            # slab by slab, spilling past the arbiter budget.
            # NotImplemented = this swap is outside the streamed story
            # (dynamic chain, lossy codec, pod iter source) and the
            # materialise-first path below serves it bit-identically.
            out = _streamlib.swap_stage(self, tuple(perm), new_split)
            if out is not NotImplemented:
                return out
        mesh = self._mesh

        if not donate:
            # a deferred chain fuses into the transpose program (donation
            # keeps materialise-first semantics: the chain's BASE buffer
            # may be aliased by other arrays, so it must not be donated)
            base, funcs = self._chain_parts()

            def build():
                def swapper(data):
                    mapped = _chain_apply(funcs, split, data)
                    return _constrain(jnp.transpose(mapped, perm), mesh,
                                      new_split)
                return jax.jit(swapper)

            fn = _cached_jit(("swap", funcs, base.shape, str(base.dtype),
                              tuple(perm), split, new_split, False, mesh),
                             build)
            return self._wrap(fn(_check_live(base)), new_split)

        def build():
            def swapper(data):
                return _constrain(jnp.transpose(data, perm), mesh, new_split)
            return jax.jit(swapper, donate_argnums=(0,))

        fn = _cached_jit(("swap", self.shape, str(self.dtype), tuple(perm),
                          split, new_split, True, mesh), build)
        out = fn(self._data)
        # only after a successful dispatch: a compile failure must not
        # brick an array whose buffer was never consumed (granted=False:
        # user-explicit donation, not an engine-policy grant)
        self._consume_donated("swap(..., donate=True)", granted=False)
        return self._wrap(out, new_split)

    def chunk(self, size="150", axis=None, padding=None):
        """Decompose the value axes into chunks; returns a
        :class:`~bolt_tpu.tpu.chunk.ChunkedArray` *view* — no data moves
        (reference: ``BoltArraySpark.chunk`` → ``ChunkedArray._chunk``;
        here chunking is bookkeeping over the already-mesh-resident array,
        the BASELINE north-star's "thin view over the mesh partition")."""
        from bolt_tpu.tpu.chunk import ChunkedArray
        return ChunkedArray.chunk(self, size=size, axis=axis, padding=padding)

    def stacked(self, size=1000):
        """Batch flat key records into blocks (reference:
        ``BoltArraySpark.stacked`` → ``StackedArray``).  On TPU batching is
        native — this view exists for API compatibility."""
        from bolt_tpu.tpu.stack import StackedArray
        return StackedArray.stack(self, size=size)

    # ------------------------------------------------------------------
    # shaping (within-group only, no data shuffle — reference:
    # ``BoltArraySpark.transpose/swapaxes/reshape/squeeze`` with
    # istransposeable/isreshapeable guards)
    # ------------------------------------------------------------------

    def transpose(self, *axes):
        axes = argpack(axes)
        if len(axes) == 0:
            axes = tuple(reversed(range(self.ndim)))
        if not istransposeable(axes, range(self.ndim)):
            raise ValueError("axes %s is not a permutation of %d axes"
                             % (str(axes), self.ndim))
        split = self._split
        if sorted(axes[:split]) != list(range(split)):
            raise ValueError(
                "transpose may not move axes between keys and values; "
                "use swap (key axes: %s)" % str(tuple(range(split))))
        if tuple(axes) == tuple(range(self.ndim)):
            return self
        mesh = self._mesh

        def build():
            def t(data):
                return _constrain(jnp.transpose(data, axes), mesh, split)
            return jax.jit(t)

        fn = _cached_jit(("transpose", self.shape, str(self.dtype),
                          split, tuple(axes), mesh), build)
        return self._wrap(fn(self._data), split)

    @property
    def T(self):
        """Reverse keys among themselves and values among themselves (the
        group-respecting transpose)."""
        split = self._split
        perm = tuple(reversed(range(split))) + tuple(
            reversed(range(split, self.ndim)))
        return self.transpose(*perm)

    def swapaxes(self, axis1, axis2):
        perm = list(range(self.ndim))
        perm[axis1], perm[axis2] = perm[axis2], perm[axis1]
        return self.transpose(*perm)

    def reshape(self, *shape):
        shape = argpack(shape)
        if not isreshapeable(shape, self.shape):
            raise ValueError("cannot reshape %s to %s" % (str(self.shape), str(shape)))
        ksize = prod(self.shape[:self._split])
        # infer the boundary: the smallest non-empty key prefix whose
        # product matches.  Ambiguous cases (trailing size-1 axes) should
        # use the keys/values views, which state the boundary explicitly.
        start = 1 if self._split > 0 else 0
        new_split = None
        for k in range(start, len(shape) + 1):
            if prod(shape[:k]) == ksize:
                new_split = k
                break
        if new_split is None:
            raise ValueError(
                "new shape %s does not preserve the key/value boundary "
                "(key size %d)" % (str(shape), ksize))
        return self._reshape_with_split(shape, new_split)

    def _reshape_with_split(self, shape, new_split):
        """Reshape to ``shape`` with an explicitly stated key-axis count
        (used by the ``keys``/``values`` views, which know the boundary)."""
        shape = tuple(shape)
        if prod(shape[:new_split]) != prod(self.shape[:self._split]):
            raise ValueError(
                "new key shape %s does not match key size %d"
                % (str(shape[:new_split]), prod(self.shape[:self._split])))
        if shape == self.shape and new_split == self._split:
            return self
        mesh = self._mesh
        ns = new_split

        def build():
            def r(data):
                return _constrain(data.reshape(shape), mesh, ns)
            return jax.jit(r)

        fn = _cached_jit(("reshape", self.shape, str(self.dtype),
                          self._split, shape, ns, mesh), build)
        return self._wrap(fn(self._data), ns)

    def squeeze(self, axis=None):
        if axis is None:
            axes = tuple(i for i, s in enumerate(self.shape) if s == 1)
        else:
            axes = tupleize(axis)
            inshape(self.shape, axes)
            for a in axes:
                if self.shape[a] != 1:
                    raise ValueError("cannot squeeze axis %d of size %d"
                                     % (a, self.shape[a]))
        new_shape = tuple(s for i, s in enumerate(self.shape) if i not in axes)
        new_split = self._split - sum(1 for a in axes if a < self._split)
        if new_shape == self.shape:
            return self
        mesh = self._mesh

        def build():
            def s(data):
                return _constrain(data.reshape(new_shape), mesh, new_split)
            return jax.jit(s)

        fn = _cached_jit(("squeeze", self.shape, str(self.dtype),
                          self._split, axes, mesh), build)
        return self._wrap(fn(self._data), new_split)

    # ------------------------------------------------------------------
    # indexing (reference: ``BoltArraySpark.__getitem__`` — per-axis
    # int/slice/list/bool, key-axis selection as record filtering, value-axis
    # as block slicing; advanced indices apply orthogonally per axis)
    # ------------------------------------------------------------------

    def __getitem__(self, index):
        from bolt_tpu.utils import normalize_index
        norm, squeezed = normalize_index(index, self.shape)

        mesh = self._mesh
        adv = tuple(ax for ax, s in enumerate(norm) if isinstance(s, np.ndarray))
        arrays = {ax: jnp.asarray(norm[ax]) for ax in adv}
        slices = tuple(s if isinstance(s, slice) else slice(None) for s in norm)
        key = ("getitem", self.shape, str(self.dtype), self._split,
               tuple((s.start, s.stop, s.step) for s in slices),
               tuple((ax, arrays[ax].shape) for ax in adv),
               tuple(squeezed), mesh)
        new_split = self._split - sum(1 for a in squeezed if a < self._split)

        def build():
            def get(data, idx_arrays):
                out = data[slices]
                for ax in adv:
                    out = jnp.take(out, idx_arrays[ax], axis=ax)
                if squeezed:
                    out = out.reshape(tuple(
                        s for i, s in enumerate(out.shape) if i not in squeezed))
                return _constrain(out, mesh, new_split)
            return jax.jit(get)

        out = _cached_jit(key, build)(self._data, arrays)
        return self._wrap(out, new_split)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __iter__(self):
        """Iterate over the leading axis, like numpy (each item is a bolt
        array with one fewer dimension).  One compiled take program serves
        every index (the index is a traced argument, not a cache key)."""
        n = len(self)
        mesh = self._mesh
        new_split = self._split - 1 if self._split > 0 else 0

        def build():
            def take(data, i):
                return _constrain(jnp.take(data, i, axis=0), mesh, new_split)
            return jax.jit(take)

        fn = _cached_jit(("iter-take", self.shape, str(self.dtype),
                          self._split, mesh), build)
        data = self._data
        for i in range(n):
            yield self._wrap(fn(data, jnp.asarray(i, dtype=jnp.int32)),
                             new_split)

    # ------------------------------------------------------------------
    # conversions / persistence
    # ------------------------------------------------------------------

    def toarray(self, out=None):
        """Gather to a host ``numpy.ndarray`` in key order (reference:
        ``BoltArraySpark.toarray`` = sortByKey → collect → reshape; here a
        single ``device_get`` — ordering is intrinsic, SURVEY §3.5).  On a
        multi-host mesh, shards the local process cannot address are
        all-gathered over DCN first.

        HOST-RAM MODEL: every process receives the FULL logical array —
        that is ``toarray``'s contract (device memory stays bounded; see
        ``_gather_multihost``), so the host must hold ``size × itemsize``
        bytes per process.  For arrays bigger than host RAM pass ``out=``
        (any writable shape/dtype-matching array, e.g. an
        ``np.lib.format.open_memmap`` / ``np.memmap``) and the gather
        writes into it shard by shard; or skip assembly entirely with
        :meth:`iter_shards`.

        A small pending ``filter`` result is fetched in ONE batched
        transfer (padded buffer + survivor count together) and sliced on
        host, so ``filter(...).toarray()`` pays a single round-trip instead
        of a count sync followed by a data fetch; the fetched count then
        resolves the device side for free.  Large padded buffers skip the
        fast path — when few records survive, shipping the full buffer
        would cost more than the extra count round-trip saves."""
        if self._fpending is not None:
            self._resolve_fpending()   # one fused pass → (padded, count)
        if self._pending is not None:
            padded, cnt = self._pending
            if (padded.is_fully_addressable
                    and padded.size * padded.dtype.itemsize
                    <= _PENDING_FETCH_MAX_BYTES):
                if np.issubdtype(np.dtype(padded.dtype),
                                 np.complexfloating):
                    p = _complex_safe_get(padded)
                    c = int(jax.device_get(cnt))
                else:
                    p, c = jax.device_get((padded, cnt))
                    c = int(c)
                # the count is on host now: resolve device-side without a
                # second sync, releasing the padded buffer
                self._resolve_pending(count=c)
                if out is not None:
                    # out= keeps the single batched round-trip: validate
                    # against the now-known filtered shape, copy the
                    # survivor slice in
                    BoltArray._check_out(
                        out, (c,) + tuple(padded.shape[1:]), padded.dtype)
                    out[...] = np.asarray(p)[:c]
                    return out
                return np.asarray(p)[:c].copy()
        data = self._data
        if out is not None:
            BoltArray._check_out(out, data.shape, data.dtype)
        if not data.is_fully_addressable:
            return self._gather_multihost(data, out=out)
        if out is not None:
            # shard-wise writes into the caller's target (which may be a
            # memmap) — fetched in ONE batched device_get (per-shard
            # gets would pay a host round-trip EACH)
            shards = data.addressable_shards
            if np.issubdtype(np.dtype(data.dtype), np.complexfloating):
                blocks = [_complex_safe_get(sh.data) for sh in shards]
            else:
                blocks = jax.device_get([sh.data for sh in shards])
            for sh, blk in zip(shards, blocks):
                out[sh.index] = np.asarray(blk)
            return out
        return np.asarray(_complex_safe_get(data))

    def iter_shards(self):
        """Yield ``(index, block)`` for every shard THIS process can
        address — ``index`` the tuple of slices locating the block in the
        logical array, ``block`` its host ndarray.  The zero-assembly
        collect: per-shard host RAM instead of ``toarray``'s full-array
        buffer, and on a multi-host mesh no DCN traffic at all (each
        process walks its own shards; a replicated array yields every
        shard from every process).  Blocks are WRITABLE host copies on
        both backends (a bare device_get view is read-only), so shard-
        walking code can scribble without mode-dependent aliasing."""
        data = self._data
        for sh in data.addressable_shards:
            yield sh.index, np.array(_complex_safe_get(sh.data))

    def _gather_multihost(self, data, out=None):
        """Shard-wise cross-host gather with bounded device memory at ANY
        array size (VERDICT r1 missing-2: ``process_allgather(tiled)``
        replicates the FULL logical array on every device, OOMing every
        host at once at TB scale).  Three steps:

        1. each process ``device_get``s its own addressable shards straight
           into the host result — most of the data, zero collectives;
        2. the global shard layout (``devices_indices_map`` — identical on
           every process) assigns each remaining region one owner;
        3. each remote region is broadcast from its owner in
           ``<= _GATHER_SLAB_BYTES`` pieces (host-sliced, so the compiled
           psum-broadcast program count is the number of distinct piece
           SHAPES, not piece count — device memory per step is one piece).

        Every process still receives the full host ndarray: all processes
        run the same SPMD program, so a one-driver collect (the
        reference's ``sortByKey().collect()``) has no analog —
        collectives need every process participating."""
        from jax.experimental import multihost_utils
        from bolt_tpu.parallel import multihost as _mh
        shape = tuple(data.shape)
        dtype = np.dtype(data.dtype)
        if out is None:
            # the full-array host buffer toarray's contract requires;
            # callers with less host RAM pass out= (e.g. a memmap) or
            # use iter_shards
            out = np.empty(shape, dtype)
        pid = _mh.process_index()

        def norm(idx):
            return tuple(s.indices(d)[:2] for s, d in zip(idx, shape))

        # step 1: local shards, no communication
        for sh in data.addressable_shards:
            out[sh.index] = np.asarray(_complex_safe_get(sh.data))

        # step 2: deterministic region -> owner map (lowest device id)
        owners, procs = {}, {}
        for dev, idx in data.sharding.devices_indices_map(shape).items():
            key = norm(idx)
            if key not in owners or dev.id < owners[key].id:
                owners[key] = dev
            procs.setdefault(key, set()).add(dev.process_index)
        nproc = _mh.process_count()
        stats = {"regions": 0, "broadcasts": 0, "max_piece_bytes": 0}

        # step 3: broadcast each non-universal region in bounded pieces
        for key in sorted(owners):
            if len(procs[key]) == nproc:
                continue  # replicated region: every process has it already
            stats["regions"] += 1
            src = owners[key].process_index
            rshape = tuple(b - a for a, b in key)
            rbytes = prod(rshape) * dtype.itemsize
            if not rshape or rbytes <= _GATHER_SLAB_BYTES:
                pieces = [tuple(slice(a, b) for a, b in key)]
            else:
                # split the largest extent so each piece fits the budget
                ax = int(np.argmax(rshape))
                step = max(1, int(rshape[ax] * _GATHER_SLAB_BYTES // rbytes))
                a0 = key[ax][0]
                pieces = []
                for p0 in range(0, rshape[ax], step):
                    pb = [slice(a, b) for a, b in key]
                    pb[ax] = slice(a0 + p0, min(a0 + p0 + step, key[ax][1]))
                    pieces.append(tuple(pb))
            for pb in pieces:
                pshape = tuple(s.stop - s.start for s in pb)
                piece = out[pb] if src == pid else np.zeros(pshape, dtype)
                got = multihost_utils.broadcast_one_to_all(
                    np.ascontiguousarray(piece), is_source=(src == pid))
                if src != pid:
                    out[pb] = got
                stats["broadcasts"] += 1
                stats["max_piece_bytes"] = max(
                    stats["max_piece_bytes"], prod(pshape) * dtype.itemsize)
        global _LAST_GATHER_STATS
        _LAST_GATHER_STATS = stats
        return out

    def __array__(self, dtype=None):
        from bolt_tpu.tpu.npdispatch import implicit_gather_warning
        implicit_gather_warning(self.size * self.dtype.itemsize)
        a = self.toarray()
        return a.astype(dtype) if dtype is not None else a

    def __array_function__(self, func, types, args, kwargs):
        """Non-ufunc numpy API (``np.sum(b)``, ``np.concatenate``, …)
        with NUMPY semantics, served on device by
        :mod:`bolt_tpu.tpu.npdispatch` where the table covers it (result
        comes back as a bolt array, zero host transfer) and by an
        explicit host fallback — which warns above a size threshold —
        otherwise.  The local backend gets the same API natively from
        ndarray (VERDICT r2 missing-3)."""
        from bolt_tpu.tpu import npdispatch
        return npdispatch.dispatch(self, func, types, args, kwargs)

    def _clone(self):
        """A new wrapper over the same (immutable) device state — the
        cheap copy behind functional forms of the in-place methods
        (``np.sort``)."""
        b = BoltArrayTPU(self._concrete, self._split, self._mesh)
        b._chain = self._chain
        b._pending = self._pending
        b._fpending = self._fpending
        # a lazy stream source is shared, not forked: callback sources
        # re-stream on demand, and either wrapper materialising adopts
        # its own concrete state without touching the other
        b._stream = self._stream
        # a pending stat handle is shared too: either wrapper's first
        # read resolves the group once and both adopt the same result
        b._spending = self._spending
        b._stat_group = self._stat_group
        b._donated = self._donated
        b._aval = self._aval
        return b

    def tolocal(self):
        from bolt_tpu.local.array import BoltArrayLocal
        return BoltArrayLocal(self.toarray())

    def totpu(self, context=None, axis=(0,)):
        if context is None or context is self._mesh:
            return self
        return BoltArray.totpu(self, context=context, axis=axis)

    def tojax(self):
        """Unwrap to the engine-native object: the underlying sharded
        ``jax.Array`` (materialises a deferred chain first).  Fills the
        structural slot of the reference's ``BoltArraySpark.tordd`` —
        unwrap to the RDD of ``(key, value)`` records."""
        return self._data

    def first(self):
        """The value block at the first key tuple (reference:
        ``BoltArraySpark.first`` — a one-record job; here one block
        transfer).  On a DEFERRED chain this compiles a one-record
        program — the chain runs on the first block only, never
        materialising the full mapped array (the reference's
        one-record-job economy, VERDICT r2 weak-5)."""
        if self.deferred:
            base, funcs = self._chain
            mesh, split = self._mesh, self._split

            def build():
                def run(d):
                    # static size-1 key slice, then the SAME chain
                    # application as materialisation (size-1 key axes
                    # make with_keys entries see exactly the all-zero
                    # first key) — one code path, one-record economy
                    rec = d[(slice(0, 1),) * split]
                    return _chain_apply(funcs, split, rec)[(0,) * split]
                return jax.jit(run)

            fn = _cached_jit(("first", funcs, base.shape, str(base.dtype),
                              split, mesh), build)
            return np.asarray(_complex_safe_get(fn(_check_live(base))))
        return np.asarray(_complex_safe_get(self._data[(0,) * self._split]))

    def _concat_many(self, others, axis):
        """Concatenate with any number of operands in ONE compiled
        program (``np.concatenate``'s dispatch target — the pairwise
        method would materialise n−1 intermediates).  ``axis=None``
        ravels every operand first, like numpy (result gets the flat
        key axis).  Built on the shared fused-program machinery
        (:func:`bolt_tpu.tpu.npdispatch._device_fused`): deferred chains
        on bolt operands fuse in, host operands upload once."""
        from bolt_tpu.tpu.npdispatch import _device_fused
        parts = [self] + list(others)
        if axis is not None:
            axis = int(axis)
            for p in parts:
                if np.ndim(p) != self.ndim:
                    raise ValueError(
                        "cannot concatenate %d-d with %d-d array"
                        % (self.ndim, np.ndim(p)))
        new_split = self._split if axis is not None \
            else (1 if self._split else 0)

        def body(*mapped):
            if axis is None:
                mapped = [m.reshape(-1) for m in mapped]
            return jnp.concatenate(mapped, axis=0 if axis is None else axis)

        return _device_fused("concat", parts, self, new_split, body, (axis,))

    def concatenate(self, arry, axis=0):
        """Concatenate along ``axis`` with another bolt array or ndarray
        (reference: ``BoltArraySpark.concatenate``).  A distributed other
        stays on device — the reshard rides ICI, no host round-trip."""
        return self._concat_many([arry], int(axis))

    def astype(self, dtype, casting="unsafe"):
        """Cast elements (reference: ``BoltArraySpark.astype`` via
        ``mapValues``; deferred like a map, so it fuses).  ``casting`` is
        validated against numpy's rules; the target dtype is canonicalised
        to what the backend holds (f64→f32 unless x64 is enabled)."""
        np.empty(0, dtype=self.dtype).astype(dtype, casting=casting)
        target = _canon(dtype)
        if self._split == 0:
            # value-shaped result of a reduction: no key axes to map over
            out = _cached_jit(
                ("astype0", self.shape, str(self.dtype), str(target), self._mesh),
                lambda: jax.jit(lambda d: d.astype(target)))(self._data)
            return self._wrap(out, 0)
        return self.map(lambda v: v.astype(target),
                        axis=tuple(range(self._split)))

    def cache(self):
        """Force materialisation of a deferred chain and keep the result
        resident (reference: ``BoltArraySpark.cache`` pins the
        lazily-computed RDD)."""
        self._data
        return self

    def unpersist(self):
        """Counterpart of :meth:`cache`; device residency is managed by
        jax, so this is a no-op for parity."""
        return self

    def repartition(self, npartitions):
        """Accepted for parity; the partition layout is the mesh and does
        not change per-array (reference: ``BoltArraySpark.repartition``)."""
        return self

    def __repr__(self):
        s = "BoltArray\n"
        s += "mode: %s\n" % self.mode
        if self._donated:
            # repr must never raise: a donated FILTER array has no aval,
            # so the shape/dtype properties below would hit the guard —
            # and printing an array is how users diagnose exactly that
            if self._aval is not None:
                s += "shape: %s\n" % str(tuple(self._aval.shape))
                s += "dtype: %s\n" % str(np.dtype(self._aval.dtype))
            s += "split: %d\n" % self._split
            s += "donated: buffer consumed by %s\n" % (
                self._donated if isinstance(self._donated, str)
                else "a donating swap or terminal")
            return s
        if self._fpending is not None:
            # don't dispatch the filter just to print; show what is known
            s += "shape: (%s)\n" % ", ".join(
                ["?"] + [str(d) for d in self._fpending[4]])
        elif self._pending is not None:
            # don't force the count sync just to print; show what is known
            s += "shape: (%s)\n" % ", ".join(
                ["?"] + [str(d) for d in self._pending[0].shape[1:]])
        else:
            s += "shape: %s\n" % str(self.shape)
        s += "split: %d\n" % self._split
        s += "dtype: %s\n" % str(self.dtype)
        if self.deferred:
            s += "deferred: %d-op map chain\n" % len(self._chain[1])
        elif self._spending is not None:
            # don't dispatch the fused group just to print
            s += "pending: lazy %s() terminal (fused group not yet " \
                 "dispatched)\n" % self._spending.name
        elif self._fpending is not None:
            s += "pending: deferred filter (predicate not yet dispatched)\n"
        elif self._pending is not None:
            s += "pending: filter count not yet synced\n"
        else:
            try:
                s += "sharding: %s\n" % str(self._concrete.sharding.spec)
            except Exception:
                pass
        return s
