"""Explicit-collective streaming statistics for the TPU backend.

Reference: the ``rdd.aggregate(StatCounter(), merge, mergeStats)`` path
behind ``BoltArraySpark.stats/_stat`` (SURVEY §3.4): per-partition Welford
accumulation in Python workers, tree-combined across the cluster.  Here each
mesh shard computes its local moments on-device and the Chan combine is a
handful of ``psum``/``pmax``/``pmin`` collectives over the ICI — one
compiled ``shard_map`` program, no host involvement until the final scalar
fetch.

This module is the framework's canonical example of the explicit-collective
(``shard_map``) style; the everyday ``mean()/var()/std()`` methods use plain
``jnp`` reductions and let GSPMD insert the same collectives automatically.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bolt_tpu.parallel.sharding import key_spec, spec_names
from bolt_tpu.statcounter import StatCounter
from bolt_tpu.tpu.array import _cached_jit
from bolt_tpu.utils import inshape, prod, tupleize


def _kernel_gate(axes, ndim, dtype):
    """ONE predicate for "the fused_welford kernel can engage here" —
    shared by the traced body and welford's compile-failure fallback
    arming, so they cannot disagree (jnp.issubdtype: bf16 IS floating,
    where np.issubdtype says no)."""
    return (axes == tuple(range(len(axes))) and len(axes) < ndim
            and jnp.issubdtype(dtype, jnp.floating))


def _shard_moments(x, axes, use_kernel=True):
    """Per-shard ``(mu, m2, min, max)`` over ``axes`` (traced inside the
    shard_map body).  When the reduced axes are the leading contiguous
    ones — the ``stats()`` default — and the shard geometry tiles cleanly,
    the single-HBM-pass pallas kernel computes them (measured 1.52× over
    the fused-XLA two-pass at 10.7 GB on a v5e chip: XLA cannot fuse the
    mean with the centred second moment, so it reads HBM twice;
    BASELINE.md).  Everything else takes the jnp path — identical
    semantics, allclose-level numerics."""
    if use_kernel and _kernel_gate(axes, x.ndim, x.dtype):
        from bolt_tpu.ops.kernels import fused_welford
        r = fused_welford(x)
        if r is not None:
            mu, m2, mn, mx = r
            if len(axes) > 1:
                # kernel reduced axis 0; Chan-combine the remaining
                # leading axes of the (small) moment arrays — groups of
                # equal count x.shape[0], so the combine is exact algebra
                red = tuple(range(len(axes) - 1))
                cnt = jnp.asarray(x.shape[0], mu.dtype)
                g = jnp.mean(mu, axis=red, keepdims=True)
                m2 = (jnp.sum(m2, axis=red)
                      + cnt * jnp.sum((mu - g) ** 2, axis=red))
                mu = g.reshape(x.shape[len(axes):])
                mn = jnp.min(mn, axis=red)
                mx = jnp.max(mx, axis=red)
            return mu, m2, mn, mx
    mu = jnp.mean(x, axis=axes)
    m2 = jnp.sum((x - jnp.mean(x, axis=axes, keepdims=True)) ** 2, axis=axes)
    return mu, m2, jnp.min(x, axis=axes), jnp.max(x, axis=axes)


def welford(barray, requested=("mean", "var", "std", "min", "max"),
            axis=None):
    """Single-pass count/mean/var/std/min/max over any axes, returned as a
    :class:`~bolt_tpu.statcounter.StatCounter` holding value-shaped moments.

    ``axis=None`` reduces over all key axes (the reference's ``stats()``).
    Any subset of key AND value axes is allowed — matching ``mean()`` /
    ``_stat`` (VERDICT r1 weak-6): value axes are whole on every shard, so
    they reduce locally and only mesh-mapped key dims join the collectives.
    Remaining axes stay as leading dimensions of each moment.
    """
    split = barray.split
    if axis is None:
        axes = tuple(range(split))
    else:
        axes = tuple(sorted(tupleize(axis)))
        inshape(barray.shape, axes)
    if len(axes) == 0:
        raise ValueError("at least one axis is required")

    mesh = barray.mesh
    shape = barray.shape
    spec = tuple(key_spec(mesh, shape, split))
    # mesh axes assigned to the reduced dims participate in the collectives
    # (a spec entry may carry SEVERAL mesh axes — flatten for psum)
    reduce_names = tuple(n for a in axes for n in spec_names(spec[a]))
    out_spec = P(*(spec[i] for i in range(len(shape)) if i not in axes))
    n_total = prod(tuple(shape[a] for a in axes))

    key = ("welford", shape, str(barray.dtype), axes, spec, mesh)

    def build(use_kernel=True):
        def local_moments(x):
            # x is the per-device shard; reduced dims may be divided across
            # the mesh, so this count is the LOCAL n.
            n_local = prod(tuple(x.shape[a] for a in axes))
            moments = _shard_moments(x, axes, use_kernel)
            mu, m2, mn, mx = moments
            if reduce_names:
                n_loc = jnp.asarray(n_local, dtype=mu.dtype)
                n_tot = jax.lax.psum(n_loc, reduce_names)
                grand = jax.lax.psum(mu * n_loc, reduce_names) / n_tot
                # Chan et al.: total M2 = sum M2_i + sum n_i (mu_i - grand)^2
                m2 = jax.lax.psum(m2 + n_loc * (mu - grand) ** 2, reduce_names)
                mu = grand
                mx = jax.lax.pmax(mx, reduce_names)
                mn = jax.lax.pmin(mn, reduce_names)
            return mu, m2, mn, mx

        # check_vma=False: the pallas kernel's out_shape carries no vma
        # annotation, and every cross-device combine here is an explicit
        # psum/pmax/pmin — there is nothing for the varying-axes checker
        # to catch on this function
        from bolt_tpu._compat import shard_map
        return jax.jit(shard_map(
            local_moments, mesh=mesh, in_specs=P(*spec),
            out_specs=(out_spec, out_spec, out_spec, out_spec),
            check_vma=False))

    # shares the bounded LRU executable cache with every other op family.
    # The compile-failure fallback arms ONLY when the pallas kernel can
    # actually engage (leading contiguous axes, floating dtype — the
    # _shard_moments gate); other geometries compile one jnp program and
    # their errors surface undisturbed (the sepfilter precedent: gate
    # eligibility BEFORE arming the fallback).
    data = barray._data
    kernel_possible = _kernel_gate(axes, len(shape), barray.dtype)
    out = None
    if not kernel_possible:
        out = _cached_jit(key, build)(data)
    elif key not in _KERNEL_FAILED:
        try:
            out = _cached_jit(key, build)(data)
        except Exception:
            # the DEFAULT stats() path must survive a flaky pallas
            # toolchain (remote-compile hiccups / Mosaic geometry
            # surprises): fall back to the jnp two-pass body, memoise so
            # the failed compile is never re-paid
            from bolt_tpu.tpu.array import _JIT_CACHE
            _JIT_CACHE.pop(key, None)
            _KERNEL_FAILED.add(key)
    if out is None:
        out = _cached_jit(key + ("nokernel",),
                          lambda: build(use_kernel=False))(data)
    mu, m2, mn, mx = (np.asarray(jax.device_get(o)) for o in out)
    return StatCounter.from_moments(n_total, mu, m2, minValue=mn, maxValue=mx,
                                    stats=requested)


# welford geometries whose pallas-backed program failed to compile on
# this toolchain — they run the jnp two-pass body without re-paying the
# failed compile
_KERNEL_FAILED = set()
