"""Stacking: batching flat key records into blocks.

Reference: ``bolt/spark/stack.py :: StackedArray`` — ``_stack(size)`` groups
consecutive records' values into one ``(n, *value_shape)`` block per
partition so a user function hits BLAS once per block instead of once per
record; ``map`` operates on blocks, ``unstack`` restores records
(symbol-level citations, SURVEY.md §0).

On TPU the batching the reference buys with this machinery is native — every
``map`` is already one fused vectorised launch — so ``StackedArray`` is a
thin compatibility view: it exposes the same block-wise ``map`` contract
(``func`` sees ``(n, *value_shape)`` and must preserve ``n``), executing all
blocks in one compiled program.
"""

import jax
import jax.numpy as jnp

from bolt_tpu import engine as _engine
from bolt_tpu import stream as _streamlib
from bolt_tpu.obs import trace as _obs
from bolt_tpu.tpu.array import (BoltArrayTPU, _TRACE_ERRORS, _cached_jit,
                                _canon, _chain_apply, _chain_donate_ok,
                                _check_live, _check_value_shape, _constrain,
                                _traceable)
from bolt_tpu.utils import prod


def _stack_map_body(data, func, split, size, canon=None):
    """The block-batched map program body: flatten records, vmap ``func``
    over full-size blocks plus one ragged tail, restore keys, optionally
    cast.  Geometry derives from ``data.shape``, so the SAME traced body
    serves the materialised program below AND the streaming executor's
    per-slab program (``bolt_tpu/stream.py``) — parity by construction."""
    kshape = data.shape[:split]
    vshape = data.shape[split:]
    n = prod(kshape)
    flat = data.reshape((n,) + vshape)
    if n == 0:
        # zero records (a filter with no survivors): func never runs,
        # but the empty output must still carry the value shape/dtype
        # func WOULD produce so empty and non-empty branches of one
        # pipeline stay consistent
        ob = jax.eval_shape(func, jax.ShapeDtypeStruct(
            (size,) + vshape, flat.dtype))
        return jnp.zeros(kshape + tuple(ob.shape[1:]), canon or ob.dtype)
    nfull = n // size
    outs = []
    if nfull:
        blocks = flat[:nfull * size].reshape((nfull, size) + vshape)
        out = jax.vmap(func)(blocks)
        if out.ndim < 2 or out.shape[:2] != (nfull, size):
            got = out.shape[1] if out.ndim >= 2 else "none"
            raise ValueError(
                "stacked map must preserve the record count: "
                "block of %d records -> %s" % (size, got))
        outs.append(out.reshape((nfull * size,) + out.shape[2:]))
    if n % size:
        tail = flat[nfull * size:]
        tout = func(tail)
        if tout.shape[0] != tail.shape[0]:
            raise ValueError(
                "stacked map must preserve the record count: "
                "block of %d records -> %d"
                % (tail.shape[0], tout.shape[0]))
        outs.append(tout)
    out = outs[0] if len(outs) == 1 else jnp.concatenate(outs, axis=0)
    out = out.reshape(kshape + out.shape[1:])
    if canon is not None:
        out = out.astype(canon)   # fused into the same program
    return out


class StackedArray:
    """A block-batched view over a :class:`BoltArrayTPU`."""

    def __init__(self, barray, size):
        self._barray = barray
        self._size = int(size)

    @classmethod
    def stack(cls, barray, size=1000):
        if int(size) < 1:
            raise ValueError("stack size must be >= 1, got %r" % (size,))
        return cls(barray, size)

    @property
    def shape(self):
        return self._barray.shape

    @property
    def split(self):
        return self._barray.split

    @property
    def dtype(self):
        return self._barray.dtype

    @property
    def mode(self):
        return "tpu"

    @property
    def size(self):
        """Records per block (reference: the ``_stack(size)`` argument)."""
        return self._size

    @property
    def nblocks(self):
        n = prod(self.shape[:self.split])
        return -(-n // self._size)

    def map(self, func, value_shape=None, dtype=None):
        """Apply ``func`` block-wise: it receives ``(n, *value_shape)`` and
        must return ``(n, *new_value_shape)`` — record counts are preserved,
        as the reference requires for ``unstack`` to restore keys.  All
        blocks run in one compiled program, and ``func`` traces at most
        TWICE (vmap over the full-size blocks + one ragged tail), so the
        trace cost is independent of the block count — ``stacked(size=1)``
        over a million records compiles as fast as ``size=1000``."""
        func = _traceable(func)
        b = self._barray
        _engine.strict_guard(b, "stacked().map()")
        if b._stream is not None:
            # streaming source (out-of-core): record the block-batched
            # map as a device-side stage; the per-slab program applies
            # the SAME _stack_map_body at slab geometry
            out = _streamlib.stacked_map_stage(self, func, dtype)
            if out is not NotImplemented:
                return out
        split = b.split
        mesh = b.mesh
        kshape = b.shape[:split]
        vshape = b.shape[split:]
        n = prod(kshape)
        size = self._size
        # donation-aware terminal: a sole-owned deferred chain donates its
        # base into the block-batched program (input-sized output)
        donate = b.deferred and _chain_donate_ok(b._chain)
        base, funcs = b._chain_parts()
        canon = None if dtype is None else _canon(dtype)
        if value_shape is not None:
            # validate BEFORE compiling/executing the full program (the
            # per-record output shape is the block shape minus the axis)
            try:
                ob = jax.eval_shape(func, jax.ShapeDtypeStruct(
                    (min(size, n) or size,) + vshape, b._aval.dtype))
            except _TRACE_ERRORS:
                # non-traceable func: skip hint validation (shape errors
                # would still surface at the real trace below)
                ob = None
            _check_value_shape(
                value_shape, None if ob is None else tuple(ob.shape[1:]))

        def build():
            def run(data):
                # ONE traced body — _stack_map_body above — serves this
                # materialised program, the streaming executor's
                # per-slab program AND (as the pattern) the serve
                # layer's batched programs: parity by construction
                data = _chain_apply(funcs, split, data)
                out = _stack_map_body(data, func, split, size, canon)
                return _constrain(out, mesh, split)
            return jax.jit(run, donate_argnums=(0,) if donate else ())

        fn = _cached_jit(("stack-map", func, funcs, base.shape,
                          str(base.dtype), split, size, canon, donate,
                          mesh), build)
        with _obs.span("stack.map", size=size, donate=donate):
            out = fn(_check_live(base))
        if donate:
            b._consume_donated("stacked().map()")
        return StackedArray(BoltArrayTPU(out, split, mesh), size)

    def unstack(self):
        """Back to a :class:`BoltArrayTPU` (reference:
        ``StackedArray.unstack``); a no-op unwrap here."""
        return self._barray

    def __repr__(self):
        s = "StackedArray\n"
        s += "mode: tpu\n"
        s += "shape: %s\n" % str(self.shape)
        s += "split: %d\n" % self.split
        s += "size: %d\n" % self._size
        s += "nblocks: %d\n" % self.nblocks
        return s
