"""Codec-encoded streaming ingest: move FEWER bytes over the link.

The streaming executor is transfer-bound by design (``stream_sum``'s
PERF.json traffic model is literally "one host→device pass per byte"),
and the on-device fused map→sum has sat at the HBM roofline for five
bench rounds — so the remaining single-chip lever is shrinking the
bytes themselves (ROADMAP item 5, SURVEY §2.3).  This module is the
codec registry the executor (``bolt_tpu.stream``) consults: uploader
workers ENCODE each slab on host (parallel, per worker, counted as
``codec_encode_seconds`` / ``codec_bytes_raw`` / ``codec_bytes_wire``),
the wire representation plus a tiny sidecar crosses the link, and the
slab program DECODES on device as the FIRST traced expression of the
existing partial/fold body — so decode costs zero extra HBM passes: the
decoded values stream straight into the same stage chain and terminal
partial the uncompressed path traces.

Registry (:func:`get` / :func:`names`):

========== ======== ======= ====================================
name       wire     ratio*  contract
========== ======== ======= ====================================
``bf16``   bfloat16 0.5     lossy down-cast; ~1e-2 relative
                            (:func:`bolt_tpu._precision.codec_bound`)
``f16``    float16  0.5     lossy down-cast; ~1e-3 relative
``int8``   uint8 +  0.25    lossy per-slab affine quantisation —
           sidecar          ``q = round((x - zp) / scale)``, the
                            float32 ``(scale, zp)`` pair rides as a
                            sidecar; worst case ~½·scale absolute
                            per element (finite values only)
``delta``  uint32   1.0     LOSSLESS: f32 bits delta-coded along the
(``delta-         (bit-     trailing value axis (wraparound uint32
``f32``)           exact)   arithmetic both ways), decoded by an
                            exact ``cumsum`` + bitcast — results are
                            BIT-IDENTICAL to uncompressed streaming
========== ======== ======= ====================================

\\* ratio = wire bytes / raw bytes for a float32 source.

Accuracy follows the ``_precision.resolve_accumulate`` contract
template: the default (no codec) is bit-exact; lossy codecs are an
explicit opt-in with parity bounds documented in
:func:`bolt_tpu._precision.codec_bound` and parity-locked in
tests/test_codec.py; order statistics (``min``/``max``/``ptp`` —
standalone or as fused multi-stat members) and integer/bool pipelines
REFUSE lossy codecs pointedly (quantising an argmax-adjacent answer is
never what the caller meant), while the lossless ``delta-f32`` codec is
accepted everywhere a float32 pipeline streams.

Selection: ``fromcallback(..., codec="bf16")`` / ``fromiter(...,
codec=...)`` per source, or the thread-local ``stream.codec("bf16")``
scope (same stack discipline as ``stream.uploaders``).  The whole stack
inherits the choice: checkpoint fingerprints include the codec id (a
resumed run never adopts a checkpoint cut under a different codec),
multi-process shards encode locally so DCN/gloo bytes shrink too
(sidecar-free codecs only — ``multihost.sidecar_codec_error``), the
serving arbiter leases the COMPRESSED slab bytes (admission floors
recompute via :meth:`Codec.ratio`), and ``analysis.check`` forecasts
the bytes saved as the BLT016 diagnostic.

Where Pallas is available, an opt-in fused decode-and-reduce kernel
(``bolt_tpu.ops.kernels.fused_decode_sum``, armed by
``BOLT_CODEC_KERNEL=1``) keeps the int8 decode in-register on the way
into a streamed ``sum`` — parity-locked against the XLA decode path
like every other kernel in that module.
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

from bolt_tpu._precision import codec_bound  # noqa: F401  (re-export)

# ---------------------------------------------------------------------
# the codec contract
# ---------------------------------------------------------------------


class Codec:
    """One wire codec: host-side :meth:`encode` (numpy, runs on the
    uploader workers) and device-side :meth:`decode` (a traced jax
    expression, fused into the slab program).

    The wire block always keeps the RAW block's shape — only the dtype
    changes — so slab sharding, per-process shard slicing and the
    donated-ring geometry are untouched; ``sidecar`` says whether
    :meth:`encode` returns per-slab side arrays (int8's scale/zero
    point) that must ride along to :meth:`decode`.  Sidecar codecs
    cannot run under a ``shard_map`` pod program (the per-process
    sidecars are not a replicated global value) — the executor refuses
    them there with the pointed
    ``multihost.sidecar_codec_error`` message."""

    name = None
    lossless = False
    sidecar = False

    def wire_dtype(self, dtype):
        """The wire dtype for source ``dtype`` — raises a pointed
        ``ValueError`` when this codec cannot encode it."""
        raise NotImplementedError

    def ratio(self, dtype):
        """wire bytes / raw bytes for ``dtype`` (sidecar excluded —
        it is O(1) per slab)."""
        dtype = np.dtype(dtype)
        return self.wire_dtype(dtype).itemsize / float(dtype.itemsize)

    def encode(self, block, delta_ok=True):
        """``(wire_block, sidecar_tuple)`` for one host slab block.
        ``delta_ok`` is False when the block has no trailing VALUE axis
        to transform along (an all-key-axes source) — only the delta
        codec consults it."""
        raise NotImplementedError

    def decode(self, wire, sidecar, dtype, delta_ok=True):
        """The traced device-side inverse: decoded values of ``dtype``
        with the raw block's shape.  Runs as the first expression of
        the slab program (inside ``shard_map`` on pods), so it must be
        shard-local: no cross-record dependence along the (sharded)
        key axes."""
        raise NotImplementedError

    def _refuse(self, dtype, why):
        raise ValueError(
            "codec %r cannot encode a %s pipeline: %s.  Stream "
            "uncompressed, or pick a codec from %r that supports the "
            "dtype" % (self.name, np.dtype(dtype), why, names()))


class _CastCodec(Codec):
    """Down-cast codecs (``bf16``/``f16``): the wire block is the raw
    block cast to a half-width float; decode is a cast back.  Lossy —
    the documented envelope is ``_precision.codec_bound(name)``
    relative — and sidecar-free, so they run unchanged on pods (each
    process encodes its local shard; the ``shard_map`` decode is
    elementwise)."""

    def __init__(self, name, np_wire):
        self.name = name
        self._np_wire = np_wire

    def wire_dtype(self, dtype):
        dtype = np.dtype(dtype)
        if not np.issubdtype(dtype, np.floating) \
                or dtype.itemsize <= self._np_wire().dtype.itemsize:
            self._refuse(dtype, "the down-cast needs a wider float "
                                "source (float32/float64)")
        return self._np_wire().dtype

    def encode(self, block, delta_ok=True):
        return np.asarray(block).astype(self.wire_dtype(block.dtype)), ()

    def decode(self, wire, sidecar, dtype, delta_ok=True):
        return wire.astype(dtype)


def _np_bf16():
    import ml_dtypes                     # jax's own dtype package
    return np.zeros((), ml_dtypes.bfloat16)


def _np_f16():
    return np.zeros((), np.float16)


class _Int8Codec(Codec):
    """Per-slab affine quantisation: ``q = round((x - zp) / scale)``
    into uint8, with the float32 ``(scale, zp)`` pair as a per-slab
    sidecar; decode is ``q * scale + zp``.  0.25x the wire bytes of a
    float32 source.  Lossy — worst case ~``scale / 2`` ABSOLUTE error
    per element (``scale`` = the slab's value range / 255) — and only
    defined for FINITE float values (a NaN/inf in the slab poisons the
    range; that is the caller's contract, like int8 accumulate's
    wraparound).  Encode is deterministic per block, so a resumed
    int8-encoded run re-derives the exact same sidecar scales for the
    remaining slabs — checkpoint-consistent by construction
    (tests/test_codec.py proves it across a kill -9)."""

    name = "int8"
    sidecar = True

    def wire_dtype(self, dtype):
        dtype = np.dtype(dtype)
        if not np.issubdtype(dtype, np.floating):
            self._refuse(dtype, "affine quantisation is defined for "
                                "float sources only")
        return np.dtype(np.uint8)

    def encode(self, block, delta_ok=True):
        block = np.asarray(block)
        self.wire_dtype(block.dtype)
        lo = float(block.min()) if block.size else 0.0
        hi = float(block.max()) if block.size else 0.0
        scale = (hi - lo) / 255.0
        if scale <= 0.0 or not np.isfinite(scale):
            scale = 1.0                     # constant slab: q == 0
        q = np.clip(np.rint((block - lo) / scale), 0, 255).astype(
            np.uint8)
        return q, (np.float32(scale), np.float32(lo))

    def decode(self, wire, sidecar, dtype, delta_ok=True):
        scale, zp = sidecar
        return (wire.astype(jnp.float32) * scale + zp).astype(dtype)


class _DictCodec(Codec):
    """LOSSLESS dictionary coding for low-cardinality INTEGER/bool
    pipelines (ISSUE 18): host encode builds the slab's sorted value
    dictionary (≤ 256 distinct values — IDs, labels, bucketed keys),
    ships uint8 indices as the wire block with the 256-entry dictionary
    as a per-slab sidecar, and the fused device decode is one gather
    (``dictionary[indices]``) — bit-identical by construction, at
    1/8 the wire bytes of an int64 key column.  This is the natural
    encoding for spilled shuffle buckets of integer keys
    (``checkpoint.spill_save`` applies it automatically), and a slab
    with MORE than 256 distinct values raises a pointed ValueError
    (the caller's cardinality contract, like int8's finite-values
    contract — never a silent fallback).

    Float pipelines are refused POINTEDLY: floating-point values are
    not dictionary-shaped data, and the lossy cast codecs (or lossless
    ``delta-f32``) are the float answer.  Sidecar codec → refused on
    pods like int8 (``multihost.sidecar_codec_error``)."""

    name = "dict"
    lossless = True
    sidecar = True

    def wire_dtype(self, dtype):
        dtype = np.dtype(dtype)
        if not (np.issubdtype(dtype, np.integer)
                or dtype == np.dtype(np.bool_)):
            self._refuse(dtype, "dictionary coding is defined for "
                                "integer/bool sources only — float "
                                "values are not dictionary-shaped "
                                "(use bf16/f16/int8/delta-f32 for "
                                "float pipelines)")
        return np.dtype(np.uint8)

    def encode(self, block, delta_ok=True):
        block = np.asarray(block)
        self.wire_dtype(block.dtype)
        values, inverse = np.unique(block, return_inverse=True)
        if values.size > 256:
            raise ValueError(
                "codec 'dict' needs <= 256 distinct values per slab, "
                "got %d: dictionary coding is for low-cardinality "
                "key/label columns — stream this source uncompressed"
                % values.size)
        # the sidecar dictionary is PADDED to a fixed 256 entries so
        # every slab shares one decode-program geometry (unused tail
        # repeats the last value — indices never reach it)
        table = np.empty(256, block.dtype)
        table[:values.size] = values
        table[values.size:] = values[-1] if values.size else 0
        wire = inverse.reshape(block.shape).astype(np.uint8)
        return wire, (table,)

    def decode(self, wire, sidecar, dtype, delta_ok=True):
        return sidecar[0][wire.astype(jnp.int32)].astype(dtype)


class _DeltaF32Codec(Codec):
    """The LOSSLESS byte-plane-friendly codec for bit-exact float32
    pipelines: the raw bits (viewed as uint32) are delta-coded along
    the TRAILING VALUE axis with wraparound uint32 subtraction, and the
    device decode is an exact wraparound ``cumsum`` + bitcast — both
    directions are pure integer arithmetic, so the decoded bits equal
    the raw bits exactly (NaN payloads included) and a delta-encoded
    streamed reduction is BIT-IDENTICAL to the uncompressed one
    (tested).  Wire bytes equal raw bytes (ratio 1.0): the win is the
    transform's compressibility for the storage/link layers beneath,
    while keeping the whole codec stack (fingerprints, counters, the
    fused on-device decode) exercised by a codec that is allowed
    EVERYWHERE — order stats and resumable bit-exact pipelines
    included.

    The delta axis is the LAST axis only when it is a value axis
    (``split < ndim``): value axes are never device-sharded, so the
    per-shard ``cumsum`` under a pod's ``shard_map`` sees every element
    it needs.  An all-key-axes source (``delta_ok=False``) skips the
    delta and ships the raw bitcast — still lossless, still one wire
    format per source geometry."""

    name = "delta-f32"
    lossless = True

    def wire_dtype(self, dtype):
        dtype = np.dtype(dtype)
        if dtype != np.dtype(np.float32):
            self._refuse(dtype, "the bit-plane delta transform is "
                                "defined for float32 sources only")
        return np.dtype(np.uint32)

    def encode(self, block, delta_ok=True):
        block = np.asarray(block)
        self.wire_dtype(block.dtype)
        u = np.ascontiguousarray(block).view(np.uint32)
        if not delta_ok or u.shape[-1] < 2:
            return u.copy(), ()
        d = u.copy()
        d[..., 1:] = u[..., 1:] - u[..., :-1]     # uint32 wraparound
        return d, ()

    def decode(self, wire, sidecar, dtype, delta_ok=True):
        acc = wire
        if delta_ok and wire.shape[-1] >= 2:
            acc = jnp.cumsum(wire.astype(jnp.uint32), axis=-1,
                             dtype=jnp.uint32)
        return jax.lax.bitcast_convert_type(acc, jnp.float32)


# ---------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------

_REGISTRY = {}


def register(codec):
    """Register a codec instance under its ``name`` (the extension
    door: a project-specific dictionary codec slots in here and the
    whole streaming stack — scopes, counters, fingerprints, arbiter
    ratios, BLT016 — picks it up)."""
    if not codec.name:
        raise ValueError("codec must carry a non-empty .name")
    _REGISTRY[codec.name] = codec
    return codec


def names():
    """The registered codec names, sorted."""
    return tuple(sorted(_REGISTRY))


def get(name):
    """The registered codec for ``name`` (a :class:`Codec` instance
    passes through) — pointed ``ValueError`` naming the known codecs
    otherwise."""
    if isinstance(name, Codec):
        return name
    c = _REGISTRY.get(name)
    if c is None:
        raise ValueError("unknown codec %r (known: %s)"
                         % (name, ", ".join(names())))
    return c


register(_CastCodec("bf16", _np_bf16))
register(_CastCodec("f16", _np_f16))
register(_Int8Codec())
register(_DeltaF32Codec())
register(_DictCodec())


# ---------------------------------------------------------------------
# the opt-in Pallas decode-and-reduce door (ops/kernels.py)
# ---------------------------------------------------------------------

def kernel_enabled():
    """True when the fused Pallas decode-and-reduce kernel is armed
    (``BOLT_CODEC_KERNEL=1``): a streamed int8 ``sum`` with no stages
    then decodes in-register inside
    ``bolt_tpu.ops.kernels.fused_decode_sum`` instead of the XLA
    decode+reduce — parity-locked, geometry-gated (the kernel returns
    None off-plan and the XLA path serves)."""
    return os.environ.get("BOLT_CODEC_KERNEL", "0").lower() in ("1",
                                                                "true")
