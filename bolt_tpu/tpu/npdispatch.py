"""``__array_function__`` dispatch: the non-ufunc NumPy API on device.

The local backend is an ndarray subclass, so ``np.sum(b)`` /
``np.concatenate([a, b])`` run natively; before this module the TPU
backend served them by silently gathering the WHOLE distributed array
through ``__array__`` — a ~100× trap at scale (VERDICT r2 missing-3).
Now the common numpy API routes to the device-native bolt methods — with
NUMPY semantics (``np.sum(b)`` reduces every axis, where ``b.sum()``
reduces the key axes), zero host transfer, results returned as bolt
arrays.  Anything not in the table (or called with kwargs the device
path cannot honour, e.g. ``out=``) falls back to the host route, which
warns through :func:`implicit_gather_warning` above a size threshold.

Reference: the ndarray-native behavior of ``bolt/local/array.py``
(symbol cite — SURVEY §0).
"""

import warnings

import numpy as np

_NV = np._NoValue

_TABLE = {}


class _Fallback(Exception):
    """Raised by a handler that cannot serve the call on device; the
    dispatcher then takes the host path (gather + plain numpy)."""


def _implements(*np_funcs):
    def deco(handler):
        for f in np_funcs:
            _TABLE[f] = handler
        return handler
    return deco


def _require_default(**pairs):
    """Raise :class:`_Fallback` when any of the given kwargs was set to
    a meaningful value — the device path cannot honour it (``None`` and
    numpy's no-value sentinel both read as "left at default")."""
    for name, (got, default) in pairs.items():
        if got is not default and got is not _NV and got is not None:
            raise _Fallback(name)


def _all_axes(a, axis):
    """numpy's ``axis=None`` means EVERY axis; bolt methods default to
    the key axes — translate explicitly."""
    return tuple(range(a.ndim)) if axis is None else axis


def _keepdims(kd):
    return False if kd in (_NV, None) else bool(kd)


# ---------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------

@_implements(np.sum)
def _sum(a, axis=None, dtype=None, out=None, keepdims=_NV, initial=_NV,
         where=_NV):
    _require_default(dtype=(dtype, None), out=(out, None),
                     initial=(initial, _NV), where=(where, _NV))
    return a.sum(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.prod)
def _prod(a, axis=None, dtype=None, out=None, keepdims=_NV, initial=_NV,
          where=_NV):
    _require_default(dtype=(dtype, None), out=(out, None),
                     initial=(initial, _NV), where=(where, _NV))
    return a.prod(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.mean)
def _mean(a, axis=None, dtype=None, out=None, keepdims=_NV, where=_NV):
    _require_default(dtype=(dtype, None), out=(out, None), where=(where, _NV))
    return a.mean(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.var)
def _var(a, axis=None, dtype=None, out=None, ddof=0, keepdims=_NV,
         where=_NV, mean=_NV, correction=_NV):
    _require_default(dtype=(dtype, None), out=(out, None), where=(where, _NV),
                     mean=(mean, _NV))
    if correction is not _NV:
        if ddof != 0:
            raise ValueError("can't specify both correction and ddof")
        ddof = correction
    return a.var(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims),
                 ddof=ddof)


@_implements(np.std)
def _std(a, axis=None, dtype=None, out=None, ddof=0, keepdims=_NV,
         where=_NV, mean=_NV, correction=_NV):
    _require_default(dtype=(dtype, None), out=(out, None), where=(where, _NV),
                     mean=(mean, _NV))
    if correction is not _NV:
        if ddof != 0:
            raise ValueError("can't specify both correction and ddof")
        ddof = correction
    return a.std(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims),
                 ddof=ddof)


@_implements(np.min, np.amin)
def _min(a, axis=None, out=None, keepdims=_NV, initial=_NV, where=_NV):
    _require_default(out=(out, None), initial=(initial, _NV),
                     where=(where, _NV))
    return a.min(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.max, np.amax)
def _max(a, axis=None, out=None, keepdims=_NV, initial=_NV, where=_NV):
    _require_default(out=(out, None), initial=(initial, _NV),
                     where=(where, _NV))
    return a.max(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.ptp)
def _ptp(a, axis=None, out=None, keepdims=_NV):
    _require_default(out=(out, None))
    return a.ptp(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.all)
def _all(a, axis=None, out=None, keepdims=_NV, where=_NV):
    _require_default(out=(out, None), where=(where, _NV))
    return a.all(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.any)
def _any(a, axis=None, out=None, keepdims=_NV, where=_NV):
    _require_default(out=(out, None), where=(where, _NV))
    return a.any(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.cumsum)
def _cumsum(a, axis=None, dtype=None, out=None):
    _require_default(dtype=(dtype, None), out=(out, None))
    return a.cumsum(axis=axis)          # axis=None flattens on both


@_implements(np.cumprod)
def _cumprod(a, axis=None, dtype=None, out=None):
    _require_default(dtype=(dtype, None), out=(out, None))
    return a.cumprod(axis=axis)


@_implements(np.argmax)
def _argmax(a, axis=None, out=None, keepdims=_NV):
    _require_default(out=(out, None))
    return a.argmax(axis=axis, keepdims=_keepdims(keepdims))


@_implements(np.argmin)
def _argmin(a, axis=None, out=None, keepdims=_NV):
    _require_default(out=(out, None))
    return a.argmin(axis=axis, keepdims=_keepdims(keepdims))


# ---------------------------------------------------------------------
# order statistics
# ---------------------------------------------------------------------

def _quantile_call(a, q, axis, method, keepdims):
    if method not in ("linear", "lower", "higher", "midpoint", "nearest"):
        # numpy's other estimators (inverted_cdf, median_unbiased, ...)
        # are not in jnp.quantile — serve them on the host path
        raise _Fallback("method")
    return a.quantile(q, axis=_all_axes(a, axis), method=method,
                      keepdims=_keepdims(keepdims))


@_implements(np.quantile)
def _quantile(a, q, axis=None, out=None, overwrite_input=False,
              method="linear", keepdims=False, weights=None,
              interpolation=None):
    _require_default(out=(out, None), weights=(weights, None),
                     interpolation=(interpolation, None))
    return _quantile_call(a, q, axis, method, keepdims)


@_implements(np.percentile)
def _percentile(a, q, axis=None, out=None, overwrite_input=False,
                method="linear", keepdims=False, weights=None,
                interpolation=None):
    _require_default(out=(out, None), weights=(weights, None),
                     interpolation=(interpolation, None))
    return _quantile_call(a, np.true_divide(q, 100.0), axis, method,
                          keepdims)


@_implements(np.median)
def _median(a, axis=None, out=None, overwrite_input=False, keepdims=False):
    _require_default(out=(out, None))
    return _quantile_call(a, 0.5, axis, "linear", keepdims)


# ---------------------------------------------------------------------
# sorting / selection / indexing
# ---------------------------------------------------------------------

@_implements(np.sort)
def _sort(a, axis=-1, kind=None, order=None, stable=None):
    _require_default(order=(order, None))
    if stable:
        kind = "stable"
    if axis is None:
        out = a.ravel()
        out.sort(axis=0, kind=kind)
        return out
    out = a._clone()
    out.sort(axis=axis, kind=kind)
    return out


@_implements(np.argsort)
def _argsort(a, axis=-1, kind=None, order=None, stable=None):
    _require_default(order=(order, None))
    return a.argsort(axis=axis, kind="stable" if stable else kind)


@_implements(np.take)
def _take(a, indices, axis=None, out=None, mode="raise"):
    _require_default(out=(out, None))
    return a.take(indices, axis=axis, mode=mode)


@_implements(np.repeat)
def _repeat(a, repeats, axis=None):
    return a.repeat(repeats, axis=axis)


@_implements(np.nonzero)
def _nonzero(a):
    return a.nonzero()


@_implements(np.searchsorted)
def _searchsorted(a, v, side="left", sorter=None):
    return a.searchsorted(v, side=side, sorter=sorter)


@_implements(np.unique)
def _unique(ar, return_index=False, return_inverse=False,
            return_counts=False, axis=None, equal_nan=True, sorted=True):
    if return_index or return_inverse or axis is not None \
            or not equal_nan or not sorted:
        raise _Fallback("unique options")
    from bolt_tpu.ops import unique as bolt_unique
    return bolt_unique(ar, return_counts=return_counts)


# ---------------------------------------------------------------------
# shaping / elementwise
# ---------------------------------------------------------------------

@_implements(np.transpose)
def _transpose(a, axes=None):
    # bolt's key/value boundary applies: a reversal that crosses it
    # raises the method's loud ValueError (use swap), never a gather
    return a.transpose() if axes is None else a.transpose(*axes)


@_implements(np.reshape)
def _reshape(a, shape=None, order="C", newshape=None, copy=None):
    _require_default(copy=(copy, None))
    if order != "C":
        raise _Fallback("order")
    if shape is None:
        shape = newshape
    from bolt_tpu.utils import tupleize
    return a.reshape(*tupleize(shape))


@_implements(np.ravel)
def _ravel(a, order="C"):
    return a.ravel(order=order)


@_implements(np.squeeze)
def _squeeze(a, axis=None):
    return a.squeeze(axis=axis)


@_implements(np.swapaxes)
def _swapaxes(a, axis1, axis2):
    return a.swapaxes(axis1, axis2)


@_implements(np.count_nonzero)
def _count_nonzero(a, axis=None, keepdims=False):
    # (a != 0) is a deferred mask entry; the int cast (astype
    # canonicalises it) and the sum fuse with it into one program
    mask = (a != 0) if np.dtype(a.dtype) != np.bool_ else a
    return mask.astype(np.int64).sum(axis=_all_axes(a, axis),
                                     keepdims=_keepdims(keepdims))


@_implements(np.diff)
def _diff(a, n=1, axis=-1, prepend=_NV, append=_NV):
    _require_default(prepend=(prepend, _NV), append=(append, _NV))
    import operator
    n = operator.index(n)
    if n < 0:
        raise ValueError("order must be non-negative but got %d" % n)
    axis = axis + a.ndim if axis < 0 else axis
    from bolt_tpu.utils import inshape
    inshape(a.shape, (axis,))
    hi = tuple(slice(1, None) if i == axis else slice(None)
               for i in range(a.ndim))
    lo = tuple(slice(None, -1) if i == axis else slice(None)
               for i in range(a.ndim))
    boolean = np.dtype(a.dtype) == np.bool_
    out = a
    for _ in range(n):
        # two slices + one elementwise program per order; numpy's bool
        # diff is XOR (subtract rejects bool on both libraries)
        out = (out[hi] != out[lo]) if boolean else out[hi] - out[lo]
    return out


@_implements(np.flip)
def _flip(m, axis=None):
    from bolt_tpu.utils import inshape, tupleize
    if axis is None:
        axes = tuple(range(m.ndim))
    else:
        axes = tuple(a + m.ndim if a < 0 else a for a in tupleize(axis))
        if len(set(axes)) != len(axes):
            raise ValueError("repeated axis")
        inshape(m.shape, axes)
    sl = tuple(slice(None, None, -1) if i in axes else slice(None)
               for i in range(m.ndim))
    return m[sl]                 # one compiled reversed-slice program


@_implements(np.moveaxis)
def _moveaxis(a, source, destination):
    from bolt_tpu.utils import inshape, tupleize
    src = [s + a.ndim if s < 0 else s for s in tupleize(source)]
    dst = [d + a.ndim if d < 0 else d for d in tupleize(destination)]
    if len(src) != len(dst):
        raise ValueError(
            "`source` and `destination` arguments must have the same "
            "number of elements")
    if len(set(src)) != len(src) or len(set(dst)) != len(dst):
        raise ValueError(
            "repeated axis in `source` or `destination` argument")
    inshape(a.shape, src)       # out-of-range (incl. doubly-negative)
    inshape(a.shape, dst)       # raises instead of silently wrapping
    rest = [i for i in range(a.ndim) if i not in src]
    perm = [None] * a.ndim
    for s, d in zip(src, dst):
        perm[d] = s
    it = iter(rest)
    perm = [next(it) if p is None else p for p in perm]
    # bolt's key/value boundary applies, like np.transpose: a move that
    # crosses it raises the loud ValueError (use swap), never a gather
    return a.transpose(*perm)


@_implements(np.clip)
def _clip(a, a_min=_NV, a_max=_NV, out=None, min=_NV, max=_NV, **kw):
    _require_default(out=(out, None))
    if kw:
        raise _Fallback("clip kwargs")
    lo = a_min if a_min is not _NV else (min if min is not _NV else None)
    hi = a_max if a_max is not _NV else (max if max is not _NV else None)
    return a.clip(lo, hi)


@_implements(np.round)
def _round(a, decimals=0, out=None):
    _require_default(out=(out, None))
    return a.round(decimals)


@_implements(np.real)
def _real(val):
    return val.real


@_implements(np.imag)
def _imag(val):
    return val.imag


@_implements(np.diagonal)
def _diagonal(a, offset=0, axis1=0, axis2=1):
    return a.diagonal(offset, axis1, axis2)


@_implements(np.trace)
def _trace(a, offset=0, axis1=0, axis2=1, dtype=None, out=None):
    _require_default(out=(out, None))
    return a.trace(offset, axis1, axis2, dtype=dtype)


@_implements(np.concatenate)
def _concatenate(arrays, axis=0, out=None, dtype=None, casting="same_kind"):
    _require_default(out=(out, None), dtype=(dtype, None))
    seq = list(arrays)
    if not seq:
        raise ValueError("need at least one array to concatenate")
    first = seq[0]
    if not _is_tpu(first):
        raise _Fallback("first operand not on device")
    # ONE compiled program over all operands (axis=None ravels each,
    # like numpy) — not n−1 pairwise copies
    return first._concat_many(seq[1:], axis)


@_implements(np.dot)
def _dot(a, b, out=None):
    _require_default(out=(out, None))
    if not _is_tpu(a):
        raise _Fallback("first operand not on device")
    return a.dot(b)


@_implements(np.where)
def _where(condition, x=_NV, y=_NV):
    if (x is _NV) != (y is _NV):
        raise ValueError(
            "either both or neither of x and y should be given")
    if x is _NV:
        # 1-arg form IS nonzero
        if not _is_tpu(condition):
            raise _Fallback("condition not on device")
        return condition.nonzero()
    import jax
    import jax.numpy as jnp
    from bolt_tpu.tpu.array import BoltArrayTPU, _cached_jit, _constrain
    devs = [a for a in (condition, x, y) if _is_tpu(a)]
    if not devs:
        raise _Fallback("no device operand")
    # anchor on the MOST-split device operand: anchoring on a
    # replicated (split=0) condition would constrain the result
    # replicated and all-gather a sharded x/y
    b = max(devs, key=lambda a: a.split)
    ops = [b._coerce_operand(b._coerce_bolt_operand(a, "where"))
           for a in (condition, x, y)]
    out_shape = np.broadcast_shapes(*(np.shape(o) for o in ops))
    split = b.split
    # keys survive only when no broadcast axis displaced them: same
    # rank AND the leading dims still match b's key axes
    new_split = split if (len(out_shape) == b.ndim
                          and out_shape[:split] == b.shape[:split]) else 0
    mesh = b.mesh

    def build():
        def run(c, xx, yy):
            return _constrain(jnp.where(c, xx, yy), mesh, new_split)
        return jax.jit(run)

    fn = _cached_jit(("where",) + tuple(
        (np.shape(o), str(getattr(o, "dtype", type(o).__name__)))
        for o in ops) + (new_split, mesh), build)
    return BoltArrayTPU(fn(*ops), new_split, mesh)


@_implements(np.histogram)
def _histogram(a, bins=10, range=None, density=False, weights=None):
    _require_default(weights=(weights, None))
    if not isinstance(bins, (int, np.integer)):
        raise _Fallback("bin edges")        # array edges: host path
    from bolt_tpu.ops import histogram as bolt_histogram
    return bolt_histogram(a, bins=bins, range=range, density=density)


@_implements(np.bincount)
def _bincount(a, weights=None, minlength=0):
    _require_default(weights=(weights, None))
    if a.ndim != 1:
        # numpy's exact rejection; ops.bincount flattens, which would
        # silently diverge from the local backend here
        raise ValueError("object too deep for desired array")
    from bolt_tpu.ops import bincount as bolt_bincount
    return bolt_bincount(a, minlength=minlength)


@_implements(np.split)
def _split_fn(ary, indices_or_sections, axis=0):
    return _do_split(ary, indices_or_sections, axis, strict=True)


@_implements(np.array_split)
def _array_split(ary, indices_or_sections, axis=0):
    return _do_split(ary, indices_or_sections, axis, strict=False)


def _do_split(ary, ios, axis, strict):
    """numpy split semantics as device-served basic slices (each piece
    is one compiled static-slice program through ``__getitem__``)."""
    import operator
    axis = int(axis)
    dim = ary.shape[axis]
    # numpy's own probe: sections-vs-indices is decided by len() — an
    # unsized value (plain int, 0-d array, even a float, which numpy
    # int()-coerces) is a SECTION COUNT; sized values are index lists
    # whose entries must be true integers (numpy's slices raise
    # TypeError for floats — operator.index mirrors that)
    try:
        nidx = len(ios)
    except TypeError:
        nidx = None
    if nidx is None:
        k = int(ios)              # numpy coerces float section counts
        if k <= 0:
            raise ValueError("number sections must be larger than 0.")
        if strict and dim % k != 0:
            raise ValueError(
                "array split does not result in an equal division")
        base, extra = divmod(dim, k)
        sizes = [base + 1] * extra + [base] * (k - extra)
        bounds = np.cumsum([0] + sizes)
    else:
        # raw indices: negative bounds wrap and oversized ones clamp
        # through ordinary slice semantics, exactly like numpy's
        # a[i:j] pieces (reversed pairs give empty pieces)
        bounds = [0] + [operator.index(i)
                        for i in np.asarray(ios).ravel().tolist()] + [dim]
    out = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        sl = [slice(None)] * ary.ndim
        sl[axis] = slice(int(lo), int(hi))
        out.append(ary[tuple(sl)])
    return out


@_implements(np.shape)
def _shape(a):
    return a.shape


@_implements(np.ndim)
def _ndim(a):
    return a.ndim


@_implements(np.size)
def _size(a, axis=None):
    return a.size if axis is None else a.shape[axis]


# ---------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------

def _is_tpu(x):
    from bolt_tpu.tpu.array import BoltArrayTPU
    return isinstance(x, BoltArrayTPU)


# the implicit-gather warning fires ONCE per session above this size;
# tests reset the flag
IMPLICIT_GATHER_WARN_BYTES = 64 << 20
_warned = [False]


def implicit_gather_warning(nbytes):
    """Called by ``BoltArrayTPU.__array__`` when plain-numpy machinery
    implicitly gathers a device array to host.  Warns once per session
    above :data:`IMPLICIT_GATHER_WARN_BYTES` — at multi-GB scale the
    silent gather is the single easiest way to lose 100× (VERDICT r2
    missing-3)."""
    if _warned[0] or nbytes < IMPLICIT_GATHER_WARN_BYTES:
        return
    _warned[0] = True
    warnings.warn(
        "a %.0f MB distributed array is being implicitly gathered to "
        "host (e.g. np.asarray(b) or an unsupported numpy function); "
        "use bolt methods / supported numpy API to stay on device, or "
        "call .toarray() to make the transfer explicit"
        % (nbytes / float(1 << 20)), stacklevel=3)


def _to_host(x):
    return np.asarray(x) if _is_tpu(x) else x


def dispatch(b, func, types, args, kwargs):
    """Serve ``func`` from the device table, else fall back to the host:
    gather every bolt operand (``__array__`` warns above the size
    threshold) and run plain numpy — numpy-correct always, device-fast
    when the table covers it.  Per NEP-18, an operand type we do not
    recognize (another library's duck array) gets ``NotImplemented`` so
    ITS ``__array_function__`` is consulted instead of being hijacked."""
    import jax
    from bolt_tpu.base import BoltArray
    for t in types:
        if not issubclass(t, (BoltArray, np.ndarray, jax.Array)):
            return NotImplemented
    handler = _TABLE.get(func)
    if handler is not None:
        try:
            return handler(*args, **kwargs)
        except _Fallback:
            pass
    host_args = tuple(
        tuple(_to_host(x) for x in a) if isinstance(a, (tuple, list))
        else _to_host(a) for a in args)
    host_kwargs = {k: _to_host(v) for k, v in kwargs.items()}
    return func(*host_args, **host_kwargs)
