"""``__array_function__`` dispatch: the non-ufunc NumPy API on device.

The local backend is an ndarray subclass, so ``np.sum(b)`` /
``np.concatenate([a, b])`` run natively; before this module the TPU
backend served them by silently gathering the WHOLE distributed array
through ``__array__`` — a ~100× trap at scale (VERDICT r2 missing-3).
Now the common numpy API routes to the device-native bolt methods — with
NUMPY semantics (``np.sum(b)`` reduces every axis, where ``b.sum()``
reduces the key axes), zero host transfer, results returned as bolt
arrays.  Anything not in the table (or called with kwargs the device
path cannot honour, e.g. ``out=``) falls back to the host route, which
warns through :func:`implicit_gather_warning` above a size threshold.

Reference: the ndarray-native behavior of ``bolt/local/array.py``
(symbol cite — SURVEY §0).
"""

import operator
import warnings

import numpy as np

_NV = np._NoValue

_TABLE = {}


class _Fallback(Exception):
    """Raised by a handler that cannot serve the call on device; the
    dispatcher then takes the host path (gather + plain numpy)."""


def _implements(*np_funcs):
    def deco(handler):
        for f in np_funcs:
            _TABLE[f] = handler
        return handler
    return deco


def _require_default(**pairs):
    """Raise :class:`_Fallback` when any of the given kwargs was set to
    a meaningful value — the device path cannot honour it (``None`` and
    numpy's no-value sentinel both read as "left at default")."""
    for name, (got, default) in pairs.items():
        if got is not default and got is not _NV and got is not None:
            raise _Fallback(name)


def _all_axes(a, axis):
    """numpy's ``axis=None`` means EVERY axis; bolt methods default to
    the key axes — translate explicitly."""
    return tuple(range(a.ndim)) if axis is None else axis


def _keepdims(kd):
    return False if kd in (_NV, None) else bool(kd)


# ---------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------

@_implements(np.sum)
def _sum(a, axis=None, dtype=None, out=None, keepdims=_NV, initial=_NV,
         where=_NV):
    _require_default(dtype=(dtype, None), out=(out, None),
                     initial=(initial, _NV), where=(where, _NV))
    return a.sum(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.prod)
def _prod(a, axis=None, dtype=None, out=None, keepdims=_NV, initial=_NV,
          where=_NV):
    _require_default(dtype=(dtype, None), out=(out, None),
                     initial=(initial, _NV), where=(where, _NV))
    return a.prod(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.mean)
def _mean(a, axis=None, dtype=None, out=None, keepdims=_NV, where=_NV):
    _require_default(dtype=(dtype, None), out=(out, None), where=(where, _NV))
    return a.mean(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.var)
def _var(a, axis=None, dtype=None, out=None, ddof=0, keepdims=_NV,
         where=_NV, mean=_NV, correction=_NV):
    _require_default(dtype=(dtype, None), out=(out, None), where=(where, _NV),
                     mean=(mean, _NV))
    if correction is not _NV:
        if ddof != 0:
            raise ValueError("can't specify both correction and ddof")
        ddof = correction
    return a.var(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims),
                 ddof=ddof)


@_implements(np.std)
def _std(a, axis=None, dtype=None, out=None, ddof=0, keepdims=_NV,
         where=_NV, mean=_NV, correction=_NV):
    _require_default(dtype=(dtype, None), out=(out, None), where=(where, _NV),
                     mean=(mean, _NV))
    if correction is not _NV:
        if ddof != 0:
            raise ValueError("can't specify both correction and ddof")
        ddof = correction
    return a.std(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims),
                 ddof=ddof)


@_implements(np.min, np.amin)
def _min(a, axis=None, out=None, keepdims=_NV, initial=_NV, where=_NV):
    _require_default(out=(out, None), initial=(initial, _NV),
                     where=(where, _NV))
    return a.min(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.max, np.amax)
def _max(a, axis=None, out=None, keepdims=_NV, initial=_NV, where=_NV):
    _require_default(out=(out, None), initial=(initial, _NV),
                     where=(where, _NV))
    return a.max(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.ptp)
def _ptp(a, axis=None, out=None, keepdims=_NV):
    _require_default(out=(out, None))
    return a.ptp(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.all)
def _all(a, axis=None, out=None, keepdims=_NV, where=_NV):
    _require_default(out=(out, None), where=(where, _NV))
    return a.all(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.any)
def _any(a, axis=None, out=None, keepdims=_NV, where=_NV):
    _require_default(out=(out, None), where=(where, _NV))
    return a.any(axis=_all_axes(a, axis), keepdims=_keepdims(keepdims))


@_implements(np.cumsum)
def _cumsum(a, axis=None, dtype=None, out=None):
    _require_default(dtype=(dtype, None), out=(out, None))
    return a.cumsum(axis=axis)          # axis=None flattens on both


@_implements(np.cumprod)
def _cumprod(a, axis=None, dtype=None, out=None):
    _require_default(dtype=(dtype, None), out=(out, None))
    return a.cumprod(axis=axis)


@_implements(np.argmax)
def _argmax(a, axis=None, out=None, keepdims=_NV):
    _require_default(out=(out, None))
    return a.argmax(axis=axis, keepdims=_keepdims(keepdims))


@_implements(np.argmin)
def _argmin(a, axis=None, out=None, keepdims=_NV):
    _require_default(out=(out, None))
    return a.argmin(axis=axis, keepdims=_keepdims(keepdims))


# ---------------------------------------------------------------------
# order statistics
# ---------------------------------------------------------------------

def _quantile_call(a, q, axis, method, keepdims):
    if method not in ("linear", "lower", "higher", "midpoint", "nearest"):
        # numpy's other estimators (inverted_cdf, median_unbiased, ...)
        # are not in jnp.quantile — serve them on the host path
        raise _Fallback("method")
    return a.quantile(q, axis=_all_axes(a, axis), method=method,
                      keepdims=_keepdims(keepdims))


@_implements(np.quantile)
def _quantile(a, q, axis=None, out=None, overwrite_input=False,
              method="linear", keepdims=False, weights=None,
              interpolation=None):
    _require_default(out=(out, None), weights=(weights, None),
                     interpolation=(interpolation, None))
    return _quantile_call(a, q, axis, method, keepdims)


@_implements(np.percentile)
def _percentile(a, q, axis=None, out=None, overwrite_input=False,
                method="linear", keepdims=False, weights=None,
                interpolation=None):
    _require_default(out=(out, None), weights=(weights, None),
                     interpolation=(interpolation, None))
    return _quantile_call(a, np.true_divide(q, 100.0), axis, method,
                          keepdims)


@_implements(np.median)
def _median(a, axis=None, out=None, overwrite_input=False, keepdims=False):
    _require_default(out=(out, None))
    return _quantile_call(a, 0.5, axis, "linear", keepdims)


# ---------------------------------------------------------------------
# sorting / selection / indexing
# ---------------------------------------------------------------------

@_implements(np.sort)
def _sort(a, axis=-1, kind=None, order=None, stable=None):
    _require_default(order=(order, None))
    if stable:
        kind = "stable"
    if axis is None:
        out = a.ravel()
        out.sort(axis=0, kind=kind)
        return out
    out = a._clone()
    out.sort(axis=axis, kind=kind)
    return out


@_implements(np.argsort)
def _argsort(a, axis=-1, kind=None, order=None, stable=None):
    _require_default(order=(order, None))
    return a.argsort(axis=axis, kind="stable" if stable else kind)


@_implements(np.take)
def _take(a, indices, axis=None, out=None, mode="raise"):
    _require_default(out=(out, None))
    return a.take(indices, axis=axis, mode=mode)


@_implements(np.repeat)
def _repeat(a, repeats, axis=None):
    return a.repeat(repeats, axis=axis)


@_implements(np.nonzero)
def _nonzero(a):
    return a.nonzero()


@_implements(np.searchsorted)
def _searchsorted(a, v, side="left", sorter=None):
    return a.searchsorted(v, side=side, sorter=sorter)


@_implements(np.unique)
def _unique(ar, return_index=False, return_inverse=False,
            return_counts=False, axis=None, equal_nan=True, sorted=True):
    if return_index or return_inverse or axis is not None \
            or not equal_nan or not sorted:
        raise _Fallback("unique options")
    from bolt_tpu.ops import unique as bolt_unique
    return bolt_unique(ar, return_counts=return_counts)


# ---------------------------------------------------------------------
# shaping / elementwise
# ---------------------------------------------------------------------

@_implements(np.transpose)
def _transpose(a, axes=None):
    # bolt's key/value boundary applies: a reversal that crosses it
    # raises the method's loud ValueError (use swap), never a gather
    return a.transpose() if axes is None else a.transpose(*axes)


@_implements(np.reshape)
def _reshape(a, shape=None, order="C", newshape=None, copy=None):
    _require_default(copy=(copy, None))
    if order != "C":
        raise _Fallback("order")
    if shape is None:
        shape = newshape
    from bolt_tpu.utils import tupleize
    return a.reshape(*tupleize(shape))


@_implements(np.ravel)
def _ravel(a, order="C"):
    return a.ravel(order=order)


@_implements(np.squeeze)
def _squeeze(a, axis=None):
    return a.squeeze(axis=axis)


@_implements(np.swapaxes)
def _swapaxes(a, axis1, axis2):
    return a.swapaxes(axis1, axis2)


@_implements(np.count_nonzero)
def _count_nonzero(a, axis=None, keepdims=False):
    # (a != 0) is a deferred mask entry; the int cast (astype
    # canonicalises it) and the sum fuse with it into one program
    mask = (a != 0) if np.dtype(a.dtype) != np.bool_ else a
    return mask.astype(np.int64).sum(axis=_all_axes(a, axis),
                                     keepdims=_keepdims(keepdims))


@_implements(np.diff)
def _diff(a, n=1, axis=-1, prepend=_NV, append=_NV):
    _require_default(prepend=(prepend, _NV), append=(append, _NV))
    import operator
    n = operator.index(n)
    if n < 0:
        raise ValueError("order must be non-negative but got %d" % n)
    axis = axis + a.ndim if axis < 0 else axis
    from bolt_tpu.utils import inshape
    inshape(a.shape, (axis,))
    hi = tuple(slice(1, None) if i == axis else slice(None)
               for i in range(a.ndim))
    lo = tuple(slice(None, -1) if i == axis else slice(None)
               for i in range(a.ndim))
    boolean = np.dtype(a.dtype) == np.bool_
    out = a
    for _ in range(n):
        # two slices + one elementwise program per order; numpy's bool
        # diff is XOR (subtract rejects bool on both libraries)
        out = (out[hi] != out[lo]) if boolean else out[hi] - out[lo]
    return out


@_implements(np.flip)
def _flip(m, axis=None):
    from bolt_tpu.utils import inshape, tupleize
    if axis is None:
        axes = tuple(range(m.ndim))
    else:
        axes = tuple(a + m.ndim if a < 0 else a for a in tupleize(axis))
        if len(set(axes)) != len(axes):
            raise ValueError("repeated axis")
        inshape(m.shape, axes)
    sl = tuple(slice(None, None, -1) if i in axes else slice(None)
               for i in range(m.ndim))
    return m[sl]                 # one compiled reversed-slice program


@_implements(np.moveaxis)
def _moveaxis(a, source, destination):
    from bolt_tpu.utils import inshape, tupleize
    src = [s + a.ndim if s < 0 else s for s in tupleize(source)]
    dst = [d + a.ndim if d < 0 else d for d in tupleize(destination)]
    if len(src) != len(dst):
        raise ValueError(
            "`source` and `destination` arguments must have the same "
            "number of elements")
    if len(set(src)) != len(src) or len(set(dst)) != len(dst):
        raise ValueError(
            "repeated axis in `source` or `destination` argument")
    inshape(a.shape, src)       # out-of-range (incl. doubly-negative)
    inshape(a.shape, dst)       # raises instead of silently wrapping
    rest = [i for i in range(a.ndim) if i not in src]
    perm = [None] * a.ndim
    for s, d in zip(src, dst):
        perm[d] = s
    it = iter(rest)
    perm = [next(it) if p is None else p for p in perm]
    # bolt's key/value boundary applies, like np.transpose: a move that
    # crosses it raises the loud ValueError (use swap), never a gather
    return a.transpose(*perm)


@_implements(np.clip)
def _clip(a, a_min=_NV, a_max=_NV, out=None, min=_NV, max=_NV, **kw):
    _require_default(out=(out, None))
    if kw:
        raise _Fallback("clip kwargs")
    lo = a_min if a_min is not _NV else (min if min is not _NV else None)
    hi = a_max if a_max is not _NV else (max if max is not _NV else None)
    return a.clip(lo, hi)


@_implements(np.round)
def _round(a, decimals=0, out=None):
    _require_default(out=(out, None))
    return a.round(decimals)


@_implements(np.real)
def _real(val):
    return val.real


@_implements(np.imag)
def _imag(val):
    return val.imag


@_implements(np.diagonal)
def _diagonal(a, offset=0, axis1=0, axis2=1):
    return a.diagonal(offset, axis1, axis2)


@_implements(np.trace)
def _trace(a, offset=0, axis1=0, axis2=1, dtype=None, out=None):
    _require_default(out=(out, None))
    return a.trace(offset, axis1, axis2, dtype=dtype)


@_implements(np.concatenate)
def _concatenate(arrays, axis=0, out=None, dtype=None, casting="same_kind"):
    _require_default(out=(out, None), dtype=(dtype, None))
    seq = list(arrays)
    if not seq:
        raise ValueError("need at least one array to concatenate")
    first = seq[0]
    if not _is_tpu(first):
        raise _Fallback("first operand not on device")
    # ONE compiled program over all operands (axis=None ravels each,
    # like numpy) — not n−1 pairwise copies
    return first._concat_many(seq[1:], axis)


@_implements(np.dot)
def _dot(a, b, out=None):
    _require_default(out=(out, None))
    if not _is_tpu(a):
        raise _Fallback("first operand not on device")
    return a.dot(b)


@_implements(np.where)
def _where(condition, x=_NV, y=_NV):
    if (x is _NV) != (y is _NV):
        raise ValueError(
            "either both or neither of x and y should be given")
    if x is _NV:
        # 1-arg form IS nonzero
        if not _is_tpu(condition):
            raise _Fallback("condition not on device")
        return condition.nonzero()
    import jax
    import jax.numpy as jnp
    from bolt_tpu.tpu.array import BoltArrayTPU, _cached_jit, _constrain
    devs = [a for a in (condition, x, y) if _is_tpu(a)]
    if not devs:
        raise _Fallback("no device operand")
    # anchor on the MOST-split device operand: anchoring on a
    # replicated (split=0) condition would constrain the result
    # replicated and all-gather a sharded x/y
    b = max(devs, key=lambda a: a.split)
    ops = [b._coerce_operand(b._coerce_bolt_operand(a, "where"))
           for a in (condition, x, y)]
    out_shape = np.broadcast_shapes(*(np.shape(o) for o in ops))
    split = b.split
    # keys survive only when no broadcast axis displaced them: same
    # rank AND the leading dims still match b's key axes
    new_split = split if (len(out_shape) == b.ndim
                          and out_shape[:split] == b.shape[:split]) else 0
    mesh = b.mesh

    def build():
        def run(c, xx, yy):
            return _constrain(jnp.where(c, xx, yy), mesh, new_split)
        return jax.jit(run)

    fn = _cached_jit(("where",) + tuple(
        (np.shape(o), str(getattr(o, "dtype", type(o).__name__)))
        for o in ops) + (new_split, mesh), build)
    return BoltArrayTPU(fn(*ops), new_split, mesh)


@_implements(np.histogram)
def _histogram(a, bins=10, range=None, density=False, weights=None):
    _require_default(weights=(weights, None))
    if not isinstance(bins, (int, np.integer)):
        raise _Fallback("bin edges")        # array edges: host path
    from bolt_tpu.ops import histogram as bolt_histogram
    return bolt_histogram(a, bins=bins, range=range, density=density)


def _static_bins(bins, d):
    """Per-dimension static int bin counts, or None → host fallback
    (array edges are data-dependent shapes)."""
    if isinstance(bins, (int, np.integer)):
        return (int(bins),) * d
    try:
        seq = list(bins)
    except TypeError:
        return None
    if len(seq) != d or not all(isinstance(v, (int, np.integer))
                                for v in seq):
        return None
    return tuple(int(v) for v in seq)


def _static_ranges(range):
    """Normalized hashable per-dim (lo, hi) ranges; a per-dimension
    ``None`` entry (numpy-legal: use the data extrema) takes the host
    fallback rather than crashing the normalization."""
    if range is None:
        return None
    out = []
    for r in range:
        if r is None:
            raise _Fallback("per-dimension None range")
        out.append(tuple(float(v) for v in r))
    return tuple(out)


@_implements(np.histogram2d)
def _histogram2d(x, y, bins=10, range=None, density=None, weights=None):
    _require_default(weights=(weights, None))
    # numpy's eager contract, checked BEFORE tracing: mismatched lengths
    # must be ITS ValueError, not a jax concat TypeError, and >1-d
    # samples must not be silently flattened (ADVICE r4)
    if np.ndim(x) > 1 or np.ndim(y) > 1:
        raise _Fallback("non-1-d histogram2d samples")
    if np.size(x) != np.size(y):
        raise ValueError("x and y must have the same length.")
    bb = _static_bins(bins, 2)
    if bb is None:
        raise _Fallback("bin edges")
    anchor = _contraction_anchor(x, y)
    import jax.numpy as jnp
    rng_key = _static_ranges(range)

    def body(xx, yy):
        return tuple(jnp.histogram2d(xx.reshape(-1), yy.reshape(-1),
                                     bins=list(bb), range=rng_key,
                                     density=density))

    h, ex, ey = _device_fused("histogram2d", [x, y], anchor, (0, 0, 0),
                              body, (bb, rng_key, bool(density)))
    # numpy returns float64 everywhere here: counts/densities AND the
    # edge vectors (which would otherwise come back f32 under
    # production x64-off numerics — ADVICE r4)
    return (np.asarray(h.toarray()).astype(np.float64),
            np.asarray(ex.toarray()).astype(np.float64),
            np.asarray(ey.toarray()).astype(np.float64))


@_implements(np.histogramdd)
def _histogramdd(sample, bins=10, range=None, density=None,
                 weights=None):
    _require_default(weights=(weights, None))
    _require_tpu(sample)
    if sample.ndim != 2:
        raise _Fallback("non-(N, D) sample")   # sequence-of-arrays form
    d = sample.shape[1]
    bb = _static_bins(bins, d)
    if bb is None:
        raise _Fallback("bin edges")
    import jax.numpy as jnp
    rng_key = _static_ranges(range)

    def body(s):
        h, edges = jnp.histogramdd(s, bins=list(bb), range=rng_key,
                                   density=density)
        return (h,) + tuple(edges)

    outs = _device_fused("histogramdd", [sample], sample,
                         (0,) * (1 + d), body,
                         (bb, rng_key, bool(density)))
    # edges in float64 like the hist — numpy's dtype even under
    # production x64-off numerics (ADVICE r4)
    return (np.asarray(outs[0].toarray()).astype(np.float64),
            [np.asarray(e.toarray()).astype(np.float64)
             for e in outs[1:]])


@_implements(np.bincount)
def _bincount(a, weights=None, minlength=0):
    _require_default(weights=(weights, None))
    if a.ndim != 1:
        # numpy's exact rejection; ops.bincount flattens, which would
        # silently diverge from the local backend here
        raise ValueError("object too deep for desired array")
    from bolt_tpu.ops import bincount as bolt_bincount
    return bolt_bincount(a, minlength=minlength)


@_implements(np.split)
def _split_fn(ary, indices_or_sections, axis=0):
    return _do_split(ary, indices_or_sections, axis, strict=True)


@_implements(np.array_split)
def _array_split(ary, indices_or_sections, axis=0):
    return _do_split(ary, indices_or_sections, axis, strict=False)


def _do_split(ary, ios, axis, strict):
    """numpy split semantics as device-served basic slices (each piece
    is one compiled static-slice program through ``__getitem__``)."""
    import operator
    axis = int(axis)
    dim = ary.shape[axis]
    # numpy's own probe: sections-vs-indices is decided by len() — an
    # unsized value (plain int, 0-d array, even a float, which numpy
    # int()-coerces) is a SECTION COUNT; sized values are index lists
    # whose entries must be true integers (numpy's slices raise
    # TypeError for floats — operator.index mirrors that)
    try:
        nidx = len(ios)
    except TypeError:
        nidx = None
    if nidx is None:
        k = int(ios)              # numpy coerces float section counts
        if k <= 0:
            raise ValueError("number sections must be larger than 0.")
        if strict and dim % k != 0:
            raise ValueError(
                "array split does not result in an equal division")
        base, extra = divmod(dim, k)
        sizes = [base + 1] * extra + [base] * (k - extra)
        bounds = np.cumsum([0] + sizes)
    else:
        # raw indices: negative bounds wrap and oversized ones clamp
        # through ordinary slice semantics, exactly like numpy's
        # a[i:j] pieces (reversed pairs give empty pieces)
        bounds = [0] + [operator.index(i)
                        for i in np.asarray(ios).ravel().tolist()] + [dim]
    out = []
    for lo, hi in zip(bounds[:-1], bounds[1:]):
        sl = [slice(None)] * ary.ndim
        sl[axis] = slice(int(lo), int(hi))
        out.append(ary[tuple(sl)])
    return out


@_implements(np.shape)
def _shape(a):
    return a.shape


@_implements(np.ndim)
def _ndim(a):
    return a.ndim


@_implements(np.size)
def _size(a, axis=None):
    return a.size if axis is None else a.shape[axis]


# ---------------------------------------------------------------------
# fused multi-operand device programs (round 4, VERDICT r3 next-2):
# the stack family, layout expanders, and contractions below all build
# ONE compiled program over mixed bolt/host operands — deferred map
# chains on bolt operands fuse in, host operands upload once, the
# output carries a key-sharding constraint — instead of the warned
# whole-array host gather they used to take.
# ---------------------------------------------------------------------


def _device_fused(tag, operands, anchor, new_split, body, extra_key):
    """ONE compiled program over ``operands`` (bolt arrays fuse their
    deferred chains; anything else is device-coerced once), computing
    ``body(*mapped)`` with the result constrained to ``new_split``
    leading key axes on the anchor's mesh.  ``extra_key`` must carry
    every parameter ``body`` closes over — the executable cache is keyed
    on it plus the per-operand (shape, dtype, chain, split) tuples.

    ``new_split`` may be a TUPLE for a ``body`` returning that many
    outputs (decomposition-shaped ops): each output is constrained to
    its own split and the call returns a tuple of bolt arrays."""
    import jax
    from bolt_tpu.tpu.array import (BoltArrayTPU, _cached_jit, _chain_apply,
                                    _check_live, _constrain)
    from bolt_tpu.base import BoltArray
    mesh = anchor.mesh
    multi = isinstance(new_split, tuple)
    parts = []
    for op in operands:
        if isinstance(op, BoltArrayTPU):
            anchor._check_mesh(op, tag)
            base, funcs = op._chain_parts()
            parts.append((base, funcs, op.split))
        else:
            if isinstance(op, BoltArray):
                op = np.asarray(op)         # local backend: host block
            parts.append((anchor._coerce_operand(op), None, None))

    def build():
        def run(datas):
            mapped = [_chain_apply(f, s, d) if f is not None else d
                      for d, (_, f, s) in zip(datas, parts)]
            out = body(*mapped)
            if multi:
                return tuple(_constrain(o, mesh, s)
                             for o, s in zip(out, new_split))
            return _constrain(out, mesh, new_split)
        return jax.jit(run)

    key = (tag, mesh, new_split, extra_key,
           tuple((tuple(b.shape), str(b.dtype), f, s) for b, f, s in parts))
    out = _cached_jit(key, build)([_check_live(b) for b, _, _ in parts])
    if multi:
        return tuple(BoltArrayTPU(o, s, mesh)
                     for o, s in zip(out, new_split))
    return BoltArrayTPU(out, new_split, mesh)


def _require_tpu(a):
    if not _is_tpu(a):
        raise _Fallback("operand not on device")
    return a


def _aval_of(x):
    import jax
    dt = getattr(x, "dtype", None)
    return jax.ShapeDtypeStruct(np.shape(x),
                                np.dtype(dt) if dt is not None
                                else np.result_type(x))


# ---------------------------------------------------------------------
# layout expanders
# ---------------------------------------------------------------------

def _expand_device(a, axes):
    """Shared size-1-axis inserter (``expand_dims`` and the
    ``atleast_*`` family): an axis inserted before the last key axis
    joins the keys, one inserted at or past the key/value boundary
    joins the values (the cheap side — no resharding)."""
    import jax.numpy as jnp
    out_ndim = a.ndim + len(axes)
    norm = []
    for ax in axes:
        nx = ax + out_ndim if ax < 0 else ax
        if not 0 <= nx < out_ndim:
            raise np.exceptions.AxisError(ax, out_ndim)
        norm.append(nx)
    if len(set(norm)) != len(norm):
        raise ValueError("repeated axis in `axis` argument")
    ins = set(norm)
    shape, new_split, nxt = [], 0, iter(range(a.ndim))
    for p in range(out_ndim):
        if p in ins:
            shape.append(1)
        else:
            i = next(nxt)
            shape.append(a.shape[i])
            if i == a.split - 1:
                new_split = p + 1
    shape = tuple(shape)
    return _device_fused("expand_dims", [a], a, new_split,
                         lambda d: jnp.reshape(d, shape), (shape,))


@_implements(np.expand_dims)
def _expand_dims(a, axis):
    _require_tpu(a)
    from bolt_tpu.utils import tupleize
    return _expand_device(a, tupleize(axis))


def _one_atleast(a, n):
    if not _is_tpu(a):
        return getattr(np, "atleast_%dd" % n)(np.asarray(a))
    if a.ndim >= n:
        return a
    # numpy's placement: atleast_2d prepends; atleast_3d gives a 1-d
    # array (1, n, 1) and a 2-d one a trailing axis
    missing = n - a.ndim
    if n == 3 and a.ndim == 2:
        axes = (2,)
    elif n == 3 and a.ndim == 1:
        axes = (0, 2)
    else:
        axes = tuple(range(missing))
    return _expand_device(a, axes)


@_implements(np.atleast_1d)
def _atleast_1d(*arys):
    res = [_one_atleast(a, 1) for a in arys]
    return res[0] if len(res) == 1 else res


@_implements(np.atleast_2d)
def _atleast_2d(*arys):
    res = [_one_atleast(a, 2) for a in arys]
    return res[0] if len(res) == 1 else res


@_implements(np.atleast_3d)
def _atleast_3d(*arys):
    res = [_one_atleast(a, 3) for a in arys]
    return res[0] if len(res) == 1 else res


@_implements(np.broadcast_to)
def _broadcast_to(array, shape, subok=False):
    _require_tpu(array)
    import jax.numpy as jnp
    from bolt_tpu.utils import tupleize
    shape = tuple(int(s) for s in tupleize(shape))
    try:
        out = np.broadcast_shapes(tuple(array.shape), shape)
    except ValueError:
        raise ValueError(
            "cannot broadcast shape %s to %s"
            % (str(tuple(array.shape)), str(shape))) from None
    if out != shape:
        # broadcast_to is one-directional: the target must BE the result
        raise ValueError(
            "cannot broadcast shape %s to %s"
            % (str(tuple(array.shape)), str(shape)))
    # prepended broadcast axes become leading key axes (keys lead by
    # bolt's model; the constraint reshards over them)
    new_split = array.split + (len(shape) - array.ndim) if array.split else 0
    return _device_fused("broadcast_to", [array], array, new_split,
                         lambda d: jnp.broadcast_to(d, shape), (shape,))


@_implements(np.tile)
def _tile(A, reps):
    _require_tpu(A)
    import jax.numpy as jnp
    from bolt_tpu.utils import tupleize
    rep_t = tuple(max(operator.index(r), 0) for r in tupleize(reps))
    # reps longer than ndim prepends axes; they lead, so they join keys
    new_split = A.split + max(0, len(rep_t) - A.ndim) if A.split else 0
    return _device_fused("tile", [A], A, new_split,
                         lambda d: jnp.tile(d, rep_t), (rep_t,))


@_implements(np.roll)
def _roll(a, shift, axis=None):
    _require_tpu(a)
    import jax.numpy as jnp
    from bolt_tpu.utils import tupleize
    sh_t = tuple(operator.index(s) for s in tupleize(shift))
    if axis is not None:
        ax_t = tuple(operator.index(x) for x in tupleize(axis))
        for x in ax_t:
            if not -a.ndim <= x < a.ndim:
                raise np.exceptions.AxisError(x, a.ndim)
    # an empty shift or axis tuple broadcasts to zero rolls: numpy
    # returns the array unchanged (as a copy)
    if len(sh_t) == 0 or (axis is not None and len(ax_t) == 0):
        return a._clone()
    if axis is None:
        if len(sh_t) != 1:
            raise _Fallback("vector shift with axis=None")
        ax_arg = None
        sh_arg = sh_t[0]
    else:
        if len(sh_t) != len(ax_t) and len(sh_t) != 1 and len(ax_t) != 1:
            raise ValueError(
                "'shift' and 'axis' should be scalars or 1D sequences")
        ax_arg = ax_t if len(ax_t) > 1 or len(sh_t) > 1 else ax_t[0]
        sh_arg = sh_t if len(sh_t) > 1 or len(ax_t) > 1 else sh_t[0]
    return _device_fused("roll", [a], a, a.split,
                         lambda d: jnp.roll(d, sh_arg, ax_arg),
                         (sh_arg, ax_arg))


@_implements(np.rot90)
def _rot90(m, k=1, axes=(0, 1)):
    _require_tpu(m)
    import jax.numpy as jnp
    axes = tuple(axes)
    if len(axes) != 2:
        raise ValueError("len(axes) must be 2.")
    a0 = axes[0] + m.ndim if axes[0] < 0 else axes[0]
    a1 = axes[1] + m.ndim if axes[1] < 0 else axes[1]
    if not (0 <= a0 < m.ndim and 0 <= a1 < m.ndim):
        raise ValueError("Axes=%s out of range for array of ndim=%d."
                         % (str(axes), m.ndim))
    if a0 == a1:
        raise ValueError("Axes must be different.")
    k = operator.index(k) % 4
    split = m.split
    if k % 2 and (a0 < split) != (a1 < split):
        # odd rotations transpose the two axes — same boundary rule as
        # transpose/moveaxis: never silently cross keys/values
        raise ValueError(
            "rot90 may not move axes between keys and values; use swap "
            "(key axes: %s)" % str(tuple(range(split))))
    if k == 0:
        return m._clone()
    return _device_fused("rot90", [m], m, split,
                         lambda d: jnp.rot90(d, k=k, axes=(a0, a1)),
                         (k, a0, a1))


@_implements(np.pad)
def _pad(array, pad_width, mode="constant", **kwargs):
    _require_tpu(array)
    import jax.numpy as jnp
    allowed = {"constant": ("constant_values",), "edge": (),
               "reflect": ("reflect_type",), "symmetric": ("reflect_type",),
               "wrap": ()}
    if callable(mode) or mode not in allowed:
        raise _Fallback("mode")           # stat/ramp/callable: host path
    unsupported = set(kwargs) - set(allowed[mode])
    if unsupported:
        raise ValueError("unsupported keyword arguments for mode '%s': %s"
                         % (mode, unsupported))
    pw = np.asarray(pad_width)
    if not np.issubdtype(pw.dtype, np.integer):
        raise TypeError("`pad_width` must be of integral type.")
    try:
        pairs = tuple(tuple(int(v) for v in row)
                      for row in np.broadcast_to(pw, (array.ndim, 2)))
    except ValueError:
        raise ValueError(
            "operands could not be broadcast together with shapes %s (%d, 2)"
            % (str(pw.shape), array.ndim)) from None
    if any(v < 0 for row in pairs for v in row):
        raise ValueError("index can't contain negative values")
    if mode == "constant":
        cv = kwargs.get("constant_values", 0)
        cv_key = tuple(map(tuple, np.broadcast_to(
            np.asarray(cv), (array.ndim, 2)).tolist()))
        kw, kw_key = {"constant_values": cv}, ("cv", cv_key)
    elif mode in ("reflect", "symmetric"):
        rt = kwargs.get("reflect_type", "even")
        if rt not in ("even", "odd"):
            raise ValueError("unsupported reflect_type '%s'" % (rt,))
        kw, kw_key = {"reflect_type": rt}, ("rt", rt)
    else:
        kw, kw_key = {}, ()
    return _device_fused("pad", [array], array, array.split,
                         lambda d: jnp.pad(d, pairs, mode=mode, **kw),
                         (pairs, mode, kw_key))


# ---------------------------------------------------------------------
# the stack family
# ---------------------------------------------------------------------

@_implements(np.stack)
def _stack(arrays, axis=0, out=None, dtype=None, casting="same_kind"):
    _require_default(out=(out, None), dtype=(dtype, None))
    if casting != "same_kind":
        raise _Fallback("casting")     # host path keeps numpy's TypeError
    import jax.numpy as jnp
    seq = list(arrays)
    if not seq:
        raise ValueError("need at least one array to stack")
    if not _is_tpu(seq[0]):
        raise _Fallback("first operand not on device")
    a = seq[0]
    if len({np.shape(s) for s in seq}) != 1:
        raise ValueError("all input arrays must have the same shape")
    out_ndim = a.ndim + 1
    ax = axis + out_ndim if axis < 0 else axis
    if not 0 <= ax < out_ndim:
        raise np.exceptions.AxisError(axis, out_ndim)
    # the new axis joins whichever group it lands in
    new_split = a.split + 1 if ax < a.split else a.split
    return _device_fused("stack", seq, a, new_split,
                         lambda *ds: jnp.stack(ds, axis=ax), (ax,))


def _stack_like(tag, tup, concat_axis, target_shape):
    """vstack/hstack/column_stack/dstack: per-operand reshape (decided
    eagerly from the host-known shapes) then ONE concatenate program.
    ``target_shape(shape) -> tuple | None`` (None = pass through)."""
    import jax.numpy as jnp
    seq = list(tup)
    if not seq:
        raise ValueError("need at least one array to concatenate")
    if not _is_tpu(seq[0]):
        raise _Fallback("first operand not on device")
    a = seq[0]
    targets = [target_shape(np.shape(s)) for s in seq]
    eff0 = targets[0] if targets[0] is not None else tuple(a.shape)
    effs = [t if t is not None else np.shape(s)
            for t, s in zip(targets, seq)]
    ax = concat_axis(effs)
    # numpy-exact cross-operand validation (a shape clash must be the
    # documented ValueError, not a jax TypeError at trace time)
    for i, e in enumerate(effs[1:], 1):
        if len(e) != len(effs[0]):
            raise ValueError(
                "all the input arrays must have same number of dimensions, "
                "but the array at index 0 has %d dimension(s) and the array "
                "at index %d has %d dimension(s)"
                % (len(effs[0]), i, len(e)))
        for d in range(len(effs[0])):
            if d != ax and e[d] != effs[0][d]:
                raise ValueError(
                    "all the input array dimensions except for the "
                    "concatenation axis must match exactly, but along "
                    "dimension %d, the array at index 0 has size %d and the "
                    "array at index %d has size %d"
                    % (d, effs[0][d], i, e[d]))
    # an anchor reshaped up to 2-d/3-d keys its leading axis; one passed
    # through keeps its own split
    new_split = a.split if targets[0] is None else (
        1 if len(eff0) >= 2 else a.split)

    def body(*ds):
        parts = [d if t is None else jnp.reshape(d, t)
                 for d, t in zip(ds, targets)]
        return jnp.concatenate(parts, axis=ax)

    return _device_fused(tag, seq, a, new_split, body,
                         (ax, tuple(targets)))


@_implements(np.vstack)
def _vstack(tup, *, dtype=None, casting="same_kind"):
    _require_default(dtype=(dtype, None))
    if casting != "same_kind":
        raise _Fallback("casting")

    def target(sh):
        if len(sh) == 0:
            return (1, 1)
        if len(sh) == 1:
            return (1, sh[0])
        return None

    return _stack_like("vstack", tup, lambda effs: 0, target)


@_implements(np.hstack)
def _hstack(tup, *, dtype=None, casting="same_kind"):
    _require_default(dtype=(dtype, None))
    if casting != "same_kind":
        raise _Fallback("casting")

    def target(sh):
        return (1,) if len(sh) == 0 else None

    # numpy decides the axis from the FIRST array alone (its error
    # message for mixed 1-d/2-d operands depends on it — ADVICE r4)
    return _stack_like(
        "hstack", tup,
        lambda effs: 0 if len(effs[0]) == 1 else 1, target)


@_implements(np.column_stack)
def _column_stack(tup):
    def target(sh):
        if len(sh) == 0:
            return (1, 1)
        if len(sh) == 1:
            return (sh[0], 1)
        return None

    return _stack_like("column_stack", tup, lambda effs: 1, target)


@_implements(np.dstack)
def _dstack(tup):
    def target(sh):
        if len(sh) == 0:
            return (1, 1, 1)
        if len(sh) == 1:
            return (1, sh[0], 1)
        if len(sh) == 2:
            return sh + (1,)
        return None

    return _stack_like("dstack", tup, lambda effs: 2, target)


@_implements(np.append)
def _append(arr, values, axis=None):
    _require_tpu(arr)
    # numpy: axis=None ravels both operands; _concat_many does exactly
    # that in one program
    return arr._concat_many([values], axis)


# ---------------------------------------------------------------------
# contractions (MXU path — same "highest" precision policy as `dot`)
# ---------------------------------------------------------------------

def _expand_einsum_ellipsis(subs, shapes):
    """Rewrite ``...`` into explicit (upper-case, unused) labels with
    numpy's semantics: per-operand ellipsis dims align RIGHT against
    the widest, and in implicit mode the broadcast labels lead the
    output.  Returns an explicit ``in->out`` string."""
    ins, arrow, out = subs.partition("->")
    terms = ins.split(",")
    if len(terms) != len(shapes):
        raise _Fallback("operand count mismatch")
    widths = []
    for t, sh in zip(terms, shapes):
        if "..." in t:
            if t.count("...") > 1:
                raise _Fallback("multiple ellipses in one term")
            k = len(sh) - (len(t) - 3)
            if k < 0:
                raise _Fallback("ellipsis width")   # host raises exactly
            widths.append(k)
        else:
            widths.append(0)
    bmax = max(widths) if widths else 0
    used = set(subs)
    pool = [c for c in "ABCDEFGHIJKLMNOPQRSTUVWXYZ" if c not in used]
    if len(pool) < bmax:
        raise _Fallback("too many broadcast dims")
    ell = "".join(pool[:bmax])
    new_terms = [t.replace("...", ell[bmax - w:]) if "..." in t else t
                 for t, w in zip(terms, widths)]
    if arrow and "..." not in out and bmax > 0:
        # numpy: an explicit output (even an EMPTY one) must carry
        # '...' when broadcast dims exist — the host path raises its
        # exact error
        raise _Fallback("output missing ellipsis")
    if out:
        new_out = out.replace("...", ell)
    elif arrow:
        new_out = ell                       # explicit empty output
    else:
        from collections import Counter
        cnt = Counter(c for t in new_terms for c in t if c not in ell)
        new_out = ell + "".join(sorted(c for c in cnt if cnt[c] == 1))
    return ",".join(new_terms) + "->" + new_out


def _contraction_anchor(*ops):
    anchor = None
    for o in ops:
        if _is_tpu(o) and (anchor is None or o.split > anchor.split):
            anchor = o
    if anchor is None:
        raise _Fallback("no device operand")
    return anchor


@_implements(np.einsum)
def _einsum(*operands, out=None, optimize=False, **kwargs):
    _require_default(out=(out, None), dtype=(kwargs.pop("dtype", None), None))
    if kwargs.pop("order", "K") not in ("K", "C"):
        raise _Fallback("order")
    if kwargs.pop("casting", "safe") != "safe":
        raise _Fallback("casting")
    if kwargs:
        raise _Fallback("einsum kwargs")
    if not operands or not isinstance(operands[0], str):
        raise _Fallback("interleaved einsum form")
    import jax
    import jax.numpy as jnp
    subs = operands[0].replace(" ", "")
    ops = list(operands[1:])
    if "..." in subs:
        subs = _expand_einsum_ellipsis(subs, [np.shape(o) for o in ops])
    anchor = _contraction_anchor(*ops)
    ins = subs.split("->")[0]
    terms = ins.split(",")
    if len(terms) != len(ops):
        raise _Fallback("operand count mismatch")   # host raises exactly
    try:
        out_aval = jax.eval_shape(
            lambda *xs: jnp.einsum(subs, *xs), *[_aval_of(o) for o in ops])
    except TypeError as e:
        raise ValueError(str(e)) from None
    if "->" in subs:
        outl = subs.split("->")[1]
    else:
        from collections import Counter
        cnt = Counter(c for c in ins if c != ",")
        outl = "".join(sorted(c for c in cnt if cnt[c] == 1))
    aidx = next(i for i, o in enumerate(ops) if o is anchor)
    term, split = terms[aidx], anchor.split
    # keys survive when the anchor's key labels still lead the output,
    # are not diagonalised within the anchor, and keep their sizes
    new_split = split if (
        len(term) == anchor.ndim
        and len(set(term[:split])) == split
        and outl[:split] == term[:split]
        and tuple(out_aval.shape[:split]) == tuple(anchor.shape[:split])
    ) else 0
    from bolt_tpu._precision import resolve
    pr = resolve()
    return _device_fused(
        "einsum", ops, anchor, new_split,
        lambda *ds: jnp.einsum(subs, *ds, precision=pr), (subs, pr))


@_implements(np.tensordot)
def _tensordot(a, b, axes=2):
    import jax
    import jax.numpy as jnp
    from bolt_tpu.utils import tupleize
    anchor = _contraction_anchor(a, b)
    try:
        k = operator.index(axes)
        ax_a = tuple(range(np.ndim(a) - k, np.ndim(a)))
        ax_b = tuple(range(k))
    except TypeError:
        axes_a, axes_b = axes
        ax_a = tuple(operator.index(x) for x in tupleize(axes_a))
        ax_b = tuple(operator.index(x) for x in tupleize(axes_b))
    try:
        out_aval = jax.eval_shape(
            lambda x, y: jnp.tensordot(x, y, (ax_a, ax_b)),
            _aval_of(a), _aval_of(b))
    except TypeError as e:
        raise ValueError(str(e)) from None
    new_split = 0
    if anchor is a:
        pa = tuple(x + a.ndim if x < 0 else x for x in ax_a)
        if all(x >= a.split for x in pa) and \
                tuple(out_aval.shape[:a.split]) == tuple(a.shape[:a.split]):
            new_split = a.split
    from bolt_tpu._precision import resolve
    pr = resolve()
    return _device_fused(
        "tensordot", [a, b], anchor, new_split,
        lambda x, y: jnp.tensordot(x, y, (ax_a, ax_b),
                                   precision=pr), (ax_a, ax_b, pr))


@_implements(np.inner)
def _inner(a, b):
    import jax
    import jax.numpy as jnp
    anchor = _contraction_anchor(a, b)
    try:
        out_aval = jax.eval_shape(lambda x, y: jnp.inner(x, y),
                                  _aval_of(a), _aval_of(b))
    except TypeError as e:
        raise ValueError(str(e)) from None
    new_split = 0
    if anchor is a:
        cap = min(a.split, max(a.ndim - 1, 0))
        if tuple(out_aval.shape[:cap]) == tuple(a.shape[:cap]):
            new_split = cap
    from bolt_tpu._precision import resolve
    pr = resolve()
    return _device_fused(
        "inner", [a, b], anchor, new_split,
        lambda x, y: jnp.inner(x, y, precision=pr), (pr,))


@_implements(np.outer)
def _outer(a, b, out=None):
    _require_default(out=(out, None))
    import jax.numpy as jnp
    anchor = _contraction_anchor(a, b)
    new_split = 1 if (anchor is a and a.split >= 1) else 0
    return _device_fused("outer", [a, b], anchor, new_split,
                         lambda x, y: jnp.outer(x, y), ())


# ---------------------------------------------------------------------
# statistics over samples x features (route to ops.linalg's one-pass
# sharded Gram programs)
# ---------------------------------------------------------------------

@_implements(np.cov)
def _cov(m, y=None, rowvar=True, bias=False, ddof=None, fweights=None,
         aweights=None, *, dtype=None):
    _require_default(y=(y, None), fweights=(fweights, None),
                     aweights=(aweights, None), dtype=(dtype, None))
    _require_tpu(m)
    if m.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    if m.ndim == 0:
        raise _Fallback("0-d")             # numpy warns and returns nan
    if ddof is not None and ddof != int(ddof):
        raise ValueError("ddof must be integer")
    ddof = (0 if bias else 1) if ddof is None else int(ddof)
    sample_axis = 0 if (m.ndim == 1 or not rowvar) else 1
    if m.shape[sample_axis] - ddof <= 0:
        raise _Fallback("non-positive dof")  # host path keeps the warning
    from bolt_tpu.ops import cov as bolt_cov
    c = bolt_cov(m, axis=(sample_axis,), ddof=ddof)
    return c.reshape(()) if m.ndim == 1 else c


@_implements(np.corrcoef)
def _corrcoef(x, y=None, rowvar=True, bias=_NV, ddof=_NV, *, dtype=None):
    # bias/ddof are accepted-and-ignored, exactly like numpy (deprecated
    # no-ops there)
    _require_default(y=(y, None), dtype=(dtype, None))
    _require_tpu(x)
    if x.ndim > 2:
        raise ValueError("m has more than 2 dimensions")
    if x.ndim == 0:
        raise _Fallback("0-d")
    sample_axis = 0 if (x.ndim == 1 or not rowvar) else 1
    if x.shape[sample_axis] < 2:
        raise _Fallback("too few samples")   # host path keeps the warning
    from bolt_tpu.ops import corrcoef as bolt_corrcoef
    r = bolt_corrcoef(x, axis=(sample_axis,))
    # numpy clips the real and imaginary parts into [-1, 1] separately
    if np.iscomplexobj(r):
        r = np.clip(r.real, -1, 1) + 1j * np.clip(r.imag, -1, 1)
    else:
        r = np.clip(r, -1, 1)
    return r.reshape(()) if x.ndim == 1 else r


@_implements(np.copy)
def _copy(a, order="K", subok=False):
    if order not in ("K", "C"):
        raise _Fallback("order")
    return a._clone()


# ---------------------------------------------------------------------
# nan-aware reductions, norms, and sampling helpers (round 4, batch 2)
# ---------------------------------------------------------------------

def _axis_reduced_split(a, axes, keepdims):
    """The canonical reduction split rule (``BoltArrayTPU._stat``):
    ``keepdims`` keeps every key axis; otherwise reduced key axes drop
    and the survivors stay leading.  ``axes`` must already be
    normalized to non-negative ints (``_all_axes`` output)."""
    if keepdims:
        return a.split
    norm = {ax + a.ndim if ax < 0 else ax
            for ax in (axes if isinstance(axes, (tuple, list, set))
                       else (axes,))}
    return a.split - sum(1 for i in range(a.split) if i in norm)


def _nan_reduce_common(name, a, axis, dtype, out, keepdims, ddof, kw):
    _require_default(out=(out, None), dtype=(dtype, None),
                     initial=(kw.pop("initial", _NV), _NV),
                     where=(kw.pop("where", _NV), _NV),
                     mean=(kw.pop("mean", _NV), _NV))
    correction = kw.pop("correction", _NV)
    if kw:
        raise _Fallback("%s kwargs" % name)
    if correction is not _NV:
        if ddof != 0:
            raise ValueError("can't specify both correction and ddof")
        ddof = correction
    _require_tpu(a)
    import jax.numpy as jnp
    jfn = getattr(jnp, name)
    ax = _all_axes(a, axis)
    kd = _keepdims(keepdims)
    args = {"axis": ax, "keepdims": kd}
    if name in ("nanvar", "nanstd"):
        args["ddof"] = ddof
    return _device_fused(name, [a], a, _axis_reduced_split(a, ax, kd),
                         lambda d: jfn(d, **args), (ax, kd, ddof))


def _nan_reduction(name):
    # numpy's positional order puts keepdims 5th for the plain
    # reductions but ddof 5th for nanvar/nanstd — the signatures must
    # match or a positional ddof would silently bind to keepdims
    if name in ("nanvar", "nanstd"):
        def handler(a, axis=None, dtype=None, out=None, ddof=0,
                    keepdims=_NV, **kw):
            return _nan_reduce_common(name, a, axis, dtype, out,
                                      keepdims, ddof, kw)
    else:
        def handler(a, axis=None, dtype=None, out=None, keepdims=_NV,
                    **kw):
            return _nan_reduce_common(name, a, axis, dtype, out,
                                      keepdims, 0, kw)
    return handler


for _name in ("nansum", "nanprod", "nanmean", "nanvar", "nanstd",
              "nanmin", "nanmax"):
    _TABLE[getattr(np, _name)] = _nan_reduction(_name)


@_implements(np.nanmedian)
def _nanmedian(a, axis=None, out=None, overwrite_input=False,
               keepdims=_NV):
    _require_default(out=(out, None))
    _require_tpu(a)
    import jax.numpy as jnp
    ax, kd = _all_axes(a, axis), _keepdims(keepdims)

    def body(d):
        xf = d.astype(jnp.promote_types(d.dtype, jnp.float32))
        return jnp.nanmedian(xf, axis=ax, keepdims=kd)

    return _device_fused("nanmedian", [a], a,
                         _axis_reduced_split(a, ax, kd), body, (ax, kd))


@_implements(np.nanquantile)
def _nanquantile(a, q, axis=None, out=None, overwrite_input=False,
                 method="linear", keepdims=_NV, weights=None,
                 interpolation=None):
    _require_default(out=(out, None), weights=(weights, None),
                     interpolation=(interpolation, None))
    if method not in ("linear", "lower", "higher", "midpoint", "nearest"):
        raise _Fallback("method")
    _require_tpu(a)
    import jax.numpy as jnp
    from bolt_tpu.utils import check_q
    qarr = check_q(q)                      # shared scalar/1-d contract
    scalar_q = qarr.ndim == 0
    ax, kd = _all_axes(a, axis), _keepdims(keepdims)

    def body(d, qv):
        # same promotion as BoltArrayTPU.quantile: integer data widens,
        # q is cast to the promoted FLOAT dtype (int data used to crash
        # the trace); q arrives as a traced OPERAND, so sweeping many
        # quantiles reuses one executable per q-shape, like the method
        xf = d.astype(jnp.promote_types(d.dtype, jnp.float32))
        return jnp.nanquantile(xf, qv.astype(xf.dtype), axis=ax,
                               method=method, keepdims=kd)

    # vector q prepends a flat KEY axis — the quantile-method
    # convention — ahead of the surviving key axes
    new_split = _axis_reduced_split(a, ax, kd) + (0 if scalar_q else 1)
    return _device_fused("nanquantile", [a, np.asarray(qarr, np.float64)],
                         a, new_split, body, (ax, kd, method))


@_implements(np.linalg.norm)
def _linalg_norm(x, ord=None, axis=None, keepdims=False):
    _require_tpu(x)
    import jax.numpy as jnp
    from bolt_tpu.utils import tupleize
    ax = None if axis is None else tuple(
        int(v) for v in tupleize(axis))
    if ax is not None and len(ax) == 1:
        ax = ax[0]
    kd = bool(keepdims)
    reduced = tuple(range(x.ndim)) if ax is None else (
        (ax,) if np.isscalar(ax) else ax)
    return _device_fused(
        "linalg_norm", [x], x, _axis_reduced_split(x, reduced, kd),
        lambda d: jnp.linalg.norm(d, ord=ord, axis=ax, keepdims=kd),
        (str(ord), ax, kd))


@_implements(np.average)
def _average(a, axis=None, weights=None, returned=False, *,
             keepdims=_NV):
    _require_tpu(a)
    import jax.numpy as jnp
    ax = _all_axes(a, axis)
    kd = _keepdims(keepdims)
    if weights is None:
        avg = a.mean(axis=ax, keepdims=kd)
        if not returned:
            return avg
        n = 1
        for i in (range(a.ndim) if axis is None else
                  [axis] if np.isscalar(axis) else axis):
            n *= a.shape[i]
        # numpy returns the sum of weights broadcast to the result shape
        scl = np.broadcast_to(np.asarray(float(n), avg.dtype),
                              avg.shape).copy()
        return avg, scl
    if _is_tpu(weights):
        raise _Fallback("bolt weights")    # host path handles mixed
    w = np.asarray(weights)
    if w.shape == tuple(a.shape):
        wb = w
    elif w.ndim == 1 and axis is not None and np.isscalar(axis):
        axn = axis + a.ndim if axis < 0 else axis
        if w.shape[0] != a.shape[axn]:
            raise ValueError(
                "Length of weights not compatible with specified axis.")
        shape = [1] * a.ndim
        shape[axn] = w.shape[0]
        wb = w.reshape(shape)
    else:
        raise _Fallback("weights shape")
    scl_full = np.broadcast_to(wb, tuple(a.shape)).sum(axis=None if
                                                       axis is None else ax,
                                                       keepdims=kd)
    if np.any(scl_full == 0):
        raise ZeroDivisionError(
            "Weights sum to zero, can't be normalized")

    def body(d, wj):
        num = jnp.sum(d * wj, axis=ax, keepdims=kd)
        den = jnp.sum(jnp.broadcast_to(wj, d.shape), axis=ax,
                      keepdims=kd)
        return num / den

    avg = _device_fused("average", [a, wb], a,
                        _axis_reduced_split(a, ax, kd), body,
                        (ax, kd, wb.shape))
    if not returned:
        return avg
    scl = np.broadcast_to(np.asarray(scl_full, avg.dtype),
                          avg.shape).copy()
    return avg, scl


@_implements(np.isin)
def _isin(element, test_elements, assume_unique=False, invert=False, *,
          kind=None):
    _require_default(kind=(kind, None))
    _require_tpu(element)
    import jax.numpy as jnp
    if _is_tpu(test_elements):
        test_elements = test_elements.tojax()
    te = np.asarray(test_elements) if not hasattr(
        test_elements, "dtype") else test_elements
    return _device_fused(
        "isin", [element, te], element, element.split,
        lambda d, t: jnp.isin(d, t, assume_unique=assume_unique,
                              invert=invert),
        (bool(assume_unique), bool(invert)))


@_implements(np.digitize)
def _digitize(x, bins, right=False):
    _require_tpu(x)
    import jax.numpy as jnp
    b = np.asarray(bins)
    if b.ndim != 1:
        raise ValueError("object too deep for desired array")
    d = np.diff(b)
    # numpy's rule is NON-strict monotonicity (equal consecutive edges
    # are legal)
    if len(b) > 1 and not (np.all(d >= 0) or np.all(d <= 0)):
        raise ValueError(
            "bins must be monotonically increasing or decreasing")
    return _device_fused(
        "digitize", [x, b], x, x.split,
        lambda d, bb: jnp.digitize(d, bb, right=bool(right)),
        (bool(right),))


@_implements(np.interp)
def _interp(x, xp, fp, left=None, right=None, period=None):
    _require_tpu(x)
    import jax.numpy as jnp
    if _is_tpu(xp) or _is_tpu(fp):
        raise _Fallback("bolt sample points")
    xpa, fpa = np.asarray(xp), np.asarray(fp)
    if xpa.ndim != 1 or fpa.ndim != 1:
        raise ValueError("Data points must be 1-D sequences")
    if len(xpa) != len(fpa):
        raise ValueError("fp and xp are not of the same length")
    if len(xpa) == 0:
        raise ValueError("array of sample points is empty")
    if period is not None and period == 0:
        raise ValueError("period must be a non-zero value")
    return _device_fused(
        "interp", [x, xpa, fpa], x, x.split,
        lambda d, xx, ff: jnp.interp(d, xx, ff, left=left, right=right,
                                     period=period),
        (left, right, period))


@_implements(np.gradient)
def _gradient(f, *varargs, axis=None, edge_order=1):
    _require_tpu(f)
    if edge_order != 1:
        raise _Fallback("edge_order")
    import jax.numpy as jnp
    from bolt_tpu.utils import tupleize, inshape
    if axis is None:
        axes = tuple(range(f.ndim))
    else:
        axes = tuple(a + f.ndim if a < 0 else a for a in tupleize(axis))
        inshape(f.shape, axes)
    if len(varargs) == 0:
        spacing = [1.0] * len(axes)
    elif len(varargs) == 1 and np.ndim(varargs[0]) == 0:
        spacing = [float(varargs[0])] * len(axes)
    elif len(varargs) == len(axes) and all(
            np.ndim(v) == 0 for v in varargs):
        spacing = [float(v) for v in varargs]
    else:
        raise _Fallback("array spacing")   # coordinate arrays: host path
    for a in axes:
        if f.shape[a] < 2:
            raise ValueError(
                "Shape of array too small to calculate a numerical "
                "gradient, at least 2 elements are required.")
    if len(axes) > 1 and f.deferred:
        # one program per axis below: materialise a deferred chain ONCE
        # so N gradients don't re-run it N times
        f._data
    outs = [
        _device_fused("gradient", [f], f, f.split,
                      lambda d, _a=a, _h=h: jnp.gradient(d, _h, axis=_a),
                      (a, float(h)))
        for a, h in zip(axes, spacing)]
    return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------
# round-4 batch 8: flips, integration, nan-aware cumulatives/arg stats
# ---------------------------------------------------------------------

@_implements(np.flipud)
def _flipud(m):
    if m.ndim < 1:
        raise ValueError("Input must be >= 1-d.")
    return _flip(m, 0)


@_implements(np.fliplr)
def _fliplr(m):
    if m.ndim < 2:
        raise ValueError("Input must be >= 2-d.")
    return _flip(m, 1)


def _trapezoid(y, x=None, dx=1.0, axis=-1):
    _require_tpu(y)
    import jax.numpy as jnp
    ax = operator.index(axis)
    if x is None:
        return _device_fused(
            "trapezoid", [y], y,
            _axis_reduced_split(y, (ax + y.ndim if ax < 0 else ax,),
                                False),
            lambda d: jnp.trapezoid(d, dx=float(dx), axis=ax),
            (float(dx), ax))
    if _is_tpu(x):
        raise _Fallback("device sample points")
    xa = np.asarray(x)
    return _device_fused(
        "trapezoid_x", [y, xa], y,
        _axis_reduced_split(y, (ax + y.ndim if ax < 0 else ax,), False),
        lambda d, xx: jnp.trapezoid(d, xx, axis=ax), (ax, xa.shape))


# numpy <2.0 has only trapz, >=2.0 both (trapz deprecated): guard EACH
if hasattr(np, "trapezoid"):
    _TABLE[np.trapezoid] = _trapezoid
if hasattr(np, "trapz"):
    _TABLE[np.trapz] = _trapezoid


@_implements(np.cross)
def _cross(a, b, axisa=-1, axisb=-1, axisc=-1, axis=None):
    anchor = _contraction_anchor(a, b)
    import jax
    import jax.numpy as jnp
    if axis is not None or (axisa, axisb, axisc) != (-1, -1, -1):
        # moved vector axes reshuffle the output layout out from under
        # the leading-keys bookkeeping: host path
        raise _Fallback("non-default cross axes")
    try:
        out_aval = jax.eval_shape(lambda u, v: jnp.cross(u, v),
                                  _aval_of(a), _aval_of(b))
    except Exception:
        raise _Fallback("cross form")   # e.g. numpy's deprecated 2x3 mix
    s = anchor.split
    new_split = s if tuple(out_aval.shape[:s]) == \
        tuple(anchor.shape[:s]) else 0
    return _device_fused("cross", [a, b], anchor, new_split,
                         lambda x, y: jnp.cross(x, y), ())


@_implements(np.ediff1d)
def _ediff1d(ary, to_end=None, to_begin=None):
    _require_tpu(ary)
    import jax.numpy as jnp
    ops = [ary]
    if to_begin is not None:
        if _is_tpu(to_begin):
            raise _Fallback("device to_begin")
        ops.append(np.asarray(to_begin))
    if to_end is not None:
        if _is_tpu(to_end):
            raise _Fallback("device to_end")
        ops.append(np.asarray(to_end))

    def body(d, *extras):
        it = iter(extras)
        tb = next(it) if to_begin is not None else None
        te = next(it) if to_end is not None else None
        return jnp.ediff1d(d, to_end=te, to_begin=tb)

    return _device_fused("ediff1d", ops, ary, min(ary.split, 1), body,
                         (to_begin is not None, to_end is not None))


def _nan_cum(name):
    def handler(a, axis=None, dtype=None, out=None):
        _require_default(out=(out, None), dtype=(dtype, None))
        _require_tpu(a)
        import jax.numpy as jnp
        jfn = getattr(jnp, name)
        ax = None if axis is None else operator.index(axis)
        # axis=None flattens: the flat result gets the filter-style
        # flat key axis, matching cumsum's convention
        new_split = (1 if a.split else 0) if ax is None else a.split
        return _device_fused(name, [a], a, new_split,
                             lambda d: jfn(d, axis=ax), (ax,))
    return handler


_TABLE[np.nancumsum] = _nan_cum("nancumsum")
_TABLE[np.nancumprod] = _nan_cum("nancumprod")


def _nan_arg(name):
    # documented divergence (API.md): an ALL-NaN slice returns jnp's -1
    # sentinel where numpy raises ValueError — detecting it would force
    # a device sync on every call
    def handler(a, axis=None, out=None, *, keepdims=_NV):
        _require_default(out=(out, None))
        _require_tpu(a)
        import jax.numpy as jnp
        jfn = getattr(jnp, name)
        kd = _keepdims(keepdims)
        if axis is None:
            ax_t = tuple(range(a.ndim))
        else:
            ax_t = (operator.index(axis) + a.ndim
                    if operator.index(axis) < 0 else operator.index(axis),)
        new_split = _axis_reduced_split(a, ax_t, kd)
        ax = None if axis is None else operator.index(axis)
        return _device_fused(name, [a], a, new_split,
                             lambda d: jfn(d, axis=ax, keepdims=kd),
                             (ax, kd))
    return handler


_TABLE[np.nanargmax] = _nan_arg("nanargmax")
_TABLE[np.nanargmin] = _nan_arg("nanargmin")


@_implements(np.fix)
def _fix(x, out=None):
    _require_default(out=(out, None))
    _require_tpu(x)
    import jax.numpy as jnp
    return _device_fused("fix", [x], x, x.split, jnp.fix, ())


# ---------------------------------------------------------------------
# set operations (round 4): the big operands reduce to their (small)
# device-side uniques — ops.unique's shard-local machinery — and the
# tiny set algebra runs on host, exactly numpy
# ---------------------------------------------------------------------

def _uniq_small(x):
    if _is_tpu(x):
        from bolt_tpu.ops import unique as bolt_unique
        return bolt_unique(x)
    return np.unique(np.asarray(x))


@_implements(np.intersect1d)
def _intersect1d(ar1, ar2, assume_unique=False, return_indices=False):
    if return_indices:
        # original positions are lost after the unique reduction
        raise _Fallback("return_indices")
    return np.intersect1d(_uniq_small(ar1), _uniq_small(ar2),
                          assume_unique=True)


@_implements(np.union1d)
def _union1d(ar1, ar2):
    return np.union1d(_uniq_small(ar1), _uniq_small(ar2))


@_implements(np.setdiff1d)
def _setdiff1d(ar1, ar2, assume_unique=False):
    return np.setdiff1d(_uniq_small(ar1), _uniq_small(ar2),
                        assume_unique=True)


@_implements(np.setxor1d)
def _setxor1d(ar1, ar2, assume_unique=False):
    return np.setxor1d(_uniq_small(ar1), _uniq_small(ar2),
                       assume_unique=True)


# ---------------------------------------------------------------------
# complex views and cleanup helpers (round 4)
# ---------------------------------------------------------------------

@_implements(np.angle)
def _angle(z, deg=False):
    _require_tpu(z)
    import jax.numpy as jnp
    return _device_fused("angle", [z], z, z.split,
                         lambda d: jnp.angle(d, deg=bool(deg)),
                         (bool(deg),))


@_implements(np.unwrap)
def _unwrap(p, discont=None, axis=-1, *, period=6.283185307179586):
    _require_tpu(p)
    import jax.numpy as jnp
    ax = operator.index(axis)
    dc = None if discont is None else float(discont)
    per = float(period)
    return _device_fused(
        "unwrap", [p], p, p.split,
        lambda d: jnp.unwrap(d, discont=dc, axis=ax, period=per),
        (dc, ax, per))


@_implements(np.sinc)
def _sinc(x):
    _require_tpu(x)
    import jax.numpy as jnp
    return _device_fused("sinc", [x], x, x.split, jnp.sinc, ())


@_implements(np.i0)
def _i0(x):
    _require_tpu(x)
    import jax.numpy as jnp
    return _device_fused("i0", [x], x, x.split, jnp.i0, ())


@_implements(np.nan_to_num)
def _nan_to_num(x, copy=True, nan=0.0, posinf=None, neginf=None):
    if not copy:
        raise _Fallback("copy=False")   # in-place: host path decides
    _require_tpu(x)
    import jax.numpy as jnp
    args = (float(nan), None if posinf is None else float(posinf),
            None if neginf is None else float(neginf))
    return _device_fused(
        "nan_to_num", [x], x, x.split,
        lambda d: jnp.nan_to_num(d, nan=args[0], posinf=args[1],
                                 neginf=args[2]), args)


def _inf_sign(name):
    def handler(x, out=None):
        _require_default(out=(out, None))
        _require_tpu(x)
        import jax.numpy as jnp
        jfn = getattr(jnp, name)
        return _device_fused(name, [x], x, x.split, jfn, ())
    return handler


_TABLE[np.isposinf] = _inf_sign("isposinf")
_TABLE[np.isneginf] = _inf_sign("isneginf")


# ---------------------------------------------------------------------
# np.fft (round 4): jnp.fft on the global sharded array, one program
# per call; key axes survive positionally (a transform along a sharded
# axis gathers that axis inside XLA, like any cross-shard op)
# ---------------------------------------------------------------------

def _fft1(name):
    def handler(a, n=None, axis=-1, norm=None, out=None):
        _require_default(out=(out, None))
        _require_tpu(a)
        import jax.numpy as jnp
        jfn = getattr(jnp.fft, name)
        nn = None if n is None else operator.index(n)
        ax = operator.index(axis)
        return _device_fused(
            "fft_" + name, [a], a, a.split,
            lambda d: jfn(d, n=nn, axis=ax, norm=norm), (nn, ax, norm))
    return handler


def _fftn(name):
    def handler(a, s=None, axes=None, norm=None, out=None):
        _require_default(out=(out, None))
        _require_tpu(a)
        import jax.numpy as jnp
        from bolt_tpu.utils import tupleize
        jfn = getattr(jnp.fft, name)
        st = None if s is None else tuple(operator.index(v)
                                          for v in tupleize(s))
        axt = None if axes is None else tuple(operator.index(v)
                                              for v in tupleize(axes))
        if axt is None and name.endswith("2"):
            axt = (-2, -1)      # jnp's 2-d forms reject axes=None
        return _device_fused(
            "fft_" + name, [a], a, a.split,
            lambda d: jfn(d, s=st, axes=axt, norm=norm),
            (st, axt, norm))
    return handler


for _name in ("fft", "ifft", "rfft", "irfft", "hfft", "ihfft"):
    _TABLE[getattr(np.fft, _name)] = _fft1(_name)
for _name in ("fft2", "ifft2", "fftn", "ifftn", "rfft2", "irfft2",
              "rfftn", "irfftn"):
    _TABLE[getattr(np.fft, _name)] = _fftn(_name)


def _fftshift_fn(name):
    def handler(x, axes=None):
        _require_tpu(x)
        import jax.numpy as jnp
        from bolt_tpu.utils import tupleize
        jfn = getattr(jnp.fft, name)
        axt = None if axes is None else tuple(operator.index(v)
                                              for v in tupleize(axes))
        return _device_fused("fft_" + name, [x], x, x.split,
                             lambda d: jfn(d, axes=axt), (axt,))
    return handler


_TABLE[np.fft.fftshift] = _fftshift_fn("fftshift")
_TABLE[np.fft.ifftshift] = _fftshift_fn("ifftshift")


@_implements(np.apply_along_axis)
def _apply_along_axis(func1d, axis, arr, *args, **kwargs):
    _require_tpu(arr)
    import jax
    import jax.numpy as jnp
    from bolt_tpu.tpu.array import _TRACE_ERRORS, _traceable
    ax = operator.index(axis)
    ax = ax + arr.ndim if ax < 0 else ax
    if not 0 <= ax < arr.ndim:
        raise np.exceptions.AxisError(axis, arr.ndim)
    try:
        hash((args, tuple(sorted(kwargs.items()))))
        hashable = all(not hasattr(v, "__array__")
                       for v in list(args) + list(kwargs.values()))
    except TypeError:
        hashable = False
    if not hashable:
        raise _Fallback("unhashable func1d extras")
    f = _traceable(func1d)
    try:
        jax.eval_shape(lambda v: f(v, *args, **kwargs),
                       jax.ShapeDtypeStruct((arr.shape[ax],), arr.dtype))
    except _TRACE_ERRORS:
        raise _Fallback("non-traceable func1d")   # host path, warned
    # keys before the applied axis survive; the func1d output dims land
    # AT the axis position, displacing everything after it
    new_split = arr.split if ax >= arr.split else ax
    return _device_fused(
        "apply_along_axis", [arr], arr, new_split,
        lambda d: jnp.apply_along_axis(f, ax, d, *args, **kwargs),
        (f, ax, args, tuple(sorted(kwargs.items()))))


# ---------------------------------------------------------------------
# triangles, diagonals, products, selection (round 4, batch 4)
# ---------------------------------------------------------------------

def _tri_fn(name):
    import jax.numpy as jnp
    jfn = getattr(jnp, name)

    def handler(m, k=0):
        _require_tpu(m)
        if m.ndim < 2:
            raise _Fallback("1-d %s" % name)   # numpy promotes to 2-d
        kk = operator.index(k)
        return _device_fused(name, [m], m, m.split,
                             lambda d: jfn(d, k=kk), (kk,))
    return handler


_TABLE[np.tril] = _tri_fn("tril")
_TABLE[np.triu] = _tri_fn("triu")


@_implements(np.diag)
def _diag(v, k=0):
    _require_tpu(v)
    import jax.numpy as jnp
    kk = operator.index(k)
    if v.ndim == 2:
        return v.diagonal(kk)
    if v.ndim != 1:
        raise ValueError("Input must be 1- or 2-d.")
    # building the (n+|k|, n+|k|) matrix: the input axis becomes the
    # row block, so a key axis stays a key axis
    return _device_fused("diag", [v], v, v.split,
                         lambda d: jnp.diag(d, k=kk), (kk,))


@_implements(np.diagflat)
def _diagflat(v, k=0):
    _require_tpu(v)
    import jax.numpy as jnp
    kk = operator.index(k)
    return _device_fused("diagflat", [v], v, 1 if v.split else 0,
                         lambda d: jnp.diagflat(d, k=kk), (kk,))


@_implements(np.vander)
def _vander(x, N=None, increasing=False):
    _require_tpu(x)
    if x.ndim != 1:
        raise ValueError("x must be a one-dimensional array or sequence.")
    import jax.numpy as jnp
    n = None if N is None else operator.index(N)
    return _device_fused(
        "vander", [x], x, x.split,
        lambda d: jnp.vander(d, N=n, increasing=bool(increasing)),
        (n, bool(increasing)))


@_implements(np.kron)
def _kron(a, b):
    anchor = _contraction_anchor(a, b)
    import jax.numpy as jnp
    new_split = anchor.split if (anchor is a
                                 and np.ndim(b) <= np.ndim(a)) else 0
    return _device_fused("kron", [a, b], anchor, new_split,
                         lambda x, y: jnp.kron(x, y), ())


@_implements(np.select)
def _select(condlist, choicelist, default=0):
    conds, choices = list(condlist), list(choicelist)
    if len(conds) != len(choices):
        raise ValueError(
            "list of cases must be same length as list of conditions")
    if len(conds) == 0:
        raise ValueError("select with an empty condition list is "
                         "not possible")
    import jax.numpy as jnp
    anchor = _contraction_anchor(*(conds + choices))
    n = len(conds)

    def body(*ops):
        return jnp.select(list(ops[:n]), list(ops[n:]), default=default)

    out_shape = np.broadcast_shapes(*(np.shape(o)
                                      for o in conds + choices))
    s = anchor.split
    new_split = s if tuple(out_shape[:s]) == tuple(anchor.shape[:s]) \
        and len(out_shape) == anchor.ndim else 0
    if not np.isscalar(default):
        raise _Fallback("array default")
    # 0 / 0.0 / False compare-and-hash equal but change the promoted
    # output dtype — the cache key must carry the type too
    return _device_fused("select", conds + choices, anchor, new_split,
                         body, (n, default, type(default).__name__))


@_implements(np.compress)
def _compress(condition, a, axis=None, out=None):
    _require_default(out=(out, None))
    if _is_tpu(condition):
        raise _Fallback("device condition")  # dynamic shape: host path
    _require_tpu(a)
    cond = np.asarray(condition)
    if cond.ndim != 1:
        raise ValueError("condition must be a 1-d array")
    dim = a.size if axis is None else a.shape[
        axis + a.ndim if axis < 0 else axis]
    idx = np.nonzero(cond)[0]
    # numpy allows an OVER-long condition when its extra entries are all
    # False; only a True index past the axis is out of bounds
    if idx.size and idx[-1] >= dim:
        raise IndexError(
            "index %d is out of bounds for axis %d with size %d"
            % (idx[-1], 0 if axis is None else axis, dim))
    return a.take(idx, axis=axis)


@_implements(np.extract)
def _extract(condition, arr):
    if _is_tpu(condition):
        raise _Fallback("device condition")
    _require_tpu(arr)
    idx = np.nonzero(np.asarray(condition).ravel())[0]
    return arr.take(idx)


def _conv1d(name):
    import jax.numpy as jnp
    jfn = getattr(jnp, name)

    def handler(a, v, mode="full" if name == "convolve" else "valid"):
        anchor = _contraction_anchor(a, v)
        # numpy promotes 0-d operands to 1-d
        if np.ndim(a) == 0 or np.ndim(v) == 0:
            if (_is_tpu(a) and np.ndim(a) == 0) or \
                    (_is_tpu(v) and np.ndim(v) == 0):
                raise _Fallback("0-d device operand")
            a = np.atleast_1d(a) if np.ndim(a) == 0 else a
            v = np.atleast_1d(v) if np.ndim(v) == 0 else v
        if np.ndim(a) != 1 or np.ndim(v) != 1:
            raise ValueError("object too deep for desired array")
        if np.shape(a)[0] == 0 or np.shape(v)[0] == 0:
            raise ValueError("v cannot be empty")
        if mode not in ("full", "same", "valid"):
            raise ValueError(
                "mode must be one of 'full', 'same', or 'valid'")
        new_split = min(anchor.split, 1) if anchor is a else 0
        return _device_fused(name, [a, v], anchor, new_split,
                             lambda x, y: jfn(x, y, mode=mode), (mode,))
    return handler


_TABLE[np.convolve] = _conv1d("convolve")
_TABLE[np.correlate] = _conv1d("correlate")


# ---------------------------------------------------------------------
# np.linalg decompositions (round 4, batch 3): jnp.linalg on the global
# sharded array in ONE fused program — XLA batches the leading (key)
# axes, so keys survive as batch dims; the (n, n)/(m, n) matrix core is
# consumed.  The local backend gets all of these from numpy natively.
# ---------------------------------------------------------------------

def _mat_split(a, consumed=2):
    """Keys surviving a batched matrix op: the leading ``ndim -
    consumed`` axes are batch dims; key axes beyond them are consumed
    by the matrix core."""
    return min(a.split, max(a.ndim - consumed, 0))


def _float_body(fn):
    """Wrap a jnp.linalg call with numpy's int→float promotion."""
    import jax.numpy as jnp

    def body(d, *rest):
        xf = d.astype(jnp.promote_types(d.dtype, jnp.float32))
        return fn(xf, *rest)
    return body


def _square_check(a):
    if a.ndim < 2:
        raise np.linalg.LinAlgError(
            "%d-dimensional array given. Array must be at least "
            "two-dimensional" % a.ndim)
    if a.shape[-1] != a.shape[-2]:
        raise np.linalg.LinAlgError(
            "Last 2 dimensions of the array must be square")


def _linalg_result(name, outs):
    """numpy ≥1.25 returns namedtuples (``EighResult`` etc., attribute
    access included); mirror that when the (private but stable) types
    are importable, else a plain tuple."""
    try:
        from numpy.linalg import _linalg
        return getattr(_linalg, name)(*outs)
    except (ImportError, AttributeError):
        return tuple(outs)


@_implements(np.linalg.inv)
def _linalg_inv(a):
    _require_tpu(a)
    _square_check(a)
    import jax.numpy as jnp
    return _device_fused("linalg_inv", [a], a, _mat_split(a),
                         _float_body(jnp.linalg.inv), ())


@_implements(np.linalg.pinv)
def _linalg_pinv(a, rcond=None, hermitian=False, *, rtol=_NV):
    _require_tpu(a)
    if a.ndim < 2:
        raise np.linalg.LinAlgError(
            "%d-dimensional array given. Array must be at least "
            "two-dimensional" % a.ndim)
    import jax.numpy as jnp
    if rtol is not _NV and rtol is not None:
        if rcond is not None:
            raise ValueError("cannot pass both rcond and rtol")
        rcond = rtol
    rc = None if rcond is None else float(rcond)
    return _device_fused(
        "linalg_pinv", [a], a, _mat_split(a),
        _float_body(lambda d: jnp.linalg.pinv(
            d, rcond=rc, hermitian=bool(hermitian))),
        (rc, bool(hermitian)))


@_implements(np.linalg.det)
def _linalg_det(a):
    _require_tpu(a)
    _square_check(a)
    import jax.numpy as jnp
    return _device_fused("linalg_det", [a], a, _mat_split(a),
                         _float_body(jnp.linalg.det), ())


@_implements(np.linalg.slogdet)
def _linalg_slogdet(a):
    _require_tpu(a)
    _square_check(a)
    import jax.numpy as jnp
    s = _mat_split(a)
    return _linalg_result("SlogdetResult", _device_fused(
        "linalg_slogdet", [a], a, (s, s),
        _float_body(lambda d: tuple(jnp.linalg.slogdet(d))), ()))


@_implements(np.linalg.cholesky)
def _linalg_cholesky(a, *, upper=False):
    _require_tpu(a)
    _square_check(a)
    import jax.numpy as jnp

    def chol(d):
        low = jnp.linalg.cholesky(d)
        if not upper:
            return low
        return jnp.swapaxes(low, -1, -2).conj()

    return _device_fused("linalg_cholesky", [a], a, _mat_split(a),
                         _float_body(chol), (bool(upper),))


def _uplo_sym(d, UPLO):
    """Mirror the named triangle — numpy reads ONLY it; feeding the raw
    matrix to jnp's symmetrization would see the other half too."""
    import jax.numpy as jnp
    tri = jnp.tril(d) if UPLO == "L" else jnp.triu(d)
    other = jnp.swapaxes(tri, -1, -2).conj()
    eye = jnp.eye(d.shape[-1], dtype=d.dtype)
    diag = jnp.real(d) if jnp.iscomplexobj(d) else d
    return tri + other - eye * diag


def _check_uplo(UPLO):
    if UPLO not in ("L", "U"):
        raise ValueError("UPLO argument must be 'L' or 'U'")


@_implements(np.linalg.eigh)
def _linalg_eigh(a, UPLO="L"):
    _require_tpu(a)
    _square_check(a)
    _check_uplo(UPLO)
    import jax.numpy as jnp
    s = _mat_split(a)
    return _linalg_result("EighResult", _device_fused(
        "linalg_eigh", [a], a, (s, s),
        _float_body(lambda d: tuple(jnp.linalg.eigh(_uplo_sym(d, UPLO)))),
        (UPLO,)))


@_implements(np.linalg.eigvalsh)
def _linalg_eigvalsh(a, UPLO="L"):
    _require_tpu(a)
    _square_check(a)
    _check_uplo(UPLO)
    import jax.numpy as jnp
    # dedicated single-output program: the eigh path would materialise
    # and constrain a full eigenvector array only to discard it
    return _device_fused(
        "linalg_eigvalsh", [a], a, _mat_split(a),
        _float_body(lambda d: jnp.linalg.eigvalsh(_uplo_sym(d, UPLO))),
        (UPLO,))


@_implements(np.linalg.svd)
def _linalg_svd(a, full_matrices=True, compute_uv=True, hermitian=False):
    _require_tpu(a)
    if a.ndim < 2:
        raise np.linalg.LinAlgError(
            "%d-dimensional array given. Array must be at least "
            "two-dimensional" % a.ndim)
    import jax.numpy as jnp
    s = _mat_split(a)
    if compute_uv:
        return _linalg_result("SVDResult", _device_fused(
            "linalg_svd", [a], a, (s, s, s),
            _float_body(lambda d: tuple(jnp.linalg.svd(
                d, full_matrices=bool(full_matrices),
                hermitian=bool(hermitian)))),
            (bool(full_matrices), bool(hermitian))))
    return _device_fused(
        "linalg_svdvals", [a], a, s,
        _float_body(lambda d: jnp.linalg.svd(
            d, compute_uv=False, hermitian=bool(hermitian))),
        ("no_uv", bool(hermitian)))


if hasattr(np.linalg, "svdvals"):
    @_implements(np.linalg.svdvals)
    def _linalg_svdvals(x, /):
        return _linalg_svd(x, compute_uv=False)


@_implements(np.linalg.qr)
def _linalg_qr(a, mode="reduced"):
    _require_tpu(a)
    if a.ndim < 2:
        raise np.linalg.LinAlgError(
            "%d-dimensional array given. Array must be at least "
            "two-dimensional" % a.ndim)
    if mode not in ("reduced", "complete", "r"):
        raise _Fallback("qr mode")          # 'raw': host path
    import jax.numpy as jnp
    s = _mat_split(a)
    if mode == "r":
        return _device_fused(
            "linalg_qr_r", [a], a, s,
            _float_body(lambda d: jnp.linalg.qr(d, mode="r")), ())
    return _linalg_result("QRResult", _device_fused(
        "linalg_qr", [a], a, (s, s),
        _float_body(lambda d: tuple(jnp.linalg.qr(d, mode=mode))),
        (mode,)))


@_implements(np.linalg.solve)
def _linalg_solve(a, b):
    anchor = _contraction_anchor(a, b)
    if np.ndim(a) < 2 or np.shape(a)[-1] != np.shape(a)[-2]:
        raise np.linalg.LinAlgError(
            "Last 2 dimensions of the array must be square")
    import jax.numpy as jnp
    # a broadcast rhs with MORE leading dims prepends batch axes that
    # displace a's keys — re-key to 0 there instead of mislabeling
    new_split = _mat_split(a) if (anchor is a
                                  and np.ndim(b) <= np.ndim(a)) else 0

    def body(x, y):
        xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
        return jnp.linalg.solve(xf, y.astype(xf.dtype))

    return _device_fused("linalg_solve", [a, b], anchor, new_split,
                         body, ())


@_implements(np.linalg.matrix_power)
def _linalg_matrix_power(a, n):
    _require_tpu(a)
    _square_check(a)
    n = operator.index(n)
    import jax.numpy as jnp
    return _device_fused(
        "linalg_matrix_power", [a], a, _mat_split(a),
        _float_body(lambda d: jnp.linalg.matrix_power(d, n)), (n,))


@_implements(np.linalg.matrix_rank)
def _linalg_matrix_rank(A, tol=None, hermitian=False, *, rtol=_NV):
    _require_tpu(A)
    if A.ndim < 2:
        # numpy: rank of a vector is whether ANY entry is nonzero — a
        # one-scalar device reduction, fetched
        nz = (A != 0).any(axis=tuple(range(A.ndim)))
        return np.intp(bool(np.asarray(nz.toarray())))
    import jax.numpy as jnp
    if rtol is not _NV and rtol is not None and tol is not None:
        raise ValueError("cannot pass both tol and rtol")
    abs_tol = None if tol is None else float(tol)
    rel_tol = float(rtol) if (rtol is not _NV and rtol is not None) \
        else None
    nmax = max(A.shape[-2:])

    def body(d):
        # numpy's thresholds: tol is ABSOLUTE; rtol (and the default
        # max(m,n)*eps) scale by the largest singular value
        s = jnp.linalg.svd(d, compute_uv=False,
                           hermitian=bool(hermitian))
        s = jnp.abs(s) if hermitian else s
        if abs_tol is not None:
            thresh = jnp.asarray(abs_tol, s.dtype)
        else:
            rel = rel_tol if rel_tol is not None \
                else nmax * jnp.finfo(s.dtype).eps
            thresh = s.max(axis=-1, keepdims=True) * rel
        return (s > thresh).sum(axis=-1)

    return _device_fused(
        "linalg_matrix_rank", [A], A, _mat_split(A), _float_body(body),
        (abs_tol, rel_tol, bool(hermitian)))


@_implements(np.linalg.lstsq)
def _linalg_lstsq(a, b, rcond=None):
    anchor = _contraction_anchor(a, b)
    if np.ndim(a) != 2:
        raise _Fallback("batched lstsq")    # numpy rejects; host raises
    import jax.numpy as jnp
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.parallel.sharding import reshard
    rc = None if rcond is None else float(rcond)
    # EAGER device execution: numpy_resid's empty-residual convention
    # branches on the CONCRETE rank, which a jitted trace cannot do —
    # and numpy parity on the residual shapes is the contract here.
    # The outputs are solution-sized (tiny), so eager dispatch costs
    # nothing material.
    xa = a.tojax() if _is_tpu(a) else anchor._coerce_operand(
        np.asarray(a))
    xb = b.tojax() if _is_tpu(b) else anchor._coerce_operand(
        np.asarray(b))
    ft = jnp.promote_types(xa.dtype, jnp.float32)
    x, res, rank, sv = jnp.linalg.lstsq(xa.astype(ft), xb.astype(ft),
                                        rcond=rc, numpy_resid=True)
    mesh = anchor.mesh
    wrap = lambda v: BoltArrayTPU(reshard(v, mesh, 0), 0, mesh)
    # numpy returns rank as a plain int scalar
    return wrap(x), wrap(res), int(np.asarray(rank)), wrap(sv)


# ---------------------------------------------------------------------
# dispatcher
# ---------------------------------------------------------------------

# ---------------------------------------------------------------------
# round-5 dispatch tail (VERDICT r4 missing-4): selection/partition,
# lexsort, grid/block/broadcast constructors, insert/delete/resize,
# the last np.linalg utilities, fft frequency grids, and the explicit
# nonsymmetric-eig policy.  Reference: ndarray-native behavior of
# ``bolt/local/array.py`` (symbol cite — SURVEY §0).
# ---------------------------------------------------------------------

@_implements(np.take_along_axis)
def _take_along_axis(arr, indices, axis=None):
    _require_tpu(arr)
    import jax.numpy as jnp
    if axis is None:
        if np.ndim(indices) != 1:
            raise ValueError(
                "when axis=None, `indices` must have a single dimension.")
        arr, ax = arr.ravel(), 0
    else:
        ax = operator.index(axis)
        if ax < 0:
            ax += arr.ndim
        if not 0 <= ax < arr.ndim:
            raise np.exceptions.AxisError(axis, arr.ndim)
        if np.ndim(indices) != arr.ndim:
            raise ValueError(
                "`indices` and `arr` must have the same number of "
                "dimensions")
    if not _is_tpu(indices):
        # host-visible indices validate numpy's bounds eagerly (jax's
        # gather would silently clamp); distributed ones are exempt —
        # checking them would be a silent gather
        host_idx = np.asarray(indices)
        n_ax = arr.shape[ax]
        if host_idx.size and ((host_idx < -n_ax) | (host_idx >= n_ax)).any():
            raise IndexError(
                "index out of bounds for axis %d with size %d" % (ax, n_ax))
    return _device_fused(
        "take_along_axis", [arr, indices], arr, arr.split,
        lambda d, idx: jnp.take_along_axis(d, idx, axis=ax), (ax,))


@_implements(np.put_along_axis)
def _put_along_axis(arr, indices, values, axis):
    if _is_tpu(arr):
        # the host fallback would mutate a gathered COPY and silently
        # discard it — reject loudly instead
        raise TypeError(
            "put_along_axis mutates its target in place; distributed "
            "bolt arrays are immutable — use b.set(...) or build the "
            "result functionally")
    raise _Fallback("target is a host array")


def _partition_common(a, kth, axis, kind, order):
    _require_default(order=(order, None))
    _require_tpu(a)
    if kind != "introselect":
        raise ValueError("unknown kind %r" % (kind,))
    if not isinstance(kth, (int, np.integer)):
        raise _Fallback("sequence kth")
    if axis is None:
        a, ax = a.ravel(), 0
    else:
        ax = operator.index(axis)
        if ax < 0:
            ax += a.ndim
        if not 0 <= ax < a.ndim:
            raise np.exceptions.AxisError(axis, a.ndim)
    n = a.shape[ax]
    k = int(kth)
    if not -n <= k < n:
        raise ValueError("kth(=%d) out of bounds (%d)" % (k, n))
    return a, (k + n if k < 0 else k), ax


@_implements(np.partition)
def _partition(a, kth, axis=-1, kind="introselect", order=None):
    import jax.numpy as jnp
    a, k, ax = _partition_common(a, kth, axis, kind, order)
    return _device_fused(
        "partition", [a], a, a.split,
        lambda d: jnp.partition(d, kth=k, axis=ax), (k, ax))


@_implements(np.argpartition)
def _argpartition(a, kth, axis=-1, kind="introselect", order=None):
    import jax.numpy as jnp
    a, k, ax = _partition_common(a, kth, axis, kind, order)
    return _device_fused(
        "argpartition", [a], a, a.split,
        lambda d: jnp.argpartition(d, kth=k, axis=ax), (k, ax))


@_implements(np.lexsort)
def _lexsort(keys, axis=-1):
    import jax.numpy as jnp
    if _is_tpu(keys):
        # a single ≥2-d array: numpy treats the rows along axis 0 as the
        # key sequence (last row is primary)
        if keys.ndim == 0:
            raise _Fallback("0-d lexsort")
        if keys.ndim == 1:
            return keys.argsort(axis=axis, kind="stable")
        return _device_fused(
            "lexsort", [keys], keys, max(keys.split - 1, 0),
            lambda d: jnp.lexsort(list(d), axis=axis), (axis,))
    seq = list(keys)
    anchor = next((k for k in seq if _is_tpu(k)), None)
    if anchor is None:
        raise _Fallback("no device operand")
    if len({np.shape(k) for k in seq}) != 1:
        raise ValueError("all keys need to be the same shape")
    return _device_fused(
        "lexsort", seq, anchor, anchor.split,
        lambda *ds: jnp.lexsort(ds, axis=axis), (axis,))


@_implements(np.meshgrid)
def _meshgrid(*xi, copy=True, sparse=False, indexing="xy"):
    import jax.numpy as jnp
    if indexing not in ("xy", "ij"):
        raise ValueError(
            "Valid values for `indexing` are 'xy' and 'ij'.")
    anchor = next((x for x in xi if _is_tpu(x)), None)
    if anchor is None:
        raise _Fallback("no device operand")
    if any(np.ndim(x) > 1 for x in xi):
        raise _Fallback("meshgrid over >1-d operands")
    k = len(xi)
    sizes = [int(np.size(x)) for x in xi]
    if not sparse:
        from bolt_tpu.tpu.array import hbm_check, _canon
        grid = 1
        for s in sizes:
            grid *= s
        item = np.dtype(_canon(np.result_type(*[
            getattr(x, "dtype", np.float64) for x in xi]))).itemsize
        hbm_check("meshgrid", k * grid * item,
                  "%d dense grids of %d elements" % (k, grid))
    return list(_device_fused(
        "meshgrid", list(xi), anchor, (0,) * k,
        lambda *ds: tuple(jnp.meshgrid(*ds, sparse=sparse,
                                       indexing=indexing)),
        (sparse, indexing)))


@_implements(np.block)
def _block(arrays):
    import jax
    import jax.numpy as jnp
    leaves = []

    def _collect(node):
        if isinstance(node, list):
            return [_collect(c) for c in node]
        leaves.append(node)
        return len(leaves) - 1

    spec = _collect(arrays)
    anchor = next((x for x in leaves if _is_tpu(x)), None)
    if anchor is None:
        raise _Fallback("no device operand")

    def _rebuild(node, ds):
        if isinstance(node, list):
            return [_rebuild(c, ds) for c in node]
        return ds[node]

    def body(*ds):
        return jnp.block(_rebuild(spec, ds))

    out_aval = jax.eval_shape(body, *[_aval_of(x) for x in leaves])
    new_split = min(anchor.split, len(out_aval.shape))
    return _device_fused("block", leaves, anchor, new_split, body,
                         (repr(spec),))


@_implements(np.broadcast_arrays)
def _broadcast_arrays(*args, subok=False):
    import jax.numpy as jnp
    anchor = next((x for x in args if _is_tpu(x)), None)
    if anchor is None:
        raise _Fallback("no device operand")
    out_shape = np.broadcast_shapes(*[np.shape(a) for a in args])
    # an operand already at the full shape keeps its keys; broadcast
    # ones gain leading/stretched axes with no key meaning
    splits = tuple(a.split if _is_tpu(a) and a.shape == out_shape else 0
                   for a in args)
    return tuple(_device_fused(
        "broadcast_arrays", list(args), anchor, splits,
        lambda *ds: tuple(jnp.broadcast_arrays(*ds)), ()))


def _static_obj_key(obj):
    """Hashable cache key for a static insert/delete selector."""
    if isinstance(obj, slice):
        return ("slice", obj.start, obj.stop, obj.step)
    if isinstance(obj, (int, np.integer)):
        return ("int", int(obj))
    return ("arr", tuple(np.asarray(obj).ravel().tolist()),
            np.asarray(obj).shape)


@_implements(np.delete)
def _delete(arr, obj, axis=None):
    _require_tpu(arr)
    import jax.numpy as jnp
    if _is_tpu(obj):
        raise _Fallback("device-resident selector")   # shape is static
    if axis is None:
        arr, ax = arr.ravel(), 0
    else:
        ax = operator.index(axis)
        if ax < 0:
            ax += arr.ndim
        if not 0 <= ax < arr.ndim:
            raise np.exceptions.AxisError(axis, arr.ndim)
    n = arr.shape[ax]
    if isinstance(obj, (int, np.integer)):
        if not -n <= obj < n:
            raise IndexError(
                "index %d is out of bounds for axis %d with size %d"
                % (obj, ax, n))
    obj_s = obj if isinstance(obj, (int, np.integer, slice)) \
        else np.asarray(obj)
    return _device_fused(
        "delete", [arr], arr, arr.split,
        lambda d: jnp.delete(d, obj_s, axis=ax),
        (ax, _static_obj_key(obj_s)))


@_implements(np.insert)
def _insert(arr, obj, values, axis=None):
    _require_tpu(arr)
    import jax.numpy as jnp
    if _is_tpu(obj):
        raise _Fallback("device-resident selector")
    if axis is None:
        arr, ax = arr.ravel(), 0
    else:
        ax = operator.index(axis)
        if ax < 0:
            ax += arr.ndim
        if not 0 <= ax < arr.ndim:
            raise np.exceptions.AxisError(axis, arr.ndim)
    n = arr.shape[ax]
    if isinstance(obj, (int, np.integer)):
        if not -n <= obj <= n:            # insert allows the end slot
            raise IndexError(
                "index %d is out of bounds for axis %d with size %d"
                % (obj, ax, n))
    obj_s = obj if isinstance(obj, (int, np.integer, slice)) \
        else np.asarray(obj)
    if isinstance(obj_s, np.ndarray) and obj_s.dtype.kind in "iu" \
            and obj_s.size:
        bad = (obj_s < -n) | (obj_s > n)  # jnp.insert would clamp
        if bad.any():
            raise IndexError(
                "index %s is out of bounds for axis %d with size %d"
                % (obj_s[bad][:1], ax, n))
    return _device_fused(
        "insert", [arr, values], arr, arr.split,
        lambda d, v: jnp.insert(d, obj_s, v, axis=ax),
        (ax, _static_obj_key(obj_s)))


@_implements(np.resize)
def _resize(a, new_shape):
    _require_tpu(a)
    import jax.numpy as jnp
    shp = tuple(operator.index(s) for s in (
        new_shape if isinstance(new_shape, (tuple, list)) else (new_shape,)))
    if any(s < 0 for s in shp):
        raise ValueError("all elements of `new_shape` must be non-negative")
    return _device_fused(
        "resize", [a], a, min(a.split, len(shp)),
        lambda d: jnp.resize(d, shp), (shp,))


@_implements(np.linalg.cond)
def _linalg_cond(x, p=None):
    _require_tpu(x)
    import jax.numpy as jnp
    if x.ndim < 2:
        raise np.linalg.LinAlgError(
            "%d-dimensional array given. Array must be at least "
            "two-dimensional" % x.ndim)
    return _device_fused(
        "linalg_cond", [x], x, _mat_split(x),
        _float_body(lambda d: jnp.linalg.cond(d, p=p)), (str(p),))


@_implements(np.linalg.multi_dot)
def _linalg_multi_dot(arrays, *, out=None):
    _require_default(out=(out, None))
    import jax.numpy as jnp
    seq = list(arrays)
    if len(seq) < 2:
        raise ValueError("Expecting at least two arrays.")
    if not any(_is_tpu(a) for a in seq):
        raise _Fallback("no device operand")
    anchor = next(a for a in seq if _is_tpu(a))
    # result ndim: 2 minus one per 1-d end operand; rows come from the
    # FIRST operand, so its keys survive iff it is 2-d and on device
    # (a 1-d first operand is contracted away — its key must NOT be
    # fabricated onto the surviving column axis)
    out_ndim = 2 - (np.ndim(seq[0]) == 1) - (np.ndim(seq[-1]) == 1)
    first_rows_survive = _is_tpu(seq[0]) and np.ndim(seq[0]) == 2 \
        and out_ndim >= 1
    new_split = min(seq[0].split, 1) if first_rows_survive else 0
    # the scoped precision policy applies like every other matmul-class
    # op (@/dot/einsum/tensordot/inner) — chained products must not fall
    # back to the TPU bf16 default under the pinned-'highest' contract
    from bolt_tpu._precision import resolve
    pr = resolve()
    # MXU matmuls need float operands; integer chains are computed in
    # f32 (exact below 2**24) and cast back to the numpy result dtype
    # instead of leaking float32 where the oracle returns ints
    dtypes = [_aval_of(o).dtype for o in seq]
    rt = np.result_type(*dtypes)
    int_out = np.issubdtype(rt, np.integer)
    from bolt_tpu.tpu.array import _canon
    target = _canon(rt) if int_out else None

    def body(*ds):
        out = jnp.linalg.multi_dot(
            [d.astype(jnp.promote_types(d.dtype, jnp.float32))
             for d in ds], precision=pr)
        if target is not None:
            out = jnp.rint(out).astype(target)
        return out
    return _device_fused("multi_dot", seq, anchor, new_split, body,
                         (pr, str(target)))


@_implements(np.linalg.tensorsolve)
def _linalg_tensorsolve(a, b, axes=None):
    import jax.numpy as jnp
    anchor = a if _is_tpu(a) else b
    _require_tpu(anchor)
    axs = None if axes is None else tuple(operator.index(x) for x in axes)
    # numpy's solve promotes through common_type: ints → float64, floats
    # keep their width — cast the f32-computed result to that target so
    # integer inputs don't silently return float32 where the oracle
    # answers (canonicalised) float64
    from bolt_tpu.tpu.array import _canon

    def _probe(x):
        dt = np.dtype(_aval_of(x).dtype)
        # common_type rejects non-numeric (bool) arrays; numpy's own
        # tensorsolve promotes bools like ints → float64
        return np.empty(0, np.int64 if dt == np.bool_ else dt)

    rt = np.common_type(_probe(a), _probe(b))
    target = _canon(rt)

    def body(da, db):
        out = jnp.linalg.tensorsolve(
            da.astype(jnp.promote_types(da.dtype, jnp.float32)),
            db.astype(jnp.promote_types(db.dtype, jnp.float32)),
            axes=axs)
        return out if out.dtype == target else out.astype(target)
    return _device_fused("tensorsolve", [a, b], anchor, 0, body,
                         (axs, str(target)))


@_implements(np.linalg.tensorinv)
def _linalg_tensorinv(a, ind=2):
    _require_tpu(a)
    import jax.numpy as jnp
    ind = operator.index(ind)
    if ind <= 0:
        raise ValueError("Invalid ind argument.")
    return _device_fused(
        "tensorinv", [a], a, 0,
        _float_body(lambda d: jnp.linalg.tensorinv(d, ind=ind)), (ind,))


@_implements(np.linalg.eig, np.linalg.eigvals)
def _linalg_eig_policy(a, *args, **kwargs):
    if _is_tpu(a):
        # XLA:TPU has no nonsymmetric eigendecomposition — an explicit
        # documented policy, not a silent warned gather (VERDICT r4
        # missing-4)
        raise NotImplementedError(
            "np.linalg.eig/eigvals of a distributed array: XLA:TPU has "
            "no nonsymmetric eigendecomposition. Use np.linalg.eigh/"
            "eigvalsh for symmetric/Hermitian matrices, or make the "
            "host transfer explicit with b.tolocal() first.")
    raise _Fallback("host operand")


# np.fft.fftfreq / rfftfreq take no array argument (n is an int), so
# they are NOT __array_function__-dispatchable (no ``__wrapped__``
# dispatcher in numpy).  With a device scalar ``d`` they are served
# COMPOSITIONALLY: numpy builds ``arange(n) * (1/(n*d))``, whose ufunc
# steps route through ``__array_ufunc__`` and the broadcasting
# ``_elementwise`` — the result is a device bolt array with zero host
# math (tests/test_array_function.py::test_tail9_fftfreq).


def _is_tpu(x):
    from bolt_tpu.tpu.array import BoltArrayTPU
    return isinstance(x, BoltArrayTPU)


# the implicit-gather warning fires ONCE per session above this size;
# tests reset the flag
IMPLICIT_GATHER_WARN_BYTES = 64 << 20
_warned = [False]


def implicit_gather_warning(nbytes):
    """Called by ``BoltArrayTPU.__array__`` when plain-numpy machinery
    implicitly gathers a device array to host.  Warns once per session
    above :data:`IMPLICIT_GATHER_WARN_BYTES` — at multi-GB scale the
    silent gather is the single easiest way to lose 100× (VERDICT r2
    missing-3)."""
    if _warned[0] or nbytes < IMPLICIT_GATHER_WARN_BYTES:
        return
    _warned[0] = True
    warnings.warn(
        "a %.0f MB distributed array is being implicitly gathered to "
        "host (e.g. np.asarray(b) or an unsupported numpy function); "
        "use bolt methods / supported numpy API to stay on device, or "
        "call .toarray() to make the transfer explicit"
        % (nbytes / float(1 << 20)), stacklevel=3)


def _to_host(x):
    return np.asarray(x) if _is_tpu(x) else x


def dispatch(b, func, types, args, kwargs):
    """Serve ``func`` from the device table, else fall back to the host:
    gather every bolt operand (``__array__`` warns above the size
    threshold) and run plain numpy — numpy-correct always, device-fast
    when the table covers it.  Per NEP-18, an operand type we do not
    recognize (another library's duck array) gets ``NotImplemented`` so
    ITS ``__array_function__`` is consulted instead of being hijacked."""
    import jax
    from bolt_tpu.base import BoltArray
    for t in types:
        if not issubclass(t, (BoltArray, np.ndarray, jax.Array)):
            return NotImplemented
    handler = _TABLE.get(func)
    if handler is not None:
        try:
            return handler(*args, **kwargs)
        except _Fallback:
            pass
    host_args = tuple(
        tuple(_to_host(x) for x in a) if isinstance(a, (tuple, list))
        else _to_host(a) for a in args)
    host_kwargs = {k: _to_host(v) for k, v in kwargs.items()}
    return func(*host_args, **host_kwargs)
