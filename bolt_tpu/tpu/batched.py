"""Batched terminal programs: continuous micro-batching for the serve
queue (ROADMAP item 4, the ``StackedArray`` "batched execution" idea —
SURVEY §2.4 — applied to the request firehose).

Everything below this module optimises ONE pipeline's bytes; a
million-user service is mostly many SMALL identical-shape pipelines
where per-request dispatch overhead, not HBM, is the roofline.  This
module gives the lazy terminals a BATCHED program form the scheduler
(``bolt_tpu.serve``) can dispatch once for N queued requests:

* :func:`batch_key` — the coalescing identity of a submitted pipeline:
  same deferred structure (map chain + terminal slots), same base
  shape/dtype, same split and mesh (⇒ same sharding) hash equal; any
  difference keeps requests apart.  Covers the lazy stat family
  (single terminals AND fused multistat groups), the deferred
  ``reduce(func)`` handle (armed by :func:`bolt_tpu.tpu.multistat.
  defer_reduce` while batching is on), and plain deferred-chain
  materialisation.
* :func:`claim` / :func:`dispatch` / :func:`unclaim` — one batched
  execution: the requests' stat groups are CLAIMED (concurrent readers
  wait on the claim event instead of double-dispatching; new members
  are declined), their bases stacked along a new leading axis inside
  ONE engine-keyed program ``("batched", inner-key, width)`` that
  vmaps the SAME traced terminal body the standalone programs use
  (``multistat._chain_stat_exprs`` / ``array._reduce_tree_expr`` /
  ``_chain_apply`` — the ``_stack_map_body`` one-body-many-programs
  seam), and every lane's results scatter back to its request's
  members — bit-identical to the standalone dispatch, because each
  lane's expressions see only that lane's row.
* **bucketed widths**: partial batches PAD to the next bucket
  (powers of two up to the policy's ``max_batch``; pad lanes replay
  lane 0 and their outputs are discarded), so steady state compiles a
  small fixed set of executables — zero fresh XLA compiles once the
  buckets are warm (:func:`warm` pre-compiles them for a fleet).

Donating pipelines never batch (the stacked program reads all N bases
— consuming them would break the one-donate-per-terminal contract),
and streamed sources batch per slab through their own executor, not
here.  The serve layer records one ``batched_dispatches`` /
``batched_requests`` engine-counter pair per coalesced dispatch plus
the ``serve.batch_occupancy.hist`` registry histogram.
"""

import os
import threading

import jax
import jax.numpy as jnp

from bolt_tpu import _lockdep
from bolt_tpu import engine as _engine
from bolt_tpu.obs import trace as _obs
from bolt_tpu.utils import prod

# ---------------------------------------------------------------------
# policy defaults (the serve layer's BatchPolicy reads these)
# ---------------------------------------------------------------------

# widest coalesced dispatch: one batched program serves up to this many
# queued same-key requests
DEFAULT_MAX_BATCH = max(2, int(os.environ.get("BOLT_SERVE_MAX_BATCH",
                                              "16")))
# micro-wait to FILL a forming batch (seconds): once a gather found at
# least one coalescible partner, the worker lingers up to this long for
# more same-key arrivals before dispatching.  A lone request never
# lingers — low-QPS single-request latency is untouched.
DEFAULT_LINGER = float(os.environ.get("BOLT_SERVE_LINGER", "0.002"))


def buckets_for(max_batch):
    """The bucketed batch widths for ``max_batch``: powers of two up to
    and including it (plus ``max_batch`` itself when it is not one), so
    steady state compiles O(log max_batch) executables per batch key."""
    max_batch = int(max_batch)
    if max_batch < 2:
        raise ValueError("max_batch must be >= 2, got %d" % max_batch)
    out, b = set(), 2
    while b <= max_batch:
        out.add(b)
        b *= 2
    out.add(max_batch)
    return tuple(sorted(out))


def bucket_width(n, buckets):
    """Smallest bucket that fits ``n`` requests (the dispatch width —
    ``bucket - n`` lanes are padding)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


def autotune_buckets(hist_buckets, max_batch, min_share=0.05):
    """Derive a bucket set from an OBSERVED batch-occupancy histogram
    (the ``[(upper_bound, count)]`` pairs of
    ``serve.batch_occupancy.hist``).  Each log2 band ``[2^e, 2^(e+1))``
    holding at least ``min_share`` of the observations contributes BOTH
    its edges as widths (clamped to ``[2, max_batch]``): the lower edge
    ``2^e`` serves the band's exact-power occupancies with ZERO padding
    (a steady occupancy of exactly 4 must dispatch at width 4, not pad
    to 8), the upper edge serves the rest of the band minimally.
    ``max_batch`` always closes the set — the
    :class:`~bolt_tpu.serve.BatchPolicy` invariant that a full batch
    never pads.  Returns ``None`` when the histogram holds no
    observations (nothing to tune from — the caller keeps its static
    buckets).

    This is the WIDTH-AUTOTUNING scaffold (ROADMAP item 4 remainder):
    ``BatchPolicy(autotune=True)`` re-derives its buckets from the
    realised occupancy mix on every :func:`warm` re-arm, so a fleet
    that mostly coalesces 3-at-a-time stops compiling (and padding to)
    widths it never fills.  With autotune off — the default — the
    static knobs are untouched."""
    import math
    total = sum(c for _, c in hist_buckets)
    if not total:
        return None
    mb = int(max_batch)
    out = {mb}
    for ub, cnt in hist_buckets:
        if not cnt or cnt / total < min_share:
            continue
        if not math.isfinite(ub):
            out.add(mb)                 # overflow band: max_batch only
            continue
        out.add(min(mb, max(2, int(ub))))        # the band's upper edge
        out.add(min(mb, max(2, int(ub) // 2)))   # ...and its lower edge
    return tuple(sorted(out))


# ---------------------------------------------------------------------
# arming (the lazy-reduce door reads this; serve arms per batching
# server)
# ---------------------------------------------------------------------

_ARMED = 0
_ARM_LOCK = _lockdep.lock("batched.arm")


def arm():
    """Arm the batching doors (called by ``serve.Server`` when a
    batching policy is configured; nests across servers)."""
    global _ARMED
    with _ARM_LOCK:
        _ARMED += 1


def disarm():
    global _ARMED
    with _ARM_LOCK:
        _ARMED = max(0, _ARMED - 1)


def armed():
    """True while at least one batching-enabled server is alive — the
    gate ``multistat.defer_reduce`` consults before deferring
    ``reduce(func)``."""
    return _ARMED > 0


# ---------------------------------------------------------------------
# the batch key
# ---------------------------------------------------------------------

def _group_slots(g):
    """A stat group's program-slot identity (deduped/sorted like the
    fused program's) — or the reduce slot for a deferred-reduce
    group."""
    from bolt_tpu.tpu.multistat import _slot
    if g.rfunc is not None:
        m = g.members[0]
        return (("reduce", m.axes, m.keepdims, None),)
    ms = g.members
    if len(ms) == 1:
        # singleton fast path — THE small-request shape; _slot already
        # returns ptp's pair in the sorted ("max" < "min") order
        return _slot(ms[0])
    return tuple(sorted({s for m in ms for s in _slot(m)},
                        key=repr))


def batch_key(arr):
    """The coalescing identity of a submitted pipeline, or ``None``
    when it cannot batch.  Two requests with equal keys share ONE
    batched dispatch: same terminal slots, same map chain (callable
    identity — hoist stage functions, exactly the cross-tenant
    coalescing contract), same base shape/dtype, same split and mesh
    (the mesh determines the key sharding, so equal keys ⇒ equal
    sharding).  Ineligible: donating chains (donation semantics stay
    standalone), streams (they batch per slab in their own executor),
    deferred filters/compactions, and already-resolved handles."""
    from bolt_tpu.tpu.array import BoltArrayTPU, _chain_donate_ok
    if not isinstance(arr, BoltArrayTPU) or arr._donated:
        return None
    h = arr._spending
    if h is not None:
        if h.result is not None:
            return None
        g = h.group
        if g.kind != "chain" or g.donate or g.dispatched:
            return None
        base = g.base
        if getattr(base, "is_deleted", lambda: False)():
            return None
        return ("stat", _group_slots(g), g.funcs, g.rfunc,
                tuple(base.shape), str(base.dtype), g.split, g.mesh)
    if (arr._chain is not None and arr._fpending is None
            and arr._pending is None and arr._stream is None
            and arr._stat_group is None):
        # a deferred map chain whose submitted terminal is
        # materialisation (serve resolves via .cache())
        if _chain_donate_ok(arr._chain):
            return None
        base, funcs = arr._chain
        if not funcs or getattr(base, "is_deleted", lambda: False)():
            return None
        return ("chain", funcs, tuple(base.shape), str(base.dtype),
                arr._split, arr._mesh)
    return None


# ---------------------------------------------------------------------
# claim / dispatch / unclaim
# ---------------------------------------------------------------------

class _Batch:
    """One claimed batched execution: the per-request sources plus the
    shared geometry the program builder closes over (geometry ONLY —
    the builder must never capture arrays)."""

    __slots__ = ("kind", "key", "arrs", "groups", "slots", "funcs",
                 "rfunc", "split", "mesh", "bases", "in_shape")

    def __init__(self, kind, key, arrs, groups, slots, funcs, rfunc,
                 split, mesh, bases, in_shape):
        self.kind = kind
        self.key = key
        self.arrs = arrs
        self.groups = groups
        self.slots = slots
        self.funcs = funcs
        self.rfunc = rfunc
        self.split = split
        self.mesh = mesh
        self.bases = bases
        self.in_shape = in_shape


def _claim_group(g, slots):
    """Claim one stat group for a batched fill; False when it raced
    away (resolved/claimed concurrently, or its slot set grew past the
    batch key's)."""
    with g.lock:
        if g.dispatched or g.claimed:
            return False
        if _group_slots(g) != slots:
            return False               # a sibling joined since submit
        g.claimed = True
        if g.claim_event is None:
            g.claim_event = threading.Event()
        else:
            g.claim_event.clear()
        return True


def _unclaim_group(g):
    with g.lock:
        g.claimed = False
        ev = g.claim_event
    if ev is not None:
        ev.set()


def claim(arrs, key):
    """Claim the requests in ``arrs`` (all sharing ``key``) for one
    batched dispatch; returns a :class:`_Batch` over the CLAIMABLE
    subset — a member that raced away (its group resolved concurrently,
    a sibling joined since submit, its base was donated) is simply
    DROPPED from the batch and dispatches standalone in the caller's
    adoption loop, so one raced request never costs the healthy
    majority their coalescing.  ``None`` when fewer than two members
    remain claimable (nothing left to coalesce)."""
    kind = key[0]
    if kind == "stat":
        slots = key[1]
        kept, groups = [], []
        for a in arrs:
            h = a._spending
            g = h.group if h is not None else None
            if (g is None or h.result is not None
                    or getattr(g.base, "is_deleted", lambda: False)()
                    or not _claim_group(g, slots)):
                continue               # raced away: standalone path
            kept.append(a)
            groups.append(g)
        if len(kept) < 2:
            for cg in groups:
                _unclaim_group(cg)
            return None
        g0 = groups[0]
        return _Batch("stat", key, kept, groups, slots, g0.funcs,
                      g0.rfunc, g0.split, g0.mesh,
                      [g.base for g in groups],
                      tuple(g0.in_aval.shape))
    kept = [a for a in arrs
            if a._chain is not None and not a._donated
            and not getattr(a._chain[0], "is_deleted", lambda: False)()]
    if len(kept) < 2:
        return None
    base0, funcs = kept[0]._chain
    return _Batch("chain", key, kept, None, None, funcs, None,
                  kept[0]._split, kept[0]._mesh,
                  [a._chain[0] for a in kept], tuple(base0.shape))


def unclaim(batch):
    """Release a claimed batch WITHOUT filling it (the dispatch failed
    or was abandoned): claimed groups un-claim so their handles resolve
    standalone; already-filled groups are left dispatched."""
    if batch.groups is not None:
        for g in batch.groups:
            _unclaim_group(g)


def dispatch(batch, buckets, record=True):
    """Run ONE batched program for every request in ``batch``: stack
    the bases along a new leading axis (padding to the bucket width
    with lane 0), vmap the shared terminal body, and scatter each
    lane's constrained outputs back to its request — stat/reduce
    members filled under their group locks (waiting readers wake),
    chain requests adopt their materialised row.  Engine-keyed as
    ``("batched", inner-key, bucket)`` so steady state re-dispatches
    compiled executables only."""
    from bolt_tpu.tpu.array import _check_live, _constrain
    from bolt_tpu.tpu import multistat as _ms
    n = len(batch.arrs)
    bw = bucket_width(n, buckets)
    kind, slots = batch.kind, batch.slots
    funcs, rfunc = batch.funcs, batch.rfunc
    split, mesh = batch.split, batch.mesh
    in_shape = batch.in_shape
    if kind == "stat" and rfunc is not None:
        from bolt_tpu.tpu.array import _reduce_tree_expr
        (_, axes, keepdims, _), = slots
        nrec = prod(in_shape[:split])
        vshape = in_shape[split:]

        def expr(d):
            return (_reduce_tree_expr(d, rfunc, funcs, split, nrec,
                                      vshape, keepdims),)
        nsplits = (split if keepdims else 0,)
    elif kind == "stat":
        def expr(d):
            return _ms._chain_stat_exprs(d, funcs, split, slots, None)
        nsplits = tuple(_ms._new_split(split, s[1], s[2]) for s in slots)
    else:
        from bolt_tpu.tpu.array import _chain_apply

        def expr(d):
            return (_chain_apply(funcs, split, d),)
        nsplits = (split,)

    def build():
        def run(*bases):
            stacked = jnp.stack(bases)
            outs = jax.vmap(expr)(stacked)
            return tuple(
                tuple(_constrain(o[i], mesh, ns)
                      for o, ns in zip(outs, nsplits))
                for i in range(bw))
        return jax.jit(run)

    fn = _engine.get(("batched", batch.key, bw), build)
    bases = [_check_live(b) for b in batch.bases]
    bases = bases + [bases[0]] * (bw - n)     # pad lanes replay lane 0
    sp = _obs.begin("serve.batched_dispatch", width=n, bucket=bw,
                    kind=kind)
    try:
        outs = fn(*bases)
    finally:
        _obs.end(sp)
    if record:
        _engine.record_batched(n)
    if kind == "stat":
        index = {s: j for j, s in enumerate(slots)}
        for i, g in enumerate(batch.groups):
            lane = outs[i]
            with g.lock:
                for m in g.members:
                    if rfunc is not None:
                        m.result = lane[0]
                    elif m.name == "ptp":
                        mx = lane[index[_ms._slot(m)[0]]]
                        mn = lane[index[_ms._slot(m)[1]]]
                        m.result = _ms._sub_program(
                            mx.shape, mx.dtype, mesh)(mx, mn)
                    else:
                        m.result = lane[index[_ms._slot(m)[0]]]
                g.dispatched = True
                g.claimed = False
                ev = g.claim_event
            if ev is not None:
                ev.set()                # wake readers parked in resolve
    else:
        for a, lane in zip(batch.arrs, outs):
            a._adopt_materialised(lane[0])
    return n


def warm(make, buckets=None, max_batch=None, policy=None):
    """Pre-compile the batched executables at every bucket width for
    the batch key of ``make()``'s pipeline (the fleet analog of
    ``engine.warm_start``): each width dispatches one throwaway batch
    built from fresh ``make()`` pipelines, so a serving steady state —
    whatever occupancy mix it realises — runs ZERO fresh XLA compiles.
    Returns the warmed widths.

    ``policy=`` is the autotune RE-ARM door: pass the server's live
    :class:`~bolt_tpu.serve.BatchPolicy` and — when it was built with
    ``autotune=True`` and the occupancy histogram has observations —
    its bucket set is re-derived from the realised occupancy mix
    (``policy.rearm()``) before warming, so the freshly compiled
    widths are the ones traffic actually fills.  A static policy
    (autotune off, the default) passes through untouched."""
    if policy is not None:
        policy.rearm()
        bks = tuple(policy.buckets)
    elif buckets:
        bks = tuple(buckets)
    else:
        bks = buckets_for(
            max_batch if max_batch is not None else DEFAULT_MAX_BATCH)
    warmed = []
    for bw in bks:
        arrs = [make() for _ in range(bw)]
        key = batch_key(arrs[0])
        if key is None:
            raise ValueError(
                "warm(): make() built a pipeline that cannot batch "
                "(no batch key — see batched.batch_key)")
        b = claim(arrs, key)
        if b is None:
            raise RuntimeError("warm(): could not claim the throwaway "
                               "warm pipelines")
        # record=False: throwaway warm dispatches must not inflate the
        # batched_dispatches/batched_requests tallies stats() reports
        # as REALISED coalescing
        dispatch(b, (bw,), record=False)
        warmed.append(bw)
    return tuple(warmed)
