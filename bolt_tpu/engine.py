"""Central dispatch engine: ONE keyed ahead-of-time executable cache.

Every op family used to hand-roll the same plumbing — a bounded
``OrderedDict`` LRU of jitted callables keyed on (op, funcs, geometry) —
in ``tpu/array.py`` and re-import it everywhere else.  That worked, but a
production executor needs three things the scattered version could not
give:

1. **Ahead-of-time compilation with visibility.**  ``get(key, builder)``
   returns a dispatcher that lowers and compiles the jitted program
   explicitly (``jit(f).lower(*args).compile()``) per argument signature,
   so the engine knows exactly when XLA compilation happens and how long
   it took — exported as the ``aot_compiles`` / ``compile_seconds``
   counters — instead of compilation hiding inside jit's first call.
   Dispatch then goes straight to the compiled executable.

2. **Cross-process persistence.**  :func:`persistent_cache` opts in to
   JAX's on-disk compilation cache (``jax_compilation_cache_dir`` with
   the min-time/min-size floors dropped to zero), so a warm process
   re-lowers but skips XLA compilation entirely: the second run of an
   identical pipeline in a fresh process shows ``compile_seconds ≈ 0``.

3. **Hit/miss accounting.**  ``hits``/``misses`` count executable-cache
   lookups at the key level, ``dispatches``/``dispatch_seconds`` the
   host-side cost of launching (launches are async; device completion is
   :func:`bolt_tpu.profile.timeit`'s job).  Snapshot via
   :func:`counters`; ``bolt_tpu.profile`` re-exports a formatted report.

The engine also owns the **donation policy** for pipeline terminals:
``reduce``/``_stat``/chained-``map`` materialisation/``chunk().map``
donate a deferred chain's base buffer to XLA when (a) the chain is that
buffer's sole owner (no other live array wraps it) and (b) the buffer is
at least :func:`donation_min_bytes` big — halving peak HBM for one-shot
``ones(10GB).map(f).sum()``-style chains, where input + intermediate
cannot coexist.  A donated parent becomes unreadable (the same guard as
``swap(donate=True)``); the size floor keeps small interactive arrays
reusable.  ``donation(min_bytes)`` scopes the policy; ``donation(None)``
disables it.

Keys follow the established convention: (op-tag, user funcs, shape,
dtype, split, mesh, precision/extras) — hashable, and holding no array
references, so cached entries pin no device memory.
"""

import contextlib
import hashlib
import os
import re
import threading
from collections import OrderedDict, deque

import jax

from bolt_tpu import _lockdep
from bolt_tpu.obs import metrics as _metrics
from bolt_tpu.obs import trace as _obs
from bolt_tpu.obs.trace import clock as _clock

# ---------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------

CACHE_MAX = 512                      # keyed entries (same bound as before)

# AOT can be turned off (pure jit dispatch, still keyed + counted) for
# debugging signature mismatches: BOLT_ENGINE_AOT=0
_AOT = os.environ.get("BOLT_ENGINE_AOT", "1").lower() not in ("0", "false")

# donation floor: terminals donate sole-owned chain bases at or above
# this size.  The default is deliberately HBM-scale (64 MB): donation's
# win is one-shot multi-GB chains where input + intermediate cannot
# coexist, while its cost — the consumed array can serve only ONE
# terminal — would surprise interactive reuse of modest arrays.  Arrays
# below the floor stay readable after any number of terminals.
# None = off entirely.
_DONATE_MIN_BYTES = int(os.environ.get("BOLT_DONATE_MIN_BYTES",
                                       str(64 << 20)))

_LOCK = _lockdep.rlock("engine.cache")   # guards the executable cache
_CACHE = OrderedDict()               # key -> _Entry
_BUILDING = {}                       # key -> Event: in-flight builds, so
                                     # concurrent same-key misses coalesce

# The engine counters live in the bolt_tpu.obs.metrics registry as the
# counter group named "engine" (PR 4): same keys, same int/float types,
# same lock-consistent snapshots as the module dict they replace —
# profile.engine_counters() is unchanged — but now enumerable (and
# resettable) alongside every other metric via obs.registry().
_SCHEMA = {
    "hits": 0,                # get() found the key
    "misses": 0,              # get() built a new entry (builder ran)
    "aot_compiles": 0,        # explicit lower+compile runs
    "lower_seconds": 0.0,     # wall time tracing/lowering (every process
                              # pays this; it is host work, not XLA)
    "compile_seconds": 0.0,   # wall time inside XLA compilation — the
                              # persistent cache drives this to ~0 in a
                              # warm process
    "dispatches": 0,          # executions dispatched through the engine
    "dispatch_seconds": 0.0,  # host-side dispatch wall time (async)
    "fallbacks": 0,           # dispatches that bypassed the AOT path
    "donations": 0,           # terminal buffer donations granted
    "persistent_hits": 0,     # XLA compiles served from the on-disk cache
    "persistent_misses": 0,   # XLA compiles that had to run for real
    "persistent_warm_hits": 0,  # persistent hits while a warm_start()
                                # fleet-preload is armed (serve.Server
                                # start_warm= — the no-compile-storm proof)
    "diagnostics": 0,         # findings emitted by bolt_tpu.analysis.check
    "strict_checks": 0,       # pre-dispatch checks forced by analysis.strict
    "strict_rejections": 0,   # dispatches refused on error-severity findings
    # host<->device traffic accounting (fed by bolt_tpu.stream.transfer —
    # the ONE device_put wrapper, enforced by lint rule BLT105)
    "transfer_bytes": 0,      # host bytes shipped to device
    "transfer_seconds": 0.0,  # seconds inside counted transfers, summed
                              # across uploader-pool workers (concurrent
                              # uploads can exceed wall time, so derive
                              # per-worker link rate, not absolute GB/s)
    # streaming-executor accounting (bolt_tpu.stream: the out-of-core
    # double-buffered pipeline).  overlap_seconds is ingest time hidden
    # behind device compute: max(0, ingest + compute - wall) per run;
    # profile.overlap_efficiency() reports it as a fraction of ingest.
    "stream_chunks": 0,           # slabs streamed through the executor
    "stream_ingest_seconds": 0.0,  # uploader-pool produce+upload time
                                   # (summed across workers: parallel
                                   # ingest can exceed wall time)
    "stream_compute_seconds": 0.0,  # main-thread dispatch + sync time
    "stream_wall_seconds": 0.0,    # end-to-end streamed-run wall time
    "stream_overlap_seconds": 0.0,  # ingest hidden behind compute
    "stream_prefetch_depth": 0,    # high-water configured prefetch depth
    "stream_upload_threads": 0,    # high-water CONCURRENT uploader
                                   # workers observed mid-upload (>1 is
                                   # the parallel-ingest proof)
    "stream_inflight_high_water": 0,  # high-water slab programs
                                      # dispatched but not yet confirmed
                                      # complete (the async window)
    # fault-tolerance accounting (ISSUE 9: resumable streams).  A retry
    # is one re-attempted slab ingest (stream.retries / the serve layer's
    # per-submit retries); a resume is one streamed run that restarted
    # from a slab-level checkpoint instead of from scratch.
    "stream_retries": 0,          # re-attempted slab ingests
    "stream_resumes": 0,          # runs resumed from a checkpoint
    "checkpoint_bytes": 0,        # partial-accumulator bytes persisted
    "checkpoint_seconds": 0.0,    # wall time inside checkpoint writes
                                  # (drain + host pull + atomic rename)
    # fused multi-terminal statistics (bolt.compute / a.stats(...) —
    # bolt_tpu/tpu/multistat.py): groups of N pending stat terminals
    # served by ONE tuple-output dispatch instead of N standalone passes
    "fused_stat_groups": 0,       # multi-terminal fused dispatches
    "fused_stat_terminals": 0,    # terminals served by those dispatches
                                  # (terminals - groups = dispatches saved)
    # cross-tenant coalescing proof (bolt_tpu.serve: N tenants running
    # the same pipeline shape must compile ONCE) — lookups that WAITED
    # for a concurrent identical build/compile instead of duplicating it
    "coalesced_builds": 0,        # get() calls that joined an in-flight
                                  # build of the same key
    "coalesced_compiles": 0,      # dispatches that joined an in-flight
                                  # lower+compile of the same signature
    # continuous micro-batching (bolt_tpu.serve Server(batching=...) +
    # bolt_tpu/tpu/batched.py): queued same-key small requests coalesced
    # into ONE stacked/vmapped dispatch at bucketed widths.
    # requests - dispatches = dispatches saved; the occupancy
    # distribution lives in the registry histogram
    # "serve.batch_occupancy.hist"
    "batched_dispatches": 0,      # coalesced batched program dispatches
    "batched_requests": 0,        # requests served BY those dispatches
    # codec-encoded streaming ingest (bolt_tpu/tpu/codec.py, ISSUE 14):
    # uploader workers ENCODE slabs on host before shipping, the slab
    # program decodes on device fused into the fold.  raw - wire =
    # host->device bytes SAVED; transfer_bytes tallies the wire bytes
    # (what actually crossed the link).
    "codec_encode_seconds": 0.0,  # host wall inside slab encodes
                                  # (summed across uploader workers)
    "codec_bytes_raw": 0,         # pre-encode logical slab bytes
    "codec_bytes_wire": 0,        # post-encode bytes actually shipped
    # the streaming shuffle (ISSUE 18): bytes moved through phase 1's
    # re-bucket dispatches (all-to-all included), bytes spilled to the
    # fingerprint directory when the plan exceeded the arbiter budget,
    # and the whole phase-1 wall (upload + re-bucket + spill).
    "shuffle_bytes": 0,
    "spill_bytes": 0,
    "shuffle_seconds": 0.0,
}

_COUNTERS = _metrics.registry().group("engine", _SCHEMA)

# ---------------------------------------------------------------------
# per-tenant counter scoping (bolt_tpu.serve)
# ---------------------------------------------------------------------
#
# A `tenant(name)` scope tags the calling thread; while active, every
# engine-counter increment ALSO lands in the registry group
# "engine/<name>" (same schema, same lock — CounterGroup.set_mirror), so
# a multi-tenant server can attribute transfer bytes, compiles and
# dispatches per tenant without a second accounting seam.  The scope is
# thread-local; bolt_tpu.stream propagates it into its uploader-pool
# threads so a streamed run's ingest traffic is attributed to the tenant
# that submitted it.

_TENANT_TLS = threading.local()


def current_tenant():
    """The calling thread's active tenant tag (``None`` outside any
    :func:`tenant` scope)."""
    return getattr(_TENANT_TLS, "name", None)


@contextlib.contextmanager
def tenant(name):
    """Scope the calling thread's tenant tag::

        with bolt_tpu.engine.tenant("team-a"):
            pipeline.sum().toarray()     # counters also land in
                                         # engine.tenant_counters("team-a")

    ``tenant(None)`` clears the tag inside the scope."""
    old = getattr(_TENANT_TLS, "name", None)
    _TENANT_TLS.name = None if name is None else str(name)
    try:
        yield
    finally:
        _TENANT_TLS.name = old


def _tenant_group():
    name = getattr(_TENANT_TLS, "name", None)
    if name is None:
        return None
    return _metrics.registry().group("engine/%s" % name, _SCHEMA)


_COUNTERS.set_mirror(_tenant_group)


def tenant_counters(name):
    """Consistent snapshot of tenant ``name``'s engine counters (the
    ``"engine/<name>"`` registry group — all zeros until a
    :func:`tenant` scope for that name does counted work)."""
    return _metrics.registry().group("engine/%s" % name, _SCHEMA).snapshot()

# latency/size distributions riding on the same registry lock: the
# counters above give totals, these give shape (log2 buckets — see
# bolt_tpu.obs.metrics.Histogram).  The ".hist" suffix keeps them off
# the group's flattened "engine.<key>" snapshot namespace.
_DISPATCH_HIST = _metrics.registry().histogram(
    "engine.dispatch_seconds.hist", lo=-20, hi=8)
_TRANSFER_HIST = _metrics.registry().histogram(
    "engine.transfer_bytes.hist", lo=6, hi=36)

_MONITORING_HOOKED = False


def _hook_persistent_monitoring():
    """Count the on-disk cache's hits/misses via jax's monitoring events
    (the only public signal of whether ``.compile()`` loaded from disk)."""
    global _MONITORING_HOOKED
    if _MONITORING_HOOKED:
        return
    try:
        from jax import monitoring

        def listen(event, **kwargs):
            if event == "/jax/compilation_cache/cache_hits":
                if _WARM_ARMED:
                    _COUNTERS.update(persistent_hits=1,
                                     persistent_warm_hits=1)
                else:
                    _COUNTERS.add("persistent_hits")
            elif event == "/jax/compilation_cache/cache_misses":
                _COUNTERS.add("persistent_misses")

        monitoring.register_event_listener(listen)
        _MONITORING_HOOKED = True
    except Exception:
        pass


def counters():
    """A CONSISTENT snapshot dict of the engine counters: the copy is
    taken under the metrics-registry lock — the same lock every
    increment holds — so a snapshot can never interleave with a
    half-applied update (e.g. ``aot_compiles`` bumped but its
    ``compile_seconds`` not yet).  Counters are monotonic within a
    process; :func:`reset_counters` zeroes them.  The backing store is
    the ``"engine"`` counter group in ``bolt_tpu.obs.registry()`` —
    keys, types and semantics are identical to the pre-registry dict."""
    return _COUNTERS.snapshot()


def reset_counters():
    _COUNTERS.reset()


def clear():
    """Drop every cached executable (counters are left alone)."""
    with _LOCK:
        _CACHE.clear()


def cache_len():
    with _LOCK:
        return len(_CACHE)


# ---------------------------------------------------------------------
# persistent on-disk compilation cache
# ---------------------------------------------------------------------

_PERSISTENT_DIR = None


def persistent_cache(cache_dir=None, enable=True):
    """Opt in to JAX's persistent on-disk XLA compilation cache.

    ::

        bolt_tpu.engine.persistent_cache("/var/cache/bolt-xla")

    Compiled programs are written under ``cache_dir`` (default
    ``~/.cache/bolt_tpu/xla``); a fresh process running the same pipeline
    re-lowers but loads the executable from disk instead of invoking XLA
    — the engine's ``compile_seconds`` counter stays ≈ 0 on the warm run.
    The min-compile-time and min-entry-size floors are dropped to zero so
    EVERY program persists (this framework's programs are many and
    individually cheap; the default floors would skip most of them).

    ``enable=False`` detaches the directory (in-memory caching only).
    Returns the resolved directory (or ``None`` when disabling).  Any
    explicit call here also DISARMS a prior :func:`warm_start` — hits
    against a re-attached ordinary cache must not keep counting as
    warm-start hits (``warm_start`` re-arms after delegating)."""
    global _PERSISTENT_DIR, _WARM_ARMED
    _hook_persistent_monitoring()
    _WARM_ARMED = False
    if not enable:
        jax.config.update("jax_compilation_cache_dir", None)
        _reset_jax_cache_singleton()
        _PERSISTENT_DIR = None
        return None
    if cache_dir is None:
        cache_dir = os.path.join(os.path.expanduser("~"), ".cache",
                                 "bolt_tpu", "xla")
    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        jax.config.update("jax_enable_compilation_cache", True)
    except AttributeError:      # flag spelling varies across versions
        pass
    _reset_jax_cache_singleton()
    _PERSISTENT_DIR = cache_dir
    return cache_dir


def _reset_jax_cache_singleton():
    """jax initialises its compilation-cache object once per process;
    flipping the directory afterwards needs an explicit reset or the old
    (absent) cache keeps being consulted."""
    try:
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()
    except Exception:
        pass


def persistent_cache_dir():
    """The active on-disk cache directory, or ``None``."""
    return _PERSISTENT_DIR


# fleet-warm start (serve.Server(start_warm=dir)): while armed, every
# persistent-cache hit ALSO tallies persistent_warm_hits — the proof a
# fresh process served its first requests from pre-seeded executables
# instead of paying a compile storm
_WARM_ARMED = False


def warm_start(cache_dir):
    """Arm the fleet-warm start: attach the on-disk XLA cache at
    ``cache_dir`` (pre-seeded by an earlier process running the fleet's
    pipeline shapes) and count every compile it serves as a
    ``persistent_warm_hits`` — a warmed process's first request then
    re-lowers but runs ZERO fresh XLA compiles (``persistent_misses``
    stays flat).  Returns the resolved cache directory.
    ``serve.Server(start_warm=dir)`` calls this at startup and
    :func:`disarm_warm_start` when it closes, so the warm tally covers
    the warmed server's lifetime, not every later cache hit."""
    global _WARM_ARMED
    out = persistent_cache(cache_dir)
    _WARM_ARMED = True
    return out


def disarm_warm_start():
    """Stop counting persistent hits as warm-start hits (the cache
    itself stays attached — sharing compiled artifacts is still the
    point; only the METRIC arming ends)."""
    global _WARM_ARMED
    _WARM_ARMED = False


# ---------------------------------------------------------------------
# donation policy
# ---------------------------------------------------------------------

# per-thread scope overrides (a stack; innermost wins) over the
# process-wide default _DONATE_MIN_BYTES
_DONATE_TLS = threading.local()


def donation_min_bytes():
    """Effective donation floor in bytes for the calling thread
    (innermost :func:`donation` scope, else the process default), or
    ``None`` when terminal donation is disabled."""
    st = getattr(_DONATE_TLS, "stack", None)
    if st:
        return st[-1]
    return _DONATE_MIN_BYTES


def set_donation_min_bytes(n):
    """Set the PROCESS-WIDE donation floor (``None`` disables terminal
    donation); per-thread :func:`donation` scopes override it."""
    global _DONATE_MIN_BYTES
    _DONATE_MIN_BYTES = None if n is None else int(n)


@contextlib.contextmanager
def donation(min_bytes):
    """Scope the terminal-donation floor::

        with bolt_tpu.engine.donation(0):      # donate at any size
            out = bolt.ones(shape, mesh).map(f).sum()

    ``donation(None)`` disables donation inside the scope.  The scope is
    THREAD-LOCAL (like ``bolt.precision``): one thread's one-shot-chain
    scope must not flip donation on for a concurrent interactive thread,
    whose arrays would silently become single-terminal."""
    st = getattr(_DONATE_TLS, "stack", None)
    if st is None:
        st = _DONATE_TLS.stack = []
    st.append(None if min_bytes is None else int(min_bytes))
    try:
        yield
    finally:
        st.pop()


def record_batched(n_requests):
    """Tally one coalesced serve dispatch (bolt_tpu/tpu/batched.py)
    serving ``n_requests`` queued same-key requests from one stacked
    program; the timeline carries it as the ``serve.batched_dispatch``
    span."""
    _COUNTERS.update(batched_dispatches=1,
                     batched_requests=int(n_requests))


def record_fused_stats(n_terminals):
    """Tally one fused multi-stat dispatch serving ``n_terminals``
    pending terminals from a single pass (bolt_tpu/tpu/multistat.py);
    the timeline carries it as the ``array.multi_stat`` span."""
    _COUNTERS.update(fused_stat_groups=1,
                     fused_stat_terminals=int(n_terminals))


def donation_granted():
    """Count a granted terminal donation (called by the op layers); a
    timeline carries it as an instant ``engine.donate`` mark under the
    consuming terminal's span."""
    _COUNTERS.add("donations")
    _obs.event("engine.donate")


# ---------------------------------------------------------------------
# static-analysis integration (bolt_tpu.analysis)
# ---------------------------------------------------------------------
#
# The abstract pipeline checker feeds the ``diagnostics`` counter on
# every check; an ``analysis.strict()`` scope installs a pre-dispatch
# guard here so the engine runs the checker before every compiling
# terminal and refuses to dispatch on error-severity findings.  The
# slot is a plain module global consulted by the op layers right before
# they enter :func:`get` — one attribute read when inactive.

_STRICT_GUARD = None


def set_strict_guard(fn):
    """Install (or clear, with ``None``) the pre-dispatch checker hook —
    owned by :func:`bolt_tpu.analysis.strict`."""
    global _STRICT_GUARD
    _STRICT_GUARD = fn


def strict_guard(arr, op):
    """Run the installed pre-dispatch checker on ``arr`` for terminal
    ``op`` (no-op when no :func:`bolt_tpu.analysis.strict` scope is
    active).  Called by the op layers immediately before a dispatching
    terminal enters :func:`get`."""
    g = _STRICT_GUARD
    if g is not None:
        g(arr, op)


def record_diagnostics(n):
    """Tally ``n`` checker findings (fed by ``bolt_tpu.analysis.check``)."""
    if n:
        _COUNTERS.add("diagnostics", n)


def strict_checked():
    _COUNTERS.add("strict_checks")


def strict_rejected():
    _COUNTERS.add("strict_rejections")
    _obs.event("engine.strict_reject")


# ---------------------------------------------------------------------
# transfer / streaming accounting (fed by bolt_tpu.stream)
# ---------------------------------------------------------------------

def record_transfer(nbytes, seconds):
    """Tally one counted host->device transfer (bolt_tpu.stream.transfer
    is the only caller — lint rule BLT105 keeps it that way)."""
    _COUNTERS.update(transfer_bytes=int(nbytes),
                     transfer_seconds=seconds)
    _TRANSFER_HIST.observe(int(nbytes))


def record_codec(raw_bytes, wire_bytes, seconds):
    """Tally one slab encode (bolt_tpu.stream's uploader workers — the
    codec-encoded ingest path, bolt_tpu/tpu/codec.py).  Applied
    atomically so a snapshot can never see a slab's raw bytes without
    its wire bytes; the timeline carries it as the ``stream.encode``
    span."""
    _COUNTERS.update(codec_bytes_raw=int(raw_bytes),
                     codec_bytes_wire=int(wire_bytes),
                     codec_encode_seconds=seconds)


def record_shuffle(nbytes, seconds):
    """Tally one streamed shuffle's phase 1 (bolt_tpu.stream's swap
    resolver): ``nbytes`` moved through the re-bucket programs and the
    phase's wall clock.  One update per shuffle, applied at the end —
    a snapshot never sees a half-accounted phase.  The timeline carries
    it as the ``stream.shuffle`` span."""
    _COUNTERS.update(shuffle_bytes=int(nbytes), shuffle_seconds=seconds)


def record_spill(nbytes):
    """Tally one spilled shuffle bucket's wire bytes
    (checkpoint.spill_save's return — dict-encoded when the slab's
    cardinality allowed, raw otherwise)."""
    _COUNTERS.update(spill_bytes=int(nbytes))


def record_stream_retry():
    """Tally one re-attempted slab ingest (a failed uploader attempt
    that was retried in place instead of poisoning the run)."""
    _COUNTERS.add("stream_retries")


def record_stream_resume():
    """Tally one streamed run resumed from a slab-level checkpoint."""
    _COUNTERS.add("stream_resumes")


def record_checkpoint(nbytes, seconds):
    """Tally one stream-checkpoint write (bolt_tpu.stream's resumable
    path; the timeline carries it as the ``stream.checkpoint`` span)."""
    _COUNTERS.update(checkpoint_bytes=int(nbytes),
                     checkpoint_seconds=seconds)


def record_stream(chunks, ingest_s, compute_s, wall_s, overlap_s, depth,
                  uploaders=1, inflight=1):
    """Tally one completed streamed run (bolt_tpu.stream executor); the
    keys apply atomically — a snapshot can never see a run's wall time
    without its overlap.  ``uploaders`` is the run's observed concurrent
    uploader high-water, ``inflight`` its dispatched-but-unconfirmed
    slab-program high-water; both (and the depth) keep process maxima."""
    _COUNTERS.update(_maxima={"stream_prefetch_depth": int(depth),
                              "stream_upload_threads": int(uploaders),
                              "stream_inflight_high_water": int(inflight)},
                     stream_chunks=int(chunks),
                     stream_ingest_seconds=ingest_s,
                     stream_compute_seconds=compute_s,
                     stream_wall_seconds=wall_s,
                     stream_overlap_seconds=overlap_s)


# ---------------------------------------------------------------------
# the keyed AOT dispatch path
# ---------------------------------------------------------------------

# ONE blessed enqueue order for executables.  A single process driving a
# multi-device mesh from SEVERAL threads (the multi-tenant serving
# layer) can enqueue two collective programs onto the per-device queues
# in different orders per device — device 0 sees run A then B, device 1
# sees B then A — and the cross-device rendezvous (psum/all_to_all)
# deadlocks with every participant waiting for a different run.  This
# lock serialises only the ENQUEUE (dispatch is async; execution still
# overlaps), so all device queues observe one global program order and
# the rendezvous always completes.  Measured µs-scale per launch; the
# slow paths (lower/compile) run OUTSIDE it.
#
# MULTI-PROCESS scope (bolt_tpu.parallel.multihost): the lock is
# PER-PROCESS — it cannot order enqueues across hosts.  Cross-process
# collective order is instead safe BY CONSTRUCTION for the programs
# that span hosts: the streaming executor's shard_map slab programs
# dispatch in slab order on every process (the re-sequencer delivers
# slabs strictly in order, and the slab schedule is a deterministic
# function of the source geometry), and multihost.barrier() takes this
# lock so a checkpoint rendezvous cannot interleave with a concurrent
# tenant's enqueue within the process.  Running MULTIPLE tenants with
# cross-host collectives concurrently would need a cross-process order
# agreement on top — not provided yet (ROADMAP item 2 remainder).
_ORDER_LOCK = _lockdep.rlock("engine.order")


def order_lock():
    """The process-wide dispatch-order lock, for the few seams outside
    this module that enqueue collective programs of their own
    (``multihost.barrier``'s rendezvous) — taking it keeps every
    per-device queue observing ONE program order per process."""
    return _ORDER_LOCK


# ---------------------------------------------------------------------
# dispatch-schedule digest (the cross-process order verifier's feed)
# ---------------------------------------------------------------------
#
# The order lock serialises enqueues WITHIN a process; across processes
# nothing checks that every pod member enqueued the SAME programs in
# the SAME order — the divergence class behind ROADMAP item 3's
# remaining gap, and it surfaces as a gloo collective hang, the worst
# possible error message.  So the engine keeps a rolling digest of the
# enqueue schedule: under the order lock, every executable enqueue
# folds its program key (address-stabilised repr — `<function f at
# 0x..>` varies per process, the qualified name does not) into a
# sha256 chain.  `multihost.verify_schedule()` exchanges the digest at
# a rendezvous and turns any divergence into a pointed error naming
# the first divergent program instead of a hang.

_SCHED_DIGEST = hashlib.sha256(b"bolt-schedule").hexdigest()
_SCHED_COUNT = 0
_SCHED_RECENT = deque(maxlen=64)      # always-on tail, for error context
_SCHED_LOG = [] if os.environ.get("BOLT_SCHED_LOG", "") == "1" else None


def _stable_key(key):
    """Cross-process-stable rendering of a program key: repr with CPython
    object addresses stripped (function/method/partial reprs embed
    them; everything else in a key — shapes, dtypes, mesh geometry —
    reprs identically on every process running the same program)."""
    return re.sub(r" at 0x[0-9a-fA-F]+", "", repr(key))


def _schedule_note(key):
    """Fold one enqueue into the schedule digest.  Caller holds
    _ORDER_LOCK — the digest order IS the enqueue order."""
    global _SCHED_DIGEST, _SCHED_COUNT
    text = _stable_key(key)
    _SCHED_DIGEST = hashlib.sha256(
        (_SCHED_DIGEST + "|" + text).encode()).hexdigest()
    _SCHED_COUNT += 1
    _SCHED_RECENT.append(text)
    if _SCHED_LOG is not None:
        _SCHED_LOG.append(text)


def schedule_digest():
    """``(count, hexdigest)`` of this process's enqueue schedule so far
    (consistent: read under the order lock)."""
    with _ORDER_LOCK:
        return _SCHED_COUNT, _SCHED_DIGEST


def schedule_recent():
    """The last few (<= 64) stabilised program keys enqueued — the
    always-on context a divergence error quotes."""
    with _ORDER_LOCK:
        return list(_SCHED_RECENT)


def schedule_log():
    """The FULL ordered key log, or ``None`` unless armed
    (:func:`schedule_log_arm` / ``BOLT_SCHED_LOG=1`` — the multihost
    harness arms it so a divergence names the exact first divergent
    key, not just the digest mismatch)."""
    with _ORDER_LOCK:
        return None if _SCHED_LOG is None else list(_SCHED_LOG)


def schedule_log_arm(on=True):
    """Arm (or drop) full schedule-key logging."""
    global _SCHED_LOG
    with _ORDER_LOCK:
        _SCHED_LOG = [] if on else None


def schedule_reset():
    """Reset digest, count and logs (tests; NOT for pod runs — peers
    must reset at the same schedule point or digests diverge)."""
    global _SCHED_DIGEST, _SCHED_COUNT
    with _ORDER_LOCK:
        _SCHED_DIGEST = hashlib.sha256(b"bolt-schedule").hexdigest()
        _SCHED_COUNT = 0
        _SCHED_RECENT.clear()
        if _SCHED_LOG is not None:
            del _SCHED_LOG[:]


def _leaf_sig(x):
    """Signature of one argument leaf: enough to pick a compiled
    executable — aval (shape/dtype) plus sharding for device arrays,
    shape/dtype for host arrays, the Python type for scalars (weak-type
    avals differ by type, and ``0 == 0.0`` would collide under equality
    hashing)."""
    if isinstance(x, jax.Array):
        return ("j", x.shape, str(x.dtype), x.sharding)
    shape = getattr(x, "shape", None)
    if shape is not None:
        return ("h", tuple(shape), str(getattr(x, "dtype", "")))
    return ("s", type(x))


class _Dispatch:
    """The callable ``get`` returns: routes a call to the per-signature
    compiled executable, lowering+compiling (counted) on first sight of a
    signature; falls back to plain jit dispatch for argument structures
    the AOT path cannot serve (and counts the fallback)."""

    __slots__ = ("jitted", "compiled", "key", "_compile_lock")

    def __init__(self, jitted, key=None):
        self.jitted = jitted
        self.compiled = {}           # signature -> compiled executable
        self.key = key               # engine cache key: what the
        #                              schedule digest folds per enqueue
        # serialises the per-signature lower+compile: N tenants racing
        # the same signature must produce ONE aot compile (the losers
        # wait and count coalesced_compiles), not N identical XLA runs
        self._compile_lock = _lockdep.lock("engine.compile")

    def lower(self, *args, **kwargs):
        """Delegate to the wrapped jitted callable so cached entries stay
        inspectable (``entry.lower(x).compile().as_text()`` — the
        HLO-contract tests read collectives out of cached programs)."""
        return self.jitted.lower(*args, **kwargs)

    def __call__(self, *args):
        _lockdep.note_dispatch()     # armed witness: no ranked lock may
        #                              be held across a dispatch (the
        #                              held-lock-across-collective
        #                              hazard; DISPATCH_SAFE excepted)
        sp = _obs.begin("engine.dispatch")
        t0 = _clock()
        try:
            out = self._dispatch(args)
        finally:
            dt = _clock() - t0
            _COUNTERS.update(dispatches=1, dispatch_seconds=dt)
            _DISPATCH_HIST.observe(dt)
            _obs.end(sp)
        return out

    def _dispatch(self, args):
        if not _AOT:
            _COUNTERS.add("fallbacks")
            with _ORDER_LOCK:
                _schedule_note(self.key)
                return self.jitted(*args)
        try:
            leaves, treedef = jax.tree_util.tree_flatten(args)
            sig = (treedef, tuple(_leaf_sig(x) for x in leaves))
        except Exception:
            sig = None
        if sig is not None:
            fn = self.compiled.get(sig)
            if fn is None:
                with self._compile_lock:
                    # a concurrent identical dispatch may have compiled
                    # while this one waited for the lock: join its
                    # executable instead of running XLA again — the
                    # cross-tenant ONE-compile guarantee
                    fn = self.compiled.get(sig)
                    if fn is not None:
                        _COUNTERS.add("coalesced_compiles")
                    else:
                        try:
                            lsp = _obs.begin("engine.lower")
                            try:
                                t0 = _clock()
                                lowered = self.jitted.lower(*args)
                                t1 = _clock()
                            finally:
                                _obs.end(lsp)
                            csp = _obs.begin("engine.compile")
                            try:
                                fn = lowered.compile()
                                t2 = _clock()
                            finally:
                                _obs.end(csp)
                            _COUNTERS.update(aot_compiles=1,
                                             lower_seconds=t1 - t0,
                                             compile_seconds=t2 - t1)
                            self.compiled[sig] = fn
                        except Exception:
                            fn = None
            if fn is not None:
                try:
                    with _ORDER_LOCK:
                        _schedule_note(self.key)
                        return fn(*args)
                except (TypeError, ValueError):
                    # argument-validation drift the leaf model missed
                    # (layouts, committed-device nuances) — raised BEFORE
                    # execution, so inputs (donated ones included) are
                    # intact and the jitted path below is safe.  Genuine
                    # runtime failures (XlaRuntimeError: OOM, nan checks,
                    # asserts) propagate — re-running them would double
                    # work and bury the real error.
                    pass
        _COUNTERS.add("fallbacks")
        # NOTE: a COLD fallback traces+compiles inside jit's first call,
        # i.e. under the order lock — unavoidable here because plain jit
        # dispatch fuses compile and enqueue.  Fallbacks are rare by
        # construction (unhashable leaves, argument-validation drift) and
        # BOLT_ENGINE_AOT=0 is an explicit single-user debug mode; the
        # hot AOT path above compiles OUTSIDE the lock.
        with _ORDER_LOCK:
            _schedule_note(self.key)
            return self.jitted(*args)


def get(key, builder):
    """The engine's dispatch lookup — the drop-in replacement for the old
    per-module ``_cached_jit``: returns a callable executing the program
    ``builder`` describes, compiled at most once per (key, argument
    signature) and shared LRU-style across every op family.

    ``builder`` must return a jitted callable (``jax.jit(f, ...)``) whose
    closure captures only geometry — never arrays (cached entries must
    not pin device memory).  ``key`` must be hashable and must determine
    the traced program (op tag, user funcs, shapes, dtypes, split, mesh,
    precision, donation flag, ...).

    Concurrent misses on the SAME key coalesce: the first caller builds,
    the rest wait on its in-flight marker and adopt the winner's entry
    (counted as ``coalesced_builds``) — so N tenants dispatching an
    identical cold pipeline trace and compile it exactly once.  A failed
    build wakes the waiters, which then build for themselves (the
    original exception propagates to the owner alone)."""
    waited = False                      # each lookup counts exactly ONCE:
    while True:                         # hit, miss, or coalesced wait
        with _LOCK:
            entry = _CACHE.get(key)
            if entry is not None:
                if not waited:
                    _COUNTERS.add("hits")
                _CACHE.move_to_end(key)
                return entry
            ev = _BUILDING.get(key)
            if ev is None:
                ev = _BUILDING[key] = threading.Event()
                break                   # this thread owns the build
            if not waited:
                _COUNTERS.add("coalesced_builds")
                waited = True
        ev.wait()
        # the owner either inserted the entry (the re-check above finds
        # it) or failed (loop again: this thread may become the owner)
    if not waited:
        _COUNTERS.add("misses")
    # build OUTSIDE the lock: builders may trace (slow) and re-enter
    sp = _obs.begin("engine.build")
    if sp is not None and isinstance(key, tuple) and key:
        sp.set(family=str(key[0]))
    try:
        entry = _Dispatch(builder(), key=key)
    except BaseException:
        with _LOCK:
            _BUILDING.pop(key, None)
        ev.set()                        # waiters retry (and may rebuild)
        raise
    finally:
        _obs.end(sp)
    with _LOCK:
        # an evict/clear may have raced; insert (or adopt) under the lock
        existing = _CACHE.get(key)
        if existing is not None:
            _CACHE.move_to_end(key)
            entry = existing
        else:
            _CACHE[key] = entry
            if len(_CACHE) > CACHE_MAX:
                _CACHE.popitem(last=False)
        _BUILDING.pop(key, None)
    ev.set()
    return entry


def evict(key):
    """Drop one keyed entry (compile-failure fallbacks memoise around a
    poisoned key)."""
    with _LOCK:
        _CACHE.pop(key, None)
