"""Checkpoint / restore for distributed bolt arrays AND streamed runs.

The reference has NO checkpointing — persistence is ``cache()`` only, and
fault tolerance is inherited from RDD lineage recomputation (SURVEY §5).
On TPU the analog is saving the sharded ``jax.Array`` itself: orbax writes
each shard from the process that owns it (multi-host safe) and restores
onto any compatible mesh, which is strictly more capable than the
reference (a cached RDD dies with the cluster; a checkpoint survives it).

>>> import bolt_tpu as bolt
>>> from bolt_tpu import checkpoint
>>> checkpoint.save("/tmp/ckpt", b)
>>> b2 = checkpoint.load("/tmp/ckpt", context=mesh)

Two degradation rules keep the dependency soft: when orbax is missing,
single-process meshes fall back to a stdlib ``np.save`` of the assembled
array (restore re-shards through the counted transfer layer), and
multi-process meshes raise a POINTED ImportError naming the package to
install — at ``save()`` call time, not as a bare mid-call import crash.

The second half is the **incremental stream-checkpoint path** (ISSUE 9):
:func:`stream_save` / :func:`stream_load` / :func:`stream_clear` persist
a streamed run's retired-slab watermark plus its folded partial
accumulator (the pairwise-tree levels and the unpaired pair partial —
sum/reduce arrays, ``(n, μ, M2)`` moment triples, fused multi-stat
component tuples alike), so a killed run restarted over the same source
resumes from the last retired slab and produces a BIT-IDENTICAL result
(``bolt_tpu.stream`` owns the resume logic; this module owns the
on-disk format).  Writes are atomic-by-rename and ordered state-first /
meta-last, so a ``kill -9`` mid-write can never leave a meta file
pointing at torn state — the interrupted checkpoint simply does not
exist and the previous one still does.
"""

import glob
import hashlib
import json
import os

import numpy as np

from bolt_tpu import _chaos
from bolt_tpu.parallel import multihost as _multihost


class CheckpointCorruptError(RuntimeError):
    """A stream-checkpoint state file failed its integrity digest (bit
    rot, truncation, a torn storage layer) — refusing to resume beats
    silently feeding a corrupt accumulator into the fold.  The message
    names the file; delete it (or the whole checkpoint dir) to restart
    the run from scratch, or restore the file from replicated storage.
    Distinct from the QUIET ``None`` cases of :func:`stream_load`
    (missing checkpoint, fingerprint drift, a kill between the two
    atomic renames): those are expected lifecycle states, corruption
    never is."""


def _array_path(path):
    return os.path.join(path, "array")


def _npy_path(path):
    return os.path.join(path, "array.npy")


def _meta_path(path):
    return os.path.join(path, "bolt_meta.json")


def _orbax():
    """The orbax checkpoint module, or a POINTED ImportError naming the
    package — raised at the call site that needed it, instead of a bare
    ``import orbax.checkpoint`` surfacing mid-call."""
    try:
        import orbax.checkpoint as ocp
        return ocp
    except ImportError as exc:
        raise ImportError(
            "bolt_tpu.checkpoint needs the 'orbax-checkpoint' package "
            "for sharded (multi-process) array checkpoints: pip install "
            "orbax-checkpoint.  Single-process meshes fall back to a "
            "stdlib np.save automatically; this mesh cannot."
        ) from exc


def _have_orbax():
    try:
        import orbax.checkpoint  # noqa: F401
        return True
    except ImportError:
        return False


def save(path, barray, force=True):
    """Write a ``mode='tpu'`` bolt array (data + split/shape/dtype
    metadata) under the directory ``path``.

    Orbax-backed when available (each process writes its own shards);
    without orbax a single-process mesh degrades to ``np.save`` of the
    assembled array, and a multi-process mesh raises the pointed
    ImportError from :func:`_orbax` — at save time, naming the
    package."""
    from bolt_tpu.tpu.array import BoltArrayTPU
    if not isinstance(barray, BoltArrayTPU):
        raise TypeError("checkpoint.save expects a mode='tpu' array; "
                        "got %r" % type(barray).__name__)
    use_orbax = _have_orbax()
    if not use_orbax and _multihost.process_count() > 1:
        _orbax()                    # raises the pointed ImportError
    os.makedirs(path, exist_ok=True)
    if use_orbax:
        import orbax.checkpoint as ocp
        ckptr = ocp.Checkpointer(ocp.ArrayCheckpointHandler())
        ckptr.save(os.path.abspath(_array_path(path)),
                   args=ocp.args.ArraySave(barray._data), force=force)
    else:
        # stdlib fallback (single process): assemble on host, write
        # atomically — the restore path re-shards through the counted
        # transfer layer
        host = np.asarray(barray._data)
        tmp = _npy_path(path) + ".tmp"
        with open(tmp, "wb") as f:       # np.save(path) would append
            np.save(f, host)             # ".npy" to the tmp name
        os.replace(tmp, _npy_path(path))
    if _multihost.process_index() == 0:
        # orbax coordinates per-shard ownership; the metadata file has one
        # writer so a shared checkpoint dir never sees interleaved writes
        meta = {"split": barray.split, "shape": list(barray.shape),
                "dtype": str(barray.dtype),
                "format": "orbax" if use_orbax else "npy"}
        with open(_meta_path(path), "w") as f:
            json.dump(meta, f)
    _multihost.barrier("bolt_checkpoint_save")


def load(path, context=None):
    """Restore a bolt array saved by :func:`save`, placing it with the key
    sharding for ``context`` (default mesh when omitted).  Reads either
    format: an orbax shard directory, or the single-process ``np.save``
    fallback (which any orbax-equipped process can also read)."""
    from bolt_tpu.parallel.sharding import key_sharding
    from bolt_tpu.tpu.array import BoltArrayTPU
    from bolt_tpu.tpu.construct import ConstructTPU

    with open(_meta_path(path)) as f:
        meta = json.load(f)
    mesh = ConstructTPU._resolve(context)
    shape = tuple(meta["shape"])
    split = int(meta["split"])
    sharding = key_sharding(mesh, shape, split)
    if meta.get("format") == "npy" or (
            not os.path.exists(_array_path(path))
            and os.path.exists(_npy_path(path))):
        from bolt_tpu.stream import transfer
        host = np.load(_npy_path(path)).astype(np.dtype(meta["dtype"]),
                                               copy=False)
        return BoltArrayTPU(transfer(host, sharding), split, mesh)
    ocp = _orbax()
    ckptr = ocp.Checkpointer(ocp.ArrayCheckpointHandler())
    data = ckptr.restore(
        os.path.abspath(_array_path(path)),
        args=ocp.args.ArrayRestore(
            restore_args=ocp.ArrayRestoreArgs(
                sharding=sharding, dtype=np.dtype(meta["dtype"]))))
    return BoltArrayTPU(data, split, mesh)


# ---------------------------------------------------------------------
# incremental stream checkpoints (the streamed-run resume format)
# ---------------------------------------------------------------------
#
# On disk: <dir>/stream_state.npz (the partial-accumulator leaves) and
# <dir>/stream_meta.json (fingerprint, watermark, leaf structure).  The
# meta file is the checkpoint's EXISTENCE: state is written and
# replaced first, meta second, both by atomic rename — a kill -9 at any
# instant leaves either the previous complete checkpoint or the new
# complete one, never a meta pointing at torn state.
#
# MULTI-PROCESS runs (bolt_tpu.parallel.multihost) extend the layout to
# PER-PROCESS SHARD FILES with a RENDEZVOUS-CONSISTENT watermark:
# process p writes <dir>/stream_state.p<p>.w<slabs>.npz (the watermark
# is IN the name — old and new checkpoints coexist), every process
# takes a barrier, and only then does process 0 replace the meta to
# point at the new watermark; a second barrier fences the cleanup of
# superseded shard files.  A kill -9 of the whole pod at ANY instant
# therefore leaves a meta whose named watermark has a complete shard
# file for EVERY process — the peers can never resume from different
# watermarks (which would cross the collective fold).  The directory
# must be shared storage (every pod checkpoint system's contract).
#
# Two pod REFINEMENTS ride on one fact (ISSUE 11): the executor's fold
# partials are psum-REPLICATED global values, so every shard file at
# one watermark holds the SAME complete accumulator.  (1) The ABORT
# path (stream_save(rendezvous=False)) lets a survivor persist its
# watermark with no barrier — peers may be dead — under an
# advance-only meta flip; a retired watermark implies every process
# participated in those slabs' collectives, so the point is
# rendezvous-consistent by construction.  (2) The TOPOLOGY REMAP
# (stream_load on a different process count) lets a pod that SHRANK
# (multihost.reform after a peer loss) adopt any surviving shard file
# and resume bit-identically on M<N processes.

_STATE_NAME = "stream_state.npz"
_SMETA_NAME = "stream_meta.json"


def _state_path(path, pid=None, slabs=None):
    if pid is None:
        return os.path.join(path, _STATE_NAME)
    return os.path.join(path, "stream_state.p%d.w%d.npz"
                        % (int(pid), int(slabs)))


def _smeta_path(path):
    return os.path.join(path, _SMETA_NAME)


def _encode(obj, leaves):
    """Structure descriptor for one fold-state node: ``None`` passes
    through, lists/tuples recurse (kind-tagged so decode rebuilds the
    exact container), anything array-like lands in ``leaves`` by
    index.  Covers every accumulator shape the executor folds: bare
    sum/reduce/min/max partials, ``(n, mu, M2)`` moment triples, and
    fused multi-stat component tuples.  Leaves are pulled through
    ``multihost.local_value``: a pod run's fold partials are
    P()-replicated global arrays, whose host copy is the local shard
    (``np.asarray`` refuses the non-fully-addressable global)."""
    if obj is None:
        return None
    if isinstance(obj, list):
        return {"l": [_encode(x, leaves) for x in obj]}
    if isinstance(obj, tuple):
        return {"t": [_encode(x, leaves) for x in obj]}
    leaves.append(_multihost.local_value(obj))
    return {"a": len(leaves) - 1}


def _decode(node, leaves):
    if node is None:
        return None
    if "l" in node:
        return [_decode(x, leaves) for x in node["l"]]
    if "t" in node:
        return tuple(_decode(x, leaves) for x in node["t"])
    return leaves[node["a"]]


def _state_digest(slabs, records, leaves):
    """Content hash of one checkpoint's accumulator state (watermark +
    every leaf's shape/dtype/bytes).  Recorded in the meta by
    :func:`stream_save` and re-verified by :func:`stream_load`, so a
    bit-rotted or truncated shard is REFUSED with a pointed error
    instead of feeding a corrupt accumulator into the fold.  Pod fold
    partials are psum-replicated, so every process's shard file at one
    watermark hashes identically — process 0's meta digest validates
    ANY adopted shard, the topology-remap path included."""
    h = hashlib.sha256()
    h.update(np.asarray([int(slabs), int(records)],
                        dtype=np.int64).tobytes())
    for leaf in leaves:
        arr = np.ascontiguousarray(leaf)
        h.update(repr((arr.shape, str(arr.dtype))).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def stream_save(path, fingerprint, slabs, records, state,
                multiprocess=None, rendezvous=True, remap_from=None,
                codec=None):
    """Persist one streamed-run checkpoint: ``slabs`` retired slabs
    covering ``records`` records, with ``state`` the executor's folded
    partial accumulator (``(levels, pend)`` — device values are pulled
    to host here).  ``fingerprint`` identifies the logical run (source
    geometry + stage chain + terminal); :func:`stream_load` refuses a
    mismatch so a stale checkpoint can never seed a different pipeline.
    Returns the state's byte count (the ``checkpoint_bytes`` tally).

    On a MULTI-PROCESS run every peer calls this at the SAME watermark
    (the executor checkpoints on a deterministic slab cadence): each
    writes its own watermark-named shard file, a barrier proves all
    landed, process 0 flips the meta, and a second barrier fences the
    cleanup of superseded files — see the section comment above.
    ``multiprocess`` says whether THIS run spans processes — the
    executor passes its MESH's answer, because a process-local mesh
    inside a multi-process runtime streams (and must checkpoint)
    single-process: its peers are not at this watermark, and a barrier
    here would hang them.  ``None`` falls back to the runtime query.

    ``rendezvous=False`` is the POD ABORT path (ISSUE 11): a survivor
    whose run just failed (peer death, injected fault) persists its
    watermark WITHOUT any barrier — peers may be dead or at other
    watermarks.  Safe because a pod run's fold partials are
    psum-replicated GLOBAL values: a retired watermark implies every
    process participated in those slabs' collectives, so ONE process's
    abort state is a complete, rendezvous-consistent resume point.
    The meta advances ONLY forward (an existing same-fingerprint meta
    at a higher-or-equal watermark is left alone), state-first /
    meta-last as always — a torn abort can never flip meta at a
    watermark whose state did not land.

    ``remap_from`` records a topology remap in the meta (the resumed
    run's first checkpoint after a shrink names the pod width the
    loaded checkpoint was cut by) — the audit trail that makes a
    3→2-process resume explainable from the directory alone.
    ``codec`` records the run's ingest codec id the same way (ISSUE
    14): the MATCHING lives in the fingerprint — a codec change names
    a different logical run and the checkpoint is ignored — but the
    meta row makes "this resume point was cut under int8" readable
    from the directory."""
    _chaos.hit("stream.checkpoint")
    os.makedirs(path, exist_ok=True)
    if multiprocess is None:
        multiprocess = _multihost.process_count() > 1
    nproc = _multihost.process_count() if multiprocess else 1
    pid = _multihost.process_index()
    leaves = []
    structure = _encode(state, leaves)
    arrays = {"leaf_%d" % i: leaf for i, leaf in enumerate(leaves)}
    # the watermark rides INSIDE the state file too: a kill between the
    # two renames below leaves the OLD meta next to the NEW state, and
    # without this cross-check a resume would fold the meta's (stale)
    # watermark onto the state's (newer) accumulator — double-counting
    # slabs silently.  stream_load refuses the pair on mismatch.
    # (Multi-process files carry the watermark in their NAME instead:
    # old and new checkpoints coexist and the meta selects one.)
    arrays["watermark"] = np.asarray([int(slabs), int(records)],
                                     dtype=np.int64)
    spath = _state_path(path) if nproc == 1 \
        else _state_path(path, pid, slabs)
    tmp = spath + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, spath)
    try:
        # the bit-rot seam: an armed "checkpoint.corrupt" fault flips
        # bytes in the JUST-WRITTEN state file (simulating storage rot
        # under the atomic rename), which stream_load's digest check
        # must refuse pointedly; action="kill" works unchanged
        _chaos.hit("checkpoint.corrupt")
    except _chaos.ChaosError:
        with open(spath, "r+b") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() // 2))
            f.write(b"\xde\xad\xbe\xef")
    if nproc > 1 and rendezvous:
        # every peer's shard file for THIS watermark exists past here —
        # only then may the meta name it
        _multihost.barrier("bolt_stream_ckpt_w%d" % int(slabs))
    meta = {"fingerprint": list(fingerprint), "slabs": int(slabs),
            "records": int(records), "structure": structure,
            "leaves": len(leaves), "nproc": nproc}
    if remap_from is not None:
        meta["remapped_from"] = int(remap_from)
    if codec is not None:
        meta["codec"] = str(codec)
    if nproc > 1 and not rendezvous:
        meta["abort"] = True
        # advance-only: survivors may abort at different watermarks and
        # each flips the meta for itself — a LOWER watermark must never
        # overwrite a higher one (both are valid resume points; keep
        # the one that loses the least work).  The read-then-rename
        # window is benign: every candidate meta names a complete,
        # rendezvous-consistent state (see the docstring).
        cur = _read_meta(path)
        if cur is not None and \
                list(cur.get("fingerprint", ())) == list(fingerprint) \
                and int(cur.get("slabs", -1)) >= int(slabs):
            return sum(int(leaf.nbytes) for leaf in leaves)
    # single-process checkpoints are written by WHOEVER streams them —
    # a process-local mesh may live on a non-zero runtime process; only
    # the pod format elects process 0 as the one meta writer (abort
    # writes have no rendezvous, so every survivor writes for itself)
    if nproc == 1 or pid == 0 or not rendezvous:
        # the digest hashes every leaf's bytes — pay for it only on
        # the rank that actually writes the meta (pod partials are
        # psum-replicated, so the writer's digest validates any
        # peer's shard), and only past the advance-only abort return
        meta["digest"] = _state_digest(slabs, records, leaves)
        _chaos.hit("checkpoint.meta")
        tmp = _smeta_path(path) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, _smeta_path(path))
    if nproc > 1 and rendezvous:
        # fence the cleanup: superseded shard files may vanish only
        # once the meta durably points at the new watermark everywhere
        _multihost.barrier("bolt_stream_ckpt_meta_w%d" % int(slabs))
        for old in glob.glob(os.path.join(
                path, "stream_state.p%d.w*.npz" % pid)):
            if old != spath:
                try:
                    os.remove(old)
                except FileNotFoundError:
                    pass
    return sum(int(leaf.nbytes) for leaf in leaves)


def _read_meta(path):
    try:
        with open(_smeta_path(path)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def stream_load(path, fingerprint, multiprocess=None, info=None):
    """Load a streamed-run checkpoint written by :func:`stream_save`:
    ``(slabs, records, state)`` with host-array leaves, or ``None``
    when no checkpoint exists, its fingerprint names a DIFFERENT
    logical run (shape/stages/terminal drifted — resuming would be
    silently wrong, so the stale checkpoint is ignored), or the meta
    and state files disagree on the watermark (a kill landed between
    the two renames: the torn pair is discarded, never resumed).

    A multi-process run loads the SHARED meta (so every peer agrees on
    the watermark) and this process's own shard file for that
    watermark.  A checkpoint cut by a DIFFERENT process count performs
    a **topology remap** (ISSUE 11 shrink-and-resume): a pod run's
    fold partials are psum-replicated global values — every shard file
    at one watermark holds the same complete accumulator — so a
    resumed M<N-process pod (or a single process) adopts any surviving
    shard file of the meta's watermark (own index preferred, lowest
    index otherwise).  ``info``, when a dict, receives
    ``{"remapped_from": N}`` so the executor can record the remap in
    its next checkpoint write.  ``multiprocess`` mirrors
    :func:`stream_save`'s (the executor passes its mesh's answer;
    ``None`` = the runtime query)."""
    meta = _read_meta(path)           # None on missing OR malformed:
    if meta is None:                  # a torn meta is not a checkpoint
        return None
    if list(meta.get("fingerprint", ())) != list(fingerprint):
        return None
    if multiprocess is None:
        multiprocess = _multihost.process_count() > 1
    nproc = _multihost.process_count() if multiprocess else 1
    meta_nproc = int(meta.get("nproc", 1))
    if meta_nproc == nproc:
        spath = _state_path(path) if nproc == 1 else _state_path(
            path, _multihost.process_index(), int(meta["slabs"]))
        if nproc > 1 and not os.path.exists(spath):
            # this index's file never landed (it was the dead peer's
            # name, or an abort write) — any peer's file is the same
            # replicated global state
            spath = _remap_state_path(path, meta)
    else:
        # topology remap: the checkpoint was cut by a different pod
        # width — adopt a surviving shard file (replicated state)
        spath = _remap_state_path(path, meta)
        if spath is not None and info is not None:
            info["remapped_from"] = meta_nproc
    if spath is None:
        return None
    corrupt = (
        "stream checkpoint state file %r is corrupt (%%s); refusing "
        "to seed the fold with it — delete the file (or the whole "
        "checkpoint dir) to restart from scratch, or restore it from "
        "replicated storage" % spath)
    try:
        z = np.load(spath)
    except FileNotFoundError:
        return None                 # raced cleanup: not a checkpoint
    except Exception as exc:        # noqa: BLE001 — an EXISTING state
        # file that cannot even open is bit rot or truncation, never a
        # torn write (writes are atomic-by-rename)
        raise CheckpointCorruptError(
            corrupt % ("unreadable npz: %s" % exc)) from exc
    try:
        try:
            wm = z["watermark"]
        except Exception as exc:    # noqa: BLE001
            raise CheckpointCorruptError(
                corrupt % ("watermark unreadable: %s" % exc)) from exc
        if int(wm[0]) != int(meta["slabs"]) \
                or int(wm[1]) != int(meta["records"]):
            return None             # meta/state from different writes
        #                             (a kill between the two renames)
        try:
            leaves = [np.asarray(z["leaf_%d" % i])
                      for i in range(int(meta["leaves"]))]
        except Exception as exc:    # noqa: BLE001 — the watermark
            # matched this meta, so the leaves were written by the
            # same atomic write: failing to read them is corruption
            raise CheckpointCorruptError(
                corrupt % ("leaf unreadable: %s" % exc)) from exc
    finally:
        z.close()
    want = meta.get("digest")
    if want is not None and _state_digest(
            meta["slabs"], meta["records"], leaves) != want:
        raise CheckpointCorruptError(
            corrupt % "content digest mismatch vs the meta record")
    state = _decode(meta["structure"], leaves)
    return int(meta["slabs"]), int(meta["records"]), state


def _remap_state_path(path, meta):
    """A usable state file for ``meta``'s watermark, whatever topology
    cut it: this process's own shard file when present, else the
    lowest-index survivor's, else the single-process file.  Valid
    because pod fold partials are replicated global values (see
    :func:`stream_load`)."""
    if int(meta.get("nproc", 1)) == 1:
        sp = _state_path(path)
        return sp if os.path.exists(sp) else None
    slabs = int(meta["slabs"])
    own = _state_path(path, _multihost.process_index(), slabs)
    if os.path.exists(own):
        return own
    cands = glob.glob(os.path.join(path, "stream_state.p*.w%d.npz"
                                   % slabs))
    if not cands:
        return None

    def _pid_of(p):
        try:
            return int(os.path.basename(p).split(".p")[1].split(".w")[0])
        except (IndexError, ValueError):
            return 1 << 30
    return min(cands, key=_pid_of)


def stream_clear(path, multiprocess=None):
    """Remove a directory's stream checkpoint (the success path: a
    finished run must leave NO stale checkpoint behind — the
    ``bench_all --check`` gate asserts it).  Meta first, then state —
    the reverse of the write order, so an interrupted clear also never
    leaves meta pointing at missing state.  Multi-process (same
    ``multiprocess`` contract as :func:`stream_save` — the executor
    passes its mesh's answer): a barrier proves every peer reached
    success, process 0 removes the meta, a second barrier fences it,
    then each peer removes its own shard files."""
    if multiprocess is None:
        multiprocess = _multihost.process_count() > 1
    if multiprocess:
        _multihost.barrier("bolt_stream_clear")
        if _multihost.process_index() == 0:
            try:
                os.remove(_smeta_path(path))
            except FileNotFoundError:
                pass
        _multihost.barrier("bolt_stream_clear_meta")
        # every peer removes its own shard files; process 0 sweeps the
        # REST too — a pod that shrank (reform) leaves dead peers'
        # stale shard files behind that no surviving index would claim
        pat = ("stream_state.p*.w*.npz"
               if _multihost.process_index() == 0
               else "stream_state.p%d.w*.npz"
               % _multihost.process_index())
        for p in glob.glob(os.path.join(path, pat)):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
        # dead peers' heartbeat/farewell markers go with their shard
        # files (ISSUE 12 satellite: the shared transport dir must not
        # accumulate a dead pod's droppings)
        from bolt_tpu.parallel import podwatch as _podwatch
        _podwatch.sweep_dead_markers()
        return
    for p in [_smeta_path(path), _state_path(path)] + glob.glob(
            os.path.join(path, "stream_state.p*.w*.npz")):
        # the glob: a single process that resumed a POD checkpoint via
        # the topology remap must not leave the pod's shard files stale
        try:
            os.remove(p)
        except FileNotFoundError:
            pass


def stream_pending(path):
    """Does ``path`` hold a resumable stream checkpoint?"""
    return os.path.exists(_smeta_path(path))


# ---------------------------------------------------------------------------
# shuffle spill slabs (ISSUE 18)
#
# When a streamed `swap` / re-axis shuffle forecasts a working set larger
# than the device arbiter's budget, phase 1 spills each re-keyed bucket
# to disk and phase 2 streams the buckets back as a fresh source.  The
# on-disk format reuses this module's contract: ATOMIC tmp+rename per
# file, self-describing payloads (codec name + dtype + shape + global
# row offset ride inside), and a fingerprint-named working directory so
# a resumed run can only ever adopt ITS OWN spill — a different
# pipeline's leftovers hash to a different directory and are invisible.
#
# Integer/bool buckets are dict-encoded when the slab's cardinality
# allows (codec "dict": uint8 indices + 256-entry dictionary — 1/8 the
# bytes of an int64 key column); anything else is stored raw.  The
# fallback is per-BUCKET and recorded in the file, so mixed-cardinality
# datasets just work and decode never guesses.
#
# Completion is tracked per SLAB (a slab is done only after every one of
# its buckets landed) in a per-process manifest, giving the kill -9
# resume point: a single-process run skips completed slabs; pod runs
# ignore manifests entirely and re-run phase 1 whole (per-process
# manifests can disagree after an asymmetric kill, and a disagreeing
# slab schedule would deadlock the all-to-all rendezvous — atomic
# overwrite keeps the re-run correct).
# ---------------------------------------------------------------------------

def _spill_root(path, fingerprint):
    h = hashlib.sha256(repr(fingerprint).encode()).hexdigest()[:16]
    return os.path.join(path, "bolt-spill-%s" % h)


def _spill_file(path, fingerprint, slab_i, bucket_i):
    return os.path.join(
        _spill_root(path, fingerprint),
        "slab%05d.bucket%05d.p%d.npz"
        % (int(slab_i), int(bucket_i), _multihost.process_index()))


def _spill_manifest_path(path, fingerprint):
    return os.path.join(_spill_root(path, fingerprint),
                        "manifest.p%d.json" % _multihost.process_index())


def spill_save(path, fingerprint, slab_i, bucket_i, block, row0):
    """Persist one re-keyed shuffle bucket (this process's rows of
    bucket ``bucket_i`` produced from input slab ``slab_i``) atomically
    under ``path``'s fingerprint directory.  ``row0`` is the bucket's
    GLOBAL output row offset — phase 2 reassembles buckets by it
    without re-deriving the plan.  Returns the bytes written (the
    ``spill_bytes`` tally).  Integer/bool blocks try the "dict" codec
    first and fall back to raw when the slab's cardinality exceeds the
    dictionary (the fallback is recorded in the file — decode never
    guesses)."""
    block = np.ascontiguousarray(block)
    codec_name = ""
    wire, sides = block, ()
    if np.issubdtype(block.dtype, np.integer) \
            or block.dtype == np.dtype(np.bool_):
        from bolt_tpu.tpu import codec as _codec
        try:
            wire, sides = _codec.get("dict").encode(block, delta_ok=False)
            codec_name = "dict"
        except ValueError:        # > 256 distinct values: store raw
            wire, sides, codec_name = block, (), ""
    root = _spill_root(path, fingerprint)
    os.makedirs(root, exist_ok=True)
    payload = {"wire": wire,
               "row0": np.asarray(int(row0), dtype=np.int64),
               "shape": np.asarray(block.shape, dtype=np.int64),
               "dtype": np.asarray(str(block.dtype)),
               "codec": np.asarray(codec_name),
               "nside": np.asarray(len(sides), dtype=np.int64)}
    for i, s in enumerate(sides):
        payload["side%d" % i] = np.asarray(s)
    fpath = _spill_file(path, fingerprint, slab_i, bucket_i)
    tmp = fpath + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, fpath)
    return os.path.getsize(fpath)


def spill_load(path, fingerprint, slab_i, bucket_i):
    """Read one spilled bucket back as ``(host block, row0)`` — the
    inverse of :func:`spill_save`, host-side decode included.  A
    missing or torn file raises :class:`CheckpointCorruptError`
    pointedly (phase 2 only reads slabs the manifest marked done, so a
    hole here is rot or an outside deletion, not a normal resume)."""
    fpath = _spill_file(path, fingerprint, slab_i, bucket_i)
    try:
        with np.load(fpath, allow_pickle=False) as z:
            wire = z["wire"]
            row0 = int(z["row0"])
            dtype = np.dtype(str(z["dtype"]))
            codec_name = str(z["codec"])
            shape = tuple(int(n) for n in z["shape"])
            sides = tuple(z["side%d" % i]
                          for i in range(int(z["nside"])))
    except FileNotFoundError:
        raise CheckpointCorruptError(
            "spill bucket missing: %s — the manifest marked slab %d "
            "done but its bucket %d file is gone (deleted or never "
            "fenced); clear the spill directory "
            "(bolt_tpu.checkpoint.spill_clear) and re-run"
            % (fpath, int(slab_i), int(bucket_i)))
    except (ValueError, OSError, KeyError) as exc:
        raise CheckpointCorruptError(
            "spill bucket unreadable: %s (%s) — torn write or storage "
            "rot; clear the spill directory "
            "(bolt_tpu.checkpoint.spill_clear) and re-run"
            % (fpath, exc))
    if codec_name:
        from bolt_tpu.tpu import codec as _codec
        block = np.asarray(_codec.get(codec_name).decode(
            wire, sides, dtype, delta_ok=False))
    else:
        block = wire.astype(dtype, copy=False)
    return block.reshape(shape), row0


def spill_slab_done(path, fingerprint, slab_i):
    """Mark input slab ``slab_i`` complete in this process's spill
    manifest — called ONLY after every bucket of the slab landed, so
    the manifest's claim is the fence (a kill between bucket writes
    leaves the slab unmarked and the resume re-runs it; the atomic
    per-bucket overwrite makes that idempotent)."""
    done = sorted(spill_manifest(path, fingerprint) | {int(slab_i)})
    mpath = _spill_manifest_path(path, fingerprint)
    os.makedirs(os.path.dirname(mpath), exist_ok=True)
    tmp = mpath + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"done": done}, f)
    os.replace(tmp, mpath)


def spill_manifest(path, fingerprint):
    """The set of input slabs this process has fully spilled for
    ``fingerprint`` under ``path`` — empty when no spill exists (a
    different fingerprint hashes to a different directory, so a stale
    spill can never leak into a changed pipeline)."""
    try:
        with open(_spill_manifest_path(path, fingerprint)) as f:
            return set(int(s) for s in json.load(f)["done"])
    except (FileNotFoundError, ValueError, KeyError):
        return set()


def spill_pending(path):
    """Does ``path`` hold any shuffle spill working directory?"""
    return bool(glob.glob(os.path.join(path, "bolt-spill-*")))


def spill_clear(path):
    """Remove every shuffle spill working directory under ``path`` (the
    success path: a completed shuffle's phase 2 owns its buckets only
    until the output is consumed — the ``bench_all --check`` gate
    asserts a cleared directory holds no ``bolt-spill-*`` residue,
    half-written ``.tmp`` droppings included)."""
    import shutil
    for d in glob.glob(os.path.join(path, "bolt-spill-*")):
        shutil.rmtree(d, ignore_errors=True)
