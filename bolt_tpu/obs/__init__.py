"""bolt_tpu.obs — structured tracing, metrics and timeline export.

The observability subsystem (PR 4): one place to see where a pipeline
spends its time — compile vs dispatch vs transfer vs overlap — without
reading engine internals.

* :mod:`bolt_tpu.obs.trace` — thread-safe span tracer.  ``obs.span``
  is the context-manager/decorator API; ``obs.begin``/``obs.end`` the
  allocation-free hot-path pair the engine and streaming executor use;
  ``obs.event`` instant marks; ``obs.clock`` THE blessed monotonic
  timer (lint rule BLT106 forbids raw ``time.perf_counter()``
  bookkeeping elsewhere in the package).  Off by default; near-zero
  cost while off.
* :mod:`bolt_tpu.obs.metrics` — typed registry (counters, gauges,
  log2-bucket histograms, locked counter groups).  The dispatch
  engine's counters are the group named ``"engine"`` here;
  ``profile.engine_counters()`` is a facade over it.
* :mod:`bolt_tpu.obs.export` — ``obs.to_chrome`` (Perfetto/
  ``chrome://tracing`` JSON), ``obs.report`` (text tree), and the
  ``obs.timeline(path)`` scope that arms tracing around one run and
  writes the file.

Quick start::

    import bolt_tpu as bolt
    with bolt.obs.timeline("/tmp/run.json"):
        bolt.fromcallback(load, shape, mesh, dtype="f4").sum()
    print(bolt.obs.report())

The obs modules themselves import ONLY the standard library (no jax,
no numpy — ``trace.py``/``metrics.py`` load standalone by path, the
property the fast CLI gates rely on); reaching them through the
``bolt_tpu`` package of course initialises the package as usual.
"""

from bolt_tpu.obs import metrics
from bolt_tpu.obs.export import report, timeline, to_chrome, trace_arg
from bolt_tpu.obs.metrics import registry, thread_census
from bolt_tpu.obs.trace import (Span, active_count, begin, cancel, clear,
                                clock, current, disable, enable, enabled,
                                end, event, span, spans)

__all__ = ["Span", "active_count", "begin", "cancel", "clear", "clock",
           "current", "disable", "enable", "enabled", "end", "event",
           "metrics", "registry", "report", "span", "spans",
           "thread_census", "timeline", "to_chrome", "trace_arg"]
