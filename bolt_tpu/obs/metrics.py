"""Typed metrics registry: the ONE backing store for the package's
numeric tallies.

PRs 1–3 accumulated their counters in per-module dicts (the engine's
``_COUNTERS``, updated under the engine lock) — workable, but every new
subsystem re-invented the same snapshot/reset/lock plumbing and nothing
could enumerate "all metrics" for export.  This registry centralises it:

* :class:`Counter` — monotonic int or float accumulator;
* :class:`Gauge` — last-value / high-water sample;
* :class:`Histogram` — fixed **log2 buckets**: observation ``v`` lands
  in bucket ``floor(log2(v))`` clamped to the configured exponent range,
  so a histogram over seconds spans microseconds..minutes in ~40 ints
  with no configuration per call site and O(1) updates;
* :class:`CounterGroup` — a fixed-schema counter family updated and
  snapshotted under ONE lock.  The dispatch engine's counters
  (:func:`bolt_tpu.engine.counters`, re-exported as
  ``profile.engine_counters()``) are a ``CounterGroup`` named
  ``engine``: same keys, same int/float types, same lock-consistent
  snapshots as the hand-rolled dict they replace — byte-for-byte
  compatible, now enumerable through :func:`snapshot` alongside
  everything else.

All metrics in one :class:`Registry` share a single re-entrant lock, so
a multi-key update (e.g. the streaming executor's six-counter tally) is
atomic against any snapshot — the same guarantee the engine lock gave.
Standard library only; importable with no jax anywhere in sight.
"""

import math
import os
import sys


def _lockdep():
    """bolt_tpu/_lockdep.py (the ranked lock inventory), loaded by path
    under its canonical name when the package is not imported: this
    module stays stdlib-only standalone, and a later ``bolt_tpu``
    import adopts the SAME witness instance.  The registry lock is the
    hierarchy's LEAF (``obs.registry``): every critical section in the
    package may count, so nothing may nest inside it."""
    mod = sys.modules.get("bolt_tpu._lockdep")
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "_lockdep.py")
        spec = importlib.util.spec_from_file_location(
            "bolt_tpu._lockdep", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["bolt_tpu._lockdep"] = mod
        spec.loader.exec_module(mod)
    return mod


class Counter:
    """Monotonic accumulator.  The initial value fixes the type: ``0``
    counts ints, ``0.0`` accumulates float seconds/bytes."""

    __slots__ = ("name", "_lock", "_initial", "_value")

    def __init__(self, name, lock, initial=0):
        self.name = name
        self._lock = lock
        self._initial = initial
        self._value = initial

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = self._initial

    def snapshot(self):
        return self.value


class Gauge:
    """Last-value sample with a high-water helper."""

    __slots__ = ("name", "_lock", "_initial", "_value")

    def __init__(self, name, lock, initial=0):
        self.name = name
        self._lock = lock
        self._initial = initial
        self._value = initial

    def set(self, v):
        with self._lock:
            self._value = v

    def high_water(self, v):
        """Keep the maximum of the current value and ``v``."""
        with self._lock:
            if v > self._value:
                self._value = v

    @property
    def value(self):
        with self._lock:
            return self._value

    def reset(self):
        with self._lock:
            self._value = self._initial

    def snapshot(self):
        return self.value


class Histogram:
    """Fixed log2-bucket histogram over positive values.

    Bucket ``i`` (for ``lo <= i < hi``) counts observations ``v`` with
    ``2**i <= v < 2**(i+1)``; values below ``2**lo`` land in the
    underflow bucket, at or above ``2**hi`` in the overflow bucket.
    The defaults (``lo=-20, hi=8``) cover ~1 µs .. ~4 min for seconds
    and are equally sensible for MB-scale byte counts with
    ``Histogram(name, lo=10, hi=36)``."""

    __slots__ = ("name", "_lock", "lo", "hi", "_counts", "_sum", "_count")

    def __init__(self, name, lock, lo=-20, hi=8):
        if hi <= lo:
            raise ValueError("histogram needs hi > lo, got [%d, %d)"
                             % (lo, hi))
        self.name = name
        self._lock = lock
        self.lo = lo
        self.hi = hi
        # [underflow] + one per exponent + [overflow]
        self._counts = [0] * (hi - lo + 2)
        self._sum = 0.0
        self._count = 0

    def _index(self, v):
        if v <= 0:
            return 0                         # underflow (incl. 0)
        e = math.frexp(v)[1] - 1             # floor(log2(v))
        if e < self.lo:
            return 0
        if e >= self.hi:
            return len(self._counts) - 1     # overflow
        return e - self.lo + 1

    def observe(self, v):
        with self._lock:
            self._counts[self._index(v)] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def buckets(self):
        """``[(upper_bound, count)]`` — bounds are ``2**e`` with leading
        ``2**lo`` underflow and trailing ``inf`` overflow entries."""
        with self._lock:
            counts = list(self._counts)
        bounds = ([float(2.0 ** self.lo)]
                  + [float(2.0 ** (e + 1)) for e in range(self.lo, self.hi)]
                  + [float("inf")])
        return list(zip(bounds, counts))

    def reset(self):
        with self._lock:
            self._counts = [0] * len(self._counts)
            self._sum = 0.0
            self._count = 0

    def snapshot(self):
        with self._lock:
            return {"count": self._count, "sum": self._sum,
                    "lo": self.lo, "hi": self.hi,
                    "counts": list(self._counts)}


class CounterGroup:
    """A fixed-schema family of counters behind ONE lock.

    ``schema`` maps key -> initial value (``0`` int / ``0.0`` float);
    iteration order is preserved in snapshots.  :meth:`update` applies
    any number of deltas (and optional high-water maxima) atomically —
    the multi-key form the engine's streaming tally needs — and
    :meth:`snapshot` returns a plain dict copied under the same lock, so
    a reader can never observe a half-applied update.

    :meth:`set_mirror` installs a scoping hook: a zero-arg provider
    returning another ``CounterGroup`` (or ``None``) consulted on EVERY
    increment, which then receives the same deltas under the same lock —
    the mechanism behind per-tenant engine-counter scoping
    (``bolt_tpu.engine.tenant``): the provider reads a thread-local
    tenant tag and returns that tenant's group, so the global tally and
    the tenant tally can never disagree about one update."""

    __slots__ = ("name", "_lock", "_schema", "_vals", "_mirror")

    def __init__(self, name, lock, schema):
        self.name = name
        self._lock = lock
        self._schema = dict(schema)
        self._vals = dict(schema)
        self._mirror = None

    def set_mirror(self, provider):
        """Install (or clear, with ``None``) the mirror provider — a
        callable returning a sibling ``CounterGroup`` (same schema) or
        ``None``; it runs under the registry lock, so it must only do
        registry lookups (the lock is re-entrant)."""
        self._mirror = provider

    def _mirror_group(self):
        p = self._mirror
        if p is None:
            return None
        m = p()
        return m if m is not self else None     # never self-mirror

    def add(self, key, n=1):
        with self._lock:
            self._vals[key] += n
            m = self._mirror_group()
            if m is not None:
                m._vals[key] += n

    def update(self, _maxima=None, **deltas):
        """Atomically add every ``key=delta``; ``_maxima`` entries keep
        ``max(current, value)`` instead (prefetch-depth high-water)."""
        with self._lock:
            for grp in (self, self._mirror_group()):
                if grp is None:
                    continue
                for k, v in deltas.items():
                    grp._vals[k] += v
                if _maxima:
                    for k, v in _maxima.items():
                        if v > grp._vals[k]:
                            grp._vals[k] = v

    def __getitem__(self, key):
        with self._lock:
            return self._vals[key]

    def __contains__(self, key):
        return key in self._schema

    def keys(self):
        return self._schema.keys()

    def snapshot(self):
        with self._lock:
            return dict(self._vals)

    def reset(self):
        with self._lock:
            self._vals = dict(self._schema)


class Registry:
    """Name -> metric table; one shared re-entrant lock for everything
    registered (see module docstring for why that lock matters)."""

    def __init__(self):
        self._lock = _lockdep().rlock("obs.registry")
        self._metrics = {}

    def _register(self, name, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def counter(self, name, initial=0):
        """Get-or-create a :class:`Counter` (idempotent per name)."""
        return self._register(name,
                              lambda: Counter(name, self._lock, initial))

    def gauge(self, name, initial=0):
        return self._register(name,
                              lambda: Gauge(name, self._lock, initial))

    def histogram(self, name, lo=-20, hi=8):
        return self._register(
            name, lambda: Histogram(name, self._lock, lo=lo, hi=hi))

    def group(self, name, schema):
        """Get-or-create a :class:`CounterGroup` with ``schema``."""
        return self._register(
            name, lambda: CounterGroup(name, self._lock, schema))

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self):
        """One consistent dict over every registered metric: group
        entries flatten to ``"<group>.<key>"``, histograms export their
        summary dict, counters/gauges their value."""
        with self._lock:
            out = {}
            for name, m in self._metrics.items():
                if isinstance(m, CounterGroup):
                    for k, v in m.snapshot().items():
                        out["%s.%s" % (name, k)] = v
                else:
                    out[name] = m.snapshot()
            return out

    def reset(self):
        with self._lock:
            for m in self._metrics.values():
                m.reset()


_REGISTRY = Registry()


def registry():
    """The process-wide default registry (the engine's counters live
    here under the group name ``engine``)."""
    return _REGISTRY


# every thread the package constructs carries one of these name
# prefixes (lint rule BLT108 confines construction to these homes)
_THREAD_PREFIXES = (
    "bolt-serve-worker-",         # serve.py scheduler pool
    "bolt-stream-prefetch",       # stream.py dispenser/prefetch lead
    "bolt-stream-upload-",        # stream.py uploader pool
    "bolt-podwatch-heartbeat",    # podwatch liveness watch
    "bolt-supervisor",            # pod recovery supervisor driver
)


def thread_census():
    """Live bolt-owned worker threads, ``{name: count}`` grouped by
    the blessed thread-name prefixes.  Empty when every pool, watch
    and supervisor has been torn down — the hygiene invariant the
    bench ``--check`` gate and the test suite assert (a leaked thread
    here is a server/executor that skipped its shutdown path)."""
    import threading
    out = {}
    for t in threading.enumerate():
        for p in _THREAD_PREFIXES:
            if t.name.startswith(p):
                key = p.rstrip("-")
                out[key] = out.get(key, 0) + 1
                break
    return out
