"""Structured span tracer: the observability layer's timing backbone.

Every earlier PR grew its own wall-clock bookkeeping — ``engine.py``
timed lower/compile/dispatch, ``stream.py`` timed ingest/compute/wall,
``construct.py`` timed uploads — each with a raw ``time.perf_counter()``
pair feeding a counter.  That gives totals but no *structure*: you can
see that a streamed reduction spent 2 s ingesting, but not whether the
ingest was hidden behind compute, which slab stalled, or how much of a
dispatch was XLA compilation.  This module adds the structure:

* :func:`span` — a context manager / decorator recording a named,
  attributed, *nested* time interval (``obs.span("stream.compute",
  slab=3)``); completed spans land in a bounded in-memory ring.
* :func:`begin` / :func:`end` — the allocation-free hot-path form the
  engine and executor call directly: when tracing is disabled,
  ``begin`` is one module-global check returning ``None`` and ``end``
  returns immediately, so instrumented dispatch paths stay counter-only.
* :func:`event` — a zero-duration instant mark (donation grants,
  strict-gate rejections).
* cross-thread nesting by EXPLICIT handoff: the streaming executor
  captures its run span and passes it as ``parent=`` to the spans its
  prefetch thread begins, so a timeline shows ingest *under* the run
  that caused it even though another thread did the work.
* :func:`clock` — the ONE blessed monotonic timer.  Lint rule BLT106
  (``bolt_tpu/analysis/astlint.py``) forbids raw ``time.perf_counter()``
  bookkeeping outside ``obs/``/``profile.py``; timing code elsewhere in
  the package imports this symbol instead, so every duration in the
  system comes from the same clock and can be correlated on one
  timeline.

Tracing is OFF by default.  :func:`enable` arms it process-wide;
:func:`bolt_tpu.obs.timeline` scopes it around one run and writes a
Chrome trace-event file.  This module imports ONLY the standard library.
"""

import functools
import itertools
import os
import sys
import threading
import time
from collections import deque

# THE timing primitive (see module docstring / lint rule BLT106)
clock = time.perf_counter


def _lockdep():
    """bolt_tpu/_lockdep.py (the ranked lock inventory), loaded by path
    under its canonical name when the package is not imported: this
    module stays stdlib-only standalone, and a later ``bolt_tpu``
    import adopts the SAME witness instance."""
    mod = sys.modules.get("bolt_tpu._lockdep")
    if mod is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "_lockdep.py")
        spec = importlib.util.spec_from_file_location(
            "bolt_tpu._lockdep", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["bolt_tpu._lockdep"] = mod
        spec.loader.exec_module(mod)
    return mod


_RING_DEFAULT = 4096

_ON = False                      # the one hot-path check
_LOCK = _lockdep().lock("obs.trace")   # guards ring + active count
_RING = deque(maxlen=_RING_DEFAULT)
_ACTIVE = 0                      # begun-but-not-ended spans (leak gate)
_IDS = itertools.count(1)
_TLS = threading.local()         # per-thread open-span stack


class Span:
    """One recorded interval: ``name``, ``attrs``, ids and timestamps.

    ``sid`` is the span's id, ``pid`` its parent span's id (0 = root);
    ``tid``/``tname`` identify the recording thread; ``t0``/``t1`` are
    :func:`clock` seconds (``t1`` is ``None`` while open).  ``kind`` is
    ``"S"`` for spans, ``"I"`` for instant events."""

    __slots__ = ("name", "attrs", "sid", "pid", "tid", "tname", "t0",
                 "t1", "kind")

    def __init__(self, name, attrs, sid, pid, tid, tname, t0, kind="S"):
        self.name = name
        self.attrs = attrs
        self.sid = sid
        self.pid = pid
        self.tid = tid
        self.tname = tname
        self.t0 = t0
        self.t1 = None
        self.kind = kind

    def set(self, **attrs):
        """Attach attributes to an open span; chainable."""
        self.attrs.update(attrs)
        return self

    @property
    def duration(self):
        """Seconds from begin to end (``None`` while still open)."""
        return None if self.t1 is None else self.t1 - self.t0

    def __repr__(self):
        dur = "open" if self.t1 is None else "%.6fs" % (self.t1 - self.t0)
        return "<Span %s sid=%d pid=%d %s>" % (self.name, self.sid,
                                               self.pid, dur)


class _NullSpan:
    """What :class:`span` yields while tracing is disabled: every method
    is a no-op, so ``with obs.span(...) as sp: sp.set(...)`` costs
    nothing when off."""

    __slots__ = ()

    def set(self, **attrs):
        return self

    duration = None


_NULL = _NullSpan()


def _stack():
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


def enabled():
    """Is the tracer armed?"""
    return _ON


def enable(ring=None):
    """Arm tracing process-wide.  ``ring`` bounds the completed-span
    buffer (oldest spans fall off); ``None`` means the default capacity
    (4096) — every ``enable()`` states its capacity rather than
    inheriting whatever a previous scope set.  Returns the capacity in
    effect."""
    global _ON, _RING
    want = _RING_DEFAULT if ring is None else max(1, int(ring))
    with _LOCK:
        if want != _RING.maxlen:
            _RING = deque(_RING, maxlen=want)
        _ON = True
        return _RING.maxlen


def disable():
    """Disarm tracing (the ring keeps its completed spans for export)."""
    global _ON
    _ON = False


def clear():
    """Drop every completed span and zero the leak counter (open spans
    begun before ``clear`` still end cleanly — ``end`` tolerates an
    already-cleared ring)."""
    global _ACTIVE
    with _LOCK:
        _RING.clear()
        _ACTIVE = 0


def spans():
    """A consistent snapshot list of the completed-span ring (oldest
    first)."""
    with _LOCK:
        return list(_RING)


def active_count():
    """Spans begun but not yet ended — a nonzero value after a run means
    an instrumented path leaked a span (``scripts/bench_all.py --check``
    gates on this)."""
    with _LOCK:
        return _ACTIVE


def begin(name, parent=None, **attrs):
    """Open a span; the hot-path primitive.  Returns ``None`` when
    tracing is disabled — one module-global check, NO allocation — so
    per-dispatch instrumentation costs nothing until someone arms the
    tracer.  ``parent`` overrides the calling thread's current span (the
    explicit cross-thread handoff; see the streaming executor)."""
    global _ACTIVE
    if not _ON:
        return None
    st = _stack()
    if parent is None and st:
        parent = st[-1]
    th = threading.current_thread()
    sp = Span(name, attrs, next(_IDS), parent.sid if parent else 0,
              th.ident, th.name, clock())
    st.append(sp)
    with _LOCK:
        _ACTIVE += 1
    return sp


def end(sp, **attrs):
    """Close a span returned by :func:`begin` (no-op on ``None``)."""
    global _ACTIVE
    if sp is None:
        return
    sp.t1 = clock()
    if attrs:
        sp.attrs.update(attrs)
    st = getattr(_TLS, "stack", None)
    if st and sp in st:
        # pop through: defensive against misordered ends so the stack
        # can never grow without bound
        while st and st[-1] is not sp:
            st.pop()
        st.pop()
    with _LOCK:
        if _ACTIVE > 0:
            _ACTIVE -= 1
        _RING.append(sp)


def cancel(sp):
    """Abandon an open span: it leaves the thread stack and the leak
    counter but never lands in the ring.  For probes that turn out to
    have observed nothing (e.g. the streaming executor's ingest probe
    that hits end-of-source)."""
    global _ACTIVE
    if sp is None:
        return
    st = getattr(_TLS, "stack", None)
    if st and sp in st:
        while st and st[-1] is not sp:
            st.pop()
        st.pop()
    with _LOCK:
        if _ACTIVE > 0:
            _ACTIVE -= 1


def current():
    """The calling thread's innermost open span (``None`` outside any,
    or while disabled).  Capture it before starting a worker thread and
    pass it to ``begin(..., parent=...)`` there to keep the timeline
    nested across threads."""
    st = getattr(_TLS, "stack", None)
    return st[-1] if st else None


def event(name, **attrs):
    """Record a zero-duration instant mark (donation grants, gate
    rejections); parents under the thread's current span.  Tolerates a
    concurrent ``disable()``: ``begin`` re-checks the flag and may
    return ``None``, in which case the mark is silently dropped rather
    than crashing the instrumented operation."""
    sp = begin(name, **attrs)
    if sp is None:
        return None
    sp.kind = "I"
    end(sp)
    return sp


class span:
    """Context manager AND decorator recording one named interval::

        with obs.span("chunk.map", blocks=n) as sp:
            ...
            sp.set(bytes=out.nbytes)

        @obs.span("analysis.check")
        def check(obj): ...

    When tracing is disabled the body runs against a shared no-op span
    (one small object per ``with``; hot per-dispatch paths use
    :func:`begin`/:func:`end` directly, which allocate nothing)."""

    __slots__ = ("_name", "_attrs", "_parent", "_live")

    def __init__(self, name, parent=None, **attrs):
        self._name = name
        self._attrs = attrs
        self._parent = parent
        self._live = None

    def __enter__(self):
        self._live = begin(self._name, parent=self._parent, **self._attrs)
        return self._live if self._live is not None else _NULL

    def __exit__(self, etype, evalue, tb):
        sp, self._live = self._live, None
        if sp is not None and etype is not None:
            sp.attrs["error"] = etype.__name__
        end(sp)
        return False

    def __call__(self, fn):
        name, attrs = self._name, self._attrs

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(name, **attrs):
                return fn(*args, **kwargs)
        return wrapper


def origin():
    """Process identity for exporters: ``(pid, clock-epoch note)``."""
    return os.getpid()
