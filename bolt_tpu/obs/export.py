"""Exporters for the span ring: Chrome trace-event JSON and a text tree.

* :func:`to_chrome` — the Trace Event Format (``B``/``E`` duration pairs
  + ``i`` instants + thread-name metadata) that ``chrome://tracing`` and
  Perfetto load directly; a streamed reduction exported here SHOWS its
  ingest spans overlapping compute spans on separate thread tracks —
  the visual twin of ``profile.overlap_efficiency()``.
* :func:`report` — an aggregated plain-text tree (span name -> calls,
  total/self seconds, bytes, XLA compiles beneath it) for terminals
  without a trace viewer.
* :func:`timeline` — the one-shot scope: arm tracing, run, write the
  file::

      with bolt_tpu.obs.timeline("/tmp/run.json"):
          bolt.fromiter(blocks, shape, mesh, dtype="f4").sum()

Standard library only (json/contextlib); spans come from
:mod:`bolt_tpu.obs.trace`.
"""

import contextlib
import json
import os

from bolt_tpu.obs import trace as _trace


def _events(spans):
    """Flatten spans into trace events.  Tie-breaking on equal
    timestamps keeps nesting well-formed: ends sort before begins (a
    span may end exactly where the next begins), child ends before
    parent ends (descending sid — children have larger sids), parent
    begins before child begins (ascending sid)."""
    if not spans:
        return []
    pid = os.getpid()
    origin = min(s.t0 for s in spans)
    evs = []
    threads = {}
    for s in spans:
        threads.setdefault(s.tid, s.tname)
        ts = (s.t0 - origin) * 1e6
        args = {k: v for k, v in s.attrs.items()
                if isinstance(v, (int, float, str, bool))}
        if s.kind == "I":
            evs.append((ts, 1, s.sid,
                        {"name": s.name, "ph": "i", "s": "t", "ts": ts,
                         "pid": pid, "tid": s.tid, "args": args}))
            continue
        t1 = s.t1 if s.t1 is not None else s.t0
        te = (t1 - origin) * 1e6
        evs.append((ts, 1, s.sid,
                    {"name": s.name, "ph": "B", "ts": ts, "pid": pid,
                     "tid": s.tid, "args": args}))
        evs.append((te, 0, -s.sid,
                    {"name": s.name, "ph": "E", "ts": te, "pid": pid,
                     "tid": s.tid}))
    evs.sort(key=lambda e: e[:3])
    out = [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": tname}} for tid, tname in threads.items()]
    out.extend(e[3] for e in evs)
    return out


def to_chrome(spans=None, path=None):
    """Chrome trace-event document for ``spans`` (default: the current
    ring).  Returns the document dict; writes JSON to ``path`` when
    given."""
    doc = {"traceEvents": _events(_trace.spans() if spans is None
                                  else spans),
           "displayTimeUnit": "ms"}
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    return doc


class _Agg:
    __slots__ = ("count", "total", "self_s", "nbytes", "compiles",
                 "children")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.self_s = 0.0
        self.nbytes = 0
        self.compiles = 0
        self.children = {}


def _aggregate(spans):
    idx = {s.sid: s for s in spans}
    kids = {}
    roots = []
    for s in spans:
        if s.pid and s.pid in idx:
            kids.setdefault(s.pid, []).append(s)
        else:
            roots.append(s)

    def visit(s, node_map):
        agg = node_map.get(s.name)
        if agg is None:
            agg = node_map[s.name] = _Agg()
        d = s.duration or 0.0
        agg.count += 1
        agg.total += d
        ch = kids.get(s.sid, ())
        # self time subtracts only SAME-thread children: spans handed
        # off to another thread (prefetch ingest under a stream run)
        # overlap their parent's own work rather than displacing it
        agg.self_s += d - sum(c.duration or 0.0 for c in ch
                              if c.tid == s.tid)
        b = s.attrs.get("bytes")
        if isinstance(b, (int, float)):
            agg.nbytes += int(b)
        n_comp = 1 if s.name == "engine.compile" else 0
        for c in ch:
            n_comp += visit(c, agg.children)
        agg.compiles += n_comp
        return n_comp

    top = {}
    for r in roots:
        visit(r, top)
    return top


def _human_bytes(n):
    if not n:
        return ""
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return ("%d%s" % (n, unit)) if unit == "B" \
                else ("%.1f%s" % (n, unit))
        n /= 1024.0
    return ""


def report(spans=None):
    """Aggregated text tree over the completed spans: per name (within
    its parent) the call count, total and self wall seconds, summed
    ``bytes`` attrs, and the number of XLA compiles
    (``engine.compile`` spans) at or beneath it."""
    sp = _trace.spans() if spans is None else spans
    if not sp:
        return "(no spans recorded — arm tracing with bolt_tpu.obs." \
               "enable() or the obs.timeline(path) scope)"
    top = _aggregate(sp)
    lines = ["%-44s %7s %10s %10s %10s %8s"
             % ("span", "calls", "total_s", "self_s", "bytes",
                "compiles")]

    def render(node_map, depth):
        for name, agg in sorted(node_map.items(),
                                key=lambda kv: -kv[1].total):
            label = "  " * depth + name
            lines.append("%-44s %7d %10.4f %10.4f %10s %8d"
                         % (label[:44], agg.count, agg.total, agg.self_s,
                            _human_bytes(agg.nbytes), agg.compiles))
            render(agg.children, depth + 1)

    render(top, 0)
    return "\n".join(lines)


def trace_arg(argv):
    """Parse the conventional ``--trace out.json`` / ``--trace=out.json``
    CLI flag (the ONE parser both ``scripts/bench_all.py`` and
    ``scripts/perf_regress.py`` use); returns the path or ``None``."""
    for i, a in enumerate(argv):
        if a == "--trace" and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith("--trace="):
            return a.split("=", 1)[1]
    return None


@contextlib.contextmanager
def timeline(path, ring=None):
    """Arm tracing, run the body, write a Chrome trace to ``path`` —
    even when the body raises (the timeline of a failed run is usually
    the point).  Restores the tracer's previous armed/disarmed state;
    the ring keeps the run's spans for :func:`report` afterwards."""
    was_on = _trace.enabled()
    _trace.clear()
    if ring is not None:
        _trace.enable(ring=ring)
    else:
        _trace.enable()
    try:
        yield
    finally:
        if not was_on:
            _trace.disable()
        to_chrome(path=path)
