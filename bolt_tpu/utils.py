"""Shared argument-normalisation helpers and test utilities.

Reference parity: ``bolt/utils.py`` — symbols ``tupleize``, ``listify``,
``argpack``, ``inshape``, ``slicify``, ``allclose``, ``iterexpand``,
``istransposeable``, ``isreshapeable``.  (Symbol-level citations only: the
reference mount was empty this round — see SURVEY.md §0.)
"""

from numbers import Integral

import numpy as np


def tupleize(arg):
    """Coerce an argument to a tuple.

    Scalars become 1-tuples; lists/ranges/ndarrays become tuples; a 1-tuple
    wrapping a tuple/list (as produced by ``f(*args)`` with ``f((0, 1))``)
    is unwrapped.  ``None`` passes through.

    Reference: ``bolt/utils.py :: tupleize``.
    """
    if arg is None:
        return None
    if isinstance(arg, (tuple, list, range, np.ndarray)):
        if isinstance(arg, tuple) and len(arg) == 1 and isinstance(arg[0], (tuple, list, range, np.ndarray)):
            return tuple(arg[0])
        return tuple(arg)
    return (arg,)


def listify(arg):
    """Like :func:`tupleize` but returns a list.

    Reference: ``bolt/utils.py :: listify``.
    """
    t = tupleize(arg)
    return None if t is None else list(t)


def argpack(args):
    """Normalise ``*args``-style shape/axis arguments.

    Supports both ``f(1, 2, 3)`` and ``f((1, 2, 3))`` calling conventions.

    Reference: ``bolt/utils.py :: argpack``.
    """
    if len(args) == 1 and isinstance(args[0], (tuple, list, range, np.ndarray)):
        return tuple(args[0])
    return tuple(args)


def inshape(shape, axes):
    """Validate that every axis index is within ``range(len(shape))``.

    Reference: ``bolt/utils.py :: inshape``.
    """
    ndim = len(shape)
    for a in tupleize(axes):
        if not isinstance(a, Integral):
            raise ValueError("axis %r is not an integer" % (a,))
        if a < 0 or a >= ndim:
            raise ValueError(
                "axis %d out of bounds for array with %d dimensions" % (a, ndim))


def iterexpand(arg, n):
    """Broadcast a scalar to an ``n``-tuple, or validate an ``n``-sequence.

    Reference: ``bolt/utils.py :: iterexpand``.
    """
    if isinstance(arg, (tuple, list, np.ndarray)):
        t = tuple(arg)
        if len(t) != n:
            raise ValueError(
                "sequence of length %d cannot be broadcast to length %d" % (len(t), n))
        return t
    return (arg,) * n


def slicify(slc, dim):
    """Normalise a single-axis index to a canonical form.

    * ``slice`` → ``slice`` with concrete, in-bounds ``start/stop/step``
    * integer → ``slice(i, i+1, 1)`` (negative values wrapped); the caller is
      responsible for tracking the implied dimension squeeze
    * list / integer ndarray → 1-d ``np.ndarray`` of wrapped, validated indices
    * boolean ndarray of length ``dim`` → ``np.ndarray`` of selected indices

    Reference: ``bolt/utils.py :: slicify``.
    """
    if isinstance(slc, slice):
        start, stop, step = slc.indices(dim)
        if step < 0 and stop < 0:
            # a computed stop of -1 means "past the beginning"; keep it None
            # so downstream indexing doesn't wrap it to dim-1
            stop = None
        return slice(start, stop, step)
    if isinstance(slc, (Integral, np.integer)):
        i = int(slc)
        if i < 0:
            i += dim
        if i < 0 or i >= dim:
            raise IndexError("index %d out of bounds for axis of size %d" % (int(slc), dim))
        return slice(i, i + 1, 1)
    if isinstance(slc, (list, tuple, np.ndarray)):
        arr = np.asarray(slc)
        if arr.dtype == bool:
            if arr.ndim != 1 or arr.shape[0] != dim:
                raise IndexError(
                    "boolean index of shape %s does not match axis of size %d" % (arr.shape, dim))
            return np.nonzero(arr)[0]
        if arr.ndim != 1:
            # the per-axis orthogonal take contract is 1-d index lists
            # (like the bool branch above); a multi-d take would silently
            # shift every later axis
            raise IndexError(
                "per-axis advanced index must be 1-d, got shape %s"
                % (arr.shape,))
        arr = arr.astype(np.int64)
        arr = np.where(arr < 0, arr + dim, arr)
        if arr.size and (arr.min() < 0 or arr.max() >= dim):
            raise IndexError("index out of bounds for axis of size %d" % dim)
        return arr
    raise ValueError("cannot index axis with %r" % (slc,))


def normalize_index(index, shape):
    """Normalise a full ``__getitem__`` index against ``shape`` to
    ``(norm, squeezed)``: one entry per axis, each a canonical ``slice`` or
    a 1-d integer ``np.ndarray`` (advanced), with ``squeezed`` listing the
    axes indexed by scalars (to drop from the result).  Expands a single
    ``Ellipsis``, pads missing axes with full slices, and treats 0-d
    integer arrays (e.g. ``np.argmax`` results) as scalars so a per-axis
    ``take`` never silently shifts later axes.

    Shared by BOTH backends' multiple-advanced-index paths — one
    normalisation, one semantics (reference: the ``_getbasic``/
    ``_getadvanced`` split in ``bolt/spark/array.py``).
    """
    idx = index if isinstance(index, tuple) else (index,)
    ndim = len(shape)
    ell = [n for n, i in enumerate(idx) if i is Ellipsis]
    if len(ell) > 1:
        raise IndexError("an index can only have a single ellipsis ('...')")
    if ell:
        pos = ell[0]
        fill = ndim - (len(idx) - 1)
        if fill < 0:
            raise ValueError("too many indices for %d-d array" % ndim)
        idx = idx[:pos] + (slice(None),) * fill + idx[pos + 1:]
    if len(idx) > ndim:
        raise ValueError("too many indices for %d-d array" % ndim)
    idx = idx + (slice(None),) * (ndim - len(idx))
    squeezed = []
    norm = []
    for ax, (i, dim) in enumerate(zip(idx, shape)):
        if isinstance(i, np.ndarray) and i.ndim == 0 and i.dtype != bool:
            i = int(i)
        if isinstance(i, (int, np.integer)):
            squeezed.append(ax)
        norm.append(slicify(i, dim))
    return norm, squeezed


def istransposeable(new, old):
    """True if ``new`` is a permutation of the axes ``old``.

    Reference: ``bolt/utils.py :: istransposeable``.
    """
    new, old = tupleize(new), tupleize(old)
    return sorted(new) == sorted(old)


def isreshapeable(new, old):
    """True if shape ``new`` has the same number of elements as ``old``.

    Reference: ``bolt/utils.py :: isreshapeable``.
    """
    new, old = tupleize(new), tupleize(old)
    return int(np.prod(new, dtype=np.int64)) == int(np.prod(old, dtype=np.int64))


def allclose(a, b, rtol=1e-5, atol=1e-8):
    """Shape-and-value comparison used throughout the test suite.

    Reference: ``bolt/utils.py :: allclose`` (shape equality + ``np.allclose``).
    """
    a = np.asarray(a)
    b = np.asarray(b)
    return a.shape == b.shape and np.allclose(a, b, rtol=rtol, atol=atol)


def prod(shape):
    """Integer product of a shape tuple (1 for the empty shape)."""
    return int(np.prod(tupleize(shape) or (1,), dtype=np.int64))


def get_kv_axes(shape, axes):
    """Split the axis indices of ``shape`` into (key axes, value axes),
    key axes being those named in ``axes``.

    Reference: ``bolt/spark/utils.py :: get_kv_axes``.
    """
    axes = sorted(tupleize(axes))
    inshape(shape, axes)
    kaxes = tuple(axes)
    vaxes = tuple(i for i in range(len(shape)) if i not in axes)
    return kaxes, vaxes


def get_kv_shape(shape, axes):
    """Split ``shape`` into (key shape, value shape) for the key axes
    ``axes``.

    Reference: ``bolt/spark/utils.py :: get_kv_shape``.
    """
    kaxes, vaxes = get_kv_axes(shape, axes)
    return (tuple(shape[a] for a in kaxes), tuple(shape[a] for a in vaxes))


def chunk_axes(vshape, axis):
    """Normalize a chunk ``axis`` request against a value shape: ``None``
    means every value axis; out-of-range axes raise (shared by both
    backends' ``chunk``; reference: the axis handling of
    ``bolt/spark/chunk.py :: ChunkedArray._chunk``)."""
    nv = len(vshape)
    if axis is None:
        return tuple(range(nv))
    axes = tuple(sorted(tupleize(axis)))
    if len(set(axes)) != len(axes):
        raise ValueError("chunk axes must be unique")
    for a in axes:
        if a < 0 or a >= nv:
            raise ValueError(
                "chunk axis %d out of range for %d value axes" % (a, nv))
    return axes


def chunk_align(vshape, axis, size, padding):
    """Normalize a chunk request to sorted axes WITHOUT breaking the
    pairing between each named axis and its per-axis ``size``/``padding``
    entry: ``chunk(size=(2, 9), axis=(1, 0))`` means size 2 on value axis
    1 and 9 on axis 0, whatever order downstream planning iterates in.
    Returns ``(axes_sorted, size, padding)`` with sequence-valued
    ``size``/``padding`` reordered to match ``axes_sorted``."""
    if axis is None:
        return chunk_axes(vshape, None), size, padding
    axes_given = tuple(tupleize(axis))
    axes = chunk_axes(vshape, axes_given)  # validates + sorts
    order = sorted(range(len(axes_given)), key=lambda i: axes_given[i])

    def reorder(arg):
        if isinstance(arg, (tuple, list, np.ndarray)):
            t = iterexpand(arg, len(axes))
            return tuple(t[i] for i in order)
        return arg

    size = size if isinstance(size, str) else reorder(size)
    padding = None if padding is None else reorder(padding)
    return axes, size, padding


def iter_record_blocks(blocks, shape, dtype):
    """Yield ``(lo, hi, block)`` from an iterable of consecutive record
    blocks (key-axes-first layout, concatenated along the first axis),
    each validated against ``shape`` and cast to ``dtype``; together the
    blocks must cover ``shape`` exactly.  The ONE ``fromiter`` block
    contract, shared by the local backend and the streaming executor so
    their error behavior cannot drift."""
    n = shape[0]
    rest = tuple(shape[1:])
    lo = 0
    for block in iter(blocks):
        block = np.asarray(block, dtype=dtype)
        if block.ndim != len(shape) or block.shape[1:] != rest:
            raise ValueError(
                "fromiter block has shape %s; expected (k,) + %s"
                % (block.shape, rest))
        hi = lo + block.shape[0]
        if hi > n:
            raise ValueError(
                "fromiter blocks overrun the declared shape: %d of %d "
                "records already consumed" % (hi, n))
        yield lo, hi, block
        lo = hi
    if lo != n:
        raise ValueError(
            "fromiter blocks cover only %d of %d declared records"
            % (lo, n))


def check_value_shape(hint, inferred):
    """Validate an explicit ``value_shape`` hint against the inferred
    per-record output shape (shared by every backend's array/chunked/
    stacked map)."""
    if hint is None or inferred is None:
        return
    if tuple(tupleize(hint)) != tuple(inferred):
        raise ValueError("value_shape %s does not match inferred %s"
                         % (tuple(tupleize(hint)), tuple(inferred)))


def assignment_index(norm, shape, squeezed=()):
    """Index tuple that ASSIGNS to the region a ``__getitem__`` with the
    same index would READ — valid for numpy in-place assignment and
    jax's ``.at[...]`` alike, so the value broadcasts against exactly
    the getitem result shape on both backends.

    Scalar-indexed axes (``squeezed``) become bare ints: they drop out
    of the region like numpy assignment (keeping them as length-1 dims
    would reject a value shaped like the getitem result whenever a
    non-1 dim precedes the scalar axis).  When the index is basic, or a
    single advanced entry with no scalars alongside, the zipped and
    orthogonal conventions coincide and the normalized entries pass
    through (cheap basic/single-gather scatter).  Otherwise EVERY
    non-scalar axis opens into an ``np.ix_``-style broadcast mesh: all
    entries are then advanced and adjacent under numpy's rules (scalars
    are 0-d advanced), so region dims follow axis order — the
    orthogonal cross product, matching ``__getitem__``.  Shared by both
    backends' ``set``/``__setitem__`` so the semantics cannot drift."""
    arrays = [s for s in norm if isinstance(s, np.ndarray)]
    if len(arrays) <= 1 and not (arrays and squeezed):
        return tuple(int(s.start) if ax in squeezed else s
                     for ax, s in enumerate(norm))
    meshed = [ax for ax in range(len(norm)) if ax not in squeezed]
    k = len(meshed)
    out = []
    for ax, (s, dim) in enumerate(zip(norm, shape)):
        if ax in squeezed:
            out.append(int(s.start))
            continue
        a = np.arange(dim)[s] if isinstance(s, slice) else s
        pos = meshed.index(ax)
        out.append(a.reshape((1,) * pos + (a.size,) + (1,) * (k - pos - 1)))
    return tuple(out)


def check_q(q):
    """Validate a quantile ``q`` (scalar or 1-d, every value in [0, 1])
    and return it as a float64 ndarray — shared by both backends so the
    contract cannot drift.  NaN is rejected explicitly: on the TPU
    backend q is a traced jit argument, so a NaN that slipped past
    validation would silently produce an all-NaN result instead of this
    error."""
    try:
        qarr = np.asarray(q, dtype=np.float64)
    except (TypeError, ValueError):
        raise ValueError(
            "q must be a scalar or 1-d array of values in [0, 1], got %r"
            % (q,))
    if qarr.ndim > 1:
        raise ValueError("q must be a scalar or 1-d, got %d-d" % qarr.ndim)
    if qarr.size and not (np.all(qarr >= 0.0) and np.all(qarr <= 1.0)):
        raise ValueError("q must be in [0, 1], got %r" % (q,))
    return qarr


def chunk_plan(vshape, itemsize, size, axes, padding=None):
    """Per-value-axis chunk sizes.  A string ``size`` is a per-block
    megabyte budget (the reference's ``size='150'`` default) — the largest
    chunkable axis is halved until the block fits; an int/tuple gives
    explicit chunk sizes for ``axes`` (reference:
    ``bolt/spark/chunk.py :: ChunkedArray._chunk`` plan computation).

    ``padding`` (the halo widths, paired with ``axes``) floors the budget
    halving at ``halo + 1`` per axis, so a wide filter under a tight
    budget gets a slightly-over-budget plan instead of an invalid one
    whose halo exceeds its chunk; explicit int sizes are the user's exact
    request and stay strictly validated downstream."""
    plan = list(vshape)
    floor = [1] * len(vshape)
    if padding is not None:
        for a, p in zip(axes, iterexpand(padding, len(axes))):
            floor[a] = min(int(p) + 1, vshape[a])
    if isinstance(size, str):
        budget = float(size) * 1e6
        while (prod(plan) * itemsize > budget
               and any(plan[a] > floor[a] for a in axes)):
            a = max(axes, key=lambda i: plan[i] - floor[i])
            plan[a] = max(-(-plan[a] // 2), floor[a])
    else:
        sizes = iterexpand(size, len(axes))
        for a, s in zip(axes, sizes):
            if s < 1:
                raise ValueError("chunk size must be >= 1, got %d" % s)
            plan[a] = min(int(s), vshape[a])
    return plan


def chunk_pad(plan, axes, padding, vshape):
    """Per-value-axis halo widths; a halo must be smaller than its chunk
    (reference: ``ChunkedArray._chunk`` padding validation) — except on an
    UNCHUNKED axis (one block spanning the whole axis), where the halo
    only ever clips at the array edges and any width is harmless (a wider-
    than-axis filter radius must still run)."""
    nv = len(vshape)
    pad = [0] * nv
    if padding is not None:
        pads = iterexpand(padding, len(axes))
        for a, p in zip(axes, pads):
            if p < 0 or (p >= plan[a] > 0 and plan[a] < vshape[a]):
                raise ValueError(
                    "padding %d must be smaller than the chunk size %d "
                    "on axis %d — a halo (e.g. a filter's width/sigma "
                    "radius) cannot exceed its block; pass a larger "
                    "size= (chunk budget or explicit per-axis sizes)"
                    % (p, plan[a], a))
            pad[a] = int(p)
    return pad


def code_token(func):
    """A process-stable identity token for a user callable: its name
    plus a digest of its bytecode and constants (nested code objects
    recursed).  Two lambdas with different bodies get DIFFERENT tokens
    — unlike ``__name__``, which calls every lambda ``<lambda>`` — so
    checkpoint fingerprints built from tokens refuse a resume across an
    edited pipeline.  Callables without bytecode (ufuncs, builtins,
    callable objects) fall back to their qualified name.  Data captured
    in a closure is NOT part of the token (no checkpoint system can
    hash the source's data; feeding a matching checkpoint the same
    bytes is the caller's contract, as with any resume format)."""
    import hashlib
    code = getattr(func, "__code__", None)
    name = getattr(func, "__name__", None) or type(func).__name__
    if code is None:
        return name

    def feed(h, c):
        h.update(c.co_code)
        for const in c.co_consts:
            if hasattr(const, "co_code"):
                feed(h, const)
            else:
                h.update(repr(const).encode())

    h = hashlib.sha1()
    feed(h, code)
    return "%s#%s" % (name, h.hexdigest()[:12])


def chain_retry_step(exc, prev, attempt, allowed, what, knob):
    """The ONE retry-chaining policy, shared by the streaming
    executor's per-slab ingest retries and the serve scheduler's
    per-submit job retries: chain this attempt's ``exc`` to the one
    before (oldest-first, back to the original failure) and either
    hand it back as the next attempt's ``prev`` (when another attempt
    is ``allowed``) or raise — a pointed chained error when retries
    were consumed, the ORIGINAL exception untouched at budget 0."""
    if prev is not None and exc.__cause__ is None and exc is not prev:
        exc.__cause__ = prev
    if allowed:
        return exc
    if attempt:
        raise RuntimeError(
            "%s failed after %d retries (%s); the final attempt's "
            "error is chained below, each attempt chained to the one "
            "before" % (what, attempt, knob)) from exc
    raise exc


def load_script(name):
    """Load ``scripts/<name>.py`` from this repo by path, WITHOUT
    importing it as a package module (scripts are not a package, and
    several — the multihost cluster harness, chaos_run — are shared by
    tests, bench_all, perf_regress and examples alike).  One loader
    instead of per-caller importlib boilerplate."""
    import importlib.util
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "scripts", "%s.py" % name)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
