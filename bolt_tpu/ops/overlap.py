"""Halo-overlap mapping and separable smoothing over the value axes.

The reference's chunk ``padding`` exists for exactly this workload: its
ecosystem (Thunder) ran spatial filters over image stacks by chunking the
spatial axes with a halo so each block sees its neighbours' boundary rows
(``bolt/spark/chunk.py :: ChunkedArray`` padding — symbol-level citation,
SURVEY.md §0).  :func:`map_overlap` packages that pattern (dask names the
same idiom ``map_overlap``); :func:`smooth` builds the canonical consumer —
a separable boxcar filter — on top of it.

Both work on either backend: on TPU the chunked map is one compiled SPMD
program and halos ride GSPMD's neighbour collectives; locally the same
contract runs on NumPy (the oracle).
"""

import numpy as np
import jax.numpy as jnp

from bolt_tpu.utils import chunk_axes, iterexpand, tupleize

_PAD_MODES = ("constant", "reflect", "edge")


def map_overlap(b, func, depth, axis=None, size="150", value_shape=None,
                dtype=None):
    """Apply ``func`` to halo-padded blocks of the value axes and
    reassemble: ``b.chunk(size, axis, padding=depth).map(func).unchunk()``.

    ``depth`` is the halo width (scalar, or per-axis paired with ``axis``
    in the order given); ``func`` must
    preserve the block shape (the padded-map contract — the halo is
    trimmed after).  Each block sees ``depth`` extra elements from its
    neighbours on the chunked axes, clipped at the array edges, so
    stencil/filter funcs compute correct values at interior block
    boundaries without any global pass.
    """
    c = b.chunk(size=size, axis=axis, padding=depth)
    return c.map(func, value_shape=value_shape, dtype=dtype).unchunk()


def _box1d(x, ax, w, mode, xp):
    """Windowed mean of width ``w`` along ``ax`` ('same' size, boundary per
    ``mode``) — the sum of ``w`` shifted slices of the padded array, which
    is exact (no cumsum cancellation) for the small widths filters use."""
    h = w // 2
    length = x.shape[ax]
    pad = [(0, 0)] * x.ndim
    pad[ax] = (h, h)
    xpad = xp.pad(x, pad, mode=mode)
    acc = None
    for off in range(w):
        sl = [slice(None)] * x.ndim
        sl[ax] = slice(off, off + length)
        piece = xpad[tuple(sl)]
        acc = piece if acc is None else acc + piece
    return acc / w


def smooth(b, width, axis=None, size="150", mode="constant"):
    """Separable moving-average (boxcar) filter along value axes — the
    Thunder-style spatial smoothing workload, one halo-padded blockwise
    program per backend.

    ``width``: odd window (scalar or per-``axis``); ``axis``: the value
    axes to filter (default: all); ``size``: chunk plan for the blockwise
    execution; ``mode``: boundary handling at the ARRAY edges —
    ``'constant'`` (zeros, numpy ``convolve 'same'`` semantics),
    ``'reflect'`` or ``'edge'``.  Boundary modes stay exact under
    chunking because an edge block's clipped halo ends exactly at the
    array boundary.  Floating inputs keep their dtype; integers promote
    through the mean's true division.
    """
    if mode not in _PAD_MODES:
        raise ValueError("mode must be one of %s, got %r"
                         % (_PAD_MODES, mode))
    split = b.split if b.mode == "tpu" else 1
    vshape = b.shape[split:]
    # widths bind to the axes in the ORDER the caller gave them; the
    # chunk layer re-sorts (axis, depth) pairs together via chunk_align
    axes = (chunk_axes(vshape, None) if axis is None
            else tuple(tupleize(axis)))
    chunk_axes(vshape, axes)  # validate (range, uniqueness)
    widths = [int(w) for w in iterexpand(width, len(axes))]
    for w in widths:
        if w < 1 or w % 2 == 0:
            raise ValueError("smoothing width must be odd and >= 1, got %d" % w)
    depth = tuple(w // 2 for w in widths)

    def boxfilter(blk):
        xp = np if isinstance(blk, np.ndarray) else jnp
        out = blk
        for ax, w in zip(axes, widths):
            if w > 1:
                out = _box1d(out, ax, w, mode, xp)
        return out

    return map_overlap(b, boxfilter, depth, axis=axes, size=size)
