"""Halo-overlap mapping and separable smoothing over the value axes.

The reference's chunk ``padding`` exists for exactly this workload: its
ecosystem (Thunder) ran spatial filters over image stacks by chunking the
spatial axes with a halo so each block sees its neighbours' boundary rows
(``bolt/spark/chunk.py :: ChunkedArray`` padding — symbol-level citation,
SURVEY.md §0).  :func:`map_overlap` packages that pattern (dask names the
same idiom ``map_overlap``); :func:`smooth` builds the canonical consumer —
a separable boxcar filter — on top of it.

Both work on either backend: on TPU the chunked map is one compiled SPMD
program and halos ride GSPMD's neighbour collectives; locally the same
contract runs on NumPy (the oracle).
"""

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp

from bolt_tpu.utils import chunk_axes, iterexpand, tupleize

# boundary-mode names follow numpy.pad; scipy.ndimage's names are accepted
# as aliases (scipy 'reflect' repeats the edge sample = np 'symmetric';
# scipy 'mirror' excludes it = np 'reflect'; scipy 'nearest' = np 'edge')
_PAD_MODES = ("constant", "reflect", "edge", "symmetric")
_MODE_ALIASES = {"mirror": "reflect", "nearest": "edge"}


def _canon_mode(mode):
    mode = _MODE_ALIASES.get(mode, mode)
    if mode not in _PAD_MODES:
        raise ValueError("mode must be one of %s (or scipy aliases %s), "
                         "got %r" % (_PAD_MODES, tuple(_MODE_ALIASES), mode))
    return mode


def map_overlap(b, func, depth, axis=None, size="150", value_shape=None,
                dtype=None, shard=None):
    """Apply ``func`` to halo-padded blocks of the value axes and
    reassemble: ``b.chunk(size, axis, padding=depth).map(func).unchunk()``.

    ``depth`` is the halo width (scalar, or per-axis paired with ``axis``
    in the order given); ``func`` must
    preserve the block shape (the padded-map contract — the halo is
    trimmed after).  Each block sees ``depth`` extra elements from its
    neighbours on the chunked axes, clipped at the array edges, so
    stencil/filter funcs compute correct values at interior block
    boundaries without any global pass.

    ``shard`` (TPU backend only) splits chunked VALUE axes across mesh
    axes — the sequence-parallel regime, for contiguous axes too long
    for one device: a mesh-axis name (applied to the first chunked
    axis) or a ``{value_axis: mesh_axis}`` dict.  Halos then ride
    GSPMD's inserted neighbour collectives (ICI/DCN).
    """
    if shard is not None and b.mode != "tpu":
        raise ValueError("shard= needs the tpu backend (a mesh); "
                         "mode=%r has no mesh axes" % (b.mode,))
    c = b.chunk(size=size, axis=axis, padding=depth)
    if shard is not None:
        if isinstance(shard, dict):
            for va, name in sorted(shard.items()):
                c = c.shard(name, axis=va)
        else:
            c = c.shard(shard)
    return c.map(func, value_shape=value_shape, dtype=dtype).unchunk()


def _odd_widths(width, n):
    """Validate per-axis window widths: odd and >= 1 (shared by the whole
    filter family — a symmetric window needs an integer radius)."""
    widths = [int(w) for w in iterexpand(width, n)]
    for w in widths:
        if w < 1 or w % 2 == 0:
            raise ValueError("filter width must be odd and >= 1, got %d" % w)
    return widths


def _halo_pad(x, axes, widths, mode, xp):
    """Pad ``x`` by each window's radius on its axis with boundary
    ``mode`` (the shared pad step before any shifted-slice window)."""
    pad = [(0, 0)] * x.ndim
    for ax, w in zip(axes, widths):
        pad[ax] = (w // 2, w // 2)
    return xp.pad(x, pad, mode=mode)


def _filter1d(x, ax, taps, mode, xp):
    """Correlation of ``x`` with the 1-d ``taps`` along ``ax`` ('same'
    size, boundary per ``mode``) — the weighted sum of ``len(taps)``
    shifted slices of the padded array, which is exact (no cumsum
    cancellation) for the small widths filters use."""
    w = len(taps)
    length = x.shape[ax]
    xpad = _halo_pad(x, [ax], [w], mode, xp)
    acc = None
    for off in range(w):
        sl = [slice(None)] * x.ndim
        sl[ax] = slice(off, off + length)
        piece = xpad[tuple(sl)] * taps[off]
        acc = piece if acc is None else acc + piece
    return acc


@lru_cache(maxsize=256)
def _sepfilter_fn(taps_key, axes, mode):
    """Memoised block function for the separable filters: identical
    (taps, axes, mode) return the SAME callable object, so the chunked
    map's jit cache (keyed on function identity) hits and repeated
    filter calls dispatch in milliseconds instead of recompiling."""
    def sepfilter(blk):
        xp = np if isinstance(blk, np.ndarray) else jnp
        out = blk
        for ax, taps in zip(axes, taps_key):
            if len(taps) > 1 or taps[0] != 1.0:  # skip only the identity
                out = _filter1d(out, ax, taps, mode, xp)
        return out
    return sepfilter


def _separable_filter(b, taps_list, axes, size, mode, shard=None,
                      precision=None):
    """Shared core of :func:`smooth`/:func:`convolve`/:func:`gaussian`:
    one program applying a 1-d tap filter per axis.

    On the TPU backend (no ``shard=``) the filter runs as ONE
    whole-array program whose per-axis correlations are Pallas window
    kernels where the plan allows — each block reads HBM once and
    windows in VMEM, where the XLA shifted-slice form re-reads the
    operand once per tap (measured 112 → ~40 ms for a 9-tap 2-axis
    gaussian on 2.1 GB; round-3).  Anything the kernel can't serve
    (unplannable geometry, non-float dtype, a failed compile on this
    toolchain) falls back to the halo-chunked machinery, which also
    serves ``shard=`` (sequence-parallel) and the local oracle."""
    from bolt_tpu._precision import resolve
    pr = resolve(precision)
    mode = _canon_mode(mode)
    depth = tuple(len(t) // 2 for t in taps_list)
    taps_key = tuple(tuple(float(t) for t in taps) for taps in taps_list)
    if b.mode == "tpu" and shard is None:
        out = _whole_array_sepfilter(b, taps_key, tuple(axes), mode, pr)
        if out is not None:
            return out
    sepfilter = _sepfilter_fn(taps_key, tuple(axes), mode)
    return map_overlap(b, sepfilter, depth, axis=axes, size=size,
                       shard=shard)


def _whole_array_sepfilter(b, taps_key, axes, mode, precision="highest"):
    """ONE compiled program filtering every requested axis of the full
    (sharded) array — Pallas window kernel per axis, shifted-slice for
    any axis the plan can't serve.  Returns None (caller takes the
    chunked path) when no axis can use the kernel or the compile fails
    on this toolchain (the kernel's Mosaic surface varies by version;
    a flaky remote-compile must degrade, not crash)."""
    import numpy as _np
    from bolt_tpu.ops import kernels
    from bolt_tpu.tpu.array import (_cached_jit, _chain_apply, _check_live,
                                    _constrain)
    split = b.split
    active = [(split + a, taps) for a, taps in zip(axes, taps_key)
              if len(taps) > 1 or taps[0] != 1.0]
    if not active:
        # identity filter: a NEW wrapper, never the input itself (the
        # in-place surface — sort, wrapper rebinds — must not alias)
        return b._clone()
    itemsize = _np.dtype(b.dtype).itemsize
    if not _np.issubdtype(_np.dtype(b.dtype), _np.floating):
        return None
    if not any(kernels.sepfilter_capable(b.shape, itemsize, g, len(t),
                                         mode=mode)
               for g, t in active):
        return None
    mesh = b.mesh
    base, funcs = b._chain_parts()
    key = ("sepfilter", taps_key, axes, mode, funcs, base.shape,
           str(base.dtype), split, mesh, precision)
    if key in _SEPFILTER_FAILED:
        return None                        # this toolchain said no once

    def build():
        def run(d):
            x = _chain_apply(funcs, split, d)
            for g, taps in active:
                y = kernels.sepfilter1d(x, taps, g, mode=mode,
                                        precision=precision)
                x = y if y is not None else _filter1d(x, g, taps, mode, jnp)
            return _constrain(x, mesh, split)
        return jax.jit(run)

    try:
        fn = _cached_jit(key, build)
        out = fn(_check_live(base))
    except Exception:
        # a Mosaic/remote-compile failure: remember it (retrying would
        # pay the failed compile EVERY call), purge the cached program,
        # and let the chunked path serve this geometry from now on
        from bolt_tpu.tpu.array import _JIT_CACHE
        _JIT_CACHE.pop(key, None)
        _SEPFILTER_FAILED.add(key)
        return None
    return b._wrap(out, split)


# geometries whose kernel program failed to compile on this toolchain —
# they take the chunked path without re-paying the failed compile
_SEPFILTER_FAILED = set()


def _filter_axes(b, axis):
    """Value axes for a filtering op, in the caller's order (widths/taps
    bind to the axes as given; the chunk layer re-sorts (axis, depth)
    pairs together via ``chunk_align``)."""
    split = b.split if b.mode == "tpu" else 1
    vshape = b.shape[split:]
    axes = (chunk_axes(vshape, None) if axis is None
            else tuple(tupleize(axis)))
    chunk_axes(vshape, axes)  # validate (range, uniqueness)
    return axes


def smooth(b, width, axis=None, size="150", mode="constant", shard=None,
           precision=None):
    """Separable moving-average (boxcar) filter along value axes — the
    Thunder-style spatial smoothing workload, one halo-padded blockwise
    program per backend.

    ``width``: odd window (scalar or per-``axis``, paired in the order
    given); ``axis``: the value axes to filter (default: all); ``size``:
    chunk plan for the blockwise execution; ``mode``: boundary handling
    at the ARRAY edges — ``'constant'`` (zeros, numpy ``convolve 'same'``
    semantics), ``'reflect'``, ``'edge'`` or ``'symmetric'`` (numpy.pad
    names; scipy's ``'mirror'``/``'nearest'`` accepted as aliases —
    see ``_canon_mode``).  Boundary modes stay exact
    under chunking because an edge block's clipped halo ends exactly at
    the array boundary.  Floating inputs keep their dtype; integers
    promote through the mean's true division.
    """
    axes = _filter_axes(b, axis)
    widths = _odd_widths(width, len(axes))
    taps_list = [[1.0 / w] * w for w in widths]
    return _separable_filter(b, taps_list, axes, size, mode, shard=shard,
                             precision=precision)


def convolve(b, kernel, axis=None, size="150", mode="constant",
             shard=None, precision=None):
    """Separable correlation with explicit 1-d kernels along value axes.

    ``kernel``: a 1-d sequence of odd length, or one such sequence per
    ``axis`` (paired in the order given).  Orientation is correlation
    (the filter is not flipped), matching ``scipy.ndimage``; symmetric
    kernels — the usual case — make the distinction moot.  Same
    boundary/chunking semantics as :func:`smooth`.
    """
    axes = _filter_axes(b, axis)
    kern = list(kernel)
    if kern and np.isscalar(kern[0]):
        taps_list = [[float(t) for t in kern]] * len(axes)
    else:
        if len(kern) != len(axes):
            raise ValueError("expected %d kernels for %d axes, got %d"
                             % (len(axes), len(axes), len(kern)))
        taps_list = [[float(t) for t in k] for k in kern]
    _odd_widths([len(taps) for taps in taps_list], len(taps_list))
    return _separable_filter(b, taps_list, axes, size, mode, shard=shard,
                             precision=precision)


def gaussian(b, sigma, axis=None, size="150", mode="constant", truncate=4.0,
             shard=None, precision=None):
    """Separable Gaussian filter along value axes (``scipy.ndimage.
    gaussian_filter`` tap construction: radius ``truncate * sigma``,
    normalised).  ``sigma``: scalar or per-``axis``."""
    axes = _filter_axes(b, axis)
    sigmas = [float(s) for s in iterexpand(sigma, len(axes))]
    taps_list = []
    for s in sigmas:
        if s < 0:
            raise ValueError("sigma must be >= 0, got %r" % (s,))
        radius = int(truncate * s + 0.5)
        grid = np.arange(-radius, radius + 1, dtype=np.float64)
        taps = np.exp(-0.5 * (grid / s) ** 2) if s > 0 else np.ones(1)
        taps_list.append([float(t) for t in taps / taps.sum()])
    return _separable_filter(b, taps_list, axes, size, mode, shard=shard,
                             precision=precision)


def median_filter(b, width, axis=None, size="150", mode="symmetric",
                  shard=None):
    """Windowed median filter along value axes — the joint (rectangular)
    window over ALL named axes, matching ``scipy.ndimage.median_filter``
    (a median is not separable, so multi-axis requests stack every
    offset in the window product).  ``width``: odd window per axis; the
    default boundary (np ``'symmetric'``) is scipy's default
    (``'reflect'`` in scipy's vocabulary).  Same halo/chunking machinery
    as the linear filters: exact at block boundaries, one compiled
    program on TPU, `shard=` for mesh-split axes."""
    mode = _canon_mode(mode)
    axes = _filter_axes(b, axis)
    widths = _odd_widths(width, len(axes))
    depth = tuple(w // 2 for w in widths)
    medfilt = _medfilt_fn(tuple(axes), tuple(widths), mode)
    return map_overlap(b, medfilt, depth, axis=axes, size=size, shard=shard)


@lru_cache(maxsize=256)
def _medfilt_fn(axes, widths, mode):
    """Memoised median block function (same rationale as
    :func:`_sepfilter_fn`)."""
    from itertools import product as _product
    offsets = list(_product(*[range(w) for w in widths]))

    def medfilt(blk):
        xp = np if isinstance(blk, np.ndarray) else jnp
        xpad = _halo_pad(blk, axes, widths, mode, xp)
        pieces = []
        for off in offsets:
            sl = [slice(None)] * blk.ndim
            for ax, o in zip(axes, off):
                sl[ax] = slice(o, o + blk.shape[ax])
            pieces.append(xpad[tuple(sl)])
        return xp.median(xp.stack(pieces, axis=0), axis=0)

    return medfilt
